"""Driver benchmark: every BASELINE.md config plus the sync-overhead north star.

Prints ONE JSON line. Headline metric = BASELINE config 2 (fused
MetricCollection update, 1k classes) measured in the deployment shape — the
collection advanced by a compiled ``lax.scan`` loop, as a jitted training
step would — with ``vs_baseline`` = reference-torch eager per-call time /
ours. The per-call jit-dispatch path (what interactive use sees) is reported
alongside in ``extra.config2``. ``--quick-tpu`` runs a <=5-minute subset so
a short healthy-tunnel window still yields a full platform:tpu record; MFU
fields (XLA cost-analysis FLOPs / time / bf16 peak) accompany the heavy
kernels. The ``extra`` field carries the full grid:

  config1   Accuracy (multiclass, 10-class) update µs/step + compute ms
            (reference analog: README quickstart)
  config2   MetricCollection(Accuracy, F1, Precision, Recall), 1k classes —
            the headline (reference: collections.py compute groups)
  sync      per-step sync overhead %, 1k-class Accuracy+F1 sweep over 64k
            samples on an 8-device mesh (driver north star: <5%; run in a
            CPU-mesh subprocess since the bench host has one real chip)
  config3   FID/LPIPS: InceptionV3 + LPIPS-alex feature-extraction
            samples/sec (reference: torch-fidelity/lpips forwards, re-created
            by the pure-torch oracles in tests/helpers/torch_nets.py since
            those packages are absent offline) + FID compute() wall time
  config4   MeanAveragePrecision samples/sec on synthetic COCO-val-shaped
            batches (reference analog tm_examples/detection_map.py; the
            reference class itself needs torchvision which is absent, so the
            baseline is the independent numpy COCO oracle in
            tests/detection/oracle.py)
  config5   BERTScore sentences/sec with a toy encoder on both sides
            (reference: tm_examples/bert_score-own_model.py)
  retrieval compiled static-shape evaluation vs eager per-query loop, 50k docs
  catbuffer AUROC with buffer_capacity: jitted update µs/step vs eager

Every sub-benchmark is isolated: failures surface as null in ``extra`` with a
note on stderr, never break the headline line.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import os
import subprocess
import sys
import time
import traceback

import numpy as np


def _xla_cache_dir() -> str:
    """Persistent-compile-cache dir keyed by host CPU identity.

    XLA's CPU AOT cache entries record the compile machine's feature set; on
    a different host they load with 'could lead to execution errors such as
    SIGILL' errors (observed when the cache dir survived a round boundary
    onto new hardware). Keying the dir by a hash of the CPU feature flags
    keeps reuse on the same host and isolation across hosts."""
    import hashlib
    import platform

    key = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    key += line
                    break
    except OSError:
        key += platform.processor() or ""
    tag = hashlib.sha1(key.encode()).hexdigest()[:12]
    return os.path.expanduser(f"~/.cache/metrics_tpu_xla_{tag}")

REPO = os.path.dirname(os.path.abspath(__file__))

NUM_CLASSES = 1000
BATCH = 1024
STEPS = 64
WARMUP = 3


# bf16 systolic-array peak per chip (public spec sheets); keyed by substrings
# of jax's device_kind. Used only to turn measured model-FLOP throughput into
# an MFU percentage — on CPU there is no meaningful peak, so mfu_pct is None.
_TPU_PEAK_TFLOPS = (
    ("v6", 918.0),
    ("v5p", 459.0),
    ("v5 lite", 197.0),
    ("v5e", 197.0),
    ("v5", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def _timed(thunk) -> float:
    t0 = time.perf_counter()
    thunk()
    return time.perf_counter() - t0


def _flops_of_compiled(compiled) -> float | None:
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax returns one dict per computation
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception:
        return None


def _model_flops(fn, *args) -> float | None:
    """XLA's compiled-program FLOP count for ``fn(*args)`` (None if the
    backend doesn't report one). NOTE: the AOT lower/compile here does NOT
    reuse the in-memory jit executable — it recompiles the program (served
    from the persistent compile cache when warm), so call it only after the
    timing it annotates, never between a measurement and its report."""
    import jax

    try:
        lowerable = fn if hasattr(fn, "lower") else jax.jit(fn)
        return _flops_of_compiled(lowerable.lower(*args).compile())
    except Exception:
        return None


def _mfu_fields(flops_per_call: float | None, sec_per_call: float) -> dict:
    """Utilization record: measured model-FLOP rate and, on TPU, the fraction
    of the chip's bf16 peak it represents (BASELINE.md reports throughput;
    MFU makes the number comparable across shapes/hardware)."""
    import jax

    if not flops_per_call:
        return {"model_gflops_per_sec": None, "mfu_pct": None}
    gflops = flops_per_call / sec_per_call / 1e9
    out = {"model_gflops_per_sec": gflops, "mfu_pct": None}
    dev = jax.devices()[0]
    if dev.platform not in ("cpu", "gpu"):
        kind = getattr(dev, "device_kind", "").lower()
        for key, peak in _TPU_PEAK_TFLOPS:
            if key in kind:
                out["mfu_pct"] = gflops / (peak * 1e3) * 100.0
                out["peak_tflops_assumed"] = peak
                out["note"] = "MFU vs bf16 peak; program dtype f32 unless stated"
                break
    return out


def _load_module(name: str, *path_parts: str):
    """Import a repo file by path (tests/ is not an installed package)."""
    spec = importlib.util.spec_from_file_location(name, os.path.join(REPO, *path_parts))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _load_torch_oracles():
    return _load_module("torch_nets", "tests", "helpers", "torch_nets.py")


def _shim_pkg_resources() -> None:
    """The reference imports pkg_resources (removed from modern setuptools)."""
    if "pkg_resources" in sys.modules:
        return
    import types

    shim = types.ModuleType("pkg_resources")

    class DistributionNotFound(Exception):
        pass

    def get_distribution(name):
        raise DistributionNotFound(name)

    shim.DistributionNotFound = DistributionNotFound
    shim.get_distribution = get_distribution
    sys.modules["pkg_resources"] = shim


def _reference_torchmetrics():
    if "/root/reference" not in sys.path:
        sys.path.insert(0, "/root/reference")
    _shim_pkg_resources()
    import torchmetrics

    return torchmetrics


# --------------------------------------------------------------------------- #
# config 1 — Accuracy, 10 classes (README quickstart shape)
# --------------------------------------------------------------------------- #
def bench_accuracy_ours() -> dict:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy

    acc = Accuracy(num_classes=10)
    step = jax.jit(acc.update_state)
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.normal(size=(128, 10)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, 10, size=(128,)), dtype=jnp.int32)

    state = acc.init_state()
    for _ in range(WARMUP):
        state = step(state, preds, target)
    jax.block_until_ready(state)
    state = acc.init_state()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        state = step(state, preds, target)
    jax.block_until_ready(state)
    t1 = time.perf_counter()
    compute = jax.jit(acc.compute_state)
    jax.block_until_ready(compute(state))  # compile
    t2 = time.perf_counter()
    jax.block_until_ready(compute(state))
    t3 = time.perf_counter()
    return {"update_us_per_step": (t1 - t0) / STEPS * 1e6, "compute_ms": (t3 - t2) * 1e3}


def bench_accuracy_ref() -> dict:
    import torch

    tm = _reference_torchmetrics()
    acc = tm.Accuracy(num_classes=10)
    rng = np.random.default_rng(0)
    preds = torch.as_tensor(rng.normal(size=(128, 10)), dtype=torch.float32)
    target = torch.as_tensor(rng.integers(0, 10, size=(128,)), dtype=torch.long)
    for _ in range(WARMUP):
        acc.update(preds, target)
    acc.reset()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        acc.update(preds, target)
    t1 = time.perf_counter()
    t2 = time.perf_counter()
    acc.compute()
    t3 = time.perf_counter()
    return {"update_us_per_step": (t1 - t0) / STEPS * 1e6, "compute_ms": (t3 - t2) * 1e3}


def bench_accuracy_compute() -> dict:
    """Config-1 ``compute()`` per call: the stateful facade (compiled-compute
    engine dispatch) vs the raw jitted ``compute_state`` executable. The gap
    between the two is pure dispatch bookkeeping — the engine's overhead."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy

    acc = Accuracy(num_classes=10)
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.normal(size=(128, 10)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, 10, size=(128,)), dtype=jnp.int32)
    acc.update(preds, target)

    raw = jax.jit(acc.compute_state)
    state = acc.get_state()
    jax.block_until_ready(raw(state))
    n = 200
    t0 = time.perf_counter()
    for _ in range(n):
        out = raw(state)
    jax.block_until_ready(out)
    raw_us = (time.perf_counter() - t0) / n * 1e6

    for _ in range(3):  # warmup sighting + compile + steady state
        acc._computed = None
        jax.block_until_ready(acc.compute())
    t0 = time.perf_counter()
    for _ in range(n):
        acc._computed = None  # defeat memoization: time the dispatch itself
        out = acc.compute()
    jax.block_until_ready(out)
    facade_us = (time.perf_counter() - t0) / n * 1e6
    return {
        "facade_us": facade_us,
        "raw_jit_us": raw_us,
        "facade_vs_raw": facade_us / raw_us if raw_us else None,
    }


# --------------------------------------------------------------------------- #
# config 2 — fused MetricCollection, 1k classes (headline)
# --------------------------------------------------------------------------- #
def bench_collection_ours() -> float:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall

    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )

    @jax.jit
    def step(states, logits, target):
        return coll.update_state(states, logits, target)

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    states = coll.init_state()
    for _ in range(WARMUP):
        states = step(states, logits, target)
    jax.block_until_ready(states)

    states = coll.init_state()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        states = step(states, logits, target)
    jax.block_until_ready(states)
    t1 = time.perf_counter()
    results = coll.compute_state(states)
    jax.block_until_ready(results)
    return (t1 - t0) / STEPS * 1e6


def bench_collection_facade() -> float:
    """Config-2 collection driven through plain ``coll.update()`` — the
    stateful facade the reference exposes. The compiled-update engine serves
    these calls from one cached fused (and donated) executable, so this is
    the apples-to-apples number against the reference's eager per-call time."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall

    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    for _ in range(WARMUP):
        coll.update(logits, target)
    coll.reset()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        coll.update(logits, target)
    jax.block_until_ready(coll["acc"].tp)
    return (time.perf_counter() - t0) / STEPS * 1e6


def bench_collection_fused_update() -> dict:
    """ISSUE-3 acceptance numbers: the fused collection update (ONE donated
    jitted program per step, compute-group dedup) against the per-member
    dispatch path (``fused_update=False, compute_groups=False``: every member
    runs its own jitted executable per step — the pre-fusion facade cost),
    plus a member-count sweep showing how the fused program scales."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall

    def build(**kw):
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
                "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
                "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
                "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
            },
            **kw,
        )

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    def timed_updates(coll, steps=STEPS, reps=3):
        for _ in range(WARMUP):  # warmup sighting + compile probe + donate
            coll.update(logits, target)

        def one_rep():
            coll.reset()
            t0 = time.perf_counter()
            for _ in range(steps):
                coll.update(logits, target)
            jax.block_until_ready(next(iter(coll.values())).get_state())
            return (time.perf_counter() - t0) / steps * 1e6

        return min(one_rep() for _ in range(reps))

    fused_us = timed_updates(build())
    permember_us = timed_updates(build(fused_update=False, compute_groups=False))

    # member-count sweep: fused path only, small shapes (a 64-member
    # per-member comparison would compile 64 separate executables). Cycling
    # ignore_index yields distinct update signatures (cheap masking, unlike
    # top_k's sort); equal-signature stat-scores members still dedup into
    # shared compute groups — `compute_groups` records how far.
    sweep = {}
    classes, batch, steps = 64, 256, 8
    s_logits = jnp.asarray(rng.normal(size=(batch, classes)), dtype=jnp.float32)
    s_target = jnp.asarray(rng.integers(0, classes, size=(batch,)), dtype=jnp.int32)
    makers = (Precision, Recall, F1Score)
    for n_members in (4, 16, 64):
        coll = MetricCollection(
            {
                f"m{i}": makers[i % len(makers)](
                    num_classes=classes, average="macro", ignore_index=i // len(makers)
                )
                for i in range(n_members)
            }
        )
        for _ in range(WARMUP):
            coll.update(s_logits, s_target)
        coll.reset()
        t0 = time.perf_counter()
        for _ in range(steps):
            coll.update(s_logits, s_target)
        jax.block_until_ready(next(iter(coll.values())).get_state())
        sweep[f"members_{n_members}"] = {
            "us_per_step": (time.perf_counter() - t0) / steps * 1e6,
            "compute_groups": len(coll._groups),
        }

    return {
        "fused_update_us_per_step": fused_us,
        "permember_update_us_per_step": permember_us,
        "fused_vs_permember": permember_us / fused_us if fused_us else None,
        "member_sweep": sweep,
    }


def bench_collection_compute() -> dict:
    """Config-2 ``MetricCollection.compute()``: the fused compiled-compute
    facade (one cached jitted program for every member's finalize) vs the
    eager per-member loop (all engines off — the pre-engine behavior) vs the
    raw fused jit. ``facade_vs_eager`` is the ISSUE-2 acceptance number
    (target >= 3x)."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall

    def build(**kw):
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
                "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
                "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
                "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
            },
            **kw,
        )

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)
    n = 50

    def timed_compute(coll):
        def clear():  # defeat the _computed memoization: time recompute+dispatch
            for m in coll.values():
                m._computed = None

        for _ in range(3):  # warmup sighting + compile + steady state
            clear()
            res = coll.compute()
        jax.block_until_ready(list(res.values()))
        t0 = time.perf_counter()
        for _ in range(n):
            clear()
            res = coll.compute()
        jax.block_until_ready(list(res.values()))
        return (time.perf_counter() - t0) / n * 1e6

    fused = build()
    fused.update(logits, target)
    fused_us = timed_compute(fused)

    eager = build(compiled_compute=False)
    for m in eager.values():
        m._compiled_compute = False  # member engines off too: the true eager loop
    eager.update(logits, target)
    eager_us = timed_compute(eager)

    states = {g[0]: fused._metrics[g[0]].get_state() for g in fused._groups}
    raw = jax.jit(fused.compute_state)
    jax.block_until_ready(list(raw(states).values()))
    t0 = time.perf_counter()
    for _ in range(n):
        out = raw(states)
    jax.block_until_ready(list(out.values()))
    raw_us = (time.perf_counter() - t0) / n * 1e6

    return {
        "facade_us": fused_us,
        "eager_loop_us": eager_us,
        "raw_jit_us": raw_us,
        "facade_vs_eager": eager_us / fused_us if fused_us else None,
    }


def bench_collection_ref() -> float:
    import torch

    tm = _reference_torchmetrics()
    coll = tm.MetricCollection(
        {
            "acc": tm.Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": tm.F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": tm.Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": tm.Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    rng = np.random.default_rng(0)
    logits = torch.as_tensor(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=torch.float32)
    target = torch.as_tensor(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=torch.long)

    for _ in range(WARMUP):
        coll.update(logits, target)
    coll.reset()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        coll.update(logits, target)
    t1 = time.perf_counter()
    coll.compute()
    return (t1 - t0) / STEPS * 1e6


def bench_collection_scan() -> dict:
    """Config-2 collection advanced by lax.scan INSIDE one jit — the shape a
    real TPU training loop uses. The per-call loop above measures host
    dispatch latency (dominant through a remote-device tunnel); this measures
    the on-device per-step cost the fused update actually has in situ."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall

    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    # Loop-VARYING inputs via a ring of pre-generated batches, indexed per
    # step: with a single closed-over (or even argument) batch, XLA hoists
    # the whole top-k/one-hot input stage out of the scan as loop-invariant
    # code (and constant-folds it for closures, ~40s extra compile), so the
    # timed loop would exclude most of the per-step work.
    rng = np.random.default_rng(0)
    ring = 8
    logits_ring = jnp.asarray(rng.normal(size=(ring, BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target_ring = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(ring, BATCH)), dtype=jnp.int32)
    n_steps = 256

    def sweep(states, logits_ring, target_ring):
        def one_step(states, i):
            logits = jax.lax.dynamic_index_in_dim(logits_ring, i % ring, keepdims=False)
            target = jax.lax.dynamic_index_in_dim(target_ring, i % ring, keepdims=False)
            return coll.update_state(states, logits, target), ()

        states, _ = jax.lax.scan(one_step, states, jnp.arange(n_steps))
        return states

    # AOT lower/compile once: the same executable is timed AND provides the
    # cost analysis, so no second (hang-prone on TPU) compile sits between a
    # successful measurement and its report
    states0 = coll.init_state()
    compiled = jax.jit(sweep).lower(states0, logits_ring, target_ring).compile()
    flops = _flops_of_compiled(compiled)
    jax.block_until_ready(compiled(states0, logits_ring, target_ring))  # warm
    best = min(
        _timed(lambda: jax.block_until_ready(compiled(states0, logits_ring, target_ring)))
        for _ in range(3)
    )
    return {
        "us_per_step": best / n_steps * 1e6,
        **_mfu_fields(flops / n_steps if flops else None, best / n_steps),
    }


# --------------------------------------------------------------------------- #
# sync overhead — the <5% north star, measured on an 8-device mesh
# --------------------------------------------------------------------------- #
def _sync_overhead_child() -> None:
    """Runs inside a CPU subprocess with 8 forced host devices."""
    import jax

    # the env-var platform selection is unreliable when a TPU plugin is
    # preloaded by sitecustomize; the config update always wins (see conftest)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import Accuracy, F1Score, MetricCollection

    devices = jax.devices()
    # BENCH_SYNC_WORLD lets a scaling sweep vary the mesh width (default: the
    # BASELINE.md 8-device config); the parent sets the matching device count
    world = int(os.environ.get("BENCH_SYNC_WORLD", "8"))
    if len(devices) < world:
        raise RuntimeError(f"expected {world} forced host devices, got {len(devices)}")
    mesh = Mesh(np.asarray(devices[:world]), ("data",))
    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    per_dev_batch = 1024
    if 65_536 % (per_dev_batch * world) != 0:
        raise RuntimeError(f"world={world} does not divide the 64k-sample sweep evenly")
    steps = 65_536 // (per_dev_batch * world)  # 64k-sample sweep (BASELINE.md)
    if steps < 1:
        raise RuntimeError(f"world={world} leaves zero sweep steps")

    def sweep(sync_every_step: bool):
        def body(seed):
            def one_step(state, i):
                key = jax.random.fold_in(jax.random.PRNGKey(0), i + seed[0, 0])
                logits = jax.random.normal(key, (per_dev_batch, NUM_CLASSES), jnp.float32)
                target = jax.random.randint(key, (per_dev_batch,), 0, NUM_CLASSES)
                state = coll.update_state(state, logits, target)
                if sync_every_step:
                    # dist_sync_on_step analog: batch-synced value each step,
                    # local accumulation continues (reference metric.py:250)
                    val = coll.compute_state(coll.sync_states(state, "data"))
                else:
                    val = coll.compute_state(state)
                return state, val["acc"]

            state, vals = jax.lax.scan(one_step, coll.init_state(), jnp.arange(steps))
            state = coll.sync_states(state, "data")
            out = coll.compute_state(state)
            return jax.tree.map(lambda x: jnp.expand_dims(x, 0), (out, vals))

        if hasattr(jax, "shard_map"):
            smapped = jax.shard_map(
                body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_vma=False
            )
        else:  # jax < 0.6: experimental namespace, check_rep spelling
            from jax.experimental.shard_map import shard_map

            smapped = shard_map(
                body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False
            )
        fn = jax.jit(smapped)
        seeds = jnp.arange(world)[:, None]
        jax.block_until_ready(fn(seeds))  # compile
        return fn, seeds

    # Paired, interleaved measurement: a sequential min-of-5 per config lets
    # slow machine drift between the two blocks masquerade as signal (recorded
    # history: -0.7%, +8.3%, -3.6% for the same code). Alternating
    # nosync/sync within each rep puts both configs under the same transient
    # load; the reported figure is the median of per-rep paired overheads
    # with the spread alongside so a noisy reading is visible as such.
    fn_nosync, seeds = sweep(False)
    fn_sync, _ = sweep(True)

    def timed(fn):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(seeds))
        return time.perf_counter() - t0

    timed(fn_nosync), timed(fn_sync)  # warm caches beyond the compile call
    reps = 9
    pairs = []
    for rep in range(reps):
        if rep % 2 == 0:  # alternate order so drift cancels across reps
            t_n, t_s = timed(fn_nosync), timed(fn_sync)
        else:
            t_s, t_n = timed(fn_sync), timed(fn_nosync)
        pairs.append((t_n, t_s))
    overheads = sorted((t_s - t_n) / t_n * 100.0 for t_n, t_s in pairs)
    med = overheads[reps // 2]
    t_nosync = float(np.median([p[0] for p in pairs]))
    t_sync = float(np.median([p[1] for p in pairs]))

    # trace-time collective counts: bucketed (default) vs per-leaf sync of
    # this collection's leader states — the coalescing win, counted exactly
    from metrics_tpu.parallel import count_collectives, set_bucketed_sync

    def count_sync_collectives(bucketed: bool) -> int:
        set_bucketed_sync(bucketed)
        try:
            with count_collectives() as box:
                jax.make_jaxpr(
                    lambda st: coll.sync_states(st, "data"), axis_env=[("data", world)]
                )(coll.init_state())
            return box["count"]
        finally:
            set_bucketed_sync(None)

    print(
        json.dumps(
            {
                "sweep_ms_nosync": t_nosync * 1e3,
                "sweep_ms_sync_every_step": t_sync * 1e3,
                "overhead_pct": med,
                "overhead_pct_min": overheads[0],
                "overhead_pct_max": overheads[-1],
                "overhead_pct_iqr": overheads[(3 * reps) // 4] - overheads[reps // 4],
                "reps": reps,
                "world": world,
                "samples": per_dev_batch * world * steps,
                "sync_collectives_bucketed": count_sync_collectives(True),
                "sync_collectives_per_leaf": count_sync_collectives(False),
            }
        )
    )


def bench_sync_overhead(timeout: float = 1200.0, world: int = 8) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SYNC_WORLD"] = str(world)
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={world}"
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", "sync_overhead"],
        capture_output=True,
        text=True,
        env=env,
        timeout=timeout,
        cwd=REPO,
    )
    if out.returncode != 0:
        raise RuntimeError(f"sync-overhead child failed:\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def _run_isolated(name: str, timeout: float = 420.0):
    """Run a ``--child`` sub-benchmark in its own process with a hard timeout.

    A TPU compile can block indefinitely (observed: a remote-compile hang that
    no in-process soft budget can interrupt, and which wedges the device
    tunnel when the whole benchmark is killed mid-operation). Isolating the
    riskiest sub-benchmarks means a hang costs one child and its timeout, not
    the run: the parent keeps the headline and every completed number.
    """
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", name],
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )
    if out.returncode != 0:
        raise RuntimeError(f"{name} child failed:\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


# --------------------------------------------------------------------------- #
# config 3 — FID / LPIPS feature extraction
# --------------------------------------------------------------------------- #
def bench_inception_ours() -> dict:
    import jax
    import jax.numpy as jnp

    from metrics_tpu.nets.inception import InceptionV3FeatureExtractor

    ext = InceptionV3FeatureExtractor("2048")
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(0, 255, size=(64, 3, 32, 32)), dtype=jnp.uint8)
    jax.block_until_ready(ext(imgs))  # compile
    # 4 reps on BOTH sides of the inception pair (torch's forward is ~14s a
    # batch; symmetric draw counts keep the min-statistics comparison fair)
    dt = min(_timed(lambda: jax.block_until_ready(ext(imgs))) for _ in range(4))
    return {"samples_per_sec": imgs.shape[0] / dt, **_mfu_fields(_model_flops(ext, imgs), dt)}


def bench_inception_ref() -> float:
    import torch

    nets = _load_torch_oracles()
    net = nets.TorchFIDInception()
    nets.randomize_inception_(net, seed=0)
    rng = np.random.default_rng(0)
    imgs = torch.as_tensor(rng.integers(0, 255, size=(64, 3, 32, 32)).astype(np.uint8))
    net(imgs)  # warmup
    dt = min(_timed(lambda: net(imgs)) for _ in range(4))
    return imgs.shape[0] / dt


def bench_lpips_ours() -> dict:
    import jax
    import jax.numpy as jnp

    from metrics_tpu.nets.lpips import LPIPSNet

    net = LPIPSNet("alex")
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.uniform(-1, 1, size=(32, 3, 64, 64)), dtype=jnp.float32)
    b = jnp.asarray(rng.uniform(-1, 1, size=(32, 3, 64, 64)), dtype=jnp.float32)
    jax.block_until_ready(net(a, b))
    # best-of-N: throughput comparisons use min time (timeit convention) so
    # scheduler noise can't read as a regression on a ~2% margin
    dt = min(_timed(lambda: jax.block_until_ready(net(a, b))) for _ in range(6))
    return {"samples_per_sec": a.shape[0] / dt, **_mfu_fields(_model_flops(net, a, b), dt)}


def bench_lpips_ref() -> float:
    import torch

    nets = _load_torch_oracles()
    from metrics_tpu.nets.lpips import NET_CHANNELS

    backbone = nets.make_lpips_backbone_state_dict("alex", seed=0)
    lin = nets.make_lpips_lin_state_dict(NET_CHANNELS["alex"], seed=1)
    rng = np.random.default_rng(0)
    a = torch.as_tensor(rng.uniform(-1, 1, size=(32, 3, 64, 64)).astype(np.float32))
    b = torch.as_tensor(rng.uniform(-1, 1, size=(32, 3, 64, 64)).astype(np.float32))
    nets.torch_lpips_forward(backbone, lin, "alex", a, b)  # warmup
    dt = min(_timed(lambda: nets.torch_lpips_forward(backbone, lin, "alex", a, b)) for _ in range(6))
    return a.shape[0] / dt


def bench_fid_compute_ms() -> dict:
    """FID compute() (mean/cov finalize + trace-sqrtm) on 2048-dim state:
    eager op walk vs the compiled-compute engine's cached jitted executable."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu.image import FrechetInceptionDistance

    fid = FrechetInceptionDistance(feature=lambda x: x, feature_size=2048, compiled_compute=False)
    rng = np.random.default_rng(0)
    for _ in range(4):
        fid.update(jnp.asarray(rng.normal(size=(512, 2048)), dtype=jnp.float32), real=True)
        fid.update(jnp.asarray(rng.normal(size=(512, 2048)), dtype=jnp.float32), real=False)
    jax.block_until_ready(fid.compute())  # warm the per-op dispatch caches
    fid._computed = None  # force recompute
    t0 = time.perf_counter()
    jax.block_until_ready(fid.compute())
    eager_ms = (time.perf_counter() - t0) * 1e3

    fid._compiled_compute = True  # hand the same instance to the engine
    for _ in range(2):  # warmup sighting, then the compile call
        fid._computed = None
        jax.block_until_ready(fid.compute())
    fid._computed = None
    t0 = time.perf_counter()
    jax.block_until_ready(fid.compute())
    engine_ms = (time.perf_counter() - t0) * 1e3
    return {
        "eager_ms": eager_ms,
        "engine_cached_ms": engine_ms,
        "speedup": eager_ms / engine_ms if engine_ms else None,
    }


def bench_fid_numerics() -> dict:
    """On-device f32 FID vs the scipy f64 oracle on rank-deficient 2048-d
    covariances from inception-like features — the on-chip numerics proof
    (VERDICT r3 ask: eigh runs f32 on TPU; the reference keeps f64 on host,
    fid.py:264-267). Recorded every bench run so a TPU round carries the
    hardware differential automatically."""
    import jax.numpy as jnp

    from metrics_tpu.ops.image.fid import frechet_distance

    fixtures = _load_module("fid_fixtures", "tests", "helpers", "fid_fixtures.py")
    rng = np.random.default_rng(0)
    d, n = 2048, 500  # n < d: singular covariances (the realistic FID regime)
    fr = fixtures.inception_like(rng, n, d)
    ff = fixtures.inception_like(rng, n, d, shift=0.05)
    oracle = fixtures.oracle_fid(fr, ff)
    ours = float(frechet_distance(jnp.asarray(fr, jnp.float32), jnp.asarray(ff, jnp.float32)))
    rel = abs(ours - oracle) / abs(oracle)
    return {"fid_f32": round(ours, 4), "fid_f64_oracle": round(oracle, 4),
            "rel_err": float(f"{rel:.3e}"), "within_1e-3": bool(rel < 1e-3)}


# --------------------------------------------------------------------------- #
# config 4 — MeanAveragePrecision on COCO-val-shaped synthetic batches
# --------------------------------------------------------------------------- #
def _synth_coco(n_img: int, n_det: int = 50, n_gt: int = 10, n_cls: int = 20, seed: int = 0):
    rng = np.random.default_rng(seed)
    preds, targets = [], []
    for _ in range(n_img):
        def boxes(n):
            xy = rng.uniform(0, 400, size=(n, 2))
            wh = rng.uniform(8, 120, size=(n, 2))
            return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)

        preds.append(
            {
                "boxes": boxes(n_det),
                "scores": rng.uniform(size=(n_det,)).astype(np.float32),
                "labels": rng.integers(0, n_cls, size=(n_det,)).astype(np.int32),
            }
        )
        targets.append(
            {
                "boxes": boxes(n_gt),
                "labels": rng.integers(0, n_cls, size=(n_gt,)).astype(np.int32),
            }
        )
    return preds, targets


def bench_map_ours() -> float:
    import jax

    from metrics_tpu.detection import MeanAveragePrecision

    n_img = 32
    preds, targets = _synth_coco(n_img)
    metric = MeanAveragePrecision()
    metric.update(preds, targets)
    jax.block_until_ready(metric.compute()["map"])  # compile
    metric.reset()
    t0 = time.perf_counter()
    metric.update(preds, targets)
    jax.block_until_ready(metric.compute()["map"])
    dt = time.perf_counter() - t0
    return n_img / dt


def bench_map_oracle() -> float:
    oracle = _load_module("coco_oracle", "tests", "detection", "oracle.py")
    n_img = 32
    preds, targets = _synth_coco(n_img)
    t0 = time.perf_counter()
    oracle.coco_map(preds, targets)
    dt = time.perf_counter() - t0
    return n_img / dt


def bench_map_segm_rle() -> float:
    """Segm mAP from COCO RLE input: host decode + dense-mask MXU kernel."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu.detection import MeanAveragePrecision
    from metrics_tpu.ops.detection.rle import rle_encode

    rng = np.random.default_rng(3)
    n_img, hw = 16, 96

    def mask_image(n):
        out = np.zeros((n, hw, hw), dtype=bool)
        for i in range(n):
            x0, y0 = rng.integers(0, hw - 24, 2)
            w, h = rng.integers(8, 24, 2)
            out[i, y0:y0 + h, x0:x0 + w] = True
        return out

    preds, targets = [], []
    for _ in range(n_img):
        nd, ng = int(rng.integers(2, 8)), int(rng.integers(1, 6))
        preds.append(dict(
            masks=[rle_encode(m) for m in mask_image(nd)],
            scores=jnp.asarray(rng.random(nd).astype(np.float32)),
            labels=jnp.asarray(rng.integers(0, 3, nd)),
        ))
        targets.append(dict(
            masks=[rle_encode(m) for m in mask_image(ng)],
            labels=jnp.asarray(rng.integers(0, 3, ng)),
        ))

    metric = MeanAveragePrecision(iou_type="segm")
    metric.update(preds, targets)
    jax.block_until_ready(metric.compute()["map"])  # compile
    metric.reset()
    t0 = time.perf_counter()
    metric.update(preds, targets)
    jax.block_until_ready(metric.compute()["map"])
    return n_img / (time.perf_counter() - t0)


# --------------------------------------------------------------------------- #
# config 5 — BERTScore with a toy encoder (tm_examples/bert_score-own_model.py)
# --------------------------------------------------------------------------- #
_BERT_VOCAB = ["[CLS]", "[SEP]", "[PAD]", "hello", "there", "general", "kenobi", "master", "world", "hi"]
_BERT_DIM = 32
_BERT_MAX_LEN = 12


def _bert_sentences(n: int):
    rng = np.random.default_rng(0)
    words = _BERT_VOCAB[3:]
    make = lambda: " ".join(rng.choice(words, size=rng.integers(3, 9)))
    return [make() for _ in range(n)], [make() for _ in range(n)]


def bench_bert_ours() -> float:
    from metrics_tpu import BERTScore

    table = np.random.default_rng(1).normal(size=(len(_BERT_VOCAB), _BERT_DIM)).astype(np.float32)

    class Tok:
        def __call__(self, sentences):
            ids = np.full((len(sentences), _BERT_MAX_LEN), _BERT_VOCAB.index("[PAD]"), dtype=np.int32)
            mask = np.zeros((len(sentences), _BERT_MAX_LEN), dtype=np.int32)
            for row, sent in enumerate(sentences):
                tokens = ["[CLS]"] + sent.split()[: _BERT_MAX_LEN - 2] + ["[SEP]"]
                for col, tok in enumerate(tokens):
                    ids[row, col] = _BERT_VOCAB.index(tok)
                    mask[row, col] = 1
            return {"input_ids": ids, "attention_mask": mask}

    n = 512
    preds, refs = _bert_sentences(n)
    metric = BERTScore(
        model=object(),
        user_tokenizer=Tok(),
        user_forward_fn=lambda model, batch: table[np.asarray(batch["input_ids"])],
        max_length=_BERT_MAX_LEN,
        batch_size=128,
    )
    metric.update(preds, refs)
    metric.compute()  # warm caches/compiles
    metric.reset()
    t0 = time.perf_counter()
    metric.update(preds, refs)
    metric.compute()
    dt = time.perf_counter() - t0
    return n / dt


def bench_bert_ref() -> float:
    import torch

    tm = _reference_torchmetrics()
    table = torch.as_tensor(np.random.default_rng(1).normal(size=(len(_BERT_VOCAB), _BERT_DIM)).astype(np.float32))

    class Tok:
        def __call__(self, sentences, max_len: int = _BERT_MAX_LEN):
            if isinstance(sentences, str):
                sentences = [sentences]
            ids = torch.full((len(sentences), max_len), float(_BERT_VOCAB.index("[PAD]")))
            mask = torch.zeros((len(sentences), max_len), dtype=torch.long)
            for row, sent in enumerate(sentences):
                tokens = ["[CLS]"] + sent.split()[: max_len - 2] + ["[SEP]"]
                for col, tok in enumerate(tokens):
                    ids[row, col] = _BERT_VOCAB.index(tok)
                    mask[row, col] = 1
            return {"input_ids": ids.long(), "attention_mask": mask}

    n = 512
    preds, refs = _bert_sentences(n)
    metric = tm.text.bert.BERTScore(
        model=torch.nn.Identity(),
        user_tokenizer=Tok(),
        user_forward_fn=lambda model, batch: table[batch["input_ids"]],
        max_length=_BERT_MAX_LEN,
        batch_size=128,
        num_threads=0,  # DataLoader workers fork, which deadlocks under JAX threads
    )
    metric.update(preds, refs)
    metric.compute()
    metric.reset()
    t0 = time.perf_counter()
    metric.update(preds, refs)
    metric.compute()
    dt = time.perf_counter() - t0
    return n / dt


# --------------------------------------------------------------------------- #
# round-2 flagship features on the bench device
# --------------------------------------------------------------------------- #
def bench_retrieval() -> dict:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import RetrievalMAP

    n_queries, docs_per_query = 4096, 12
    n = n_queries * docs_per_query  # ~50k docs
    rng = np.random.default_rng(0)
    indexes = jnp.asarray(np.repeat(np.arange(n_queries), docs_per_query).astype(np.int32))
    preds = jnp.asarray(rng.uniform(size=(n,)).astype(np.float32))
    target = jnp.asarray((rng.uniform(size=(n,)) < 0.2).astype(np.int32))

    compiled = RetrievalMAP(max_queries=n_queries, max_docs_per_query=16)
    compiled.update(preds, target, indexes=indexes)
    jax.block_until_ready(compiled.compute())  # compile
    t0 = time.perf_counter()
    compiled._computed = None
    jax.block_until_ready(compiled.compute())
    compiled_ms = (time.perf_counter() - t0) * 1e3

    # the eager per-query python loop is timed on a subset and scaled: its cost
    # is strictly linear in queries, and the full 4096-query loop through a
    # remote-device tunnel takes tens of minutes (each query is dozens of tiny
    # dispatches — the very overhead the compiled path removes)
    eager_queries = 256
    sub = eager_queries * docs_per_query
    eager = RetrievalMAP()
    eager.update(preds[:sub], target[:sub], indexes=indexes[:sub])
    t0 = time.perf_counter()
    jax.block_until_ready(eager.compute())
    eager_ms = (time.perf_counter() - t0) * 1e3 * (n_queries / eager_queries)
    return {
        "docs": n,
        "compiled_compute_ms": compiled_ms,
        "eager_compute_ms_extrapolated": eager_ms,
        "eager_sample_queries": eager_queries,
        "speedup": eager_ms / compiled_ms,
    }


def bench_pesq_native() -> dict:
    """Native jax PESQ throughput: batch of 2 s narrowband utterances scored
    in one jitted program (the reference's C extension is per-sample host
    code — there is no on-device baseline to compare against)."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu.ops.audio.pesq_native import pesq_native

    rng = np.random.default_rng(0)
    batch, n = 16, 2 * 8000
    t = np.arange(n) / 8000.0
    clean = np.stack([
        np.sin(2 * np.pi * (110 + 7 * i) * t) * (0.3 + 0.7 * (np.sin(2 * np.pi * 3 * t + i) > 0))
        for i in range(batch)
    ]).astype(np.float32)
    noisy = clean + 0.2 * rng.normal(size=clean.shape).astype(np.float32)
    fn = jax.jit(lambda p, tt: pesq_native(p, tt, 8000, "nb"))
    noisy_d, clean_d = jnp.asarray(noisy), jnp.asarray(clean)  # transfer once
    jax.block_until_ready(fn(noisy_d, clean_d))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(noisy_d, clean_d))
        best = min(best, time.perf_counter() - t0)
    return {"utterances_per_sec": batch / best, "batch": batch, "seconds_each": 2}


def bench_binned_curve() -> dict:
    """Binned PR-curve update, three ways: the naive (N, C, T) broadcast, the
    bucketize+histogram XLA path (the default), and — on TPU — the pallas
    kernel, answering VERDICT r3's ask for a pallas-vs-XLA on-chip number."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu.ops.classification.binned_pallas import (
        _binned_counts_broadcast,
        binned_stat_counts,
    )

    n, c, t = 4096, 128, 101
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.uniform(size=(n, c)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 2, size=(n, c)).astype(bool))
    thresholds = jnp.linspace(0.0, 1.0, t)

    def timed(make_fn):
        # jit every path: the comparison is compiled programs, not dispatch
        fn = jax.jit(make_fn)
        jax.block_until_ready(fn(preds, target))  # compile
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(preds, target))
            best = min(best, time.perf_counter() - t0)
        return best * 1e6

    out = {
        "n": n, "classes": c, "thresholds": t,
        "xla_us": timed(lambda p, tt: binned_stat_counts(p, tt, thresholds, use_pallas="never")),
        "xla_broadcast_us": timed(lambda p, tt: _binned_counts_broadcast(p, tt, thresholds)),
    }
    if jax.default_backend() not in ("cpu", "gpu"):
        out["pallas_us"] = timed(
            lambda p, tt: binned_stat_counts(p, tt, thresholds, use_pallas="force")
        )
    return out


def bench_catbuffer_auroc() -> dict:
    import jax
    import jax.numpy as jnp

    from metrics_tpu import AUROC

    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.uniform(size=(256,)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 2, size=(256,)).astype(np.int32))

    buffered = AUROC(buffer_capacity=256 * 40)
    step = jax.jit(buffered.update_state)
    state = buffered.init_state()
    state = step(state, preds, target)
    state = step(state, preds, target)  # compile BOTH signatures: the first
    jax.block_until_ready(state)  # append materializes the buffer (new treedef)
    state = buffered.init_state()
    state = step(state, preds, target)
    t0 = time.perf_counter()
    for _ in range(32):
        state = step(state, preds, target)
    jax.block_until_ready(state)
    jit_us = (time.perf_counter() - t0) / 32 * 1e6

    # the stateful facade: plain .update() calls, served by the compiled-update
    # engine's cached donated executables after warmup
    stateful = AUROC(buffer_capacity=256 * 40)
    for _ in range(5):
        # warm both buffer signatures AND both executables: the donating
        # variant compiles lazily on the first donated call (call 4 here)
        stateful.update(preds, target)
    stateful.reset()
    stateful.update(preds, target)  # re-materialize the buffer treedef
    t0 = time.perf_counter()
    for _ in range(32):
        stateful.update(preds, target)
    jax.block_until_ready(stateful.preds.data)
    stateful_us = (time.perf_counter() - t0) / 32 * 1e6

    # list-state eager baseline (no buffer: dynamic shapes, engine-ineligible)
    eager = AUROC()
    eager.update(preds, target)  # warm
    eager.reset()
    t0 = time.perf_counter()
    for _ in range(32):
        eager.update(preds, target)
    jax.block_until_ready(eager.preds)
    list_eager_us = (time.perf_counter() - t0) / 32 * 1e6
    return {
        "jit_update_us_per_step": jit_us,
        "eager_update_us_per_step": stateful_us,
        "list_eager_update_us_per_step": list_eager_us,
    }


# --------------------------------------------------------------------------- #
_BENCH_START = time.perf_counter()
_BENCH_BUDGET = float(os.environ.get("BENCH_BUDGET_SECONDS", "1500"))


def _safe(fn, *args):
    """Run one sub-benchmark, isolated; skip when the soft time budget is
    spent so the headline line always lands within the driver's window."""
    label = " ".join([fn.__name__, *map(str, args)])
    if time.perf_counter() - _BENCH_START > _BENCH_BUDGET:
        print(f"[bench] {label} skipped: budget exhausted", file=sys.stderr)
        return {"skipped": "budget"}
    t0 = time.perf_counter()
    try:
        out = fn(*args)
        print(f"[bench] {label} ok in {time.perf_counter() - t0:.1f}s", file=sys.stderr)
        return out
    except Exception:
        print(f"[bench] {label} failed after {time.perf_counter() - t0:.1f}s:", file=sys.stderr)
        traceback.print_exc()
        return None


def _num(x):
    """Numeric result of a ``_safe`` call, or None (failures return None but
    budget skips return a truthy ``{"skipped": ...}`` dict — both must read
    as missing wherever arithmetic follows)."""
    return x if isinstance(x, (int, float)) else None


def _round(x, nd=2):
    if isinstance(x, dict):
        return {k: _round(v, nd) for k, v in x.items()}
    if isinstance(x, float):
        # fixed decimals above 1; significant digits below so small values
        # (mfu_pct, sub-GFLOP rates) don't collapse to 0.0
        return round(x, nd) if abs(x) >= 1 else float(f"{x:.3g}")
    return x


_CHILD_BENCHES = {
    "retrieval": bench_retrieval,
    "catbuffer": bench_catbuffer_auroc,
    "binned": bench_binned_curve,
}


def _split_throughput(d, key="samples_per_sec"):
    """(value, mfu-record) from a bench dict; passes misses/skips through."""
    if not isinstance(d, dict) or key not in d:
        return d, None
    return d[key], {k: v for k, v in d.items() if k != key}


def bench_analysis() -> None:
    """``--analysis``: run the full three-stage analyzer (AST lint,
    abstract-eval sweep, stage-3 cost model) over the registered metric
    universe and record wall time plus the live manifest's aggregate resource
    totals — collectives, wire/state/copied bytes, recompile risks — into
    ``BENCH_r24.json`` (one JSON line on stdout, same shape), judged by the
    regression watchdog so manifest-level byte growth shows up as a bench
    regression too, not only as the ``--manifest --diff`` CI gate."""
    import glob as _glob

    import jax

    jax.config.update("jax_platforms", "cpu")  # host-only: axis_env mock mesh
    from metrics_tpu.analysis import manifest as _manifest
    from metrics_tpu.analysis import run_analysis
    from metrics_tpu.analysis.rules import INFO, WARNING
    from metrics_tpu.observability import regress as _regress

    t0 = time.perf_counter()
    report = run_analysis()
    wall_s = time.perf_counter() - t0

    totals = dict(report.manifest["totals"])
    committed = _manifest.load_manifest()
    diff_regressions = None
    if committed is not None:
        records = _manifest.diff_manifest(committed, report.manifest)
        diff_regressions = len(_manifest.gate_failures(records))

    record = {
        # three-stage headline (its own key: the two-stage r09 wall time is
        # not a comparable baseline for a run that also builds the manifest)
        "metric": "analysis_manifest_wall_s",
        "value": round(wall_s, 3),
        "unit": "s",
        "extra": {
            "classes": report.classes,
            "linted_classes": report.linted_classes,
            "errors": report.errors,
            "warnings": report.count(WARNING),
            "info": report.count(INFO),
            "suppressed": sum(1 for f in report.findings if f.suppressed),
            "by_rule": report.by_rule(),
            "eval_skipped": len(report.skipped),
            # the watched *_bytes keys make the watchdog track resource
            # aggregates round-over-round alongside the diff gate
            "manifest": {
                "profiled": totals["profiled"],
                "skipped": totals["skipped"],
                "collectives": totals["collectives"],
                "state_bytes": totals["state_bytes"],
                "wire_bytes": totals["wire_bytes"],
                "copied_bytes": totals["copied_bytes"],
                "recompile_risks": totals["recompile_risks"],
                "incremental_eligible_leaves": totals["incremental_eligible_leaves"],
            },
            "manifest_diff_regressions": diff_regressions,
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r
        for r in _regress.load_rounds(sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r24"
    ]
    rounds.append(_regress.Round("r24", "<this-run>", record))
    regress_report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": regress_report.ok,
        "regression_count": len(regress_report.regressions),
        "keys_checked": regress_report.keys_checked,
        "regressions": [r.describe() for r in regress_report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r24.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)


def bench_checkpoint() -> None:
    """``--checkpoint``: snapshot/restore wall time for the config2 collection
    (Accuracy/F1/Precision/Recall at NUM_CLASSES) plus an 8-shard offline
    merge, recorded into ``BENCH_r10.json`` (one JSON line on stdout, same
    shape). Host-side I/O bench: runs on CPU regardless of accelerator."""
    import shutil
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall
    from metrics_tpu.checkpoint import merge_shards, restore_checkpoint, save_checkpoint

    def build():
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
                "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
                "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
                "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
            }
        )

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    coll = build()
    for _ in range(4):
        coll.update(logits, target)
    jax.block_until_ready({k: m.get_state() for k, m in coll.items()})

    reps = 5
    tmp = tempfile.mkdtemp(prefix="mtpu-ckpt-bench-")
    try:
        # blocking save: device->host copy + shard write + commit + rename
        save_ms = []
        for r in range(reps):
            t0 = time.perf_counter()
            save_checkpoint(coll, os.path.join(tmp, f"save{r}"))
            save_ms.append((time.perf_counter() - t0) * 1e3)

        # async save: time until update() may safely continue (host copy +
        # thread handoff), and separately until the commit landed
        async_resume_ms, async_total_ms = [], []
        for r in range(reps):
            t0 = time.perf_counter()
            handle = save_checkpoint(coll, os.path.join(tmp, f"async{r}"), blocking=False)
            async_resume_ms.append((time.perf_counter() - t0) * 1e3)
            handle.wait()
            async_total_ms.append((time.perf_counter() - t0) * 1e3)

        restore_ms = []
        for r in range(reps):
            fresh = build()
            t0 = time.perf_counter()
            restore_checkpoint(fresh, os.path.join(tmp, "save0"), host_index=0, host_count=1)
            restore_ms.append((time.perf_counter() - t0) * 1e3)

        # 8-shard world written per host, folded to 1 host on restore and
        # offline via the CLI-level merge
        world = 8
        sharded_root = os.path.join(tmp, "world8")
        for i in range(world):
            m = build()
            m.update(logits, target)
            save_checkpoint(m, sharded_root, step=0, shard_index=i, world_size=world)
        t0 = time.perf_counter()
        restore_checkpoint(build(), sharded_root, host_index=0, host_count=1)
        reshard_restore_ms = (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        merge_shards(sharded_root, os.path.join(tmp, "merged"))
        merge_ms = (time.perf_counter() - t0) * 1e3

        ckpt_bytes = sum(
            os.path.getsize(os.path.join(root, f))
            for root, _, files in os.walk(os.path.join(tmp, "save0"))
            for f in files
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    med = lambda xs: round(float(np.median(xs)), 3)
    record = {
        "metric": "checkpoint_save_ms",
        "value": med(save_ms),
        "unit": "ms",
        "extra": {
            "config": "config2_collection",
            "num_classes": NUM_CLASSES,
            "reps": reps,
            "snapshot_bytes": ckpt_bytes,
            "save_blocking_ms": med(save_ms),
            "save_async_resume_ms": med(async_resume_ms),
            "save_async_total_ms": med(async_total_ms),
            "restore_ms": med(restore_ms),
            "reshard_restore_8to1_ms": round(reshard_restore_ms, 3),
            "merge_8shard_ms": round(merge_ms, 3),
        },
    }
    with open(os.path.join(REPO, "BENCH_r10.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)


def _sharded_state_child() -> None:
    """``--child sharded_state``: per-device state bytes + trace-time
    collective bytes for replicated vs sharded placement at one mesh width
    (``BENCH_SHARD_WORLD``, device count forced by the parent's XLA_FLAGS).

    Two configs: the config2 collection (micro Accuracy scalar states stay
    replicated, macro F1/Precision/Recall per-class vectors shard) and a
    4096-class ConfusionMatrix ((4096, 4096) int32 — the state that motivates
    sharding: 64 MiB per device replicated, 1/width sharded)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, ConfusionMatrix, F1Score, MetricCollection, Precision, Recall
    from metrics_tpu.parallel import count_collectives, make_mesh
    from metrics_tpu.parallel.sync import sync_state as _canonical_sync

    world = int(os.environ.get("BENCH_SHARD_WORLD", "8"))
    devices = jax.devices()
    if len(devices) < world:
        raise RuntimeError(f"expected {world} forced host devices, got {len(devices)}")
    mesh = make_mesh([world], ["data"], devices[:world])

    rng = np.random.default_rng(0)

    def state_bytes(metrics) -> dict:
        """Total per-device and global bytes across all registered leaves."""
        per_dev = glob = 0
        for m in metrics:
            for leaf in jax.tree_util.tree_leaves(m.metric_state):
                n = int(leaf.nbytes)
                glob += n
                shards = getattr(leaf, "addressable_shards", None)
                per_dev += int(shards[0].data.nbytes) if shards else n
        return {"per_device_bytes": per_dev, "global_bytes": glob}

    def sync_bytes(metric_like, members) -> dict:
        """Trace-time collective bytes-by-kind for the live sync routing."""
        out: dict = {}
        with count_collectives() as box:
            for m in members:
                state = {k: v for k, v in m.metric_state.items()}
                jax.make_jaxpr(
                    lambda s, m=m: _canonical_sync(
                        s, dict(m._reductions), "data", shard_axes=m.active_shard_axes
                    ),
                    axis_env=[("data", world)],
                )(state)
        out["collectives_by_kind"] = dict(box["by_kind"])
        out["bytes_by_kind"] = dict(box["bytes_by_kind"])
        return out

    def run_config(build, update_args, n_steps=4):
        # replicated baseline
        base = build()
        for a in update_args[:n_steps]:
            base.update(*a)
        expect = jax.tree_util.tree_map(np.asarray, base.compute())
        base_members = [base] if not isinstance(base, MetricCollection) else list(base.values())

        # sharded run over the same data
        shard = build().shard_state(mesh)
        for a in update_args[:n_steps]:
            shard.update(*a)
        got = jax.tree_util.tree_map(np.asarray, shard.compute())
        if isinstance(shard, MetricCollection):
            shard._realias_members()
        shard_members = [shard] if not isinstance(shard, MetricCollection) else list(shard.values())

        equal = all(
            np.array_equal(a, b)
            for a, b in zip(jax.tree_util.tree_leaves(expect), jax.tree_util.tree_leaves(got))
        )
        rec = {
            "world": world,
            "bitwise_equal_vs_replicated": bool(equal),
            "replicated": {**state_bytes(base_members), **sync_bytes(base, base_members)},
            "sharded": {**state_bytes(shard_members), **sync_bytes(shard, shard_members)},
        }
        return rec

    def build_config2():
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
                "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
                "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
                "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
            }
        )

    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)
    config2 = run_config(build_config2, [(logits, target)] * 4)

    c = 4096
    cm_preds = jnp.asarray(rng.integers(0, c, size=(8192,)), dtype=jnp.int32)
    cm_target = jnp.asarray(rng.integers(0, c, size=(8192,)), dtype=jnp.int32)
    confusion = run_config(lambda: ConfusionMatrix(num_classes=c), [(cm_preds, cm_target)] * 4)

    print(json.dumps({"world": world, "config2": config2, "confusion_4096": confusion}), flush=True)


def bench_sharded_state() -> None:
    """``--sharded-state``: replicated-vs-sharded state footprint and
    collective bytes across mesh widths 1/4/8, recorded into
    ``BENCH_r11.json``. Host-side CPU bench (forced device counts)."""
    widths = (1, 4, 8)
    out = {}
    for w in widths:
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_SHARD_WORLD"] = str(w)
        env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={w}"
        child = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", "sharded_state"],
            capture_output=True,
            text=True,
            env=env,
            timeout=1500.0,
            cwd=REPO,
        )
        if child.returncode != 0:
            raise RuntimeError(f"sharded-state child (world={w}) failed:\n{child.stderr[-2000:]}")
        out[f"world_{w}"] = json.loads(child.stdout.strip().splitlines()[-1])

    w8 = out["world_8"]["confusion_4096"]
    record = {
        # headline: the per-device bytes of the 4096-class confusion matrix at
        # width 8 — lower is better, replicated baseline in extra
        "metric": "sharded_confmat4096_per_device_bytes",
        "value": w8["sharded"]["per_device_bytes"],
        "unit": "bytes",
        "extra": {
            "replicated_per_device_bytes": w8["replicated"]["per_device_bytes"],
            "bitwise_equal_vs_replicated": w8["bitwise_equal_vs_replicated"],
            "sharded_bytes_by_kind": w8["sharded"]["bytes_by_kind"],
            "replicated_bytes_by_kind": w8["replicated"]["bytes_by_kind"],
            "widths": out,
        },
    }
    with open(os.path.join(REPO, "BENCH_r11.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)


def _sharded_compute_child() -> None:
    """``--child sharded_compute``: the gather-free finalize on the 8-device
    CPU mesh (device count forced by the parent's XLA_FLAGS).

    For each config the child traces ``sync_compute_state`` both ways under
    ``count_collectives`` — the reshard fallback
    (``compute_state(sync_states(...))``) vs the shipped routing (which takes
    the ``compute_sharded_state`` protocol for declarers) — and, for the big
    states, times both paths as jitted ``shard_map`` programs over the same
    sharded global state. Protocol metrics must spend zero ``"reshard"``
    bytes and match the replicated twin."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from metrics_tpu import (
        Accuracy,
        BinnedPrecisionRecallCurve,
        ConfusionMatrix,
        F1Score,
        MatthewsCorrCoef,
        MetricCollection,
        Precision,
        Recall,
    )
    from metrics_tpu.parallel import count_collectives, make_mesh

    world = int(os.environ.get("BENCH_SHARD_WORLD", "8"))
    devices = jax.devices()
    if len(devices) < world:
        raise RuntimeError(f"expected {world} forced host devices, got {len(devices)}")
    mesh = make_mesh([world], ["data"], devices[:world])
    rng = np.random.default_rng(0)

    def _activated(m, update_args, n_steps=2):
        """Updated replicated metric with the analyzer-style placement
        sentinel: full-shaped state, ``active_shard_axes`` live, no device
        placement needed for tracing (shard_map splits it at run time)."""
        for a in update_args[:n_steps]:
            m.update(*a)
        state = {k: getattr(m, k) for k in m._defaults}
        m._state_sharding = (mesh, "data")
        return m, state

    def trace_paths(m, state) -> dict:
        """Trace-time bytes-by-kind: reshard fallback vs shipped routing.

        Both functions see what ``shard_map`` would hand them — sharded
        leaves as this device's local block, replicated leaves full-shaped."""
        local = {}
        for k, v in state.items():
            ax = m.active_shard_axes.get(k)
            local[k] = (
                v if ax is None else jax.lax.slice_in_dim(v, 0, v.shape[ax] // world, axis=ax)
            )
        out = {}
        for key, fn in (
            ("fallback", lambda s: m.compute_state(m.sync_states(s, "data"))),
            ("routed", lambda s: m.sync_compute_state(s, "data")),
        ):
            with count_collectives() as box:
                jax.make_jaxpr(fn, axis_env=[("data", world)])(local)
            out[key] = {
                "bytes_by_kind": dict(box["bytes_by_kind"]),
                "collectives_by_kind": dict(box["by_kind"]),
            }
        return out

    def timed_paths(m, state, reps=20) -> dict:
        """us/step for both finalize paths as jitted shard_map programs."""
        in_specs = (
            {k: P("data") if m.active_shard_axes.get(k) is not None else P() for k in state},
        )

        def _program(fn):
            return jax.jit(
                shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=P(), check_rep=False)
            )

        out = {}
        for key, fn in (
            ("fallback", lambda s: m.compute_state(m.sync_states(s, "data"))),
            ("routed", lambda s: m.sync_compute_state(s, "data")),
        ):
            prog = _program(fn)
            jax.block_until_ready(prog(state))  # compile + warm
            ts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(prog(state))
                ts.append((time.perf_counter() - t0) * 1e6)
            out[f"{key}_us_per_step"] = round(float(np.median(ts)), 1)
        routed = _program(lambda s: m.sync_compute_state(s, "data"))
        out["routed_result"] = routed(state)
        return out

    def _equal(a, b, exact=True):
        la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
        cmp = (
            (lambda x, y: np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True))
            if exact
            else (lambda x, y: np.allclose(np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-6))
        )
        return len(la) == len(lb) and all(cmp(x, y) for x, y in zip(la, lb))

    def run_big(build, update_args, exact=True) -> dict:
        ref = build()
        for a in update_args[:2]:
            ref.update(*a)
        expect = ref.compute()
        m, state = _activated(build(), update_args)
        rec = trace_paths(m, state)
        rec.update(timed_paths(m, state))
        rec["equal_vs_replicated"] = bool(_equal(expect, rec.pop("routed_result"), exact))
        rec["supports_protocol"] = bool(m.supports_sharded_compute)
        return rec

    # --- config2: per-member before/after routing ---------------------------
    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)
    config2 = {}
    for name, member in coll.items():
        m, state = _activated(member, [(logits, target)] * 2)
        config2[name] = {
            "supports_protocol": bool(m.supports_sharded_compute),
            **trace_paths(m, state),
        }

    # --- the big states: trace bytes + timed shard_map finalize -------------
    c = 4096
    cm_args = [
        (
            jnp.asarray(rng.integers(0, c, size=(8192,)), dtype=jnp.int32),
            jnp.asarray(rng.integers(0, c, size=(8192,)), dtype=jnp.int32),
        )
        for _ in range(2)
    ]
    confusion = run_big(lambda: ConfusionMatrix(num_classes=c, normalize="true"), cm_args)
    matthews = run_big(lambda: MatthewsCorrCoef(num_classes=c), cm_args)

    bc, bt = 1024, 64
    pr_args = [
        (
            jnp.asarray(rng.random((2048, bc), dtype=np.float32)),
            jnp.asarray(rng.integers(0, bc, size=(2048,)), dtype=jnp.int32),
        )
        for _ in range(2)
    ]
    binned = run_big(
        lambda: BinnedPrecisionRecallCurve(num_classes=bc, thresholds=bt), pr_args
    )

    print(
        json.dumps(
            {
                "world": world,
                "config2": config2,
                "confusion_4096": confusion,
                "matthews_4096": matthews,
                "binned_pr_1024x64": binned,
            }
        ),
        flush=True,
    )


def bench_sharded_compute() -> None:
    """``--sharded-compute``: the sharded-compute protocol on the 8-device
    mesh (reshard bytes before vs after, finalize us/step both ways) plus the
    streaming restore plan's modeled peak vs the gather-everything baseline,
    recorded into ``BENCH_r17.json`` and judged by the regression watchdog.
    Host-side CPU bench (forced device counts in a child process)."""
    import glob as _glob
    import shutil
    import tempfile

    from metrics_tpu.observability import regress as _regress

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_SHARD_WORLD"] = "8"
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", "sharded_compute"],
        capture_output=True,
        text=True,
        env=env,
        timeout=1500.0,
        cwd=REPO,
    )
    if child.returncode != 0:
        raise RuntimeError(f"sharded-compute child failed:\n{child.stderr[-2000:]}")
    mesh8 = json.loads(child.stdout.strip().splitlines()[-1])

    # --- restore: streaming reshard plan vs gather-everything ---------------
    # An 8-host ConfusionMatrix checkpoint folded onto 2 hosts: host 0 claims
    # 4 shards; the plan holds one payload resident at a time.
    from metrics_tpu import ConfusionMatrix
    from metrics_tpu.checkpoint import restore_checkpoint, save_checkpoint

    c, n_hosts, m_hosts = 2048, 8, 2
    rng = np.random.default_rng(1)
    tmp = tempfile.mkdtemp(prefix="bench_reshard_plan_")
    try:
        root = os.path.join(tmp, "ckpt")
        for i in range(n_hosts):
            m = ConfusionMatrix(num_classes=c)
            m.update(
                rng.integers(0, c, size=(4096,)).astype(np.int32),
                rng.integers(0, c, size=(4096,)).astype(np.int32),
            )
            save_checkpoint(m, root, step=0, shard_index=i, world_size=n_hosts)
        t0 = time.perf_counter()
        info = restore_checkpoint(
            ConfusionMatrix(num_classes=c), root, host_index=0, host_count=m_hosts
        )
        restore_ms = round((time.perf_counter() - t0) * 1e3, 3)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    restore = {
        "config": f"confmat{c}_{n_hosts}to{m_hosts}",
        "shards_loaded": list(info.shards_loaded),
        "plan_peak_bytes": int(info.plan_peak_bytes),
        "gather_peak_bytes": int(info.gather_peak_bytes),
        "measured_peak_bytes": int(info.measured_peak_bytes),
        "peak_reduction_x": round(info.gather_peak_bytes / max(1, info.plan_peak_bytes), 2),
        "restore_wall_ms": restore_ms,
    }

    confusion = mesh8["confusion_4096"]
    reshard_after = int(confusion["routed"]["bytes_by_kind"].get("reshard", 0))
    record = {
        # headline: reshard bytes spent by the 4096-class confusion matrix's
        # finalize on the 8-device mesh — the protocol's whole point is zero
        "metric": "sharded_compute_confmat4096_reshard_bytes",
        "value": reshard_after,
        "unit": "bytes",
        "extra": {
            "world": mesh8["world"],
            "fallback_reshard_bytes": int(
                confusion["fallback"]["bytes_by_kind"].get("reshard", 0)
            ),
            "confmat4096_routed_us_per_step": confusion["routed_us_per_step"],
            "confmat4096_fallback_us_per_step": confusion["fallback_us_per_step"],
            "confusion_4096": confusion,
            "matthews_4096": mesh8["matthews_4096"],
            "binned_pr_1024x64": mesh8["binned_pr_1024x64"],
            "config2_members": mesh8["config2"],
            "restore": restore,
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r
        for r in _regress.load_rounds(sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r17"
    ]
    rounds.append(_regress.Round("r17", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r17.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)

    problems = []
    for name, rec in (
        ("confusion_4096", confusion),
        ("matthews_4096", mesh8["matthews_4096"]),
        ("binned_pr_1024x64", mesh8["binned_pr_1024x64"]),
    ):
        got = int(rec["routed"]["bytes_by_kind"].get("reshard", 0))
        if got != 0:
            problems.append(f"{name}: protocol path spent {got} reshard bytes (want 0)")
        if not rec["equal_vs_replicated"]:
            problems.append(f"{name}: sharded finalize diverged from the replicated twin")
    for name in ("precision", "recall"):
        got = int(mesh8["config2"][name]["routed"]["bytes_by_kind"].get("reshard", 0))
        if got != 0:
            problems.append(f"config2.{name}: protocol path spent {got} reshard bytes")
    if int(mesh8["config2"]["f1"]["routed"]["bytes_by_kind"].get("reshard", 0)) == 0:
        problems.append(
            "config2.f1: non-declarer spent zero reshard bytes — the MRO guard "
            "should have routed it through the fallback"
        )
    if not restore["plan_peak_bytes"] < restore["gather_peak_bytes"]:
        problems.append(
            f"restore plan peak {restore['plan_peak_bytes']} not below gather "
            f"baseline {restore['gather_peak_bytes']}"
        )
    if not restore["measured_peak_bytes"] < restore["gather_peak_bytes"]:
        problems.append(
            f"measured restore peak {restore['measured_peak_bytes']} not below "
            f"gather baseline {restore['gather_peak_bytes']}"
        )
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] sharded-compute round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def _quantized_sync_child() -> None:
    """``--child quantized_sync``: the transport codec layer on the 8-device
    CPU mesh (device count forced by the parent's XLA_FLAGS).

    Three configs: the merged config2 state (one int32-sum bucket — the fused
    collection sync), a 4096-class ConfusionMatrix (trace-time wire accounting
    only: 64 MiB logical), and a capacity-256 TenantSet stacked sync. For each
    transport the child records wire-vs-logical bytes from the trace-time
    box, and for config2 also the *measured* max relative error of a real
    shard_map sync against the exact transport plus the jitted sync wall
    time — the error must sit under the abstract E112 bound the analyzer
    reports."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import (
        Accuracy, ConfusionMatrix, F1Score, MetricCollection, Precision, Recall,
    )
    from metrics_tpu.core.metric import Metric
    from metrics_tpu.parallel.sync import (
        count_collectives, sync_stacked_states, sync_state, transport_error_bound,
    )
    from metrics_tpu.tenancy import TenantSet

    world = 8
    devices = jax.devices()
    if len(devices) < world:
        raise RuntimeError(f"expected {world} forced host devices, got {len(devices)}")
    mesh = Mesh(np.asarray(devices[:world]), ("data",))
    rng = np.random.default_rng(0)

    # ---- config2: merged member states, one flat dict (the fused sync) -----
    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)
    coll.update(logits, target)
    flat_state, flat_reds = {}, {}
    for mname, m in coll.items():
        for sname, leaf in m.metric_state.items():
            flat_state[f"{mname}.{sname}"] = jnp.asarray(leaf)
            flat_reds[f"{mname}.{sname}"] = m._reductions[sname]

    def trace_bytes(state, reds, transport, stacked=False, reductions=None):
        transports = (
            None if transport == "exact"
            else {k: transport for k in state}
        )
        with count_collectives() as box:
            if stacked:
                tmap = (
                    None if transport == "exact"
                    else {l: {n: transport for n in st} for l, st in state.items()}
                )
                jax.make_jaxpr(
                    lambda st: sync_stacked_states(st, reductions, "data", transports=tmap),
                    axis_env=[("data", world)],
                )(state)
            else:
                jax.make_jaxpr(
                    lambda st: sync_state(st, reds, "data", transports=transports),
                    axis_env=[("data", world)],
                )(state)
        wire = sum(v["wire"] for v in box["bytes_by_transport"].values())
        logical = sum(v["logical"] for v in box["bytes_by_transport"].values())
        return {
            "wire_bytes": int(wire),
            "logical_bytes": int(logical),
            "by_transport": {k: dict(v) for k, v in box["bytes_by_transport"].items()},
            "collectives": dict(box["by_kind"]),
            "refusals": len(box["refusals"]),
        }

    def measured(transport):
        transports = None if transport == "exact" else {k: transport for k in flat_state}

        def body(s):
            local = jax.tree_util.tree_map(lambda x: x[0], s)
            out = sync_state(local, flat_reds, "data", transports=transports)
            return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), out)

        f = jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False
        ))
        stacked = jax.tree_util.tree_map(
            lambda a: jnp.stack([a * (i + 1) for i in range(world)]), flat_state
        )
        out = jax.block_until_ready(f(stacked))  # compile + first run
        reps = [_timed(lambda: jax.block_until_ready(f(stacked))) for _ in range(5)]
        return out, min(reps) * 1e3

    exact_out, exact_ms = measured("exact")
    config2 = {"transports": {}}
    for t in ("exact", "bf16", "int8"):
        rec = trace_bytes(flat_state, flat_reds, t)
        out, sync_ms = measured(t)
        # error in the bound's own frame: relative to the bucket's
        # max-magnitude exact value (the whole flat concat is one bucket)
        denom = max(
            float(max(np.max(np.abs(np.asarray(v, np.float64))) for v in exact_out.values())),
            1e-30,
        )
        err = max(
            float(np.max(np.abs(np.asarray(out[k], np.float64) - np.asarray(exact_out[k], np.float64))))
            for k in flat_state
        ) / denom
        bound = transport_error_bound(t, world)
        rec.update(
            sync_ms=round(sync_ms, 3),
            max_rel_err=err,
            error_bound=bound,
            wire_reduction_x=round(rec["logical_bytes"] / max(1, rec["wire_bytes"]), 3),
        )
        config2["transports"][t] = rec

    # ---- confmat-4096: trace-time wire accounting only (64 MiB logical) ----
    cm = ConfusionMatrix(num_classes=4096)
    cm.update(
        jnp.asarray(rng.integers(0, 4096, size=(8192,)), dtype=jnp.int32),
        jnp.asarray(rng.integers(0, 4096, size=(8192,)), dtype=jnp.int32),
    )
    cm_state = {k: jnp.asarray(v) for k, v in cm.metric_state.items()}
    confmat = {
        "transports": {
            t: dict(
                trace_bytes(cm_state, dict(cm._reductions), t),
                error_bound=transport_error_bound(t, world),
            )
            for t in ("exact", "bf16", "int8", "sparse_count")
        }
    }

    # ---- tenancy N=256: stacked sync, collective count independent of N ----
    class TinySum(Metric):
        full_state_update = False

        def __init__(self, **kw):
            super().__init__(**kw)
            self.add_state("total", default=jnp.zeros((16,), jnp.float32), dist_reduce_fx="sum")
            self.add_state("count", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

        def update(self, values):
            self.total = self.total + values[:16]
            self.count = self.count + 1.0

        def compute(self):
            return self.total / jnp.maximum(self.count, 1.0)

    def stacked_at(capacity, n_admit):
        ts = TenantSet(MetricCollection({"mean": TinySum()}), capacity=capacity)
        ids = [f"t{i}" for i in range(n_admit)]
        for tid in ids:
            ts.admit(tid)
        ts.update(ids, jnp.ones((n_admit, 16), jnp.float32))
        reds = {
            lname: {n: ts.template._metrics[lname]._reductions[n] for n in st}
            for lname, st in ts.stacked_states.items()
        }
        return ts.stacked_states, reds

    st256, reds256 = stacked_at(256, 37)
    st16, reds16 = stacked_at(16, 3)
    tenancy = {"capacity": 256, "transports": {}}
    for t in ("exact", "bf16", "int8"):
        big = trace_bytes(st256, None, t, stacked=True, reductions=reds256)
        small = trace_bytes(st16, None, t, stacked=True, reductions=reds16)
        big["count_independent_of_n"] = big["collectives"] == small["collectives"]
        tenancy["transports"][t] = big

    print(
        json.dumps({
            "world": world,
            "config2": config2,
            "confmat_4096": confmat,
            "tenancy": tenancy,
        }),
        flush=True,
    )


def _incremental_sync_child() -> None:
    """``--child incremental_sync``: the in-streak emission protocol on the
    8-device CPU mesh.

    Two configs — the merged config2 state (one int32-sum fold bucket) and a
    4096-class ConfusionMatrix (trace-time accounting only: 64 MiB payload) —
    each run as a 16-step streak, deferred (one finalize-time sync burst)
    versus incremental at cadence K in {1, 4, 16}. Records trace-time
    collective counts/bytes per emission and at finalize (the finalize-burst
    elimination claim), measured streak wall time both ways, the retrace count
    after warmup (the recompiles-0 gate), and the async-save overlap timings
    (caller-blocked seconds with and without ``overlap_copy``)."""
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import (
        Accuracy, ConfusionMatrix, F1Score, MetricCollection, Precision, Recall,
        save_checkpoint,
    )
    from metrics_tpu.parallel.sync import (
        advance_incremental, count_collectives, finalize_incremental_state,
        init_incremental, sync_state,
    )

    world = 8
    steps = 16
    devices = jax.devices()
    if len(devices) < world:
        raise RuntimeError(f"expected {world} forced host devices, got {len(devices)}")
    mesh = Mesh(np.asarray(devices[:world]), ("data",))
    rng = np.random.default_rng(0)

    # ---- config2: merged member states, one flat dict (the fused sync) -----
    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)
    coll.update(logits, target)
    flat_state, flat_reds = {}, {}
    for mname, m in coll.items():
        for sname, leaf in m.metric_state.items():
            flat_state[f"{mname}.{sname}"] = jnp.asarray(leaf)
            flat_reds[f"{mname}.{sname}"] = m._reductions[sname]

    def _step_state(st):
        # cheap, dtype-preserving elementwise advance standing in for the
        # member update programs of the donated streak
        return {k: v + jnp.ones_like(v) for k, v in st.items()}

    def measured_config(state, reds):
        modes = {k: "incremental" for k in state}
        stacked = jax.tree_util.tree_map(
            lambda a: jnp.stack([a * (i + 1) for i in range(world)]), state
        )
        smap = dict(mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)

        fin_def = []
        def run_def(s):
            st = jax.tree_util.tree_map(lambda x: x[0], s)
            for _ in range(steps):
                st = _step_state(st)
            with count_collectives() as box:
                out = sync_state(st, reds, "data")
            fin_def.append({"collectives": box["count"], "bytes": box["bytes"]})
            return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), out)

        f_def = jax.jit(shard_map(run_def, **smap))
        jax.block_until_ready(f_def(stacked))
        def_ms = min(_timed(lambda: jax.block_until_ready(f_def(stacked))) for _ in range(5)) * 1e3
        record = {
            "deferred": {
                "streak_ms": round(def_ms, 3),
                "finalize_collectives": fin_def[-1]["collectives"],
                "finalize_bytes": fin_def[-1]["bytes"],
            },
            "incremental": {},
        }

        for k in (1, 4, 16):
            traces = {"n": 0}
            emit_boxes, fin_boxes = [], []

            def run_incr(s, _k=k, _traces=traces, _emit=emit_boxes, _fin=fin_boxes):
                _traces["n"] += 1
                local = jax.tree_util.tree_map(lambda x: x[0], s)
                carry = init_incremental(local, reds, modes=modes, sync_every=_k)
                emits = []
                for _ in range(steps):
                    st = _step_state(carry.state)
                    with count_collectives() as box:
                        carry = advance_incremental(carry, st, reds, "data", modes=modes)
                    if box["count"]:
                        emits.append({"collectives": box["count"], "bytes": box["bytes"]})
                with count_collectives() as box:
                    out = finalize_incremental_state(carry, reds, "data", modes=modes)
                _emit.append(emits)
                _fin.append({"collectives": box["count"], "bytes": box["bytes"]})
                return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), out)

            f_incr = jax.jit(shard_map(run_incr, **smap))
            jax.block_until_ready(f_incr(stacked))
            incr_ms = min(_timed(lambda: jax.block_until_ready(f_incr(stacked))) for _ in range(5)) * 1e3
            emits = emit_boxes[-1]
            record["incremental"][f"k{k}"] = {
                "streak_ms": round(incr_ms, 3),
                "emissions": len(emits),
                "per_emission_collectives": emits[-1]["collectives"] if emits else 0,
                "per_emission_bytes": emits[-1]["bytes"] if emits else 0,
                "finalize_collectives": fin_boxes[-1]["collectives"],
                "finalize_bytes": fin_boxes[-1]["bytes"],
                # 7 warm calls total: any retrace after the first is a broken
                # static-signature set (the carry must not re-key per step)
                "retraces_after_warm": traces["n"] - 1,
            }
        d = record["deferred"]
        k1 = record["incremental"]["k1"]
        record["finalize_burst_reduction_x"] = round(
            d["finalize_bytes"] / max(1, k1["finalize_bytes"]), 3
        )
        return record

    config2 = measured_config(flat_state, flat_reds)

    # ---- confmat-4096: trace-time accounting only (64 MiB payload) ---------
    cm = ConfusionMatrix(num_classes=4096)
    cm.update(
        jnp.asarray(rng.integers(0, 4096, size=(8192,)), dtype=jnp.int32),
        jnp.asarray(rng.integers(0, 4096, size=(8192,)), dtype=jnp.int32),
    )
    cm_state = {k: jnp.asarray(v) for k, v in cm.metric_state.items()}
    cm_reds = dict(cm._reductions)
    cm_modes = {k: "incremental" for k in cm_state}

    def trace_confmat(k):
        emit_boxes, fin_boxes = [], []

        def streak(st0):
            carry = init_incremental(dict(st0), cm_reds, modes=cm_modes, sync_every=k)
            for _ in range(steps):
                st = _step_state(carry.state)
                with count_collectives() as box:
                    carry = advance_incremental(carry, st, cm_reds, "data", modes=cm_modes)
                if box["count"]:
                    emit_boxes.append({"collectives": box["count"], "bytes": box["bytes"]})
            with count_collectives() as box:
                return finalize_incremental_state(carry, cm_reds, "data", modes=cm_modes), fin_boxes.append(
                    {"collectives": box["count"], "bytes": box["bytes"]}
                )

        jax.make_jaxpr(lambda st: streak(st)[0], axis_env=[("data", world)])(cm_state)
        return {
            "emissions": len(emit_boxes),
            "per_emission_bytes": emit_boxes[-1]["bytes"] if emit_boxes else 0,
            "finalize_collectives": fin_boxes[-1]["collectives"],
            "finalize_bytes": fin_boxes[-1]["bytes"],
        }

    with count_collectives() as box:
        jax.make_jaxpr(
            lambda st: sync_state(st, cm_reds, "data"), axis_env=[("data", world)]
        )(cm_state)
    confmat = {
        "deferred": {"finalize_collectives": box["count"], "finalize_bytes": box["bytes"]},
        "incremental": {f"k{k}": trace_confmat(k) for k in (1, 4, 16)},
    }

    # ---- async-save overlap: caller-blocked seconds with/without ----------
    acc = Accuracy(num_classes=NUM_CLASSES)
    acc.update(logits, target)
    with tempfile.TemporaryDirectory() as tmp:
        h_plain = save_checkpoint(acc, os.path.join(tmp, "plain"), blocking=False)
        h_plain.wait()
        h_overlap = save_checkpoint(
            acc, os.path.join(tmp, "overlap"), blocking=False, overlap_copy=True
        )
        h_overlap.wait()
    overlap = {
        "plain_caller_blocked_s": round(
            h_plain.timings["snapshot_s"] + h_plain.timings["host_copy_s"], 6
        ),
        "overlap_caller_blocked_s": round(
            h_overlap.timings["snapshot_s"] + h_overlap.timings["copy_enqueue_s"], 6
        ),
        "plain_host_copy_s": round(h_plain.timings["host_copy_s"], 6),
        "overlap_copy_enqueue_s": round(h_overlap.timings["copy_enqueue_s"], 6),
        "overlap_thread_host_copy_s": round(h_overlap.timings["host_copy_s"], 6),
    }

    print(
        json.dumps({
            "world": world,
            "steps": steps,
            "config2": config2,
            "confmat_4096": confmat,
            "overlap_save": overlap,
        }),
        flush=True,
    )


def bench_incremental_sync() -> None:
    """``--incremental-sync``: the in-streak emission protocol versus the
    deferred finalize burst (config2 merged state and confmat-4096, cadence
    K in {1, 4, 16}) plus the async-save overlap gain; recorded into
    ``BENCH_r20.json`` and judged by the regression watchdog. Host-side CPU
    bench (forced device count in a child process).

    Hard gates: zero finalize collectives at every cadence that divides the
    streak (the residue proof), finalize-burst byte reduction >= 2x on
    config2's fully-mergeable buckets, and zero retraces after warmup at
    every cadence (the bounded carry-signature claim)."""
    import glob as _glob

    from metrics_tpu.observability import regress as _regress

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", "incremental_sync"],
        capture_output=True,
        text=True,
        env=env,
        timeout=1500.0,
        cwd=REPO,
    )
    if child.returncode != 0:
        raise RuntimeError(f"incremental-sync child failed:\n{child.stderr[-2000:]}")
    mesh8 = json.loads(child.stdout.strip().splitlines()[-1])

    c2 = mesh8["config2"]
    record = {
        # headline: the full 16-step incremental streak at K=1 — emissions
        # inside the donated streak, residue-free finalize; lower is better
        "metric": "incremental_sync_config2_k1_streak_ms",
        "value": c2["incremental"]["k1"]["streak_ms"],
        "unit": "ms",
        "extra": {
            "world": mesh8["world"],
            "steps": mesh8["steps"],
            "config2_deferred_streak_ms": c2["deferred"]["streak_ms"],
            "config2_finalize_burst_reduction_x": c2["finalize_burst_reduction_x"],
            "config2": c2,
            "confmat_4096": mesh8["confmat_4096"],
            "overlap_save": mesh8["overlap_save"],
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r
        for r in _regress.load_rounds(sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r20"
    ]
    rounds.append(_regress.Round("r20", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r20.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)

    problems = []
    for k in ("k1", "k4", "k16"):
        for cfg_name, cfg in (("config2", c2), ("confmat_4096", mesh8["confmat_4096"])):
            fin = cfg["incremental"][k]["finalize_collectives"]
            if fin != 0:
                problems.append(
                    f"{cfg_name} {k}: finalize still pays {fin} collectives "
                    "(cadence divides the streak — residue must be empty)"
                )
        retraces = c2["incremental"][k]["retraces_after_warm"]
        if retraces != 0:
            problems.append(f"config2 {k}: {retraces} retraces after warmup (want 0)")
    if c2["finalize_burst_reduction_x"] < 2.0:
        problems.append(
            f"config2 finalize-burst reduction {c2['finalize_burst_reduction_x']}x < 2x"
        )
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] incremental-sync round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def _heavy_kernels_child() -> None:
    """``--child heavy_kernels``: the compiled heavy-kernel layer end to end.

    mAP: 64 ragged synthetic COCO images through the device-resident state
    (pow2-padded CatBuffers + the fused ``iou_matching`` kernel) versus the
    pre-change host-list eager path (``device_state=False``) — update+compute
    wall time, steady-state recompiles read off the kernel trace counters and
    the update-engine stats, results bitwise-compared. BERTScore: pad-on-append
    packed-cache copy work at N versus 4N updates (the amortized-O(1) claim —
    the legacy ``_cat_padded`` re-pad did O(N^2) work over a
    compute-after-every-update stream) plus an interleaved-compute timing
    against the forced fallback, byte-identical scores required. One JSON
    line on stdout."""
    import jax

    from metrics_tpu import BERTScore
    from metrics_tpu.detection import MeanAveragePrecision
    from metrics_tpu.ops import kernels as K

    out = {"platform": jax.default_backend()}

    # ------------------------- mAP end to end ------------------------------ #
    rng = np.random.default_rng(7)
    n_img, n_cls, per_batch = 64, 20, 8

    def boxes(n):
        xy = rng.uniform(0, 400, size=(n, 2))
        wh = rng.uniform(8, 120, size=(n, 2))
        return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)

    preds, targets = [], []
    for _ in range(n_img):
        nd = int(rng.integers(4, 50))
        ng = int(rng.integers(2, 16))
        preds.append({
            "boxes": boxes(nd),
            "scores": rng.uniform(size=(nd,)).astype(np.float32),
            "labels": rng.integers(0, n_cls, size=(nd,)).astype(np.int32),
        })
        targets.append({
            "boxes": boxes(ng),
            "labels": rng.integers(0, n_cls, size=(ng,)).astype(np.int32),
        })
    batches = [
        (preds[i:i + per_batch], targets[i:i + per_batch])
        for i in range(0, n_img, per_batch)
    ]

    def one_pass(metric):
        for p, t in batches:
            metric.update(p, t)
        res = metric.compute()
        jax.block_until_ready(res["map"])
        return res

    def timed(build, reps):
        m = build()
        one_pass(m)  # warmup: every pow2 bucket this stream hits gets traced
        trace_before = dict(K.trace_counts())
        eng = getattr(m, "_update_engine", None)
        misses_before = eng.stats.cache_misses if eng is not None else 0
        best, res = float("inf"), None
        for _ in range(reps):
            m.reset()
            t0 = time.perf_counter()
            res = one_pass(m)
            best = min(best, time.perf_counter() - t0)
        trace_after = dict(K.trace_counts())
        retraces = sum(trace_after.values()) - sum(trace_before.values())
        misses_after = eng.stats.cache_misses if eng is not None else 0
        return best, res, retraces + (misses_after - misses_before)

    device_s, device_res, device_retraces = timed(
        lambda: MeanAveragePrecision(device_state=True), reps=3)
    legacy_s, legacy_res, _ = timed(
        lambda: MeanAveragePrecision(device_state=False), reps=2)
    out["map"] = {
        "n_images": n_img,
        "legacy_eager_s": legacy_s,
        "device_state_s": device_s,
        "e2e_speedup_x": legacy_s / device_s,
        "steady_recompiles": int(device_retraces),
        "parity_bitwise": bool(np.array_equal(
            np.asarray(device_res["map"]), np.asarray(legacy_res["map"]))),
        "map_value": float(np.asarray(device_res["map"])),
        "trace_counts": dict(K.trace_counts()),
    }

    # --------------------------- BERTScore --------------------------------- #
    table = np.random.default_rng(1).normal(
        size=(len(_BERT_VOCAB), _BERT_DIM)).astype(np.float32)

    class VarWidthTok:
        """Width follows the longest sentence in the batch — a ragged stream,
        the shape regime the packed cache has to absorb without re-padding."""

        def __call__(self, sentences):
            width = max(len(s.split()) for s in sentences) + 2
            ids = np.full((len(sentences), width), _BERT_VOCAB.index("[PAD]"), dtype=np.int32)
            mask = np.zeros((len(sentences), width), dtype=np.int32)
            for row, sent in enumerate(sentences):
                tokens = ["[CLS]"] + sent.split()[: width - 2] + ["[SEP]"]
                for col, tok in enumerate(tokens):
                    ids[row, col] = _BERT_VOCAB.index(tok)
                    mask[row, col] = 1
            return {"input_ids": ids, "attention_mask": mask}

    def build_bert():
        return BERTScore(
            model=object(),
            user_tokenizer=VarWidthTok(),
            user_forward_fn=lambda model, b: table[np.asarray(b["input_ids"])],
            max_length=_BERT_MAX_LEN,
            batch_size=64,
        )

    def feed(metric, n_updates, seed=0):
        srng = np.random.default_rng(seed)
        words = _BERT_VOCAB[3:]
        make = lambda: " ".join(srng.choice(words, size=srng.integers(3, 9)))
        for _ in range(n_updates):
            metric.update([make() for _ in range(4)], [make() for _ in range(4)])

    def copied_after(n_updates):
        m = build_bert()
        feed(m, n_updates)
        return m._packed_stats["rows_copied"]

    copied_1x = copied_after(24)
    copied_4x = copied_after(96)
    copied_growth = copied_4x / max(copied_1x, 1)

    def interleaved(force_fallback):
        m = build_bert()
        srng = np.random.default_rng(3)
        words = _BERT_VOCAB[3:]
        make = lambda: " ".join(srng.choice(words, size=srng.integers(3, 9)))
        total, res = 0.0, None
        for i in range(48):
            m.update([make() for _ in range(4)], [make() for _ in range(4)])
            if (i + 1) % 8 == 0:
                if force_fallback:
                    m._packed = {}
                t0 = time.perf_counter()
                res = m.compute()
                total += time.perf_counter() - t0
        return total, np.asarray(res["f1"])

    interleaved(force_fallback=False)  # warmup: both variants hit the same shapes
    packed_s, f1_packed = interleaved(force_fallback=False)
    fallback_s, f1_fallback = interleaved(force_fallback=True)
    out["bert"] = {
        "updates_1x": 24,
        "updates_4x": 96,
        "rows_copied_1x": int(copied_1x),
        "rows_copied_4x": int(copied_4x),
        # linear (amortized O(1) per row) growth is ~4x across a 4x stream;
        # the legacy quadratic re-pad grows ~16x
        "copied_growth_over_4x_stream": copied_growth,
        "interleaved_packed_s": packed_s,
        "interleaved_fallback_s": fallback_s,
        "interleaved_speedup_x": fallback_s / max(packed_s, 1e-9),
        "parity_bitwise": bool(np.array_equal(f1_packed, f1_fallback)),
    }

    print(json.dumps(out), flush=True)


def bench_heavy_kernels() -> None:
    """``--heavy-kernels``: the compiled heavy-kernel layer (ops/kernels/) —
    device-resident mAP through the fused ``iou_matching`` kernel versus the
    pre-change host-list eager path, and the BERTScore pad-on-append packed
    cache versus the quadratic ``_cat_padded`` re-pad; recorded into
    ``BENCH_r21.json`` and judged by the regression watchdog. Host-side CPU
    bench (child process pinned to the CPU backend).

    Hard gates: mAP end-to-end (update+compute, 64 ragged images) >= 3x over
    the eager path with 0 steady-state recompiles after warmup, bitwise mAP
    parity, BERTScore packed copy work growing linearly (not quadratically)
    over a 4x update stream, and byte-identical BERTScore results."""
    import glob as _glob

    from metrics_tpu.observability import regress as _regress

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", "heavy_kernels"],
        capture_output=True,
        text=True,
        env=env,
        timeout=1500.0,
        cwd=REPO,
    )
    if child.returncode != 0:
        raise RuntimeError(f"heavy-kernels child failed:\n{child.stderr[-2000:]}")
    res = json.loads(child.stdout.strip().splitlines()[-1])

    record = {
        # headline: end-to-end 64-image ragged mAP speedup of the
        # device-resident kernel path over the host-list eager path
        "metric": "heavy_map_e2e_speedup_x",
        "value": res["map"]["e2e_speedup_x"],
        "unit": "x",
        "extra": {
            "platform": res["platform"],
            "map": res["map"],
            "bert": res["bert"],
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r
        for r in _regress.load_rounds(sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r21"
    ]
    rounds.append(_regress.Round("r21", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r21.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)

    problems = []
    m = res["map"]
    if m["e2e_speedup_x"] < 3.0:
        problems.append(f"mAP end-to-end speedup {m['e2e_speedup_x']:.2f}x < 3x")
    if m["steady_recompiles"] != 0:
        problems.append(f"mAP device path: {m['steady_recompiles']} steady-state recompiles after warmup (want 0)")
    if not m["parity_bitwise"]:
        problems.append("mAP device-state result differs from the host-list path (bitwise)")
    b = res["bert"]
    if b["copied_growth_over_4x_stream"] > 8.0:
        problems.append(
            f"BERTScore packed copy work grew {b['copied_growth_over_4x_stream']:.1f}x over a "
            "4x update stream (linear is ~4x, the quadratic re-pad is ~16x)"
        )
    if not b["parity_bitwise"]:
        problems.append("BERTScore packed scores differ from the _cat_padded fallback (bitwise)")
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] heavy-kernels round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def bench_quantized_sync() -> None:
    """``--quantized-sync``: wire-byte reduction and measured quantization
    error of the bf16/int8 (and sparse_count) sync transports on the 8-device
    mesh — config2's merged bucketed sync, a 4096-class confusion matrix, and
    a capacity-256 tenancy stacked sync; recorded into ``BENCH_r19.json`` and
    judged by the regression watchdog. Host-side CPU bench (forced device
    count in a child process).

    Hard gates: exact stays bitwise (zero measured error); bf16 cuts config2
    wire bytes >= 1.9x and int8 >= 3.5x; every measured error sits under the
    abstract E112 bound the analyzer reports for the same bucket."""
    import glob as _glob

    from metrics_tpu.observability import regress as _regress

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", "quantized_sync"],
        capture_output=True,
        text=True,
        env=env,
        timeout=1500.0,
        cwd=REPO,
    )
    if child.returncode != 0:
        raise RuntimeError(f"quantized-sync child failed:\n{child.stderr[-2000:]}")
    mesh8 = json.loads(child.stdout.strip().splitlines()[-1])

    c2 = mesh8["config2"]["transports"]
    record = {
        # headline: config2's int8 wire bytes per sync — lower is better,
        # the exact baseline rides in extra
        "metric": "quantized_sync_config2_int8_wire_bytes",
        "value": c2["int8"]["wire_bytes"],
        "unit": "bytes",
        "extra": {
            "world": mesh8["world"],
            "config2_exact_wire_bytes": c2["exact"]["wire_bytes"],
            "config2_bf16_wire_reduction_x": c2["bf16"]["wire_reduction_x"],
            "config2_int8_wire_reduction_x": c2["int8"]["wire_reduction_x"],
            "config2_bf16_max_rel_err": c2["bf16"]["max_rel_err"],
            "config2_int8_max_rel_err": c2["int8"]["max_rel_err"],
            "config2": mesh8["config2"],
            "confmat_4096": mesh8["confmat_4096"],
            "tenancy": mesh8["tenancy"],
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r
        for r in _regress.load_rounds(sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r19"
    ]
    rounds.append(_regress.Round("r19", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r19.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)

    problems = []
    if c2["exact"]["max_rel_err"] != 0.0:
        problems.append(
            f"exact transport measured error {c2['exact']['max_rel_err']} (want bitwise 0)"
        )
    if c2["bf16"]["wire_reduction_x"] < 1.9:
        problems.append(
            f"config2 bf16 wire reduction {c2['bf16']['wire_reduction_x']}x < 1.9x"
        )
    if c2["int8"]["wire_reduction_x"] < 3.5:
        problems.append(
            f"config2 int8 wire reduction {c2['int8']['wire_reduction_x']}x < 3.5x"
        )
    for t in ("bf16", "int8"):
        if c2[t]["max_rel_err"] > c2[t]["error_bound"]:
            problems.append(
                f"config2 {t} measured error {c2[t]['max_rel_err']} exceeds the "
                f"E112 bound {c2[t]['error_bound']}"
            )
        if c2[t]["refusals"]:
            problems.append(f"config2 {t} bucket was refused — nothing was measured")
        if not mesh8["tenancy"]["transports"][t]["count_independent_of_n"]:
            problems.append(f"tenancy {t}: collective count depends on capacity N")
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] quantized-sync round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def _self_tuning_child() -> None:
    """``--child self_tuning``: the ISSUE-17 self-tuning sync controller on
    the 8-device CPU mesh (device count forced by the parent's XLA_FLAGS).

    Three regimes on the merged config2 state: all-exact (the floor the
    tuner must beat), the hand-best declaration from BENCH_r19 (int8
    everywhere), and the tuner starting from nothing — a driver loop that
    re-jits exactly when the decision epoch moves, until every bucket
    commits. Records the converged wire bytes, steady-state jitted sync wall
    time, realized error against the exact sync, retraces after warmup, and
    the decision log; plus the tuned trace-time wire accounting of a
    4096-class confusion matrix and the facade dispatch fast-lane overhead
    (the ``Metric.update()`` hot path vs a raw jit call on the same-shaped
    pytree)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    import metrics_tpu
    from metrics_tpu import (
        Accuracy, ConfusionMatrix, F1Score, MetricCollection, Precision, Recall,
    )
    from metrics_tpu.autotune import controller as _at
    from metrics_tpu.parallel.sync import count_collectives, sync_state

    world = 8
    devices = jax.devices()
    if len(devices) < world:
        raise RuntimeError(f"expected {world} forced host devices, got {len(devices)}")
    mesh = Mesh(np.asarray(devices[:world]), ("data",))
    rng = np.random.default_rng(0)

    # ---- config2: the merged member states, one flat dict ------------------
    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)
    coll.update(logits, target)
    flat_state, flat_reds = {}, {}
    for mname, m in coll.items():
        for sname, leaf in m.metric_state.items():
            flat_state[f"{mname}.{sname}"] = jnp.asarray(leaf)
            flat_reds[f"{mname}.{sname}"] = m._reductions[sname]
    stacked = jax.tree_util.tree_map(
        lambda a: jnp.stack([a * (i + 1) for i in range(world)]), flat_state
    )

    def make_fn(transports=None):
        def body(s):
            local = jax.tree_util.tree_map(lambda x: x[0], s)
            out = sync_state(
                local, flat_reds, "data", bucketed=True, transports=transports
            )
            return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), out)

        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False
        ))

    def trace_wire(transports=None):
        with count_collectives() as box:
            jax.make_jaxpr(
                lambda st: sync_state(
                    st, flat_reds, "data", bucketed=True, transports=transports
                ),
                axis_env=[("data", world)],
            )(flat_state)
        return {
            "wire_bytes": int(sum(v["wire"] for v in box["bytes_by_transport"].values())),
            "logical_bytes": int(sum(v["logical"] for v in box["bytes_by_transport"].values())),
            "by_transport": {k: dict(v) for k, v in box["bytes_by_transport"].items()},
            "refusals": len(box["refusals"]),
        }

    def steady_ms(fn):
        out = jax.block_until_ready(fn(stacked))
        return out, min(
            _timed(lambda: jax.block_until_ready(fn(stacked))) for _ in range(5)
        ) * 1e3

    def rel_err(out, ref):
        denom = max(
            float(max(np.max(np.abs(np.asarray(v, np.float64))) for v in ref.values())),
            1e-30,
        )
        return max(
            float(np.max(np.abs(
                np.asarray(out[k], np.float64) - np.asarray(ref[k], np.float64)
            )))
            for k in ref
        ) / denom

    # the two fixed regimes: all-exact and the BENCH_r19 hand-best (int8)
    metrics_tpu.set_autotune(False)
    exact_out, exact_ms = steady_ms(make_fn({k: "exact" for k in flat_state}))
    exact_rec = dict(trace_wire({k: "exact" for k in flat_state}), sync_ms=round(exact_ms, 3))
    hand = {k: "int8" for k in flat_state}
    hand_out, hand_ms = steady_ms(make_fn(hand))
    hand_rec = dict(
        trace_wire(hand),
        sync_ms=round(hand_ms, 3),
        max_rel_err=rel_err(hand_out, exact_out),
    )

    # ---- the tuner: re-jit on epoch movement until every bucket commits ----
    metrics_tpu.set_autotune(True)
    epoch = _at.decision_epoch()
    fn = make_fn()
    retraces = 0
    for _ in range(48):
        if _at.decision_epoch() != epoch:
            epoch = _at.decision_epoch()
            fn = make_fn()
            retraces += 1
        out = fn(stacked)
    ctl = _at.get_controller()
    converged = all(t.phase == "committed" for t in ctl.buckets.values())
    # warm now: further steps (and one fresh trace) must add zero decisions
    pre = _at.decision_epoch()
    for _ in range(4):
        out = fn(stacked)
    make_fn()(stacked)
    retraces_after_warm = _at.decision_epoch() - pre
    tuned_out, tuned_ms = steady_ms(fn)
    tuned_rec = dict(
        trace_wire(),  # traces with the committed transports
        sync_ms=round(tuned_ms, 3),
        max_rel_err=rel_err(tuned_out, exact_out),
        converged=converged,
        retraces=retraces,
        retraces_after_warm=int(retraces_after_warm),
        decisions=len(ctl.decisions),
        committed={k: t.committed for k, t in sorted(ctl.buckets.items())},
        error_budget=max(
            t.tolerance_for(t.current)
            for t in ctl.buckets.values()
            if t.current not in ("exact", "sparse_count")
        ) if any(
            t.current not in ("exact", "sparse_count") for t in ctl.buckets.values()
        ) else 0.0,
    )
    plan = _at.export_plan().to_dict()

    # ---- confmat-4096: tuned trace-time wire accounting --------------------
    metrics_tpu.set_autotune(True)  # fresh controller for the new universe
    cm = ConfusionMatrix(num_classes=4096)
    cm.update(
        jnp.asarray(rng.integers(0, 4096, size=(8192,)), dtype=jnp.int32),
        jnp.asarray(rng.integers(0, 4096, size=(8192,)), dtype=jnp.int32),
    )
    cm_state = {k: jnp.asarray(v) for k, v in cm.metric_state.items()}
    cm_reds = dict(cm._reductions)
    box_rec = None
    for _ in range(12):
        before = _at.decision_epoch()
        with count_collectives() as box:
            jax.make_jaxpr(
                lambda st: sync_state(st, cm_reds, "data", bucketed=True),
                axis_env=[("data", world)],
            )(cm_state)
        box_rec = {
            "wire_bytes": int(sum(v["wire"] for v in box["bytes_by_transport"].values())),
            "logical_bytes": int(sum(v["logical"] for v in box["bytes_by_transport"].values())),
        }
        cm_ctl = _at.get_controller()
        if _at.decision_epoch() == before and all(
            t.phase == "committed" for t in cm_ctl.buckets.values()
        ):
            break
    confmat = dict(
        box_rec,
        committed={k: t.committed for k, t in sorted(cm_ctl.buckets.items())},
        wire_reduction_x=round(
            box_rec["logical_bytes"] / max(1, box_rec["wire_bytes"]), 3
        ),
    )
    metrics_tpu.set_autotune(None)

    # ---- facade dispatch fast lane (satellite: the update() hot path) ------
    acc = Accuracy(num_classes=4)
    preds = jnp.asarray(rng.normal(size=(32, 4)), dtype=jnp.float32)
    labels = jnp.asarray(rng.integers(0, 4, size=(32,)), dtype=jnp.int32)
    for _ in range(8):
        acc.update(preds, labels)  # warm past the eager-warmup window
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        acc.update(preds, labels)
    jax.block_until_ready(acc.metric_state["tp"])
    facade_us = (time.perf_counter() - t0) / n * 1e6
    raw_state = {k: jnp.asarray(v) for k, v in acc.metric_state.items()}
    raw_fn = jax.jit(lambda s: {k: v + 1 for k, v in s.items()})
    raw_out = jax.block_until_ready(raw_fn(raw_state))
    t0 = time.perf_counter()
    for _ in range(n):
        raw_out = raw_fn(raw_out)
    jax.block_until_ready(raw_out["tp"])
    raw_us = (time.perf_counter() - t0) / n * 1e6
    stats = acc.engine_stats()["update"]
    dispatch = {
        "facade_us_per_update": round(facade_us, 2),
        "raw_jit_us_per_call": round(raw_us, 2),
        "facade_overhead_us": round(facade_us - raw_us, 2),
        "key_fast_hits": int(stats.key_fast_hits),
        "cache_hits": int(stats.cache_hits),
        "eager_calls": int(stats.eager_calls),
    }

    print(
        json.dumps({
            "world": world,
            "config2": {
                "exact": exact_rec,
                "hand_best_int8": hand_rec,
                "tuned": tuned_rec,
            },
            "tuned_plan": plan,
            "confmat_4096": confmat,
            "dispatch": dispatch,
        }),
        flush=True,
    )


def bench_self_tuning() -> None:
    """``--self-tuning``: the self-tuning sync controller end to end on the
    8-device mesh — tuned vs hand-best vs all-exact on config2's merged sync
    plus a tuned 4096-class confusion matrix — and the facade dispatch
    fast-lane overhead; recorded into ``BENCH_r22.json`` and judged by the
    regression watchdog. Host-side CPU bench (forced device count in a child
    process).

    Hard gates: the tuner converges (every bucket committed) with zero
    retraces after warmup; realized error <= the error budget; tuned wire
    bytes within 10% of the BENCH_r19 hand-best declaration; the facade
    fast-lane is live (key_fast_hits > 0) and its dispatch overhead over a
    raw jit call stays under 120 µs."""
    import glob as _glob

    from metrics_tpu.observability import regress as _regress

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", "self_tuning"],
        capture_output=True,
        text=True,
        env=env,
        timeout=1500.0,
        cwd=REPO,
    )
    if child.returncode != 0:
        raise RuntimeError(f"self-tuning child failed:\n{child.stderr[-2000:]}")
    mesh8 = json.loads(child.stdout.strip().splitlines()[-1])

    c2 = mesh8["config2"]
    tuned, hand, exact = c2["tuned"], c2["hand_best_int8"], c2["exact"]
    record = {
        # headline: config2's tuned wire bytes per sync — lower is better;
        # the hand-best and exact baselines ride in extra
        "metric": "self_tuning_config2_tuned_wire_bytes",
        "value": tuned["wire_bytes"],
        "unit": "bytes",
        "extra": {
            "world": mesh8["world"],
            "config2_exact_wire_bytes": exact["wire_bytes"],
            "config2_hand_best_wire_bytes": hand["wire_bytes"],
            "config2_tuned_vs_hand_best_x": round(
                tuned["wire_bytes"] / max(1, hand["wire_bytes"]), 3
            ),
            "config2_tuned_sync_ms": tuned["sync_ms"],
            "config2_tuned_max_rel_err": tuned["max_rel_err"],
            "config2_tuned_retraces_after_warm": tuned["retraces_after_warm"],
            "config2": c2,
            "confmat_4096": mesh8["confmat_4096"],
            "dispatch": mesh8["dispatch"],
            "tuned_plan_buckets": mesh8["tuned_plan"]["buckets"],
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r
        for r in _regress.load_rounds(sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r22"
    ]
    rounds.append(_regress.Round("r22", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r22.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)

    problems = []
    if not tuned["converged"]:
        problems.append("tuner did not commit every config2 bucket in budget")
    if tuned["retraces_after_warm"] != 0:
        problems.append(
            f"{tuned['retraces_after_warm']} retraces after warmup (want 0)"
        )
    if tuned["error_budget"] and tuned["max_rel_err"] > tuned["error_budget"]:
        problems.append(
            f"tuned realized error {tuned['max_rel_err']} exceeds the "
            f"budget {tuned['error_budget']}"
        )
    if tuned["wire_bytes"] > 1.10 * hand["wire_bytes"]:
        problems.append(
            f"tuned wire bytes {tuned['wire_bytes']} not within 10% of the "
            f"hand-best {hand['wire_bytes']}"
        )
    if tuned["refusals"]:
        problems.append("the converged tuned trace still hit gate refusals")
    dispatch = mesh8["dispatch"]
    if dispatch["key_fast_hits"] <= 0:
        problems.append("facade fast lane never hit (key_fast_hits == 0)")
    if dispatch["facade_overhead_us"] > 120.0:
        problems.append(
            f"facade dispatch overhead {dispatch['facade_overhead_us']} µs "
            "over a raw jit call exceeds the 120 µs gate"
        )
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] self-tuning round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def _sketches_child() -> None:
    """``--child sketches``: mergeable sketch states vs the CatBuffer gather
    on the 8-device CPU mesh (device count forced by the parent's XLA_FLAGS).

    One million lognormal samples. The CatBuffer path must gather every
    per-device row on sync (wire grows with N); the QuantileSketch path syncs
    a fixed ~16 KB of bucket counts whatever N is. Records the traced wire
    accounting for both, the realized quantile error of the sketch against
    the exact ``np.quantile`` at N=1e6, bitwise merge-order invariance across
    1/2/4/8-way shardings, and the jitted insert throughput."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from metrics_tpu import Quantile
    from metrics_tpu.core.buffers import CatBuffer
    from metrics_tpu.parallel.sync import count_collectives, sync_state
    from metrics_tpu.sketches import QuantileSketch

    world = 8
    devices = jax.devices()
    if len(devices) < world:
        raise RuntimeError(f"expected {world} forced host devices, got {len(devices)}")
    mesh = Mesh(np.asarray(devices[:world]), ("data",))
    rng = np.random.default_rng(0)

    n_total = 1_000_000
    per_dev = n_total // world
    data = rng.lognormal(mean=1.0, sigma=1.2, size=n_total).astype(np.float32)

    # ---- traced wire accounting: what one sync moves ----------------------
    def trace_wire(state, reds):
        with count_collectives() as box:
            jax.make_jaxpr(
                lambda st: sync_state(st, reds, "data", bucketed=True),
                axis_env=[("data", world)],
            )(state)
        return {
            "wire_bytes": int(sum(v["wire"] for v in box["bytes_by_transport"].values())),
            "logical_bytes": int(sum(v["logical"] for v in box["bytes_by_transport"].values())),
        }

    cat_state = {"value": CatBuffer.from_array(jnp.asarray(data[:per_dev]), capacity=per_dev)}
    cat_rec = trace_wire(cat_state, {"value": "cat"})
    # the gather's real cost: every device receives the other shards' rows and
    # materializes all N of them — wire_bytes above only counts what one
    # device *sends* (N/world rows)
    cat_rec["gathered_bytes"] = int(world * cat_rec["wire_bytes"])
    cat_rec["host_state_bytes"] = int(per_dev * 4)

    sketch = QuantileSketch().insert(jnp.asarray(data[:per_dev]))
    sketch_rec = trace_wire({"sketch": sketch}, {"sketch": "sketch"})
    # elementwise psum/pmax: the synced state each device holds is the same
    # fixed-size sketch, independent of N and world
    sketch_rec["gathered_bytes"] = int(sketch_rec["wire_bytes"])
    sketch_rec["host_state_bytes"] = int(sketch.state_nbytes)

    # ---- realized quantile error at N=1e6 (jitted chunk inserts) ----------
    m = Quantile(q=[0.01, 0.5, 0.99])
    insert = jax.jit(lambda s, x: s.insert(x))
    chunk = 65536
    sk = m.sketch
    t0 = time.perf_counter()
    for lo in range(0, n_total, chunk):
        sk = insert(sk, jnp.asarray(data[lo:lo + chunk]))
    jax.block_until_ready(sk.pos)
    insert_s = time.perf_counter() - t0
    qs = np.asarray([0.01, 0.5, 0.99], np.float32)
    got = np.asarray(sk.quantile(jnp.asarray(qs)))
    exact = np.quantile(data, qs, method="inverted_cdf")
    max_rel_err = float(np.max(np.abs(got - exact) / exact))
    gamma = float(sk.error_bound()["value"])

    # ---- bitwise merge-order invariance across shard counts ---------------
    whole = QuantileSketch().insert(jnp.asarray(data[: 8 * 4096]))
    invariant = True
    for shards in (1, 2, 4, 8):
        parts = [
            QuantileSketch().insert(jnp.asarray(c))
            for c in np.array_split(data[: 8 * 4096], shards)
        ]
        folded = parts[0]
        for p in parts[1:]:
            folded = folded.merge(p)
        for fname, _ in whole.sketch_fields:
            if not np.array_equal(np.asarray(getattr(folded, fname)), np.asarray(getattr(whole, fname))):
                invariant = False

    # ---- the synced mesh estimate agrees with the whole stream ------------
    mq = Quantile(q=0.5)

    def body(x):
        state = mq.update_state(mq.init_state(), jnp.ravel(x))
        state = mq.sync_states(state, "data")
        return jnp.atleast_1d(mq.compute_state(state))

    synced = np.asarray(
        jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False))(
            jnp.asarray(data).reshape(world, per_dev)
        )
    )
    mesh_agrees = bool(np.all(synced == synced[0]))
    mesh_rel_err = float(
        abs(synced[0] - np.quantile(data, 0.5, method="inverted_cdf"))
        / np.quantile(data, 0.5, method="inverted_cdf")
    )

    print(json.dumps(_round({
        "world": world,
        "n_total": n_total,
        "catbuffer": cat_rec,
        "sketch": sketch_rec,
        # headline: bytes every device must receive + materialize for one
        # CatBuffer gather vs the sketch's fixed sync payload
        "gather_reduction_x": cat_rec["gathered_bytes"] / max(1, sketch_rec["gathered_bytes"]),
        "sent_wire_reduction_x": cat_rec["wire_bytes"] / max(1, sketch_rec["wire_bytes"]),
        "host_reduction_x": cat_rec["host_state_bytes"] / max(1, sketch_rec["host_state_bytes"]),
        "quantile_max_rel_err": max_rel_err,
        "quantile_error_bound": gamma,
        "merge_order_bitwise_invariant": invariant,
        "mesh_devices_agree_bitwise": mesh_agrees,
        "mesh_median_rel_err": mesh_rel_err,
        "insert_throughput_msamples_per_s": n_total / insert_s / 1e6,
    })), flush=True)


def bench_sketches() -> None:
    """``--sketches``: bounded-memory sketch states vs the CatBuffer gather at
    N=1e6 on the 8-device mesh; recorded into ``BENCH_r23.json`` and judged by
    the regression watchdog. Host-side CPU bench (forced device count in a
    child process).

    Hard gates: sketch sync wire bytes >= 50x below the CatBuffer gather;
    realized quantile error <= the declared rank-error bound; bitwise
    merge-order invariance across 1/2/4/8-way shardings; all mesh devices
    agree bitwise after sync."""
    import glob as _glob

    from metrics_tpu.observability import regress as _regress

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", "sketches"],
        capture_output=True,
        text=True,
        env=env,
        timeout=1500.0,
        cwd=REPO,
    )
    if child.returncode != 0:
        raise RuntimeError(f"sketches child failed:\n{child.stderr[-2000:]}")
    mesh8 = json.loads(child.stdout.strip().splitlines()[-1])

    record = {
        # headline: how many times fewer bytes one sync makes each device
        # receive + materialize with the sketch state than with the CatBuffer
        # gather (which hands every device all N rows) — higher is better
        "metric": "sketch_vs_catbuffer_gather_reduction_x",
        "value": mesh8["gather_reduction_x"],
        "unit": "x",
        "extra": {
            "world": mesh8["world"],
            "n_total": mesh8["n_total"],
            "catbuffer_gathered_bytes": mesh8["catbuffer"]["gathered_bytes"],
            "sketch_wire_bytes": mesh8["sketch"]["wire_bytes"],
            "sent_wire_reduction_x": mesh8["sent_wire_reduction_x"],
            "host_reduction_x": mesh8["host_reduction_x"],
            "quantile_max_rel_err": mesh8["quantile_max_rel_err"],
            "quantile_error_bound": mesh8["quantile_error_bound"],
            "merge_order_bitwise_invariant": mesh8["merge_order_bitwise_invariant"],
            "mesh_devices_agree_bitwise": mesh8["mesh_devices_agree_bitwise"],
            "mesh_median_rel_err": mesh8["mesh_median_rel_err"],
            "insert_throughput_msamples_per_s": mesh8["insert_throughput_msamples_per_s"],
            "catbuffer": mesh8["catbuffer"],
            "sketch": mesh8["sketch"],
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r
        for r in _regress.load_rounds(sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r23"
    ]
    rounds.append(_regress.Round("r23", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r23.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)

    problems = []
    if mesh8["gather_reduction_x"] < 50.0:
        problems.append(
            f"sketch sync gather reduction {mesh8['gather_reduction_x']}x below the 50x gate"
        )
    if mesh8["quantile_max_rel_err"] > mesh8["quantile_error_bound"]:
        problems.append(
            f"realized quantile error {mesh8['quantile_max_rel_err']} exceeds "
            f"the declared bound {mesh8['quantile_error_bound']}"
        )
    if not mesh8["merge_order_bitwise_invariant"]:
        problems.append("sketch merge is not bitwise order-invariant across shardings")
    if not mesh8["mesh_devices_agree_bitwise"]:
        problems.append("mesh devices disagree after a sketch sync")
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] sketches round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def bench_observability() -> None:
    """``--observability``: tracer on/off overhead on the config2 fused
    update (the ISSUE-7 hard rule: tracer *off* must not move the 4x fused
    win; tracer *on* cost is recorded, not gated) plus the event-volume
    profile of a traced eval loop (updates + compute + checkpoint save),
    recorded into ``BENCH_r12.json``. Host-side CPU bench."""
    import shutil
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall, observability
    from metrics_tpu.checkpoint import save_checkpoint

    def build():
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
                "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
                "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
                "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
            }
        )

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    def fused_us_per_step(coll, steps=STEPS, reps=3):
        for _ in range(WARMUP):
            coll.update(logits, target)

        def one_rep():
            coll.reset()
            t0 = time.perf_counter()
            for _ in range(steps):
                coll.update(logits, target)
            jax.block_until_ready(next(iter(coll.values())).get_state())
            return (time.perf_counter() - t0) / steps * 1e6

        return min(one_rep() for _ in range(reps))

    observability.disable()
    off_us = fused_us_per_step(build())
    observability.enable()
    try:
        on_us = fused_us_per_step(build())
    finally:
        observability.disable()

    # PR-6 baseline for the same config. The r08 recording is from a
    # different run/day, so machine drift dwarfs a one-branch flag check;
    # BENCH_OBS_BASELINE_US lets a driver pass a baseline re-measured under
    # current conditions (run the probe from a pre-observability checkout in
    # the same session) — that is the number the <3% bound is against.
    baseline_us, baseline_source = None, None
    if os.environ.get("BENCH_OBS_BASELINE_US"):
        baseline_us = float(os.environ["BENCH_OBS_BASELINE_US"])
        baseline_source = "remeasured_pr6"
    else:
        try:
            with open(os.path.join(REPO, "BENCH_r08.json")) as fh:
                tail = json.load(fh)["tail"]
            baseline_us = json.loads(tail)["extra"]["config2_collection_1k"]["fused_update"][
                "fused_update_us_per_step"
            ]
            baseline_source = "BENCH_r08_recorded"
        except Exception:
            pass

    # event-volume profile: traced eval loop — updates, compute, checkpoint
    # save — then export + validate the Chrome trace it produces
    tmp = tempfile.mkdtemp(prefix="mtpu-obs-bench-")
    try:
        with observability.trace() as tracer:
            coll = build()
            for _ in range(8):
                coll.update(logits, target)
            jax.block_until_ready(coll.compute())
            save_checkpoint(coll, os.path.join(tmp, "ckpt"))
            doc = observability.to_chrome_trace(tracer)
        problems = observability.validate_chrome_trace(doc)
        volume = dict(tracer.counts_by_name())
        dropped = tracer.dropped
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    record = {
        "metric": "observability_tracer_off_overhead_pct",
        # headline: what the disabled tracer costs the fused update vs the
        # PR-6 baseline (the <3% acceptance bound); same-process on/off in
        # extra is the jitter-free cross-check
        "value": round((off_us / baseline_us - 1.0) * 100, 2) if baseline_us else None,
        "unit": "%",
        "extra": {
            "config": "config2_collection",
            "num_classes": NUM_CLASSES,
            "fused_update_us_per_step_tracer_off": round(off_us, 2),
            "fused_update_us_per_step_tracer_on": round(on_us, 2),
            "tracer_on_overhead_pct": round((on_us / off_us - 1.0) * 100, 2),
            "baseline_fused_update_us_per_step": baseline_us,
            "baseline_source": baseline_source,
            "eval_loop_event_volume": volume,
            "eval_loop_events_total": sum(volume.values()),
            "eval_loop_events_dropped": dropped,
            "chrome_trace_valid": not problems,
        },
    }
    with open(os.path.join(REPO, "BENCH_r12.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)


def bench_offhost() -> None:
    """``--offhost`` (also run by ``--observability``): the off-host telemetry
    loop measured end to end — scrape latency of the live HTTP server while a
    fused-update streak populates the registry, the 8-host shard merge +
    device correlation wall time, and the regression watchdog's self-check
    over the whole checked-in BENCH trajectory including this round —
    recorded into ``BENCH_r13.json``. Host-side CPU bench."""
    import glob as _glob
    import statistics
    import urllib.request

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall, observability
    from metrics_tpu.observability import regress as _regress
    from metrics_tpu.observability import shards as _shards

    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    observability.enable()
    try:
        server = observability.serve(port=0)
        for _ in range(WARMUP):
            coll.update(logits, target)
        for _ in range(STEPS):
            coll.update(logits, target)
        jax.block_until_ready(coll.compute())

        def scrape_ms(endpoint, n=30):
            times, size = [], 0
            for _ in range(n):
                t0 = time.perf_counter()
                with urllib.request.urlopen(server.url + endpoint, timeout=10) as resp:
                    size = len(resp.read())
                times.append((time.perf_counter() - t0) * 1e3)
            times.sort()
            return {
                "p50_ms": round(statistics.median(times), 3),
                "p95_ms": round(times[int(0.95 * (len(times) - 1))], 3),
                "payload_bytes": size,
            }

        scrape = {ep.strip("/").replace(".", "_"): scrape_ms(ep)
                  for ep in ("/metrics", "/trace", "/healthz")}

        # multi-host merge: N shards of this buffer under distinct host ids
        hosts = 8
        base = _shards.build_trace_shard(host_id="h0")
        shard_docs = [json.loads(json.dumps(base)) for _ in range(hosts)]
        for i, doc in enumerate(shard_docs):
            doc["otherData"]["shard"]["host_id"] = f"h{i}"
        t0 = time.perf_counter()
        merged = _shards.merge_trace_shards(shard_docs)
        merge_wall_ms = (time.perf_counter() - t0) * 1e3
        merged_valid = not observability.validate_chrome_trace(merged)

        # correlation against a synthetic device trace mirroring the streak's
        # dispatch spans under their TraceAnnotation names
        device_events = []
        for rec in merged["traceEvents"]:
            args = rec.get("args") or {}
            if rec.get("ph") == "M" or "owner" not in args or "kind" not in args:
                continue
            device_events.append({
                "name": _shards.dispatch_annotation(args["owner"], args["kind"]),
                "cat": "device", "ph": "X", "ts": rec["ts"] + 40_000,
                "dur": max(1, rec.get("dur", 1)), "pid": 99, "tid": 0,
            })
        t0 = time.perf_counter()
        combined = _shards.correlate_device_trace(merged, {"traceEvents": device_events})
        correlate_wall_ms = (time.perf_counter() - t0) * 1e3
        correlation = combined["otherData"]["correlation"]
    finally:
        observability.shutdown()
        observability.disable()

    record = {
        # headline: what one /metrics scrape of a live streak costs — the
        # per-poll price an external Prometheus pays
        "metric": "offhost_scrape_metrics_p50_ms",
        "value": scrape["metrics"]["p50_ms"],
        "unit": "ms",
        "extra": {
            "config": "config2_collection",
            "num_classes": NUM_CLASSES,
            "streak_steps": STEPS,
            "scrape": scrape,
            "merge": {
                "hosts": hosts,
                "events_per_shard": sum(
                    1 for r in base["traceEvents"] if r.get("ph") != "M"),
                "merge_wall_ms": round(merge_wall_ms, 3),
                "merged_valid": merged_valid,
                "correlate_wall_ms": round(correlate_wall_ms, 3),
                "correlated_matched": correlation["matched"],
                "correlated_host_dispatches": correlation["host_dispatches"],
            },
        },
    }

    # the watchdog self-check: judge this round (in memory) against the
    # checked-in trajectory before recording it
    rounds = [
        r for r in _regress.load_rounds(
            sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r13"
    ]
    rounds.append(_regress.Round("r13", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r13.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)
    if not report.ok:
        print("[bench] offhost round REGRESSED vs rolling baseline:", file=sys.stderr)
        for r in report.regressions:
            print(f"[bench]   {r.describe()}", file=sys.stderr)
        sys.exit(1)


def bench_partitioned_dispatch() -> None:
    """``--partitioned-dispatch``: the ISSUE-9 headline — config2 plus one
    host-readback straggler plus one ``batch_buckets`` member, partitioned
    dispatch (fused majority + bucketed + eager straggler) vs the pre-PR
    behaviour where one untraceable member demoted the *whole* collection to
    the eager loop. Computes must be bitwise-identical between the two arms;
    recorded into ``BENCH_r14.json``. Host-side CPU bench."""
    import glob as _glob

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1Score, Metric, MetricCollection, Precision, Recall
    from metrics_tpu.observability import regress as _regress

    class HostReadback(Metric):
        """An untraceable straggler: the host round-trip breaks the fused
        trace probe, so the dispatcher migrates it to the eager set."""

        full_state_update = False

        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

        def update(self, preds, target):
            self.total = self.total + float(jnp.sum(target))

        def compute(self):
            return self.total

    class BucketedPositives(Metric):
        """A ragged-batch counter under pow2 bucketing: bucket padding rows
        are zeros, so the padded sum is exact."""

        full_state_update = False

        def __init__(self, **kwargs):
            super().__init__(batch_buckets=True, **kwargs)
            self.add_state("total", default=jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

        def update(self, preds, target):
            self.total = self.total + jnp.sum(target).astype(jnp.float32)

        def compute(self):
            return self.total

    def build(**coll_kwargs):
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
                "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
                "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
                "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
                "host": HostReadback(),
                "bucketed_pos": BucketedPositives(),
            },
            **coll_kwargs,
        )

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    def one_rep(coll, steps=STEPS):
        coll.reset()
        t0 = time.perf_counter()
        for _ in range(steps):
            coll.update(logits, target)
        jax.block_until_ready(next(iter(coll.values())).get_state())
        return (time.perf_counter() - t0) / steps * 1e6

    # "before" arm: the whole-collection eager demotion — one untraceable
    # member used to revert everything to the per-member eager loop, the same
    # baseline PR 3's 4.03x fused win was measured against. Reps of the three
    # arms are interleaved so host noise (thermal / scheduler drift) hits
    # them evenly instead of biasing whichever arm ran last.
    demoted = build(fused_update=False, compute_groups=False)
    grouped = build(fused_update=False)
    partitioned = build()
    arms = (demoted, grouped, partitioned)
    for coll in arms:
        for _ in range(WARMUP):
            coll.update(logits, target)
    reps = {id(coll): [] for coll in arms}
    for _ in range(5):
        for coll in arms:
            reps[id(coll)].append(one_rep(coll))
    eager_us = min(reps[id(demoted)])
    grouped_us = min(reps[id(grouped)])
    part_us = min(reps[id(partitioned)])
    stats = partitioned.engine_stats()
    part_view = stats["partition"]

    # numeric parity: same stream through both arms, computes must match bitwise
    ref, ours = build(fused_update=False, compute_groups=False), build()
    for i in range(6):
        chunk_logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
        chunk_target = jnp.asarray(
            rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32
        )
        ref.update(chunk_logits, chunk_target)
        ours.update(chunk_logits, chunk_target)
    ref_vals, our_vals = ref.compute(), ours.compute()
    bitwise = set(ref_vals) == set(our_vals) and all(
        np.asarray(ref_vals[k]).tobytes() == np.asarray(our_vals[k]).tobytes()
        for k in ref_vals
    )

    speedup = eager_us / part_us if part_us else None
    record = {
        # headline: what partition-aware dispatch buys back on a collection
        # that the old engine would have demoted wholesale
        "metric": "partitioned_dispatch_speedup",
        "value": round(speedup, 2) if speedup else None,
        "unit": "x",
        "extra": {
            "config": "config2_plus_straggler_plus_bucketed",
            "num_classes": NUM_CLASSES,
            "batch": BATCH,
            "partitioned_us_per_step": round(part_us, 2),
            "eager_demotion_us_per_step": round(eager_us, 2),
            "grouped_eager_us_per_step": round(grouped_us, 2),
            "partition_speedup": round(speedup, 2) if speedup else None,
            "vs_grouped_eager": round(grouped_us / part_us, 2) if part_us else None,
            "bitwise_identical": bool(bitwise),
            "partition": {
                "update": {
                    name: info["path"] for name, info in part_view["update"].items()
                },
                "compute": {
                    name: info["path"] for name, info in part_view["compute"].items()
                },
                "builds": part_view["builds"],
                "repartitions": part_view["repartitions"],
                "migrations": part_view["migrations"],
                "stable_hits": part_view["stable_hits"],
            },
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r for r in _regress.load_rounds(
            sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r14"
    ]
    rounds.append(_regress.Round("r14", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r14.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)
    problems = []
    if not bitwise:
        problems.append("partitioned computes are not bitwise-identical to the eager arm")
    if speedup is not None and speedup < 3.0:
        problems.append(f"partition speedup {speedup:.2f}x is below the 3x acceptance floor")
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] partitioned-dispatch round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def bench_resilience() -> None:
    """``--resilience``: the ISSUE-10 resilience layer measured end to end —
    the per-op cost of the retry wrapper every storage byte now funnels
    through, the chaos harness's disabled/armed overhead on the config2 fused
    update (the tracer-off discipline: *disabled* must cost nothing), a
    3-seed deterministic chaos sweep (engine faults + flaky storage) asserting
    the final compute is bitwise-equal to the fault-free run, and the
    probation re-promotion latency in dispatches — recorded into
    ``BENCH_r15.json`` and judged by the regression watchdog. Host-side CPU
    bench."""
    import contextlib
    import glob as _glob

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from metrics_tpu import (
        Accuracy,
        F1Score,
        MetricCollection,
        Precision,
        Recall,
        set_probation,
    )
    from metrics_tpu.checkpoint import (
        InMemoryStorage,
        restore_checkpoint,
        save_checkpoint,
        use_storage,
    )
    from metrics_tpu.observability import regress as _regress
    from metrics_tpu.resilience import FaultSpec, RetryPolicy, call_with_retry
    from metrics_tpu.resilience import chaos as _chaos

    def build():
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
                "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
                "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
                "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
            }
        )

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    # --- retry-path overhead: what call_with_retry costs per successful op --
    # (the wrapper runs on EVERY storage op now — its happy-path cost is the
    # per-byte tax of the resilience layer, so it gets measured, not assumed)
    n_ops = 50_000

    def noop():
        return None

    policy = RetryPolicy(seed=0)
    jrng = policy.rng()
    for _ in range(1000):  # warm both paths
        noop()
        call_with_retry(noop, policy, op="bench", rng=jrng)
    t0 = time.perf_counter()
    for _ in range(n_ops):
        noop()
    raw_us = (time.perf_counter() - t0) / n_ops * 1e6
    t0 = time.perf_counter()
    for _ in range(n_ops):
        call_with_retry(noop, policy, op="bench", rng=jrng)
    wrapped_us = (time.perf_counter() - t0) / n_ops * 1e6

    # --- chaos disabled vs armed-but-silent on the fused update ------------
    def fused_us_per_step(coll, steps=STEPS, reps=3):
        for _ in range(WARMUP):
            coll.update(logits, target)

        def one_rep():
            coll.reset()
            t0 = time.perf_counter()
            for _ in range(steps):
                coll.update(logits, target)
            jax.block_until_ready(next(iter(coll.values())).get_state())
            return (time.perf_counter() - t0) / steps * 1e6

        return min(one_rep() for _ in range(reps))

    off_us = fused_us_per_step(build())
    # armed with a spec that never fires: pays the full plan-consult path
    # (lock + spec scan) every dispatch — the honest upper bound on what a
    # *quiet* armed harness costs
    with _chaos.plan([FaultSpec("engine/dispatch", nth=10**9)], seed=0):
        armed_us = fused_us_per_step(build())

    # --- 3-seed chaos sweep: faulty final compute must equal fault-free ----
    steps_total = 24
    batches = []
    for _ in range(steps_total):
        batches.append(
            (
                jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32),
                jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32),
            )
        )

    def eval_loop(seed=None):
        """Update streak -> checkpoint save -> restore into a fresh
        collection -> compute, optionally under a seeded fault plan."""
        specs = [
            # one compiled-dispatch fault: fallback + migration + probation
            FaultSpec("engine/dispatch", nth=5, times=1),
            # flaky storage: deterministic every-Nth transient errors (the
            # retry wrapper's next attempt is the N+1th call and succeeds)
            FaultSpec("storage/write", every=7, times=4),
            FaultSpec("storage/read", every=5, times=4),
            # seed-sensitive flakiness on the read path
            FaultSpec("storage/read", probability=0.2, times=3),
        ]
        store = InMemoryStorage()
        set_probation(3)
        try:
            with contextlib.ExitStack() as stack:
                stack.enter_context(use_storage(store))
                plan_ = None
                if seed is not None:
                    plan_ = stack.enter_context(_chaos.plan(specs, seed=seed))
                coll = build()
                for lg, tg in batches:
                    coll.update(lg, tg)
                save_checkpoint(coll, "bench-resilience/ckpt", world_size=1, shard_index=0)
                fresh = build()
                restore_checkpoint(fresh, "bench-resilience/ckpt", host_count=1)
                vals = fresh.compute()
                fired = plan_.fired() if plan_ is not None else 0
            return {k: np.asarray(v).tobytes() for k, v in vals.items()}, fired
        finally:
            set_probation(None)

    baseline, _ = eval_loop(seed=None)
    sweep = {}
    for seed in (0, 1, 2):
        vals, fired = eval_loop(seed=seed)
        sweep[f"seed{seed}"] = {
            "bitwise_equal": vals == baseline,
            "faults_fired": fired,
        }
    pass_rate = sum(1 for s in sweep.values() if s["bitwise_equal"]) / len(sweep)

    # --- probation re-promotion latency ------------------------------------
    # one injected dispatch fault demotes the fused set; with cooldown=3 the
    # dispatcher re-probes after the cooldown and a compiled trial dispatch
    # re-promotes — the latency is the dispatch distance migrate->repromote
    cooldown = 3
    set_probation(cooldown)
    migrate_step = promote_step = None
    try:
        coll = build()
        with _chaos.plan([FaultSpec("engine/dispatch", nth=4, times=1)], seed=0):
            for step in range(1, 64):
                coll.update(logits, target)
                pv = coll.engine_stats()["partition"]
                if migrate_step is None and pv["migrations"] > 0:
                    migrate_step = step
                if pv["repromotions"] > 0:
                    promote_step = step
                    break
    finally:
        set_probation(None)
    repromote_latency = (
        promote_step - migrate_step
        if promote_step is not None and migrate_step is not None
        else None
    )

    record = {
        # headline: the sweep's bitwise-equality pass rate — the property the
        # whole resilience layer exists to defend
        "metric": "resilience_chaos_sweep_pass_rate",
        "value": pass_rate,
        "unit": "ratio",
        "extra": {
            "config": "config2_collection",
            "num_classes": NUM_CLASSES,
            "sweep_steps": steps_total,
            "sweep": sweep,
            "retry": {
                "noop_raw_us_per_op": round(raw_us, 4),
                "noop_wrapped_us_per_op": round(wrapped_us, 4),
                "wrapper_overhead_us_per_op": round(wrapped_us - raw_us, 4),
            },
            "chaos": {
                "fused_update_us_per_step_chaos_off": round(off_us, 2),
                "fused_update_us_per_step_chaos_armed": round(armed_us, 2),
                "armed_overhead_pct": round((armed_us / off_us - 1.0) * 100, 2),
            },
            "probation": {
                "cooldown_dispatches": cooldown,
                "migrate_step": migrate_step,
                "repromote_step": promote_step,
                "repromotion_latency_dispatches": repromote_latency,
            },
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r for r in _regress.load_rounds(
            sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r15"
    ]
    rounds.append(_regress.Round("r15", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r15.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)
    problems = []
    if pass_rate < 1.0:
        failed = sorted(k for k, s in sweep.items() if not s["bitwise_equal"])
        problems.append(
            f"chaos sweep pass rate {pass_rate:.2f} < 1.0 (failed: {', '.join(failed)})"
        )
    if repromote_latency is None:
        problems.append("probation trial never re-promoted the fused set")
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] resilience round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def bench_tenancy() -> None:
    """``--tenancy``: the ISSUE-11 multi-tenant engine measured end to end —
    per-tenant update cost of one TenantSet dispatch vs N independent
    per-stream dispatches of the same (jitted, shared) fused program at
    N in {16, 256, 1024}; the ragged-arrival invariants at 1024 capacity / 37
    active (one cached executable across occupancy churn, zero recompiles for
    reset/evict/admit); and the tenant-batched sync's collective count, which
    must not grow with N — recorded into ``BENCH_r16.json`` and judged by the
    regression watchdog. Host-side CPU bench."""
    import glob as _glob

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection, TenantSet
    from metrics_tpu.observability import regress as _regress
    from metrics_tpu.parallel import sync as _sync

    n_classes, per_tenant_batch, steps = 16, 64, 8

    def build():
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=n_classes, average="micro"),
                "mse": MeanSquaredError(),
            }
        )

    rng = np.random.default_rng(0)

    def batch(n):
        preds = jnp.asarray(
            rng.integers(0, n_classes, size=(n, per_tenant_batch)), dtype=jnp.int32
        )
        target = jnp.asarray(
            rng.integers(0, n_classes, size=(n, per_tenant_batch)), dtype=jnp.int32
        )
        return preds, target

    # --- per-tenant dispatch cost: one stacked executable vs N dispatches ---
    sweep = {}
    for n in (16, 256, 1024):
        preds, target = batch(n)
        ids = [f"t{i}" for i in range(n)]

        ts = TenantSet(build(), capacity=n, name=f"bench-{n}")
        for tid in ids:
            ts.admit(tid)
        for _ in range(WARMUP):
            ts.update(ids, preds, target)
        t0 = time.perf_counter()
        for _ in range(steps):
            ts.update(ids, preds, target)
        jax.block_until_ready(ts.stacked_states)
        stacked_us = (time.perf_counter() - t0) / steps * 1e6

        # baseline: the best a per-stream loop can do — ONE shared jitted
        # fused program (no per-stream compile), paying only the Python
        # dispatch + state bookkeeping per tenant per step
        ref = build()
        step_fn = jax.jit(ref.update_state)
        s0 = ref.init_state(preds[0], target[0])
        states = [jax.tree_util.tree_map(jnp.array, s0) for _ in range(n)]
        for i in range(n):  # warm the one executable, touch every state once
            states[i] = step_fn(states[i], preds[i], target[i])
        t0 = time.perf_counter()
        for _ in range(steps):
            for i in range(n):
                states[i] = step_fn(states[i], preds[i], target[i])
        jax.block_until_ready(states[-1])
        loop_us = (time.perf_counter() - t0) / steps * 1e6

        sweep[f"n{n}"] = {
            "stacked_us_per_step": round(stacked_us, 1),
            "stacked_us_per_tenant": round(stacked_us / n, 3),
            "per_stream_loop_us_per_step": round(loop_us, 1),
            "per_stream_loop_us_per_tenant": round(loop_us / n, 3),
            "speedup": round(loop_us / stacked_us, 2),
            "executables": int(ts.stats.compiles),
        }

    # --- ragged arrival at 1024 capacity / 37 active ------------------------
    cap, active = 1024, 37
    ts = TenantSet(build(), capacity=cap, name="bench-ragged")
    for i in range(cap):
        ts.admit(f"t{i}")
    all_ids = ts.tenant_ids()
    preds, target = batch(active)
    ts.update(all_ids[:active], preds, target)  # first 37-dispatch compiles
    compiles_after_first = int(ts.stats.compiles)
    for off in range(1, 9):  # churn the active subset; same pow2 bucket
        subset = [all_ids[(off * 101 + j) % cap] for j in range(active)]
        ts.update(subset, preds, target)
    ragged_recompiles = int(ts.stats.compiles) - compiles_after_first

    before = int(ts.stats.compiles)
    ts.reset(all_ids[:5])
    reset_compiles = int(ts.stats.compiles) - before  # first width-8 reset program
    ts.evict(all_ids[0])  # warm the width-1 scrub program once
    ts.admit(all_ids[0])
    before = int(ts.stats.compiles)
    ts.reset(all_ids[5:10])
    ts.evict(all_ids[0])
    ts.admit("fresh")
    ts.update(all_ids[1 : active + 1], preds, target)
    lifecycle_recompiles = int(ts.stats.compiles) - before

    # --- tenant-batched sync: collective count must not grow with N --------
    def collectives_at(n):
        s = TenantSet(build(), capacity=n, name=f"sync-{n}")
        for i in range(n):
            s.admit(f"t{i}")
        with _sync.count_collectives() as box:
            jax.make_jaxpr(
                lambda st: s.sync_states(st, "data"), axis_env=[("data", 8)]
            )(s.stacked_states)
        return box["count"]

    coll_16, coll_1024 = collectives_at(16), collectives_at(1024)

    n256 = sweep["n256"]
    record = {
        # headline: per-tenant dispatch speedup at N=256 — the reason the
        # tenancy subsystem exists
        "metric": "tenancy_speedup_n256",
        "value": n256["speedup"],
        "unit": "x",
        "extra": {
            "config": "acc+mse_collection",
            "num_classes": n_classes,
            "per_tenant_batch": per_tenant_batch,
            "steps": steps,
            "sweep": sweep,
            "ragged": {
                "capacity": cap,
                "active": active,
                "executables_after_first_dispatch": compiles_after_first,
                "recompiles_over_8_occupancy_churns": ragged_recompiles,
                "first_reset_compiles": reset_compiles,
                "reset_evict_admit_redispatch_recompiles": lifecycle_recompiles,
                "cache_hits": int(ts.stats.cache_hits),
            },
            "sync": {
                "collectives_n16": coll_16,
                "collectives_n1024": coll_1024,
            },
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r for r in _regress.load_rounds(
            sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r16"
    ]
    rounds.append(_regress.Round("r16", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r16.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)
    problems = []
    if n256["speedup"] < 10.0:
        problems.append(f"N=256 stacked speedup {n256['speedup']}x < 10x")
    if ragged_recompiles != 0:
        problems.append(
            f"occupancy churn inside the 64-bucket recompiled {ragged_recompiles}x"
        )
    if lifecycle_recompiles != 0:
        problems.append(
            f"reset/evict/admit cycle recompiled {lifecycle_recompiles}x"
        )
    if coll_1024 != coll_16:
        problems.append(
            f"sync collectives grew with N: {coll_16} at N=16 vs {coll_1024} at N=1024"
        )
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] tenancy round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def bench_serve() -> None:
    """``--serve``: the ISSUE-13 ingestion front-end measured end to end over
    real loopback HTTP — per-post ingest latency (p50/p99) and throughput of
    ragged round-robin posts into a 16-tenant set with every pow2 coalesce
    bucket pre-warmed (so the steady-state phase must be recompile-free),
    plus rejection behavior at 2x overload with a chaos-stalled consumer
    (every rejection surfaced as 429 + Retry-After, exact admission
    accounting, every admitted batch applied) — recorded into
    ``BENCH_r18.json`` and judged by the regression watchdog. Host-side CPU
    bench."""
    import glob as _glob

    import jax

    jax.config.update("jax_platforms", "cpu")

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu import serve as _serve
    from metrics_tpu.observability import regress as _regress
    from metrics_tpu.resilience import chaos as _chaos

    n_classes, per_tenant_batch, n_tenants, steps = 16, 64, 16, 24

    def build():
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=n_classes, average="micro"),
                "mse": MeanSquaredError(),
            }
        )

    rng = np.random.default_rng(0)
    ids = [f"t{i}" for i in range(n_tenants)]

    def batch(n=n_tenants):
        preds = rng.integers(0, n_classes, size=(n, per_tenant_batch)).astype(np.int32)
        target = rng.integers(0, n_classes, size=(n, per_tenant_batch)).astype(np.int32)
        return preds, target

    # --- steady-state ingest: latency + throughput + zero recompiles --------
    server = _serve.IngestServer(build(), queue_capacity=256).start()
    try:
        client = _serve.IngestClient(server.url)
        ts = server.pipeline.tenant_set
        # warm every pow2 coalesce bucket the dispatcher can hit, so the
        # measured phase is the recompile-free steady state by construction
        preds, target = batch()
        with server.pipeline.apply_lock:
            for w in (1, 2, 4, 8, 16):
                ts.apply_batch(ids[:w], preds[:w], target[:w], auto_admit=True)
        assert server.drain(30.0)
        warm_compiles = int(ts.stats.compiles)

        lat_us = []
        t_wall = time.perf_counter()
        for step in range(steps):
            preds, target = batch()
            for j, tid in enumerate(ids):
                t0 = time.perf_counter()
                doc = client.post(tid, preds[j], target[j])
                lat_us.append((time.perf_counter() - t0) * 1e6)
                if not doc.get("admitted"):
                    raise RuntimeError(f"steady-state post rejected: {doc}")
        posts = steps * n_tenants
        throughput = posts / (time.perf_counter() - t_wall)
        assert server.drain(30.0)
        stats = server.stats()
        steady_recompiles = int(ts.stats.compiles) - warm_compiles
        lat_us.sort()
        p50_us = lat_us[len(lat_us) // 2]
        p99_us = lat_us[min(len(lat_us) - 1, int(len(lat_us) * 0.99))]
        steady = {
            "posts": posts,
            "ingest_p50_us": round(p50_us, 1),
            "ingest_p99_us": round(p99_us, 1),
            "ingest_throughput_per_sec": round(throughput, 1),
            "steady_state_recompiles": steady_recompiles,
            "partition_builds": stats["tenant_set"]["partition_builds"],
            "partition_stable_hits": stats["tenant_set"]["partition_stable_hits"],
            "dispatches": stats["dispatcher"]["dispatches"],
            "max_coalesce_width": stats["dispatcher"]["max_width"],
            "executables": int(ts.stats.compiles),
            "applied": stats["ledger"]["applied"],
            "dead_letters": stats["dispatcher"]["dead_letters"],
        }
    finally:
        server.stop(drain=False)

    # --- 2x overload: a chaos-stalled consumer against a bounded queue ------
    overload_cap = 16
    server = _serve.IngestServer(
        build(), queue_capacity=overload_cap, per_tenant_cap=overload_cap,
        retry_after_s=1.0,
    ).start()
    try:
        client = _serve.IngestClient(server.url)
        offered = 2 * overload_cap
        admitted = rejected = 0
        reasons = {}
        preds, target = batch(offered)
        with _chaos.plan(
            [_chaos.FaultSpec("serve/coalesce", kind="latency", latency_s=0.25)],
            seed=0,
        ):
            for j in range(offered):
                doc = client.post(ids[j % n_tenants], preds[j], target[j])
                if doc.get("admitted"):
                    admitted += 1
                else:
                    rejected += 1
                    reasons[doc["reason"]] = reasons.get(doc["reason"], 0) + 1
                    if doc["status"] != 429 or "retry_after_s" not in doc:
                        raise RuntimeError(f"unsurfaced rejection: {doc}")
        assert server.drain(30.0)  # chaos disarmed: the backlog applies
        ostats = server.stats()
        overload = {
            "offered": offered,
            "admitted": admitted,
            "rejected": rejected,
            "rejected_fraction": round(rejected / offered, 3),
            "reject_reasons": reasons,
            "queue_admitted_total": ostats["queue"]["admitted_total"],
            "queue_rejected_total": ostats["queue"]["rejected_total"],
            "applied_after_drain": ostats["ledger"]["applied"],
            "dead_letters": ostats["dispatcher"]["dead_letters"],
        }
    finally:
        server.stop(drain=False)

    record = {
        # headline: tail ingest latency of one HTTP post on the steady-state
        # (recompile-free) path — what a producer actually waits on
        "metric": "serve_ingest_p99_us",
        "value": steady["ingest_p99_us"],
        "unit": "us",
        "extra": {
            "config": "acc+mse_collection_http",
            "num_classes": n_classes,
            "per_tenant_batch": per_tenant_batch,
            "tenants": n_tenants,
            "steps": steps,
            "steady": steady,
            "overload": overload,
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r for r in _regress.load_rounds(
            sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r18"
    ]
    rounds.append(_regress.Round("r18", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r18.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)
    problems = []
    if steady["steady_state_recompiles"] != 0:
        problems.append(
            f"steady-state ingest recompiled {steady['steady_state_recompiles']}x "
            "(pow2 bucketing should absorb queue-depth churn)"
        )
    if steady["partition_builds"] != 1:
        problems.append(f"partition built {steady['partition_builds']}x (want 1)")
    if steady["applied"] != steady["posts"]:  # warmup bypassed the ledger
        problems.append(
            f"steady ledger applied {steady['applied']} != {steady['posts']} posts"
        )
    if steady["dead_letters"] or overload["dead_letters"]:
        problems.append("dead letters on a healthy path")
    if overload["admitted"] + overload["rejected"] != overload["offered"]:
        problems.append("overload accounting leaked an offer")
    if overload["rejected"] == 0:
        problems.append("2x overload produced zero rejections (queue unbounded?)")
    if overload["applied_after_drain"] != overload["admitted"]:
        problems.append(
            f"admitted {overload['admitted']} but applied "
            f"{overload['applied_after_drain']} — an admitted batch was dropped"
        )
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] serve round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def bench_cluster() -> None:
    """``--cluster``: the scale-out serving tier measured end to end in
    process — live-migration write-unavailability (fence→cutover downtime
    p50/p99 over repeated moves of a warm tenant), the routing layer's
    per-post overhead (shard-aware ``ClusterClient`` vs posting straight
    into the owner's pipeline), and the 3-seed × 5-site chaos sweep's
    abort-and-total-rollback pass rate — recorded into ``BENCH_r25.json``
    and judged by the regression watchdog. Host-side CPU bench."""
    import glob as _glob

    import jax

    jax.config.update("jax_platforms", "cpu")

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.cluster import ClusterClient, ClusterCoordinator
    from metrics_tpu.observability import regress as _regress
    from metrics_tpu.resilience import chaos as _chaos
    from metrics_tpu.serve import IngestPipeline

    n_classes, per_tenant_batch, n_tenants = 16, 64, 8
    migrations_timed, chaos_seeds = 10, (0, 1, 2)
    fault_sites = {
        "cluster/fence": "fence",
        "cluster/export": "export",
        "cluster/transfer": "transfer",
        "cluster/import": "import",
        "cluster/cutover": "cutover",
    }

    def build():
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=n_classes, average="micro"),
                "mse": MeanSquaredError(),
            }
        )

    rng = np.random.default_rng(0)
    ids = [f"t{i}" for i in range(n_tenants)]

    def batch():
        preds = rng.integers(0, n_classes, size=(per_tenant_batch,)).astype(np.int32)
        target = rng.integers(0, n_classes, size=(per_tenant_batch,)).astype(np.int32)
        return preds, target

    coordinator = ClusterCoordinator(
        {
            rid: IngestPipeline(build(), name=rid, queue_capacity=2048)
            for rid in ("r0", "r1")
        },
        name="bench",
    ).start()
    try:
        client = ClusterClient(dict(coordinator.replicas), coordinator)

        def drain_all():
            for replica in coordinator.replicas.values():
                if replica.alive and not replica.pipeline.drain(60.0):
                    raise RuntimeError("cluster drain timed out")

        # warm every tenant (admit + trace) so timed phases are steady state
        for tid in ids:
            for _ in range(2):
                doc = client.post(tid, *batch())
                if not doc.get("admitted"):
                    raise RuntimeError(f"warmup post rejected: {doc}")
        drain_all()

        # --- routed-post overhead: ClusterClient vs the owner pipeline ------
        posts_per_path = 200
        routed_us, direct_us = [], []
        for j in range(posts_per_path):
            tid = ids[j % n_tenants]
            preds, target = batch()
            t0 = time.perf_counter()
            doc = client.post(tid, preds, target)
            routed_us.append((time.perf_counter() - t0) * 1e6)
            if not doc.get("admitted"):
                raise RuntimeError(f"routed post rejected: {doc}")
        drain_all()
        owner_pipeline = {
            tid: coordinator.replicas[coordinator.owner(tid)].pipeline
            for tid in ids
        }
        for j in range(posts_per_path):
            tid = ids[j % n_tenants]
            preds, target = batch()
            pipeline = owner_pipeline[tid]
            t0 = time.perf_counter()
            admission = pipeline.post(tid, preds, target)
            direct_us.append((time.perf_counter() - t0) * 1e6)
            if not admission.admitted:
                raise RuntimeError("direct post rejected")
        drain_all()
        routed_us.sort()
        direct_us.sort()
        routed_p50 = routed_us[len(routed_us) // 2]
        direct_p50 = direct_us[len(direct_us) // 2]
        routing = {
            "posts_per_path": posts_per_path,
            "routed_p50_us": round(routed_p50, 1),
            "direct_p50_us": round(direct_p50, 1),
            "routing_overhead_p50_us": round(routed_p50 - direct_p50, 1),
            "redirects_followed": client.redirects_followed,
        }

        # --- migration downtime: fence→cutover over repeated warm moves -----
        mover = ids[0]
        downtimes_ms = []
        for _ in range(migrations_timed):
            src = coordinator.owner(mover)
            dst = next(r for r in coordinator.replicas if r != src)
            record = coordinator.migrate(mover, dst)
            if record.outcome != "committed":
                raise RuntimeError(f"timed migration failed: {record.to_dict()}")
            downtimes_ms.append(record.downtime_s * 1e3)
            # the tenant keeps serving between moves — state stays warm
            doc = client.post_with_retry(mover, *batch())
            if not doc.get("admitted"):
                raise RuntimeError(f"post-migration post rejected: {doc}")
        drain_all()
        downtimes_ms.sort()
        migration = {
            "migrations": migrations_timed,
            "downtime_p50_ms": round(downtimes_ms[len(downtimes_ms) // 2], 2),
            "downtime_p99_ms": round(
                downtimes_ms[
                    min(len(downtimes_ms) - 1, int(len(downtimes_ms) * 0.99))
                ],
                2,
            ),
            "downtime_max_ms": round(downtimes_ms[-1], 2),
        }

        # --- chaos sweep: a fault at every phase must abort + roll back -----
        sweep_pass = sweep_total = 0
        victim = ids[1]
        for seed in chaos_seeds:
            for site, phase in fault_sites.items():
                sweep_total += 1
                src = coordinator.owner(victim)
                dst = next(r for r in coordinator.replicas if r != src)
                epoch_before = coordinator.shard_map.epoch
                with _chaos.plan(
                    [_chaos.FaultSpec(site=site, kind="error", nth=1, times=1)],
                    seed=seed,
                ):
                    record = coordinator.migrate(victim, dst)
                doc = client.post_with_retry(victim, *batch())
                ok = (
                    record.outcome == "aborted"
                    and record.phase == phase
                    and coordinator.owner(victim) == src
                    and coordinator.shard_map.epoch == epoch_before
                    and victim not in map(
                        str, coordinator.replicas[dst].tenant_ids()
                    )
                    and bool(doc.get("admitted"))
                )
                sweep_pass += ok
                if not ok:
                    print(
                        f"[bench] chaos case failed: seed={seed} site={site} "
                        f"record={record.to_dict()} post={doc}",
                        file=sys.stderr,
                    )
        # chaos disarmed: the same move commits cleanly
        retry = coordinator.migrate(
            victim, next(r for r in coordinator.replicas if r != coordinator.owner(victim))
        )
        drain_all()
        outcomes = {"committed": 0, "aborted": 0}
        for r in coordinator.migrations:
            outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1
        sweep = {
            "seeds": list(chaos_seeds),
            "sites": sorted(fault_sites),
            "cases": sweep_total,
            "passed": sweep_pass,
            "pass_rate": round(sweep_pass / sweep_total, 3),
            "retry_after_sweep": retry.outcome,
            "migration_outcomes": outcomes,
        }
    finally:
        for replica in coordinator.replicas.values():
            if replica.alive:
                replica.stop(drain=False)

    record = {
        # headline: the tail write-unavailability one live migration costs a
        # tenant — the number a rebalance planner budgets against
        "metric": "cluster_migration_downtime_p99_ms",
        "value": migration["downtime_p99_ms"],
        "unit": "ms",
        "extra": {
            "config": "acc+mse_collection_2replicas_inproc",
            "num_classes": n_classes,
            "per_tenant_batch": per_tenant_batch,
            "tenants": n_tenants,
            "migration": migration,
            "routing": routing,
            "chaos_sweep": sweep,
        },
    }

    # watchdog self-check: judge this round against the checked-in trajectory
    rounds = [
        r for r in _regress.load_rounds(
            sorted(_glob.glob(os.path.join(REPO, "BENCH_r*.json"))))
        if r.name != "r25"
    ]
    rounds.append(_regress.Round("r25", "<this-run>", record))
    report = _regress.check_trajectory(rounds)
    record["extra"]["regress"] = {
        "ok": report.ok,
        "regression_count": len(report.regressions),
        "keys_checked": report.keys_checked,
        "regressions": [r.describe() for r in report.regressions],
    }

    with open(os.path.join(REPO, "BENCH_r25.json"), "w") as fh:
        json.dump(record, fh, indent=1)
        fh.write("\n")
    print(json.dumps(record), flush=True)
    problems = []
    if sweep["pass_rate"] != 1.0:
        problems.append(
            f"chaos sweep pass rate {sweep['pass_rate']} != 1.0 "
            f"({sweep['passed']}/{sweep['cases']})"
        )
    if sweep["retry_after_sweep"] != "committed":
        problems.append("clean migration after the chaos sweep did not commit")
    if outcomes["aborted"] != sweep_total:
        problems.append(
            f"{outcomes['aborted']} aborts recorded, expected exactly the "
            f"{sweep_total} injected faults"
        )
    if migration["downtime_p99_ms"] > 5000.0:
        problems.append(
            f"migration downtime p99 {migration['downtime_p99_ms']} ms "
            "exceeds the 5 s budget"
        )
    if routing["routing_overhead_p50_us"] > 500.0:
        problems.append(
            f"routing layer adds {routing['routing_overhead_p50_us']} us/post "
            "(want < 500 us: an owner lookup, not a hop)"
        )
    if routing["redirects_followed"] != 0:
        problems.append("fresh-map posts followed redirects")
    if not report.ok:
        problems.extend(r.describe() for r in report.regressions)
    if problems:
        print("[bench] cluster round FAILED its gates:", file=sys.stderr)
        for p in problems:
            print(f"[bench]   {p}", file=sys.stderr)
        sys.exit(1)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--analysis",
        action="store_true",
        help="run the three-stage metrics_tpu.analysis analyzer and record "
        "wall time + manifest resource aggregates into BENCH_r24.json",
    )
    parser.add_argument(
        "--observability",
        action="store_true",
        help="measure tracer on/off overhead on the config2 fused update and "
        "the traced eval-loop event volume, record into BENCH_r12.json; then "
        "run --offhost for BENCH_r13.json",
    )
    parser.add_argument(
        "--offhost",
        action="store_true",
        help="measure live scrape-server latency, 8-host shard merge + device "
        "correlation wall time, and run the regression watchdog over the "
        "BENCH trajectory; record into BENCH_r13.json",
    )
    parser.add_argument(
        "--partitioned-dispatch",
        action="store_true",
        help="measure partition-aware collection dispatch (fused + bucketed + "
        "eager straggler) vs the old whole-collection eager demotion and "
        "record into BENCH_r14.json",
    )
    parser.add_argument(
        "--resilience",
        action="store_true",
        help="measure retry-wrapper per-op overhead, chaos armed/disabled "
        "overhead on the fused update, the 3-seed deterministic chaos sweep's "
        "bitwise pass rate, and probation re-promotion latency; record into "
        "BENCH_r15.json",
    )
    parser.add_argument(
        "--tenancy",
        action="store_true",
        help="measure TenantSet stacked dispatch vs N independent per-stream "
        "dispatches at N=16/256/1024, the ragged 1024/37 zero-recompile "
        "invariants, and tenant-batched sync collective counts; record into "
        "BENCH_r16.json",
    )
    parser.add_argument(
        "--serve",
        action="store_true",
        help="measure the HTTP ingestion front-end: steady-state per-post "
        "latency (p50/p99) + throughput with zero recompiles, and rejection "
        "behavior at 2x overload against a chaos-stalled consumer; record "
        "into BENCH_r18.json and judge with the regression watchdog",
    )
    parser.add_argument(
        "--cluster",
        action="store_true",
        help="measure the scale-out serving tier: live-migration downtime "
        "p50/p99 over repeated warm moves, shard-aware routed-post overhead "
        "vs posting straight into the owner pipeline, and the 3-seed x "
        "5-site chaos sweep's abort+rollback pass rate; record into "
        "BENCH_r25.json and judge with the regression watchdog",
    )
    parser.add_argument(
        "--checkpoint",
        action="store_true",
        help="time config2 snapshot save/restore plus an 8-shard merge and "
        "record into BENCH_r10.json",
    )
    parser.add_argument(
        "--sharded-state",
        action="store_true",
        help="measure replicated-vs-sharded per-device state bytes and "
        "collective bytes at mesh widths 1/4/8 and record into BENCH_r11.json",
    )
    parser.add_argument(
        "--sharded-compute",
        action="store_true",
        help="measure the gather-free sharded-compute protocol (reshard bytes "
        "before vs after, finalize us/step both ways on the 8-device mesh) and "
        "the streaming restore plan's peak-vs-gather bytes; record into "
        "BENCH_r17.json and judge with the regression watchdog",
    )
    parser.add_argument(
        "--quantized-sync",
        action="store_true",
        help="measure wire-byte reduction and quantization error of the "
        "bf16/int8/sparse_count sync transports (config2 merged sync, "
        "confmat-4096, capacity-256 tenancy) on the 8-device mesh and record "
        "into BENCH_r19.json; gates: bf16 >= 1.9x, int8 >= 3.5x, error <= "
        "the E112 bound",
    )
    parser.add_argument(
        "--incremental-sync",
        action="store_true",
        help="measure the in-streak incremental emission protocol vs the "
        "deferred finalize burst (config2 + confmat-4096, cadence K in "
        "{1,4,16}) and the async-save overlap gain on the 8-device mesh; "
        "record into BENCH_r20.json; gates: zero finalize collectives, "
        "burst byte reduction >= 2x, zero retraces after warmup",
    )
    parser.add_argument(
        "--heavy-kernels",
        action="store_true",
        help="measure the compiled heavy-kernel layer: device-resident mAP "
        "(fused iou_matching kernel, 64 ragged images, update+compute) vs the "
        "host-list eager path, and the BERTScore pad-on-append packed cache "
        "vs the quadratic _cat_padded re-pad; record into BENCH_r21.json; "
        "gates: >= 3x mAP speedup, 0 steady-state recompiles, linear packed "
        "copy growth, bitwise parity both ways",
    )
    parser.add_argument(
        "--self-tuning",
        action="store_true",
        help="measure the self-tuning sync controller: tuned vs hand-best vs "
        "all-exact on config2's merged sync plus a tuned confmat-4096, and "
        "the facade dispatch fast-lane overhead; record into BENCH_r22.json; "
        "gates: error <= budget, 0 retraces after warmup, tuned wire bytes "
        "within 10% of hand-best, fast lane live",
    )
    parser.add_argument(
        "--sketches",
        action="store_true",
        help="measure mergeable sketch states vs the CatBuffer gather at "
        "N=1e6 on the 8-device mesh; record into BENCH_r23.json; gates: "
        "sync wire bytes >= 50x below the gather, quantile error <= the "
        "declared bound, bitwise merge-order invariance across shardings",
    )
    parser.add_argument(
        "--child",
        choices=["sync_overhead", "sharded_state", "sharded_compute", "quantized_sync", "incremental_sync", "heavy_kernels", "self_tuning", "sketches", *_CHILD_BENCHES],
    )
    parser.add_argument(
        "--sync-scaling",
        action="store_true",
        help="run the sync-overhead config across mesh widths 2/4/8/16 and print one JSON dict",
    )
    parser.add_argument(
        "--quick-tpu",
        action="store_true",
        help="<=5-minute subset (config1/2 + sync overhead + binned A/B + one "
        "Inception batch) so a short healthy-tunnel window still yields a "
        "full platform:tpu record",
    )
    global _BENCH_START
    args = parser.parse_args()
    if args.analysis:
        bench_analysis()
        return
    if args.observability:
        bench_observability()
        bench_offhost()
        return
    if args.offhost:
        bench_offhost()
        return
    if args.partitioned_dispatch:
        bench_partitioned_dispatch()
        return
    if args.resilience:
        bench_resilience()
        return
    if args.tenancy:
        bench_tenancy()
        return
    if args.serve:
        bench_serve()
        return
    if args.cluster:
        bench_cluster()
        return
    if args.checkpoint:
        bench_checkpoint()
        return
    if args.sharded_state:
        bench_sharded_state()
        return
    if args.sharded_compute:
        bench_sharded_compute()
        return
    if args.quantized_sync:
        bench_quantized_sync()
        return
    if args.incremental_sync:
        bench_incremental_sync()
        return
    if args.heavy_kernels:
        bench_heavy_kernels()
        return
    if args.self_tuning:
        bench_self_tuning()
        return
    if args.sketches:
        bench_sketches()
        return
    if args.sync_scaling:
        out = {}
        for w in (2, 4, 8, 16):
            # the four width children share one process, but the soft budget
            # is per width: without this reset the earlier (slower to warm up)
            # configs eat the whole window and the wide configs silently land
            # as {"skipped": "budget"}
            _BENCH_START = time.perf_counter()
            out[f"world_{w}"] = _safe(bench_sync_overhead, 1500.0, w)
        print(json.dumps(_round(out)))
        return
    if args.child == "sync_overhead":
        _sync_overhead_child()
        return
    if args.child == "sharded_state":
        _sharded_state_child()
        return
    if args.child == "sharded_compute":
        _sharded_compute_child()
        return
    if args.child == "quantized_sync":
        _quantized_sync_child()
        return
    if args.child == "incremental_sync":
        _incremental_sync_child()
        return
    if args.child == "heavy_kernels":
        _heavy_kernels_child()
        return
    if args.child == "self_tuning":
        _self_tuning_child()
        return
    if args.child == "sketches":
        _sketches_child()
        return
    if args.child in _CHILD_BENCHES:
        import jax

        if os.environ.get("BENCH_FORCE_CPU"):
            jax.config.update("jax_platforms", "cpu")
        try:  # share the parent's persistent compile cache
            jax.config.update("jax_compilation_cache_dir", _xla_cache_dir())
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass
        print(json.dumps(_CHILD_BENCHES[args.child]()))
        return
    force_cpu = bool(os.environ.get("BENCH_FORCE_CPU"))
    # provisional record FIRST: the device probes below may retry for many
    # minutes against a wedged tunnel, and the driver parses the LAST complete
    # line — if the run is killed mid-probe this line is what survives,
    # honestly marked; every later print overrides it
    print(
        json.dumps(
            {
                "metric": "metric_collection_update_us_per_step",
                # lower-is-better metric: a huge sentinel fails SAFE if a
                # killed run leaves this as the last line (-1 would rank as
                # the best result ever)
                "value": 1e12,
                "unit": "us/step",
                "vs_baseline": 0,
                "tpu_targets_unmet": True,
                "partial": "provisional: benchmark still running (device-probe phase)",
            }
        ),
        flush=True,
    )
    if not force_cpu:
        # watchdog: a wedged accelerator tunnel hangs backend init forever
        # (observed when a process dies mid-TPU-operation). Probe device init
        # in a disposable subprocess — each retry is a fresh process, so each
        # gets a fresh PJRT client/backend-init attempt — with escalating
        # timeouts and backoff between attempts (the tunnel has been seen to
        # recover minutes after a wedge). Only after every attempt fails do we
        # fall back to CPU, and then the output is loudly marked
        # `tpu_targets_unmet` at the JSON top level so a CPU round can never
        # read as a TPU result.
        # quick mode exists to exploit a short healthy-tunnel window — don't
        # spend the window inside the probe itself
        probe_timeouts = (120, 240) if args.quick_tpu else (180, 300, 600)
        for attempt, probe_timeout in enumerate(probe_timeouts, 1):
            t0 = time.perf_counter()
            hung, err_tail = False, ""
            try:
                probe = subprocess.run(
                    [sys.executable, "-u", "-c",
                     "import jax; d = jax.devices(); "
                     "import jax.numpy as jnp; (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready(); "
                     "print(d[0].platform)"],
                    capture_output=True,
                    timeout=probe_timeout,
                )
                platform = probe.stdout.decode(errors="replace").strip().splitlines()[-1] if probe.stdout.strip() else ""
                # exit 0 alone is not enough: a silent jax CPU fallback would
                # exit cleanly and print "cpu" — that is still a failed TPU probe
                ok = probe.returncode == 0 and platform not in ("", "cpu")
                err_tail = probe.stderr.decode(errors="replace")[-400:]
            except subprocess.TimeoutExpired:
                ok, hung = False, True
            dt = time.perf_counter() - t0
            if ok:
                print(f"[bench] device probe ok on attempt {attempt} in {dt:.0f}s ({platform})",
                      file=sys.stderr)
                break
            print(f"[bench] device-init probe attempt {attempt}/{len(probe_timeouts)} "
                  + ("hung" if hung else "failed") + f" after {dt:.0f}s"
                  + (f"; stderr tail: {err_tail!r}" if err_tail else ""), file=sys.stderr)
            if attempt < len(probe_timeouts):
                # a wedged tunnel needs recovery time; a fast deterministic
                # failure only needs a beat before the re-check
                time.sleep(30 * attempt if hung else 5)
        if not ok:
            force_cpu = True
            os.environ["BENCH_FORCE_CPU"] = "1"  # children must fall back too
            print("[bench] all device-init probes failed; falling back to CPU — "
                  "TPU targets UNMEASURED this run", file=sys.stderr)
        # probing may have eaten many minutes; the budget is for the
        # benchmarks themselves, so restart the clock here
        _BENCH_START = time.perf_counter()
    import jax

    if force_cpu:
        # the config update is the only reliable platform override here
        jax.config.update("jax_platforms", "cpu")
    try:
        # persistent compile cache: repeated bench runs (and the driver's)
        # skip recompilation of the big programs (inception, matcher, sweeps)
        jax.config.update("jax_compilation_cache_dir", _xla_cache_dir())
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    ours_us = bench_collection_ours()
    ref_us = _num(_safe(bench_collection_ref))

    def headline_record(value_us, path, **fields):
        return {
            "metric": "metric_collection_update_us_per_step",
            "value": round(value_us, 2),
            "unit": "us/step",
            "vs_baseline": round(ref_us / value_us, 3) if ref_us else 1.0,
            "tpu_targets_unmet": force_cpu,
            "headline_path": path,
            **fields,
        }

    percall_path = "per-call jit dispatch (compiled lax.scan loop failed/skipped)"
    scan_path = "compiled lax.scan loop (per-call dispatch path in extra.config2)"

    # the headline is safe the moment it exists: if any later sub-benchmark
    # hangs past the driver's window (the scan bench below is an in-process
    # TPU compile — the hang-prone class), the LAST complete line printed is
    # this one, and the driver's last-line parse still records the round
    print(
        json.dumps(headline_record(ours_us, percall_path,
                                   partial="headline only; full grid follows")),
        flush=True,
    )

    # Preferred headline = the compiled lax.scan loop: the shape a real (TPU)
    # training loop runs the collection in, where the fused update's cost is
    # on-device work rather than host dispatch latency. The per-call jit path
    # (interactive/eager deployments, dominated by dispatch) stays in extra.
    # vs_baseline compares the reference's only execution shape (eager
    # per-call) against whichever path the headline reports.
    scan_raw = _safe(bench_collection_scan)
    scan_val, scan_mfu = _split_throughput(scan_raw, key="us_per_step")
    scan_us = _num(scan_val)
    headline_us, headline_path = (scan_us, scan_path) if scan_us else (ours_us, percall_path)
    print(
        json.dumps(headline_record(headline_us, headline_path,
                                   partial="headline only; full grid follows")),
        flush=True,
    )

    quick = args.quick_tpu
    if quick:
        # enforce the documented <=5-minute bound: shrink the soft budget and
        # every child timeout so one wedged TPU compile can't outlive the
        # hardware window the mode exists to exploit
        global _BENCH_BUDGET
        _BENCH_BUDGET = min(_BENCH_BUDGET, 270.0)
    inc_ours, inc_mfu = _split_throughput(_safe(bench_inception_ours))
    config3 = {
        "inception2048_samples_per_sec": inc_ours,
        "inception2048_mfu": inc_mfu,
    }
    if not quick:
        lpips_ours, lpips_mfu = _split_throughput(_safe(bench_lpips_ours))
        config3.update(
            {
                "inception2048_reference_torch_samples_per_sec": _safe(bench_inception_ref),
                "lpips_alex_samples_per_sec": lpips_ours,
                "lpips_alex_mfu": lpips_mfu,
                "lpips_alex_reference_torch_samples_per_sec": _safe(bench_lpips_ref),
                "fid_compute_ms_2048d": _safe(bench_fid_compute_ms),
                "fid_numerics_2048": _safe(bench_fid_numerics),
            }
        )
    extra = {
        **({"mode": "quick-tpu"} if quick else {}),
        "config1_accuracy_10c": {
            "ours": _safe(bench_accuracy_ours),
            "reference_torch": _safe(bench_accuracy_ref),
            "compute_us_per_step": _safe(bench_accuracy_compute),
        },
        "config2_collection_1k": {
            # keep the budget-skip marker visible when the scan was skipped
            "collection_scan_us_per_step": scan_us if scan_us is not None else scan_raw,
            "collection_scan_mfu": scan_mfu,
            "percall_us_per_step": ours_us,
            "facade_update_us_per_step": _num(_safe(bench_collection_facade)),
            "fused_update": _safe(bench_collection_fused_update),
            "compute_us_per_step": _safe(bench_collection_compute),
            "reference_torch_us_per_step": ref_us,
            "vs_baseline_percall": round(ref_us / ours_us, 3) if ref_us else None,
        },
        "sync_overhead_8dev_64k": _safe(bench_sync_overhead, 420.0 if quick else 1200.0),
        "config3_fid_lpips": config3,
    }
    if not quick:
        extra.update(
            {
                "config4_map_coco_shaped": {
                    "samples_per_sec": _safe(bench_map_ours),
                    "numpy_oracle_samples_per_sec": _safe(bench_map_oracle),
                    "segm_rle_samples_per_sec": _safe(bench_map_segm_rle),
                    "note": "reference MeanAveragePrecision needs torchvision (absent); baseline = independent numpy COCO oracle",
                },
                "config5_bertscore_toy": {
                    "sentences_per_sec": _safe(bench_bert_ours),
                    "reference_torch_sentences_per_sec": _safe(bench_bert_ref),
                },
                # isolated: these have hung in TPU remote compiles; a stuck
                # child is killed at its timeout instead of stalling the bench
                "retrieval_compiled_50k_docs": _safe(_run_isolated, "retrieval"),
                "catbuffer_auroc": _safe(_run_isolated, "catbuffer"),
                "pesq_native": _safe(bench_pesq_native),
            }
        )
    extra["binned_curve_counts"] = _safe(_run_isolated, "binned", 180.0 if quick else 420.0)

    import jax

    platform = jax.devices()[0].platform + (" (forced-cpu fallback)" if force_cpu else "")
    print(
        json.dumps(headline_record(headline_us, headline_path,
                                   platform=platform, extra=_round(extra)))
    )


if __name__ == "__main__":
    main()
