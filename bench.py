"""Driver benchmark: fused MetricCollection update+compute, 1k classes.

BASELINE.md config 2 — MetricCollection(Accuracy, F1, Precision, Recall) over a
1000-class, 64k-sample sweep. Ours: one jitted XLA call per step (fused
compute-group update). Baseline: the reference TorchMetrics implementation
(/root/reference, torch CPU — the reference publishes no absolute numbers, so
its own implementation on the host is the measured baseline).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

NUM_CLASSES = 1000
BATCH = 1024
STEPS = 64
WARMUP = 3


def bench_ours() -> float:
    """µs/step for the fused jitted collection update (+ final compute)."""
    import jax
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall

    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )

    @jax.jit
    def step(states, logits, target):
        return coll.update_state(states, logits, target)

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)

    states = coll.init_state()
    for _ in range(WARMUP):
        states = step(states, logits, target)
    jax.block_until_ready(states)

    states = coll.init_state()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        states = step(states, logits, target)
    jax.block_until_ready(states)
    t1 = time.perf_counter()
    results = coll.compute_state(states)
    jax.block_until_ready(results)
    return (t1 - t0) / STEPS * 1e6


def bench_reference() -> float:
    """µs/step for the reference TorchMetrics collection (torch CPU)."""
    sys.path.insert(0, "/root/reference")
    if "pkg_resources" not in sys.modules:  # removed from setuptools; shim the two names the reference uses
        import types

        shim = types.ModuleType("pkg_resources")

        class DistributionNotFound(Exception):
            pass

        def get_distribution(name):
            raise DistributionNotFound(name)

        shim.DistributionNotFound = DistributionNotFound
        shim.get_distribution = get_distribution
        sys.modules["pkg_resources"] = shim
    import torch
    from torchmetrics import Accuracy, F1Score, MetricCollection, Precision, Recall

    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )
    rng = np.random.default_rng(0)
    logits = torch.as_tensor(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=torch.float32)
    target = torch.as_tensor(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=torch.long)

    for _ in range(WARMUP):
        coll.update(logits, target)
    coll.reset()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        coll.update(logits, target)
    t1 = time.perf_counter()
    coll.compute()
    return (t1 - t0) / STEPS * 1e6


def main() -> None:
    ours_us = bench_ours()
    try:
        ref_us = bench_reference()
        vs_baseline = ref_us / ours_us  # >1 == faster than the reference
    except Exception:
        vs_baseline = 1.0
    print(
        json.dumps(
            {
                "metric": "metric_collection_update_us_per_step",
                "value": round(ours_us, 2),
                "unit": "us/step",
                "vs_baseline": round(vs_baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
