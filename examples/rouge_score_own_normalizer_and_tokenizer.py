"""ROUGEScore with a custom normalizer and tokenizer.

Reference parity: tm_examples/rouge_score-own_normalizer_and_tokenizer.py —
the user replaces the default text normalization/tokenization, e.g. to handle
non-alphanumeric scripts.

To run: python examples/rouge_score_own_normalizer_and_tokenizer.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import re
from pprint import pprint
from typing import Sequence

from metrics_tpu.text import ROUGEScore


class UserNormalizer:
    """Keeps digits and word characters, lowercases (the default drops
    non-ascii; a user normalizer can keep any script)."""

    def __init__(self) -> None:
        self.pattern = re.compile(r"[^\w\d]+")

    def __call__(self, text: str) -> str:
        return self.pattern.sub(" ", text.lower()).strip()


class UserTokenizer:
    """Whitespace tokenizer."""

    def __call__(self, text: str) -> Sequence[str]:
        return text.split()


if __name__ == "__main__":
    rouge = ROUGEScore(normalizer=UserNormalizer(), tokenizer=UserTokenizer())
    rouge.update(["Is your name John?"], ["Is your name John"])
    pprint({k: float(v) for k, v in rouge.compute().items()})
