"""Distributed evaluation: one fused metric program per step over a mesh.

Runs anywhere: provisions an 8-device CPU mesh, so
`python examples/distributed_eval.py` demonstrates the exact
sharding/collective pattern a TPU pod uses without needing one.

Pattern (docs/distributed.md): update on each device's shard inside
shard_map -> one psum bundle via sync_states -> compute. The final value must
equal a single-host evaluation of all shards — asserted at the bottom.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# Demo provisioning: an 8-device CPU mesh. On a real pod, delete these two
# lines — jax.devices() already lists the chips. (Must run before any jax op.)
try:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 8)
except RuntimeError:  # pragma: no cover - backend already initialized
    pass

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Accuracy, F1Score, MetricCollection

NUM_CLASSES = 16
PER_DEVICE_BATCH = 32
STEPS = 4

mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
world = mesh.devices.size

coll = MetricCollection(
    {
        "acc": Accuracy(num_classes=NUM_CLASSES),
        "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
    }
)

rng = np.random.default_rng(0)
logits = rng.normal(size=(STEPS, world * PER_DEVICE_BATCH, NUM_CLASSES)).astype(np.float32)
labels = rng.integers(0, NUM_CLASSES, size=(STEPS, world * PER_DEVICE_BATCH)).astype(np.int32)


# Each device owns its accumulator between steps: state leaves carry a
# leading (world,) axis sharded over 'data', so device d reads and writes
# slice d. (Replicated P() state specs would silently keep only one device's
# updates — per-device state must be explicit.)
def eval_step(state, logits_local, labels_local):
    """Per-device shard update — one XLA program, no collectives yet."""
    local = jax.tree.map(lambda x: x[0], state)
    local = coll.update_state(local, logits_local, labels_local)
    return jax.tree.map(lambda x: x[None], local)


def finalize(state):
    """Epoch end: one fused collective bundle per compute group, then compute."""
    local = jax.tree.map(lambda x: x[0], state)
    local = coll.sync_states(local, "data")
    return jax.tree.map(lambda x: jnp.expand_dims(x, 0), coll.compute_state(local))


stepped = jax.jit(
    jax.shard_map(
        eval_step,
        mesh=mesh,
        in_specs=(P("data"), P("data"), P("data")),
        out_specs=P("data"),
        check_vma=False,
    )
)
finalized = jax.jit(
    jax.shard_map(finalize, mesh=mesh, in_specs=(P("data"),), out_specs=P("data"), check_vma=False)
)

state = jax.tree.map(lambda x: jnp.stack([x] * world), coll.init_state())
for i in range(STEPS):
    state = stepped(state, jnp.asarray(logits[i]), jnp.asarray(labels[i]))
results = {k: float(v[0]) for k, v in finalized(state).items()}
print("distributed:", {k: round(v, 4) for k, v in results.items()})

# oracle: same batches through a single-host metric
single = MetricCollection(
    {
        "acc": Accuracy(num_classes=NUM_CLASSES),
        "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
    }
)
single.update(jnp.asarray(logits.reshape(-1, NUM_CLASSES)), jnp.asarray(labels.reshape(-1)))
want = {k: float(v) for k, v in single.compute().items()}
print("single-host:", {k: round(v, 4) for k, v in want.items()})
for key in results:
    np.testing.assert_allclose(results[key], want[key], rtol=1e-6)
print("distributed == single-host OK")
