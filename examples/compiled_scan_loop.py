"""Metrics inside ONE compiled training loop — the TPU deployment shape.

The benchmark headline measures this pattern: the whole epoch is a single
``lax.scan`` XLA program, the fused MetricCollection state is the scan
carry, and per-step host dispatch disappears (reference analog: the
per-step ``metric.update`` calls in a Lightning loop and the compute-group
discussion in the reference docs' overview page — re-shaped for XLA).

Run: python examples/compiled_scan_loop.py  (any backend; ~seconds on CPU)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu import Accuracy, F1Score, MetricCollection, Precision, Recall

NUM_CLASSES, BATCH, STEPS = 10, 256, 50

collection = MetricCollection(
    {
        "acc": Accuracy(num_classes=NUM_CLASSES),
        "f1": F1Score(num_classes=NUM_CLASSES, average="macro", mdmc_average="global"),
        "precision": Precision(num_classes=NUM_CLASSES, average="macro", mdmc_average="global"),
        "recall": Recall(num_classes=NUM_CLASSES, average="macro", mdmc_average="global"),
    }
)
# F1/Precision/Recall share one fused stat-scores pass; Accuracy (its own
# update signature) forms the second group
assert len(collection.compute_groups) == 2

rng = np.random.default_rng(0)
logits = jnp.asarray(rng.normal(size=(STEPS, BATCH, NUM_CLASSES)), jnp.float32)
labels = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(STEPS, BATCH)))

init = collection.init_state(logits[0], labels[0])


@jax.jit
def epoch(states, batched_logits, batched_labels):
    def step(states, batch):
        preds, target = batch
        # a real loop would compute grads here too; the metric update rides
        # the same compiled program instead of paying per-step dispatch
        return collection.update_state(states, preds, target), ()

    states, _ = jax.lax.scan(step, states, (batched_logits, batched_labels))
    return states


final_states = epoch(init, logits, labels)
results = collection.compute_state(final_states)

expected = float((logits.argmax(-1) == labels).mean())
print({k: round(float(v), 4) for k, v in results.items()})
assert abs(float(results["acc"]) - expected) < 1e-6, "scan accumulation must equal the eager epoch"
print("ok: one XLA program for the whole epoch;", len(collection.compute_groups), "fused group(s)")
