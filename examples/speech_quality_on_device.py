"""Batched on-device speech-quality evaluation: PESQ (native) + STOI + SI-SNR.

Beyond-reference example: the reference evaluates PESQ/STOI per sample on the
host through C extensions (torchmetrics/audio/pesq.py:25). Here the whole
quality panel — the native P.862-style PESQ model, STOI DSP, and SI-SNR —
runs as ONE jitted program over a batch of utterances, so a TPU evaluates an
entire eval set of clips in a single dispatch.

To run: python examples/speech_quality_on_device.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu import (
    PerceptualEvaluationSpeechQuality,
    ScaleInvariantSignalNoiseRatio,
    ShortTimeObjectiveIntelligibility,
)
from metrics_tpu.ops.audio.pesq_native import pesq_native
from metrics_tpu.ops.audio.snr import scale_invariant_signal_noise_ratio
from metrics_tpu.ops.audio.stoi import short_time_objective_intelligibility

FS_STOI = 10000  # STOI's native rate — no resampling inside jit
FS_PESQ = 8000   # narrowband PESQ rate
BATCH, SECONDS = 8, 2

def make_batch(fs):
    """Synthesize the SAME utterances at a given rate (each metric gets audio
    at its native rate — never truncate one rate into another). A fresh
    seeded rng per call keeps the noise process identical across rates."""
    rng = np.random.default_rng(0)
    t = np.arange(SECONDS * fs) / fs
    clean = np.stack([
        np.sin(2 * np.pi * (110 + 15 * i) * t) * (0.3 + 0.7 * (np.sin(2 * np.pi * 3 * t + i) > 0))
        for i in range(BATCH)
    ]).astype(np.float32)
    noise = rng.normal(size=clean.shape).astype(np.float32)
    return clean, clean + 0.25 * noise


clean10, noisy10 = make_batch(FS_STOI)
clean8, noisy8 = make_batch(FS_PESQ)


# one compiled program scores the whole batch on all three metrics
@jax.jit
def quality_panel(preds10, target10, preds8, target8):
    return {
        "pesq_nb": pesq_native(preds8, target8, FS_PESQ, "nb"),
        "stoi": short_time_objective_intelligibility(preds10, target10, FS_STOI),
        "si_snr": scale_invariant_signal_noise_ratio(preds10, target10),
    }


panel = quality_panel(jnp.asarray(noisy10), jnp.asarray(clean10), jnp.asarray(noisy8), jnp.asarray(clean8))
for name, vals in panel.items():
    print(f"{name:>8}: per-clip {np.round(np.asarray(vals), 3)}  mean {float(jnp.mean(vals)):.3f}")

# the same metrics through the stateful facade, accumulating across batches
metrics = {
    "pesq": PerceptualEvaluationSpeechQuality(FS_PESQ, "nb", implementation="native"),
    "stoi": ShortTimeObjectiveIntelligibility(fs=FS_STOI),
    "si_snr": ScaleInvariantSignalNoiseRatio(),
}
for start in range(0, BATCH, 4):
    sl = slice(start, start + 4)
    metrics["pesq"].update(jnp.asarray(noisy8[sl]), jnp.asarray(clean8[sl]))
    metrics["stoi"].update(jnp.asarray(noisy10[sl]), jnp.asarray(clean10[sl]))
    metrics["si_snr"].update(jnp.asarray(noisy10[sl]), jnp.asarray(clean10[sl]))
print("epoch:", {k: round(float(m.compute()), 3) for k, m in metrics.items()})
