"""MeanAveragePrecision quickstart on toy detections.

Reference parity: tm_examples/detection_map.py — same shape of example, with
jax arrays and the metrics_tpu MeanAveragePrecision.

To run: python examples/detection_map.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pprint import pprint

import jax.numpy as jnp

from metrics_tpu.detection import MeanAveragePrecision

preds = [
    {
        "boxes": jnp.asarray([[258.0, 41.0, 606.0, 285.0]]),
        "scores": jnp.asarray([0.536]),
        "labels": jnp.asarray([0]),
    }
]
target = [
    {
        "boxes": jnp.asarray([[214.0, 41.0, 562.0, 285.0]]),
        "labels": jnp.asarray([0]),
    }
]

if __name__ == "__main__":
    metric = MeanAveragePrecision()
    metric.update(preds, target)
    pprint({k: float(v) if v.ndim == 0 else v.tolist() for k, v in metric.compute().items()})
