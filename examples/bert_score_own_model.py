"""BERTScore with a user-defined model and tokenizer.

Reference parity: tm_examples/bert_score-own_model.py — the user plugs a
custom encoder through ``model``/``user_tokenizer``/``user_forward_fn``. Here
the "model" is a tiny jax function over word embeddings; any Flax module works
the same way (its ``__call__``/apply output plays the last-hidden-state role).

To run: python examples/bert_score_own_model.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pprint import pprint
from typing import Dict, List, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.text import BERTScore

_MODEL_DIM = 4
_MAX_LEN = 6


class UserTokenizer:
    """Maps words to fixed embeddings; returns input_ids as embeddings plus an
    attention mask, the structure BERTScore's user hooks expect."""

    CLS_TOKEN = "<cls>"
    SEP_TOKEN = "<sep>"
    PAD_TOKEN = "<pad>"

    def __init__(self) -> None:
        self.word2vec = {
            "hello": 0.5 * jnp.ones((1, _MODEL_DIM)),
            "world": -0.5 * jnp.ones((1, _MODEL_DIM)),
            self.CLS_TOKEN: jnp.zeros((1, _MODEL_DIM)),
            self.SEP_TOKEN: jnp.zeros((1, _MODEL_DIM)),
            self.PAD_TOKEN: jnp.zeros((1, _MODEL_DIM)),
        }

    def __call__(self, sentences: Union[str, List[str]], max_len: int = _MAX_LEN) -> Dict[str, Array]:
        if isinstance(sentences, str):
            sentences = [sentences]
        output_ids = []
        attention_mask = []
        for sentence in sentences:
            tokens = [self.CLS_TOKEN, *sentence.lower().split(), self.SEP_TOKEN]
            tokens += [self.PAD_TOKEN] * (max_len - len(tokens))
            output_ids.append(jnp.concatenate([self.word2vec[t] for t in tokens[:max_len]], axis=0))
            attention_mask.append(jnp.asarray([1 if t != self.PAD_TOKEN else 0 for t in tokens[:max_len]]))
        return {
            "input_ids": jnp.stack(output_ids),
            "attention_mask": jnp.stack(attention_mask).astype(jnp.int32),
        }


def user_forward_fn(model, batch: Dict[str, Array]) -> Array:
    """Run the user model; returns [batch, seq_len, dim] embeddings."""
    return model(batch["input_ids"])


def toy_model(embeddings: Array) -> Array:
    # identity "encoder": the embeddings ARE the hidden states
    return embeddings


if __name__ == "__main__":
    tokenizer = UserTokenizer()
    scorer = BERTScore(
        model=toy_model, user_tokenizer=tokenizer, user_forward_fn=user_forward_fn, max_length=_MAX_LEN
    )
    scorer.update(["hello world", "world world"], ["hello world", "hello hello"])
    pprint(scorer.compute())
