"""Pure-tensor image metric parity vs hand-rolled numpy/scipy oracles.

Reference parity: tests/image/test_ssim.py, test_psnr.py, test_uqi.py,
test_d_lambda.py, test_ergas.py, test_sam.py, test_image_gradients.py.
The oracles below are independent numpy implementations (scipy.signal convs),
mirroring the reference's tests/helpers/reference_metrics.py approach where no
trusted package oracle is installed.
"""
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.signal import correlate2d

from metrics_tpu.image import (
    ErrorRelativeGlobalDimensionlessSynthesis,
    MultiScaleStructuralSimilarityIndexMeasure,
    PeakSignalNoiseRatio,
    SpectralAngleMapper,
    SpectralDistortionIndex,
    StructuralSimilarityIndexMeasure,
    UniversalImageQualityIndex,
)
from metrics_tpu.ops.image import (
    error_relative_global_dimensionless_synthesis,
    image_gradients,
    multiscale_structural_similarity_index_measure,
    peak_signal_noise_ratio,
    spectral_angle_mapper,
    spectral_distortion_index,
    structural_similarity_index_measure,
    universal_image_quality_index,
)
from tests.helpers.testers import MetricTester

_rng = np.random.default_rng(42)
NB = 4
PREDS = _rng.random((NB, 4, 1, 16, 16)).astype(np.float32)
TARGET = (0.75 * PREDS + 0.25 * _rng.random((NB, 4, 1, 16, 16))).astype(np.float32)
PREDS_C3 = _rng.random((NB, 4, 3, 16, 16)).astype(np.float32)
TARGET_C3 = (0.6 * PREDS_C3 + 0.4 * _rng.random((NB, 4, 3, 16, 16))).astype(np.float32)


# --------------------------------------------------------------------------- #
# numpy oracles
# --------------------------------------------------------------------------- #
def _np_gaussian_1d(size, sigma):
    dist = np.arange((1 - size) / 2, (1 + size) / 2)
    g = np.exp(-((dist / sigma) ** 2) / 2)
    return g / g.sum()


def _np_gauss_size(sigma):
    return int(3.5 * sigma + 0.5) * 2 + 1


def _np_ssim_cs(preds, target, sigma=1.5, data_range=None, k1=0.01, k2=0.03):
    """Per-image (ssim, cs) means over the valid (un-padded) region."""
    if data_range is None:
        data_range = max(preds.max() - preds.min(), target.max() - target.min())
    c1, c2 = (k1 * data_range) ** 2, (k2 * data_range) ** 2
    size = _np_gauss_size(sigma)
    g = _np_gaussian_1d(size, sigma)
    kern = np.outer(g, g)
    conv = lambda x: correlate2d(x, kern, mode="valid")
    sims, css = [], []
    for b in range(preds.shape[0]):
        sim_maps, cs_maps = [], []
        for c in range(preds.shape[1]):
            p, t = preds[b, c].astype(np.float64), target[b, c].astype(np.float64)
            mu_p, mu_t = conv(p), conv(t)
            s_pp = conv(p * p) - mu_p ** 2
            s_tt = conv(t * t) - mu_t ** 2
            s_pt = conv(p * t) - mu_p * mu_t
            upper = 2 * s_pt + c2
            lower = s_pp + s_tt + c2
            sim_maps.append(((2 * mu_p * mu_t + c1) * upper) / ((mu_p ** 2 + mu_t ** 2 + c1) * lower))
            cs_maps.append(upper / lower)
        sims.append(np.mean(sim_maps))
        css.append(np.mean(cs_maps))
    return np.asarray(sims), np.asarray(css)


def _np_ssim(preds, target, **kw):
    return _np_ssim_cs(preds, target, **kw)[0].mean()


def _np_avg_pool2(x):
    b, c, h, w = x.shape
    return x[:, :, : h // 2 * 2, : w // 2 * 2].reshape(b, c, h // 2, 2, w // 2, 2).mean(axis=(3, 5))


def _np_ms_ssim(preds, target, sigma, betas, data_range=None, normalize=None):
    """Per-image MS-SSIM (canonical Wang et al. form), then batch mean."""
    sims, css = [], []
    for _ in betas:
        s, c = _np_ssim_cs(preds, target, sigma=sigma, data_range=data_range)  # (B,)
        if normalize == "relu":
            s, c = np.maximum(s, 0.0), np.maximum(c, 0.0)
        sims.append(s)
        css.append(c)
        preds, target = _np_avg_pool2(preds), _np_avg_pool2(target)
    sims, css = np.stack(sims), np.stack(css)  # (S, B)
    if normalize == "simple":
        sims, css = (sims + 1) / 2, (css + 1) / 2
    betas = np.asarray(betas)[:, None]
    per_image = np.prod(css[:-1] ** betas[:-1], axis=0) * sims[-1] ** betas[-1]
    return per_image.mean()


def _np_psnr(preds, target, data_range=None, base=10.0):
    if data_range is None:
        data_range = target.max() - target.min()
    mse = np.mean((preds.astype(np.float64) - target.astype(np.float64)) ** 2)
    return (2 * np.log(data_range) - np.log(mse)) * 10 / np.log(base)


def _np_uqi(preds, target, sigma=1.5, size=11):
    g = _np_gaussian_1d(size, sigma)
    kern = np.outer(g, g)
    conv = lambda x: correlate2d(x, kern, mode="valid")
    maps = []
    for b in range(preds.shape[0]):
        for c in range(preds.shape[1]):
            p, t = preds[b, c].astype(np.float64), target[b, c].astype(np.float64)
            mu_p, mu_t = conv(p), conv(t)
            s_pp = conv(p * p) - mu_p ** 2
            s_tt = conv(t * t) - mu_t ** 2
            s_pt = conv(p * t) - mu_p * mu_t
            maps.append(((2 * mu_p * mu_t) * 2 * s_pt) / ((mu_p ** 2 + mu_t ** 2) * (s_pp + s_tt)))
    return np.mean(maps)


def _np_d_lambda(preds, target, p=1):
    length = preds.shape[1]
    m1 = np.zeros((length, length))
    m2 = np.zeros((length, length))
    for k in range(length):
        for r in range(k, length):
            m1[k, r] = m1[r, k] = _np_uqi(target[:, k : k + 1], target[:, r : r + 1])
            m2[k, r] = m2[r, k] = _np_uqi(preds[:, k : k + 1], preds[:, r : r + 1])
    diff = np.abs(m1 - m2) ** p
    if length == 1:
        return diff.item() ** (1 / p)
    return (diff.sum() / (length * (length - 1))) ** (1 / p)


def _np_ergas(preds, target, ratio=4):
    b, c, h, w = preds.shape
    p = preds.reshape(b, c, -1).astype(np.float64)
    t = target.reshape(b, c, -1).astype(np.float64)
    rmse = np.sqrt(np.mean((p - t) ** 2, axis=2))
    mean_t = t.mean(axis=2)
    return np.mean(100 * ratio * np.sqrt(np.sum((rmse / mean_t) ** 2, axis=1) / c))


def _np_sam(preds, target):
    p, t = preds.astype(np.float64), target.astype(np.float64)
    dot = (p * t).sum(axis=1)
    cos = np.clip(dot / (np.linalg.norm(p, axis=1) * np.linalg.norm(t, axis=1)), -1, 1)
    return np.arccos(cos).mean()


# --------------------------------------------------------------------------- #
# functional parity
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("data_range", [None, 1.0])
def test_ssim_functional(data_range):
    res = structural_similarity_index_measure(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), data_range=data_range)
    np.testing.assert_allclose(np.asarray(res), _np_ssim(PREDS[0], TARGET[0], data_range=data_range), atol=1e-4)


def test_ssim_multichannel():
    res = structural_similarity_index_measure(jnp.asarray(PREDS_C3[0]), jnp.asarray(TARGET_C3[0]))
    np.testing.assert_allclose(np.asarray(res), _np_ssim(PREDS_C3[0], TARGET_C3[0]), atol=1e-4)


def test_ssim_reduction_none():
    res = structural_similarity_index_measure(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), reduction="none")
    np.testing.assert_allclose(np.asarray(res), _np_ssim_cs(PREDS[0], TARGET[0])[0], atol=1e-4)


def test_ssim_identical_images():
    res = structural_similarity_index_measure(jnp.asarray(PREDS[0]), jnp.asarray(PREDS[0]), data_range=1.0)
    np.testing.assert_allclose(np.asarray(res), 1.0, atol=1e-5)


def test_ssim_3d_smoke():
    p = jnp.asarray(_rng.random((2, 1, 12, 12, 12)).astype(np.float32))
    res = structural_similarity_index_measure(p, p, data_range=1.0)
    np.testing.assert_allclose(np.asarray(res), 1.0, atol=1e-5)


def test_ssim_contrast_sensitivity():
    sim, cs = structural_similarity_index_measure(
        jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), return_contrast_sensitivity=True
    )
    np_sim, np_cs = _np_ssim_cs(PREDS[0], TARGET[0])
    np.testing.assert_allclose(np.asarray(sim), np_sim.mean(), atol=1e-4)
    np.testing.assert_allclose(np.asarray(cs), np_cs.mean(), atol=1e-4)


@pytest.mark.parametrize("normalize", [None, "relu", "simple"])
def test_ms_ssim_functional(normalize):
    p = _rng.random((2, 1, 32, 32)).astype(np.float32)
    t = (0.75 * p + 0.25 * _rng.random((2, 1, 32, 32))).astype(np.float32)
    betas = (0.2, 0.3, 0.5)
    res = multiscale_structural_similarity_index_measure(
        jnp.asarray(p), jnp.asarray(t), sigma=0.5, kernel_size=5, betas=betas, normalize=normalize
    )
    expected = _np_ms_ssim(p, t, sigma=0.5, betas=betas, normalize=normalize)
    np.testing.assert_allclose(np.asarray(res), expected, atol=1e-4)


def test_ms_ssim_small_image_guard():
    p = jnp.asarray(_rng.random((1, 1, 11, 11)).astype(np.float32))
    with pytest.raises(ValueError, match="must be larger than"):
        multiscale_structural_similarity_index_measure(p, p, betas=(0.5, 0.5))


@pytest.mark.parametrize("base", [10.0, 2.0])
@pytest.mark.parametrize("data_range", [None, 1.0])
def test_psnr_functional(base, data_range):
    res = peak_signal_noise_ratio(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), data_range=data_range, base=base)
    np.testing.assert_allclose(np.asarray(res), _np_psnr(PREDS[0], TARGET[0], data_range, base), rtol=1e-5)


def test_psnr_dim():
    res = peak_signal_noise_ratio(
        jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), data_range=1.0, dim=(1, 2, 3), reduction="elementwise_mean"
    )
    per_img = [_np_psnr(PREDS[0][i], TARGET[0][i], 1.0) for i in range(PREDS.shape[1])]
    np.testing.assert_allclose(np.asarray(res), np.mean(per_img), rtol=1e-5)


def test_psnr_dim_requires_data_range():
    with pytest.raises(ValueError, match="The `data_range` must be given"):
        peak_signal_noise_ratio(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), dim=0)


def test_uqi_functional():
    res = universal_image_quality_index(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]))
    np.testing.assert_allclose(np.asarray(res), _np_uqi(PREDS[0], TARGET[0]), atol=1e-4)


@pytest.mark.parametrize("p", [1, 3])
def test_d_lambda_functional(p):
    res = spectral_distortion_index(jnp.asarray(PREDS_C3[0]), jnp.asarray(TARGET_C3[0]), p=p)
    np.testing.assert_allclose(np.asarray(res), _np_d_lambda(PREDS_C3[0], TARGET_C3[0], p=p), atol=1e-4)


@pytest.mark.parametrize("ratio", [4, 2])
def test_ergas_functional(ratio):
    res = error_relative_global_dimensionless_synthesis(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), ratio=ratio)
    np.testing.assert_allclose(np.asarray(res), _np_ergas(PREDS[0], TARGET[0], ratio), rtol=1e-4)


def test_sam_functional():
    res = spectral_angle_mapper(jnp.asarray(PREDS_C3[0]), jnp.asarray(TARGET_C3[0]))
    np.testing.assert_allclose(np.asarray(res), _np_sam(PREDS_C3[0], TARGET_C3[0]), atol=1e-5)


def test_sam_requires_multichannel():
    with pytest.raises(ValueError, match="channel dimension"):
        spectral_angle_mapper(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]))


def test_image_gradients():
    image = jnp.arange(25, dtype=jnp.float32).reshape(1, 1, 5, 5)
    dy, dx = image_gradients(image)
    assert dy.shape == dx.shape == (1, 1, 5, 5)
    np.testing.assert_allclose(np.asarray(dy[0, 0, :4]), np.full((4, 5), 5.0))
    np.testing.assert_allclose(np.asarray(dy[0, 0, 4]), np.zeros(5))
    np.testing.assert_allclose(np.asarray(dx[0, 0, :, :4]), np.full((5, 4), 1.0))
    np.testing.assert_allclose(np.asarray(dx[0, 0, :, 4]), np.zeros(5))


def test_image_gradients_validation():
    with pytest.raises(RuntimeError, match="4D tensor"):
        image_gradients(jnp.zeros((5, 5)))


# --------------------------------------------------------------------------- #
# module classes (incl. ddp over the 8-device CPU mesh)
# --------------------------------------------------------------------------- #
class TestImageModules(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("ddp", [False, True])
    def test_psnr_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=PREDS,
            target=TARGET,
            metric_class=PeakSignalNoiseRatio,
            sk_metric=lambda p, t: _np_psnr(p, t, data_range=1.0),
            metric_args={"data_range": 1.0},
            check_batch=True,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_ssim_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=PREDS,
            target=TARGET,
            metric_class=StructuralSimilarityIndexMeasure,
            sk_metric=lambda p, t: _np_ssim(p, t, data_range=1.0),
            metric_args={"data_range": 1.0},
            check_batch=True,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_sam_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=PREDS_C3,
            target=TARGET_C3,
            metric_class=SpectralAngleMapper,
            sk_metric=_np_sam,
            check_batch=True,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_ergas_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=PREDS,
            target=TARGET,
            metric_class=ErrorRelativeGlobalDimensionlessSynthesis,
            sk_metric=_np_ergas,
            check_batch=True,
        )

    def test_uqi_class(self):
        self.run_class_metric_test(
            ddp=False,
            preds=PREDS,
            target=TARGET,
            metric_class=UniversalImageQualityIndex,
            sk_metric=_np_uqi,
            check_batch=True,
        )

    def test_d_lambda_class(self):
        self.run_class_metric_test(
            ddp=False,
            preds=PREDS_C3,
            target=TARGET_C3,
            metric_class=SpectralDistortionIndex,
            sk_metric=_np_d_lambda,
            check_batch=True,
        )

    def test_ms_ssim_class(self):
        p = _rng.random((2, 2, 1, 32, 32)).astype(np.float32)
        t = (0.75 * p + 0.25 * _rng.random((2, 2, 1, 32, 32))).astype(np.float32)
        betas = (0.2, 0.3, 0.5)
        self.run_class_metric_test(
            ddp=False,
            preds=p,
            target=t,
            metric_class=MultiScaleStructuralSimilarityIndexMeasure,
            sk_metric=lambda pp, tt: _np_ms_ssim(pp, tt, sigma=0.5, betas=np.asarray(betas), normalize="relu"),
            metric_args={"sigma": 0.5, "kernel_size": 5, "betas": betas},
            check_batch=True,
        )

    def test_precision_bf16(self):
        ssim_cast = lambda p, t, **kw: structural_similarity_index_measure(p, t.astype(p.dtype), **kw)
        self.run_precision_test(PREDS, TARGET, ssim_cast, {"data_range": 1.0})
        self.run_precision_test(PREDS, TARGET, peak_signal_noise_ratio, {"data_range": 1.0})

    def test_differentiability(self):
        self.run_differentiability_test(PREDS, TARGET, structural_similarity_index_measure, {"data_range": 1.0})
        self.run_differentiability_test(PREDS, TARGET, peak_signal_noise_ratio, {"data_range": 1.0})
