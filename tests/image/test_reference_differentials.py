"""Image option surfaces pinned directly against the reference implementation.

The SSIM family's gaussian kernels, padding, and multiscale downsampling are
the numerically fiddliest part of the image domain; the repo's other tests
use self-written numpy oracles. This module asserts exact agreement with the
reference functionals running live on identical inputs (reference
functional/image/ssim.py, psnr.py, uqi.py, sam.py, ergas.py, d_lambda.py).
"""
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.functional as mtf

_rng = np.random.default_rng(21)
PREDS = _rng.random((4, 3, 32, 32)).astype(np.float32)
TARGET = _rng.random((4, 3, 32, 32)).astype(np.float32)


def _ref():
    from tests.conftest import reference_functional

    return reference_functional()


@pytest.mark.parametrize("sigma", [0.8, 1.5, 2.5])
@pytest.mark.parametrize("data_range", [1.0, 2.0])
def test_ssim_sigma_vs_reference(sigma, data_range):
    torch, F = _ref()
    ours = float(
        mtf.structural_similarity_index_measure(
            jnp.asarray(PREDS), jnp.asarray(TARGET), sigma=sigma, data_range=data_range
        )
    )
    want = float(
        F.structural_similarity_index_measure(
            torch.tensor(PREDS), torch.tensor(TARGET), sigma=sigma, data_range=data_range
        )
    )
    np.testing.assert_allclose(ours, want, atol=1e-5)


@pytest.mark.parametrize("kernel_size", [7, 11])
@pytest.mark.parametrize("k1,k2", [(0.01, 0.03), (0.05, 0.1)])
def test_ssim_kernel_k_vs_reference(kernel_size, k1, k2):
    torch, F = _ref()
    ours = float(
        mtf.structural_similarity_index_measure(
            jnp.asarray(PREDS), jnp.asarray(TARGET), kernel_size=kernel_size, k1=k1, k2=k2, data_range=1.0
        )
    )
    want = float(
        F.structural_similarity_index_measure(
            torch.tensor(PREDS), torch.tensor(TARGET), kernel_size=kernel_size, k1=k1, k2=k2, data_range=1.0
        )
    )
    np.testing.assert_allclose(ours, want, atol=1e-5)


@pytest.mark.parametrize("normalize", [None, "relu", "simple"])
def test_ms_ssim_vs_reference(normalize):
    torch, F = _ref()
    # 5 scales halve 4x; the effective gaussian kernel (11) must fit at the
    # smallest scale, so 256 -> 16 per side is the minimum that passes the guard
    p = _rng.random((2, 3, 256, 256)).astype(np.float32)
    t = np.clip(p + 0.1 * _rng.standard_normal(p.shape).astype(np.float32), 0, 1)
    ours = float(
        mtf.multiscale_structural_similarity_index_measure(
            jnp.asarray(p), jnp.asarray(t), data_range=1.0, normalize=normalize
        )
    )
    want = float(
        F.multiscale_structural_similarity_index_measure(
            torch.tensor(p), torch.tensor(t), data_range=1.0, normalize=normalize
        )
    )
    np.testing.assert_allclose(ours, want, atol=1e-5)


@pytest.mark.parametrize("base", [2.0, 10.0])
@pytest.mark.parametrize("reduction", ["elementwise_mean", "sum", "none"])
def test_psnr_options_vs_reference(base, reduction):
    torch, F = _ref()
    ours = mtf.peak_signal_noise_ratio(
        jnp.asarray(PREDS), jnp.asarray(TARGET), data_range=1.0, base=base, reduction=reduction, dim=(1, 2, 3)
    )
    want = F.peak_signal_noise_ratio(
        torch.tensor(PREDS), torch.tensor(TARGET), data_range=1.0, base=base, reduction=reduction, dim=(1, 2, 3)
    )
    np.testing.assert_allclose(np.asarray(ours), np.asarray(want), atol=1e-4)


def test_uqi_vs_reference():
    torch, F = _ref()
    ours = float(mtf.universal_image_quality_index(jnp.asarray(PREDS), jnp.asarray(TARGET)))
    want = float(F.universal_image_quality_index(torch.tensor(PREDS), torch.tensor(TARGET)))
    np.testing.assert_allclose(ours, want, atol=1e-5)


@pytest.mark.parametrize("reduction", ["elementwise_mean", "sum"])
def test_sam_vs_reference(reduction):
    torch, F = _ref()
    ours = mtf.spectral_angle_mapper(jnp.asarray(PREDS), jnp.asarray(TARGET), reduction=reduction)
    want = F.spectral_angle_mapper(torch.tensor(PREDS), torch.tensor(TARGET), reduction=reduction)
    # rtol: 'sum' accumulates ~1k angles in f32, so agreement is relative
    np.testing.assert_allclose(np.asarray(ours), np.asarray(want), rtol=1e-5)


@pytest.mark.parametrize("ratio", [2, 4])
def test_ergas_vs_reference(ratio):
    torch, F = _ref()
    ours = float(mtf.error_relative_global_dimensionless_synthesis(jnp.asarray(PREDS), jnp.asarray(TARGET), ratio=ratio))
    want = float(
        F.error_relative_global_dimensionless_synthesis(torch.tensor(PREDS), torch.tensor(TARGET), ratio=ratio)
    )
    np.testing.assert_allclose(ours, want, rtol=1e-4)


@pytest.mark.parametrize("p", [1, 3])
def test_d_lambda_vs_reference(p):
    torch, F = _ref()
    ours = float(mtf.spectral_distortion_index(jnp.asarray(PREDS), jnp.asarray(TARGET), p=p))
    want = float(F.spectral_distortion_index(torch.tensor(PREDS), torch.tensor(TARGET), p=p))
    np.testing.assert_allclose(ours, want, atol=1e-5)
