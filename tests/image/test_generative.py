"""FID / IS / KID / LPIPS tests.

Reference parity: tests/image/test_fid.py, test_inception.py, test_kid.py,
test_lpips.py. Math is verified against scipy oracles (scipy.linalg.sqrtm for
the Frechet term) with stub feature extractors; the Inception/LPIPS nets are
exercised architecture-only (shape, determinism, jit) since original torch
checkpoints are not available offline.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.linalg

from metrics_tpu.image import (
    FrechetInceptionDistance,
    InceptionScore,
    KernelInceptionDistance,
    LearnedPerceptualImagePatchSimilarity,
)
from metrics_tpu.nets.inception import InceptionV3, InceptionV3FeatureExtractor
from metrics_tpu.nets.lpips import LPIPSNet
from metrics_tpu.ops.image.fid import _compute_fid, frechet_distance, sqrtm_psd, trace_sqrtm_product
from metrics_tpu.ops.image.kid import poly_mmd

_rng = np.random.default_rng(7)
D = 16


def _random_cov(d, rng):
    a = rng.normal(size=(d, 2 * d))
    return a @ a.T / (2 * d)


def _np_fid(mu1, s1, mu2, s2):
    covmean, _ = scipy.linalg.sqrtm(s1 @ s2, disp=False)
    diff = mu1 - mu2
    return float(diff @ diff + np.trace(s1) + np.trace(s2) - 2 * np.trace(covmean.real))


class _StubExtractor:
    """Feature extractor stub: flattens and projects images to D dims."""

    num_features = D

    def __init__(self, in_dim):
        self.w = jnp.asarray(_rng.normal(size=(in_dim, D)).astype(np.float32) / np.sqrt(in_dim))

    def __call__(self, imgs):
        return imgs.reshape(imgs.shape[0], -1) @ self.w


# --------------------------------------------------------------------------- #
# frechet math vs scipy
# --------------------------------------------------------------------------- #
def test_sqrtm_psd_vs_scipy():
    s = _random_cov(D, _rng)
    got = np.asarray(sqrtm_psd(jnp.asarray(s)))
    want = scipy.linalg.sqrtm(s).real
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_trace_sqrtm_product_vs_scipy():
    s1, s2 = _random_cov(D, _rng), _random_cov(D, _rng)
    got = float(trace_sqrtm_product(jnp.asarray(s1), jnp.asarray(s2)))
    want = np.trace(scipy.linalg.sqrtm(s1 @ s2).real)
    np.testing.assert_allclose(got, want, rtol=1e-4)


def test_compute_fid_vs_scipy():
    mu1, mu2 = _rng.normal(size=D), _rng.normal(size=D)
    s1, s2 = _random_cov(D, _rng), _random_cov(D, _rng)
    got = float(_compute_fid(jnp.asarray(mu1), jnp.asarray(s1), jnp.asarray(mu2), jnp.asarray(s2)))
    np.testing.assert_allclose(got, _np_fid(mu1, s1, mu2, s2), rtol=1e-4)


def test_compute_fid_near_singular():
    # rank-deficient covariances must not produce NaN (reference adds eps offsets)
    a = _rng.normal(size=(D, 3))
    s1 = a @ a.T
    s2 = s1.copy()
    mu = _rng.normal(size=D)
    got = float(_compute_fid(jnp.asarray(mu), jnp.asarray(s1), jnp.asarray(mu), jnp.asarray(s2)))
    # f32 eigh noise scales with trace(s); exact answer is 0
    assert np.isfinite(got) and abs(got) < 2e-3 * np.trace(s1)


def test_frechet_distance_identical_sets():
    feats = jnp.asarray(_rng.normal(size=(200, D)).astype(np.float32))
    assert abs(float(frechet_distance(feats, feats))) < 1e-3


# --------------------------------------------------------------------------- #
# FID module: streaming moments == batch-at-once, ddp merge
# --------------------------------------------------------------------------- #
def test_fid_module_vs_oracle():
    extractor = _StubExtractor(3 * 8 * 8)
    fid = FrechetInceptionDistance(feature=extractor)
    real = _rng.normal(size=(4, 16, 3, 8, 8)).astype(np.float32)
    fake = (_rng.normal(size=(4, 16, 3, 8, 8)) + 0.5).astype(np.float32)
    for i in range(4):
        fid.update(jnp.asarray(real[i]), real=True)
        fid.update(jnp.asarray(fake[i]), real=False)
    got = float(fid.compute())

    rf = np.asarray(extractor(jnp.asarray(real.reshape(-1, 3, 8, 8)))).astype(np.float64)
    ff = np.asarray(extractor(jnp.asarray(fake.reshape(-1, 3, 8, 8)))).astype(np.float64)
    mu1, mu2 = rf.mean(0), ff.mean(0)
    s1 = np.cov(rf, rowvar=False)
    s2 = np.cov(ff, rowvar=False)
    np.testing.assert_allclose(got, _np_fid(mu1, s1, mu2, s2), rtol=1e-3, atol=1e-3)


def test_fid_streaming_precision_noncentered():
    # means dominating the spread is the norm for Inception activations; raw
    # sum(xx^T) moments cancel catastrophically in f32, Welford must not
    fid = FrechetInceptionDistance(feature=lambda x: x, feature_size=D)
    feats = (5.0 + 0.05 * _rng.normal(size=(200, 100, D))).astype(np.float32)
    fake = (5.1 + 0.05 * _rng.normal(size=(200, 100, D))).astype(np.float32)
    for i in range(200):
        fid.update(jnp.asarray(feats[i]), real=True)
        fid.update(jnp.asarray(fake[i]), real=False)
    got_cov = np.asarray(fid.real_m2) / (float(fid.real_n) - 1)
    want_cov = np.cov(feats.reshape(-1, D).astype(np.float64), rowvar=False)
    assert np.max(np.abs(got_cov - want_cov)) / np.max(np.abs(want_cov)) < 1e-2
    rf = feats.reshape(-1, D).astype(np.float64)
    ff = fake.reshape(-1, D).astype(np.float64)
    want = _np_fid(rf.mean(0), np.cov(rf, rowvar=False), ff.mean(0), np.cov(ff, rowvar=False))
    np.testing.assert_allclose(float(fid.compute()), want, rtol=5e-2, atol=5e-3)


@pytest.mark.mesh8
def test_fid_distributed_sync():
    # joint Welford sync over an 8-device mesh == oracle on all shards
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 devices")
    mesh = Mesh(np.stack(devices[:8]), ("data",))
    fid = FrechetInceptionDistance(feature=lambda x: x, feature_size=D)
    real = _rng.normal(size=(8, 32, D)).astype(np.float32)
    fake = (_rng.normal(size=(8, 32, D)) + 0.3).astype(np.float32)

    def body(r, f):
        state = fid.init_state()
        state = fid.update_state(state, r[0], True)
        state = fid.update_state(state, f[0], False)
        state = fid.sync_states(state, "data")
        return jax.tree.map(lambda x: jnp.expand_dims(x, 0), state)

    out = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data"), check_vma=False)
    )(jnp.asarray(real), jnp.asarray(fake))
    state = jax.tree.map(lambda x: x[0], out)
    got = float(fid.compute_state(state))

    rf = real.reshape(-1, D).astype(np.float64)
    ff = fake.reshape(-1, D).astype(np.float64)
    want = _np_fid(rf.mean(0), np.cov(rf, rowvar=False), ff.mean(0), np.cov(ff, rowvar=False))
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


def test_fid_reset_real_features():
    extractor = _StubExtractor(3 * 8 * 8)
    fid = FrechetInceptionDistance(feature=extractor, reset_real_features=False)
    imgs = jnp.asarray(_rng.normal(size=(16, 3, 8, 8)).astype(np.float32))
    fid.update(imgs, real=True)
    fid.update(imgs, real=False)
    n_before = int(fid.real_n)
    fid.reset()
    assert int(fid.real_n) == n_before and int(fid.fake_n) == 0

    fid2 = FrechetInceptionDistance(feature=extractor, reset_real_features=True)
    fid2.update(imgs, real=True)
    fid2.reset()
    assert int(fid2.real_n) == 0


def test_fid_requires_valid_feature_int():
    with pytest.raises(ValueError, match="must be one of"):
        FrechetInceptionDistance(feature=100)
    with pytest.raises(TypeError, match="unknown input"):
        FrechetInceptionDistance(feature=[1])


# --------------------------------------------------------------------------- #
# KID
# --------------------------------------------------------------------------- #
def _np_poly_mmd(f_real, f_fake, degree=3, gamma=None, coef=1.0):
    if gamma is None:
        gamma = 1.0 / f_real.shape[1]
    k_xx = (f_real @ f_real.T * gamma + coef) ** degree
    k_yy = (f_fake @ f_fake.T * gamma + coef) ** degree
    k_xy = (f_real @ f_fake.T * gamma + coef) ** degree
    m = k_xx.shape[0]
    val = (k_xx.sum() - np.trace(k_xx) + k_yy.sum() - np.trace(k_yy)) / (m * (m - 1))
    return val - 2 * k_xy.sum() / m ** 2


def test_poly_mmd_vs_numpy():
    fr = _rng.normal(size=(32, D)).astype(np.float32)
    ff = _rng.normal(size=(32, D)).astype(np.float32)
    got = float(poly_mmd(jnp.asarray(fr), jnp.asarray(ff)))
    np.testing.assert_allclose(got, _np_poly_mmd(fr, ff), rtol=1e-4, atol=1e-5)


def test_kid_module():
    extractor = _StubExtractor(3 * 8 * 8)
    kid = KernelInceptionDistance(feature=extractor, subsets=10, subset_size=20, seed=0)
    real = jnp.asarray(_rng.normal(size=(40, 3, 8, 8)).astype(np.float32))
    fake = jnp.asarray((_rng.normal(size=(40, 3, 8, 8)) + 1.0).astype(np.float32))
    kid.update(real, real=True)
    kid.update(fake, real=False)
    mean, std = kid.compute()
    assert float(mean) > 0 and float(std) >= 0
    # same distribution -> KID ~ 0
    kid2 = KernelInceptionDistance(feature=extractor, subsets=10, subset_size=20, seed=0)
    kid2.update(real, real=True)
    kid2.update(real, real=False)
    assert abs(float(kid2.compute()[0])) < abs(float(mean))


def test_kid_subset_size_guard():
    extractor = _StubExtractor(3 * 8 * 8)
    kid = KernelInceptionDistance(feature=extractor, subsets=2, subset_size=100)
    imgs = jnp.asarray(_rng.normal(size=(10, 3, 8, 8)).astype(np.float32))
    kid.update(imgs, real=True)
    kid.update(imgs, real=False)
    with pytest.raises(ValueError, match="subset_size"):
        kid.compute()


# --------------------------------------------------------------------------- #
# InceptionScore
# --------------------------------------------------------------------------- #
def test_inception_score_module():
    class _LogitStub:
        num_features = 10

        def __call__(self, imgs):
            return imgs.reshape(imgs.shape[0], -1)[:, :10]

    # n=25, splits=10: torch.chunk gives 9 groups of ceil(25/10)=3 (last of 1)
    # while array_split would give 10 balanced groups — exercises the
    # chunk-semantics path (reference inception.py:133).
    n, splits = 25, 10
    is_metric = InceptionScore(feature=_LogitStub(), splits=splits, seed=0)
    logits = _rng.normal(size=(n, 3, 4, 4)).astype(np.float32)
    is_metric.update(jnp.asarray(logits))
    mean, std = is_metric.compute()

    feats = logits.reshape(n, -1)[:, :10]
    idx = np.random.default_rng(0).permutation(n)
    feats = feats[idx].astype(np.float64)
    prob = np.exp(feats) / np.exp(feats).sum(1, keepdims=True)
    log_prob = feats - np.log(np.exp(feats).sum(1, keepdims=True))
    scores = []
    chunk = -(-n // splits)
    for start in range(0, n, chunk):
        p, lp = prob[start : start + chunk], log_prob[start : start + chunk]
        mp = p.mean(0, keepdims=True)
        scores.append(np.exp((p * (lp - np.log(mp))).sum(1).mean()))
    assert len(scores) == 9  # torch.chunk group count, not array_split's 10
    np.testing.assert_allclose(float(mean), np.mean(scores), rtol=1e-4)
    np.testing.assert_allclose(float(std), np.std(scores, ddof=1), rtol=1e-3)


# --------------------------------------------------------------------------- #
# LPIPS
# --------------------------------------------------------------------------- #
def test_lpips_module_stub_net():
    class _StubNet:
        def __call__(self, a, b):
            return jnp.mean((a - b) ** 2, axis=(1, 2, 3))

    lp = LearnedPerceptualImagePatchSimilarity(net=_StubNet())
    a = jnp.asarray(_rng.uniform(-1, 1, size=(8, 3, 16, 16)).astype(np.float32))
    b = jnp.asarray(_rng.uniform(-1, 1, size=(8, 3, 16, 16)).astype(np.float32))
    lp.update(a, b)
    lp.update(a, a)
    want = (np.mean((np.asarray(a) - np.asarray(b)) ** 2, axis=(1, 2, 3)).sum()) / 16
    np.testing.assert_allclose(float(lp.compute()), want, rtol=1e-5)


def test_lpips_input_validation():
    lp = LearnedPerceptualImagePatchSimilarity(net=lambda a, b: jnp.zeros(a.shape[0]))
    bad = jnp.full((4, 3, 8, 8), 2.0)  # out of [-1,1]
    with pytest.raises(ValueError, match="normalized tensors"):
        lp.update(bad, bad)
    with pytest.raises(ValueError, match="normalized tensors"):
        lp.update(jnp.zeros((4, 1, 8, 8)), jnp.zeros((4, 1, 8, 8)))


@pytest.mark.parametrize("net_type", ["alex", "squeeze"])
def test_lpips_net_architecture(net_type):
    net = LPIPSNet(net_type)
    a = jnp.asarray(_rng.uniform(-1, 1, size=(2, 3, 64, 64)).astype(np.float32))
    b = jnp.asarray(_rng.uniform(-1, 1, size=(2, 3, 64, 64)).astype(np.float32))
    d = net(a, b)
    assert d.shape == (2,)
    assert float(net(a, a).sum()) < 1e-6  # identical images -> zero distance
    np.testing.assert_allclose(np.asarray(net(a, b)), np.asarray(net(a, b)))  # deterministic


def test_lpips_bf16_compute_dtype():
    """Opt-in bf16 trunk: f32 output dtype, distances within bf16 tolerance
    of the f32 path (the TPU-rate deployment mode)."""
    f32 = LPIPSNet("alex")
    bf16 = LPIPSNet("alex", variables=f32.variables, compute_dtype=jnp.bfloat16)
    a = jnp.asarray(_rng.uniform(-1, 1, size=(4, 3, 64, 64)).astype(np.float32))
    b = jnp.asarray(_rng.uniform(-1, 1, size=(4, 3, 64, 64)).astype(np.float32))
    d32, d16 = f32(a, b), bf16(a, b)
    assert d16.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(d16), np.asarray(d32), rtol=2e-2)


# --------------------------------------------------------------------------- #
# Inception architecture (no pretrained weights available offline)
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("feature,dim", [(64, 64), (192, 192)])
def test_inception_taps_small(feature, dim):
    ext = InceptionV3FeatureExtractor(feature)
    imgs = jnp.asarray(_rng.integers(0, 255, size=(2, 3, 64, 64)).astype(np.uint8))
    out = ext(imgs)
    assert out.shape == (2, dim)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_inception_full_trunk_shapes():
    # one full-depth forward: all taps incl. logits on a single tiny batch
    module = InceptionV3(features_list=("64", "192", "768", "2048", "logits_unbiased", "logits"))
    x = jnp.zeros((1, 299, 299, 3))
    variables = module.init(jax.random.PRNGKey(0), x)
    out = module.apply(variables, x)
    shapes = {k: v.shape for k, v in out.items()}
    assert shapes == {
        "64": (1, 64),
        "192": (1, 192),
        "768": (1, 768),
        "2048": (1, 2048),
        "logits_unbiased": (1, 1008),
        "logits": (1, 1008),
    }


# --------------------------------------------------------------------------- #
# torch-forward differentials: converter + flax architecture vs a pure-torch
# oracle with the exact torch-fidelity / lpips forward semantics (the packages
# themselves are unavailable offline; see tests/helpers/torch_nets.py)
# --------------------------------------------------------------------------- #
def _torch_inception_fixture():
    torch = pytest.importorskip("torch")

    from tests.helpers.torch_nets import TorchFIDInception, randomize_inception_

    net = TorchFIDInception()
    randomize_inception_(net, seed=3)
    from metrics_tpu.nets.inception import load_inception_torch_state_dict

    taps = ("64", "192", "768", "2048", "logits_unbiased", "logits")
    variables = load_inception_torch_state_dict(
        {k: v.numpy() for k, v in net.state_dict().items()}, features_list=taps
    )
    return net, variables, taps


def test_inception_torch_forward_differential():
    """flax(convert(torch_state_dict)) must equal the torch forward per tap."""
    torch = pytest.importorskip("torch")

    net, variables, taps = _torch_inception_fixture()
    imgs = _rng.integers(0, 255, size=(3, 3, 96, 96)).astype(np.uint8)
    want = net(torch.as_tensor(imgs))

    from metrics_tpu.nets.inception import _resize_bilinear_tf1

    module = InceptionV3(features_list=taps)
    x = jnp.transpose(jnp.asarray(imgs, jnp.float32), (0, 2, 3, 1))
    x = _resize_bilinear_tf1(x, 299, 299)
    x = (x - 128.0) / 128.0
    got = module.apply(variables, x)
    for tap in taps:
        w = want[tap].numpy()
        scale = np.abs(w).max()
        np.testing.assert_allclose(
            np.asarray(got[tap]), w, rtol=1e-3, atol=1e-3 * scale, err_msg=f"tap {tap}"
        )


def test_fid_end_to_end_torch_differential():
    """Same images through both full FID pipelines -> same number."""
    torch = pytest.importorskip("torch")

    net, variables, _ = _torch_inception_fixture()
    real = _rng.integers(0, 255, size=(16, 3, 64, 64)).astype(np.uint8)
    fake = _rng.integers(0, 255, size=(16, 3, 64, 64)).astype(np.uint8)

    ext = InceptionV3FeatureExtractor("64", variables=variables)
    fid = FrechetInceptionDistance(feature=ext)
    for i in range(0, 16, 8):
        fid.update(jnp.asarray(real[i : i + 8]), real=True)
        fid.update(jnp.asarray(fake[i : i + 8]), real=False)
    got = float(fid.compute())

    rf = net(torch.as_tensor(real))["64"].numpy().astype(np.float64)
    ff = net(torch.as_tensor(fake))["64"].numpy().astype(np.float64)
    want = _np_fid(rf.mean(0), np.cov(rf, rowvar=False), ff.mean(0), np.cov(ff, rowvar=False))
    assert abs(got - want) / max(1.0, abs(want)) < 2e-2


@pytest.mark.parametrize("net_type", ["alex", "vgg"])
def test_lpips_torch_forward_differential(net_type):
    """flax(convert(torch trunk + lin heads)) must equal the torch LPIPS oracle."""
    torch = pytest.importorskip("torch")

    from metrics_tpu.nets.lpips import NET_CHANNELS, load_lpips_torch_state_dict
    from tests.helpers.torch_nets import (
        make_lpips_backbone_state_dict,
        make_lpips_lin_state_dict,
        torch_lpips_forward,
    )

    backbone = make_lpips_backbone_state_dict(net_type, seed=5)
    lin = make_lpips_lin_state_dict(NET_CHANNELS[net_type], seed=6)
    variables = load_lpips_torch_state_dict(backbone, lin, net_type)

    a = _rng.uniform(-1, 1, size=(3, 3, 64, 64)).astype(np.float32)
    b = _rng.uniform(-1, 1, size=(3, 3, 64, 64)).astype(np.float32)
    want = torch_lpips_forward(backbone, lin, net_type, torch.as_tensor(a), torch.as_tensor(b)).numpy()

    net = LPIPSNet(net_type, variables=variables)
    got = np.asarray(net(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # end to end through the metric module: running mean over two updates
    c = _rng.uniform(-1, 1, size=(3, 3, 64, 64)).astype(np.float32)
    want2 = torch_lpips_forward(backbone, lin, net_type, torch.as_tensor(a), torch.as_tensor(c)).numpy()
    lp = LearnedPerceptualImagePatchSimilarity(net=net)
    lp.update(jnp.asarray(a), jnp.asarray(b))
    lp.update(jnp.asarray(a), jnp.asarray(c))
    np.testing.assert_allclose(
        float(lp.compute()), np.concatenate([want, want2]).mean(), rtol=1e-4
    )
