"""FID numerics under f32 on ill-conditioned covariances — the TPU regime.

Reference keeps float64 deliberately for the FID epoch-end math
(torchmetrics/image/fid.py:264-267, scipy sqrtm on host). The tpu path runs
``eigh`` in f32 on device (ops/image/fid.py:36-56); this suite proves that is
enough: on rank-deficient 2048-d covariances built from inception-like
features (n < D, correlated, nonneg, means dominating spread — the worst
realistic conditioning), f32 FID stays within 1e-3 relative error of the
scipy f64 oracle. bench.py records the same differential on the real chip
(``fid_numerics_2048``), making the on-TPU proof part of every bench run.
"""
import numpy as np
import pytest
import scipy.linalg

import jax.numpy as jnp

from metrics_tpu.ops.image.fid import frechet_distance, sqrtm_psd, trace_sqrtm_product
from tests.helpers.fid_fixtures import inception_like, oracle_fid

_rng = np.random.default_rng(0)


def _inception_like(n, d, shift=0.0, rank=64):
    return inception_like(_rng, n, d, shift=shift, rank=rank)


_oracle_fid = oracle_fid


@pytest.mark.parametrize("d,n", [(256, 100), (2048, 500)], ids=["256d-rankdef", "2048d-rankdef"])
def test_f32_fid_vs_f64_oracle_rank_deficient(d, n):
    """n < d: the covariances are singular by construction."""
    fr = _inception_like(n, d)
    ff = _inception_like(n, d, shift=0.05)
    want = _oracle_fid(fr, ff)
    got = float(frechet_distance(jnp.asarray(fr, jnp.float32), jnp.asarray(ff, jnp.float32)))
    rel = abs(got - want) / abs(want)
    assert rel < 1e-3, f"f32 FID rel err {rel:.2e} vs f64 oracle (want {want}, got {got})"


def test_f32_trace_term_bounded():
    """The trace term alone is the weak link — pin its f32 drift explicitly."""
    fr = _inception_like(500, 2048)
    ff = _inception_like(500, 2048, shift=0.05)
    s1 = np.cov(fr, rowvar=False)
    s2 = np.cov(ff, rowvar=False)
    want = float(np.trace(scipy.linalg.sqrtm(s1 @ s2).real))
    got = float(trace_sqrtm_product(jnp.asarray(s1, jnp.float32), jnp.asarray(s2, jnp.float32)))
    assert abs(got - want) / abs(want) < 5e-3


def test_identical_distributions_near_zero():
    # n >> d so the two halves genuinely estimate the same Gaussian (with
    # n < d the TRUE FID between halves is dominated by sampling noise —
    # an f64 oracle shows the same gap, so that regime belongs to the
    # rank-deficient differential tests above, not to this sanity check)
    feats = _inception_like(4000, 64)
    half_a = jnp.asarray(feats[:2000], jnp.float32)
    half_b = jnp.asarray(feats[2000:], jnp.float32)
    fid = float(frechet_distance(half_a, half_b))
    scale = float(np.trace(np.cov(feats, rowvar=False)))
    assert 0 <= fid < 0.05 * scale, (fid, scale)


def test_sqrtm_psd_f32_roundtrip():
    a = _rng.normal(size=(256, 64)) @ _rng.normal(size=(64, 256)) * 0.1
    s = (a @ a.T + 1e-6 * np.eye(256)).astype(np.float64)
    r = np.asarray(sqrtm_psd(jnp.asarray(s, jnp.float32)), np.float64)
    rel = np.linalg.norm(r @ r - s) / np.linalg.norm(s)
    assert rel < 1e-4, rel
