"""SSIM/PSNR parameter sweeps vs the hand-rolled numpy oracles.

Reference analog: tests/image/test_ssim.py parametrizes sigma and data_range
against skimage (absent offline — the oracle here is the independent
scipy.signal implementation from test_image.py). The sweep covers the knobs
that change the Gaussian window and the stabilization constants, where a
broadcasting or constant-handling bug would hide at the defaults.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import ops
from tests.image.test_image import _np_psnr, _np_ssim

_rng = np.random.default_rng(23)
_P = _rng.random((3, 2, 32, 32)).astype(np.float32)
_T = np.clip(_P + 0.1 * _rng.normal(size=_P.shape), 0, 1).astype(np.float32)


@pytest.mark.parametrize("sigma", [0.8, 1.5, 2.5])
def test_ssim_sigma_sweep(sigma):
    # with gaussian_kernel=True the op derives the window size from sigma
    # (same int(3.5*sigma+0.5)*2+1 formula as the oracle) — kernel_size is
    # intentionally NOT passed, it would be ignored
    got = float(ops.structural_similarity_index_measure(
        jnp.asarray(_P), jnp.asarray(_T), sigma=sigma, data_range=1.0,
    ))
    want = _np_ssim(_P, _T, sigma=sigma, data_range=1.0)
    np.testing.assert_allclose(got, want, atol=1e-4)


@pytest.mark.parametrize("k1,k2", [(0.01, 0.03), (0.05, 0.1), (0.001, 0.001)])
def test_ssim_stability_constants(k1, k2):
    got = float(ops.structural_similarity_index_measure(
        jnp.asarray(_P), jnp.asarray(_T), data_range=1.0, k1=k1, k2=k2,
    ))
    want = _np_ssim(_P, _T, data_range=1.0, k1=k1, k2=k2)
    np.testing.assert_allclose(got, want, atol=1e-4)


@pytest.mark.parametrize("data_range", [0.5, 1.0, 255.0])
def test_ssim_data_range_sweep(data_range):
    scale = data_range
    got = float(ops.structural_similarity_index_measure(
        jnp.asarray(_P * scale), jnp.asarray(_T * scale), data_range=data_range,
    ))
    want = _np_ssim(_P * scale, _T * scale, data_range=data_range)
    np.testing.assert_allclose(got, want, atol=1e-4)
    if data_range != 1.0:
        # SSIM is invariant under joint rescaling when data_range scales along
        # (at 1.0 this would compare the call to itself — vacuous)
        base = float(ops.structural_similarity_index_measure(
            jnp.asarray(_P), jnp.asarray(_T), data_range=1.0,
        ))
        np.testing.assert_allclose(got, base, atol=1e-4)


@pytest.mark.parametrize("base", [2.0, 10.0])
@pytest.mark.parametrize("data_range", [1.0, 255.0])
def test_psnr_base_and_range_sweep(base, data_range):
    got = float(ops.peak_signal_noise_ratio(
        jnp.asarray(_P * data_range), jnp.asarray(_T * data_range),
        data_range=data_range, base=base,
    ))
    want = _np_psnr(_P * data_range, _T * data_range, data_range=data_range, base=base)
    np.testing.assert_allclose(got, want, atol=1e-4)
