"""bf16-precision and differentiability grid over the pure-tensor image
functionals not already covered in test_image.py (SSIM/PSNR live there).

Reference parity: tests/helpers/testers.py:478-570.
"""
import numpy as np
import pytest

from metrics_tpu import ops
from tests.helpers.testers import MetricTester

_t = MetricTester()
_rng = np.random.default_rng(37)

PREDS = _rng.random((2, 4, 3, 16, 16)).astype(np.float32)
TARGET = _rng.random((2, 4, 3, 16, 16)).astype(np.float32)

# image metrics enforce matching dtypes, so the target is cast alongside the
# bf16 preds (same pattern as test_image.py's ssim_cast)
CASES = [
    ("uqi", lambda p, t: ops.universal_image_quality_index(p, t.astype(p.dtype))),
    ("sam", lambda p, t: ops.spectral_angle_mapper(p, t.astype(p.dtype))),
    ("ergas", lambda p, t: ops.error_relative_global_dimensionless_synthesis(p, t.astype(p.dtype))),
    ("d_lambda", lambda p, t: ops.spectral_distortion_index(p, t.astype(p.dtype))),
]


@pytest.mark.parametrize("name,fn", CASES, ids=[c[0] for c in CASES])
def test_bf16_precision(name, fn):
    _t.run_precision_test(PREDS, TARGET, fn)


@pytest.mark.parametrize("name,fn", CASES, ids=[c[0] for c in CASES])
def test_differentiability(name, fn):
    _t.run_differentiability_test(PREDS, TARGET, fn)
