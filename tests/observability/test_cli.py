"""``python -m metrics_tpu.observability`` golden tests (pure host-side)."""
import json

import pytest

from metrics_tpu import observability as obs
from metrics_tpu.observability import tracer as _otrace
from metrics_tpu.observability.__main__ import main


@pytest.fixture
def trace_file(tmp_path):
    t = obs.EventTracer()
    t.record("dispatch/compile", "engine", ph=_otrace.PH_COMPLETE, ts=100, dur=5000,
             args={"compile_s": 0.005})
    t.record("dispatch/cached", "engine", ph=_otrace.PH_COMPLETE, ts=6000, dur=40)
    t.record("dispatch/cached", "engine", ph=_otrace.PH_COMPLETE, ts=7000, dur=60)
    t.record("sync/bucket_build", "sync", ph=_otrace.PH_COMPLETE, ts=8000, dur=300,
             args={"collectives": {"psum": 1}})
    return str(obs.write_chrome_trace(tmp_path / "trace.json", t))


class TestDump:
    def test_table_lists_every_event(self, trace_file, capsys):
        assert main(["dump", trace_file]) == 0
        out = capsys.readouterr().out
        assert "dispatch/compile" in out and "sync/bucket_build" in out
        assert "-- 4 events" in out

    def test_cat_and_name_filters(self, trace_file, capsys):
        assert main(["dump", trace_file, "--cat", "sync"]) == 0
        out = capsys.readouterr().out
        assert "sync/bucket_build" in out and "dispatch/" not in out
        assert main(["dump", trace_file, "--name", "cached", "--limit", "1"]) == 0
        out = capsys.readouterr().out
        assert out.count("dispatch/cached") == 1

    def test_json_output_is_parseable(self, trace_file, capsys):
        assert main(["dump", trace_file, "--json"]) == 0
        rows = json.loads(capsys.readouterr().out)
        assert [r["name"] for r in rows] == [
            "dispatch/compile", "dispatch/cached", "dispatch/cached", "sync/bucket_build",
        ]
        assert rows[0]["args"] == {"compile_s": 0.005}


class TestSummarize:
    def test_aggregates_sorted_by_total_time(self, trace_file, capsys):
        assert main(["summarize", trace_file, "--json"]) == 0
        summary = json.loads(capsys.readouterr().out)
        assert list(summary["events"])[0] == "dispatch/compile"  # 5000us dominates
        cached = summary["events"]["dispatch/cached"]
        assert cached["count"] == 2 and cached["total_us"] == 100.0

    def test_human_output_mentions_span(self, trace_file, capsys):
        assert main(["summarize", trace_file]) == 0
        assert "4 events over" in capsys.readouterr().out


class TestDiff:
    def test_diff_json(self, trace_file, tmp_path, capsys):
        t = obs.EventTracer()
        t.record("dispatch/cached", "engine", ph=_otrace.PH_COMPLETE, ts=0, dur=500)
        t.record("dispatch/fallback", "engine", args={"reason": "boom"})
        other = obs.write_chrome_trace(tmp_path / "b.json", t)
        assert main(["diff", trace_file, str(other), "--json"]) == 0
        diff = json.loads(capsys.readouterr().out)
        assert "dispatch/fallback" in diff["only_b"]
        assert "sync/bucket_build" in diff["only_a"]
        assert diff["events"]["dispatch/cached"]["total_us"]["delta"] == 400.0

    def test_diff_table(self, trace_file, capsys):
        assert main(["diff", trace_file, trace_file]) == 0
        out = capsys.readouterr().out
        assert "span:" in out and "dispatch/cached" in out


class TestValidate:
    def test_valid_file_passes(self, trace_file, capsys):
        assert main(["validate", trace_file]) == 0
        assert "valid (4 events)" in capsys.readouterr().out

    def test_invalid_file_fails_with_problems(self, tmp_path, capsys):
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"traceEvents": [{"ph": "X"}]}))
        assert main(["validate", str(bad)]) == 1
        assert "missing keys" in capsys.readouterr().err

    def test_unreadable_file_fails(self, tmp_path, capsys):
        garbled = tmp_path / "garbled.json"
        garbled.write_text("{nope")
        assert main(["validate", str(garbled)]) == 1
        assert "unreadable" in capsys.readouterr().err
