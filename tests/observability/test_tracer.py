"""The event tracer itself: ring bound, switch semantics, emit helpers."""
import threading

import pytest

from metrics_tpu import observability as obs
from metrics_tpu.observability import tracer as _otrace


class TestRingBuffer:
    def test_capacity_bounds_the_buffer_and_counts_drops(self):
        t = obs.EventTracer(capacity=4)
        for i in range(10):
            t.record(f"e{i}", "test")
        assert len(t) == 4
        assert t.dropped == 6
        assert [e.name for e in t.events()] == ["e6", "e7", "e8", "e9"]

    def test_clear_resets_buffer_and_drop_counter(self):
        t = obs.EventTracer(capacity=2)
        for i in range(5):
            t.record(f"e{i}", "test")
        t.clear()
        assert len(t) == 0 and t.dropped == 0

    def test_capacity_must_be_positive(self):
        with pytest.raises(ValueError):
            obs.EventTracer(capacity=0)

    def test_record_defaults(self):
        t = obs.EventTracer()
        e = t.record("x", "test")
        assert e.ph == _otrace.PH_INSTANT
        assert e.dur == 0
        assert e.ts > 0
        assert e.tid == threading.get_ident() & 0xFFFFFFFF
        assert e.args == {}

    def test_counts_by_name(self):
        t = obs.EventTracer()
        for name in ("a", "b", "a", "a"):
            t.record(name, "test")
        assert t.counts_by_name() == {"a": 3, "b": 1}


class TestSwitch:
    def test_off_by_default(self):
        assert not obs.enabled()
        assert not _otrace.active

    def test_enable_disable(self):
        tracer = obs.enable(capacity=128)
        try:
            assert obs.enabled() and _otrace.active
            assert obs.get_tracer() is tracer
            assert tracer.capacity == 128
        finally:
            obs.disable()
        assert not obs.enabled()
        # the buffer survives disable for post-hoc export
        assert obs.get_tracer() is tracer

    def test_reenable_same_capacity_keeps_buffer(self):
        tracer = obs.enable(capacity=64)
        try:
            tracer.record("before", "test")
            obs.disable()
            tracer2 = obs.enable(capacity=64)
            assert tracer2 is tracer
            assert tracer2.counts_by_name() == {"before": 1}
        finally:
            obs.disable()

    def test_trace_context_is_scoped_and_fresh(self):
        with obs.trace() as tracer:
            assert obs.enabled()
            assert len(tracer) == 0
            _otrace.emit_instant("inside", "test")
        assert not obs.enabled()
        assert tracer.counts_by_name() == {"inside": 1}

    def test_nested_trace_rides_the_outer_tracer(self):
        with obs.trace() as outer:
            with obs.trace() as inner:
                assert inner is outer
                _otrace.emit_instant("nested", "test")
            assert obs.enabled()  # inner exit must not kill the outer scope
            _otrace.emit_instant("after", "test")
        assert outer.counts_by_name() == {"nested": 1, "after": 1}


class TestEmitHelpers:
    def test_emit_instant_records_args(self):
        with obs.trace() as tracer:
            _otrace.emit_instant("marker", "engine", reason="x", step=3)
        (e,) = tracer.events()
        assert (e.name, e.cat, e.ph) == ("marker", "engine", _otrace.PH_INSTANT)
        assert e.args == {"reason": "x", "step": 3}

    def test_emit_complete_uses_explicit_timestamps(self):
        with obs.trace() as tracer:
            _otrace.emit_complete("spanned", "sync", 1000, 250, leaves=4)
        (e,) = tracer.events()
        assert (e.ph, e.ts, e.dur) == (_otrace.PH_COMPLETE, 1000, 250)

    def test_emit_complete_clamps_negative_duration(self):
        with obs.trace() as tracer:
            _otrace.emit_complete("clock-skew", "test", 1000, -5)
        assert tracer.events()[0].dur == 0

    def test_span_records_block_and_attaches_args(self):
        with obs.trace() as tracer:
            with _otrace.span("work", "checkpoint", step=1) as args:
                args["bytes"] = 42
        (e,) = tracer.events()
        assert e.ph == _otrace.PH_COMPLETE
        assert e.args == {"step": 1, "bytes": 42}
        assert e.dur >= 0

    def test_emit_helpers_safe_without_a_tracer(self, monkeypatch):
        """emit_* assume call sites gated on `active`; they must still be
        harmless (not crash) when no tracer exists at all."""
        monkeypatch.setattr(_otrace, "active", False)
        monkeypatch.setattr(_otrace, "_tracer", None)
        _otrace.emit_instant("ghost", "test")
        _otrace.emit_complete("ghost", "test", 0, 0)
        with _otrace.span("ghost", "test"):
            pass
        assert obs.get_tracer() is None

    def test_span_is_noop_while_disabled(self):
        tracer = obs.enable()
        obs.disable()
        tracer.clear()
        with _otrace.span("ghost", "test"):
            pass
        assert "ghost" not in tracer.counts_by_name()


class TestCatalog:
    def test_event_names_are_unique_across_categories(self):
        seen = set()
        for names in obs.EVENT_CATALOG.values():
            for name in names:
                assert name not in seen
                seen.add(name)

    def test_catalog_covers_the_lifecycle(self):
        flat = {n for names in obs.EVENT_CATALOG.values() for n in names}
        for required in (
            "dispatch/eager", "dispatch/compile", "dispatch/cached",
            "dispatch/fallback", "streak/detach", "streak/realias",
            "sync/bucket_build", "shard/place",
            "checkpoint/save/write", "checkpoint/restore/apply",
        ):
            assert required in flat
