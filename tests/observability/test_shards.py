"""Multi-host trace shards: anchors, merge, spool, and device correlation."""
import json

from metrics_tpu import observability as obs
from metrics_tpu.observability import shards as _shards
from metrics_tpu.observability import tracer as _otrace


def _shard(host_id, events, unix_us, monotonic_us, pid=1234):
    """Hand-built shard: events carry monotonic-domain timestamps; the anchor
    maps them onto the wall-clock axis (offset = unix_us - monotonic_us)."""
    return {
        "traceEvents": [
            {"name": "process_name", "ph": "M", "ts": 0, "pid": pid, "tid": 0,
             "args": {"name": f"host:{host_id}"}},
            *events,
        ],
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "metrics_tpu.observability",
            "dropped_events": 0,
            "shard": {
                "format": _shards.SHARD_FORMAT_VERSION,
                "host_id": host_id,
                "pid": pid,
                "epoch_anchor": {"unix_us": unix_us, "monotonic_us": monotonic_us},
            },
        },
    }


def _span(name, ts, dur=10, pid=1234, args=None):
    rec = {"name": name, "cat": "engine", "ph": "X", "ts": ts, "dur": dur,
           "pid": pid, "tid": 7}
    if args is not None:
        rec["args"] = args
    return rec


class TestAnnotationBridge:
    def test_round_trip(self):
        name = _shards.dispatch_annotation("Accuracy", "update")
        assert name == "metrics_tpu/Accuracy.update"
        assert _shards.parse_dispatch_annotation(name) == ("Accuracy", "update")

    def test_non_bridge_names_do_not_parse(self):
        for name in ("jit_update", "metrics_tpu/", "metrics_tpu/NoKind",
                     "other/Accuracy.update", "metrics_tpu/A.b.c extra"):
            assert _shards.parse_dispatch_annotation(name) is None

    def test_profiling_reexports_the_same_functions(self):
        from metrics_tpu.utils import profiling

        assert profiling.dispatch_annotation is _shards.dispatch_annotation
        assert profiling.parse_dispatch_annotation is _shards.parse_dispatch_annotation


class TestShardBuilding:
    def test_epoch_anchor_is_paired_microseconds(self):
        a = _shards.epoch_anchor()
        assert set(a) == {"unix_us", "monotonic_us"}
        assert a["unix_us"] > 10**15  # wall clock is past 2001 in us
        assert a["monotonic_us"] >= 0

    def test_build_trace_shard_annotates_the_doc(self):
        t = obs.EventTracer()
        t.record("dispatch/cached", "engine", ph=_otrace.PH_COMPLETE, ts=50, dur=5)
        doc = _shards.build_trace_shard(t, host_id="hostA")
        assert obs.validate_chrome_trace(doc) == []
        shard = doc["otherData"]["shard"]
        assert shard["host_id"] == "hostA"
        assert shard["format"] == _shards.SHARD_FORMAT_VERSION
        assert "epoch_anchor" in shard

    def test_write_is_atomic_and_overwrites_per_host(self, tmp_path):
        t = obs.EventTracer()
        t.record("a", "x")
        p1 = _shards.write_trace_shard(tmp_path, t, host_id="worker/0")
        p2 = _shards.write_trace_shard(tmp_path, t, host_id="worker/0")
        assert p1 == p2  # same host re-spools over its previous shard
        assert _shards.list_trace_shards(tmp_path) == [p1]
        assert not any(n.endswith(".tmp") for n in [p1])
        with open(p1) as fh:
            assert json.load(fh)["otherData"]["shard"]["host_id"] == "worker/0"


class TestMerge:
    def test_two_hosts_get_distinct_pids_and_aligned_clocks(self):
        # host A's monotonic zero is 500us before its events; host B's clock
        # started ~100ms earlier. On the wall axis B's span precedes A's.
        doc_a = _shard("A", [_span("dispatch/cached", ts=600)],
                       unix_us=1_000_000, monotonic_us=500)
        doc_b = _shard("B", [_span("dispatch/eager", ts=100_050)],
                       unix_us=1_000_000, monotonic_us=100_000)
        merged = _shards.merge_trace_shards([doc_a, doc_b])
        assert obs.validate_chrome_trace(merged) == []
        data = [r for r in merged["traceEvents"] if r["ph"] != "M"]
        assert {r["pid"] for r in data} == {1, 2}
        by_name = {r["name"]: r for r in data}
        # wall: A = 1_000_100, B = 1_000_050 -> rebased to t0 = B's wall time
        assert by_name["dispatch/eager"]["ts"] == 0
        assert by_name["dispatch/cached"]["ts"] == 50
        assert by_name["dispatch/eager"]["ts"] < by_name["dispatch/cached"]["ts"]
        assert merged["otherData"]["t0_unix_us"] == 1_000_050
        assert merged["otherData"]["merged_hosts"] == ["A", "B"]
        assert merged["otherData"]["unaligned"] == []

    def test_process_tracks_are_named_per_host(self):
        merged = _shards.merge_trace_shards([
            _shard("A", [_span("x", ts=1)], 10, 0),
            _shard("B", [_span("y", ts=1)], 10, 0),
        ])
        names = {r["pid"]: r["args"]["name"]
                 for r in merged["traceEvents"]
                 if r["ph"] == "M" and r["name"] == "process_name"}
        assert names == {1: "host:A", 2: "host:B"}

    def test_anchorless_shard_merges_unshifted_and_is_flagged(self):
        plain = {"traceEvents": [_span("z", ts=7)], "otherData": {"dropped_events": 0}}
        anchored = _shard("A", [_span("x", ts=3)], unix_us=5, monotonic_us=3)
        merged = _shards.merge_trace_shards([anchored, plain])
        assert merged["otherData"]["unaligned"] == ["shard1"]
        assert obs.validate_chrome_trace(merged) == []

    def test_dropped_events_accumulate(self):
        a = _shard("A", [_span("x", ts=1)], 10, 0)
        a["otherData"]["dropped_events"] = 3
        b = _shard("B", [_span("y", ts=1)], 10, 0)
        b["otherData"]["dropped_events"] = 4
        merged = _shards.merge_trace_shards([a, b])
        assert merged["otherData"]["dropped_events"] == 7

    def test_merge_spool_dir_round_trip(self, tmp_path):
        for host in ("hostA", "hostB"):
            t = obs.EventTracer()
            t.record("dispatch/cached", "engine", ph=_otrace.PH_COMPLETE, ts=10, dur=2)
            _shards.write_trace_shard(tmp_path, t, host_id=host)
        merged = _shards.merge_spool_dir(tmp_path)
        assert obs.validate_chrome_trace(merged) == []
        assert merged["otherData"]["merged_hosts"] == ["hostA", "hostB"]
        pids = {r["pid"] for r in merged["traceEvents"] if r["ph"] != "M"}
        assert pids == {1, 2}


class TestCorrelation:
    def _host_doc(self):
        return {
            "traceEvents": [
                {"name": "process_name", "ph": "M", "ts": 0, "pid": 1, "tid": 0,
                 "args": {"name": "host:A"}},
                _span("dispatch/cached", ts=100, pid=1,
                      args={"owner": "Accuracy", "kind": "update"}),
                _span("dispatch/cached", ts=300, pid=1,
                      args={"owner": "Accuracy", "kind": "update"}),
                _span("sync/bucket_build", ts=200, pid=1),
            ],
            "displayTimeUnit": "ms",
            "otherData": {"dropped_events": 0},
        }

    def _device_doc(self):
        ann = _shards.dispatch_annotation("Accuracy", "update")
        return {
            "traceEvents": [
                _span(ann, ts=5000, dur=8, pid=99),
                _span(ann, ts=5200, dur=8, pid=99),
                _span("fusion.123", ts=5100, dur=2, pid=99),
            ],
        }

    def test_kth_dispatch_matches_kth_annotation(self):
        combined = _shards.correlate_device_trace(self._host_doc(), self._device_doc())
        assert obs.validate_chrome_trace(combined) == []
        corr = combined["otherData"]["correlation"]
        assert corr["matched"] == 2
        assert corr["host_dispatches"] == 2
        assert corr["device_annotations"] == 2
        assert corr["device_events"] == 3
        # offset estimated from the first matched pair: 100 - 5000
        assert corr["offset_us"] == -4900.0
        data = [r for r in combined["traceEvents"] if r["ph"] != "M"]
        dev = [r for r in data if r["pid"] == 2]
        assert {r["ts"] for r in dev} == {100.0, 300.0, 200.0}
        host_matched = [r for r in data if r.get("args", {}).get("annotation")]
        assert len(host_matched) == 2
        assert all(r["args"]["annotation"].startswith("metrics_tpu/") for r in host_matched)

    def test_explicit_offset_wins(self):
        combined = _shards.correlate_device_trace(
            self._host_doc(), self._device_doc(), offset_us=-5000.0)
        dev_ts = sorted(r["ts"] for r in combined["traceEvents"]
                        if r.get("pid") == 2 and r["ph"] != "M")
        assert dev_ts == [0.0, 100.0, 200.0]

    def test_device_track_is_named(self):
        combined = _shards.correlate_device_trace(
            self._host_doc(), self._device_doc(), device_name="device:tpu0")
        meta = [r for r in combined["traceEvents"]
                if r["ph"] == "M" and r["name"] == "process_name" and r["pid"] == 2]
        assert meta and meta[0]["args"]["name"] == "device:tpu0"

    def test_merge_then_correlate_is_still_valid(self):
        shard = _shard("A", [
            _span("dispatch/cached", ts=100,
                  args={"owner": "F1Score", "kind": "compute"}),
        ], unix_us=1_000, monotonic_us=0)
        merged = _shards.merge_trace_shards([shard])
        device = {"traceEvents": [
            _span(_shards.dispatch_annotation("F1Score", "compute"), ts=1, pid=42),
        ]}
        combined = _shards.correlate_device_trace(merged, device)
        assert obs.validate_chrome_trace(combined) == []
        assert combined["otherData"]["correlation"]["matched"] == 1
