"""Scrape-server lifecycle: endpoints, idempotent serve/shutdown, the
port-in-use spool fallback, and concurrent scrapes against a live fused
update streak (no deadlock, no tracer mutation)."""
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from metrics_tpu import Accuracy, MetricCollection, observability as obs
from metrics_tpu.observability import server as _oserver
from metrics_tpu.observability import shards as _shards
from metrics_tpu.observability import tracer as _otrace

pytestmark = pytest.mark.network

NUM_CLASSES = 8


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.headers.get("Content-Type", ""), resp.read()


class TestLifecycle:
    def test_serve_binds_scrapes_and_shuts_down(self):
        server = obs.serve(port=0)
        assert server.kind == "http"
        assert server.running
        assert obs.get_server() is server
        # idempotent: a second call returns the live handle
        assert obs.serve(port=0) is server

        status, ctype, body = _get(server.url + "/healthz")
        assert status == 200
        health = json.loads(body)
        assert health["status"] == "ok"
        assert health["tracing"] is False
        assert health["host_id"] == server.host_id

        thread = server._thread
        obs.shutdown()
        assert obs.get_server() is None
        assert not thread.is_alive()  # joined, not abandoned
        # idempotent shutdown
        obs.shutdown()

    def test_unknown_path_is_404_and_server_survives(self):
        server = obs.serve(port=0)
        with pytest.raises(urllib.error.HTTPError) as err:
            _get(server.url + "/nope")
        assert err.value.code == 404
        status, _, _ = _get(server.url + "/healthz")
        assert status == 200

    def test_port_in_use_without_spool_raises(self):
        blocker = socket.socket()
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        taken = blocker.getsockname()[1]
        try:
            with pytest.raises(OSError):
                _oserver.ObservabilityServer(port=taken).start()
        finally:
            blocker.close()

    def test_port_in_use_falls_back_to_spool(self, tmp_path):
        blocker = socket.socket()
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        taken = blocker.getsockname()[1]
        try:
            handle = obs.serve(port=taken, spool_dir=tmp_path, host_id="w0")
            assert handle.kind == "spool"
            assert not handle.running
            assert "bind" in handle.reason
            path = handle.flush()
            merged = _shards.merge_spool_dir(tmp_path)
            assert obs.validate_chrome_trace(merged) == []
            assert merged["otherData"]["merged_hosts"] == ["w0"]
            assert path.endswith(_shards.SHARD_SUFFIX)
        finally:
            blocker.close()


class TestEndpoints:
    def test_metrics_endpoint_is_prometheus_text(self):
        obs.enable()
        acc = Accuracy(num_classes=NUM_CLASSES)
        logits = np.random.randn(16, NUM_CLASSES).astype(np.float32)
        target = np.random.randint(0, NUM_CLASSES, size=(16,))
        acc.update(logits, target)
        server = obs.serve(port=0)
        status, ctype, body = _get(server.url + "/metrics")
        assert status == 200
        assert ctype == _oserver.PROMETHEUS_CONTENT_TYPE
        text = body.decode()
        assert "metrics_tpu_tracer_dropped_events_total" in text
        assert "metrics_tpu_tracer_ring_utilization" in text
        # the server observes itself: a later scrape reports the earlier ones
        # (the latency lands in the registry after the response is flushed, so
        # poll briefly instead of racing the handler thread)
        wanted = 'metrics_tpu_obs_scrapes_total{endpoint="/metrics"}'
        deadline = time.monotonic() + 5.0
        while True:
            _, _, body = _get(server.url + "/metrics")
            if wanted in body.decode():
                break
            assert time.monotonic() < deadline, "self-observation never appeared"
            time.sleep(0.05)

    def test_trace_endpoint_is_a_mergeable_shard(self):
        obs.enable()
        obs.get_tracer().record("dispatch/cached", "engine")
        server = obs.serve(port=0, host_id="scraped-host")
        _, ctype, body = _get(server.url + "/trace")
        assert ctype == "application/json"
        doc = json.loads(body)
        assert obs.validate_chrome_trace(doc) == []
        assert doc["otherData"]["shard"]["host_id"] == "scraped-host"
        merged = _shards.merge_trace_shards([doc])
        assert obs.validate_chrome_trace(merged) == []

    def test_stats_json_matches_registry_snapshot_shape(self):
        server = obs.serve(port=0)
        _, _, body = _get(server.url + "/stats.json")
        snap = json.loads(body)
        assert isinstance(snap, dict)
        for name, series in snap.items():
            assert name.startswith("metrics_tpu_")
            assert all({"labels", "value", "kind"} <= set(s) for s in series)


class TestConcurrentScrape:
    def test_scrapes_during_fused_update_streak(self):
        """Scrapes landing mid-streak must neither deadlock nor mutate the
        tracer; the hot loop and every scrape complete."""
        obs.enable()
        coll = MetricCollection({"acc": Accuracy(num_classes=NUM_CLASSES)})
        logits = np.random.randn(32, NUM_CLASSES).astype(np.float32)
        target = np.random.randint(0, NUM_CLASSES, size=(32,))
        server = obs.serve(port=0)

        errors = []
        stop = threading.Event()

        def scraper(endpoint):
            while not stop.is_set():
                try:
                    status, _, _ = _get(server.url + endpoint, timeout=5)
                    assert status == 200
                except Exception as err:  # noqa: BLE001 — collected for the assert
                    errors.append(err)
                    return

        threads = [threading.Thread(target=scraper, args=(ep,), daemon=True)
                   for ep in ("/metrics", "/trace", "/healthz")]
        for t in threads:
            t.start()
        try:
            for _ in range(20):
                coll.update(logits, target)
            result = coll.compute()
        finally:
            stop.set()
            for t in threads:
                t.join(10)
        assert not errors, errors
        assert not any(t.is_alive() for t in threads)
        assert float(result["acc"]) >= 0.0
        # the streak's events survived the concurrent snapshots
        names = {e.name for e in obs.get_tracer().events()}
        assert any(n.startswith("dispatch/") for n in names)
