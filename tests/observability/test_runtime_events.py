"""Integration: the runtime actually emits the catalogued events, with
accurate payloads — including the ISSUE-7 acceptance loop (traced config2
eval: updates + compute + checkpoint save exporting a valid Chrome trace with
dispatch, sync-bucket, and checkpoint-phase spans)."""
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu
from metrics_tpu import (
    Accuracy,
    F1Score,
    MetricCollection,
    Precision,
    Recall,
    observability as obs,
)
from metrics_tpu.checkpoint import restore_checkpoint, save_checkpoint
from metrics_tpu.core.metric import Metric
from metrics_tpu.observability import instruments as _instruments
from metrics_tpu.observability import tracer as _otrace
from metrics_tpu.parallel import count_collectives, make_mesh

NUM_CLASSES = 32


def _collection():
    return MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )


def _batch(seed=0, batch=64):
    rng = np.random.default_rng(seed)
    logits = jnp.asarray(rng.normal(size=(batch, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(batch,)), dtype=jnp.int32)
    return logits, target


class TestEngineDispatchEvents:
    def test_warmup_compile_cached_sequence(self):
        logits, target = _batch()
        with obs.trace() as tracer:
            m = Accuracy(num_classes=NUM_CLASSES)
            for _ in range(4):
                m.update(logits, target)
        counts = tracer.counts_by_name()
        assert counts["dispatch/eager"] == 1  # one warmup sighting
        assert counts["dispatch/compile"] == 1  # one cache-miss compile
        assert counts["dispatch/cached"] == 2  # steady state
        compile_ev = next(e for e in tracer.events() if e.name == "dispatch/compile")
        assert compile_ev.args["compile_s"] > 0
        assert compile_ev.dur > 0
        cached = [e for e in tracer.events() if e.name == "dispatch/cached"]
        assert all("donated" in e.args for e in cached)

    def test_compile_seconds_accumulates_in_stats(self):
        logits, target = _batch()
        m = Accuracy(num_classes=NUM_CLASSES)
        for _ in range(3):
            m.update(logits, target)
        stats = m.engine_stats()["update"]
        assert stats.cache_misses >= 1
        assert stats.compile_seconds > 0
        assert stats.last_fallback_step is None

    def test_fallback_emits_event_and_records_step(self):
        class HostUpdate(Metric):
            full_state_update = False

            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

            def update(self, x):
                if float(jnp.sum(x)) > -1e30:  # host readback: untraceable
                    self.total = self.total + jnp.sum(x)

            def compute(self):
                return self.total

        with obs.trace() as tracer:
            m = HostUpdate()
            x = jnp.asarray([1.0, 2.0])
            m.update(x)
            with pytest.warns(UserWarning, match="compiled-update engine disabled"):
                m.update(x)
        (fallback,) = [e for e in tracer.events() if e.name == "dispatch/fallback"]
        assert "reason" in fallback.args and fallback.args["step"] == 2
        assert m._update_engine.stats.last_fallback_step == 2

    def test_no_events_recorded_while_disabled(self):
        logits, target = _batch()
        before = obs.get_tracer()
        n_before = len(before) if before is not None else 0
        m = Accuracy(num_classes=NUM_CLASSES)
        for _ in range(3):
            m.update(logits, target)
        after = obs.get_tracer()
        assert (len(after) if after is not None else 0) == n_before


class TestStreakEvents:
    def test_fused_streak_detach_and_realias(self):
        logits, target = _batch()
        with obs.trace() as tracer:
            coll = _collection()
            for _ in range(3):
                coll.update(logits, target)
            coll.compute()  # observation point realiases the members
        counts = tracer.counts_by_name()
        assert counts.get("streak/detach", 0) >= 1
        assert counts.get("streak/realias", 0) >= 1
        detach = next(e for e in tracer.events() if e.name == "streak/detach")
        # config2: acc leads its own group; f1/precision/recall share one
        # stat-scores compute group -> 2 non-leader members detach
        assert detach.args["members"] == 2


class TestSyncBucketEvents:
    def test_bucket_build_tallies_match_count_collectives(self):
        logits, target = _batch()
        m = F1Score(num_classes=NUM_CLASSES, average="macro")
        m.update(logits, target)
        state = m.get_state()
        with obs.trace() as tracer:
            with count_collectives() as box:
                jax.make_jaxpr(
                    lambda s: m.sync_states(s, "data"), axis_env=[("data", 8)]
                )(state)
        events = [e for e in tracer.events() if e.name == "sync/bucket_build"]
        assert events, "bucketed sync emitted no bucket_build event"
        got_counts: dict = {}
        got_bytes: dict = {}
        for e in events:
            for k, v in e.args["collectives"].items():
                got_counts[k] = got_counts.get(k, 0) + v
            for k, v in e.args["collective_bytes"].items():
                got_bytes[k] = got_bytes.get(k, 0) + v
        assert got_counts == dict(box["by_kind"])
        assert got_bytes == dict(box["bytes_by_kind"])
        assert events[0].args["axis"] == "data"

    def test_user_collective_tallies_unchanged_by_tracing(self):
        """The tracer's own count_collectives box must not steal ticks from
        a box the caller already holds."""
        logits, target = _batch()
        m = F1Score(num_classes=NUM_CLASSES, average="macro")
        m.update(logits, target)
        state = m.get_state()

        def _measure():
            with count_collectives() as box:
                jax.make_jaxpr(
                    lambda s: m.sync_states(s, "data"), axis_env=[("data", 8)]
                )(state)
            return dict(box["by_kind"]), dict(box["bytes_by_kind"])

        plain = _measure()
        with obs.trace():
            traced = _measure()
        assert traced == plain


class TestShardAndMeshEvents:
    @pytest.mark.mesh8
    def test_shard_place_and_unshard(self):
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device conftest mesh")
        logits, target = _batch()
        with obs.trace() as tracer:
            mesh = make_mesh([8], ["data"])
            m = F1Score(num_classes=NUM_CLASSES, average="macro")
            m.update(logits, target)
            m.shard_state(mesh)
            m.unshard_state()
        counts = tracer.counts_by_name()
        assert counts.get("mesh/build") == 1
        assert counts.get("shard/place") == 1
        assert counts.get("shard/unshard") == 1
        place = next(e for e in tracer.events() if e.name == "shard/place")
        assert place.args["owner"] == "F1Score"
        assert place.args["leaves"] >= 1


class TestCheckpointEvents:
    def test_save_and_restore_phases(self, tmp_path):
        logits, target = _batch()
        coll = _collection()
        for _ in range(2):
            coll.update(logits, target)
        with obs.trace() as tracer:
            handle = save_checkpoint(coll, str(tmp_path / "ckpt"))
            fresh = _collection()
            info = restore_checkpoint(fresh, str(tmp_path / "ckpt"))
        counts = tracer.counts_by_name()
        for name in (
            "checkpoint/save/snapshot", "checkpoint/save/host_copy",
            "checkpoint/save/write", "checkpoint/save/commit",
            "checkpoint/restore/verify", "checkpoint/restore/apply",
        ):
            assert counts.get(name) == 1, name
        # phase timings recorded regardless of tracing
        assert set(handle.timings) == {
            "snapshot_s", "host_copy_s", "write_s", "commit_s", "total_s",
        }
        assert handle.timings["total_s"] > 0
        assert set(info.timings) == {"verify_s", "apply_s", "total_s"}
        assert info.timings["verify_s"] > 0

    def test_async_save_write_happens_on_its_own_thread(self, tmp_path):
        logits, target = _batch()
        coll = _collection()
        coll.update(logits, target)
        with obs.trace() as tracer:
            handle = save_checkpoint(coll, str(tmp_path / "ckpt"), blocking=False)
            handle.wait()
        events = {e.name: e for e in tracer.events()}
        assert events["checkpoint/save/write"].tid != events["checkpoint/save/snapshot"].tid
        assert handle.timings["write_s"] > 0

    def test_timings_recorded_with_tracing_off(self, tmp_path):
        coll = _collection()
        coll.update(*_batch())
        handle = save_checkpoint(coll, str(tmp_path / "ckpt"))
        assert handle.timings["snapshot_s"] >= 0
        assert "write_s" in handle.timings

    def test_phase_histograms_populated(self, tmp_path):
        coll = _collection()
        coll.update(*_batch())
        hist = _instruments.REGISTRY.histogram(
            "checkpoint_phase_seconds",
            help="wall seconds per checkpoint phase", op="save", phase="write",
        )
        before = hist.count
        save_checkpoint(coll, str(tmp_path / "ckpt"))
        assert hist.count == before + 1


class TestEngineStatsView:
    def test_metric_engine_stats_shape_is_backward_compatible(self):
        m = Accuracy(num_classes=NUM_CLASSES)
        stats = m.engine_stats()
        assert set(stats) == {"update", "compute", "fallback_reasons", "partition"}
        assert stats["update"] is None and stats["fallback_reasons"] == {}
        m.update(*_batch())
        stats = m.engine_stats()
        assert stats["update"] is m._update_engine.stats

    def test_collection_member_fallback_reasons_are_name_prefixed(self):
        """Two members of the same class must not collide in the merged
        fallback_reasons dict (the pre-observability bug)."""
        coll = MetricCollection(
            {
                "a": F1Score(num_classes=NUM_CLASSES, average="macro"),
                "b": F1Score(num_classes=NUM_CLASSES, average="macro"),
            }
        )
        coll.update(*_batch())
        for name in ("a", "b"):
            member = coll._metrics.__getitem__(name)
            engine = member._update_engine
            if engine is None:
                member.update(*_batch())
                engine = member._update_engine
            engine.stats.fallback_reasons["F1Score"] = f"boom-{name}"
        merged = coll.engine_stats()["fallback_reasons"]
        assert merged["a.update:F1Score"] == "boom-a"
        assert merged["b.update:F1Score"] == "boom-b"
        assert "members" in coll.engine_stats()

    def test_registry_exports_engine_counters(self):
        m = Precision(num_classes=NUM_CLASSES, average="macro")
        for _ in range(3):
            m.update(*_batch())
        samples = [
            s for s in _instruments.REGISTRY.samples()
            if s.labels.get("owner") == "Precision" and s.labels.get("kind") == "update"
        ]
        by_name = {s.name: s.value for s in samples}
        assert by_name["metrics_tpu_engine_eager_calls"] >= 1
        assert by_name["metrics_tpu_engine_compiled_calls"] >= 1
        assert by_name["metrics_tpu_engine_compile_seconds"] > 0
        text = obs.to_prometheus_text()
        assert 'metrics_tpu_engine_cache_hits{kind="update",owner="Precision"}' in text

    def test_dead_engines_drop_out_of_snapshots(self):
        import gc

        m = Recall(num_classes=NUM_CLASSES, average="macro")
        m.update(*_batch())
        live_before = len(_instruments.REGISTRY.live_engines())
        del m
        gc.collect()
        assert len(_instruments.REGISTRY.live_engines()) < live_before


class TestAcceptanceLoop:
    def test_traced_config2_eval_loop_exports_complete_chrome_trace(self, tmp_path):
        """The ISSUE-7 acceptance criterion, end to end: a tracer-enabled
        config2-style eval loop (updates + compute + checkpoint save) exports
        Chrome trace JSON containing dispatch spans, sync-bucket spans whose
        per-kind collective bytes match an independent count_collectives
        tally, and checkpoint-phase spans — and the file validates."""
        logits, target = _batch()
        with obs.trace() as tracer:
            coll = _collection()
            for _ in range(4):
                coll.update(logits, target)
            jax.block_until_ready(coll.compute())
            # mock-mesh distributed finalize: traces the bucketed sync
            with count_collectives() as box:
                for member in coll.values():
                    state = member.get_state()
                    jax.make_jaxpr(
                        lambda s, m=member: m.sync_states(s, "data"),
                        axis_env=[("data", 8)],
                    )(state)
            save_checkpoint(coll, str(tmp_path / "ckpt"))
            path = obs.write_chrome_trace(tmp_path / "trace.json", tracer)

        doc = obs.load_trace(path)
        assert obs.validate_chrome_trace(doc) == []
        names = {r["name"] for r in doc["traceEvents"] if r["ph"] != "M"}
        assert {"dispatch/eager", "dispatch/compile", "dispatch/cached"} <= names
        assert "sync/bucket_build" in names
        assert {
            "checkpoint/save/snapshot", "checkpoint/save/host_copy",
            "checkpoint/save/write", "checkpoint/save/commit",
        } <= names

        # sync-bucket collective bytes must match the independent tally
        got_bytes: dict = {}
        for rec in doc["traceEvents"]:
            if rec.get("name") == "sync/bucket_build":
                for k, v in rec["args"]["collective_bytes"].items():
                    got_bytes[k] = got_bytes.get(k, 0) + v
        assert got_bytes == dict(box["bytes_by_kind"])
        assert sum(got_bytes.values()) > 0

        # and the CLI can read its own output
        summary = obs.summarize_trace(doc)
        assert summary["total_events"] == len(tracer)
        assert summary["dropped"] == 0
