"""Observability suite hygiene: tracing and the scrape server are
process-global state, so every test leaves them the way it found them
(tracing off with no leftover buffer, no server thread still bound)."""
import pytest

from metrics_tpu.observability import server as _oserver
from metrics_tpu.observability import tracer as _otrace


@pytest.fixture(autouse=True)
def _tracer_off_after_each_test():
    yield
    _otrace.disable()
    tracer = _otrace.get_tracer()
    if tracer is not None:
        tracer.clear()
    _oserver.shutdown()
