"""Observability suite hygiene: tracing is process-global state, so every
test leaves it the way it found it (off, with no leftover buffer)."""
import pytest

from metrics_tpu.observability import tracer as _otrace


@pytest.fixture(autouse=True)
def _tracer_off_after_each_test():
    yield
    _otrace.disable()
    tracer = _otrace.get_tracer()
    if tracer is not None:
        tracer.clear()
