"""Exporters: Chrome trace schema round-trip, Prometheus text, analytics."""
import json

import numpy as np
import pytest

from metrics_tpu import observability as obs
from metrics_tpu.observability import tracer as _otrace
from metrics_tpu.observability.instruments import InstrumentRegistry


def _sample_tracer():
    t = obs.EventTracer()
    t.record("dispatch/cached", "engine", ph=_otrace.PH_COMPLETE, ts=100, dur=50,
             args={"donated": True})
    t.record("dispatch/eager", "engine", args={"owner": "F1Score"})
    t.record("sync/bucket_build", "sync", ph=_otrace.PH_COMPLETE, ts=200, dur=30,
             args={"collective_bytes": {"psum": np.int64(16)}})
    return t


class TestChromeTrace:
    def test_export_is_valid_perfetto_input(self):
        doc = obs.to_chrome_trace(_sample_tracer())
        assert obs.validate_chrome_trace(doc) == []

    def test_object_format_shape(self):
        doc = obs.to_chrome_trace(_sample_tracer(), process_name="myproc")
        assert doc["displayTimeUnit"] == "ms"
        assert doc["otherData"]["producer"] == "metrics_tpu.observability"
        meta = [r for r in doc["traceEvents"] if r["ph"] == "M"]
        assert any(r["name"] == "process_name" and r["args"]["name"] == "myproc"
                   for r in meta)
        assert any(r["name"] == "thread_name" for r in meta)

    def test_phase_specific_fields(self):
        doc = obs.to_chrome_trace(_sample_tracer())
        data = [r for r in doc["traceEvents"] if r["ph"] != "M"]
        complete = next(r for r in data if r["name"] == "dispatch/cached")
        assert complete["ph"] == "X" and complete["dur"] == 50
        instant = next(r for r in data if r["name"] == "dispatch/eager")
        assert instant["ph"] == "i" and instant["s"] == "t"

    def test_args_are_json_safe(self):
        doc = obs.to_chrome_trace(_sample_tracer())
        text = json.dumps(doc)  # numpy scalars must not leak into the doc
        rec = next(r for r in doc["traceEvents"] if r["name"] == "sync/bucket_build")
        assert rec["args"]["collective_bytes"]["psum"] == 16
        assert isinstance(rec["args"]["collective_bytes"]["psum"], int)
        assert "sync/bucket_build" in text

    def test_write_and_load_round_trip(self, tmp_path):
        tracer = _sample_tracer()
        path = obs.write_chrome_trace(tmp_path / "t.json", tracer)
        doc = obs.load_trace(path)
        assert obs.validate_chrome_trace(doc) == []
        assert doc == obs.to_chrome_trace(tracer)

    def test_dropped_events_recorded(self):
        t = obs.EventTracer(capacity=1)
        t.record("a", "x")
        t.record("b", "x")
        assert obs.to_chrome_trace(t)["otherData"]["dropped_events"] == 1

    @pytest.mark.parametrize(
        "doc, fragment",
        [
            ([], "traceEvents"),
            ({"traceEvents": {}}, "array"),
            ({"traceEvents": ["nope"]}, "not an object"),
            ({"traceEvents": [{"ph": "X"}]}, "missing keys"),
            ({"traceEvents": [{"name": "a", "ph": "Z", "ts": 0, "pid": 1, "tid": 1}]}, "unknown phase"),
            ({"traceEvents": [{"name": "a", "ph": "X", "ts": 0, "pid": 1, "tid": 1, "dur": -1}]}, "dur"),
            ({"traceEvents": [{"name": "a", "ph": "i", "ts": 0, "pid": 1, "tid": 1, "s": "q"}]}, "scope"),
            ({"traceEvents": [{"name": "a", "ph": "i", "ts": 0, "pid": 1, "tid": 1, "args": 3}]}, "args"),
        ],
    )
    def test_validate_rejects_malformed_documents(self, doc, fragment):
        problems = obs.validate_chrome_trace(doc)
        assert problems and fragment in problems[0]


class TestPrometheus:
    def test_counter_gauge_rendering(self):
        reg = InstrumentRegistry()
        reg.counter("requests_total", help="reqs", route="/a").inc(3)
        reg.gauge("queue_depth").set(7)
        text = obs.to_prometheus_text(reg)
        assert "# TYPE metrics_tpu_requests_total counter" in text
        assert "# HELP metrics_tpu_requests_total reqs" in text
        assert 'metrics_tpu_requests_total{route="/a"} 3' in text
        assert "# TYPE metrics_tpu_queue_depth gauge" in text
        assert "metrics_tpu_queue_depth 7" in text

    def test_histogram_cumulative_buckets(self):
        reg = InstrumentRegistry()
        h = reg.histogram("dur_seconds", buckets=(0.1, 1.0), op="save")
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = obs.to_prometheus_text(reg)
        assert "# TYPE metrics_tpu_dur_seconds histogram" in text
        assert 'metrics_tpu_dur_seconds_bucket{le="0.1",op="save"} 1' in text
        assert 'metrics_tpu_dur_seconds_bucket{le="1.0",op="save"} 2' in text
        assert 'metrics_tpu_dur_seconds_bucket{le="+Inf",op="save"} 3' in text
        assert 'metrics_tpu_dur_seconds_count{op="save"} 3' in text
        assert 'metrics_tpu_dur_seconds_sum{op="save"} 5.55' in text

    def test_label_escaping(self):
        reg = InstrumentRegistry()
        reg.counter("odd_total", tag='he said "hi"\nback\\slash').inc()
        text = obs.to_prometheus_text(reg)
        assert r'tag="he said \"hi\"\nback\\slash"' in text

    def test_get_or_create_returns_same_instrument(self):
        reg = InstrumentRegistry()
        a = reg.counter("c_total", op="x")
        b = reg.counter("c_total", op="x")
        assert a is b
        assert reg.counter("c_total", op="y") is not a

    def test_kind_conflict_raises(self):
        reg = InstrumentRegistry()
        reg.counter("thing")
        with pytest.raises(TypeError):
            reg.gauge("thing")

    def test_counters_refuse_negative_increments(self):
        reg = InstrumentRegistry()
        with pytest.raises(ValueError):
            reg.counter("c_total").inc(-1)

    def test_json_snapshot_groups_by_name(self):
        reg = InstrumentRegistry()
        reg.counter("c_total", op="x").inc(2)
        snap = obs.to_metrics_json(reg)
        assert snap["metrics_tpu_c_total"] == [
            {"labels": {"op": "x"}, "value": 2.0, "kind": "counter"}
        ]


import re


class _StrictPromParser:
    """An unforgiving reader of the text exposition format: every line must be
    a HELP/TYPE header or a sample; families must be contiguous; label values
    are unescaped back to their originals."""

    NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
    SAMPLE_RE = re.compile(
        rf"^(?P<name>{NAME})(?:\{{(?P<labels>.*)\}})? (?P<value>[^ ]+)$")
    LABEL_RE = re.compile(
        rf'(?P<key>{NAME})="(?P<value>(?:[^"\\]|\\.)*)"(?:,|$)')
    HEADER_RE = re.compile(rf"^# (?P<kw>HELP|TYPE) (?P<name>{NAME}) (?P<rest>.*)$")
    KINDS = {"counter", "gauge", "histogram", "summary", "untyped"}

    @staticmethod
    def _unescape_label(value):
        return value.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")

    @staticmethod
    def _parse_value(text):
        return {"+Inf": float("inf"), "-Inf": float("-inf"), "NaN": float("nan")}.get(
            text, None) if text in ("+Inf", "-Inf", "NaN") else float(text)

    def parse(self, text):
        assert text.endswith("\n"), "exposition must end with a newline"
        families, samples = {}, []
        current, closed = None, set()
        for lineno, line in enumerate(text.splitlines(), start=1):
            assert line.strip(), f"line {lineno}: blank line"
            header = self.HEADER_RE.match(line)
            if header:
                fam = header.group("name")
                assert fam not in closed, f"line {lineno}: family {fam} reopened"
                if header.group("kw") == "HELP":
                    if current is not None:
                        closed.add(current)
                    current = fam
                    assert header.group("rest"), f"line {lineno}: empty HELP"
                    families[fam] = {"help": header.group("rest"), "type": None}
                else:
                    assert fam == current, f"line {lineno}: TYPE without its HELP"
                    assert header.group("rest") in self.KINDS, line
                    families[fam]["type"] = header.group("rest")
                continue
            m = self.SAMPLE_RE.match(line)
            assert m, f"line {lineno}: unparseable sample {line!r}"
            name = m.group("name")
            fam = current
            assert fam is not None and (
                name == fam or (families[fam]["type"] == "histogram"
                                and name in (f"{fam}_bucket", f"{fam}_sum", f"{fam}_count"))
            ), f"line {lineno}: sample {name} outside its family block ({fam})"
            labels = {}
            raw = m.group("labels")
            if raw:
                consumed = 0
                for lm in self.LABEL_RE.finditer(raw):
                    labels[lm.group("key")] = self._unescape_label(lm.group("value"))
                    consumed = lm.end()
                assert consumed == len(raw), f"line {lineno}: trailing label junk"
            samples.append((name, labels, self._parse_value(m.group("value"))))
        return families, samples


class TestPrometheusRoundTrip:
    def test_live_registry_exposition_parses_strictly(self):
        # the real process registry with an engine attached: engine counters,
        # process gauges, and the tracer's dropped-events counter all present
        obs.enable()
        acc = metrics_for_roundtrip()
        logits = np.random.randn(8, 4).astype(np.float32)
        target = np.random.randint(0, 4, size=(8,))
        acc.update(logits, target)
        from metrics_tpu.observability.instruments import get_registry

        text = obs.to_prometheus_text(get_registry())
        families, samples = _StrictPromParser().parse(text)
        names = {s[0] for s in samples}
        assert "metrics_tpu_tracer_dropped_events_total" in names
        assert "metrics_tpu_tracer_ring_utilization" in names
        assert any(n.startswith("metrics_tpu_engine_") for n in names)
        assert families["metrics_tpu_tracer_dropped_events_total"]["type"] == "counter"
        # every family got a non-default-free HELP and a TYPE
        assert all(f["help"] and f["type"] for f in families.values())

    def test_ingest_pipeline_series_parse_strictly(self):
        """The serving bridge: a registered IngestPipeline exports
        metrics_tpu_ingest_* gauges/counters through the same strict
        Prometheus exposition as every other family."""
        from metrics_tpu import MetricCollection, MeanSquaredError
        from metrics_tpu.serve import IngestPipeline

        reg = InstrumentRegistry()
        pipeline = IngestPipeline(
            MetricCollection({"mse": MeanSquaredError()}),
            queue_capacity=4, name="export-test",
        )
        reg.register_ingest_pipeline(pipeline)
        pipeline.post("t0", np.ones((4,), np.float32), np.zeros((4,), np.float32))
        text = obs.to_prometheus_text(reg)
        families, samples = _StrictPromParser().parse(text)
        by_name = {s[0]: s for s in samples}
        name, labels, value = by_name["metrics_tpu_ingest_queue_depth"]
        assert labels == {"queue": "export-test"} and value == 1.0
        assert by_name["metrics_tpu_ingest_queue_capacity"][2] == 4.0
        assert "metrics_tpu_ingest_dispatch_dead_letters_total" in by_name
        assert families["metrics_tpu_ingest_queue_depth"]["type"] == "gauge"
        assert families["metrics_tpu_ingest_dispatch_retries_total"]["type"] == "counter"

    def test_sync_transport_series_parse_strictly(self):
        """The transport layer's wire accounting: tracing a quantized sync
        ticks metrics_tpu_sync_wire_bytes_total / _logical_bytes_total (and,
        on a refused bucket, _transport_refusals_total) in the process
        registry — all three parse through the strict exposition."""
        import jax
        import jax.numpy as jnp
        from metrics_tpu.observability.instruments import get_registry
        from metrics_tpu.parallel.sync import sync_state

        get_registry().clear()
        try:
            state = {"c": jnp.zeros((256,), jnp.int32), "f": jnp.zeros((64,), jnp.float32)}
            reds = {"c": "sum", "f": "sum"}
            jax.make_jaxpr(
                lambda st: sync_state(
                    st, reds, "data",
                    transports={"c": "bf16", "f": "bf16"},
                    tolerances={"f": 1e-6},  # refused: bound >> tolerance
                ),
                axis_env=[("data", 8)],
            )(state)
            text = obs.to_prometheus_text(get_registry())
            families, samples = _StrictPromParser().parse(text)
            by = {}
            for name, labels, value in samples:
                by[(name, tuple(sorted(labels.items())))] = value
            assert by[("metrics_tpu_sync_wire_bytes_total", (("transport", "bf16"),))] == 512.0
            assert by[("metrics_tpu_sync_logical_bytes_total", (("transport", "bf16"),))] == 1024.0
            # the refused f32 bucket crossed exact at full width
            assert by[("metrics_tpu_sync_wire_bytes_total", (("transport", "exact"),))] == 256.0
            assert by[(
                "metrics_tpu_sync_transport_refusals_total",
                (("reason", "error_budget"), ("transport", "bf16")),
            )] == 1.0
            assert families["metrics_tpu_sync_wire_bytes_total"]["type"] == "counter"
        finally:
            get_registry().clear()

    def test_incremental_sync_series_parse_strictly(self):
        """ISSUE-15: tracing an incremental streak ticks
        metrics_tpu_engine_incremental_emissions_total (one per emission) and
        sets metrics_tpu_engine_incremental_deferred_residue_buckets to the
        collectives the finalize still paid — both parse through the strict
        exposition with the right family types."""
        import jax
        import jax.numpy as jnp
        from metrics_tpu.observability.instruments import get_registry
        from metrics_tpu.parallel.sync import (
            advance_incremental, finalize_incremental_state, init_incremental,
        )

        get_registry().clear()
        try:
            reds = {"hits": "sum", "rows": "cat"}
            modes = {"hits": "incremental"}

            def streak(state):
                carry = init_incremental(dict(state), reds, modes=modes, sync_every=1)
                for _ in range(3):
                    stepped = {"hits": carry.state["hits"] + 1, "rows": carry.state["rows"]}
                    carry = advance_incremental(carry, stepped, reds, "data", modes=modes)
                return finalize_incremental_state(carry, reds, "data", modes=modes)

            jax.make_jaxpr(streak, axis_env=[("data", 8)])(
                {"hits": jnp.zeros((8,), jnp.int32), "rows": jnp.zeros((2, 3), jnp.float32)}
            )
            text = obs.to_prometheus_text(get_registry())
            families, samples = _StrictPromParser().parse(text)
            by_name = {s[0]: s for s in samples}
            assert by_name["metrics_tpu_engine_incremental_emissions_total"][2] == 3.0
            # the cat leaf is residue: the finalize paid exactly its gather
            assert by_name["metrics_tpu_engine_incremental_deferred_residue_buckets"][2] == 1.0
            assert families["metrics_tpu_engine_incremental_emissions_total"]["type"] == "counter"
            assert families["metrics_tpu_engine_incremental_deferred_residue_buckets"]["type"] == "gauge"
        finally:
            get_registry().clear()

    def test_autotune_series_parse_strictly(self):
        """Self-tuning sync (ISSUE-17): tuner decisions tick
        metrics_tpu_autotune_decisions_total and the per-bucket gauges, the
        snapshot synthesizes the controller-level gauges (enabled / epoch /
        pinned / tracked / committed), every decision lands in the tracer
        under its catalogued name sync/tune_decision — and the whole family
        set parses through the strict exposition."""
        import metrics_tpu
        from metrics_tpu.autotune import bucket_key
        from metrics_tpu.autotune import controller as at_controller
        from metrics_tpu.observability.instruments import get_registry
        from metrics_tpu.observability.tracer import EVENT_CATALOG

        assert "sync/tune_decision" in EVENT_CATALOG["sync"]
        get_registry().clear()
        metrics_tpu.set_autotune(True)
        f32 = np.dtype("float32")
        key = bucket_key("sum", f32)
        try:
            with obs.trace() as tracer:
                ctl = at_controller.get_controller()
                for _ in range(4):
                    tuner = ctl.buckets.get(key)
                    cur = tuner.current if tuner else "exact"
                    ctl.observe_bucket(
                        "sum", f32, requested=cur, transport=cur,
                        nelems=8192, world=8,
                    )
                ctl.observe_error("sum", f32, measured=0.001)
                ctl.observe_sync_seconds(0.0125)
            counts = tracer.counts_by_name()
            assert counts.get("sync/tune_decision", 0) == len(ctl.decisions) >= 3

            text = obs.to_prometheus_text(get_registry())
            families, samples = _StrictPromParser().parse(text)
            by = {}
            for name, labels, value in samples:
                by[(name, tuple(sorted(labels.items())))] = value

            # the decision counter carries the transition labels
            assert by[(
                "metrics_tpu_autotune_decisions_total",
                (("bucket", key), ("from", "exact"), ("to", "bf16")),
            )] == 1.0
            assert families["metrics_tpu_autotune_decisions_total"]["type"] == "counter"

            # per-bucket gauges pushed by the controller
            blabel = (("bucket", key),)
            assert by[("metrics_tpu_autotune_predicted_wire_bytes", blabel)] == 8320.0
            assert ("metrics_tpu_autotune_realized_wire_bytes", blabel) in by
            assert ("metrics_tpu_autotune_predicted_error_bound", blabel) in by
            assert ("metrics_tpu_autotune_dwell", blabel) in by
            assert by[("metrics_tpu_autotune_realized_error", blabel)] == 0.001
            assert by[("metrics_tpu_autotune_last_sync_seconds", ())] == 0.0125

            # controller-level derived gauges synthesized at snapshot time
            assert by[("metrics_tpu_autotune_enabled", ())] == 1.0
            assert by[("metrics_tpu_autotune_pinned", ())] == 0.0
            assert by[("metrics_tpu_autotune_tracked_buckets", ())] == 1.0
            assert by[("metrics_tpu_autotune_committed_buckets", ())] == 1.0
            assert by[("metrics_tpu_autotune_decision_epoch", ())] > 0.0
            for fam in ("metrics_tpu_autotune_enabled",
                        "metrics_tpu_autotune_decision_epoch",
                        "metrics_tpu_autotune_tracked_buckets"):
                assert families[fam]["type"] == "gauge"
        finally:
            metrics_tpu.set_autotune(None)
            get_registry().clear()

    def test_awkward_label_values_round_trip(self):
        reg = InstrumentRegistry()
        awkward = 'quote " backslash \\ newline \n tab\tdone'
        reg.counter("edge_total", help="edge cases", tag=awkward).inc(2)
        reg.gauge("nan_gauge", help="nan").set(float("nan"))
        reg.gauge("inf_gauge", help="inf").set(float("inf"))
        h = reg.histogram("lat_seconds", help="lat", buckets=(0.5,))
        h.observe(0.1)
        text = obs.to_prometheus_text(reg)
        families, samples = _StrictPromParser().parse(text)
        by_name = {}
        for name, labels, value in samples:
            by_name.setdefault(name, []).append((labels, value))
        ((labels, value),) = by_name["metrics_tpu_edge_total"]
        assert labels == {"tag": awkward}  # escape -> parse -> original
        assert value == 2.0
        assert by_name["metrics_tpu_nan_gauge"][0][1] != by_name["metrics_tpu_nan_gauge"][0][1]
        assert by_name["metrics_tpu_inf_gauge"][0][1] == float("inf")
        assert families["metrics_tpu_lat_seconds"]["type"] == "histogram"
        bucket_labels = [l for (l, v) in by_name["metrics_tpu_lat_seconds_bucket"]]
        assert {"le": "0.5"} in bucket_labels and {"le": "+Inf"} in bucket_labels

    def test_interleaved_engine_families_are_regrouped(self):
        # two instruments sharing names but differing labels arrive
        # interleaved; the exposition must still keep each family contiguous
        reg = InstrumentRegistry()
        for owner in ("A", "B"):
            reg.counter("hits_total", help="h", owner=owner).inc()
            reg.gauge("depth", help="d", owner=owner).set(1)
        _StrictPromParser().parse(obs.to_prometheus_text(reg))


def metrics_for_roundtrip():
    from metrics_tpu import Accuracy

    return Accuracy(num_classes=4)


def _doc(events):
    return {
        "traceEvents": [
            {"name": n, "cat": c, "ph": "X", "ts": ts, "dur": dur, "pid": 1, "tid": 1}
            for (n, c, ts, dur) in events
        ],
        "otherData": {"dropped_events": 0},
    }


class TestAnalytics:
    def test_summarize_aggregates_per_name(self):
        doc = _doc([("a", "x", 0, 10), ("a", "x", 20, 30), ("b", "y", 5, 1)])
        s = obs.summarize_trace(doc)
        assert s["total_events"] == 3
        assert s["span_us"] == 50.0  # 0 .. 20+30
        assert list(s["events"]) == ["a", "b"]  # sorted by total time
        a = s["events"]["a"]
        assert (a["count"], a["total_us"], a["mean_us"], a["max_us"]) == (2, 40.0, 20.0, 30.0)

    def test_diff_reports_deltas_and_one_sided_events(self):
        a = _doc([("shared", "x", 0, 10), ("gone", "x", 0, 5)])
        b = _doc([("shared", "x", 0, 30), ("new", "x", 0, 7)])
        d = obs.diff_traces(a, b)
        assert d["only_a"] == ["gone"]
        assert d["only_b"] == ["new"]
        shared = d["events"]["shared"]
        assert shared["total_us"]["delta"] == 20.0
        assert shared["total_ratio"] == 3.0

    def test_summarize_empty_doc(self):
        s = obs.summarize_trace({"traceEvents": []})
        assert s["total_events"] == 0 and s["span_us"] == 0.0
