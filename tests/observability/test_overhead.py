"""Disabled-path overhead guard: tracing off must cost one flag check.

The cross-PR acceptance number (tracer-off fused update within 3% of the
pre-observability baseline) is recorded by ``bench.py --observability`` into
``BENCH_r12.json`` — a unit test cannot hold a run-to-run 3% bound without
flaking on shared CI hosts. What it *can* hold:

* tracer-off must not be slower than tracer-on beyond timer noise (the off
  path is a strict subset of the on path), and
* the tracer-off fused collection update must stay within the same
  2x-of-raw-jit + fixed-floor envelope the engine dispatch guard
  (tests/core/test_compiled_update_engine.py) has enforced since before the
  tracer existed — if the flag checks were doing real work, this breaks.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu import (
    Accuracy,
    F1Score,
    MetricCollection,
    Precision,
    Recall,
    observability as obs,
)

NUM_CLASSES = 256
BATCH = 256
STEPS = 32


def _collection():
    return MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "f1": F1Score(num_classes=NUM_CLASSES, average="macro"),
            "precision": Precision(num_classes=NUM_CLASSES, average="macro"),
            "recall": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )


def _batch():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(BATCH, NUM_CLASSES)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, NUM_CLASSES, size=(BATCH,)), dtype=jnp.int32)
    return logits, target


def _fused_us_per_step(coll, logits, target, reps=3):
    for _ in range(3):  # warmup sighting + compile + donation
        coll.update(logits, target)

    def one_rep():
        coll.reset()
        t0 = time.perf_counter()
        for _ in range(STEPS):
            coll.update(logits, target)
        jax.block_until_ready(next(iter(coll.values())).get_state())
        return (time.perf_counter() - t0) / STEPS * 1e6

    return min(one_rep() for _ in range(reps))


def test_disabled_tracer_is_not_slower_than_enabled():
    logits, target = _batch()
    assert not obs.enabled()
    off_us = _fused_us_per_step(_collection(), logits, target)
    with obs.trace():
        on_us = _fused_us_per_step(_collection(), logits, target)
    # the off path is a strict subset of the on path; 15% + 50us headroom
    # absorbs CI timer noise without hiding a real regression (an accidental
    # always-on emit would cost far more than that)
    assert off_us <= on_us * 1.15 + 50, (
        f"tracer-off fused update slower than tracer-on: "
        f"{off_us:.1f}us vs {on_us:.1f}us per step"
    )


def test_disabled_path_stays_in_the_dispatch_envelope():
    """Same envelope as the engine dispatch guard: stateful fused update
    within 2x of hand-driving the raw jitted update_state, plus a fixed
    bookkeeping floor. The tracer's flag checks must live inside it."""
    logits, target = _batch()
    assert not obs.enabled()

    m = Accuracy(num_classes=NUM_CLASSES)
    raw = Accuracy(num_classes=NUM_CLASSES, compiled_update=False)
    step = jax.jit(raw.update_state)
    state = step(raw.init_state(), logits, target)
    jax.block_until_ready(state)

    def time_raw():
        s = step(raw.init_state(), logits, target)
        t0 = time.perf_counter()
        for _ in range(STEPS):
            s = step(s, logits, target)
        jax.block_until_ready(s)
        return (time.perf_counter() - t0) / STEPS

    for _ in range(3):
        m.update(logits, target)

    def time_stateful():
        m.reset()
        m.update(logits, target)
        t0 = time.perf_counter()
        for _ in range(STEPS):
            m.update(logits, target)
        jax.block_until_ready(m.get_state())
        return (time.perf_counter() - t0) / STEPS

    raw_s = min(time_raw() for _ in range(3))
    stateful_s = min(time_stateful() for _ in range(3))
    assert stateful_s <= 2.0 * raw_s + 150e-6, (
        f"tracer-off stateful update outside the dispatch envelope: "
        f"{stateful_s * 1e6:.1f}us vs raw {raw_s * 1e6:.1f}us per step"
    )
