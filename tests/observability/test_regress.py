"""Bench regression watchdog: key classification, record extraction,
rolling-baseline judgement, and the repo's own BENCH trajectory as the
always-green fixture."""
import glob
import json
import os

import pytest

from metrics_tpu.observability import __main__ as obs_main
from metrics_tpu.observability import regress as _regress

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _write_round(tmp_path, name, record):
    path = tmp_path / f"BENCH_{name}.json"
    path.write_text(json.dumps(record))
    return str(path)


def _record(value=100.0, **extra):
    return {
        "metric": "fused_update_us_per_step",
        "value": value,
        "unit": "us",
        "extra": extra,
    }


class TestClassifyKey:
    @pytest.mark.parametrize(
        "key, expected",
        [
            ("extra.fused.fused_update_us_per_step", _regress.LOWER_IS_BETTER),
            ("extra.t.compile_s", _regress.LOWER_IS_BETTER),
            ("extra.scrape.scrape_p50_ms", _regress.LOWER_IS_BETTER),
            ("extra.sync.collective_bytes", _regress.LOWER_IS_BETTER),
            ("extra.merge.merge_wall_s", _regress.LOWER_IS_BETTER),
            ("extra.tput.steps_per_sec", _regress.HIGHER_IS_BETTER),
            ("extra.engine.speedup", _regress.HIGHER_IS_BETTER),
            ("extra.model.mfu_pct", _regress.HIGHER_IS_BETTER),
            ("extra.overhead.tracer_overhead_pct", _regress.PCT_POINTS),
            ("value.overhead_pct_max", _regress.PCT_POINTS),
            ("extra.cfg.num_classes", None),
            ("extra.flags.chrome_trace_valid", None),
            ("extra.fused.fused_update_us_per_step_tracer_off", None),
        ],
    )
    def test_direction(self, key, expected):
        assert _regress.classify_key(key) == expected


class TestLoading:
    def test_direct_record(self, tmp_path):
        p = _write_round(tmp_path, "r01", _record(42.0))
        (r,) = _regress.load_rounds([p])
        assert r.ok and r.name == "r01"
        assert r.record["value"] == 42.0

    def test_driver_wrapper_with_noisy_tail(self, tmp_path):
        record = _record(7.5)
        wrapper = {
            "n": 3, "cmd": "python bench.py", "rc": 0,
            "tail": "WARNING: platform noise\n"
                    'log prefix {"metric": "stale", "value": 1}\n'
                    f"more noise {json.dumps(record)}\n",
        }
        p = _write_round(tmp_path, "r02", wrapper)
        (r,) = _regress.load_rounds([p])
        assert r.ok
        assert r.record["value"] == 7.5  # last parseable record line wins

    def test_unparseable_tail_is_a_note_not_a_crash(self, tmp_path):
        p = _write_round(tmp_path, "r03", {"n": 1, "cmd": "x", "rc": 0, "tail": "truncated {\"met"})
        (r,) = _regress.load_rounds([p])
        assert not r.ok and "no parseable" in r.note

    def test_rounds_sort_numerically(self, tmp_path):
        paths = [_write_round(tmp_path, name, _record()) for name in ("r10", "r2", "r1")]
        names = [r.name for r in _regress.load_rounds(paths)]
        assert names == ["r1", "r2", "r10"]

    def test_headline_flattens_under_metric_name(self):
        flat = _regress.flatten_record(_record(33.0, cfg={"batch": 1024}))
        assert flat["value.fused_update_us_per_step"] == 33.0
        assert flat["extra.cfg.batch"] == 1024.0


class TestJudgement:
    def _trajectory(self, tmp_path, values, extra_fn=None):
        paths = []
        for i, v in enumerate(values, start=1):
            extra = extra_fn(i, v) if extra_fn else {}
            paths.append(_write_round(tmp_path, f"r{i:02d}", _record(v, **extra)))
        return paths

    def test_stable_trajectory_is_ok(self, tmp_path):
        paths = self._trajectory(tmp_path, [100, 104, 98, 101])
        report = _regress.check_paths(paths)
        assert report.ok
        assert report.checked_rounds == ["r04"]
        assert report.keys_checked >= 1

    def test_degraded_duration_regresses(self, tmp_path):
        paths = self._trajectory(tmp_path, [100, 104, 98, 200])
        report = _regress.check_paths(paths)
        assert not report.ok
        (reg,) = report.regressions
        assert reg.key == "value.fused_update_us_per_step"
        assert reg.round == "r04"
        assert reg.direction == _regress.LOWER_IS_BETTER
        assert reg.delta > 50.0
        assert "lower is better" in reg.describe()

    def test_throughput_drop_regresses(self, tmp_path):
        def extra(i, v):
            return {"tput": {"steps_per_sec": 1000.0 if i < 4 else 300.0}}
        paths = self._trajectory(tmp_path, [100, 100, 100, 100], extra)
        report = _regress.check_paths(paths)
        assert any(r.key == "extra.tput.steps_per_sec" for r in report.regressions)

    def test_pct_keys_use_absolute_points(self, tmp_path):
        def extra(i, v):
            return {"overhead_pct": 1.0 if i < 4 else 8.0}
        paths = self._trajectory(tmp_path, [100, 100, 100, 100], extra)
        # 1% -> 8% is a 8x ratio but only 7 points: under the 10-point default
        assert _regress.check_paths(paths).ok
        def extra2(i, v):
            return {"overhead_pct": 1.0 if i < 4 else 15.0}
        (tmp_path / "b").mkdir()
        paths2 = self._trajectory(tmp_path / "b", [100, 100, 100, 100], extra2)
        report = _regress.check_paths(paths2)
        assert any(r.direction == _regress.PCT_POINTS for r in report.regressions)

    def test_new_key_without_history_is_skipped(self, tmp_path):
        def extra(i, v):
            return {"scrape": {"p50_ms": 3.0}} if i == 4 else {}
        paths = self._trajectory(tmp_path, [100, 100, 100, 100], extra)
        report = _regress.check_paths(paths)
        assert report.ok
        assert report.keys_skipped_no_history >= 1

    def test_only_newest_round_is_judged_by_default(self, tmp_path):
        # r03 is a spike that recovered: latest-only mode stays green,
        # all_rounds replays history and flags the spike where it happened
        paths = self._trajectory(tmp_path, [100, 100, 400, 100])
        assert _regress.check_paths(paths).ok
        replay = _regress.check_paths(paths, all_rounds=True)
        assert any(r.round == "r03" for r in replay.regressions)

    def test_rolling_window_bounds_the_baseline(self, tmp_path):
        # old slow rounds age out of the 2-round window: baseline is the
        # recent fast pair, so the jump back to 300 regresses
        paths = self._trajectory(tmp_path, [300, 310, 100, 102, 300])
        assert not _regress.check_paths(paths, window=2).ok
        # with the full history in the window the median forgives it
        assert _regress.check_paths(paths, window=5).ok


class TestRepoTrajectory:
    def _repo_rounds(self):
        return sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")))

    def test_checked_in_trajectory_is_green(self):
        paths = self._repo_rounds()
        assert len(paths) >= 12, "BENCH trajectory missing from repo root"
        report = _regress.check_paths(paths)
        assert report.checked_rounds, report.notes
        assert report.ok, [r.describe() for r in report.regressions]

    def test_cli_exit_codes(self, tmp_path):
        paths = self._repo_rounds()
        assert obs_main.main(["regress", *paths]) == 0
        # synthetically degrade a new newest round: re-record r12's watched
        # duration 100x slower and its overhead 50 points up
        latest = json.loads(open(os.path.join(REPO_ROOT, "BENCH_r12.json")).read())
        record, note = _regress._extract_record(latest)
        assert record is not None, note
        bad = json.loads(json.dumps(record))
        bad["extra"]["baseline_fused_update_us_per_step"] *= 100.0
        bad["extra"]["tracer_on_overhead_pct"] += 50.0
        bad_path = str(tmp_path / "BENCH_r99.json")
        with open(bad_path, "w") as fh:
            json.dump(bad, fh)
        assert obs_main.main(["regress", *paths, bad_path]) == 1
        empty = str(tmp_path / "BENCH_r98.json")
        with open(empty, "w") as fh:
            json.dump({"n": 1, "cmd": "x", "rc": 0, "tail": "no record"}, fh)
        assert obs_main.main(["regress", empty]) == 2
