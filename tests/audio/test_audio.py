"""Audio metric parity vs independent numpy/scipy oracles.

Reference parity: tests/audio/test_snr.py, test_sdr.py, test_si_sdr.py,
test_pit.py, test_stoi.py. Oracles are hand-rolled numpy (SNR family), a
scipy ``solve_toeplitz`` SDR implementation (an independent solver path from
the FFT+linalg.solve/CG used in the library), scipy ``linear_sum_assignment``
for PIT, and a dynamic-shape numpy STOI following the published algorithm.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy.linalg import solve_toeplitz
from scipy.optimize import linear_sum_assignment

from metrics_tpu.audio import (
    PermutationInvariantTraining,
    ScaleInvariantSignalDistortionRatio,
    ScaleInvariantSignalNoiseRatio,
    ShortTimeObjectiveIntelligibility,
    SignalDistortionRatio,
    SignalNoiseRatio,
)
from metrics_tpu.ops.audio import (
    permutation_invariant_training,
    pit_permutate,
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    short_time_objective_intelligibility,
    signal_distortion_ratio,
    signal_noise_ratio,
)
from tests.helpers.testers import MetricTester

_rng = np.random.default_rng(11)
NB, BS, T = 4, 4, 2000
PREDS = _rng.normal(size=(NB, BS, T)).astype(np.float32)
TARGET = (0.8 * PREDS + 0.4 * _rng.normal(size=(NB, BS, T))).astype(np.float32)


# --------------------------------------------------------------------------- #
# oracles
# --------------------------------------------------------------------------- #
def _np_snr(preds, target, zero_mean=False):
    p, t = preds.astype(np.float64), target.astype(np.float64)
    if zero_mean:
        p = p - p.mean(-1, keepdims=True)
        t = t - t.mean(-1, keepdims=True)
    noise = t - p
    return 10 * np.log10(np.sum(t ** 2, -1) / np.sum(noise ** 2, -1))


def _np_si_sdr(preds, target, zero_mean=False):
    p, t = preds.astype(np.float64), target.astype(np.float64)
    if zero_mean:
        p = p - p.mean(-1, keepdims=True)
        t = t - t.mean(-1, keepdims=True)
    alpha = np.sum(p * t, -1, keepdims=True) / np.sum(t ** 2, -1, keepdims=True)
    ts = alpha * t
    return 10 * np.log10(np.sum(ts ** 2, -1) / np.sum((ts - p) ** 2, -1))


def _np_sdr(preds, target, filter_length=512):
    """Projection-based SDR via scipy solve_toeplitz (independent solver)."""
    out = np.empty(preds.shape[:-1])
    flat_p = preds.reshape(-1, preds.shape[-1]).astype(np.float64)
    flat_t = target.reshape(-1, target.shape[-1]).astype(np.float64)
    for i, (p, t) in enumerate(zip(flat_p, flat_t)):
        t = t / max(np.linalg.norm(t), 1e-6)
        p = p / max(np.linalg.norm(p), 1e-6)
        n_fft = 2 ** int(np.ceil(np.log2(p.shape[-1] + t.shape[-1] - 1)))
        t_fft = np.fft.rfft(t, n=n_fft)
        r = np.fft.irfft(np.abs(t_fft) ** 2, n=n_fft)[:filter_length]
        b = np.fft.irfft(np.conj(t_fft) * np.fft.rfft(p, n=n_fft), n=n_fft)[:filter_length]
        sol = solve_toeplitz(r, b)
        coh = b @ sol
        out.reshape(-1)[i] = 10 * np.log10(coh / (1 - coh))
    return out


def _np_stoi(x, y, extended=False):
    """Dynamic-shape numpy STOI (published algorithm, pystoi constants)."""
    FS, NF, NFFT_, J, MIN_F, N, BETA, DYN = 10000, 256, 512, 15, 150, 30, -15.0, 40.0
    EPS = np.finfo(np.float64).eps
    x, y = x.astype(np.float64), y.astype(np.float64)

    w = np.hanning(NF + 2)[1:-1]
    hop = NF // 2
    frames = range(0, len(x) - NF, hop)  # pystoi's exclusive stop
    x_frames = np.array([w * x[i : i + NF] for i in frames])
    y_frames = np.array([w * y[i : i + NF] for i in frames])
    energies = 20 * np.log10(np.linalg.norm(x_frames, axis=1) + EPS)
    mask = (np.max(energies) - DYN - energies) < 0
    x_frames, y_frames = x_frames[mask], y_frames[mask]

    def ola(frames):
        buf = np.zeros((len(frames) - 1) * hop + NF)
        for i, f in enumerate(frames):
            buf[i * hop : i * hop + NF] += f
        return buf

    x_sil, y_sil = ola(x_frames), ola(y_frames)

    f = np.linspace(0, FS / 2, NFFT_ // 2 + 1)
    k = np.arange(J)
    fl = MIN_F * 2.0 ** ((2 * k - 1) / 6.0)
    fh = MIN_F * 2.0 ** ((2 * k + 1) / 6.0)
    obm = np.zeros((J, len(f)))
    for i in range(J):
        obm[i, np.argmin((f - fl[i]) ** 2) : np.argmin((f - fh[i]) ** 2)] = 1

    def bands(sig):
        frames = np.array([w * sig[i : i + NF] for i in range(0, len(sig) - NF, hop)])
        spec = np.fft.rfft(frames, n=NFFT_, axis=-1)
        return np.sqrt((np.abs(spec) ** 2) @ obm.T).T  # (J, M)

    X, Y = bands(x_sil), bands(y_sil)
    M = X.shape[1]
    scores = []
    for m in range(N, M + 1):
        Xs, Ys = X[:, m - N : m], Y[:, m - N : m]
        if extended:
            def rcnorm(a):
                a = a - a.mean(-1, keepdims=True)
                a = a / (np.linalg.norm(a, axis=-1, keepdims=True) + EPS)
                a = a - a.mean(0, keepdims=True)
                return a / (np.linalg.norm(a, axis=0, keepdims=True) + EPS)
            scores.append(np.sum(rcnorm(Xs) * rcnorm(Ys)) / N)
        else:
            alpha = np.linalg.norm(Xs, axis=-1, keepdims=True) / (np.linalg.norm(Ys, axis=-1, keepdims=True) + EPS)
            Yp = np.minimum(alpha * Ys, Xs * (1 + 10 ** (-BETA / 20)))
            xn = Xs - Xs.mean(-1, keepdims=True)
            yn = Yp - Yp.mean(-1, keepdims=True)
            corr = np.sum(xn * yn, -1) / (np.linalg.norm(xn, axis=-1) * np.linalg.norm(yn, axis=-1) + EPS)
            scores.append(corr.mean())
    return np.mean(scores)


# --------------------------------------------------------------------------- #
# functional parity
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("zero_mean", [False, True])
def test_snr_functional(zero_mean):
    res = signal_noise_ratio(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), zero_mean=zero_mean)
    np.testing.assert_allclose(np.asarray(res), _np_snr(PREDS[0], TARGET[0], zero_mean), rtol=1e-4)


@pytest.mark.parametrize("zero_mean", [False, True])
def test_si_sdr_functional(zero_mean):
    res = scale_invariant_signal_distortion_ratio(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), zero_mean=zero_mean)
    np.testing.assert_allclose(np.asarray(res), _np_si_sdr(PREDS[0], TARGET[0], zero_mean), rtol=1e-4)


def test_si_snr_functional():
    res = scale_invariant_signal_noise_ratio(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]))
    np.testing.assert_allclose(np.asarray(res), _np_si_sdr(PREDS[0], TARGET[0], zero_mean=True), rtol=1e-4)


@pytest.mark.parametrize("filter_length", [128, 512])
def test_sdr_functional_vs_scipy_toeplitz(filter_length):
    res = signal_distortion_ratio(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), filter_length=filter_length)
    want = _np_sdr(PREDS[0], TARGET[0], filter_length)
    np.testing.assert_allclose(np.asarray(res), want, rtol=1e-2, atol=5e-3)


def test_sdr_cg_close_to_direct():
    direct = signal_distortion_ratio(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), filter_length=128)
    cg = signal_distortion_ratio(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]), filter_length=128, use_cg_iter=50)
    np.testing.assert_allclose(np.asarray(cg), np.asarray(direct), atol=2e-2)


def test_sdr_jittable():
    f = jax.jit(lambda p, t: signal_distortion_ratio(p, t, filter_length=128, use_cg_iter=10))
    out = f(jnp.asarray(PREDS[0]), jnp.asarray(TARGET[0]))
    assert bool(jnp.all(jnp.isfinite(out)))


# --------------------------------------------------------------------------- #
# PIT
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("n_spk", [2, 3])
@pytest.mark.parametrize("eval_func", ["max", "min"])
def test_pit_vs_scipy(n_spk, eval_func):
    rng = np.random.default_rng(77 + n_spk)
    preds = jnp.asarray(rng.normal(size=(5, n_spk, 500)).astype(np.float32))
    target = jnp.asarray(rng.normal(size=(5, n_spk, 500)).astype(np.float32))
    best_metric, best_perm = permutation_invariant_training(
        preds, target, scale_invariant_signal_distortion_ratio, eval_func
    )
    # oracle: metric matrix + scipy assignment
    mtx = np.empty((5, n_spk, n_spk))
    for t in range(n_spk):
        for p in range(n_spk):
            mtx[:, t, p] = _np_si_sdr(np.asarray(preds)[:, p], np.asarray(target)[:, t])
    for b in range(5):
        rows, cols = linear_sum_assignment(mtx[b], maximize=(eval_func == "max"))
        want = mtx[b][rows, cols].mean()
        np.testing.assert_allclose(float(best_metric[b]), want, rtol=1e-3, atol=1e-2)
        np.testing.assert_allclose(np.asarray(best_perm[b]), cols)


def test_pit_permutate():
    preds = jnp.asarray(_rng.normal(size=(2, 3, 10)).astype(np.float32))
    perm = jnp.asarray([[2, 0, 1], [0, 1, 2]])
    out = pit_permutate(preds, perm)
    np.testing.assert_allclose(np.asarray(out[0, 0]), np.asarray(preds[0, 2]))
    np.testing.assert_allclose(np.asarray(out[1]), np.asarray(preds[1]))


def test_pit_jittable():
    preds = jnp.asarray(_rng.normal(size=(3, 2, 200)).astype(np.float32))
    target = jnp.asarray(_rng.normal(size=(3, 2, 200)).astype(np.float32))
    f = jax.jit(
        lambda p, t: permutation_invariant_training(p, t, scale_invariant_signal_distortion_ratio, "max")[0]
    )
    eager = permutation_invariant_training(preds, target, scale_invariant_signal_distortion_ratio, "max")[0]
    np.testing.assert_allclose(np.asarray(f(preds, target)), np.asarray(eager), rtol=1e-5)


def test_pit_validation():
    with pytest.raises(ValueError, match="eval_func"):
        permutation_invariant_training(
            jnp.zeros((2, 2, 10)), jnp.zeros((2, 2, 10)), scale_invariant_signal_distortion_ratio, "med"
        )
    with pytest.raises(RuntimeError, match="same shape"):
        permutation_invariant_training(
            jnp.zeros((2, 2, 10)), jnp.zeros((2, 3, 10)), scale_invariant_signal_distortion_ratio
        )


# --------------------------------------------------------------------------- #
# STOI
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("extended", [False, True])
def test_stoi_vs_numpy_oracle(extended):
    t = np.sin(2 * np.pi * 440 * np.arange(20000) / 10000) + 0.1 * _rng.normal(size=20000)
    # insert silence so the silent-frame removal path is exercised
    t[5000:8000] = 1e-6 * _rng.normal(size=3000)
    p = t + 0.5 * _rng.normal(size=20000)
    got = float(short_time_objective_intelligibility(jnp.asarray(p, dtype=jnp.float32), jnp.asarray(t, dtype=jnp.float32), fs=10000, extended=extended))
    want = _np_stoi(t, p, extended=extended)
    np.testing.assert_allclose(got, want, atol=2e-3)


def test_stoi_perfect_signal_high():
    t = np.sin(2 * np.pi * 300 * np.arange(16000) / 10000).astype(np.float32)
    got = float(short_time_objective_intelligibility(jnp.asarray(t), jnp.asarray(t), fs=10000))
    assert got > 0.99


def test_stoi_too_short_returns_nan():
    clip = jnp.asarray(_rng.normal(size=200).astype(np.float32))  # < one frame
    assert np.isnan(float(short_time_objective_intelligibility(clip, clip, fs=10000)))
    clip2 = jnp.asarray(_rng.normal(size=2000).astype(np.float32))  # < one segment
    assert np.isnan(float(short_time_objective_intelligibility(clip2, clip2, fs=10000)))


def test_stoi_resample_path():
    t = _rng.normal(size=(2, 16000)).astype(np.float32)
    p = (t + 0.3 * _rng.normal(size=(2, 16000))).astype(np.float32)
    vals = short_time_objective_intelligibility(jnp.asarray(p), jnp.asarray(t), fs=16000)
    assert vals.shape == (2,)
    assert bool(jnp.all(jnp.isfinite(vals)))


# --------------------------------------------------------------------------- #
# module classes incl. ddp
# --------------------------------------------------------------------------- #
class TestAudioModules(MetricTester):
    atol = 1e-4

    @pytest.mark.parametrize("ddp", [False, True])
    def test_snr_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=PREDS,
            target=TARGET,
            metric_class=SignalNoiseRatio,
            sk_metric=lambda p, t: _np_snr(p, t).mean(),
            check_batch=True,
        )

    @pytest.mark.parametrize("ddp", [False, True])
    def test_si_sdr_class(self, ddp):
        self.run_class_metric_test(
            ddp=ddp,
            preds=PREDS,
            target=TARGET,
            metric_class=ScaleInvariantSignalDistortionRatio,
            sk_metric=lambda p, t: _np_si_sdr(p, t).mean(),
            check_batch=True,
        )

    def test_si_snr_class(self):
        self.run_class_metric_test(
            ddp=False,
            preds=PREDS,
            target=TARGET,
            metric_class=ScaleInvariantSignalNoiseRatio,
            sk_metric=lambda p, t: _np_si_sdr(p, t, zero_mean=True).mean(),
            check_batch=True,
        )

    def test_sdr_class(self):
        self.run_class_metric_test(
            ddp=False,
            preds=PREDS,
            target=TARGET,
            metric_class=SignalDistortionRatio,
            sk_metric=lambda p, t: _np_sdr(p, t, filter_length=128).mean(),
            metric_args={"filter_length": 128},
            check_batch=False,
        )

    def test_pit_class(self):
        preds = _rng.normal(size=(2, 3, 2, 400)).astype(np.float32)
        target = _rng.normal(size=(2, 3, 2, 400)).astype(np.float32)

        def oracle(p, t):
            vals = []
            for b in range(p.shape[0]):
                mtx = np.empty((2, 2))
                for ti in range(2):
                    for pi in range(2):
                        mtx[ti, pi] = _np_si_sdr(p[b, pi], t[b, ti])
                rows, cols = linear_sum_assignment(mtx, maximize=True)
                vals.append(mtx[rows, cols].mean())
            return np.mean(vals)

        self.run_class_metric_test(
            ddp=False,
            preds=preds,
            target=target,
            metric_class=PermutationInvariantTraining,
            sk_metric=oracle,
            metric_args={"metric_func": scale_invariant_signal_distortion_ratio, "eval_func": "max"},
            check_batch=True,
        )

    def test_stoi_class(self):
        t = _rng.normal(size=(2, 2, 12000)).astype(np.float32)
        p = (t + 0.5 * _rng.normal(size=(2, 2, 12000))).astype(np.float32)
        self.run_class_metric_test(
            ddp=False,
            preds=p,
            target=t,
            metric_class=ShortTimeObjectiveIntelligibility,
            sk_metric=lambda pp, tt: np.mean([_np_stoi(tt[i], pp[i]) for i in range(pp.shape[0])]),
            metric_args={"fs": 10000},
            check_batch=True,
        )

    def test_pesq_gating(self):
        from metrics_tpu.ops.audio.pesq import _PESQ_AVAILABLE

        if not _PESQ_AVAILABLE:
            from metrics_tpu.audio import PerceptualEvaluationSpeechQuality

            with pytest.raises(ModuleNotFoundError, match="pesq"):
                PerceptualEvaluationSpeechQuality(fs=16000, mode="wb")

    def test_differentiability(self):
        self.run_differentiability_test(PREDS, TARGET, signal_noise_ratio)
        self.run_differentiability_test(PREDS, TARGET, scale_invariant_signal_distortion_ratio)

    def test_precision_bf16(self):
        self.run_precision_test(PREDS, TARGET, lambda p, t: signal_noise_ratio(p, t.astype(p.dtype)))
        self.run_precision_test(PREDS, TARGET, lambda p, t: scale_invariant_signal_noise_ratio(p, t.astype(p.dtype)))
        self.run_precision_test(PREDS, TARGET, lambda p, t: scale_invariant_signal_distortion_ratio(p, t.astype(p.dtype)))


# --------------------------------------------------------------------------- #
# PESQ delegation path (VERDICT r2 item 5): the host-side plumbing — batch
# reshape, device round-trip, (sum, count) accumulation — asserted numerically
# against an injected deterministic backend; a live differential runs when the
# real `pesq` C extension is present.
# --------------------------------------------------------------------------- #
class _FakePesqBackend:
    """Deterministic stand-in with the `pesq.pesq(fs, ref, deg, mode)` signature."""

    @staticmethod
    def pesq(fs, ref, deg, mode):
        ref = np.asarray(ref, dtype=np.float64)
        deg = np.asarray(deg, dtype=np.float64)
        corr = float(np.corrcoef(ref, deg)[0, 1])
        return 2.0 + corr + (0.25 if mode == "wb" else 0.0) + fs / 80000.0


@pytest.fixture
def fake_pesq(monkeypatch):
    import sys as _sys

    import metrics_tpu.audio.pesq as pesq_module
    import metrics_tpu.ops.audio.pesq as pesq_ops

    monkeypatch.setitem(_sys.modules, "pesq", _FakePesqBackend)
    monkeypatch.setattr(pesq_ops, "_PESQ_AVAILABLE", True)
    monkeypatch.setattr(pesq_module, "_PESQ_AVAILABLE", True)
    return _FakePesqBackend


def _pesq_waveforms(shape=(2, 3), n=4000, seed=11):
    rng = np.random.default_rng(seed)
    t = np.sin(2 * np.pi * 440 * np.arange(n) / 8000).astype(np.float32)
    target = np.broadcast_to(t, (*shape, n)).copy()
    preds = target + 0.3 * rng.normal(size=(*shape, n)).astype(np.float32)
    return preds, target


def test_pesq_batch_reshape_numeric(fake_pesq):
    from metrics_tpu.ops.audio.pesq import perceptual_evaluation_speech_quality

    preds, target = _pesq_waveforms()
    out = perceptual_evaluation_speech_quality(jnp.asarray(preds), jnp.asarray(target), 8000, "nb")
    assert out.shape == (2, 3)
    want = np.asarray(
        [[fake_pesq.pesq(8000, target[i, j], preds[i, j], "nb") for j in range(3)] for i in range(2)]
    )
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)

    # 1-D path
    one = perceptual_evaluation_speech_quality(jnp.asarray(preds[0, 0]), jnp.asarray(target[0, 0]), 16000, "wb")
    np.testing.assert_allclose(float(one), fake_pesq.pesq(16000, target[0, 0], preds[0, 0], "wb"), rtol=1e-6)


def test_pesq_module_accumulation(fake_pesq):
    from metrics_tpu.audio import PerceptualEvaluationSpeechQuality

    preds, target = _pesq_waveforms(shape=(4,))
    metric = PerceptualEvaluationSpeechQuality(fs=8000, mode="nb")
    metric.update(jnp.asarray(preds[:2]), jnp.asarray(target[:2]))
    metric.update(jnp.asarray(preds[2:]), jnp.asarray(target[2:]))
    want = np.mean([fake_pesq.pesq(8000, target[i], preds[i], "nb") for i in range(4)])
    np.testing.assert_allclose(float(metric.compute()), want, rtol=1e-6)


def test_pesq_argument_validation(fake_pesq):
    from metrics_tpu.ops.audio.pesq import perceptual_evaluation_speech_quality

    preds, target = _pesq_waveforms(shape=(1,))
    p, t = jnp.asarray(preds), jnp.asarray(target)
    with pytest.raises(ValueError, match="to either be 8000 or 16000"):
        perceptual_evaluation_speech_quality(p, t, 44100, "nb")
    with pytest.raises(ValueError, match="to either be 'wb' or 'nb'"):
        perceptual_evaluation_speech_quality(p, t, 16000, "speech")
    with pytest.raises(ValueError, match="'nb' for a 8000Hz signal"):
        perceptual_evaluation_speech_quality(p, t, 8000, "wb")


def test_pesq_live_differential():
    pesq_backend = pytest.importorskip("pesq")
    from metrics_tpu.ops.audio.pesq import perceptual_evaluation_speech_quality

    preds, target = _pesq_waveforms(shape=(3,), n=16000)
    got = perceptual_evaluation_speech_quality(jnp.asarray(preds), jnp.asarray(target), 8000, "nb")
    want = [pesq_backend.pesq(8000, target[i], preds[i], "nb") for i in range(3)]
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5)
