"""Native jax PESQ model: perceptual-property tests + gated C-extension differential.

The C extension stays the default backend and the oracle (reference
torchmetrics/audio/pesq.py:25 delegates outright); the native model's local
contract is the set of properties any PESQ implementation must satisfy —
identity scores near the ceiling, monotonic degradation under noise, level
invariance (the level-alignment stage), delay invariance (the time-alignment
stage), jit/vmap consistency — with the exact-tolerance differential gated
on ``pesq`` being installed.
"""
import importlib.util

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import PerceptualEvaluationSpeechQuality
from metrics_tpu.ops.audio.pesq import perceptual_evaluation_speech_quality
from metrics_tpu.ops.audio.pesq_native import pesq_native

_HAS_PESQ = importlib.util.find_spec("pesq") is not None

_rng = np.random.default_rng(21)


def _speech_like(n, fs):
    """Synthetic voiced speech: pitch train + formants + syllabic envelope."""
    t = np.arange(n) / fs
    f0 = 120 + 20 * np.sin(2 * np.pi * 2.1 * t)
    phase = 2 * np.pi * np.cumsum(f0) / fs
    sig = np.zeros(n)
    for k, amp in ((1, 1.0), (2, 0.6), (3, 0.4), (4, 0.25)):
        sig += amp * np.sin(k * phase)
    for fc, bw, amp in ((500, 80, 0.8), (1500, 120, 0.5), (2500, 160, 0.3)):
        sig += amp * np.sin(2 * np.pi * fc * t) * np.exp(-((np.sin(2 * np.pi * 1.3 * t)) ** 2) * bw / 100)
    envelope = 0.2 + 0.8 * (np.sin(2 * np.pi * 3.7 * t) > -0.3)
    return (sig * envelope).astype(np.float32)


_FS = 8000
_REF = _speech_like(4 * _FS, _FS)


def _mos(deg, ref=_REF, fs=_FS, mode="nb"):
    return float(pesq_native(jnp.asarray(deg), jnp.asarray(ref), fs, mode))


def test_identity_scores_near_ceiling():
    assert _mos(_REF) > 4.3


def test_monotonic_under_noise():
    scores = []
    for snr_db in (40, 20, 10, 0, -10):
        noise = _rng.normal(size=_REF.shape).astype(np.float32)
        noise *= np.linalg.norm(_REF) / np.linalg.norm(noise) * 10 ** (-snr_db / 20)
        scores.append(_mos(_REF + noise))
    assert all(a >= b - 1e-6 for a, b in zip(scores, scores[1:])), scores
    assert scores[0] - scores[-1] > 1.0, f"insufficient dynamic range: {scores}"


def test_level_invariance():
    base = _mos(_REF + 0.05 * _rng.normal(size=_REF.shape).astype(np.float32))
    deg = _REF + 0.05 * _rng.normal(size=_REF.shape).astype(np.float32)
    for scale in (0.1, 10.0):
        np.testing.assert_allclose(_mos(deg * scale), _mos(deg), atol=0.05)
    assert abs(base - _mos(deg)) < 0.2  # same noise level, same ballpark


def test_delay_invariance():
    deg = np.roll(_REF, 3 * 128)  # 3 frame-hops of pure delay
    assert _mos(deg) > 4.0, "time alignment failed to absorb a constant delay"


def test_jit_vmap_parity():
    deg = _REF + 0.1 * _rng.normal(size=_REF.shape).astype(np.float32)
    eager = pesq_native(jnp.asarray(deg), jnp.asarray(_REF), _FS, "nb")
    jitted = jax.jit(lambda p, t: pesq_native(p, t, _FS, "nb"))(jnp.asarray(deg), jnp.asarray(_REF))
    np.testing.assert_allclose(float(jitted), float(eager), atol=1e-4)

    batch_p = jnp.stack([jnp.asarray(deg), jnp.asarray(_REF)])
    batch_t = jnp.stack([jnp.asarray(_REF), jnp.asarray(_REF)])
    out = pesq_native(batch_p, batch_t, _FS, "nb")
    assert out.shape == (2,)
    np.testing.assert_allclose(float(out[0]), float(eager), atol=1e-4)


def test_wideband_mapping():
    ref = _speech_like(4 * 16000, 16000)
    clean = float(pesq_native(jnp.asarray(ref), jnp.asarray(ref), 16000, "wb"))
    noisy = float(pesq_native(
        jnp.asarray(ref + 0.3 * _rng.normal(size=ref.shape).astype(np.float32)),
        jnp.asarray(ref), 16000, "wb",
    ))
    assert clean > noisy
    assert 1.0 <= noisy < clean <= 4.64


def test_functional_implementation_arg():
    deg = _REF + 0.1 * _rng.normal(size=_REF.shape).astype(np.float32)
    v = perceptual_evaluation_speech_quality(
        jnp.asarray(deg), jnp.asarray(_REF), _FS, "nb", implementation="native",
    )
    assert 1.0 <= float(v) <= 4.64
    with pytest.raises(ValueError, match="implementation"):
        perceptual_evaluation_speech_quality(
            jnp.asarray(deg), jnp.asarray(_REF), _FS, "nb", implementation="bogus",
        )


def test_class_native_backend():
    m = PerceptualEvaluationSpeechQuality(_FS, "nb", implementation="native")
    deg = _REF + 0.1 * _rng.normal(size=_REF.shape).astype(np.float32)
    m.update(jnp.asarray(deg), jnp.asarray(_REF))
    m.update(jnp.asarray(deg), jnp.asarray(_REF))
    assert 1.0 <= float(m.compute()) <= 4.64
    with pytest.raises(ValueError, match="implementation"):
        PerceptualEvaluationSpeechQuality(_FS, "nb", implementation="bogus")


@pytest.mark.skipif(not _HAS_PESQ, reason="pesq C extension absent")
def test_differential_vs_c_extension():
    """Rank correlation and bounded absolute error vs the ITU reference code."""
    import pesq as pesq_backend

    degradations = []
    for snr_db in (30, 20, 15, 10, 5, 0):
        noise = _rng.normal(size=_REF.shape).astype(np.float32)
        noise *= np.linalg.norm(_REF) / np.linalg.norm(noise) * 10 ** (-snr_db / 20)
        degradations.append(_REF + noise)

    ours = np.asarray([_mos(d) for d in degradations])
    theirs = np.asarray([pesq_backend.pesq(_FS, _REF, d, "nb") for d in degradations])

    # identical quality ordering, and bounded deviation on speech material
    assert (np.argsort(ours) == np.argsort(theirs)).all(), (ours, theirs)
    assert np.max(np.abs(ours - theirs)) < 0.35, (ours, theirs)


# ---------------------------------------------------------------------------
# Pinned goldens: ungated numeric regression net for the native model.
#
# The C-extension differential above is the *truth* test but only runs where
# `pesq` is installed; these constants freeze the native model's current MOS
# output on deterministic fixtures so a numeric change to any pipeline stage
# (level/time alignment, bark bands, loudness mapping, disturbance
# aggregation) fails CI everywhere. Regenerate deliberately (and re-run the
# gated differential) if the model is intentionally improved.
# ---------------------------------------------------------------------------

_GOLDEN_SNRS = (30, 20, 15, 10, 5, 0)
_GOLDEN_MOS = {
    # (fs, mode) -> [identity, snr30, snr20, snr15, snr10, snr5, snr0]
    (8000, "nb"): [4.500000, 4.494661, 4.449308, 4.305016, 3.921289, 3.275458, 2.588635],
    (16000, "wb"): [4.640000, 4.640000, 4.640000, 4.640000, 4.625756, 4.538018, 4.195332],
}


def _golden_degradations(fs):
    ref = _speech_like(4 * fs, fs)
    degs = [ref]
    for i, snr_db in enumerate(_GOLDEN_SNRS):
        rng = np.random.default_rng(1000 + i)  # per-fixture seed: order-independent
        noise = rng.normal(size=ref.shape).astype(np.float32)
        noise *= np.linalg.norm(ref) / np.linalg.norm(noise) * 10 ** (-snr_db / 20)
        degs.append(ref + noise)
    return ref, degs


@pytest.mark.parametrize("fs,mode", [(8000, "nb"), (16000, "wb")])
def test_pinned_goldens(fs, mode):
    ref, degs = _golden_degradations(fs)
    got = [float(pesq_native(jnp.asarray(d), jnp.asarray(ref), fs, mode)) for d in degs]
    # 0.02 MOS absorbs cross-platform float32 FFT reassociation while still
    # catching any real pipeline regression (those move scores by >> 0.1)
    np.testing.assert_allclose(got, _GOLDEN_MOS[(fs, mode)], atol=2e-2)
