"""Audio option surfaces pinned directly against the reference implementation.

SNR/SI-SNR/SI-SDR/SDR and PIT run live on both sides over identical
correlated signals (random noise alone makes SDR ill-conditioned in f32).
Reference: functional/audio/{snr,sdr,pit}.py. Uses the shared conftest
import helper; skips when the checkout or torch is unavailable.
"""
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu.functional as mtf

_rng = np.random.default_rng(33)
TARGET = _rng.standard_normal((6, 400)).astype(np.float32)
PREDS = (TARGET + 0.3 * _rng.standard_normal((6, 400))).astype(np.float32)


def _ref():
    from tests.conftest import reference_functional

    return reference_functional()


@pytest.mark.parametrize("zero_mean", [False, True], ids=["raw", "zero_mean"])
def test_snr_vs_reference(zero_mean):
    torch, F = _ref()
    ours = mtf.signal_noise_ratio(jnp.asarray(PREDS), jnp.asarray(TARGET), zero_mean=zero_mean)
    want = F.signal_noise_ratio(torch.tensor(PREDS), torch.tensor(TARGET), zero_mean=zero_mean)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(want), rtol=1e-4)


@pytest.mark.parametrize("zero_mean", [False, True], ids=["raw", "zero_mean"])
def test_si_sdr_vs_reference(zero_mean):
    torch, F = _ref()
    ours = mtf.scale_invariant_signal_distortion_ratio(
        jnp.asarray(PREDS), jnp.asarray(TARGET), zero_mean=zero_mean
    )
    want = F.scale_invariant_signal_distortion_ratio(
        torch.tensor(PREDS), torch.tensor(TARGET), zero_mean=zero_mean
    )
    np.testing.assert_allclose(np.asarray(ours), np.asarray(want), rtol=1e-4)


def test_si_snr_vs_reference():
    torch, F = _ref()
    ours = mtf.scale_invariant_signal_noise_ratio(jnp.asarray(PREDS), jnp.asarray(TARGET))
    want = F.scale_invariant_signal_noise_ratio(torch.tensor(PREDS), torch.tensor(TARGET))
    np.testing.assert_allclose(np.asarray(ours), np.asarray(want), rtol=1e-4)


@pytest.mark.parametrize("zero_mean", [False, True], ids=["raw", "zero_mean"])
def test_sdr_vs_reference(zero_mean):
    torch, F = _ref()
    ours = mtf.signal_distortion_ratio(jnp.asarray(PREDS), jnp.asarray(TARGET), zero_mean=zero_mean)
    want = F.signal_distortion_ratio(torch.tensor(PREDS), torch.tensor(TARGET), zero_mean=zero_mean)
    # SDR solves a 512-tap Toeplitz system; f64 reference vs our f32-CG path
    np.testing.assert_allclose(np.asarray(ours), np.asarray(want), rtol=1e-3)


@pytest.mark.parametrize("eval_func", ["max", "min"])
def test_pit_vs_reference(eval_func):
    torch, F = _ref()
    spk_t = _rng.standard_normal((3, 2, 200)).astype(np.float32)
    perm = [1, 0]
    spk_p = (spk_t[:, perm] + 0.2 * _rng.standard_normal((3, 2, 200))).astype(np.float32)

    def jax_sisdr(p, t):
        return mtf.scale_invariant_signal_distortion_ratio(p, t)

    ours_val, ours_perm = mtf.permutation_invariant_training(
        jnp.asarray(spk_p), jnp.asarray(spk_t), jax_sisdr, eval_func=eval_func
    )
    want_val, want_perm = F.permutation_invariant_training(
        torch.tensor(spk_p),
        torch.tensor(spk_t),
        F.scale_invariant_signal_distortion_ratio,
        eval_func=eval_func,
    )
    np.testing.assert_allclose(np.asarray(ours_val), np.asarray(want_val), rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(ours_perm), np.asarray(want_perm))
