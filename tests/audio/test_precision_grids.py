"""bf16 precision grid for the audio family.

Reference analog: the fp16 test grid in tests/helpers/testers.py:478-534 run
by every reference audio test. On TPU the half precision that matters is
bfloat16; each functional must stay finite and track its f32 value within a
band that reflects bf16's 8-bit mantissa across batch layouts.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import ops
from metrics_tpu.ops.audio.pesq_native import pesq_native

_rng = np.random.default_rng(77)

_T1 = _rng.normal(size=(2000,)).astype(np.float32)
_P1 = _T1 + 0.3 * _rng.normal(size=(2000,)).astype(np.float32)
_T2 = _rng.normal(size=(4, 2000)).astype(np.float32)
_P2 = _T2 + 0.3 * _rng.normal(size=(4, 2000)).astype(np.float32)
_MIX_T = _rng.normal(size=(2, 3, 1500)).astype(np.float32)
_MIX_P = _MIX_T[:, ::-1] + 0.2 * _rng.normal(size=(2, 3, 1500)).astype(np.float32)
_LONG_T = _rng.normal(size=(8000,)).astype(np.float32)
_LONG_P = _LONG_T + 0.2 * _rng.normal(size=(8000,)).astype(np.float32)

_CASES = [
    ("snr-1d", lambda p, t: ops.signal_noise_ratio(p, t), _P1, _T1, 0.5),
    ("snr-2d", lambda p, t: ops.signal_noise_ratio(p, t), _P2, _T2, 0.5),
    ("si_snr", lambda p, t: ops.scale_invariant_signal_noise_ratio(p, t), _P2, _T2, 0.5),
    ("si_sdr", lambda p, t: ops.scale_invariant_signal_distortion_ratio(p, t), _P2, _T2, 0.5),
    ("si_sdr-zero_mean", lambda p, t: ops.scale_invariant_signal_distortion_ratio(p, t, zero_mean=True), _P2, _T2, 0.5),
    ("sdr", lambda p, t: ops.signal_distortion_ratio(p, t), _P2, _T2, 1.5),
    ("pit", lambda p, t: ops.permutation_invariant_training(p, t, ops.scale_invariant_signal_noise_ratio)[0], _MIX_P, _MIX_T, 0.5),
    ("stoi", lambda p, t: ops.short_time_objective_intelligibility(p, t, 10000), _LONG_P, _LONG_T, 0.05),
    ("pesq-native", lambda p, t: pesq_native(p, t, 8000, "nb"), _LONG_P, _LONG_T, 0.15),
]


@pytest.mark.parametrize("name,fn,p,t,tol", _CASES, ids=[c[0] for c in _CASES])
def test_bf16_tracks_f32(name, fn, p, t, tol):
    f32 = np.asarray(fn(jnp.asarray(p), jnp.asarray(t)), dtype=np.float64)
    bf16 = np.asarray(
        jnp.asarray(fn(jnp.asarray(p, jnp.bfloat16), jnp.asarray(t, jnp.bfloat16)), jnp.float32),
        dtype=np.float64,
    )
    assert np.isfinite(bf16).all(), f"{name}: non-finite under bf16"
    np.testing.assert_allclose(bf16, f32, atol=tol, rtol=0.05, err_msg=name)


@pytest.mark.parametrize("name,fn,p,t,tol", _CASES[:6], ids=[c[0] for c in _CASES[:6]])
def test_bf16_preds_f32_target_mixed(name, fn, p, t, tol):
    """Mixed precision (bf16 model output vs f32 reference) must also work."""
    out = fn(jnp.asarray(p, jnp.bfloat16), jnp.asarray(t))
    assert bool(jnp.isfinite(jnp.asarray(out, jnp.float32)).all()), name
