"""Property tests of the DEFINING invariances in the audio/image metrics.

Scale invariance is what the SI- prefix means; permutation invariance is the
entire point of PIT; SSIM/UQI of an image with itself is 1. These hold by
definition in the reference math and must survive the jax re-design —
hypothesis searches for violations.
"""
import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="property-based tests need the `test` extra (pip install metrics-tpu[test])")
from hypothesis import HealthCheck, given, settings
from hypothesis import strategies as st

import jax.numpy as jnp

from metrics_tpu.ops import (
    permutation_invariant_training,
    scale_invariant_signal_distortion_ratio,
    scale_invariant_signal_noise_ratio,
    structural_similarity_index_measure,
    universal_image_quality_index,
)

SETTINGS = settings(
    max_examples=20,
    deadline=None,
    suppress_health_check=[HealthCheck.too_slow],
    derandomize=True,
)


def _signals(seed, shape=(64,)):
    rng = np.random.default_rng(seed)
    target = rng.normal(size=shape).astype(np.float32)
    preds = target + 0.3 * rng.normal(size=shape).astype(np.float32)
    return jnp.asarray(preds), jnp.asarray(target)


@SETTINGS
@given(seed=st.integers(min_value=0, max_value=10_000), scale=st.floats(min_value=0.05, max_value=20.0))
def test_si_snr_scale_invariance(seed, scale):
    preds, target = _signals(seed)
    base = float(scale_invariant_signal_noise_ratio(preds, target))
    scaled = float(scale_invariant_signal_noise_ratio(preds * scale, target))
    assert scaled == pytest.approx(base, abs=1e-2)


@SETTINGS
@given(seed=st.integers(min_value=0, max_value=10_000), scale=st.floats(min_value=0.05, max_value=20.0))
def test_si_sdr_scale_invariance(seed, scale):
    preds, target = _signals(seed)
    base = float(scale_invariant_signal_distortion_ratio(preds, target))
    scaled = float(scale_invariant_signal_distortion_ratio(preds * scale, target))
    assert scaled == pytest.approx(base, abs=1e-2)


@SETTINGS
@given(seed=st.integers(min_value=0, max_value=10_000))
def test_pit_speaker_permutation_invariance(seed):
    rng = np.random.default_rng(seed)
    target = rng.normal(size=(2, 3, 32)).astype(np.float32)
    preds = target + 0.5 * rng.normal(size=(2, 3, 32)).astype(np.float32)
    best, _ = permutation_invariant_training(
        jnp.asarray(preds), jnp.asarray(target), scale_invariant_signal_noise_ratio
    )
    shuffled = preds[:, [2, 0, 1], :]  # reorder the speaker axis
    best_shuffled, _ = permutation_invariant_training(
        jnp.asarray(shuffled), jnp.asarray(target), scale_invariant_signal_noise_ratio
    )
    np.testing.assert_allclose(np.asarray(best_shuffled), np.asarray(best), atol=1e-4)


@SETTINGS
@given(seed=st.integers(min_value=0, max_value=10_000))
def test_ssim_uqi_identity(seed):
    rng = np.random.default_rng(seed)
    img = jnp.asarray(rng.uniform(size=(1, 1, 24, 24)).astype(np.float32))
    assert float(structural_similarity_index_measure(img, img, data_range=1.0)) == pytest.approx(1.0, abs=1e-5)
    assert float(universal_image_quality_index(img, img)) == pytest.approx(1.0, abs=1e-5)
