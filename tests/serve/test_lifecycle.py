"""Pins the shared HTTP-server lifecycle helper (metrics_tpu.utils.httpd):
bind, port 0, daemon thread, idempotent stop, and the "taken port never
kills a shared-pod job" fallback — implemented once, used by BOTH servers
(the observability scrape server and the ingestion front-end)."""
import socket
from http.server import BaseHTTPRequestHandler

import pytest

import metrics_tpu as mt
from metrics_tpu.observability.server import ObservabilityServer
from metrics_tpu.serve import IngestPipeline, IngestServer
from metrics_tpu.serve import server as _iserver
from metrics_tpu.utils import httpd as _httpd

pytestmark = pytest.mark.network


class _NoopHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802
        self.send_response(204)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def log_message(self, *a):  # noqa: A002
        pass


def _collection():
    return mt.MetricCollection({"mse": mt.MeanSquaredError()})


class TestDaemonHTTPServer:
    def test_port0_binds_ephemeral_and_stop_is_idempotent(self):
        life = _httpd.DaemonHTTPServer(_NoopHandler)
        assert life.port == 0
        life.start()
        try:
            assert life.port > 0
            assert life.url == f"http://127.0.0.1:{life.port}"
            assert life.running
            assert life.start() is life  # idempotent start
        finally:
            life.stop()
            life.stop()  # idempotent stop
        assert not life.running

    def test_taken_port_raises_oserror(self):
        with socket.socket() as blocker:
            blocker.bind(("127.0.0.1", 0))
            taken = blocker.getsockname()[1]
            with pytest.raises(OSError):
                _httpd.DaemonHTTPServer(_NoopHandler, port=taken).start()

    def test_start_with_fallback_degrades_instead_of_raising(self):
        err = OSError(98, "Address already in use")

        def boom():
            raise err

        handle = _httpd.start_with_fallback(boom, lambda e: ("degraded", e))
        assert handle == ("degraded", err)
        with pytest.raises(OSError):
            _httpd.start_with_fallback(boom, None)  # no fallback: propagate

    def test_resolve_port_argument_env_then_zero(self, monkeypatch):
        monkeypatch.setenv("T_PORT", "4242")
        assert _httpd.resolve_port(1234, "T_PORT") == 1234
        assert _httpd.resolve_port(None, "T_PORT") == 4242
        monkeypatch.delenv("T_PORT")
        assert _httpd.resolve_port(None, "T_PORT") == 0


class TestSharedAcrossBothServers:
    def test_both_servers_run_the_same_lifecycle(self):
        """The pin: one lifecycle implementation, two servers on top of it."""
        obs = ObservabilityServer()
        ingest = IngestServer(_collection())
        assert isinstance(obs._life, _httpd.DaemonHTTPServer)
        assert isinstance(ingest._life, _httpd.DaemonHTTPServer)
        obs.start()
        ingest.start()
        try:
            assert obs.running and ingest.running
            assert obs.port != ingest.port
        finally:
            ingest.stop(drain=False)
            obs.stop()
        assert not obs.running and not ingest.running

    def test_serve_singleton_falls_back_to_local_pipeline(self):
        """A taken port degrades the ingest singleton to the in-process
        pipeline (kind 'local') instead of killing the job."""
        with socket.socket() as blocker:
            blocker.bind(("127.0.0.1", 0))
            taken = blocker.getsockname()[1]
            handle = _iserver.serve(_collection(), port=taken, fallback_local=True)
            try:
                assert isinstance(handle, IngestPipeline)
                assert handle.kind == "local"
                assert "failed" in handle.fallback_reason
                # the degraded handle still ingests and serves in-process
                import numpy as np
                adm = handle.post("t0", np.ones((4,), np.float32),
                                  np.zeros((4,), np.float32))
                assert adm.admitted
                assert handle.drain(10.0)
                doc = handle.read("t0", max_staleness_steps=0)
                assert doc["staleness_steps"] == 0
            finally:
                _iserver.shutdown()
        assert _iserver.get_server() is None

    def test_serve_singleton_is_idempotent(self):
        first = _iserver.serve(_collection())
        try:
            assert _iserver.serve() is first  # no template needed on re-entry
        finally:
            _iserver.shutdown()

    def test_serve_needs_a_template_on_first_call(self):
        from metrics_tpu.utils.exceptions import MetricsUserError
        with pytest.raises(MetricsUserError):
            _iserver.serve()
