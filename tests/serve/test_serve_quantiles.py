"""Quantile passthrough on the read endpoint (ISSUE-18 satellite).

``GET /read/<tenant>?quantiles=0.5,0.99`` evaluates arbitrary quantiles from
the tenant's ``QuantileSketch`` states at read time — the sketch holds the
whole (approximate) distribution, so readers are not limited to the ``q`` the
template metric was constructed with.
"""
import urllib.error
import urllib.request

import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu import serve as msv

pytestmark = pytest.mark.network


@pytest.fixture()
def server():
    srv = msv.IngestServer(
        mt.TenantSet(mt.Quantile(q=0.5), capacity=4), queue_capacity=64
    ).start()
    yield srv
    srv.stop(drain=False, timeout=5.0)


def test_read_quantiles_end_to_end(server):
    client = msv.IngestClient(server.url)
    rng = np.random.default_rng(3)
    data = rng.uniform(1.0, 100.0, size=(16, 8)).astype(np.float32)
    for row in data:
        assert client.post_with_retry("t1", row)["admitted"]
    doc = client.read("t1", max_staleness_steps=0, timeout_s=10, quantiles=[0.5, 0.99])
    assert doc["status"] == 200
    flat = data.ravel()
    for q in (0.5, 0.99):
        got = doc["quantiles"]["Quantile"][repr(q)]
        exact = float(np.quantile(flat, q, method="inverted_cdf"))
        assert got == pytest.approx(exact, rel=0.03), q
    # the plain values key is untouched and matches the ctor's q=0.5
    assert doc["values"]["Quantile"] == pytest.approx(
        doc["quantiles"]["Quantile"][repr(0.5)]
    )


def test_read_without_quantiles_has_no_key(server):
    client = msv.IngestClient(server.url)
    rng = np.random.default_rng(4)
    assert client.post_with_retry("t1", rng.uniform(1.0, 2.0, 8).astype(np.float32))["admitted"]
    doc = client.read("t1", max_staleness_steps=0, timeout_s=10)
    assert doc["status"] == 200
    assert "quantiles" not in doc


def test_out_of_range_quantile_is_400(server):
    client = msv.IngestClient(server.url)
    rng = np.random.default_rng(5)
    assert client.post_with_retry("t1", rng.uniform(1.0, 2.0, 8).astype(np.float32))["admitted"]
    assert server.drain(10.0)
    doc = client.read("t1", quantiles=[1.5])
    assert doc["status"] == 400
    assert "quantile" in doc["error"]


def test_malformed_quantiles_is_400(server):
    client = msv.IngestClient(server.url)
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(server.url + "/read/t1?quantiles=abc")
    assert err.value.code == 400


def test_sketchless_template_returns_empty_quantiles():
    srv = msv.IngestServer(
        mt.TenantSet(mt.MeanMetric(), capacity=2), queue_capacity=16
    ).start()
    try:
        client = msv.IngestClient(srv.url)
        assert client.post_with_retry("t", np.asarray([1.0, 2.0], np.float32))["admitted"]
        doc = client.read("t", max_staleness_steps=0, timeout_s=10, quantiles=[0.5])
        assert doc["status"] == 200
        assert doc["quantiles"] == {}
    finally:
        srv.stop(drain=False, timeout=5.0)
