"""Deterministic chaos over the ingestion path: overload, slow consumer, and
mid-request preemption replay bitwise under seeded FaultSpec schedules, and
an admitted batch is NEVER silently dropped — every rejection is surfaced,
every failure is dead-lettered and visible."""
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu import serve as msv
from metrics_tpu.resilience.chaos import KNOWN_SITES, ChaosError, FaultSpec
from metrics_tpu.resilience import chaos as _chaos

pytestmark = pytest.mark.chaos


def _factory():
    return mt.MetricCollection(
        {"acc": mt.Accuracy(num_classes=4), "mse": mt.MeanSquaredError()}
    )


def _run_chaosd_ingest(specs, seed, steps=12, tenants=4):
    """One chaos'd HTTP ingest run; returns (admitted log, final values,
    rejection statuses). The client is sequential, so the serve/ingest spec
    stream ordering — and with it the admitted set — is seed-deterministic."""
    server = msv.IngestServer(_factory(), queue_capacity=64).start()
    try:
        client = msv.IngestClient(server.url)
        rng = np.random.default_rng(seed)
        log, statuses = [], []
        with _chaos.plan(specs, seed=seed):
            for step in range(steps):
                tid = f"t{step % tenants}"
                preds = rng.integers(0, 4, (8,)).astype(np.int32)
                target = rng.integers(0, 4, (8,)).astype(np.int32)
                doc = client.post(tid, preds, target)
                statuses.append((doc["status"], doc.get("reason", "")))
                if doc["admitted"]:
                    log.append((tid, (preds, target), {}))
            assert server.drain(30.0)
        values = {}
        for tid in sorted({t for t, _, _ in log}):
            doc = client.read(tid, max_staleness_steps=0, timeout_s=10)
            assert doc["status"] == 200
            values[tid] = {k: np.asarray(v) for k, v in doc["values"].items()}
        stats = server.stats()
        return log, values, statuses, stats
    finally:
        server.stop(drain=False)


class TestSites:
    def test_serve_sites_are_registered(self):
        for site in ("serve/ingest", "serve/coalesce", "serve/dispatch", "serve/read"):
            assert site in KNOWN_SITES

    def test_unknown_site_still_rejected_by_spec(self):
        with pytest.raises(ValueError):
            FaultSpec("serve/ingest", kind="nope")


class TestIngressFaults:
    def test_ingress_fault_surfaces_as_503_and_state_matches_replay(self):
        """Every 3rd post is killed at admission: the client sees 503
        reason=fault, nothing enters the queue, and the final state is the
        offline replay of exactly the admitted posts."""
        specs = [FaultSpec("serve/ingest", kind="error", every=3, transient=False)]
        log, values, statuses, stats = _run_chaosd_ingest(specs, seed=0)
        faulted = [s for s in statuses if s == (503, "fault")]
        assert len(faulted) == 4  # every 3rd of 12 sequential posts
        assert len(log) == 8
        assert stats["ledger"]["admitted"] == stats["ledger"]["applied"] == 8
        expect = msv.offline_replay(_factory, log)
        for tid, ref in expect.items():
            for name, want in ref.items():
                got = values[tid][name].astype(want.dtype)
                assert np.array_equal(got, want), (tid, name)

    def test_same_seed_replays_bitwise(self):
        specs = [FaultSpec("serve/ingest", kind="error", probability=0.4)]
        a = _run_chaosd_ingest(specs, seed=11)
        b = _run_chaosd_ingest(specs, seed=11)
        assert a[2] == b[2]  # identical rejection pattern
        assert [t for t, _, _ in a[0]] == [t for t, _, _ in b[0]]  # same admitted set
        assert sorted(a[1]) == sorted(b[1])
        for tid in a[1]:
            for name in a[1][tid]:
                assert np.array_equal(a[1][tid][name], b[1][tid][name]), (tid, name)


class TestDispatchFaults:
    def test_transient_dispatch_faults_retry_without_state_loss(self):
        """serve/dispatch fires BEFORE any state moves, so a transient fault
        retried by the consumer is invisible in the final values."""
        specs = [FaultSpec("serve/dispatch", kind="error", every=2, times=3,
                           transient=True)]
        log, values, statuses, stats = _run_chaosd_ingest(specs, seed=3)
        assert all(s == (200, "") for s in statuses)  # ingress untouched
        # how many of the (up to 3) faults fire depends on how arrivals
        # coalesced — but at least one does, and none leaks into the state
        assert 1 <= stats["dispatcher"]["retries"] <= 3
        assert stats["dispatcher"]["dead_letters"] == 0
        assert stats["ledger"]["admitted"] == stats["ledger"]["applied"] == len(log)
        expect = msv.offline_replay(_factory, log)
        for tid, ref in expect.items():
            for name, want in ref.items():
                assert np.array_equal(values[tid][name].astype(want.dtype), want)

    def test_nontransient_dispatch_fault_dead_letters_loudly(self):
        """A permanent apply failure parks the batch on the dead-letter list:
        the ledger accounts for it, healthz degrades, and the tenant's read
        reports the loss — never a silent drop."""
        server = msv.IngestServer(_factory(), queue_capacity=64).start()
        try:
            client = msv.IngestClient(server.url)
            x = np.zeros((8,), np.int32)
            with _chaos.plan([FaultSpec("serve/dispatch", kind="error", nth=1,
                                        transient=False)], seed=0):
                assert client.post("t0", x, x)["admitted"]
                assert server.pipeline.drain(10.0)  # accounted, not applied
            stats = server.stats()
            assert stats["dispatcher"]["dead_letters"] == 1
            assert stats["ledger"]["dead_lettered"] == 1
            assert stats["ledger"]["applied"] == 0
            assert client.healthz()["status"] == "degraded"
            doc = client.read("t0", max_staleness_steps=0, timeout_s=5)
            assert doc["dead_lettered_steps"] == 1
            assert doc["last_applied_step"] == 0
            assert doc["staleness_steps"] == 0  # dead != pending
        finally:
            server.stop(drain=False)


class TestReadFaults:
    def test_read_fault_is_a_retryable_503(self):
        server = msv.IngestServer(_factory()).start()
        try:
            client = msv.IngestClient(server.url)
            x = np.zeros((8,), np.int32)
            client.post("t0", x, x)
            assert server.drain(10.0)
            with _chaos.plan([FaultSpec("serve/read", kind="error", nth=1)], seed=0):
                doc = client.read("t0")
                assert doc["status"] == 503 and doc["reason"] == "fault"
                doc = client.read("t0")  # next read succeeds
                assert doc["status"] == 200
        finally:
            server.stop(drain=False)

    def test_in_process_read_fault_raises(self):
        pipeline = msv.IngestPipeline(_factory()).start()
        try:
            pipeline.post("t0", np.zeros((8,), np.int32), np.zeros((8,), np.int32))
            assert pipeline.drain(10.0)
            with _chaos.plan([FaultSpec("serve/read", kind="error", nth=1)], seed=0):
                with pytest.raises(ChaosError):
                    pipeline.read("t0")
        finally:
            pipeline.stop(drain=False)


class TestSlowConsumerSweep:
    def _sweep_once(self, seed):
        """Slow-consumer chaos: latency at serve/coalesce varies the coalesce
        widths run to run, but the final state depends only on the admitted
        set — the serving stack's core determinism argument."""
        specs = [
            FaultSpec("serve/coalesce", kind="latency", latency_s=0.03,
                      probability=0.5),
            FaultSpec("serve/dispatch", kind="error", every=5, transient=True),
        ]
        return _run_chaosd_ingest(specs, seed=seed, steps=10)

    def test_slow_consumer_quick(self):
        log, values, statuses, stats = self._sweep_once(seed=0)
        assert all(s == (200, "") for s in statuses)
        assert stats["ledger"]["admitted"] == stats["ledger"]["applied"] == len(log)
        expect = msv.offline_replay(_factory, log)
        for tid, ref in expect.items():
            for name, want in ref.items():
                assert np.array_equal(values[tid][name].astype(want.dtype), want)

    @pytest.mark.slow
    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_slow_consumer_three_seed_sweep(self, seed):
        """The 3-seed sweep: under seeded slow-consumer + transient-dispatch
        chaos every admitted batch lands and the state is bitwise the
        offline replay, independent of the timing-dependent coalescing."""
        log, values, statuses, stats = self._sweep_once(seed=seed)
        assert stats["ledger"]["admitted"] == stats["ledger"]["applied"] == len(log)
        assert stats["dispatcher"]["dead_letters"] == 0
        expect = msv.offline_replay(_factory, log)
        for tid, ref in expect.items():
            for name, want in ref.items():
                assert np.array_equal(values[tid][name].astype(want.dtype), want), (
                    seed, tid, name)
