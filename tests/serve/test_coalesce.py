"""Unit tests for the bounded ingest queue and the ragged-arrival coalescer
(no sockets, no dispatcher thread — pure admission/coalescing semantics)."""
import numpy as np
import pytest

from metrics_tpu.serve import BoundedIngestQueue, Observation


def _obs(tid, *shapes):
    return Observation(tid, tuple(np.zeros(s, np.float32) for s in shapes))


class TestAdmission:
    def test_admits_until_capacity_then_rejects_queue_full(self):
        q = BoundedIngestQueue(capacity=3, per_tenant_cap=3)
        for i in range(3):
            adm = q.offer(_obs(f"t{i}", (4,)))
            assert adm.admitted and adm.seq == i + 1
        adm = q.offer(_obs("t3", (4,)))
        assert not adm.admitted
        assert adm.reason == "queue_full"
        assert adm.queue_depth == 3
        assert q.admitted_total == 3 and q.rejected_total == 1

    def test_retry_after_header_is_http_delta_seconds(self):
        q = BoundedIngestQueue(capacity=1, retry_after_s=2.5)
        q.offer(_obs("a", (2,)))
        adm = q.offer(_obs("b", (2,)))
        assert adm.retry_after_s == 2.5
        assert adm.retry_after_header == "3"  # ceil, integer, >= 1
        assert BoundedIngestQueue(capacity=1, retry_after_s=0.1).retry_after_s == 0.1

    def test_per_tenant_cap_is_fairness_not_capacity(self):
        """A hot tenant hits its cap while a cold tenant still gets slots."""
        q = BoundedIngestQueue(capacity=8, per_tenant_cap=2)
        assert q.offer(_obs("hog", (2,))).admitted
        assert q.offer(_obs("hog", (2,))).admitted
        adm = q.offer(_obs("hog", (2,)))
        assert not adm.admitted and adm.reason == "tenant_cap"
        assert q.offer(_obs("cold", (2,))).admitted  # others unaffected

    def test_default_cap_is_quarter_of_capacity(self):
        assert BoundedIngestQueue(capacity=256).per_tenant_cap == 64
        assert BoundedIngestQueue(capacity=2).per_tenant_cap == 1

    def test_close_rejects_draining_and_reopen_admits(self):
        q = BoundedIngestQueue(capacity=4)
        q.close()
        adm = q.offer(_obs("a", (2,)))
        assert not adm.admitted and adm.reason == "draining"
        q.reopen()
        assert q.offer(_obs("a", (2,))).admitted

    def test_invalid_bounds_raise(self):
        with pytest.raises(ValueError):
            BoundedIngestQueue(capacity=0)
        with pytest.raises(ValueError):
            BoundedIngestQueue(capacity=4, per_tenant_cap=0)


class TestCoalesce:
    def test_distinct_tenants_one_batch(self):
        q = BoundedIngestQueue(capacity=16)
        for tid in ("a", "b", "c"):
            q.offer(_obs(tid, (4,)))
        batch = q.pop_coalesced(max_width=8, timeout=0.1)
        assert [o.tenant_id for o in batch] == ["a", "b", "c"]
        assert len(q) == 0

    def test_same_tenant_second_obs_waits_for_next_batch(self):
        """FIFO per tenant: a duplicate tenant never joins the same batch
        (the stacked scatter would be undefined)."""
        q = BoundedIngestQueue(capacity=16)
        for tid in ("a", "b", "a", "c", "a"):
            q.offer(_obs(tid, (4,)))
        first = q.pop_coalesced(max_width=8, timeout=0.1)
        assert [o.tenant_id for o in first] == ["a", "b", "c"]
        second = q.pop_coalesced(max_width=8, timeout=0.1)
        assert [o.tenant_id for o in second] == ["a"]
        third = q.pop_coalesced(max_width=8, timeout=0.1)
        assert [o.tenant_id for o in third] == ["a"]
        assert q.pop_coalesced(timeout=0.01) is None

    def test_signature_split_keeps_shapes_separate(self):
        """Mixed arrival shapes coalesce per-signature, FIFO-respecting."""
        q = BoundedIngestQueue(capacity=16)
        q.offer(_obs("a", (4,)))
        q.offer(_obs("b", (8,)))   # different shape: next signature group
        q.offer(_obs("c", (4,)))
        first = q.pop_coalesced(max_width=8, timeout=0.1)
        assert [o.tenant_id for o in first] == ["a", "c"]
        second = q.pop_coalesced(max_width=8, timeout=0.1)
        assert [o.tenant_id for o in second] == ["b"]

    def test_static_config_participates_in_signature(self):
        q = BoundedIngestQueue(capacity=16)
        q.offer(Observation("a", (np.zeros(2, np.float32),), {"gain": 2.0}))
        q.offer(Observation("b", (np.zeros(2, np.float32),), {"gain": 3.0}))
        batch = q.pop_coalesced(timeout=0.1)
        assert [o.tenant_id for o in batch] == ["a"]  # gain repr differs

    def test_max_width_caps_the_batch(self):
        q = BoundedIngestQueue(capacity=64)
        for i in range(10):
            q.offer(_obs(f"t{i}", (2,)))
        batch = q.pop_coalesced(max_width=4, timeout=0.1)
        assert len(batch) == 4
        assert len(q) == 6

    def test_per_tenant_depth_released_on_pop(self):
        q = BoundedIngestQueue(capacity=8, per_tenant_cap=1)
        q.offer(_obs("a", (2,)))
        assert not q.offer(_obs("a", (2,))).admitted
        q.pop_coalesced(timeout=0.1)
        assert q.tenant_depth("a") == 0
        assert q.offer(_obs("a", (2,))).admitted  # slot freed

    def test_empty_timeout_returns_none(self):
        q = BoundedIngestQueue(capacity=4)
        assert q.pop_coalesced(timeout=0.01) is None

    def test_closed_and_drained_returns_none(self):
        q = BoundedIngestQueue(capacity=4)
        q.offer(_obs("a", (2,)))
        q.close()
        assert q.pop_coalesced(timeout=0.1) is not None  # drains the backlog
        assert q.pop_coalesced(timeout=0.1) is None      # then signals done

    def test_wait_empty(self):
        q = BoundedIngestQueue(capacity=4)
        q.offer(_obs("a", (2,)))
        assert not q.wait_empty(timeout=0.05)
        q.pop_coalesced(timeout=0.1)
        assert q.wait_empty(timeout=0.05)
