"""End-to-end tests of the ingestion front-end over real loopback HTTP:
bitwise parity with the offline replay oracle, zero steady-state recompiles,
backpressure (429 + Retry-After), per-tenant fairness, tenant capacity,
staleness-bounded reads, and graceful drain."""
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu import serve as msv
from metrics_tpu.observability.instruments import REGISTRY

pytestmark = pytest.mark.network


def _factory():
    return mt.MetricCollection(
        {"acc": mt.Accuracy(num_classes=4), "mse": mt.MeanSquaredError()}
    )


def _post_batches(client, rng, tenants, steps, log):
    """Ragged arrivals: each step posts a random subset of the tenants."""
    for _ in range(steps):
        k = int(rng.integers(1, len(tenants) + 1))
        for t in sorted(rng.choice(len(tenants), size=k, replace=False)):
            preds = rng.integers(0, 4, (8,)).astype(np.int32)
            target = rng.integers(0, 4, (8,)).astype(np.int32)
            doc = client.post(tenants[t], preds, target)
            assert doc["admitted"], doc
            log.append((tenants[t], (preds, target), {}))


class TestEndToEnd:
    def test_ragged_http_ingest_matches_offline_replay_bitwise(self):
        """The acceptance property: ragged per-tenant batches over HTTP,
        coalesced and stacked on the device, must be bitwise-equal to the
        pure per-tenant offline replay — with zero steady-state recompiles
        (stable_hits monotone, partition builds == 1)."""
        server = msv.IngestServer(_factory(), queue_capacity=256).start()
        try:
            client = msv.IngestClient(server.url)
            tenants = [f"tenant-{i}" for i in range(7)]
            rng = np.random.default_rng(42)
            log = []
            _post_batches(client, rng, tenants, steps=4, log=log)
            assert server.drain(30.0)
            warm_compiles = server.stats()["tenant_set"]["compiles"]
            _post_batches(client, rng, tenants, steps=8, log=log)
            assert server.drain(30.0)
            stats = server.stats()
            # 0 recompiles after warmup: pow2 bucketing absorbs raggedness
            assert stats["tenant_set"]["compiles"] == warm_compiles
            assert stats["tenant_set"]["partition_builds"] == 1
            assert stats["tenant_set"]["partition_stable_hits"] >= stats["dispatcher"]["dispatches"]
            assert stats["ledger"]["admitted"] == stats["ledger"]["applied"] == len(log)
            assert stats["dispatcher"]["dead_letters"] == 0

            expect = msv.offline_replay(_factory, log)
            for tid, ref in expect.items():
                doc = client.read(tid, max_staleness_steps=0, timeout_s=10)
                assert doc["status"] == 200
                assert doc["staleness_steps"] == 0
                for name, want in ref.items():
                    got = np.asarray(doc["values"][name], dtype=want.dtype)
                    assert np.array_equal(got, want), (tid, name)
        finally:
            server.stop(drain=False)

    def test_json_body_reaches_the_same_state_as_npz(self):
        results = {}
        for encoding in ("npz", "json"):
            server = msv.IngestServer(_factory()).start()
            try:
                client = msv.IngestClient(server.url)
                preds = np.asarray([1, 2, 3, 0], np.int32)
                target = np.asarray([1, 1, 3, 2], np.int32)
                doc = client.post("t0", preds, target, encoding=encoding)
                assert doc["admitted"], doc
                assert server.drain(10.0)
                read = client.read("t0", max_staleness_steps=0)
                results[encoding] = read["values"]
            finally:
                server.stop(drain=False)
        # JSON ints decode as int64 vs npz int32 — values must still agree
        for name in results["npz"]:
            assert np.allclose(results["npz"][name], results["json"][name])

    def test_read_echoes_the_staleness_contract(self):
        server = msv.IngestServer(_factory()).start()
        try:
            client = msv.IngestClient(server.url)
            preds = np.zeros((4,), np.int32)
            client.post("t0", preds, preds)
            doc = client.read("t0", max_staleness_steps=0, timeout_s=10)
            assert doc["last_applied_step"] == 1
            assert doc["admitted_steps"] == 1
            assert doc["staleness_steps"] == 0
            assert doc["dead_lettered_steps"] == 0
            assert doc["max_staleness_steps"] == 0
        finally:
            server.stop(drain=False)

    def test_unknown_tenant_reads_404(self):
        server = msv.IngestServer(_factory()).start()
        try:
            assert msv.IngestClient(server.url).read("ghost")["status"] == 404
        finally:
            server.stop(drain=False)


class TestBackpressure:
    """Admission control with the consumer deliberately NOT running, so the
    queue state is exact — no race against the dispatcher draining it."""

    def _stalled_server(self, **kw):
        server = msv.IngestServer(_factory(), **kw)
        server._life.start()  # HTTP up; dispatcher intentionally not started
        return server

    def test_full_queue_answers_429_with_retry_after(self):
        server = self._stalled_server(queue_capacity=3, retry_after_s=2.0)
        try:
            client = msv.IngestClient(server.url)
            x = np.zeros((4,), np.int32)
            for i in range(3):
                assert client.post(f"t{i}", x, x)["admitted"]
            doc = client.post("t3", x, x)
            assert doc["status"] == 429
            assert doc["reason"] == "queue_full"
            assert doc["retry_after_s"] == 2.0  # the Retry-After header
            assert server.stats()["queue"]["rejected_total"] == 1
        finally:
            server.stop(drain=False, timeout=1.0)

    def test_per_tenant_fairness_cap_shields_cold_tenants(self):
        server = self._stalled_server(queue_capacity=8, per_tenant_cap=2)
        try:
            client = msv.IngestClient(server.url)
            x = np.zeros((4,), np.int32)
            assert client.post("hog", x, x)["admitted"]
            assert client.post("hog", x, x)["admitted"]
            doc = client.post("hog", x, x)
            assert doc["status"] == 429 and doc["reason"] == "tenant_cap"
            assert "retry_after_s" in doc
            assert client.post("cold", x, x)["admitted"]  # fairness
        finally:
            server.stop(drain=False, timeout=1.0)

    def test_tenant_set_capacity_rejects_new_tenants(self):
        ts = mt.TenantSet(_factory(), capacity=2)
        server = self._stalled_server()
        server.pipeline.tenant_set = ts
        try:
            client = msv.IngestClient(server.url)
            x = np.zeros((4,), np.int32)
            assert client.post("t0", x, x)["admitted"]
            assert client.post("t1", x, x)["admitted"]
            doc = client.post("t2", x, x)
            assert doc["status"] == 429 and doc["reason"] == "tenant_capacity"
            # known tenants still ingest
            assert client.post("t0", x, x)["admitted"]
        finally:
            server.stop(drain=False, timeout=1.0)

    def test_stalled_consumer_misses_the_read_deadline(self):
        server = self._stalled_server()
        try:
            client = msv.IngestClient(server.url)
            x = np.zeros((4,), np.int32)
            assert client.post("t0", x, x)["admitted"]
            doc = client.read("t0", max_staleness_steps=0, timeout_s=0.2)
            assert doc["status"] == 503
            assert doc["reason"] == "deadline_missed"
            assert doc["staleness_steps"] == 1
            assert "retry_after_s" in doc
            missed = [
                s for s in REGISTRY.samples()
                if s.name == "metrics_tpu_ingest_deadline_missed_total"
            ]
            assert missed and missed[0].value == 1.0
            # an unbounded read answers immediately with the stale echo
            doc = client.read("t0")
            assert doc["status"] == 200 and doc["staleness_steps"] == 1
            assert doc["values"] is None  # nothing materialized on device yet
        finally:
            server.stop(drain=False, timeout=1.0)

    def test_retry_loop_eventually_lands_when_consumer_resumes(self):
        server = self._stalled_server(queue_capacity=1, retry_after_s=0.02)
        try:
            client = msv.IngestClient(server.url)
            x = np.zeros((4,), np.int32)
            assert client.post("t0", x, x)["admitted"]
            assert client.post("t1", x, x)["status"] == 429
            server.pipeline.start()  # consumer comes alive
            doc = client.post_with_retry("t1", x, x, max_attempts=50)
            assert doc["admitted"], doc
        finally:
            server.stop(drain=False, timeout=2.0)


class TestBatchedIngest:
    """One request, many steps: ``application/x-npz`` bodies with a leading
    step axis are sliced back into per-step observations and admitted in
    order, so ``offline_replay`` stays the bitwise oracle."""

    def test_batched_post_matches_offline_replay_bitwise(self):
        server = msv.IngestServer(_factory(), queue_capacity=256).start()
        try:
            client = msv.IngestClient(server.url)
            rng = np.random.default_rng(11)
            steps = 6
            preds = rng.integers(0, 4, (steps, 8)).astype(np.int32)
            target = rng.integers(0, 4, (steps, 8)).astype(np.int32)
            doc = client.post_steps("t0", preds, target)
            assert doc["admitted"], doc
            assert doc["steps"] == steps
            assert doc["admitted_steps"] == steps
            assert doc["seqs"] == sorted(doc["seqs"]) and len(doc["seqs"]) == steps
            assert doc["seq"] == doc["seqs"][-1]
            assert server.drain(30.0)

            log = [("t0", (preds[i], target[i]), {}) for i in range(steps)]
            expect = msv.offline_replay(_factory, log)
            read = client.read("t0", max_staleness_steps=0, timeout_s=10)
            assert read["last_applied_step"] == steps
            for name, want in expect["t0"].items():
                got = np.asarray(read["values"][name], dtype=want.dtype)
                assert np.array_equal(got, want), name
        finally:
            server.stop(drain=False)

    def test_batched_and_single_posts_reach_the_same_state(self):
        rng = np.random.default_rng(13)
        steps = 4
        preds = rng.integers(0, 4, (steps, 8)).astype(np.int32)
        target = rng.integers(0, 4, (steps, 8)).astype(np.int32)
        results = {}
        for mode in ("single", "batched"):
            server = msv.IngestServer(_factory()).start()
            try:
                client = msv.IngestClient(server.url)
                if mode == "single":
                    for i in range(steps):
                        doc = client.post("t0", preds[i], target[i])
                        assert doc["admitted"], doc
                        assert "steps" not in doc  # single-step shape unchanged
                else:
                    assert client.post_steps("t0", preds, target)["admitted"]
                assert server.drain(10.0)
                results[mode] = client.read("t0", max_staleness_steps=0)["values"]
            finally:
                server.stop(drain=False)
        for name in results["single"]:
            a = np.asarray(results["single"][name])
            b = np.asarray(results["batched"][name], dtype=a.dtype)
            assert np.array_equal(a, b), name

    def test_partial_rejection_reports_the_admitted_prefix(self):
        server = msv.IngestServer(
            _factory(), queue_capacity=2, per_tenant_cap=64, retry_after_s=1.5)
        server._life.start()  # HTTP up; dispatcher intentionally not started
        try:
            client = msv.IngestClient(server.url)
            x = np.zeros((5, 4), np.int32)
            doc = client.post_steps("t0", x, x)
            assert doc["status"] == 429 and doc["reason"] == "queue_full"
            assert doc["steps"] == 5
            assert doc["admitted_steps"] == 2
            assert len(doc["seqs"]) == 2
            assert doc["retry_after_s"] > 0
        finally:
            server.stop(drain=False, timeout=1.0)

    def test_batched_post_during_drain_is_rejected_loudly(self):
        server = msv.IngestServer(_factory()).start()
        try:
            client = msv.IngestClient(server.url)
            server.pipeline.queue.close()
            x = np.zeros((3, 4), np.int32)
            doc = client.post_steps("t0", x, x)
            assert doc["status"] == 503 and doc["reason"] == "draining"
            assert doc["steps"] == 3 and doc["admitted_steps"] == 0
        finally:
            server.stop(drain=False, timeout=2.0)

    def test_malformed_batched_bodies_answer_400(self):
        import io
        import urllib.request

        server = msv.IngestServer(_factory()).start()
        try:
            # client-side validation refuses mismatched leading axes outright
            with pytest.raises(ValueError, match="leading step axis"):
                msv.encode_npz_steps(np.zeros((3, 4)), np.zeros((2, 4)))
            with pytest.raises(ValueError, match="at least one array"):
                msv.encode_npz_steps()
            # a hand-crafted body lying about its step count answers 400
            buf = io.BytesIO()
            np.savez(buf, __steps__=np.asarray(3, np.int64),
                     arg0=np.zeros((2, 4), np.int32))
            req = urllib.request.Request(
                f"{server.url}/ingest/t0", data=buf.getvalue(),
                headers={"Content-Type": "application/x-npz"}, method="POST")
            import urllib.error
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=10)
            assert exc.value.code == 400
            assert "leading step axis" in exc.value.read().decode()
        finally:
            server.stop(drain=False)

    def test_decode_steps_single_body_passthrough(self):
        body = msv.encode_npz(np.arange(4), kw=np.ones(2))
        steps, batched = msv.decode_steps("application/x-npz", body)
        assert not batched and len(steps) == 1
        (args, kwargs), = steps
        assert np.array_equal(args[0], np.arange(4))
        assert np.array_equal(kwargs["kw"], np.ones(2))
        body = msv.encode_npz_steps(np.arange(6).reshape(3, 2))
        steps, batched = msv.decode_steps("application/x-npz", body)
        assert batched and len(steps) == 3
        assert np.array_equal(steps[2][0][0], np.asarray([4, 5]))


class TestGracefulDrain:
    def test_drain_applies_every_admitted_batch(self):
        server = msv.IngestServer(_factory(), queue_capacity=256).start()
        client = msv.IngestClient(server.url)
        rng = np.random.default_rng(7)
        log = []
        _post_batches(client, rng, [f"t{i}" for i in range(5)], steps=6, log=log)
        posted = len(log)
        # posts during the drain are rejected loudly, not dropped quietly
        server.pipeline.queue.close()
        x = np.zeros((8,), np.int32)
        doc = client.post("t0", x, x)
        assert doc["status"] == 503 and doc["reason"] == "draining"
        assert "retry_after_s" in doc
        assert server.stop(drain=True, timeout=30.0)
        ledger = server.pipeline.stats()["ledger"]
        assert ledger["admitted"] == ledger["applied"] == posted
        assert ledger["dead_lettered"] == 0
        # the pipeline stays readable after the socket is gone
        per_tenant = {}
        for tid, _, _ in log:
            per_tenant[tid] = per_tenant.get(tid, 0) + 1
        for tid, n in per_tenant.items():
            doc = server.pipeline.read(tid, max_staleness_steps=0, timeout_s=1.0)
            assert doc["last_applied_step"] == n
