"""Serving suite hygiene: the ingest singleton, the chaos plan, the tracer,
and the instrument registry are process-global — every test leaves them the
way it found them (server drained and stopped, harness disarmed, tracing
off, registry cleared)."""
import pytest

from metrics_tpu.observability import tracer as _otrace
from metrics_tpu.observability.instruments import REGISTRY
from metrics_tpu.resilience import chaos as _chaos
from metrics_tpu.serve import server as _iserver


@pytest.fixture(autouse=True)
def _pristine_serve_globals():
    yield
    _chaos.uninstall()
    _iserver.shutdown(drain=False, timeout=5.0)
    _otrace.disable()
    tracer = _otrace.get_tracer()
    if tracer is not None:
        tracer.clear()
    REGISTRY.clear()
