"""The serving stack observes itself: every catalogued serve/* event is
emitted by a live scenario, and the metrics_tpu_ingest_* instrument series
land in the registry (and therefore in the Prometheus exposition)."""
import numpy as np
import pytest

import metrics_tpu as mt
from metrics_tpu import observability as obs
from metrics_tpu import serve as msv
from metrics_tpu.observability.instruments import REGISTRY, InstrumentRegistry
from metrics_tpu.observability.tracer import EVENT_CATALOG
from metrics_tpu.resilience import chaos as _chaos
from metrics_tpu.resilience.chaos import FaultSpec


def _factory():
    return mt.MetricCollection({"mse": mt.MeanSquaredError()})


class TestEventCatalog:
    def test_serve_category_is_catalogued(self):
        assert set(EVENT_CATALOG["serve"]) == {
            "serve/ingest", "serve/reject", "serve/coalesce", "serve/dispatch",
            "serve/read", "serve/drain", "serve/dead_letter",
        }

    def test_every_catalogued_serve_event_is_emitted_live(self):
        """One scenario per catalogue entry: admit, reject (full queue),
        coalesce, dispatch, read, drain, dead-letter (injected apply fault)."""
        x = np.ones((4,), np.float32)
        with obs.trace() as tracer:
            pipeline = msv.IngestPipeline(_factory(), queue_capacity=1,
                                          name="obs-test")
            assert pipeline.post("a", x, x).admitted      # serve/ingest
            assert not pipeline.post("b", x, x).admitted  # serve/reject
            pipeline.start()                              # coalesce+dispatch
            assert pipeline.drain(10.0)                   # serve/drain
            pipeline.read("a", max_staleness_steps=0)     # serve/read
            with _chaos.plan([FaultSpec("serve/dispatch", kind="error",
                                        transient=False)], seed=0):
                assert pipeline.post("a", x, x).admitted
                assert pipeline.drain(10.0)               # serve/dead_letter
            pipeline.stop(drain=False)
        counts = tracer.counts_by_name()
        for name in EVENT_CATALOG["serve"]:
            assert counts.get(name, 0) >= 1, name
        # and nothing emitted off-catalogue
        flat = {n for names in EVENT_CATALOG.values() for n in names}
        served = [e for e in tracer.events() if e.cat == "serve"]
        assert served and all(e.name in flat for e in served)

    def test_event_payloads_carry_the_load_bearing_args(self):
        x = np.ones((4,), np.float32)
        with obs.trace() as tracer:
            pipeline = msv.IngestPipeline(_factory()).start()
            pipeline.post("a", x, x)
            assert pipeline.drain(10.0)
            pipeline.read("a", max_staleness_steps=0)
            pipeline.stop(drain=False)
        events = {e.name: e for e in tracer.events()}
        assert events["serve/ingest"].args["seq"] == 1
        assert events["serve/coalesce"].args["width"] == 1
        assert events["serve/dispatch"].args["tenants"] == 1
        assert events["serve/read"].args["staleness"] == 0


class TestIngestInstruments:
    def test_pipeline_gauges_and_counters_land_in_snapshots(self):
        reg = InstrumentRegistry()
        pipeline = msv.IngestPipeline(_factory(), queue_capacity=8,
                                      name="snap-test")
        reg.register_ingest_pipeline(pipeline)
        x = np.ones((4,), np.float32)
        pipeline.post("a", x, x)
        by_name = {s.name: s for s in reg.samples()
                   if s.labels.get("queue") == "snap-test"}
        assert by_name["metrics_tpu_ingest_queue_depth"].value == 1.0
        assert by_name["metrics_tpu_ingest_queue_capacity"].value == 8.0
        assert by_name["metrics_tpu_ingest_draining"].value == 0.0
        assert by_name["metrics_tpu_ingest_dispatch_observations_total"].value == 0.0
        pipeline.start()
        assert pipeline.drain(10.0)
        pipeline.stop(drain=False)
        by_name = {s.name: s for s in reg.samples()
                   if s.labels.get("queue") == "snap-test"}
        assert by_name["metrics_tpu_ingest_queue_depth"].value == 0.0
        assert by_name["metrics_tpu_ingest_dispatch_observations_total"].value == 1.0
        assert by_name["metrics_tpu_ingest_last_coalesce_width"].value == 1.0
        assert by_name["metrics_tpu_ingest_draining"].value == 1.0

    def test_admission_counters_tick_on_the_global_registry(self):
        pipeline = msv.IngestPipeline(_factory(), queue_capacity=1,
                                      per_tenant_cap=1, name="adm-test")
        x = np.ones((4,), np.float32)
        assert pipeline.post("a", x, x).admitted
        assert not pipeline.post("b", x, x).admitted  # queue_full
        samples = {(s.name, s.labels.get("reason", "")): s.value
                   for s in REGISTRY.samples() if s.labels.get("queue") == "adm-test"}
        assert samples[("metrics_tpu_ingest_admitted_total", "")] == 1.0
        assert samples[("metrics_tpu_ingest_rejected_total", "queue_full")] == 1.0

    def test_coalesce_width_histogram_observes_pow2_bins(self):
        pipeline = msv.IngestPipeline(_factory(), name="hist-test")
        x = np.ones((4,), np.float32)
        for tid in ("a", "b", "c"):
            pipeline.post(tid, x, x)
        batch = pipeline.queue.pop_coalesced(max_width=8, timeout=0.5)
        assert len(batch) == 3
        hist = [s for s in REGISTRY.samples()
                if s.name == "metrics_tpu_ingest_coalesce_width_bucket"
                and s.labels.get("queue") == "hist-test"]
        # cumulative: the width-3 observation lands in the le=4 pow2 bin
        by_le = {s.labels["le"]: s.value for s in hist}
        assert by_le["2.0"] == 0.0 and by_le["4.0"] == 1.0

    def test_registry_clear_drops_pipeline_registrations(self):
        reg = InstrumentRegistry()
        pipeline = msv.IngestPipeline(_factory(), name="clear-test")
        reg.register_ingest_pipeline(pipeline)
        assert reg.live_ingest_pipelines() == [pipeline]
        reg.clear()
        assert reg.live_ingest_pipelines() == []
