"""Factory inventory covering EVERY exported class metric.

Reference analog: the reference test suite torch-scripts every class metric
inside every test run (tests/helpers/testers.py:163-176) — its guarantee that
no class silently falls off the compiled path. Here the analogous guarantee is
explicit: each entry pins a ``compile_level`` stating exactly how far that
metric participates in jit/shard_map compilation, and
tests/core/test_compile_sweep.py enforces it against the live class.

compile_level semantics:

- ``"full"``: ``update_state`` -> ``sync_states`` -> ``compute_state`` runs as
  ONE traced program under shard_map over the 8-device CPU mesh, and the
  result matches the eager sequential oracle.
- ``"update_sync"``: update+sync trace (fixed-shape states), but ``compute``
  needs host-side work (dynamic output shapes, python grouping) and runs
  eagerly on the synced state.
- ``"buffered"``: default construction has unbounded list states (eager-only);
  the ``buffered`` factory (buffer_capacity=N) is the compiled variant and is
  tested at the level given by ``buffered_level``.
- ``"eager_only"``: states stay unbounded lists by design (e.g. per-image
  variable-count detection lists); compiled update is asserted to be
  unsupported via ``supports_compiled_update == False``.
- ``"host"``: update consumes python objects (strings, dicts, token lists) —
  tracing does not apply; the class is asserted functional end-to-end eagerly.

Inputs are deterministic (module-level seeded rng) so the shard-vs-sequential
oracle comparison is exact.
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax.numpy as jnp
import numpy as np

import metrics_tpu as M

_rng = np.random.default_rng(1234)

# ---------------------------------------------------------------- fixtures --
N = 24  # divisible by 8 for even shard splits
C = 4

_PROBS = jnp.asarray(_rng.dirichlet(np.ones(C), size=N).astype(np.float32))  # (N, C)
_LABELS = jnp.asarray(_rng.integers(0, C, N))
_BIN_PROBS = jnp.asarray(_rng.random(N).astype(np.float32))
_BIN_LABELS = jnp.asarray(_rng.integers(0, 2, N))
_ML_PROBS = jnp.asarray(_rng.random((N, C)).astype(np.float32))
_ML_LABELS = jnp.asarray(_rng.integers(0, 2, (N, C)))
_LOGITS = jnp.asarray(_rng.normal(size=(N, C)).astype(np.float32))
_REG_P = jnp.asarray(_rng.random(N).astype(np.float32) + 0.1)
_REG_T = jnp.asarray(_rng.random(N).astype(np.float32) + 0.1)
_REG_P2 = jnp.asarray(_rng.random((N, 2)).astype(np.float32) + 0.1)
_REG_T2 = jnp.asarray(_rng.random((N, 2)).astype(np.float32) + 0.1)
_IMG_P = jnp.asarray(_rng.random((8, 3, 16, 16)).astype(np.float32))
_IMG_T = jnp.asarray(_rng.random((8, 3, 16, 16)).astype(np.float32))
_BIG_P = jnp.asarray(_rng.random((8, 1, 192, 192)).astype(np.float32))
_BIG_T = 0.8 * _BIG_P + 0.2 * jnp.asarray(_rng.random((8, 1, 192, 192)).astype(np.float32))
_AUD_T = jnp.asarray(_rng.normal(size=(8, 2000)).astype(np.float32))
_AUD_P = _AUD_T + 0.3 * jnp.asarray(_rng.normal(size=(8, 2000)).astype(np.float32))
_MIX_T = jnp.asarray(_rng.normal(size=(8, 2, 1200)).astype(np.float32))
_MIX_P = _MIX_T[:, ::-1] + 0.2 * jnp.asarray(_rng.normal(size=(8, 2, 1200)).astype(np.float32))
# long enough that every shard clears STOI's 30-frame segment window even
# after silent-frame removal shortens the overlap-add reconstruction
_STOI_T = jnp.asarray(_rng.normal(size=(8, 8000)).astype(np.float32))
_STOI_P = _STOI_T + 0.2 * jnp.asarray(_rng.normal(size=(8, 8000)).astype(np.float32))
_RET_P = jnp.asarray(_rng.random(N).astype(np.float32))
_RET_T = jnp.asarray(_rng.integers(0, 2, N))
_RET_IDX = jnp.asarray(np.sort(_rng.integers(0, 4, N)))

_FEAT_D = 6


class _StubFeatures:
    """Deterministic ``imgs -> (N, d)`` projection standing in for InceptionV3."""

    num_features = _FEAT_D

    def __init__(self, in_dim: int = 3 * 16 * 16) -> None:
        self.w = jnp.asarray(_rng.normal(size=(in_dim, _FEAT_D)).astype(np.float32) / np.sqrt(in_dim))

    def __call__(self, imgs):
        return imgs.reshape(imgs.shape[0], -1) @ self.w


class _StubLPIPSNet:
    """Callable ``(img1, img2) -> (N,)`` distance standing in for LPIPS trunks."""

    def __call__(self, a, b):
        return jnp.mean((a - b) ** 2, axis=(1, 2, 3))


# single shared instances: the sweep compares a sharded run against a fresh
# eager oracle instance, so the projection weights must be identical
_STUB_FEATURES = _StubFeatures()
_STUB_LPIPS = _StubLPIPSNet()


class Entry(NamedTuple):
    make: Callable[[], Any]
    # returns ONE (update_args, static_kwargs) pair, or a LIST of such pairs
    # (multi-call updates, e.g. FID/KID real+fake); arrays traced, kwargs static
    batch: Callable[[], Any]
    compile_level: str  # full | update_sync | buffered | eager_only | host
    buffered: Optional[Callable[[], Any]] = None
    buffered_level: str = "full"
    skip: Optional[str] = None  # gated optional dependency


def _b(*args, **kwargs):
    return lambda: (args, kwargs)


TEXT_PREDS = ["the cat sat on the mat", "a quick brown fox"]
TEXT_TARGETS = [["there is a cat on the mat"], ["a fast brown fox jumps"]]
TEXT_TARGETS_FLAT = ["there is a cat on the mat", "a fast brown fox jumps"]

_DET_PREDS = [
    dict(
        boxes=jnp.asarray([[10.0, 10.0, 50.0, 50.0], [20.0, 20.0, 60.0, 60.0]]),
        scores=jnp.asarray([0.9, 0.4]),
        labels=jnp.asarray([0, 1]),
    )
]
_DET_TARGET = [
    dict(
        boxes=jnp.asarray([[12.0, 12.0, 52.0, 52.0]]),
        labels=jnp.asarray([0]),
    )
]


INVENTORY = {
    # ------------------------------------------------------- aggregation ----
    "MeanMetric": Entry(lambda: M.MeanMetric(), _b(_REG_P), "full"),
    "SumMetric": Entry(lambda: M.SumMetric(), _b(_REG_P), "full"),
    "MaxMetric": Entry(lambda: M.MaxMetric(), _b(_REG_P), "full"),
    "MinMetric": Entry(lambda: M.MinMetric(), _b(_REG_P), "full"),
    "CatMetric": Entry(
        lambda: M.CatMetric(), _b(_REG_P), "buffered",
        buffered=lambda: M.CatMetric(buffer_capacity=256), buffered_level="update_sync",
    ),
    "Quantile": Entry(lambda: M.Quantile(q=0.5), _b(_REG_P), "full"),
    "Median": Entry(lambda: M.Median(), _b(_REG_P), "full"),
    "DistinctCount": Entry(lambda: M.DistinctCount(), _b(_LABELS), "full"),
    # heavy-hitter extraction is a host-side dyadic descent (compiled_compute=False)
    "HeavyHitters": Entry(
        lambda: M.HeavyHitters(threshold=0.05, max_hitters=4), _b(_LABELS), "update_sync",
    ),
    # ---------------------------------------------------- classification ----
    "Accuracy": Entry(lambda: M.Accuracy(num_classes=C), _b(_PROBS, _LABELS), "full"),
    "AUC": Entry(
        lambda: M.AUC(reorder=True), _b(_REG_P, _REG_T), "buffered",
        # AUC compute sorts a dynamic concat -> static with CatBuffer capacity
        buffered=lambda: M.AUC(reorder=True, buffer_capacity=256), buffered_level="update_sync",
    ),
    "AUROC": Entry(
        lambda: M.AUROC(num_classes=C), _b(_PROBS, _LABELS), "buffered",
        buffered=lambda: M.AUROC(num_classes=C, buffer_capacity=256), buffered_level="update_sync",
    ),
    "AveragePrecision": Entry(
        lambda: M.AveragePrecision(num_classes=C), _b(_PROBS, _LABELS), "buffered",
        buffered=lambda: M.AveragePrecision(num_classes=C, buffer_capacity=256),
        buffered_level="update_sync",  # AP curve has data-dependent thresholds
    ),
    "BinnedAveragePrecision": Entry(
        lambda: M.BinnedAveragePrecision(num_classes=C, thresholds=21), _b(_PROBS, _LABELS), "full",
    ),
    "BinnedPrecisionRecallCurve": Entry(
        lambda: M.BinnedPrecisionRecallCurve(num_classes=C, thresholds=21), _b(_PROBS, _LABELS), "full",
    ),
    "BinnedRecallAtFixedPrecision": Entry(
        lambda: M.BinnedRecallAtFixedPrecision(num_classes=C, min_precision=0.5, thresholds=21),
        _b(_PROBS, _LABELS), "full",
    ),
    "CalibrationError": Entry(
        lambda: M.CalibrationError(n_bins=10), _b(_BIN_PROBS, _BIN_LABELS), "buffered",
        buffered=lambda: M.CalibrationError(n_bins=10, buffer_capacity=256), buffered_level="update_sync",
    ),
    "CohenKappa": Entry(lambda: M.CohenKappa(num_classes=C), _b(_PROBS, _LABELS), "full"),
    "ConfusionMatrix": Entry(lambda: M.ConfusionMatrix(num_classes=C), _b(_PROBS, _LABELS), "full"),
    "Dice": Entry(lambda: M.Dice(num_classes=C, multiclass=True), _b(_PROBS, _LABELS), "full"),
    "F1Score": Entry(lambda: M.F1Score(num_classes=C), _b(_PROBS, _LABELS), "full"),
    "FBetaScore": Entry(lambda: M.FBetaScore(num_classes=C, beta=2.0), _b(_PROBS, _LABELS), "full"),
    "HammingDistance": Entry(lambda: M.HammingDistance(), _b(_ML_PROBS, _ML_LABELS), "full"),
    "HingeLoss": Entry(lambda: M.HingeLoss(multiclass_mode="crammer-singer"), _b(_LOGITS, _LABELS), "full"),
    "JaccardIndex": Entry(lambda: M.JaccardIndex(num_classes=C), _b(_PROBS, _LABELS), "full"),
    "KLDivergence": Entry(lambda: M.KLDivergence(), _b(_PROBS, _PROBS[::-1]), "full"),
    "MatthewsCorrCoef": Entry(lambda: M.MatthewsCorrCoef(num_classes=C), _b(_PROBS, _LABELS), "full"),
    "Precision": Entry(lambda: M.Precision(num_classes=C), _b(_PROBS, _LABELS), "full"),
    "PrecisionRecallCurve": Entry(
        lambda: M.PrecisionRecallCurve(num_classes=C), _b(_PROBS, _LABELS), "buffered",
        buffered=lambda: M.PrecisionRecallCurve(num_classes=C, buffer_capacity=256),
        buffered_level="update_sync",  # curve output length is data-dependent
    ),
    "ROC": Entry(
        lambda: M.ROC(num_classes=C), _b(_PROBS, _LABELS), "buffered",
        buffered=lambda: M.ROC(num_classes=C, buffer_capacity=256),
        buffered_level="update_sync",
    ),
    "Recall": Entry(lambda: M.Recall(num_classes=C), _b(_PROBS, _LABELS), "full"),
    "Specificity": Entry(lambda: M.Specificity(num_classes=C), _b(_PROBS, _LABELS), "full"),
    "StatScores": Entry(lambda: M.StatScores(num_classes=C), _b(_PROBS, _LABELS), "full"),
    "CoverageError": Entry(lambda: M.CoverageError(), _b(_ML_PROBS, _ML_LABELS), "full"),
    "LabelRankingAveragePrecision": Entry(
        lambda: M.LabelRankingAveragePrecision(), _b(_ML_PROBS, _ML_LABELS), "full",
    ),
    "LabelRankingLoss": Entry(lambda: M.LabelRankingLoss(), _b(_ML_PROBS, _ML_LABELS), "full"),
    # -------------------------------------------------------- regression ----
    "CosineSimilarity": Entry(
        lambda: M.CosineSimilarity(), _b(_REG_P2, _REG_T2), "buffered",
        buffered=lambda: M.CosineSimilarity(buffer_capacity=256), buffered_level="update_sync",
    ),
    "ExplainedVariance": Entry(lambda: M.ExplainedVariance(), _b(_REG_P, _REG_T), "full"),
    "MeanAbsoluteError": Entry(lambda: M.MeanAbsoluteError(), _b(_REG_P, _REG_T), "full"),
    "MeanAbsolutePercentageError": Entry(
        lambda: M.MeanAbsolutePercentageError(), _b(_REG_P, _REG_T), "full",
    ),
    "MeanSquaredError": Entry(lambda: M.MeanSquaredError(), _b(_REG_P, _REG_T), "full"),
    "MeanSquaredLogError": Entry(lambda: M.MeanSquaredLogError(), _b(_REG_P, _REG_T), "full"),
    "PearsonCorrCoef": Entry(lambda: M.PearsonCorrCoef(), _b(_REG_P, _REG_T), "full"),
    "R2Score": Entry(lambda: M.R2Score(), _b(_REG_P, _REG_T), "full"),
    "SpearmanCorrCoef": Entry(
        lambda: M.SpearmanCorrCoef(), _b(_REG_P, _REG_T), "buffered",
        buffered=lambda: M.SpearmanCorrCoef(buffer_capacity=256),
        buffered_level="update_sync",  # rank transform reads the full buffer
    ),
    "SymmetricMeanAbsolutePercentageError": Entry(
        lambda: M.SymmetricMeanAbsolutePercentageError(), _b(_REG_P, _REG_T), "full",
    ),
    "TweedieDevianceScore": Entry(lambda: M.TweedieDevianceScore(power=1.5), _b(_REG_P, _REG_T), "full"),
    "WeightedMeanAbsolutePercentageError": Entry(
        lambda: M.WeightedMeanAbsolutePercentageError(), _b(_REG_P, _REG_T), "full",
    ),
    # ------------------------------------------------------------- image ----
    "ErrorRelativeGlobalDimensionlessSynthesis": Entry(
        lambda: M.ErrorRelativeGlobalDimensionlessSynthesis(), _b(_IMG_P, _IMG_T), "buffered",
        buffered=lambda: M.ErrorRelativeGlobalDimensionlessSynthesis(buffer_capacity=64),
        buffered_level="update_sync",
    ),
    "MultiScaleStructuralSimilarityIndexMeasure": Entry(
        lambda: M.MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0), _b(_BIG_P, _BIG_T), "buffered",
        buffered=lambda: M.MultiScaleStructuralSimilarityIndexMeasure(data_range=1.0, buffer_capacity=16),
        buffered_level="update_sync",
    ),
    "PeakSignalNoiseRatio": Entry(
        lambda: M.PeakSignalNoiseRatio(data_range=1.0), _b(_IMG_P, _IMG_T), "full",
    ),
    "SpectralAngleMapper": Entry(
        lambda: M.SpectralAngleMapper(), _b(_IMG_P, _IMG_T), "buffered",
        buffered=lambda: M.SpectralAngleMapper(buffer_capacity=64), buffered_level="update_sync",
    ),
    "SpectralDistortionIndex": Entry(
        lambda: M.SpectralDistortionIndex(), _b(_IMG_P, _IMG_T), "buffered",
        buffered=lambda: M.SpectralDistortionIndex(buffer_capacity=64), buffered_level="update_sync",
    ),
    "StructuralSimilarityIndexMeasure": Entry(
        lambda: M.StructuralSimilarityIndexMeasure(data_range=1.0), _b(_IMG_P, _IMG_T), "buffered",
        buffered=lambda: M.StructuralSimilarityIndexMeasure(data_range=1.0, buffer_capacity=16),
        buffered_level="update_sync",
    ),
    "UniversalImageQualityIndex": Entry(
        lambda: M.UniversalImageQualityIndex(), _b(_IMG_P, _IMG_T), "buffered",
        buffered=lambda: M.UniversalImageQualityIndex(buffer_capacity=16),
        buffered_level="update_sync",
    ),
    "FrechetInceptionDistance": Entry(
        lambda: M.FrechetInceptionDistance(feature=_STUB_FEATURES, feature_size=_FEAT_D),
        lambda: [((_IMG_P,), dict(real=True)), ((_IMG_T,), dict(real=False))], "full",
    ),
    "InceptionScore": Entry(
        lambda: M.InceptionScore(feature=_STUB_FEATURES), _b(_IMG_P), "buffered",
        buffered=lambda: M.InceptionScore(feature=_STUB_FEATURES, buffer_capacity=64),
        buffered_level="update_sync",  # compute reads the dynamic-count buffer
    ),
    "KernelInceptionDistance": Entry(
        lambda: M.KernelInceptionDistance(feature=_STUB_FEATURES, subset_size=8, subsets=2),
        lambda: [((_IMG_P,), dict(real=True)), ((_IMG_T,), dict(real=False))], "buffered",
        buffered=lambda: M.KernelInceptionDistance(
            feature=_STUB_FEATURES, subset_size=8, subsets=2, buffer_capacity=64,
        ),
        buffered_level="update_sync",  # compute draws host-side rng subsets
    ),
    "LearnedPerceptualImagePatchSimilarity": Entry(
        lambda: M.LearnedPerceptualImagePatchSimilarity(net=_STUB_LPIPS),
        _b(_IMG_P, _IMG_T), "full",
    ),
    # ------------------------------------------------------------- audio ----
    "SignalNoiseRatio": Entry(lambda: M.SignalNoiseRatio(), _b(_AUD_P, _AUD_T), "full"),
    "ScaleInvariantSignalNoiseRatio": Entry(
        lambda: M.ScaleInvariantSignalNoiseRatio(), _b(_AUD_P, _AUD_T), "full",
    ),
    "ScaleInvariantSignalDistortionRatio": Entry(
        lambda: M.ScaleInvariantSignalDistortionRatio(), _b(_AUD_P, _AUD_T), "full",
    ),
    "SignalDistortionRatio": Entry(
        lambda: M.SignalDistortionRatio(filter_length=64), _b(_AUD_P, _AUD_T), "full",
    ),
    "PermutationInvariantTraining": Entry(
        lambda: M.PermutationInvariantTraining(
            M.ops.scale_invariant_signal_noise_ratio, eval_func="max",
        ),
        _b(_MIX_P, _MIX_T), "full",
    ),
    "ShortTimeObjectiveIntelligibility": Entry(
        lambda: M.ShortTimeObjectiveIntelligibility(fs=10000), _b(_STOI_P, _STOI_T), "full",
    ),
    "PerceptualEvaluationSpeechQuality": Entry(
        # native jax backend: the full P.862-style pipeline traces, so the
        # whole update->sync->compute chain compiles (the default C-extension
        # backend stays host-side and is covered by its own gated tests)
        lambda: M.PerceptualEvaluationSpeechQuality(fs=8000, mode="nb", implementation="native"),
        _b(_STOI_P, _STOI_T), "full",
    ),
    # --------------------------------------------------------- retrieval ----
    **{
        name: Entry(
            (lambda cls: lambda: cls())(getattr(M, name)),
            _b(_RET_P, _RET_T, _RET_IDX),
            "buffered",
            buffered=(lambda cls: lambda: cls(buffer_capacity=256))(getattr(M, name)),
            buffered_level="update_sync",  # compute groups per-query host-side
        )
        for name in [
            "RetrievalMAP", "RetrievalMRR", "RetrievalPrecision", "RetrievalRecall",
            "RetrievalFallOut", "RetrievalHitRate", "RetrievalNormalizedDCG",
            "RetrievalRPrecision",
        ]
    },
    "RetrievalPrecisionRecallCurve": Entry(
        lambda: M.RetrievalPrecisionRecallCurve(max_k=4),
        _b(_RET_P, _RET_T, _RET_IDX), "buffered",
        buffered=lambda: M.RetrievalPrecisionRecallCurve(max_k=4, buffer_capacity=256),
        buffered_level="update_sync",
    ),
    "RetrievalRecallAtFixedPrecision": Entry(
        lambda: M.RetrievalRecallAtFixedPrecision(min_precision=0.3, max_k=4),
        _b(_RET_P, _RET_T, _RET_IDX), "buffered",
        buffered=lambda: M.RetrievalRecallAtFixedPrecision(
            min_precision=0.3, max_k=4, buffer_capacity=256,
        ),
        buffered_level="update_sync",
    ),
    # -------------------------------------------------------------- text ----
    "BLEUScore": Entry(lambda: M.BLEUScore(), lambda: ((TEXT_PREDS, TEXT_TARGETS), {}), "host"),
    "SacreBLEUScore": Entry(
        lambda: M.SacreBLEUScore(), lambda: ((TEXT_PREDS, TEXT_TARGETS), {}), "host",
    ),
    "CHRFScore": Entry(lambda: M.CHRFScore(), lambda: ((TEXT_PREDS, TEXT_TARGETS), {}), "host"),
    "TranslationEditRate": Entry(
        lambda: M.TranslationEditRate(), lambda: ((TEXT_PREDS, TEXT_TARGETS), {}), "host",
    ),
    "ExtendedEditDistance": Entry(
        lambda: M.ExtendedEditDistance(), lambda: ((TEXT_PREDS, TEXT_TARGETS_FLAT), {}), "host",
    ),
    "CharErrorRate": Entry(
        lambda: M.CharErrorRate(), lambda: ((TEXT_PREDS, TEXT_TARGETS_FLAT), {}), "host",
    ),
    "WordErrorRate": Entry(
        lambda: M.WordErrorRate(), lambda: ((TEXT_PREDS, TEXT_TARGETS_FLAT), {}), "host",
    ),
    "MatchErrorRate": Entry(
        lambda: M.MatchErrorRate(), lambda: ((TEXT_PREDS, TEXT_TARGETS_FLAT), {}), "host",
    ),
    "WordInfoLost": Entry(
        lambda: M.WordInfoLost(), lambda: ((TEXT_PREDS, TEXT_TARGETS_FLAT), {}), "host",
    ),
    "WordInfoPreserved": Entry(
        lambda: M.WordInfoPreserved(), lambda: ((TEXT_PREDS, TEXT_TARGETS_FLAT), {}), "host",
    ),
    "ROUGEScore": Entry(
        lambda: M.ROUGEScore(), lambda: ((TEXT_PREDS, TEXT_TARGETS_FLAT), {}), "host",
    ),
    "SQuAD": Entry(
        lambda: M.SQuAD(),
        lambda: ((
            [dict(prediction_text="the cat", id="1")],
            [dict(answers=dict(text=["the cat"], answer_start=[0]), id="1")],
        ), {}),
        "host",
    ),
    "BERTScore": Entry(
        lambda: M.BERTScore(
            model=object(),  # opaque handle passed through to the forward fn
            user_forward_fn=lambda model, batch: jnp.stack(
                [jnp.sin(jnp.arange(8, dtype=jnp.float32) * (1.0 + i))
                 for i in np.asarray(batch["input_ids"]).reshape(-1)]
            ).reshape(*batch["input_ids"].shape, 8),
            user_tokenizer=_WhitespaceTokenizer(),
        ),
        lambda: ((TEXT_PREDS, TEXT_TARGETS_FLAT), {}),
        "host",
    ),
    # --------------------------------------------------------- detection ----
    "MeanAveragePrecision": Entry(
        # host-list mode: per-image variable-count box lists by design. The
        # device_state=True default compiles update through CatBuffer states
        # and is pinned by tests/ops/test_heavy_kernels.py + tests/detection.
        lambda: M.MeanAveragePrecision(device_state=False),
        lambda: ((_DET_PREDS, _DET_TARGET), {}),
        "eager_only",
    ),
    # ---------------------------------------------------------- wrappers ----
    "BootStrapper": Entry(
        lambda: M.BootStrapper(M.MeanSquaredError(), num_bootstraps=4), _b(_REG_P, _REG_T),
        "eager_only",  # resample indices are drawn on host each step (documented)
    ),
    "ClasswiseWrapper": Entry(
        lambda: M.ClasswiseWrapper(M.Accuracy(num_classes=C, average="none")),
        _b(_PROBS, _LABELS), "eager_only",  # compute returns a python dict keyed by class
    ),
    "MinMaxMetric": Entry(
        lambda: M.MinMaxMetric(M.MeanSquaredError()), _b(_REG_P, _REG_T), "eager_only",
    ),
    "MultioutputWrapper": Entry(
        lambda: M.MultioutputWrapper(M.MeanSquaredError(), num_outputs=2),
        _b(_REG_P2, _REG_T2), "eager_only",  # delegates through child metric instances
    ),
    "CompositionalMetric": Entry(
        lambda: M.MeanSquaredError() + M.MeanAbsoluteError(), _b(_REG_P, _REG_T), "eager_only",
    ),
}


class _WhitespaceTokenizer:
    """Minimal tokenizer contract for the BERTScore user hook."""

    def __call__(self, sentences, max_length=64, **kwargs):
        vocab = {}
        ids = np.zeros((len(sentences), 8), dtype=np.int32)
        mask = np.zeros((len(sentences), 8), dtype=np.int32)
        for i, s in enumerate(sentences):
            for j, tok in enumerate(s.split()[:8]):
                ids[i, j] = vocab.setdefault(tok, len(vocab) + 1)
                mask[i, j] = 1
        return {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)}


def exported_metric_classes():
    """Every Metric subclass exported at the package root."""
    import inspect

    from metrics_tpu.core.metric import Metric

    out = {}
    for n in dir(M):
        obj = getattr(M, n)
        if inspect.isclass(obj) and issubclass(obj, Metric) and obj is not Metric:
            out[n] = obj
    return out
