"""Differential test harness.

Reference parity: tests/helpers/testers.py (MetricTester :335, _class_test :111,
_functional_test :253). Philosophy unchanged (SURVEY.md §4): differential
testing against a trusted oracle (sklearn et al.) over a parametrized grid,
including distributed runs with batches strided across ranks and the rank-0
assertion comparing against the oracle on the concatenation of all ranks'
batches — which is what validates the collective sync.

The "cluster" here is the 8-device CPU mesh (`xla_force_host_platform_device_count`),
and the distributed path exercises the *pure* protocol under `shard_map`:
per-device state update -> `sync_states` collectives -> `compute_state`.
"""
from __future__ import annotations

import pickle
from functools import partial
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu.core.metric import Metric

NUM_PROCESSES = 2  # logical ranks for the strided-batch ddp test
NUM_BATCHES = 8    # divisible by NUM_PROCESSES
BATCH_SIZE = 32
NUM_CLASSES = 5
EXTRA_DIM = 3
THRESHOLD = 0.5


def _assert_allclose(tm_result: Any, sk_result: Any, atol: float = 1e-6) -> None:
    """Recursive closeness assert over arrays / dicts / sequences."""
    if isinstance(tm_result, dict):
        assert isinstance(sk_result, dict), f"expected dict, got {type(sk_result)}"
        for k in tm_result:
            _assert_allclose(tm_result[k], sk_result[k], atol=atol)
    elif isinstance(tm_result, (list, tuple)):
        assert len(tm_result) == len(sk_result)
        for t, s in zip(tm_result, sk_result):
            _assert_allclose(t, s, atol=atol)
    else:
        t = np.asarray(tm_result, dtype=np.float64)
        s = np.asarray(sk_result, dtype=np.float64)
        np.testing.assert_allclose(t, s, atol=atol, rtol=1e-5)


def _class_test_single(
    preds: np.ndarray,
    target: np.ndarray,
    metric_class: type,
    sk_metric: Callable,
    metric_args: dict,
    check_batch: bool = True,
    atol: float = 1e-6,
    fragment_kwargs: bool = False,
    **kwargs_update: Any,
) -> None:
    """Single-device stateful test: forward per batch, compute over epoch.

    Mirrors reference _class_test (testers.py:111-250): per-batch value parity,
    end-of-epoch parity, pickling, reset behavior.
    """
    metric = metric_class(**metric_args)
    # pickling round-trip (reference :175)
    pickled = pickle.dumps(metric)
    metric = pickle.loads(pickled)

    num_batches = preds.shape[0]
    for i in range(num_batches):
        batch_kwargs = {
            k: (v[i] if isinstance(v, (np.ndarray, jnp.ndarray)) and v.shape[0] == num_batches and fragment_kwargs else v)
            for k, v in kwargs_update.items()
        }
        batch_result = metric(jnp.asarray(preds[i]), jnp.asarray(target[i]), **batch_kwargs)
        if check_batch:
            sk_batch_result = sk_metric(preds[i], target[i], **batch_kwargs)
            _assert_allclose(batch_result, sk_batch_result, atol=atol)

    result = metric.compute()
    total_kwargs = {
        k: (np.concatenate(list(v)) if isinstance(v, (np.ndarray, jnp.ndarray)) and v.ndim > 1 and fragment_kwargs else v)
        for k, v in kwargs_update.items()
    }
    sk_result = sk_metric(np.concatenate(list(preds)), np.concatenate(list(target)), **total_kwargs)
    _assert_allclose(result, sk_result, atol=atol)

    # reset restores defaults (reference test_metric lifecycle)
    metric.reset()
    for name, default in metric._defaults.items():
        current = getattr(metric, name)
        if isinstance(default, list):
            assert current == [] or current == default
        else:
            assert jnp.allclose(jnp.asarray(current, dtype=jnp.float32), jnp.asarray(default, dtype=jnp.float32))


def _class_test_ddp(
    preds: np.ndarray,
    target: np.ndarray,
    metric_class: type,
    sk_metric: Callable,
    metric_args: dict,
    atol: float = 1e-6,
    world: int = NUM_PROCESSES,
    **kwargs_update: Any,
) -> None:
    """Distributed test: strided batches over a `world`-device mesh.

    Device d consumes batches d, d+world, ... (reference testers.py:178); the
    final value — computed from psum/all_gather-synced state inside shard_map —
    must equal the oracle on ALL batches (reference :225-250), which validates
    the collective path end to end.
    """
    devices = jax.devices()
    if len(devices) < world:
        import pytest

        pytest.skip(f"needs {world} devices")
    mesh = Mesh(np.asarray(devices[:world]), ("data",))
    metric = metric_class(**metric_args)

    num_batches = preds.shape[0]
    assert num_batches % world == 0
    steps = num_batches // world
    # stride: rank r takes batches r, r+world, ... -> shape (world, steps, ...)
    preds_strided = jnp.asarray(np.stack([preds[r::world] for r in range(world)]))
    target_strided = jnp.asarray(np.stack([target[r::world] for r in range(world)]))

    # list/cat-state metrics have data-dependent compute (eager-only by
    # design); fixed-state metrics must keep compute inside the XLA program so
    # the suite covers traceability of the full update->sync->compute chain
    has_list_state = any(isinstance(v, list) for v in metric.init_state().values())

    def body(p, t):  # p: (1, steps, B, ...) block per device
        p, t = p[0], t[0]
        state = metric.init_state()
        for i in range(steps):
            state = metric.update_state(state, p[i], t[i])
        state = metric.sync_states(state, "data")
        out = state if has_list_state else metric.compute_state(state)
        return jax.tree.map(lambda x: jnp.expand_dims(jnp.asarray(x), 0), out)

    out = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data"), check_vma=False)
    )(preds_strided, target_strided)
    out = jax.tree.map(lambda x: x[0], out)
    result = metric.compute_state(out) if has_list_state else out

    sk_result = sk_metric(np.concatenate(list(preds)), np.concatenate(list(target)), **kwargs_update)
    _assert_allclose(result, sk_result, atol=atol)


def merge_world(ranks: Sequence[Metric]) -> Metric:
    """Host-gather ddp analog for metrics whose updates are python objects.

    Text/detection metrics (and wrappers over them) consume strings or
    per-image dict lists, so the shard_map path of `_class_test_ddp` cannot
    apply; the reference covers them through torch.distributed host gathers
    (tests/helpers/testers.py:398-439). Here the same guarantee comes from the
    framework's documented equivalence sync == merge (SURVEY.md §7 decision
    2): every rank's state — including child-metric states, deep — is folded
    into rank 0 via ``merge_states`` with true update counts. Returns rank 0,
    whose ``compute()`` must then equal the single-process all-data oracle.
    """
    nodes_per_rank = [[m for (m, _, _) in r._deep_snapshot()] for r in ranks]
    assert all(len(n) == len(nodes_per_rank[0]) for n in nodes_per_rank), "rank metric trees differ"
    for nodes in zip(*nodes_per_rank):
        m0 = nodes[0]
        merged = m0.get_state()
        count = m0._update_count
        for m in nodes[1:]:
            merged = m0.merge_states(merged, m.get_state(), update_counts=(count, m._update_count))
            count += m._update_count
        m0.set_state(merged)
        m0._update_count = count
        m0._computed = None
    return ranks[0]


def _functional_test(
    preds: np.ndarray,
    target: np.ndarray,
    metric_functional: Callable,
    sk_metric: Callable,
    metric_args: Optional[dict] = None,
    atol: float = 1e-6,
    **kwargs_update: Any,
) -> None:
    """Stateless functional parity per batch (reference testers.py:253-301)."""
    metric_args = metric_args or {}
    metric = partial(metric_functional, **metric_args)
    for i in range(preds.shape[0]):
        tm_result = metric(jnp.asarray(preds[i]), jnp.asarray(target[i]), **kwargs_update)
        sk_result = sk_metric(preds[i], target[i], **kwargs_update)
        _assert_allclose(tm_result, sk_result, atol=atol)


class MetricTester:
    """Parity-test orchestrator (reference testers.py:335-476)."""

    atol: float = 1e-6

    def run_functional_metric_test(self, preds, target, metric_functional, sk_metric, metric_args=None, **kwargs_update):
        _functional_test(
            np.asarray(preds), np.asarray(target), metric_functional, sk_metric,
            metric_args=metric_args, atol=self.atol, **kwargs_update,
        )

    def run_class_metric_test(
        self,
        ddp: bool,
        preds,
        target,
        metric_class: type,
        sk_metric: Callable,
        metric_args: Optional[dict] = None,
        check_batch: bool = True,
        fragment_kwargs: bool = False,
        **kwargs_update: Any,
    ):
        metric_args = metric_args or {}
        preds, target = np.asarray(preds), np.asarray(target)
        if ddp:
            _class_test_ddp(preds, target, metric_class, sk_metric, metric_args, atol=self.atol, **kwargs_update)
        else:
            _class_test_single(
                preds, target, metric_class, sk_metric, metric_args,
                check_batch=check_batch, atol=self.atol, fragment_kwargs=fragment_kwargs, **kwargs_update,
            )

    def run_precision_test(self, preds, target, metric_functional, metric_args=None, dtype=jnp.bfloat16):
        """bf16 smoke test (reference fp16 tests, testers.py:478-534)."""
        metric_args = metric_args or {}
        p = jnp.asarray(np.asarray(preds)[0])
        t = jnp.asarray(np.asarray(target)[0])
        if jnp.issubdtype(p.dtype, jnp.floating):
            p = p.astype(dtype)
        res = metric_functional(p, t, **metric_args)
        assert jax.tree.all(jax.tree.map(lambda x: bool(jnp.all(jnp.isfinite(jnp.asarray(x, jnp.float32)))), res))

    def run_differentiability_test(self, preds, target, metric_functional, metric_args=None):
        """Gradients flow and are finite (reference testers.py:536-570 gradcheck)."""
        metric_args = metric_args or {}
        p = jnp.asarray(np.asarray(preds)[0], dtype=jnp.float32)
        t = jnp.asarray(np.asarray(target)[0])

        def scalar_fn(p_):
            out = metric_functional(p_, t, **metric_args)
            leaves = jax.tree.leaves(out)
            return sum(jnp.sum(jnp.asarray(l, jnp.float32)) for l in leaves)

        grad = jax.grad(scalar_fn)(p)
        assert bool(jnp.all(jnp.isfinite(grad)))


# --------------------------------------------------------------------------- #
# dummy metrics for base-runtime isolation (reference testers.py:573-621)
# --------------------------------------------------------------------------- #
class DummyMetric(Metric):
    name = "Dummy"
    full_state_update = True

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("x", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, *args, **kwargs):
        pass

    def compute(self):
        pass


class DummyListMetric(Metric):
    name = "DummyList"
    full_state_update = True

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("x", [], dist_reduce_fx="cat")

    def update(self, x=None):
        if x is not None:
            self.x = self.x + [jnp.asarray(x)]

    def compute(self):
        return self.x


class DummyMetricSum(DummyMetric):
    def update(self, x):
        self.x = self.x + x

    def compute(self):
        return self.x


class DummyMetricDiff(DummyMetric):
    def update(self, y):
        self.x = self.x - y

    def compute(self):
        return self.x


class DummyMetricMultiOutput(DummyMetricSum):
    def compute(self):
        return [self.x, self.x]
