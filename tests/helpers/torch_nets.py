"""Pure-torch oracle networks for weight-converter differential tests.

The reference obtains FID/IS/KID features from torch-fidelity's
``FeatureExtractorInceptionV3`` (torchmetrics/image/fid.py:27-46) and LPIPS
scores from the ``lpips`` package (torchmetrics/image/lpip.py:34-45). Neither
package is installed offline, so these oracles re-implement the exact same
forward semantics in plain torch (which IS installed), with state-dict key
names matching the community checkpoints (``pt_inception-2015-12-05`` /
torchvision ``features.N``). The tests then:

  torch random-init -> state_dict() -> metrics_tpu converter -> flax forward
                    -> must equal the torch forward tap-for-tap.

That proves the converter key-mapping AND the flax architecture reproduce
torch numerics — something a flax-side synthesized round-trip cannot show.
Test-only code: nothing here ships in the package.
"""
from __future__ import annotations

from typing import Dict, List

import torch
import torch.nn.functional as F
from torch import nn


# --------------------------------------------------------------------------- #
# FID-compat InceptionV3 (torch-fidelity semantics)
# --------------------------------------------------------------------------- #
class BasicConv2d(nn.Module):
    def __init__(self, in_ch: int, out_ch: int, **conv_kwargs) -> None:
        super().__init__()
        self.conv = nn.Conv2d(in_ch, out_ch, bias=False, **conv_kwargs)
        self.bn = nn.BatchNorm2d(out_ch, eps=1e-3)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        return F.relu(self.bn(self.conv(x)))


def _avg3(x: torch.Tensor) -> torch.Tensor:
    return F.avg_pool2d(x, kernel_size=3, stride=1, padding=1, count_include_pad=False)


class InceptionA(nn.Module):
    def __init__(self, in_ch: int, pool_features: int) -> None:
        super().__init__()
        self.branch1x1 = BasicConv2d(in_ch, 64, kernel_size=1)
        self.branch5x5_1 = BasicConv2d(in_ch, 48, kernel_size=1)
        self.branch5x5_2 = BasicConv2d(48, 64, kernel_size=5, padding=2)
        self.branch3x3dbl_1 = BasicConv2d(in_ch, 64, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = BasicConv2d(96, 96, kernel_size=3, padding=1)
        self.branch_pool = BasicConv2d(in_ch, pool_features, kernel_size=1)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        b1 = self.branch1x1(x)
        b5 = self.branch5x5_2(self.branch5x5_1(x))
        bd = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        bp = self.branch_pool(_avg3(x))
        return torch.cat([b1, b5, bd, bp], 1)


class InceptionB(nn.Module):
    def __init__(self, in_ch: int) -> None:
        super().__init__()
        self.branch3x3 = BasicConv2d(in_ch, 384, kernel_size=3, stride=2)
        self.branch3x3dbl_1 = BasicConv2d(in_ch, 64, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = BasicConv2d(96, 96, kernel_size=3, stride=2)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        b3 = self.branch3x3(x)
        bd = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        bp = F.max_pool2d(x, kernel_size=3, stride=2)
        return torch.cat([b3, bd, bp], 1)


class InceptionC(nn.Module):
    def __init__(self, in_ch: int, c7: int) -> None:
        super().__init__()
        self.branch1x1 = BasicConv2d(in_ch, 192, kernel_size=1)
        self.branch7x7_1 = BasicConv2d(in_ch, c7, kernel_size=1)
        self.branch7x7_2 = BasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7_3 = BasicConv2d(c7, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_1 = BasicConv2d(in_ch, c7, kernel_size=1)
        self.branch7x7dbl_2 = BasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_3 = BasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7dbl_4 = BasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_5 = BasicConv2d(c7, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch_pool = BasicConv2d(in_ch, 192, kernel_size=1)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        b1 = self.branch1x1(x)
        b7 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        bd = self.branch7x7dbl_5(
            self.branch7x7dbl_4(self.branch7x7dbl_3(self.branch7x7dbl_2(self.branch7x7dbl_1(x))))
        )
        bp = self.branch_pool(_avg3(x))
        return torch.cat([b1, b7, bd, bp], 1)


class InceptionD(nn.Module):
    def __init__(self, in_ch: int) -> None:
        super().__init__()
        self.branch3x3_1 = BasicConv2d(in_ch, 192, kernel_size=1)
        self.branch3x3_2 = BasicConv2d(192, 320, kernel_size=3, stride=2)
        self.branch7x7x3_1 = BasicConv2d(in_ch, 192, kernel_size=1)
        self.branch7x7x3_2 = BasicConv2d(192, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7x3_3 = BasicConv2d(192, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7x3_4 = BasicConv2d(192, 192, kernel_size=3, stride=2)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        b3 = self.branch3x3_2(self.branch3x3_1(x))
        b7 = self.branch7x7x3_4(self.branch7x7x3_3(self.branch7x7x3_2(self.branch7x7x3_1(x))))
        bp = F.max_pool2d(x, kernel_size=3, stride=2)
        return torch.cat([b3, b7, bp], 1)


class InceptionE(nn.Module):
    def __init__(self, in_ch: int, pool: str) -> None:
        super().__init__()
        self.pool = pool
        self.branch1x1 = BasicConv2d(in_ch, 320, kernel_size=1)
        self.branch3x3_1 = BasicConv2d(in_ch, 384, kernel_size=1)
        self.branch3x3_2a = BasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3_2b = BasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = BasicConv2d(in_ch, 448, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(448, 384, kernel_size=3, padding=1)
        self.branch3x3dbl_3a = BasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = BasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch_pool = BasicConv2d(in_ch, 192, kernel_size=1)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        b1 = self.branch1x1(x)
        b3 = self.branch3x3_1(x)
        b3 = torch.cat([self.branch3x3_2a(b3), self.branch3x3_2b(b3)], 1)
        bd = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        bd = torch.cat([self.branch3x3dbl_3a(bd), self.branch3x3dbl_3b(bd)], 1)
        if self.pool == "max":
            bp = F.max_pool2d(x, kernel_size=3, stride=1, padding=1)
        else:
            bp = _avg3(x)
        bp = self.branch_pool(bp)
        return torch.cat([b1, b3, bd, bp], 1)


def resize_bilinear_tf1_torch(x: torch.Tensor, out_h: int, out_w: int) -> torch.Tensor:
    """TF1 asymmetric bilinear resize of an NCHW float batch (torch side).

    Same convention as torch-fidelity's interpolate_bilinear_2d_like_tensorflow1x:
    dest coordinate i maps to source i * in/out with no half-pixel offset.
    """
    n, c, h, w = x.shape
    ys = torch.arange(out_h, dtype=torch.float32) * (h / out_h)
    xs = torch.arange(out_w, dtype=torch.float32) * (w / out_w)
    y0 = torch.floor(ys).long()
    x0 = torch.floor(xs).long()
    y1 = torch.clamp(y0 + 1, max=h - 1)
    x1 = torch.clamp(x0 + 1, max=w - 1)
    wy = (ys - y0.float()).view(1, 1, out_h, 1)
    wx = (xs - x0.float()).view(1, 1, 1, out_w)
    rows = x[:, :, y0, :] * (1 - wy) + x[:, :, y1, :] * wy
    return rows[:, :, :, x0] * (1 - wx) + rows[:, :, :, x1] * wx


class TorchFIDInception(nn.Module):
    """FID-compat InceptionV3 oracle; state_dict keys match the converter input.

    Forward returns every feature tap the flax net exposes.
    """

    def __init__(self) -> None:
        super().__init__()
        self.Conv2d_1a_3x3 = BasicConv2d(3, 32, kernel_size=3, stride=2)
        self.Conv2d_2a_3x3 = BasicConv2d(32, 32, kernel_size=3)
        self.Conv2d_2b_3x3 = BasicConv2d(32, 64, kernel_size=3, padding=1)
        self.Conv2d_3b_1x1 = BasicConv2d(64, 80, kernel_size=1)
        self.Conv2d_4a_3x3 = BasicConv2d(80, 192, kernel_size=3)
        self.Mixed_5b = InceptionA(192, 32)
        self.Mixed_5c = InceptionA(256, 64)
        self.Mixed_5d = InceptionA(288, 64)
        self.Mixed_6a = InceptionB(288)
        self.Mixed_6b = InceptionC(768, 128)
        self.Mixed_6c = InceptionC(768, 160)
        self.Mixed_6d = InceptionC(768, 160)
        self.Mixed_6e = InceptionC(768, 192)
        self.Mixed_7a = InceptionD(768)
        self.Mixed_7b = InceptionE(1280, "avg")
        self.Mixed_7c = InceptionE(2048, "max")
        self.fc = nn.Linear(2048, 1008)

    @torch.no_grad()
    def forward(self, imgs: torch.Tensor) -> Dict[str, torch.Tensor]:
        """NCHW uint8/float batch -> dict of all taps (same pipeline as flax)."""
        out: Dict[str, torch.Tensor] = {}
        x = imgs.float()
        x = resize_bilinear_tf1_torch(x, 299, 299)
        x = (x - 128.0) / 128.0
        x = self.Conv2d_2b_3x3(self.Conv2d_2a_3x3(self.Conv2d_1a_3x3(x)))
        x = F.max_pool2d(x, kernel_size=3, stride=2)
        out["64"] = x.mean(dim=(2, 3))
        x = self.Conv2d_4a_3x3(self.Conv2d_3b_1x1(x))
        x = F.max_pool2d(x, kernel_size=3, stride=2)
        out["192"] = x.mean(dim=(2, 3))
        x = self.Mixed_5d(self.Mixed_5c(self.Mixed_5b(x)))
        x = self.Mixed_6e(self.Mixed_6d(self.Mixed_6c(self.Mixed_6b(self.Mixed_6a(x)))))
        out["768"] = x.mean(dim=(2, 3))
        x = self.Mixed_7c(self.Mixed_7b(self.Mixed_7a(x)))
        pooled = x.mean(dim=(2, 3))
        out["2048"] = pooled
        out["logits_unbiased"] = pooled @ self.fc.weight.T
        out["logits"] = out["logits_unbiased"] + self.fc.bias
        return out


def randomize_inception_(net: TorchFIDInception, seed: int = 0) -> None:
    """Seeded, numerically tame random weights (BN stats must be sane)."""
    gen = torch.Generator().manual_seed(seed)
    for mod in net.modules():
        if isinstance(mod, nn.Conv2d):
            fan_in = mod.in_channels * mod.kernel_size[0] * mod.kernel_size[1]
            mod.weight.data = torch.randn(mod.weight.shape, generator=gen) / fan_in**0.5
        elif isinstance(mod, nn.BatchNorm2d):
            mod.weight.data = 0.5 + torch.rand(mod.weight.shape, generator=gen)
            mod.bias.data = 0.1 * torch.randn(mod.bias.shape, generator=gen)
            mod.running_mean.data = 0.1 * torch.randn(mod.running_mean.shape, generator=gen)
            mod.running_var.data = 0.5 + torch.rand(mod.running_var.shape, generator=gen)
        elif isinstance(mod, nn.Linear):
            mod.weight.data = torch.randn(mod.weight.shape, generator=gen) / mod.in_features**0.5
            mod.bias.data = 0.1 * torch.randn(mod.bias.shape, generator=gen)
    net.eval()


# --------------------------------------------------------------------------- #
# LPIPS oracle (lpips-package semantics, torchvision trunk key names)
# --------------------------------------------------------------------------- #
_LPIPS_SHIFT = torch.tensor([-0.030, -0.088, -0.188]).view(1, 3, 1, 1)
_LPIPS_SCALE = torch.tensor([0.458, 0.448, 0.450]).view(1, 3, 1, 1)

# torchvision `features` indices of conv layers per trunk
ALEX_CONV_IDX = (0, 3, 6, 8, 10)
VGG16_CONV_IDX = (0, 2, 5, 7, 10, 12, 14, 17, 19, 21, 24, 26, 28)
ALEX_CFG = ((64, 11, 4, 2), (192, 5, 1, 2), (384, 3, 1, 1), (256, 3, 1, 1), (256, 3, 1, 1))
VGG16_CHANNELS = (64, 64, 128, 128, 256, 256, 256, 512, 512, 512, 512, 512, 512)


def make_lpips_backbone_state_dict(net_type: str, seed: int = 0) -> Dict[str, torch.Tensor]:
    """Random torchvision-style ``features.N.weight/bias`` dict for a trunk."""
    gen = torch.Generator().manual_seed(seed)
    sd: Dict[str, torch.Tensor] = {}

    def add_conv(idx: int, out_ch: int, in_ch: int, k: int) -> None:
        fan_in = in_ch * k * k
        sd[f"features.{idx}.weight"] = torch.randn((out_ch, in_ch, k, k), generator=gen) / fan_in**0.5
        sd[f"features.{idx}.bias"] = 0.1 * torch.randn((out_ch,), generator=gen)

    if net_type == "alex":
        in_ch = 3
        for idx, (out_ch, k, _s, _p) in zip(ALEX_CONV_IDX, ALEX_CFG):
            add_conv(idx, out_ch, in_ch, k)
            in_ch = out_ch
    elif net_type == "vgg":
        in_ch = 3
        for idx, out_ch in zip(VGG16_CONV_IDX, VGG16_CHANNELS):
            add_conv(idx, out_ch, in_ch, 3)
            in_ch = out_ch
    else:
        raise ValueError(net_type)
    return sd


def make_lpips_lin_state_dict(channels, seed: int = 0) -> Dict[str, torch.Tensor]:
    """Random non-negative 1x1 lin heads, lpips checkpoint key format."""
    gen = torch.Generator().manual_seed(seed)
    return {
        f"lin{i}.model.1.weight": torch.rand((1, c, 1, 1), generator=gen) for i, c in enumerate(channels)
    }


def _normalize_tensor(x: torch.Tensor) -> torch.Tensor:
    norm = torch.sqrt(torch.sum(x**2, dim=1, keepdim=True))
    return x / (norm + 1e-10)


@torch.no_grad()
def torch_lpips_forward(
    backbone_sd: Dict[str, torch.Tensor],
    lin_sd: Dict[str, torch.Tensor],
    net_type: str,
    img1: torch.Tensor,
    img2: torch.Tensor,
) -> torch.Tensor:
    """LPIPS distance oracle on NCHW [-1,1] batches using raw state dicts."""

    def trunk(x: torch.Tensor) -> List[torch.Tensor]:
        taps: List[torch.Tensor] = []
        if net_type == "alex":
            for i, (idx, (_c, _k, stride, pad)) in enumerate(zip(ALEX_CONV_IDX, ALEX_CFG)):
                if i in (1, 2):  # maxpool precedes conv2 and conv3
                    x = F.max_pool2d(x, kernel_size=3, stride=2)
                x = F.relu(F.conv2d(x, backbone_sd[f"features.{idx}.weight"], backbone_sd[f"features.{idx}.bias"], stride=stride, padding=pad))
                taps.append(x)
        else:  # vgg
            tap_positions = {1, 3, 6, 9, 12}  # conv1_2, conv2_2, conv3_3, conv4_3, conv5_3
            pool_before = {2, 4, 7, 10}  # pools precede conv2_1, conv3_1, conv4_1, conv5_1
            for i, idx in enumerate(VGG16_CONV_IDX):
                if i in pool_before:
                    x = F.max_pool2d(x, kernel_size=2, stride=2)
                x = F.relu(F.conv2d(x, backbone_sd[f"features.{idx}.weight"], backbone_sd[f"features.{idx}.bias"], stride=1, padding=1))
                if i in tap_positions:
                    taps.append(x)
        return taps

    def scale(x: torch.Tensor) -> torch.Tensor:
        return (x - _LPIPS_SHIFT) / _LPIPS_SCALE

    taps1, taps2 = trunk(scale(img1.float())), trunk(scale(img2.float()))
    total = torch.zeros(img1.shape[0])
    for i, (f1, f2) in enumerate(zip(taps1, taps2)):
        diff = (_normalize_tensor(f1) - _normalize_tensor(f2)) ** 2
        w = lin_sd[f"lin{i}.model.1.weight"]
        total = total + F.conv2d(diff, w).mean(dim=(2, 3)).squeeze(1)
    return total
