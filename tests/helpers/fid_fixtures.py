"""Shared FID-numerics fixture: inception-like features + the f64 oracle.

Single source of truth for both tests/image/test_fid_numerics.py and
bench.py's ``fid_numerics_2048`` entry, which claim to measure the SAME
differential (f32 on-device FID vs scipy f64) — duplicated constants would
let the two drift apart silently.
"""
from __future__ import annotations

import numpy as np
import scipy.linalg


def inception_like(rng: np.random.Generator, n: int, d: int, shift: float = 0.0, rank: int = 64) -> np.ndarray:
    """Correlated nonneg activations with means dominating spread (post-ReLU
    statistics) — with n < d the covariance is singular by construction, the
    worst realistic FID conditioning."""
    base = rng.normal(size=(n, rank)) @ rng.normal(size=(rank, d)) * 0.05
    return np.maximum(base + rng.normal(size=(n, d)) * 0.02 + 0.5 + shift, 0.0).astype(np.float64)


def oracle_fid(fr: np.ndarray, ff: np.ndarray) -> float:
    """Reference pipeline: f64 moments + scipy sqrtm (reference fid.py:98-117)."""
    mu1, mu2 = fr.mean(0), ff.mean(0)
    s1 = np.cov(fr, rowvar=False)
    s2 = np.cov(ff, rowvar=False)
    covmean = scipy.linalg.sqrtm(s1 @ s2)
    return float((mu1 - mu2) @ (mu1 - mu2) + np.trace(s1) + np.trace(s2) - 2 * np.trace(covmean.real))
