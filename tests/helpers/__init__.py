import random

import numpy as np

seed_all = lambda seed=42: (random.seed(seed), np.random.seed(seed))
