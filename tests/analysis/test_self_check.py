"""The repo must pass its own analyzer: ``--strict`` over the full registered
metric universe exits clean, the full A-rule audit of the host-side packages
is explained down to zero, and the committed ``analysis_manifest.json``
matches a live stage-3 build. This is the merge gate the CI step enforces."""
import glob
import json
import subprocess
import sys

import pytest

from metrics_tpu.analysis import audit_paths, run_analysis
from metrics_tpu.analysis import manifest as manifest_mod

# the host-side infrastructure swept by the full A-rule audit; every finding
# here must be either clean code or an ANALYSIS_MODULE_SPECS exemption
AUDIT_PACKAGES = ("metrics_tpu/serve", "metrics_tpu/tenancy", "metrics_tpu/parallel")


@pytest.fixture(scope="module")
def report():
    return run_analysis()


class TestSelfCheck:
    def test_zero_unsuppressed_errors(self, report):
        errors = [f for f in report.active() if f.severity == "error"]
        assert errors == [], "\n".join(f"{f.rule} {f.obj}: {f.message}" for f in errors)

    def test_universe_is_covered(self, report):
        # ~91 exported metrics, ~98 lintable classes at time of writing; a
        # floor guards against the registry silently going empty
        assert report.classes >= 80
        assert report.linted_classes >= report.classes

    def test_known_suppressions_are_recorded(self, report):
        # CatMetric.compute carries the one inline allow[A002] in the repo
        suppressed = [f for f in report.findings if f.suppressed]
        assert any(f.rule == "A002" and f.obj.startswith("CatMetric") for f in suppressed)

    def test_catbuffer_compute_warnings_stay_warnings(self, report):
        # the CatBuffer.to_array E107 class is expected and must not be errors
        e107 = [f for f in report.active() if f.rule == "E107"]
        assert all(f.severity == "warning" for f in e107)

    def test_skip_reasons_are_explicit(self, report):
        assert all(why for why in report.skipped.values())

    def test_stage3_manifest_is_built(self, report):
        totals = report.manifest["totals"]
        assert totals["profiled"] >= 60
        assert totals["collectives"] > 0
        assert totals["copied_bytes"] == 0  # donation aliasing holds universe-wide
        assert totals["recompile_risks"] == 0

    def test_committed_manifest_matches_live(self, report):
        """The gate the CI ``--manifest --diff`` step enforces, in-process:
        the committed ledger must describe the tree as it is."""
        committed = manifest_mod.load_manifest()
        assert committed is not None, (
            "analysis_manifest.json missing — run "
            "`python -m metrics_tpu.analysis --manifest --write` and commit"
        )
        records = manifest_mod.diff_manifest(committed, report.manifest)
        failures = manifest_mod.gate_failures(records)
        assert failures == [], "\n".join(
            f"{r['kind']} {r['obj']}: {r['detail']}" for r in failures
        )

    def test_committed_manifest_bytes_are_canonical(self, report):
        with open(manifest_mod.manifest_path(), "r") as fh:
            on_disk = fh.read()
        assert on_disk == manifest_mod.canonical_dumps(json.loads(on_disk))


class TestHostSideAudit:
    """Satellite sweep: the full A-rule audit over the host-side packages
    must be explained down to zero — every wall clock, tracer emit, and
    module global is either removed or carries a module-spec exemption."""

    @pytest.fixture(scope="class")
    def audit(self):
        paths = sorted(
            p for pkg in AUDIT_PACKAGES for p in glob.glob(f"{pkg}/**/*.py", recursive=True)
        )
        assert paths, "audit package globs resolved to nothing"
        return audit_paths(paths)

    def test_zero_unsuppressed_findings(self, audit):
        active = audit.active()
        assert active == [], "\n".join(
            f"{f.rule} {f.file}:{f.line} {f.message}" for f in active
        )

    def test_exemptions_carry_reasons(self, audit):
        exempted = [f for f in audit.findings if f.suppressed and "exempt" in f.extra]
        assert exempted, "expected module-spec exemptions to be exercised"
        assert all(f.extra["exempt"] for f in exempted)


@pytest.mark.slow
def test_cli_strict_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", "--strict", "--json"],
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["summary"]["errors"] == 0


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", "--list-rules"],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0
    for rule_id in ("A001", "A006", "E002", "E107", "E114"):
        assert rule_id in proc.stdout
