"""The repo must pass its own analyzer: ``--strict`` over the full registered
metric universe exits clean. This is the merge gate the CI step enforces."""
import json
import subprocess
import sys

import pytest

from metrics_tpu.analysis import run_analysis


@pytest.fixture(scope="module")
def report():
    return run_analysis()


class TestSelfCheck:
    def test_zero_unsuppressed_errors(self, report):
        errors = [f for f in report.active() if f.severity == "error"]
        assert errors == [], "\n".join(f"{f.rule} {f.obj}: {f.message}" for f in errors)

    def test_universe_is_covered(self, report):
        # ~91 exported metrics, ~98 lintable classes at time of writing; a
        # floor guards against the registry silently going empty
        assert report.classes >= 80
        assert report.linted_classes >= report.classes

    def test_known_suppressions_are_recorded(self, report):
        # CatMetric.compute carries the one inline allow[A002] in the repo
        suppressed = [f for f in report.findings if f.suppressed]
        assert any(f.rule == "A002" and f.obj.startswith("CatMetric") for f in suppressed)

    def test_catbuffer_compute_warnings_stay_warnings(self, report):
        # the CatBuffer.to_array E107 class is expected and must not be errors
        e107 = [f for f in report.active() if f.rule == "E107"]
        assert all(f.severity == "warning" for f in e107)

    def test_skip_reasons_are_explicit(self, report):
        assert all(why for why in report.skipped.values())


@pytest.mark.slow
def test_cli_strict_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", "--strict", "--json"],
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["summary"]["errors"] == 0


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "metrics_tpu.analysis", "--list-rules"],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0
    for rule_id in ("A001", "A006", "E002", "E107", "E114"):
        assert rule_id in proc.stdout
