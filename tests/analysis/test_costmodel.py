"""Stage-3 cost model and manifest gate, exercised on test-only fixture
metrics: profile determinism, seeded drift (an extra collective, a dropped
donation alias) caught by the diff, E117/E118 positive and suppressed paths,
and A009 over unknown suppression ids.

Fixtures live at module top level (same pattern as ``test_rules.py``) so the
registry machinery resolves real source when it needs to.
"""
import copy
import json
import subprocess
import sys

import jax.numpy as jnp
import pytest

from metrics_tpu.analysis import _validate_spec_allows, ast_stage
from metrics_tpu.analysis import costmodel, manifest as manifest_mod
from metrics_tpu.analysis.registry import Entry
from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel import sync as _sync

_SPEC = {"init": {}, "inputs": [("float32", (8,))]}


# --------------------------------------------------------------------------- #
# fixtures: one clean counter and two seeded regressions of it
# --------------------------------------------------------------------------- #
class FixtureCounter(Metric):
    """The clean baseline: one scalar sum state, one fused psum, donation-
    aliased across consecutive steps."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)

    def compute(self):
        return self.total


class ChattyCounter(FixtureCounter):
    """Seeded regression: sync emits an extra per-leaf psum on top of the
    bucketed base sync — the ``new_collective`` drift kind."""

    def sync_states(self, state, axis_name):
        state = super().sync_states(state, axis_name)
        return {k: _sync.psum_result(v, axis_name) for k, v in state.items()}


class GrowingCounter(Metric):
    """Seeded regression: the state aval drifts every step (concat growth),
    so the donated buffer can never be aliased — ``lost_donation_alias``
    plus a recompile risk."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros((1,)), dist_reduce_fx="sum")

    def update(self, values):
        self.total = jnp.concatenate(
            [jnp.atleast_1d(self.total), jnp.atleast_1d(jnp.sum(values))]
        )

    def compute(self):
        return jnp.sum(self.total)


def _profile(cls, spec=_SPEC):
    return costmodel.profile_entry(Entry(cls=cls, spec=dict(spec)))


def _doc(profile, name="FixtureCounter"):
    """A minimal manifest document wrapping one profile."""
    return {"metrics": {name: copy.deepcopy(profile)}}


@pytest.fixture(scope="module")
def clean_profile():
    return _profile(FixtureCounter)


@pytest.fixture(scope="module")
def chatty_profile():
    return _profile(ChattyCounter)


@pytest.fixture(scope="module")
def growing_profile():
    return _profile(GrowingCounter)


# --------------------------------------------------------------------------- #
# profiles
# --------------------------------------------------------------------------- #
class TestProfiles:
    def test_clean_profile_shape(self, clean_profile):
        p = clean_profile
        assert "skipped" not in p
        assert p["flops_per_step"] > 0
        assert p["state_bytes"] == 4
        assert p["collectives"]["count"] >= 1
        assert p["donation"]["copied_bytes"] == 0
        assert p["donation"]["copied_leaves"] == []
        assert p["recompile_risks"] == 0
        assert p["wire"]["total_bytes"] == sum(
            r["wire_bytes"] for r in p["buckets"]
        )

    def test_profile_is_deterministic(self, clean_profile):
        again = _profile(FixtureCounter)
        assert manifest_mod.canonical_dumps(_doc(clean_profile)) == (
            manifest_mod.canonical_dumps(_doc(again))
        )

    def test_chatty_emits_more_collectives(self, clean_profile, chatty_profile):
        assert (
            chatty_profile["collectives"]["count"]
            > clean_profile["collectives"]["count"]
        )

    def test_growing_loses_donation_alias(self, growing_profile):
        assert growing_profile["donation"]["copied_leaves"] == ["['total']"]
        assert growing_profile["donation"]["copied_bytes"] > 0
        assert growing_profile["recompile_risks"] >= 1

    def test_specless_entry_is_skipped_not_crashed(self):
        p = costmodel.profile_entry(Entry(cls=FixtureCounter, spec=None))
        assert "skipped" in p

    def test_canonical_dumps_is_canonical(self, clean_profile):
        text = manifest_mod.canonical_dumps(_doc(clean_profile))
        assert text.endswith("\n")
        assert json.loads(text) == _doc(clean_profile)


# --------------------------------------------------------------------------- #
# diff gate: seeded regressions
# --------------------------------------------------------------------------- #
class TestDiffGate:
    def test_clean_diff_is_empty(self, clean_profile):
        records = manifest_mod.diff_manifest(
            _doc(clean_profile), _doc(clean_profile)
        )
        assert records == []

    def test_seeded_extra_collective_fails_gate(self, clean_profile, chatty_profile):
        records = manifest_mod.diff_manifest(
            _doc(clean_profile), _doc(chatty_profile)
        )
        kinds = {r["kind"] for r in records if r["regression"]}
        assert "new_collective" in kinds
        assert manifest_mod.gate_failures(records)

    def test_seeded_lost_donation_alias_fails_gate(
        self, clean_profile, growing_profile
    ):
        records = manifest_mod.diff_manifest(
            _doc(clean_profile), _doc(growing_profile)
        )
        kinds = {r["kind"] for r in records if r["regression"]}
        assert "lost_donation_alias" in kinds
        assert "new_recompile_risk" in kinds
        assert manifest_mod.gate_failures(records)

    def test_improvement_never_fails(self, clean_profile, chatty_profile):
        # recorded chatty, live clean: fewer collectives is a note, not a gate
        records = manifest_mod.diff_manifest(
            _doc(chatty_profile), _doc(clean_profile)
        )
        assert records  # the stale manifest is reported...
        assert manifest_mod.gate_failures(records) == []  # ...but passes

    def test_wire_growth_within_tolerance_is_silent(self, clean_profile):
        bumped = copy.deepcopy(clean_profile)
        for row in bumped["buckets"]:
            row["wire_bytes"] += manifest_mod.WIRE_ABS_FLOOR  # inside slack
        assert (
            manifest_mod.diff_manifest(_doc(clean_profile), _doc(bumped)) == []
        )

    def test_wire_growth_beyond_tolerance_fails(self, clean_profile):
        bumped = copy.deepcopy(clean_profile)
        for row in bumped["buckets"]:
            row["wire_bytes"] += 10 * manifest_mod.WIRE_ABS_FLOOR
        records = manifest_mod.diff_manifest(_doc(clean_profile), _doc(bumped))
        assert {r["kind"] for r in records} == {"wire_bytes_growth"}
        assert manifest_mod.gate_failures(records)

    def test_new_and_removed_metric_are_regressions(self, clean_profile):
        records = manifest_mod.diff_manifest(
            _doc(clean_profile, name="OldCounter"),
            _doc(clean_profile, name="NewCounter"),
        )
        kinds = sorted(r["kind"] for r in records)
        assert kinds == ["new_metric", "removed_metric"]
        assert len(manifest_mod.gate_failures(records)) == 2

    def test_waiver_keeps_record_but_passes_gate(
        self, clean_profile, chatty_profile
    ):
        records = manifest_mod.diff_manifest(
            _doc(clean_profile),
            _doc(chatty_profile),
            waivers={"FixtureCounter": ("new_collective",)},
        )
        waived = [r for r in records if r["kind"] == "new_collective"]
        assert waived and all(r["waived"] for r in waived)
        assert manifest_mod.gate_failures(records) == []

    def test_collect_waivers_reads_manifest_allow(self):
        entries = [
            Entry(
                cls=FixtureCounter,
                spec={**_SPEC, "manifest_allow": ("new_collective",)},
            )
        ]
        assert manifest_mod.collect_waivers(entries) == {
            "FixtureCounter": ("new_collective",)
        }


# --------------------------------------------------------------------------- #
# E117 / E118
# --------------------------------------------------------------------------- #
class TestBudgetRules:
    def test_e117_fires_on_overrun(self, clean_profile):
        entries = [
            Entry(cls=FixtureCounter, spec={**_SPEC, "cost_budget": {"collectives": 0}})
        ]
        findings = costmodel.cost_budget_findings(
            entries, {"FixtureCounter": clean_profile}
        )
        assert [f.rule for f in findings] == ["E117"]
        assert not findings[0].suppressed
        assert findings[0].extra["field"] == "collectives"
        assert findings[0].extra["budget"] == 0

    def test_e117_suppressed_by_allow(self, clean_profile):
        entries = [
            Entry(
                cls=FixtureCounter,
                spec={
                    **_SPEC,
                    "cost_budget": {"collectives": 0},
                    "allow": ("E117",),
                },
            )
        ]
        findings = costmodel.cost_budget_findings(
            entries, {"FixtureCounter": clean_profile}
        )
        assert [f.suppressed for f in findings] == [True]

    def test_e117_silent_within_budget(self, clean_profile):
        entries = [
            Entry(
                cls=FixtureCounter,
                spec={**_SPEC, "cost_budget": {"copied_bytes": 0, "recompile_risks": 0}},
            )
        ]
        assert (
            costmodel.cost_budget_findings(entries, {"FixtureCounter": clean_profile})
            == []
        )

    def test_e118_fires_on_drift(self, clean_profile, chatty_profile):
        records = manifest_mod.diff_manifest(
            _doc(clean_profile), _doc(chatty_profile)
        )
        entries = [Entry(cls=FixtureCounter, spec=dict(_SPEC))]
        findings = manifest_mod.drift_findings(records, entries)
        assert any(f.rule == "E118" and not f.suppressed for f in findings)

    def test_e118_suppressed_by_allow_or_waiver(self, clean_profile, chatty_profile):
        records = manifest_mod.diff_manifest(
            _doc(clean_profile), _doc(chatty_profile)
        )
        entries = [Entry(cls=FixtureCounter, spec={**_SPEC, "allow": ("E118",)})]
        findings = manifest_mod.drift_findings(records, entries)
        assert findings and all(f.suppressed for f in findings)

        waived = manifest_mod.diff_manifest(
            _doc(clean_profile),
            _doc(chatty_profile),
            waivers={"FixtureCounter": ("new_collective",)},
        )
        entries = [Entry(cls=FixtureCounter, spec=dict(_SPEC))]
        findings = manifest_mod.drift_findings(waived, entries)
        assert findings and all(f.suppressed for f in findings)


# --------------------------------------------------------------------------- #
# A009 — unknown suppression ids
# --------------------------------------------------------------------------- #
class TestUnknownSuppressions:
    def test_unknown_allow_rule_id(self):
        entries = [Entry(cls=FixtureCounter, spec={**_SPEC, "allow": ("E999",)})]
        findings = _validate_spec_allows(entries)
        assert [f.rule for f in findings] == ["A009"]
        assert findings[0].extra == {"unknown": "E999", "where": "allow"}

    def test_unknown_manifest_allow_kind(self):
        entries = [
            Entry(cls=FixtureCounter, spec={**_SPEC, "manifest_allow": ("wire_bytez",)})
        ]
        findings = _validate_spec_allows(entries)
        assert [f.extra["where"] for f in findings] == ["manifest_allow"]

    def test_unknown_cost_budget_field(self):
        entries = [
            Entry(cls=FixtureCounter, spec={**_SPEC, "cost_budget": {"flopz": 1}})
        ]
        findings = _validate_spec_allows(entries)
        assert [f.extra["where"] for f in findings] == ["cost_budget"]

    def test_known_ids_are_silent(self):
        entries = [
            Entry(
                cls=FixtureCounter,
                spec={
                    **_SPEC,
                    "allow": ("E117", "E118"),
                    "manifest_allow": ("new_collective",),
                    "cost_budget": {"collectives": 8},
                },
            )
        ]
        assert _validate_spec_allows(entries) == []

    def test_inline_unknown_id_flags_a009(self):
        source = (
            "import jax.numpy as jnp\n"
            "x = jnp.zeros(())  # metrics-tpu: allow[E999]\n"
        )
        findings = ast_stage.lint_source("fixture.py", source, set())
        assert any(
            f.rule == "A009" and f.extra.get("unknown") == "E999" for f in findings
        )

    def test_inline_known_id_is_silent(self):
        source = (
            "import jax.numpy as jnp\n"
            "x = jnp.zeros(())  # metrics-tpu: allow[A007]\n"
        )
        findings = ast_stage.lint_source("fixture.py", source, set())
        assert not [f for f in findings if f.rule == "A009"]


# --------------------------------------------------------------------------- #
# CLI: the committed manifest gates for real
# --------------------------------------------------------------------------- #
@pytest.mark.slow
def test_cli_diff_catches_seeded_regression(tmp_path):
    """A doctored committed manifest (recorded collectives lower than live)
    makes ``--manifest --diff`` exit 1; a missing one exits 2."""
    committed = manifest_mod.load_manifest()
    assert committed is not None, "analysis_manifest.json must be committed"
    committed["metrics"]["Accuracy"]["collectives"]["count"] = 0
    seeded = tmp_path / "seeded_manifest.json"
    seeded.write_text(manifest_mod.canonical_dumps(committed))

    proc = subprocess.run(
        [
            sys.executable, "-m", "metrics_tpu.analysis",
            "--manifest", "--diff", "--manifest-path", str(seeded),
        ],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "new_collective" in proc.stdout

    proc = subprocess.run(
        [
            sys.executable, "-m", "metrics_tpu.analysis",
            "--manifest", "--diff", "--manifest-path", str(tmp_path / "absent.json"),
        ],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
