"""Fixture metrics seeded with one hazard class each, asserting the analyzer
flags each with exactly the expected rule — AST stage (stage 1) and
abstract-eval stage (stage 2) over the mock 8-device mesh.

The fixtures live at module top level so ``inspect.getsourcefile`` resolves
this file and the AST stage lints real source, suppression comments included.
"""
import time
from time import monotonic

import jax.numpy as jnp
import pytest

from metrics_tpu.analysis import ast_stage, eval_stage
from metrics_tpu.analysis.registry import Entry
from metrics_tpu.analysis.rules import ERROR, RULES, parse_suppressions
from metrics_tpu.core.metric import Metric
from metrics_tpu.observability import tracer as _otrace
from metrics_tpu.parallel import sync as _sync
from metrics_tpu.utils.checks import _is_concrete


# --------------------------------------------------------------------------- #
# stage-1 fixtures (linted, never instantiated)
# --------------------------------------------------------------------------- #
class HostRoundTripMetric(Metric):
    """A001: float() on a traced value is a device->host sync."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + float(values.sum())

    def compute(self):
        return self.total


class BranchyMetric(Metric):
    """A002: Python `if` on an input-derived value."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        if values.sum() > 0:
            self.total = self.total + values.sum()

    def compute(self):
        return self.total


class HiddenWriteMetric(Metric):
    """A003: writes an attribute that is neither add_state'd nor __init__'d."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        self.scratch = values.sum()
        self.total = self.total + self.scratch

    def compute(self):
        return self.total


class ScalarStateMetric(Metric):
    """A004: bare Python scalar as an add_state default (the constructor
    would reject it at runtime; the lint catches it without constructing)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("count", default=0.0, dist_reduce_fx="sum")

    def update(self, values):
        self.count = self.count + values.sum()

    def compute(self):
        return self.count


class ClockReadMetric(Metric):
    """A007: host-clock read in update — a trace-time constant under jit."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        t0 = time.perf_counter()  # noqa: F841
        self.total = self.total + jnp.sum(values)

    def compute(self):
        return self.total


class TracerEmitMetric(Metric):
    """A007: tracer emit from a jit-facing method fires once per compile."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)

    def compute(self):
        _otrace.emit_instant("my_metric/compute", "engine")
        return self.total


class BareClockMetric(Metric):
    """A007 via a `from time import monotonic` binding."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        start = monotonic()  # noqa: F841
        self.total = self.total + jnp.sum(values)

    def compute(self):
        return self.total


class GuardedClockMetric(Metric):
    """Control for A007: clock reads under an _is_concrete guard are
    host-side by design (same exemption as A001/A002)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")
        self._last_update_s = 0.0

    def update(self, values):
        if _is_concrete(values):
            self._last_update_s = time.perf_counter()
        self.total = self.total + jnp.sum(values)

    def compute(self):
        return self.total


class SuppressedHostMetric(Metric):
    """Same A001 hazard as HostRoundTripMetric, silenced inline."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + float(values.sum())  # metrics-tpu: allow[A001]

    def compute(self):
        return self.total


class CleanMetric(Metric):
    """Control: no hazards, no findings."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)

    def compute(self):
        return self.total


# --------------------------------------------------------------------------- #
# stage-2 fixtures (instantiated and traced under the mock mesh)
# --------------------------------------------------------------------------- #
class DriftySyncMetric(CleanMetric):
    """E105: sync_states grows the state treedef."""

    def sync_states(self, state, axis_name):
        synced = super().sync_states(state, axis_name)
        synced = dict(synced)
        synced["extra"] = jnp.zeros(())
        return synced


class ChattySyncMetric(Metric):
    """E106: per-leaf collectives where the canonical bucketed sync coalesces
    four same-dtype sum states into one psum."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        for name in ("a", "b", "c", "d"):
            self.add_state(name, default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        self.a = self.a + jnp.sum(values)
        self.b = self.b + jnp.sum(values)
        self.c = self.c + jnp.sum(values)
        self.d = self.d + jnp.sum(values)

    def compute(self):
        return self.a + self.b + self.c + self.d

    def sync_states(self, state, axis_name):
        return {k: _sync.sync_array(v, "sum", axis_name) for k, v in state.items()}


class TreedefDriftUpdateMetric(CleanMetric):
    """E102: the state treedef oscillates step to step (a one-time warmup
    materialization is tolerated; this alternates forever)."""

    def update_state(self, state, *args, **kwargs):
        state = dict(state)
        stray = state.pop("stray", None)
        out = dict(super().update_state(state, *args, **kwargs))
        if stray is None:
            out["stray"] = jnp.zeros(())
        return out


class ShardedCleanMetric(Metric):
    """Control for E108/E111: a class-sharded vector state with canonical
    sync AND the sharded-compute protocol (its finalize reduces over the
    sharded extent, so without ``compute_sharded_state`` it would be exactly
    the reshard-at-compute headroom E111 flags)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("counts", default=jnp.zeros((8,)), dist_reduce_fx="sum", shard_axis=0)

    def update(self, values):
        self.counts = self.counts + values

    def compute(self):
        return self.counts.sum()

    def compute_sharded_state(self, state, axis_name):
        return _sync.psum_result(state["counts"].sum(), axis_name)


class ShardIgnorantSyncMetric(ShardedCleanMetric):
    """E108: the sync override psums every leaf, ignoring active_shard_axes —
    with sharded state the per-device blocks are disjoint, so the psum
    double-counts instead of gathering."""

    def sync_states(self, state, axis_name):
        return {k: _sync.sync_array(v, "sum", axis_name) for k, v in state.items()}


class ValueDependentComputeMetric(CleanMetric):
    """E107 + E109: compute's output shape depends on state *values*, so the
    fused compute leg cannot trace — yet the partition dispatcher's static
    probes still classify the metric as fused-compute."""

    def compute(self):
        return jnp.nonzero(jnp.ones((4,)) * self.total)[0]  # metrics-tpu: allow[A002]


class ReshardAtComputeMetric(Metric):
    """E111: class-sharded counts whose finalize sums over the sharded
    extent, with no compute_sharded_state — the finalize re-materializes the
    tiled state (reshard bytes) for a reduction that could run on the shard."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("counts", default=jnp.zeros((8,)), dist_reduce_fx="sum", shard_axis=0)

    def update(self, values):
        self.counts = self.counts + values

    def compute(self):
        return self.counts.sum()


class ProtocolDeclaredMetric(ReshardAtComputeMetric):
    """Control for E111: the same finalize, but the sharded-compute protocol
    is declared — exactly the fix the rule asks for."""

    def compute_sharded_state(self, state, axis_name):
        return _sync.psum_result(state["counts"].sum(), axis_name)


class ElementwiseShardedComputeMetric(Metric):
    """Control for E111: sharded state whose finalize is elementwise — no
    reduction over the sharded extent, nothing the protocol could shortcut."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("counts", default=jnp.zeros((8,)), dist_reduce_fx="sum", shard_axis=0)

    def update(self, values):
        self.counts = self.counts + values

    def compute(self):
        return self.counts * 2.0


class OffAxisReductionMetric(Metric):
    """Control for E111: the finalize reduces a (row-local) dimension whose
    extent differs from the sharded one — shard-local math, not headroom."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state(
            "table", default=jnp.zeros((8, 3)), dist_reduce_fx="sum", shard_axis=0
        )

    def update(self, values):
        self.table = self.table + values[:, None] * jnp.ones((1, 3))

    def compute(self):
        return self.table.sum(axis=1)


class OverBudgetTransportMetric(Metric):
    """E112: a declared bf16 transport whose tolerance is tighter than the
    canonical-mesh error bound — the runtime gate refuses the bucket, so the
    declaration silently buys nothing."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state(
            "total", default=jnp.zeros((16,)), dist_reduce_fx="sum",
            sync_transport="bf16", sync_tolerance=0.001,
        )

    def update(self, values):
        self.total = self.total + values[:16].sum() + jnp.zeros((16,))

    def compute(self):
        return self.total.sum()


class InBudgetTransportMetric(OverBudgetTransportMetric):
    """Control for E112: the same declaration at the transport's default
    tolerance — within budget on the canonical mesh, gate admits it."""

    def __init__(self, **kwargs):
        Metric.__init__(self, **kwargs)
        self.add_state(
            "total", default=jnp.zeros((16,)), dist_reduce_fx="sum",
            sync_transport="bf16",
        )


class NoByteWinTransportMetric(Metric):
    """E112 (reason no_byte_win): sparse_count on a bucket too small for the
    index+value encoding to beat dense wire bytes."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state(
            "pair", default=jnp.zeros((2,), jnp.int32), dist_reduce_fx="sum",
            sync_transport="sparse_count",
        )

    def update(self, values):
        self.pair = self.pair + jnp.ones((2,), jnp.int32)

    def compute(self):
        return self.pair.sum()


class DeferredPinnedMetric(Metric):
    """E113: every state leaf is mergeable-elementwise — fully
    emission-eligible — but per-state ``sync_mode='deferred'`` declarations
    pin the whole group to one finalize burst."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state(
            "total", default=jnp.zeros((8,)), dist_reduce_fx="sum",
            sync_mode="deferred",
        )
        self.add_state(
            "count", default=jnp.zeros((), jnp.int32), dist_reduce_fx="sum",
            sync_mode="deferred",
        )

    def update(self, values):
        self.total = self.total + values[:8]
        self.count = self.count + 1

    def compute(self):
        return self.total.sum() / jnp.maximum(self.count, 1)


class EngagedIncrementalMetric(DeferredPinnedMetric):
    """Control for E113: the same states declared ``sync_mode='incremental'``
    — the group takes in-streak emissions, nothing is pinned."""

    def __init__(self, **kwargs):
        Metric.__init__(self, **kwargs)
        self.add_state(
            "total", default=jnp.zeros((8,)), dist_reduce_fx="sum",
            sync_mode="incremental",
        )
        self.add_state(
            "count", default=jnp.zeros((), jnp.int32), dist_reduce_fx="sum",
            sync_mode="incremental",
        )


class CatReductionMetric(Metric):
    """E110: dense state under a ``cat`` reduction — fine for the compiled
    engines, but a TenantSet cannot fold its tenant axis into the flat sync
    buckets, so the member demotes to per-tenant eager clones."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("vals", default=jnp.zeros((4,)), dist_reduce_fx="cat")

    def update(self, values):
        self.vals = self.vals + values[:4]

    def compute(self):
        return self.vals.sum()


def _pairwise_merge(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


class CallableReductionMetric(Metric):
    """E119: a callable ``dist_reduce_fx`` — the migration wire carries
    values only, so the importing process cannot reconstruct or validate the
    merge semantics behind the leaf."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros((4,)), dist_reduce_fx=_pairwise_merge)

    def update(self, values):
        self.total = self.total + values[:4]

    def compute(self):
        return self.total.sum()


class ListBufferMetric(Metric):
    """E119 (and E116): a capacity-less list state — data-dependent byte
    count, no transfer plan; bounded by constructing with
    ``buffer_capacity=N`` (the control spec in the tests)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("vals", default=[], dist_reduce_fx="cat")

    def update(self, values):
        self.vals.append(values)

    def compute(self):
        return jnp.concatenate(list(self.vals)).sum()


_SPEC = {"init": {}, "inputs": [("float32", (8,))]}


def _lint(cls):
    return ast_stage.lint_class(cls)


def _active_rules(findings):
    return sorted({f.rule for f in findings if not f.suppressed})


def _evaluate(cls, spec=_SPEC):
    return eval_stage.evaluate_entry(Entry(cls=cls, spec=dict(spec)))


# --------------------------------------------------------------------------- #
# stage 1
# --------------------------------------------------------------------------- #
class TestASTStage:
    @pytest.mark.parametrize(
        "cls, expected",
        [
            (HostRoundTripMetric, "A001"),
            (BranchyMetric, "A002"),
            (HiddenWriteMetric, "A003"),
            (ScalarStateMetric, "A004"),
            (ClockReadMetric, "A007"),
            (TracerEmitMetric, "A007"),
            (BareClockMetric, "A007"),
        ],
        ids=lambda x: getattr(x, "__name__", x),
    )
    def test_each_hazard_flagged_by_exactly_its_rule(self, cls, expected):
        findings = _lint(cls)
        assert _active_rules(findings) == [expected]
        f = next(f for f in findings if f.rule == expected)
        assert f.obj.startswith(cls.__name__)
        assert f.file and f.file.endswith("test_rules.py") and f.line

    def test_clean_metric_has_no_findings(self):
        assert _lint(CleanMetric) == []

    def test_guarded_clock_read_is_exempt(self):
        assert "A007" not in _active_rules(_lint(GuardedClockMetric))

    def test_inline_suppression_keeps_finding_but_marks_it(self):
        findings = _lint(SuppressedHostMetric)
        assert [f.rule for f in findings] == ["A001"]
        assert findings[0].suppressed
        assert _active_rules(findings) == []

    def test_parse_suppressions(self):
        src = "x = 1\ny = foo()  # metrics-tpu: allow[A001, E106]\n"
        assert parse_suppressions(src) == {2: ("A001", "E106")}

    def test_every_finding_rule_is_in_catalog(self):
        for cls in (HostRoundTripMetric, BranchyMetric, HiddenWriteMetric, ScalarStateMetric):
            for f in _lint(cls):
                assert f.rule in RULES


# --------------------------------------------------------------------------- #
# stage 2 — mock 8-device mesh (axis_env trace, no real devices needed)
# --------------------------------------------------------------------------- #
class TestEvalStage:
    def test_clean_metric_passes(self):
        findings = _evaluate(CleanMetric)
        assert [f for f in findings if not f.suppressed] == []

    def test_sync_treedef_drift_is_E105(self):
        findings = _evaluate(DriftySyncMetric)
        errors = sorted({f.rule for f in findings if f.severity == ERROR and not f.suppressed})
        assert errors == ["E105"]

    def test_collective_budget_overrun_is_E106(self):
        findings = _evaluate(ChattySyncMetric)
        errors = [f for f in findings if f.severity == ERROR and not f.suppressed]
        assert [f.rule for f in errors] == ["E106"]
        extra = errors[0].extra
        assert extra["collectives"] == 4  # one psum per leaf
        assert extra["budget"] < 4  # canonical bucketed sync coalesces them
        assert extra["by_kind"] == {"psum": 4}

    def test_budget_override_silences_E106(self):
        spec = dict(_SPEC, collective_budget=4)
        findings = _evaluate(ChattySyncMetric, spec)
        assert "E106" not in {f.rule for f in findings if not f.suppressed}

    def test_update_treedef_drift_is_E102(self):
        findings = _evaluate(TreedefDriftUpdateMetric)
        assert "E102" in {f.rule for f in findings if not f.suppressed}

    def test_spec_level_allow_suppresses(self):
        spec = dict(_SPEC, allow=("E105",))
        findings = _evaluate(DriftySyncMetric, spec)
        e105 = [f for f in findings if f.rule == "E105"]
        assert e105 and all(f.suppressed for f in e105)

    def test_sharded_clean_metric_passes(self):
        findings = _evaluate(ShardedCleanMetric)
        assert [f for f in findings if not f.suppressed] == []

    def test_sharded_routing_violation_is_E108(self):
        findings = _evaluate(ShardIgnorantSyncMetric)
        e108 = [f for f in findings if f.rule == "E108" and not f.suppressed]
        assert e108, [f.rule for f in findings]
        extra = e108[0].extra
        assert extra["kind"] == "psum"
        assert extra["bytes"] == 8 * 4  # the whole sharded leaf went through psum
        assert extra["budget_bytes"] == 0  # canonical sharded sync psums nothing

    def test_spec_sharded_promise_mismatch_is_E108(self):
        # spec promises a sharded state the class never declares
        findings = _evaluate(CleanMetric, dict(_SPEC, sharded={"total": 0}))
        e108 = [f for f in findings if f.rule == "E108" and not f.suppressed]
        assert e108 and "drifted" in e108[0].message

    def test_sharded_canonical_trace_failure_is_reported_not_compared(self, monkeypatch):
        """When the canonical sharded sync fails to trace there is no byte
        budget — the failure itself must be the finding, not a spurious
        'reduced as if replicated' comparison against an empty budget."""
        real = _sync.sync_state

        def failing(state, reductions, axis_name, **kwargs):
            if kwargs.get("shard_axes"):
                raise RuntimeError("canonical sharded sync exploded")
            return real(state, reductions, axis_name, **kwargs)

        monkeypatch.setattr(_sync, "sync_state", failing)
        findings = _evaluate(ShardIgnorantSyncMetric)
        e108 = [f for f in findings if f.rule == "E108" and not f.suppressed]
        assert e108, [f.rule for f in findings]
        assert all("cannot be validated" in f.message for f in e108)
        assert not any("reduced as if replicated" in f.message for f in e108)

    def test_untraceable_update_drift_is_E101_plus_E109(self):
        # statically fused-eligible, but the update leg cannot abstract-eval:
        # the runtime dispatcher would pay a failed trace + migration
        findings = _evaluate(SuppressedHostMetric)
        rules = _active_rules(findings)
        assert "E101" in rules and "E109" in rules, rules
        e109 = [f for f in findings if f.rule == "E109"]
        assert len(e109) == 1
        assert e109[0].extra["kind"] == "update"
        assert e109[0].severity == "warning"

    def test_update_opt_out_silences_E109(self):
        # compiled_update=False pre-assigns the eager set — no drift to report
        findings = _evaluate(SuppressedHostMetric, dict(_SPEC, init={"compiled_update": False}))
        rules = {f.rule for f in findings if not f.suppressed}
        assert "E101" in rules and "E109" not in rules

    def test_untraceable_compute_drift_is_E107_plus_E109(self):
        findings = _evaluate(ValueDependentComputeMetric)
        rules = _active_rules(findings)
        assert "E107" in rules and "E109" in rules, rules
        e109 = [f for f in findings if f.rule == "E109"]
        assert len(e109) == 1
        assert e109[0].extra["kind"] == "compute"

    def test_compute_opt_out_silences_E109(self):
        findings = _evaluate(ValueDependentComputeMetric, dict(_SPEC, init={"compiled_compute": False}))
        rules = {f.rule for f in findings if not f.suppressed}
        assert "E107" in rules and "E109" not in rules

    def test_reshard_at_compute_is_E111(self):
        findings = _evaluate(ReshardAtComputeMetric)
        e111 = [f for f in findings if f.rule == "E111" and not f.suppressed]
        assert len(e111) == 1, [f.rule for f in findings]
        assert e111[0].severity == "warning"
        assert "compute_sharded_state" in e111[0].message
        assert e111[0].extra["states"] == ["counts"]
        assert e111[0].extra["shard_axes"] == {"counts": 0}
        assert e111[0].extra["extents"] == {"counts": 8}

    def test_protocol_declaration_silences_E111(self):
        findings = _evaluate(ProtocolDeclaredMetric)
        assert "E111" not in {f.rule for f in findings}

    def test_elementwise_sharded_compute_has_no_E111(self):
        findings = _evaluate(ElementwiseShardedComputeMetric)
        assert "E111" not in {f.rule for f in findings}

    def test_off_axis_reduction_has_no_E111(self):
        findings = _evaluate(OffAxisReductionMetric)
        assert "E111" not in {f.rule for f in findings}

    def test_E111_is_suppressible_via_spec_allow(self):
        findings = _evaluate(ReshardAtComputeMetric, dict(_SPEC, allow=("E111",)))
        e111 = [f for f in findings if f.rule == "E111"]
        assert e111 and all(f.suppressed for f in e111)

    def test_tenant_unstackable_is_E110(self):
        findings = _evaluate(CatReductionMetric)
        e110 = [f for f in findings if f.rule == "E110" and not f.suppressed]
        assert len(e110) == 1
        assert e110[0].severity == "warning"
        assert "cat" in e110[0].message and "eager" in e110[0].message
        assert e110[0].extra["tenant_path"] == "eager"

    def test_stackable_metric_has_no_E110(self):
        findings = _evaluate(CleanMetric)
        assert "E110" not in {f.rule for f in findings}

    def test_E110_is_suppressible_via_spec_allow(self):
        findings = _evaluate(CatReductionMetric, dict(_SPEC, allow=("E110",)))
        e110 = [f for f in findings if f.rule == "E110"]
        assert e110 and all(f.suppressed for f in e110)

    def test_over_budget_transport_is_E112(self):
        findings = _evaluate(OverBudgetTransportMetric)
        e112 = [f for f in findings if f.rule == "E112" and not f.suppressed]
        assert len(e112) == 1, [f.rule for f in findings]
        assert e112[0].severity == "warning"
        assert "falls back to the exact transport" in e112[0].message
        extra = e112[0].extra
        assert extra["requested"] == "bf16"
        assert extra["states"] == ["total"]
        assert extra["refusal"]["reason"] == "error_budget"
        assert extra["refusal"]["bound"] > extra["refusal"]["tolerance"] == 0.001

    def test_in_budget_transport_has_no_E112(self):
        findings = _evaluate(InBudgetTransportMetric)
        assert "E112" not in {f.rule for f in findings}

    def test_no_byte_win_is_E112(self):
        findings = _evaluate(NoByteWinTransportMetric)
        e112 = [f for f in findings if f.rule == "E112" and not f.suppressed]
        assert len(e112) == 1, [f.rule for f in findings]
        assert e112[0].extra["refusal"]["reason"] == "no_byte_win"
        assert "no_byte_win" in e112[0].message

    def test_undeclared_metric_has_no_E112(self):
        findings = _evaluate(CleanMetric)
        assert "E112" not in {f.rule for f in findings}

    def test_E112_is_suppressible_via_spec_allow(self):
        findings = _evaluate(OverBudgetTransportMetric, dict(_SPEC, allow=("E112",)))
        e112 = [f for f in findings if f.rule == "E112"]
        assert e112 and all(f.suppressed for f in e112)

    def test_deferred_pinned_metric_is_E113(self):
        findings = _evaluate(DeferredPinnedMetric)
        e113 = [f for f in findings if f.rule == "E113" and not f.suppressed]
        assert len(e113) == 1, [f.rule for f in findings]
        assert e113[0].severity == "warning"
        extra = e113[0].extra
        assert extra["global_mode"] == "deferred"
        assert extra["declared_modes"] == {"total": "deferred", "count": "deferred"}
        named = sorted(n for b in extra["residue_buckets"] for n in b["states"])
        assert named == ["count", "total"]
        assert "residue bucket" in e113[0].message

    def test_engaged_incremental_has_no_E113(self):
        findings = _evaluate(EngagedIncrementalMetric)
        assert "E113" not in {f.rule for f in findings}

    def test_undeclared_metric_under_default_mode_has_no_E113(self):
        findings = _evaluate(CleanMetric)
        assert "E113" not in {f.rule for f in findings}

    def test_global_incremental_mode_flags_pinned_declarations_only(self):
        import metrics_tpu

        metrics_tpu.set_sync_mode("incremental")
        try:
            pinned = _evaluate(DeferredPinnedMetric)
            clean = _evaluate(CleanMetric)
        finally:
            metrics_tpu.set_sync_mode(None)
        e113 = [f for f in pinned if f.rule == "E113" and not f.suppressed]
        assert len(e113) == 1
        assert e113[0].extra["global_mode"] == "incremental"
        # undeclared leaves follow the global mode — engaged, nothing pinned
        assert "E113" not in {f.rule for f in clean}

    def test_E113_is_suppressible_via_spec_allow(self):
        findings = _evaluate(DeferredPinnedMetric, dict(_SPEC, allow=("E113",)))
        e113 = [f for f in findings if f.rule == "E113"]
        assert e113 and all(f.suppressed for f in e113)

    def test_callable_reduction_is_E119(self):
        findings = _evaluate(CallableReductionMetric)
        e119 = [f for f in findings if f.rule == "E119" and not f.suppressed]
        assert len(e119) == 1, [f.rule for f in findings]
        assert e119[0].severity == "warning"
        assert "callable dist_reduce_fx" in e119[0].message
        assert e119[0].extra["states"] == ("total",)

    def test_capacity_less_buffer_is_E119(self):
        findings = _evaluate(ListBufferMetric)
        e119 = [f for f in findings if f.rule == "E119" and not f.suppressed]
        assert len(e119) == 1, [f.rule for f in findings]
        assert "capacity-less list state" in e119[0].message
        assert e119[0].extra["states"] == ("vals",)

    def test_buffer_capacity_bound_silences_E119(self):
        findings = _evaluate(ListBufferMetric, dict(_SPEC, init={"buffer_capacity": 4}))
        assert "E119" not in {f.rule for f in findings}

    def test_dense_named_reductions_have_no_E119(self):
        findings = _evaluate(CleanMetric)
        assert "E119" not in {f.rule for f in findings}

    def test_E119_is_suppressible_via_spec_allow(self):
        findings = _evaluate(ListBufferMetric, dict(_SPEC, allow=("E119",)))
        e119 = [f for f in findings if f.rule == "E119"]
        assert e119 and all(f.suppressed for f in e119)

    def test_missing_spec_is_E002(self):
        findings = eval_stage.evaluate_entry(Entry(cls=CleanMetric, spec=None))
        assert [f.rule for f in findings] == ["E002"]

    def test_uninstantiable_is_E003(self):
        findings = eval_stage.evaluate_entry(
            Entry(cls=CleanMetric, spec={"init": {"no_such_kwarg": 1}, "inputs": _SPEC["inputs"]})
        )
        assert [f.rule for f in findings] == ["E003"]


# --------------------------------------------------------------------------- #
# audit mode (--paths) — file-wide A007 and the module-spec exemption
# --------------------------------------------------------------------------- #
_CLOCKY_SOURCE = '''
import time
from time import monotonic
from metrics_tpu.observability import tracer as _otrace

def heartbeat():
    t0 = time.perf_counter()
    t1 = monotonic()
    _otrace.emit_instant("server/poll", "server")
    return t0, t1

def quiet():
    return time.time_ns()  # metrics-tpu: allow[A007]
'''


class TestAuditMode:
    def test_audit_flags_every_clock_and_tracer_call(self):
        findings = ast_stage.lint_source("somefile.py", _CLOCKY_SOURCE, set())
        a007 = [f for f in findings if f.rule == "A007"]
        assert len(a007) == 4
        active = [f for f in a007 if not f.suppressed]
        assert len(active) == 3  # the inline allow[] silences the fourth
        messages = " | ".join(f.message for f in active)
        assert "time.perf_counter" in messages
        assert "monotonic()" in messages
        assert "_otrace.emit_instant" in messages

    def test_observability_host_modules_are_spec_exempt(self):
        from metrics_tpu.analysis.registry import (
            collect_module_specs,
            module_spec_for_path,
        )

        specs = collect_module_specs()
        for path in (
            "metrics_tpu/observability/server.py",
            "metrics_tpu/observability/shards.py",
            "metrics_tpu/observability/tracer.py",
        ):
            spec = module_spec_for_path(specs, f"/root/anywhere/{path}")
            assert spec is not None, path
            assert "A007" in spec["allow"]
            assert spec["reason"]
        assert module_spec_for_path(specs, "metrics_tpu/core/engine.py") is None
        # suffix matching must not cross path-segment boundaries
        assert module_spec_for_path(specs, "not_metrics_tpu/observability/server.py") is None

    def test_audit_paths_suppresses_with_reason(self, tmp_path, monkeypatch):
        from metrics_tpu import analysis as _analysis
        from metrics_tpu.analysis import registry as _registry

        target = tmp_path / "clocky.py"
        target.write_text(_CLOCKY_SOURCE)
        monkeypatch.setattr(
            _registry, "collect_module_specs",
            lambda: {"clocky.py": {"allow": ("A007",), "reason": "host-side poller"}},
        )
        report = _analysis.audit_paths([str(target)])
        a007 = [f for f in report.findings if f.rule == "A007"]
        assert a007 and all(f.suppressed for f in a007)
        assert any(f.extra.get("exempt") == "host-side poller" for f in a007)
        assert report.errors == 0

    def test_exemption_never_reaches_jit_facing_methods(self, monkeypatch):
        """The module-spec exemption is audit-only: even with this test file
        itself spec-exempted for A007, lint_class still flags the clock read
        in ClockReadMetric.update."""
        from metrics_tpu.analysis import registry as _registry

        monkeypatch.setattr(
            _registry, "collect_module_specs",
            lambda: {"tests/analysis/test_rules.py": {"allow": ("A007",),
                                                      "reason": "leak probe"}},
        )
        findings = _lint(ClockReadMetric)
        assert _active_rules(findings) == ["A007"]


# --------------------------------------------------------------------------- #
# A008 — over-broad exception handlers (jit-facing methods + audit mode)
# --------------------------------------------------------------------------- #
class SwallowingMetric(Metric):
    """A008: ``except Exception: pass`` in a jit-facing method swallows the
    trace failures the engine fallback and chaos harness depend on."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        try:
            self.total = self.total + values.sum()
        except Exception:
            pass

    def compute(self):
        return self.total


class ReRaisingMetric(Metric):
    """Clean: a broad handler that re-raises is a legitimate cleanup shape."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        try:
            self.total = self.total + values.sum()
        except Exception:
            self.total = self.total
            raise

    def compute(self):
        return self.total


class NarrowHandlerMetric(Metric):
    """Clean: catching specific exception types is exactly the fix A008 asks
    for."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        try:
            self.total = self.total + values.sum()
        except (TypeError, ValueError):
            self.total = self.total

    def compute(self):
        return self.total


class SuppressedSwallowingMetric(Metric):
    """A008 present but inline-suppressed."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        try:
            self.total = self.total + values.sum()
        except Exception:  # metrics-tpu: allow[A008]
            pass

    def compute(self):
        return self.total


_EXCEPTY_SOURCE = '''
def swallows_everything():
    try:
        risky()
    except:
        pass

def swallows_base():
    try:
        risky()
    except BaseException:
        cleanup()

def reraises():
    try:
        risky()
    except BaseException:
        cleanup()
        raise

def plain_exception_is_file_wide_tolerated():
    try:
        risky()
    except Exception:
        pass

def suppressed():
    try:
        risky()
    except:  # metrics-tpu: allow[A008]
        pass
'''


class TestA008:
    def test_swallowing_update_is_flagged(self):
        findings = _lint(SwallowingMetric)
        assert _active_rules(findings) == ["A008"]
        f = next(f for f in findings if f.rule == "A008")
        assert f.obj.startswith("SwallowingMetric")
        assert f.file and f.file.endswith("test_rules.py") and f.line

    def test_reraising_and_narrow_handlers_are_clean(self):
        assert "A008" not in _active_rules(_lint(ReRaisingMetric))
        assert "A008" not in _active_rules(_lint(NarrowHandlerMetric))

    def test_inline_allow_suppresses_but_reports(self):
        findings = _lint(SuppressedSwallowingMetric)
        assert [f.rule for f in findings] == ["A008"]
        assert findings[0].suppressed
        assert _active_rules(findings) == []

    def test_audit_flags_bare_and_baseexception_only(self):
        findings = ast_stage.lint_source("somefile.py", _EXCEPTY_SOURCE, set())
        a008 = [f for f in findings if f.rule == "A008"]
        # bare + BaseException without re-raise + the suppressed bare one;
        # the re-raising handler and the plain `except Exception` are not
        # audit findings (Exception breadth is only an error in jit-facing
        # metric methods)
        assert len(a008) == 3
        active = [f for f in a008 if not f.suppressed]
        assert len(active) == 2
        messages = " | ".join(f.message for f in active)
        assert "bare" in messages
        assert "BaseException" in messages

    def test_a008_is_an_error_severity_rule(self):
        assert RULES["A008"].severity == ERROR


# --------------------------------------------------------------------------- #
# E114 — heavy-eager-residue
# --------------------------------------------------------------------------- #
def _toy_net():
    return lambda x: x


class HeavyModelMetric(Metric):
    """E114 (a): a model-like attribute built in __init__ runs its forward
    from compute with no declared kernel path."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.net = _toy_net()
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)

    def compute(self):
        return self.net(self.total)


class HeavyLoopMetric(Metric):
    """E114 (b): compute runs a per-item Python loop calling back into self."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

    def update(self, values):
        self.total = self.total + jnp.sum(values)

    def _score_one(self, i):
        return self.total * i

    def compute(self):
        out = jnp.zeros(())
        for i in range(4):
            out = out + self._score_one(i)
        return out


class DeclaredHeavyMetric(HeavyModelMetric):
    """Control: the declaration names a real registry kernel, clearing E114."""

    heavy_kernels = ("feature_extract",)


class BogusDeclarationMetric(HeavyModelMetric):
    """E114: the declaration vouches for a kernel that does not exist."""

    heavy_kernels = ("not_a_kernel",)


class TestE114HeavyEagerResidue:
    def test_model_attr_without_declaration_is_E114(self):
        findings = _evaluate(HeavyModelMetric)
        e114 = [f for f in findings if f.rule == "E114" and not f.suppressed]
        assert len(e114) == 1, [f.rule for f in findings]
        assert e114[0].severity == "warning"
        assert e114[0].extra["model_attrs"] == ("net",)
        assert "heavy_kernels" in e114[0].message

    def test_compute_loop_without_declaration_is_E114(self):
        findings = _evaluate(HeavyLoopMetric)
        e114 = [f for f in findings if f.rule == "E114" and not f.suppressed]
        assert len(e114) == 1, [f.rule for f in findings]
        assert e114[0].extra["loop_method"] == "compute"
        assert e114[0].obj == "HeavyLoopMetric.compute"

    def test_registry_declaration_clears_E114(self):
        findings = _evaluate(DeclaredHeavyMetric)
        assert "E114" not in {f.rule for f in findings}

    def test_unknown_kernel_name_is_E114(self):
        findings = _evaluate(BogusDeclarationMetric)
        e114 = [f for f in findings if f.rule == "E114" and not f.suppressed]
        assert len(e114) == 1
        assert "not_a_kernel" in e114[0].message

    def test_clean_metric_has_no_E114(self):
        findings = _evaluate(CleanMetric)
        assert "E114" not in {f.rule for f in findings}

    def test_E114_is_suppressible_via_spec_allow(self):
        findings = _evaluate(HeavyModelMetric, dict(_SPEC, allow=("E114",)))
        e114 = [f for f in findings if f.rule == "E114"]
        assert e114 and all(f.suppressed for f in e114)

    def test_declared_heavies_in_repo_pass(self):
        """The shipped heavy metrics all declare registry kernels."""
        from metrics_tpu.ops.kernels import KERNELS

        for cls_name, mod in (
            ("MeanAveragePrecision", "metrics_tpu.detection"),
            ("BERTScore", "metrics_tpu.text.bert"),
            ("FrechetInceptionDistance", "metrics_tpu.image"),
            ("KernelInceptionDistance", "metrics_tpu.image"),
            ("InceptionScore", "metrics_tpu.image"),
            ("LearnedPerceptualImagePatchSimilarity", "metrics_tpu.image"),
        ):
            import importlib

            cls = getattr(importlib.import_module(mod), cls_name)
            declared = cls.heavy_kernels
            assert declared, f"{cls_name} must declare its heavy-kernel path"
            assert set(declared) <= set(KERNELS), f"{cls_name}: {declared}"


# --------------------------------------------------------------------------- #
# E115 — pinned tuned-plan drift (universe-level leg)
# --------------------------------------------------------------------------- #
class TestPlanDriftE115:
    """``plan_drift`` unit coverage per drift kind, plus the universe-level
    ``evaluate_plan_drift`` leg end-to-end under a pinned plan."""

    @staticmethod
    def _live(red="sum", dtype="float32", kind="psum", elements=8192,
              names=("total",), tolerance=None):
        return {
            "names": list(names), "reduction": red, "dtype": dtype,
            "kind": kind, "elements": elements, "tolerance": tolerance,
        }

    @staticmethod
    def _plan(buckets):
        from metrics_tpu.autotune.plan import TunedPlan

        return TunedPlan(buckets=buckets)

    def test_matching_plan_has_no_drift(self):
        from metrics_tpu.autotune.plan import plan_drift

        plan = self._plan({"sum|float32|psum": {"transport": "bf16"}})
        assert plan_drift(plan, [self._live()], world=8) == []

    def test_missing_bucket(self):
        from metrics_tpu.autotune.plan import plan_drift

        plan = self._plan({
            "sum|float32|psum": {"transport": "exact"},
            "mean|float64|psum": {"transport": "bf16"},
        })
        drift = plan_drift(plan, [self._live()], world=8)
        assert [d["kind"] for d in drift] == ["missing_bucket"]
        assert drift[0]["bucket"] == "mean|float64|psum"

    def test_stale_bucket(self):
        from metrics_tpu.autotune.plan import plan_drift

        plan = self._plan({"sum|float32|psum": {"transport": "exact"}})
        drift = plan_drift(
            plan,
            [self._live(), self._live(dtype="int32", names=("count",))],
            world=8,
        )
        assert [d["kind"] for d in drift] == ["stale_bucket"]
        assert drift[0]["bucket"] == "sum|int32|psum"

    def test_inadmissible_transport(self):
        from metrics_tpu.autotune.plan import plan_drift

        # pinned tolerance is tighter than the bf16 psum bound on world=8,
        # so the gate refuses the pin — it silently syncs exact at runtime
        plan = self._plan(
            {"sum|float32|psum": {"transport": "bf16", "tolerance": 0.001}}
        )
        drift = plan_drift(plan, [self._live()], world=8)
        assert [d["kind"] for d in drift] == ["inadmissible_transport"]
        assert "error_budget" in drift[0]["detail"]

    def test_live_declared_tolerance_wins_over_pinned(self):
        from metrics_tpu.autotune.plan import plan_drift

        # the live bucket's declared tolerance gates, not the plan's snapshot
        plan = self._plan({"sum|float32|psum": {"transport": "bf16"}})
        drift = plan_drift(plan, [self._live(tolerance=0.001)], world=8)
        assert [d["kind"] for d in drift] == ["inadmissible_transport"]

    def test_non_tunable_live_entries_are_ignored(self):
        from metrics_tpu.autotune.plan import plan_drift

        plan = self._plan({})
        drift = plan_drift(
            plan,
            [self._live(red="cat"), self._live(red=None, dtype="int64")],
            world=8,
        )
        assert drift == []

    # ------------------------------------------------------------------ #
    # the analyzer leg
    # ------------------------------------------------------------------ #
    @staticmethod
    def _entries():
        entry = Entry(cls=DeferredPinnedMetric, spec={"init": {}})
        entry.instance = DeferredPinnedMetric()
        return [entry]

    def test_pinned_drift_is_E115(self):
        import metrics_tpu

        plan = self._plan({
            "sum|float32|psum": {"transport": "exact"},
            "mean|float64|psum": {"transport": "bf16"},
        })
        metrics_tpu.set_autotune(plan)
        try:
            findings = eval_stage.evaluate_plan_drift(self._entries())
        finally:
            metrics_tpu.set_autotune(None)
        kinds = sorted(f.extra["kind"] for f in findings)
        assert kinds == ["missing_bucket", "stale_bucket"], [
            (f.obj, f.extra["kind"]) for f in findings
        ]
        assert all(f.rule == "E115" for f in findings)
        assert all(f.severity == "warning" for f in findings)
        assert all(f.obj.startswith("tuned_plan[") for f in findings)

    def test_exactly_matching_pin_has_no_E115(self):
        import metrics_tpu

        plan = self._plan({
            "sum|float32|psum": {"transport": "exact"},
            "sum|int32|psum": {"transport": "exact"},
        })
        metrics_tpu.set_autotune(plan)
        try:
            findings = eval_stage.evaluate_plan_drift(self._entries())
        finally:
            metrics_tpu.set_autotune(None)
        assert findings == []

    def test_live_tuning_has_no_E115(self):
        import metrics_tpu

        metrics_tpu.set_autotune(True)  # live tuning: nothing pinned to drift
        try:
            findings = eval_stage.evaluate_plan_drift(self._entries())
        finally:
            metrics_tpu.set_autotune(None)
        assert findings == []

    def test_autotune_off_has_no_E115(self):
        assert eval_stage.evaluate_plan_drift(self._entries()) == []

    def test_E115_rule_is_cataloged_as_warning(self):
        assert RULES["E115"].name == "autotune-plan-drift"
        assert RULES["E115"].severity == "warning"
