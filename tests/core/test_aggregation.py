"""Aggregator tests (reference parity: tests/bases/test_aggregation.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import CatMetric, MaxMetric, MeanMetric, MinMetric, SumMetric


@pytest.mark.parametrize(
    "cls,values,expected",
    [
        (SumMetric, [1.0, 2.0, 3.0], 6.0),
        (MaxMetric, [1.0, 5.0, 3.0], 5.0),
        (MinMetric, [4.0, 2.0, 3.0], 2.0),
        (MeanMetric, [1.0, 2.0, 3.0], 2.0),
    ],
)
def test_simple_aggregation(cls, values, expected):
    m = cls()
    for v in values:
        m.update(jnp.asarray(v))
    assert float(m.compute()) == pytest.approx(expected)


def test_cat_metric():
    m = CatMetric()
    m.update(jnp.asarray([1.0, 2.0]))
    m.update(jnp.asarray(3.0))
    np.testing.assert_allclose(np.asarray(m.compute()), [1.0, 2.0, 3.0])


def test_mean_weighted():
    m = MeanMetric()
    m.update(jnp.asarray([1.0, 2.0]), weight=jnp.asarray([1.0, 3.0]))
    assert float(m.compute()) == pytest.approx((1 + 6) / 4)


def test_nan_error():
    m = SumMetric(nan_strategy="error")
    with pytest.raises(RuntimeError, match="nan"):
        m.update(jnp.asarray([1.0, float("nan")]))


@pytest.mark.parametrize("strategy,expected", [("ignore", 3.0), (0.0, 3.0), (10.0, 13.0)])
def test_nan_strategies_sum(strategy, expected):
    m = SumMetric(nan_strategy=strategy)
    m.update(jnp.asarray([1.0, 2.0, float("nan")]))
    assert float(m.compute()) == pytest.approx(expected)


def test_nan_ignore_max():
    m = MaxMetric(nan_strategy="ignore")
    m.update(jnp.asarray([1.0, float("nan"), 3.0]))
    assert float(m.compute()) == 3.0


def test_nan_ignore_mean():
    m = MeanMetric(nan_strategy="ignore")
    m.update(jnp.asarray([2.0, float("nan"), 4.0]))
    assert float(m.compute()) == pytest.approx(3.0)


def test_invalid_strategy():
    with pytest.raises(ValueError, match="nan_strategy"):
        SumMetric(nan_strategy="bogus")


def test_aggregators_in_forward():
    m = SumMetric()
    out = m(jnp.asarray([1.0, 2.0]))
    assert float(out) == 3.0
    m(jnp.asarray(4.0))
    assert float(m.compute()) == 7.0


def test_nan_in_weight_ignored():
    m = MeanMetric(nan_strategy="ignore")
    m.update(jnp.asarray([1.0, 2.0]), weight=jnp.asarray([1.0, float("nan")]))
    assert float(m.compute()) == pytest.approx(1.0)


def test_nan_in_weight_error():
    m = MeanMetric(nan_strategy="error")
    with pytest.raises(RuntimeError, match="nan"):
        m.update(jnp.asarray([1.0]), weight=jnp.asarray([float("nan")]))


# ---- nan_strategy x aggregator product (reference test_aggregation.py:33-94) --
_NAN_VEC = [1.0, float("nan"), 3.0]


# impute value 10.0 is outside [1, 3] so every aggregator's impute result
# differs from its ignore result — a drop-instead-of-impute regression fails
@pytest.mark.parametrize(
    "cls,ignore_expected,impute_expected",
    [
        (SumMetric, 4.0, 14.0),
        (MeanMetric, 2.0, 14.0 / 3),
        (MaxMetric, 3.0, 10.0),
        (MinMetric, 1.0, 1.0),          # min insensitive to a high impute; covered by max
        (CatMetric, [1.0, 3.0], [1.0, 10.0, 3.0]),
    ],
    ids=["sum", "mean", "max", "min", "cat"],
)
@pytest.mark.parametrize("strategy", ["error", "warn", "ignore", 10.0], ids=str)
def test_nan_strategy_product(cls, ignore_expected, impute_expected, strategy):
    """Every aggregator x every nan policy on a nan-bearing vector: error
    raises; warn warns AND removes (reference aggregation.py:75-77 — warn is
    ignore plus the warning); ignore silently drops; float imputes."""
    m = cls(nan_strategy=strategy)
    if strategy == "error":
        with pytest.raises(RuntimeError, match="nan"):
            m.update(jnp.asarray(_NAN_VEC))
        return
    if strategy == "warn":
        with pytest.warns(UserWarning, match="[Nn]a[Nn]"):
            m.update(jnp.asarray(_NAN_VEC))
        np.testing.assert_allclose(np.asarray(m.compute()), ignore_expected, atol=1e-6)
        return
    m.update(jnp.asarray(_NAN_VEC))
    want = ignore_expected if strategy == "ignore" else impute_expected
    np.testing.assert_allclose(np.asarray(m.compute()), want, atol=1e-6)


@pytest.mark.parametrize("cls", [SumMetric, MeanMetric, MaxMetric, MinMetric], ids=["sum", "mean", "max", "min"])
def test_scalar_nan_update_ignored(cls):
    """A pure-nan scalar update under 'ignore' must leave the state unchanged."""
    m = cls(nan_strategy="ignore")
    m.update(jnp.asarray(2.0))
    m.update(jnp.asarray(float("nan")))
    m.update(jnp.asarray(4.0))
    want = {SumMetric: 6.0, MeanMetric: 3.0, MaxMetric: 4.0, MinMetric: 2.0}[cls]
    assert float(m.compute()) == pytest.approx(want)


def test_aggregator_ddp_world_merge():
    """Aggregator states across ranks fold with their own reductions."""
    from tests.helpers.testers import merge_world

    vals = np.arange(1.0, 9.0)
    for cls, want in [(SumMetric, vals.sum()), (MeanMetric, vals.mean()), (MaxMetric, 8.0), (MinMetric, 1.0)]:
        ranks = []
        for r in range(4):
            m = cls()
            m.update(jnp.asarray(vals[r::4]))
            ranks.append(m)
        assert float(merge_world(ranks).compute()) == pytest.approx(float(want)), cls.__name__


# ---- reference differential (aggregation.py classes run live) --------------
def _ref():
    from tests.conftest import reference_modular

    return reference_modular()


@pytest.mark.parametrize(
    "name", ["SumMetric", "MeanMetric", "MaxMetric", "MinMetric", "CatMetric"],
    ids=["sum", "mean", "max", "min", "cat"],
)
@pytest.mark.parametrize("nan_strategy", ["ignore", 7.0], ids=["ignore", "impute"])
def test_aggregators_vs_reference(name, nan_strategy):
    import metrics_tpu as M

    torch, tm = _ref()
    updates = [[1.0, float("nan"), 3.0], [5.0], [2.0, 4.0]]
    ours = getattr(M, name)(nan_strategy=nan_strategy)
    ref = getattr(tm, name)(nan_strategy=nan_strategy)
    for u in updates:
        ours.update(jnp.asarray(u))
        ref.update(torch.tensor(u))
    np.testing.assert_allclose(np.asarray(ours.compute()), np.asarray(ref.compute()), atol=1e-6)


def test_weighted_mean_vs_reference():
    torch, tm = _ref()
    ours, ref = MeanMetric(), tm.MeanMetric()
    ours.update(jnp.asarray([1.0, 2.0, 3.0]), weight=jnp.asarray([0.5, 1.5, 2.0]))
    ours.update(jnp.asarray(4.0), weight=jnp.asarray(3.0))
    ref.update(torch.tensor([1.0, 2.0, 3.0]), weight=torch.tensor([0.5, 1.5, 2.0]))
    ref.update(torch.tensor(4.0), weight=torch.tensor(3.0))
    np.testing.assert_allclose(float(ours.compute()), float(ref.compute()), atol=1e-6)
