"""Aggregator tests (reference parity: tests/bases/test_aggregation.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import CatMetric, MaxMetric, MeanMetric, MinMetric, SumMetric


@pytest.mark.parametrize(
    "cls,values,expected",
    [
        (SumMetric, [1.0, 2.0, 3.0], 6.0),
        (MaxMetric, [1.0, 5.0, 3.0], 5.0),
        (MinMetric, [4.0, 2.0, 3.0], 2.0),
        (MeanMetric, [1.0, 2.0, 3.0], 2.0),
    ],
)
def test_simple_aggregation(cls, values, expected):
    m = cls()
    for v in values:
        m.update(jnp.asarray(v))
    assert float(m.compute()) == pytest.approx(expected)


def test_cat_metric():
    m = CatMetric()
    m.update(jnp.asarray([1.0, 2.0]))
    m.update(jnp.asarray(3.0))
    np.testing.assert_allclose(np.asarray(m.compute()), [1.0, 2.0, 3.0])


def test_mean_weighted():
    m = MeanMetric()
    m.update(jnp.asarray([1.0, 2.0]), weight=jnp.asarray([1.0, 3.0]))
    assert float(m.compute()) == pytest.approx((1 + 6) / 4)


def test_nan_error():
    m = SumMetric(nan_strategy="error")
    with pytest.raises(RuntimeError, match="nan"):
        m.update(jnp.asarray([1.0, float("nan")]))


@pytest.mark.parametrize("strategy,expected", [("ignore", 3.0), (0.0, 3.0), (10.0, 13.0)])
def test_nan_strategies_sum(strategy, expected):
    m = SumMetric(nan_strategy=strategy)
    m.update(jnp.asarray([1.0, 2.0, float("nan")]))
    assert float(m.compute()) == pytest.approx(expected)


def test_nan_ignore_max():
    m = MaxMetric(nan_strategy="ignore")
    m.update(jnp.asarray([1.0, float("nan"), 3.0]))
    assert float(m.compute()) == 3.0


def test_nan_ignore_mean():
    m = MeanMetric(nan_strategy="ignore")
    m.update(jnp.asarray([2.0, float("nan"), 4.0]))
    assert float(m.compute()) == pytest.approx(3.0)


def test_invalid_strategy():
    with pytest.raises(ValueError, match="nan_strategy"):
        SumMetric(nan_strategy="bogus")


def test_aggregators_in_forward():
    m = SumMetric()
    out = m(jnp.asarray([1.0, 2.0]))
    assert float(out) == 3.0
    m(jnp.asarray(4.0))
    assert float(m.compute()) == 7.0


def test_nan_in_weight_ignored():
    m = MeanMetric(nan_strategy="ignore")
    m.update(jnp.asarray([1.0, 2.0]), weight=jnp.asarray([1.0, float("nan")]))
    assert float(m.compute()) == pytest.approx(1.0)


def test_nan_in_weight_error():
    m = MeanMetric(nan_strategy="error")
    with pytest.raises(RuntimeError, match="nan"):
        m.update(jnp.asarray([1.0]), weight=jnp.asarray([float("nan")]))
