"""Fused collection update engine: one donated jitted program per signature.

``MetricCollection.update()`` dispatches through :class:`CollectionUpdateEngine`
(``metrics_tpu/core/engine.py``): ONE jitted ``update_state`` over the joint
``{leader: state}`` pytree per (input-aval, state-aval) signature, donated in
steady state, with compute-group members skipped entirely during updates and
realiased lazily at observation points. These tests pin that contract:
domain-sweep parity (classification/regression/retrieval mixed in one
collection), donation safety when members share a state leaf, group-rebuild
invalidation of the fused cache, the permanent eager fallback when one member
is untraceable, and the ``fused_update`` switch surface.
"""
import pickle
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu
from metrics_tpu import (
    Accuracy,
    F1Score,
    MeanAbsoluteError,
    MeanSquaredError,
    Metric,
    MetricCollection,
    Precision,
    Recall,
    RetrievalMRR,
    StatScores,
)
from metrics_tpu.core import engine as engine_mod


@pytest.fixture(autouse=True)
def _engines_on():
    metrics_tpu.set_compiled_update(True)
    metrics_tpu.set_fused_update(True)
    yield
    metrics_tpu.set_compiled_update(None)
    metrics_tpu.set_fused_update(None)


def _data(n=64, c=5, seed=0):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(rng.standard_normal((n, c)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, c, n))
    return preds, target


def _binary_data(n=64, q=8, seed=0):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(rng.random(n).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 2, n))
    indexes = jnp.asarray(rng.integers(0, q, n))
    return preds, target, indexes


def _grouped_coll(**kw):
    return MetricCollection(
        {
            "precision": Precision(num_classes=5, average="macro"),
            "recall": Recall(num_classes=5, average="macro"),
            "f1": F1Score(num_classes=5, average="macro"),
            "acc": Accuracy(),
        },
        **kw,
    )


# ---------------------------------------------------------------- parity -----
class TestDomainSweepParity:
    def _mixed_coll(self, **kw):
        """Classification + regression + retrieval behind one call signature."""
        return MetricCollection(
            {
                "acc": Accuracy(),
                "prec": Precision(num_classes=None),
                "rec": Recall(num_classes=None),
                "mse": MeanSquaredError(),
                "mae": MeanAbsoluteError(),
                "mrr": RetrievalMRR(buffer_capacity=512),
            },
            **kw,
        )

    def test_mixed_domain_parity_and_fused_dispatch(self):
        fused = self._mixed_coll()
        eager = self._mixed_coll(fused_update=False)
        for s in range(5):
            p, t, i = _binary_data(seed=s)
            fused.update(p, t, indexes=i)
            eager.update(p, t, indexes=i)
        rf, re = fused.compute(), eager.compute()
        assert set(rf) == set(re)
        for k in rf:
            np.testing.assert_allclose(np.asarray(rf[k]), np.asarray(re[k]), rtol=1e-6)
        eng = fused._update_engine
        assert eng is not None and eng.broken is None
        assert eng.stats.compiled_calls >= 2  # fused program actually ran
        assert eager._update_engine is None

    def test_grouped_classification_parity(self):
        fused = _grouped_coll()
        eager = _grouped_coll(fused_update=False)
        for s in range(4):
            p, t = _data(seed=s)
            fused.update(p, t)
            eager.update(p, t)
        rf, re = fused.compute(), eager.compute()
        for k in rf:
            np.testing.assert_array_equal(np.asarray(rf[k]), np.asarray(re[k]))
        # stat-scores family fuses into one group: one state threads 3 members
        assert any(len(g) == 3 for g in fused._groups)

    def test_interleaved_observe_update_parity(self):
        """compute() mid-stream (members realias) must not perturb later
        fused updates."""
        fused = _grouped_coll()
        eager = _grouped_coll(fused_update=False)
        for s in range(6):
            p, t = _data(seed=s)
            fused.update(p, t)
            eager.update(p, t)
            if s % 2:
                rf, re = fused.compute(), eager.compute()
                for k in rf:
                    np.testing.assert_array_equal(np.asarray(rf[k]), np.asarray(re[k]))


# ----------------------------------------------------------- member skip -----
class TestMemberSkip:
    def test_members_detached_between_observations(self):
        coll = _grouped_coll()
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        # steady state: leaders advanced, members detached until observed
        assert coll._members_stale
        member = coll["recall"]  # __getitem__ realiases
        assert not coll._members_stale
        assert member._update_count == 3
        leader = coll["precision"]
        assert member.tp is leader.tp  # realias is reference assignment

    def test_update_counts_consistent_after_fused_runs(self):
        coll = _grouped_coll()
        p, t = _data()
        for _ in range(4):
            coll.update(p, t)
        counts = {k: m._update_count for k, m in coll.items(keep_base=True)}
        assert set(counts.values()) == {4}

    def test_reset_after_fused_updates(self):
        coll = _grouped_coll()
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        coll.reset()
        for k, m in coll.items(keep_base=True):
            assert m._update_count == 0
        coll.update(p, t)
        ref = _grouped_coll(fused_update=False)
        ref.update(p, t)
        r1, r2 = coll.compute(), ref.compute()
        for k in r1:
            np.testing.assert_array_equal(np.asarray(r1[k]), np.asarray(r2[k]))

    def test_clone_and_pickle_see_whole_members(self):
        coll = _grouped_coll()
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        assert coll._members_stale
        c = coll.clone()
        for k, m in c.items(keep_base=True):
            assert m._update_count == 3
            assert all(v is not None for v in m.metric_state.values())
        roundtrip = pickle.loads(pickle.dumps(coll))
        r1, r2 = roundtrip.compute(), coll.compute()
        for k in r1:
            np.testing.assert_array_equal(np.asarray(r1[k]), np.asarray(r2[k]))


# ------------------------------------------------------------- donation ------
@pytest.mark.skipif(
    not engine_mod.backend_supports_donation(), reason="backend has no buffer donation"
)
class TestDonationSafety:
    def test_steady_state_donates(self):
        coll = _grouped_coll()
        p, t = _data()
        for _ in range(5):
            coll.update(p, t)
        # call 1 eager, call 2 compiles (plain probe), calls 3+ donate
        assert coll._update_engine.stats.donated_calls >= 2

    def test_shared_state_leaf_survives_donation(self):
        """A caller-held reference into a group member's (leader-shared) state
        must never be invalidated by the fused program's donation."""
        coll = _grouped_coll()
        p, t = _data()
        for _ in range(4):
            coll.update(p, t)
        held = coll["recall"].tp  # realias: now aliases the leader's tp leaf
        donated_before = coll._update_engine.stats.donated_calls
        coll.update(p, t)  # refcount guard sees the extra reference: no donate
        assert coll._update_engine.stats.donated_calls == donated_before
        assert not held.is_deleted()
        _ = np.asarray(held)  # still readable
        del held
        coll.update(p, t)
        coll.update(p, t)
        assert coll._update_engine.stats.donated_calls > donated_before  # resumes

    def test_held_leader_snapshot_survives(self):
        coll = _grouped_coll()
        p, t = _data()
        for _ in range(4):
            coll.update(p, t)
        snap = coll["precision"].get_state()
        coll.update(p, t)
        assert all(not v.is_deleted() for v in snap.values())
        _ = [np.asarray(v) for v in snap.values()]


# ------------------------------------------------------------- rebuilds ------
class TestGroupRebuild:
    def test_rebuild_invalidates_fused_cache_and_realiases(self):
        coll = _grouped_coll()
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        assert coll._members_stale
        stale = coll._update_engine
        coll["stat"] = StatScores(reduce="macro", num_classes=5)
        # the rebuild realiased members BEFORE regrouping and dropped the engine
        assert not coll._members_stale
        assert coll._update_engine is None
        for name in ("recall", "f1"):
            m = coll[name]
            assert m._update_count == 3
            assert all(v is not None for v in m.metric_state.values())
        coll.update(p, t)
        assert coll._update_engine is not stale
        ref = Recall(num_classes=5, average="macro", compiled_update=False)
        for _ in range(4):
            ref.update(p, t)
        np.testing.assert_array_equal(
            np.asarray(coll.compute()["recall"]), np.asarray(ref.compute())
        )


# ------------------------------------------------------------- fallback ------
class _HostReadbackMetric(Metric):
    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds, target):
        if float(jnp.sum(preds)) > -1e30:  # host readback: untraceable
            self.total = self.total + jnp.sum(preds)

    def compute(self):
        return self.total


class TestEagerFallback:
    def test_one_untraceable_member_migrates_alone(self):
        """A runtime trace failure migrates only the culprit to the eager
        set; the rest of the collection keeps (a rebuilt) fused program
        instead of the old whole-collection eager demotion."""
        coll = MetricCollection(
            {"acc": Accuracy(), "host": _HostReadbackMetric()}
        )
        p = jnp.asarray(np.random.default_rng(0).random(32).astype(np.float32))
        t = jnp.asarray(np.random.default_rng(1).integers(0, 2, 32))
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            for _ in range(6):
                coll.update(p, t)
        assert any("engine disabled" in str(w.message) for w in caught)
        dispatcher = coll._dispatcher
        assert dispatcher.stats.migrations == 1
        assert set(dispatcher._migrated_update) == {"host"}
        part = dispatcher._partition
        assert part.update_fused == ("acc",)
        assert part.update_eager == ("host",)
        # the remainder's fused subset engine is live and compiled
        assert coll._update_engine.broken is None
        assert coll._update_engine.stats.compiled_calls >= 1
        # the retired engine's cause stays visible in the merged reasons
        assert any(k.startswith("update:") for k in coll.engine_stats()["fallback_reasons"])
        # every update landed: nothing was lost to the failed probe/migration
        np.testing.assert_allclose(
            float(coll.compute()["host"]), 6 * float(jnp.sum(p)), rtol=1e-6
        )
        assert coll["acc"]._update_count == 6

    def test_fallback_is_permanent_and_warns_once(self):
        coll = MetricCollection({"host": _HostReadbackMetric()})
        x = jnp.asarray([1.0, 2.0])
        t = jnp.asarray([1.0, 2.0])
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            for _ in range(6):
                coll.update(x, t)
        fused_warnings = [
            w for w in caught
            if "CollectionUpdateEngine" in str(w.message)
        ]
        assert len(fused_warnings) == 1


# --------------------------------------------------------------- switches ----
class TestSwitchSurface:
    def test_global_off_reverts_to_eager_loop(self):
        metrics_tpu.set_fused_update(False)
        coll = _grouped_coll()
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        assert coll._update_engine is None
        # member engines are governed separately and still compile
        leader_name = next(g[0] for g in coll._groups if len(g) > 1)
        leader = coll[leader_name]
        assert leader._update_engine is not None
        assert leader._update_engine.stats.compiled_calls >= 1

    def test_per_collection_true_overrides_global_false(self):
        metrics_tpu.set_fused_update(False)
        coll = _grouped_coll(fused_update=True)
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        assert coll._update_engine is not None
        assert coll._update_engine.stats.compiled_calls >= 1

    def test_per_collection_false_overrides_global_true(self):
        coll = _grouped_coll(fused_update=False)
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        assert coll._update_engine is None

    def test_env_flag_off(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_FUSED_UPDATE", "0")
        metrics_tpu.set_fused_update(None)  # follow the environment
        assert not engine_mod.fused_update_enabled()
        coll = _grouped_coll()
        p, t = _data()
        coll.update(p, t)
        coll.update(p, t)
        assert coll._update_engine is None

    def test_none_restores_env_default(self):
        metrics_tpu.set_fused_update(False)
        assert not engine_mod.fused_update_enabled()
        metrics_tpu.set_fused_update(None)
        assert engine_mod.fused_update_enabled()  # env default: on

    def test_compiled_update_false_also_gates_fused(self):
        # the fused engine layers on compiled_update: both must allow it
        coll = _grouped_coll(compiled_update=False)
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        assert coll._update_engine is None
