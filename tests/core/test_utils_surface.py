"""Direct tests for the small utility surfaces (prints, mesh helpers).

These modules were only exercised indirectly; the reference ships dedicated
utilities tests (tests/test_utilities.py), so the gated-logging contract and
the mesh constructors get their own assertions here.
"""
import logging
import warnings

import numpy as np
import pytest

import jax

from metrics_tpu.parallel.mesh import batch_sharded, data_parallel_mesh, make_mesh, replicated
from metrics_tpu.utils.prints import rank_zero_debug, rank_zero_info, rank_zero_only, rank_zero_warn


def test_rank_zero_warn_fires_on_process_zero():
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        rank_zero_warn("a warning for rank zero")
    assert any("a warning for rank zero" in str(w.message) for w in caught)


def test_rank_zero_only_suppresses_nonzero_rank(monkeypatch):
    import metrics_tpu.utils.prints as prints

    monkeypatch.setattr(prints, "_get_rank", lambda: 1)

    calls = []

    @rank_zero_only
    def record():
        calls.append(1)
        return "ran"

    assert record() is None
    assert calls == []


def test_rank_zero_log_levels(caplog):
    with caplog.at_level(logging.DEBUG, logger="metrics_tpu"):
        rank_zero_info("informational")
        rank_zero_debug("debugging")
    messages = [r.message for r in caplog.records]
    assert "informational" in messages and "debugging" in messages


def test_make_mesh_shapes_and_names():
    mesh = make_mesh((4, 2), ("data", "model"))
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (4, 2)


def test_make_mesh_rejects_mismatched_sizes():
    with pytest.raises(ValueError):
        make_mesh((3,), ("data",), devices=jax.devices()[:2])


def test_data_parallel_mesh_and_shardings():
    mesh = data_parallel_mesh(4)
    assert mesh.axis_names == ("data",)
    assert mesh.devices.shape == (4,)
    repl = replicated(mesh)
    shard = batch_sharded(mesh)
    x = np.zeros((8, 3), dtype=np.float32)
    replicated_x = jax.device_put(x, repl)
    sharded_x = jax.device_put(x, shard)
    assert len(replicated_x.sharding.device_set) == 4
    # batch axis split 4 ways: each shard holds 2 of the 8 rows
    assert sharded_x.addressable_shards[0].data.shape == (2, 3)
