"""Compiled-update engine: cache dispatch, donation safety, bucketing, rebuilds.

The engine (``metrics_tpu/core/engine.py``) makes plain ``metric.update()``
hit a cached jitted ``update_state`` from the second call per input signature.
These tests pin the dispatch contract: warmup-then-compile counting, the
donation aliasing guard (a caller-held state reference must never be
invalidated), bucketed-batch numeric parity against unpadded eager updates,
and MetricCollection group rebuilds dropping stale fused executables.
"""
import pickle
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu
from metrics_tpu import (
    AUROC,
    Accuracy,
    CatMetric,
    MeanMetric,
    Metric,
    MetricCollection,
    Precision,
    Recall,
    StatScores,
)
from metrics_tpu.core import engine as engine_mod


@pytest.fixture(autouse=True)
def _engine_on():
    metrics_tpu.set_compiled_update(True)
    yield
    metrics_tpu.set_compiled_update(None)


def _data(n=64, c=5, seed=0):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(rng.standard_normal((n, c)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, c, n))
    return preds, target


# --------------------------------------------------------------------- cache --
class TestCacheCounting:
    def test_warmup_then_hit(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(4):
            m.update(preds, target)
        stats = m._update_engine.stats
        assert stats.eager_calls == 1  # first call per signature runs eagerly
        assert stats.cache_misses == 1  # second call compiles
        assert stats.cache_hits == 2

    def test_new_signature_recompiles(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(2):
            m.update(preds, target)
        m.update(preds[:16], target[:16])  # new aval -> new warmup
        m.update(preds[:16], target[:16])
        stats = m._update_engine.stats
        assert stats.eager_calls == 2
        assert stats.cache_misses == 2

    def test_parity_with_eager(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        ref = StatScores(reduce="macro", num_classes=5, compiled_update=False)
        for _ in range(5):
            m.update(preds, target)
            ref.update(preds, target)
        assert ref._update_engine is None
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(ref.compute()))

    def test_global_switch(self):
        preds, target = _data()
        metrics_tpu.set_compiled_update(False)
        m = StatScores(reduce="macro", num_classes=5)
        m.update(preds, target)
        assert m._update_engine is None
        # per-instance True overrides the global False
        m2 = StatScores(reduce="macro", num_classes=5, compiled_update=True)
        m2.update(preds, target)
        m2.update(preds, target)
        assert m2._update_engine.stats.compiled_calls == 1

    def test_untraceable_update_falls_back_permanently(self):
        class HostUpdate(Metric):
            full_state_update = False

            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

            def update(self, x):
                if float(jnp.sum(x)) > -1e30:  # host readback: untraceable
                    self.total = self.total + jnp.sum(x)

            def compute(self):
                return self.total

        m = HostUpdate()
        x = jnp.asarray([1.0, 2.0])
        m.update(x)
        with pytest.warns(UserWarning, match="compiled-update engine disabled"):
            m.update(x)  # first compiled attempt fails the trace
        assert m._update_engine.broken is not None
        m.update(x)
        assert float(m.compute()) == 9.0  # all three updates applied eagerly
        assert m._update_engine.stats.compiled_calls == 0

    def test_list_state_metric_stays_eager(self):
        m = AUROC()  # unbounded list states -> not compilable
        rng = np.random.default_rng(0)
        p = jnp.asarray(rng.random(32).astype(np.float32))
        t = jnp.asarray(rng.integers(0, 2, 32))
        for _ in range(3):
            m.update(p, t)
        assert m._update_engine.stats.compiled_calls == 0


# ------------------------------------------------------------------ donation --
@pytest.mark.skipif(
    not engine_mod.backend_supports_donation(), reason="backend has no buffer donation"
)
class TestDonationSafety:
    def test_steady_state_donates(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(5):
            m.update(preds, target)
        # call 1 eager, call 2 compiles (plain probe), calls 3+ donate
        assert m._update_engine.stats.donated_calls >= 2

    def test_held_state_reference_survives(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(4):
            m.update(preds, target)
        held = m.tp  # caller keeps a reference into the state
        donated_before = m._update_engine.stats.donated_calls
        m.update(preds, target)
        assert m._update_engine.stats.donated_calls == donated_before
        assert not held.is_deleted()
        _ = np.asarray(held)  # still readable
        del held
        m.update(preds, target)
        m.update(preds, target)
        assert m._update_engine.stats.donated_calls > donated_before  # resumes

    def test_held_snapshot_survives(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(4):
            m.update(preds, target)
        snap = m.get_state()
        donated_before = m._update_engine.stats.donated_calls
        m.update(preds, target)
        assert m._update_engine.stats.donated_calls == donated_before
        assert all(not v.is_deleted() for v in snap.values())

    def test_defaults_never_donated(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(4):
            m.update(preds, target)
        m.reset()  # state now aliases the registered defaults
        donated_before = m._update_engine.stats.donated_calls
        m.update(preds, target)
        assert m._update_engine.stats.donated_calls == donated_before
        assert all(not jnp.asarray(v).is_deleted() for v in m._defaults.values())

    def test_donate_state_false_never_donates(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5, donate_state=False)
        for _ in range(6):
            m.update(preds, target)
        assert m._update_engine.stats.compiled_calls >= 4
        assert m._update_engine.stats.donated_calls == 0

    def test_donated_catbuffer_updates_in_place(self):
        rng = np.random.default_rng(0)
        p = jnp.asarray(rng.random(128).astype(np.float32))
        t = jnp.asarray(rng.integers(0, 2, 128))
        m = AUROC(buffer_capacity=4096)
        ref = AUROC(compiled_update=False)
        for _ in range(6):
            m.update(p, t)
            ref.update(p, t)
        assert m._update_engine.stats.donated_calls >= 2
        np.testing.assert_allclose(
            np.asarray(m.compute()), np.asarray(ref.compute()), rtol=1e-6
        )


# ----------------------------------------------------------------- bucketing --
class TestBatchBuckets:
    RAGGED = [100, 37, 64, 13, 100, 99, 5, 1]

    def test_mask_path_parity(self):
        rng = np.random.default_rng(1)
        m = StatScores(reduce="macro", num_classes=5, batch_buckets=True)
        ref = StatScores(reduce="macro", num_classes=5, compiled_update=False)
        for n in self.RAGGED:
            p = jnp.asarray(rng.standard_normal((n, 5)).astype(np.float32))
            t = jnp.asarray(rng.integers(0, 5, n))
            m.update(p, t)
            ref.update(p, t)
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(ref.compute()))
        stats = m._update_engine.stats
        assert stats.bucketed_calls == len(self.RAGGED)
        # ragged sizes collapse onto power-of-two buckets
        assert len(m._update_engine._seen) <= 5

    def test_chunk_path_parity(self):
        rng = np.random.default_rng(2)
        m = MeanMetric(batch_buckets=True)  # no sample_mask support -> chunks
        ref = MeanMetric(compiled_update=False)
        for n in self.RAGGED:
            v = jnp.asarray(rng.standard_normal(n).astype(np.float32))
            m.update(v)
            ref.update(v)
        np.testing.assert_allclose(
            np.asarray(m.compute()), np.asarray(ref.compute()), rtol=1e-5
        )
        assert m._update_engine.stats.bucketed_calls == len(self.RAGGED)

    def test_chunk_path_cat_order(self):
        rng = np.random.default_rng(3)
        m = CatMetric(buffer_capacity=1024, batch_buckets=True)
        ref = CatMetric(compiled_update=False)
        for n in [10, 33, 7]:
            v = jnp.asarray(rng.standard_normal(n).astype(np.float32))
            m.update(v)
            ref.update(v)
        np.testing.assert_allclose(np.asarray(m.compute()), np.asarray(ref.compute()))


# --------------------------------------------------------------- collections --
class TestCollectionEngine:
    def _coll(self, **kw):
        return MetricCollection(
            {
                "precision": Precision(num_classes=5, average="macro"),
                "recall": Recall(num_classes=5, average="macro"),
                "acc": Accuracy(),
            },
            **kw,
        )

    def test_fused_parity(self):
        preds, target = _data()
        coll = self._coll()
        ref = self._coll(compiled_update=False)
        for _ in range(4):
            coll.update(preds, target)
            ref.update(preds, target)
        r1, r2 = coll.compute(), ref.compute()
        for k in r1:
            np.testing.assert_allclose(np.asarray(r1[k]), np.asarray(r2[k]))
        stats = coll._update_engine.stats
        assert stats.eager_calls == 1 and stats.cache_misses == 1 and stats.cache_hits == 2

    def test_group_rebuild_invalidates_engine(self):
        preds, target = _data()
        coll = self._coll()
        for _ in range(3):
            coll.update(preds, target)
        stale = coll._update_engine
        assert stale is not None
        coll["f1"] = metrics_tpu.F1Score(num_classes=5, average="macro")
        assert coll._update_engine is None  # rebuild dropped the stale executable
        ref = MetricCollection(
            {
                "precision": Precision(num_classes=5, average="macro"),
                "recall": Recall(num_classes=5, average="macro"),
                "acc": Accuracy(),
                "f1": metrics_tpu.F1Score(num_classes=5, average="macro"),
            },
            compiled_update=False,
        )
        for _ in range(3):
            ref.update(preds, target)
        coll.update(preds, target)  # pre-rebuild updates for old members kept
        assert coll._update_engine is not stale
        # the new member's counts cover only post-rebuild updates
        f1_solo = metrics_tpu.F1Score(num_classes=5, average="macro", compiled_update=False)
        f1_solo.update(preds, target)
        np.testing.assert_allclose(
            np.asarray(coll.compute()["f1"]), np.asarray(f1_solo.compute())
        )

    def test_collection_flag_false_leaves_member_engines(self):
        preds, target = _data()
        coll = self._coll(compiled_update=False)
        for _ in range(3):
            coll.update(preds, target)
        assert coll._update_engine is None
        # group leaders still compile through their own per-metric engines
        leader = coll["precision"]
        assert leader._update_engine is not None
        assert leader._update_engine.stats.compiled_calls >= 1

    def test_member_shared_state_protected_from_member_engine(self):
        preds, target = _data(seed=4)
        coll = MetricCollection(
            {
                "precision": Precision(num_classes=5, average="macro"),
                "recall": Recall(num_classes=5, average="macro"),
            },
            compiled_update=False,  # eager loop shares leader state with members
        )
        for _ in range(4):
            coll.update(preds, target)
        recall = coll["recall"]
        assert recall._shared_state_ids  # sharing recorded
        # direct member updates must not donate the group-shared leaves
        donated = recall._update_engine.stats.donated_calls if recall._update_engine else 0
        recall.update(preds, target)
        recall.update(preds, target)
        precision = coll["precision"]
        assert all(
            not jnp.asarray(v).is_deleted() for v in precision.metric_state.values()
        )


# ------------------------------------------------------------- lifecycle ----
class TestLifecycle:
    def test_clone_and_pickle_drop_engine(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(3):
            m.update(preds, target)
        assert m._update_engine is not None
        c = m.clone()
        assert c._update_engine is None
        c.update(preds, target)  # engine rebuilds lazily
        p = pickle.loads(pickle.dumps(m))
        assert p._update_engine is None
        np.testing.assert_array_equal(np.asarray(p.compute()), np.asarray(m.compute()))

    def test_reset_keeps_compiled_cache(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(3):
            m.update(preds, target)
        misses = m._update_engine.stats.cache_misses
        m.reset()
        m.update(preds, target)  # same signature: straight to the cached executable
        assert m._update_engine.stats.cache_misses == misses
        ref = StatScores(reduce="macro", num_classes=5, compiled_update=False)
        ref.update(preds, target)
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(ref.compute()))


# ------------------------------------------------ dispatch-overhead guard ----
def test_jit_cached_dispatch_overhead_guard():
    """Tier-1 perf guard: the stateful jit-cached ``update()`` must stay within
    ~2x of driving the raw jitted ``update_state`` by hand (plus a fixed
    per-call bookkeeping floor for signature hashing / stats)."""
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.random(256).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 2, 256).astype(np.int32))

    raw = AUROC(buffer_capacity=256 * 64, compiled_update=False)
    step = jax.jit(raw.update_state)
    state = raw.init_state()
    state = step(state, preds, target)
    state = step(state, preds, target)
    jax.block_until_ready(state)

    def time_raw():
        s = step(raw.init_state(), preds, target)
        t0 = time.perf_counter()
        for _ in range(64):
            s = step(s, preds, target)
        jax.block_until_ready(s)
        return (time.perf_counter() - t0) / 64

    stateful = AUROC(buffer_capacity=256 * 64)
    for _ in range(3):
        stateful.update(preds, target)  # warm both buffer signatures

    def time_stateful():
        stateful.reset()
        stateful.update(preds, target)
        t0 = time.perf_counter()
        for _ in range(64):
            stateful.update(preds, target)
        jax.block_until_ready(stateful.preds.data)
        return (time.perf_counter() - t0) / 64

    raw_s = min(time_raw() for _ in range(3))
    stateful_s = min(time_stateful() for _ in range(3))
    assert stateful.supports_compiled_update
    assert stateful._update_engine.stats.compiled_calls > 64
    # steady-state dispatch must ride the id-keyed signature memo, not re-hash
    # (args repeat by object identity; state is re-seeded after every dispatch)
    assert stateful._update_engine.stats.key_fast_hits > 64
    # 2x relative + 150us absolute floor absorbs timer noise on tiny steps
    assert stateful_s <= 2.0 * raw_s + 150e-6, (
        f"stateful jit-cached update too slow: {stateful_s * 1e6:.1f}us/step vs "
        f"raw jitted {raw_s * 1e6:.1f}us/step"
    )


# ------------------------------------------------- signature fast path ------
class TestSignatureFastPath:
    def test_repeated_objects_hit_the_memo(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(6):
            m.update(preds, target)  # same array objects every call
        stats = m._update_engine.stats
        # both key halves fast-path in steady state: the args memo from the
        # second sighting on, the state memo from the first dispatch's seed
        assert stats.key_fast_hits >= 2 * (stats.cache_hits - 1)

    def test_fresh_arrays_still_dispatch_correctly(self):
        m = StatScores(reduce="macro", num_classes=5)
        ref = StatScores(reduce="macro", num_classes=5, compiled_update=False)
        for s in range(5):
            preds, target = _data(seed=s)  # new objects, same avals
            m.update(preds, target)
            ref.update(preds, target)
        stats = m._update_engine.stats
        # fresh args miss the id memo but land on the same compiled signature
        assert stats.cache_misses == 1 and stats.cache_hits == 3
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(ref.compute()))

    def test_collection_engine_fast_path(self):
        preds, target = _data()
        coll = MetricCollection(
            {
                "precision": Precision(num_classes=5, average="macro"),
                "recall": Recall(num_classes=5, average="macro"),
                "acc": Accuracy(),
            }
        )
        for _ in range(5):
            coll.update(preds, target)
        assert coll._update_engine.stats.key_fast_hits >= 4

    def test_memo_never_lies_across_mutation(self):
        """Interleaving signatures must re-derive keys, never serve a stale
        memo entry: parity against eager across alternating batch sizes."""
        m = StatScores(reduce="macro", num_classes=5)
        ref = StatScores(reduce="macro", num_classes=5, compiled_update=False)
        big, small = _data(n=64), _data(n=16, seed=1)
        for _ in range(3):
            for args in (big, small):
                m.update(*args)
                ref.update(*args)
        assert len(m._update_engine._seen) == 2  # one entry per signature
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(ref.compute()))

    def test_scalar_leaves_intern_into_the_memo(self):
        """Python scalars are not weakrefable, but _SigCache interns them by
        (type, value): a fresh 2.5 every call still hits the fast path."""
        m = MeanMetric()
        ref = MeanMetric(compiled_update=False)
        for _ in range(5):
            m.update(2.5)
            ref.update(2.5)
        assert m._update_engine.stats.compiled_calls >= 1
        assert m._update_engine.stats.key_fast_hits > 0
        np.testing.assert_allclose(np.asarray(m.compute()), np.asarray(ref.compute()))

    def test_scalar_interning_distinguishes_type_and_value(self):
        """Interned keys are (type, value): 2.5 vs 3.5 and 1 vs 1.0 must not
        collide, and correctness holds across interleavings."""
        m = MeanMetric()
        ref = MeanMetric(compiled_update=False)
        for _ in range(3):
            for v in (2.5, 3.5, 1, True):
                m.update(v)
                ref.update(v)
        np.testing.assert_allclose(np.asarray(m.compute()), np.asarray(ref.compute()))


# ------------------------------------------------------------- observability --
class TestEngineStatsObservability:
    def test_healthy_metric_reports_counters_and_no_reasons(self):
        m = StatScores(reduce="macro", num_classes=5)
        args = _data()
        for _ in range(3):
            m.update(*args)
        stats = m.engine_stats()
        assert stats["update"].compiled_calls >= 1
        assert stats["fallback_reasons"] == {}

    def test_fallback_reason_surfaces_in_engine_stats(self):
        class HostUpdate(Metric):
            full_state_update = False

            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

            def update(self, x):
                if float(jnp.sum(x)) > -1e30:  # host readback: untraceable
                    self.total = self.total + jnp.sum(x)

            def compute(self):
                return self.total

        m = HostUpdate()
        x = jnp.asarray([1.0, 2.0])
        m.update(x)
        with pytest.warns(UserWarning, match="compiled-update engine disabled"):
            m.update(x)
        reasons = m.engine_stats()["fallback_reasons"]
        assert "update:HostUpdate" in reasons
        assert "ConcretizationTypeError" in reasons["update:HostUpdate"] or reasons[
            "update:HostUpdate"
        ]

    def test_collection_engine_stats_include_members(self):
        coll = MetricCollection({"p": Precision(num_classes=5), "r": Recall(num_classes=5)})
        args = _data()
        for _ in range(3):
            coll.update(*args)
        stats = coll.engine_stats()
        assert stats["update"].compiled_calls >= 1
        assert set(stats["members"]) == {"p", "r"}
        assert stats["fallback_reasons"] == {}
