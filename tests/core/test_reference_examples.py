"""Differential sweep over the REFERENCE's own docstring examples.

Every deterministic example block in the reference's docstrings is executed
twice — once in torch against the reference implementation, once against
metrics_tpu with a jnp-backed ``torch`` shim — and each displayed value must
match numerically. This turns the reference's entire worked-example corpus
(the values its authors vouched for) into an automated parity oracle, without
copying any expected number into this repo.

Gated: skipped wholesale when the reference checkout or torch is unavailable.
"""
import doctest
import pathlib
import re
import sys
import types

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from tests.conftest import import_reference_torchmetrics  # noqa: E402

REFERENCE = pathlib.Path("/root/reference/torchmetrics")
import_reference_torchmetrics(allow_module_level=True)  # shim + sys.path, or skip

import jax.numpy as jnp  # noqa: E402

# sources that cannot run or compare here: model-downloading,
# optional-dependency, or printing non-numeric objects (RNG-based examples DO
# run: both sides draw from one shared seeded numpy generator, see _RNG)
_SKIP_TOKENS = (
    "pesq", "torchvision", "plot", "bert", "Bert",  # absent optional deps
    "MulticlassMode", "_gaussian", "_rouge_score_update",  # private helpers
    "nltk", "rouge",  # needs the punkt download
    "check_forward_no_full_state",  # timing probe, not a value
    "generator=",  # explicit torch.Generator plumbing can't be shimmed
    ".softmax(",  # torch tensor-method call; jax arrays have no method form
    "BootStrapper",  # resampling draws inside update differ by design
)


class _SharedRNG:
    """One numpy generator behind both frameworks' sampling calls, so an
    RNG-using reference example draws IDENTICAL values on both sides."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._rng = np.random.default_rng(20260730)

    @staticmethod
    def _shape(shape):
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            return tuple(shape[0])
        return shape or ()

    def randn(self, *shape):
        return self._rng.normal(size=self._shape(shape)).astype(np.float32)

    def rand(self, *shape):
        return self._rng.uniform(size=self._shape(shape)).astype(np.float32)

    def randint(self, *args, **kwargs):
        size = kwargs.get("size")
        if size is None and args and isinstance(args[-1], (tuple, list)):
            *args, size = args
        low, high = (0, args[0]) if len(args) == 1 else args[:2]
        return self._rng.integers(low, high, size=size or ())


_RNG = _SharedRNG()


class _TorchProxy:
    """Real torch, with sampling routed through the shared numpy generator."""

    def __getattr__(self, name):
        return getattr(torch, name)

    @staticmethod
    def manual_seed(seed):
        _RNG.reset()

    @staticmethod
    def randn(*shape, **kw):
        return torch.as_tensor(_RNG.randn(*shape))

    @staticmethod
    def rand(*shape, **kw):
        return torch.as_tensor(_RNG.rand(*shape))

    @staticmethod
    def randint(*args, **kw):
        return torch.as_tensor(np.asarray(_RNG.randint(*args, **kw)))

# a jnp-backed stand-in for the torch symbols reference examples actually use
_FAKE_TORCH = types.SimpleNamespace(
    tensor=jnp.asarray,
    Tensor=jnp.asarray,  # the constructor form torch.Tensor([...])
    reshape=jnp.reshape,
    arange=jnp.arange,
    ones=jnp.ones,
    zeros=jnp.zeros,
    linspace=jnp.linspace,
    float32=jnp.float32,
    float64=jnp.float64,
    float=jnp.float32,
    int32=jnp.int32,
    int64=jnp.int32,
    long=jnp.int32,
    bool=bool,
)
_FAKE_TORCH.manual_seed = lambda seed: _RNG.reset()
_FAKE_TORCH.randn = lambda *shape, **kw: jnp.asarray(_RNG.randn(*shape))
_FAKE_TORCH.rand = lambda *shape, **kw: jnp.asarray(_RNG.rand(*shape))
_FAKE_TORCH.randint = lambda *args, **kw: jnp.asarray(np.asarray(_RNG.randint(*args, **kw)))


def _collect_cases():
    parser = doctest.DocTestParser()
    cases = []
    for path in sorted(REFERENCE.rglob("*.py")):
        rel = str(path.relative_to(REFERENCE))
        # utilities/data.py carries the to_onehot/select_topk/... examples our
        # utils.data mirrors by name; other utilities modules are torch-internal
        if rel.startswith(("utilities", "setup_tools")) and rel != "utilities/data.py":
            continue
        for block in re.findall(r'"""(.*?)"""', path.read_text(), re.S):
            if ">>>" not in block:
                continue
            try:
                examples = parser.get_examples(block)
            except Exception:
                continue
            if not examples:
                continue
            source = "".join(e.source for e in examples)
            if any(tok in source for tok in _SKIP_TOKENS):
                continue
            if re.search(r"\b_[a-z]\w*\s*\(", source):
                # demonstrates reference-private helpers; the public surface is
                # the parity contract, the internal decomposition is not
                continue
            if re.search(r"\[[^\]]*\]\s*=[^=]", source):
                continue  # in-place subscript mutation: jax arrays are immutable
            cases.append(pytest.param(rel, examples, id=f"{rel}:{len(cases)}"))
    return cases


def _ref_module(rel: str):
    import importlib

    name = "torchmetrics." + rel[: -len(".py")].replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return importlib.import_module(name)


def _ours_extra_namespace(rel: str) -> dict:
    if rel == "utilities/data.py":
        import metrics_tpu.utils.data as our_data

        return {**vars(our_data), "Tensor": jnp.ndarray}  # bare Tensor used as a dtype filter
    return {}


def _exec_examples(examples, glb):
    """Run example statements, returning the values each displaying statement
    (one with expected output in the docstring) produced."""
    shown = []
    for example in examples:
        buf = []
        old_hook = sys.displayhook
        sys.displayhook = buf.append
        try:
            exec(compile(example.source, "<example>", "single"), glb)
        finally:
            sys.displayhook = old_hook
        if example.want.strip():
            shown.append(buf[-1] if buf else None)
    return shown


def _to_np(value):
    if isinstance(value, torch.Tensor):
        return value.detach().cpu().numpy()
    if isinstance(value, (list, tuple)):
        return [_to_np(v) for v in value]
    if isinstance(value, dict):
        return {k: _to_np(v) for k, v in value.items()}
    return np.asarray(value)


def _assert_close(want, got):
    if want is None and got is None:  # a print-based statement; nothing displayed
        return
    want, got = _to_np(want), _to_np(got)
    if isinstance(want, list):
        assert isinstance(got, (list, np.ndarray)) and len(want) == len(got)
        for w, g in zip(want, got):
            _assert_close(w, g)
    elif isinstance(want, dict):
        assert set(want) == set(got), (sorted(want), sorted(got))
        for key in want:
            _assert_close(want[key], got[key])
    else:
        np.testing.assert_allclose(
            np.asarray(want, dtype=np.float64), np.asarray(got, dtype=np.float64), atol=1e-4, rtol=1e-3
        )


@pytest.mark.parametrize("rel,examples", _collect_cases())
def test_reference_example_parity(rel, examples):
    import metrics_tpu
    import metrics_tpu.ops

    try:
        ref_glb = dict(vars(_ref_module(rel)))
    except Exception as err:  # optional-dep module
        pytest.skip(f"reference module unimportable: {err}")
    ref_glb.update(torch=_TorchProxy(), tensor=torch.tensor)
    # neutralize in-example torch imports on this side too: they would rebind
    # the RNG-sharing proxy back to the real module
    examples = [
        types.SimpleNamespace(
            source=re.sub(r"^(\s*)import torch\s*$", r"\1pass", e.source, flags=re.M), want=e.want
        )
        for e in examples
    ]
    _RNG.reset()
    try:
        want = _exec_examples(examples, ref_glb)
    except Exception as err:
        pytest.skip(f"reference-side example not runnable here: {err}")

    def _translate(src: str) -> str:
        src = src.replace("torchmetrics.functional", "metrics_tpu.ops").replace("torchmetrics", "metrics_tpu")
        # the jnp-backed ``torch`` shim is pre-seeded in the globals; real
        # torch imports inside an example must not rebind it
        src = re.sub(r"^(\s*)import torch\s*$", r"\1pass", src, flags=re.M)
        src = re.sub(r"^(\s*)from torch import tensor\s*$", r"\1pass", src, flags=re.M)
        src = src.replace(".long()", ".astype('int32')")
        return src

    source_ours = [types.SimpleNamespace(source=_translate(e.source), want=e.want) for e in examples]
    ours_glb = {**vars(metrics_tpu.ops), **vars(metrics_tpu), **_ours_extra_namespace(rel)}
    ours_glb.update(torch=_FAKE_TORCH, tensor=jnp.asarray, jnp=jnp)
    _RNG.reset()
    got = _exec_examples(source_ours, ours_glb)

    assert len(want) == len(got), f"displayed {len(got)} values, reference displayed {len(want)}"
    for w, g in zip(want, got):
        _assert_close(w, g)
