"""Full-inventory class-metric compile sweep.

Reference analog: tests/helpers/testers.py:163-176 — the reference
torch-scripts every class metric inside every test. The tpu equivalent is
this sweep: every exported class metric is instantiated from
tests/helpers/inventory.py and its pinned ``compile_level`` is ENFORCED:

- ``full``: one traced shard_map program runs update -> sync -> compute over
  the 8-device mesh and matches the eager sequential oracle on all shards.
- ``update_sync``: update+sync trace under shard_map; compute runs eagerly on
  the synced state and matches the oracle.
- ``buffered``: the default construction is eager-only (unbounded lists) AND
  the ``buffer_capacity`` variant achieves ``buffered_level``.
- ``eager_only``: ``supports_compiled_update`` is False by design (host rng,
  python-structured compute) and the eager path works.
- ``host``: update consumes python objects; eager end-to-end only.

A completeness guard asserts the inventory covers every exported Metric
subclass, so a newly added metric cannot silently skip the sweep.
"""
from __future__ import annotations

import importlib.util

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8 moved shard_map to the root namespace
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from tests.helpers.inventory import INVENTORY, exported_metric_classes

WORLD = 8


def _mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip(f"needs {WORLD} devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


def _as_calls(batch):
    """Normalize Entry.batch output to a list of (args, kwargs) update calls."""
    out = batch()
    return out if isinstance(out, list) else [out]


def _shard_call(args, d, world):
    return tuple(a[d * (a.shape[0] // world):(d + 1) * (a.shape[0] // world)] for a in args)


def _tree_close(a, b, atol=1e-4):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        xa = np.asarray(x, dtype=np.float64)
        assert np.isfinite(xa).all(), "non-finite sweep output (NaN==NaN must not mask a failure)"
        np.testing.assert_allclose(xa, np.asarray(y, dtype=np.float64), atol=atol, rtol=1e-3)


def _eager_oracle(make, calls):
    """Sequential eager update over every shard of every call, fresh instance."""
    m = make()
    for args, kwargs in calls:
        for d in range(WORLD):
            m.update(*_shard_call(args, d, WORLD), **kwargs)
    return m.compute()


def _run_level(make, batch, level):
    calls = _as_calls(batch)
    metric = make()
    assert metric.supports_compiled_update, (
        f"{type(metric).__name__} pinned as compiled but supports_compiled_update is False"
    )
    mesh = _mesh()
    flat_args = [a for args, _ in calls for a in args]
    assert all(a.shape[0] % WORLD == 0 for a in flat_args)
    static_kwargs = [kwargs for _, kwargs in calls]

    def update_and_sync(*all_shard_args):
        st = metric.get_state()
        i = 0
        for (args, _), kwargs in zip(calls, static_kwargs):
            n = len(args)
            st = metric.update_state(st, *all_shard_args[i:i + n], **kwargs)
            i += n
        return metric.sync_states(st, "data")

    in_specs = tuple(P("data") for _ in flat_args)

    if level == "full":
        def program(*all_shard_args):
            out = metric.compute_state(update_and_sync(*all_shard_args))
            return jax.tree_util.tree_map(lambda x: jnp.expand_dims(jnp.asarray(x), 0), out)

        fn = shard_map(program, mesh=mesh, in_specs=in_specs, out_specs=P("data"), check_vma=False)
        out = jax.jit(fn)(*flat_args)
        oracle = _eager_oracle(make, calls)
        for d in range(WORLD):  # every device row must equal the oracle
            _tree_close(jax.tree_util.tree_map(lambda x: x[d], out), oracle)
    elif level == "update_sync":
        def program(*all_shard_args):
            st = update_and_sync(*all_shard_args)
            return jax.tree_util.tree_map(lambda x: jnp.expand_dims(jnp.asarray(x), 0), dict(st))

        fn = shard_map(program, mesh=mesh, in_specs=in_specs, out_specs=P("data"), check_vma=False)
        synced = jax.jit(fn)(*flat_args)
        # CatBuffer states are pytrees, so tree_map rebuilds them intact.
        # compute on the SAME instance that traced the updates: mode-switching
        # metrics (e.g. AUROC) pin their input mode as python config during
        # update, outside the state pytree.
        st0 = jax.tree_util.tree_map(lambda x: x[0], synced)
        out = metric.compute_state(st0)
        _tree_close(out, _eager_oracle(make, calls))
    else:  # pragma: no cover
        raise AssertionError(level)


@pytest.mark.parametrize("name", sorted(INVENTORY), ids=str)
@pytest.mark.mesh8
def test_compile_sweep(name):
    entry = INVENTORY[name]
    if entry.skip and importlib.util.find_spec(entry.skip) is None:
        pytest.skip(f"optional dependency {entry.skip} absent")

    if entry.compile_level in ("full", "update_sync"):
        _run_level(entry.make, entry.batch, entry.compile_level)
    elif entry.compile_level == "buffered":
        plain = entry.make()
        assert not plain.supports_compiled_update, (
            f"{name} pinned 'buffered' but the default construction already compiles — "
            "promote its compile_level"
        )
        for args, kwargs in _as_calls(entry.batch):
            plain.update(*args, **kwargs)
        plain.compute()  # eager default path must work
        assert entry.buffered is not None, f"{name}: buffered factory missing"
        _run_level(entry.buffered, entry.batch, entry.buffered_level)
    elif entry.compile_level == "eager_only":
        m = entry.make()
        for args, kwargs in _as_calls(entry.batch):
            m.update(*args, **kwargs)
        m.compute()
        # the explicit assertion: this class does NOT claim the compiled path
        assert not getattr(m, "supports_compiled_update", False) or name in (
            "ClasswiseWrapper", "MinMaxMetric", "MultioutputWrapper", "CompositionalMetric",
        ), f"{name} pinned eager_only but reports supports_compiled_update"
    elif entry.compile_level == "host":
        m = entry.make()
        for args, kwargs in _as_calls(entry.batch):
            m.update(*args, **kwargs)
        m.compute()
    else:  # pragma: no cover
        raise AssertionError(entry.compile_level)


def test_inventory_is_complete():
    exported = set(exported_metric_classes())
    covered = set(INVENTORY)
    missing = exported - covered
    assert not missing, f"exported class metrics missing from the sweep inventory: {sorted(missing)}"
    stale = covered - exported
    assert not stale, f"inventory names not exported (renamed/removed?): {sorted(stale)}"
