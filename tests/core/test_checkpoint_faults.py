"""Fault injection against the checkpoint write protocol.

The protocol under test: every file lands in ``step_X.pending/`` via
mkstemp + fsync + rename; the COMMIT marker is written strictly last and the
pending directory is atomically renamed into place. A reader therefore only
ever sees (a) a fully committed snapshot or (b) nothing — and every
corruption mode below must surface as a clean, typed error, never as a
silently wrong restore.
"""
from __future__ import annotations

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, MeanMetric
from metrics_tpu.checkpoint import (
    CheckpointCorruptError,
    CheckpointMismatchError,
    CheckpointNotFoundError,
    restore_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)
from metrics_tpu.checkpoint import io as ckpt_io
from metrics_tpu.checkpoint.format import build_shard


def _batch(seed=0, n=32):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.uniform(0, 1, (n,)).astype(np.float32)),
        jnp.asarray(rng.integers(0, 2, (n,)).astype(np.int32)),
    )


def _committed_accuracy(root, seed=1):
    m = Accuracy()
    m.update(*_batch(seed=seed))
    save_checkpoint(m, str(root))
    return m


def _step_dir(root):
    step = ckpt_io.latest_step(str(root))
    return step, os.path.join(str(root), ckpt_io.step_dir_name(step))


# ------------------------------------------------- kill mid-write ------------
def test_kill_before_commit_leaves_old_snapshot_intact(tmp_path):
    m = _committed_accuracy(tmp_path, seed=1)
    ref = m.compute()

    # simulate preemption after the shard file landed but before commit:
    # the shard is in the pending dir, no COMMIT, no rename
    m.update(*_batch(seed=2))
    payload, shard_meta = build_shard(m)
    step2 = ckpt_io.next_step(str(tmp_path))
    pending = ckpt_io.pending_dir(str(tmp_path), step2)
    ckpt_io.write_shard(pending, 0, 2, payload, shard_meta)  # 1 of 2 shards: can't commit
    assert not ckpt_io.try_commit(str(tmp_path), step2, 2)

    # readers never see the aborted attempt
    assert ckpt_io.available_steps(str(tmp_path)) == [0]
    fresh = Accuracy()
    restore_checkpoint(fresh, str(tmp_path), host_index=0, host_count=1)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(fresh.compute()))

    # and the janitor reaps the orphan
    removed = ckpt_io.clean_pending(str(tmp_path))
    assert removed and not os.path.exists(pending)


def test_commit_requires_every_shard(tmp_path):
    m = Accuracy()
    m.update(*_batch(seed=3))
    payload, shard_meta = build_shard(m)
    pending = ckpt_io.pending_dir(str(tmp_path), 0)
    ckpt_io.write_shard(pending, 0, 2, payload, shard_meta)
    assert not ckpt_io.try_commit(str(tmp_path), 0, 2)
    ckpt_io.write_shard(pending, 1, 2, payload, shard_meta)
    assert ckpt_io.try_commit(str(tmp_path), 0, 2)
    assert ckpt_io.available_steps(str(tmp_path)) == [0]


def test_uncommitted_dir_is_invisible(tmp_path):
    # a step dir without a COMMIT marker (e.g. interrupted rename cleanup)
    os.makedirs(tmp_path / "step_0000000000")
    assert ckpt_io.available_steps(str(tmp_path)) == []
    with pytest.raises(CheckpointNotFoundError):
        restore_checkpoint(Accuracy(), str(tmp_path), host_index=0, host_count=1)


# ---------------------------------------------------- corruption -------------
def test_truncated_shard_raises_corrupt(tmp_path):
    m = _committed_accuracy(tmp_path, seed=4)
    step, step_dir = _step_dir(tmp_path)
    npz = [f for f in os.listdir(step_dir) if f.endswith(".npz")][0]
    path = os.path.join(step_dir, npz)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) // 2)
    with pytest.raises(CheckpointCorruptError, match="size|sha|bytes"):
        restore_checkpoint(Accuracy(), str(tmp_path), host_index=0, host_count=1)
    assert not verify_checkpoint(str(tmp_path)).ok


def test_bitflipped_shard_raises_corrupt(tmp_path):
    _committed_accuracy(tmp_path, seed=5)
    step, step_dir = _step_dir(tmp_path)
    npz = [f for f in os.listdir(step_dir) if f.endswith(".npz")][0]
    path = os.path.join(step_dir, npz)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF  # same size, different content -> sha catches
    open(path, "wb").write(bytes(data))
    with pytest.raises(CheckpointCorruptError, match="checksum"):
        restore_checkpoint(Accuracy(), str(tmp_path), host_index=0, host_count=1)


def test_tampered_manifest_raises(tmp_path):
    _committed_accuracy(tmp_path, seed=6)
    step, step_dir = _step_dir(tmp_path)
    mpath = os.path.join(step_dir, "MANIFEST.json")
    manifest = json.load(open(mpath))
    manifest["world_size"] = 99
    json.dump(manifest, open(mpath, "w"))
    with pytest.raises(CheckpointCorruptError, match="MANIFEST"):
        restore_checkpoint(Accuracy(), str(tmp_path), host_index=0, host_count=1)


def test_future_format_version_refused(tmp_path):
    _committed_accuracy(tmp_path, seed=7)
    step, step_dir = _step_dir(tmp_path)
    cpath = os.path.join(step_dir, "COMMIT")
    commit = json.load(open(cpath))
    commit["format_version"] = 999
    json.dump(commit, open(cpath, "w"))
    with pytest.raises(CheckpointMismatchError, match="format version"):
        restore_checkpoint(Accuracy(), str(tmp_path), host_index=0, host_count=1)


# ------------------------------------------------------- refusals ------------
def test_wrong_class_refused_before_state_touched(tmp_path):
    _committed_accuracy(tmp_path, seed=8)
    other = MeanMetric()
    other.update(jnp.asarray(41.0))
    with pytest.raises(CheckpointMismatchError, match="class"):
        restore_checkpoint(other, str(tmp_path), host_index=0, host_count=1)
    # refusal happened before any state was replaced
    np.testing.assert_allclose(np.asarray(other.compute()), 41.0)


def test_verify_payload_false_skips_checksums(tmp_path):
    m = _committed_accuracy(tmp_path, seed=9)
    step, step_dir = _step_dir(tmp_path)
    npz = [f for f in os.listdir(step_dir) if f.endswith(".npz")][0]
    path = os.path.join(step_dir, npz)
    data = bytearray(open(path, "rb").read())
    data[len(data) - 1] ^= 0x01
    open(path, "wb").write(bytes(data))
    with pytest.raises(CheckpointCorruptError):
        restore_checkpoint(Accuracy(), str(tmp_path), host_index=0, host_count=1, verify_payload=True)
