"""CatBuffer overflow surfacing at update time (ISSUE-18 satellite).

Compiled appends beyond capacity silently overwrite the buffer tail and only
blow up later, at ``to_array()`` inside compute. The facade now surfaces the
sticky ``overflowed`` flag the first time it flips: a
``metrics_tpu_catbuffer_overflows_total{owner}`` counter, a one-shot warning,
and a ``buffer/overflow`` tracer instant. ``reset()`` re-arms the one-shot.
"""
import warnings

import jax.numpy as jnp
import pytest

from metrics_tpu import CatMetric, observability
from metrics_tpu.observability import tracer as otrace


def _overflow_warnings(records):
    return [str(w.message) for w in records if "overflowed its capacity" in str(w.message)]


def _counter(owner):
    return observability.get_registry().counter("catbuffer_overflows_total", owner=owner)


@pytest.fixture()
def overflowing_metric():
    # compiled update: static shapes, so appends past capacity clamp + flag
    m = CatMetric(buffer_capacity=4, compiled_update=True)
    return m


def _push_8_rows(m):
    for i in range(4):
        m.update(jnp.arange(2, dtype=jnp.float32) + i)


def test_overflow_reported_once_with_counter_and_trace(overflowing_metric):
    m = overflowing_metric
    before = _counter("CatMetric.value").value
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        with otrace.trace() as tr:
            _push_8_rows(m)
    msgs = _overflow_warnings(rec)
    assert len(msgs) == 1  # one-shot, even though updates kept overflowing
    assert "CatMetric.value" in msgs[0] and "buffer_capacity" in msgs[0]
    assert _counter("CatMetric.value").value == before + 1
    events = [e for e in tr.events() if e.name == "buffer/overflow"]
    assert len(events) == 1
    assert events[0].cat == "buffer"
    assert events[0].args == {"owner": "CatMetric.value", "capacity": 4}


def test_reset_rearms_the_one_shot(overflowing_metric):
    m = overflowing_metric
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        _push_8_rows(m)
    before = _counter("CatMetric.value").value
    m.reset()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        _push_8_rows(m)
    assert len(_overflow_warnings(rec)) == 1
    assert _counter("CatMetric.value").value == before + 1


def test_eager_growth_never_warns():
    # eager appends grow the buffer geometrically — no overflow, no report
    m = CatMetric(buffer_capacity=2, compiled_update=False)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        for i in range(5):
            m.update(jnp.arange(2, dtype=jnp.float32) + i)
    assert not _overflow_warnings(rec)
    assert len(m.value) == 10


def test_within_capacity_never_warns():
    m = CatMetric(buffer_capacity=64, compiled_update=True)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        _push_8_rows(m)
    assert not _overflow_warnings(rec)


def test_catalog_lists_the_event():
    from metrics_tpu.observability.tracer import EVENT_CATALOG

    assert EVENT_CATALOG["buffer"] == ("buffer/overflow",)
