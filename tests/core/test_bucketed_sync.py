"""Coalesced (bucketed) state sync: one collective per (reduction, dtype).

``sync_state`` (``metrics_tpu/parallel/sync.py``) buckets state leaves by
``(reduction, dtype)`` into one flat buffer and emits a single
``psum``/``pmean``/``pmax``/``pmin``/``all_gather`` per bucket — the gradient
bucketing trick applied to metric state. These tests pin the contract: bitwise
parity against the per-leaf path on the 8-device CPU mesh (metrics, mixed
pytrees, and whole collections), trace-time collective counts actually
shrinking, the ``set_bucketed_sync`` switch surface, callables staying
per-leaf, and the container-type regression (a tuple state must come back a
tuple, not a list — drift changes the pytree structure across a sync and
forces recompiles).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu
from metrics_tpu import MetricCollection, Precision, Recall, StatScores
from metrics_tpu.parallel import sync as sync_mod
from metrics_tpu.parallel.sync import count_collectives, sync_state

WORLD = 8


@pytest.fixture(autouse=True)
def _bucketed_default():
    metrics_tpu.set_bucketed_sync(None)
    yield
    metrics_tpu.set_bucketed_sync(None)


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


# mixed reductions, dtypes, ranks — exercises every bucket shape at once
_STATE = {
    "tp": jnp.arange(5, dtype=jnp.float32),
    "fp": jnp.full((5,), 2.0, jnp.float32),
    "n": jnp.asarray(3.0, jnp.float32),
    "running_mean": jnp.asarray(0.25, jnp.float32),
    "mx": jnp.asarray(7.0, jnp.float32),
    "hits": jnp.arange(4, dtype=jnp.int32),
    "misses": jnp.asarray([9, 1], jnp.int32),
    "chunks": jnp.arange(6, dtype=jnp.float32).reshape(3, 2),
    "per_device": jnp.asarray([1.0, 2.0]),
    "extra": jnp.asarray([0.5, 1.5, 2.5]),
}
_REDS = {
    "tp": "sum",
    "fp": "sum",
    "n": "mean",
    "running_mean": "mean",
    "mx": "max",
    "hits": "sum",
    "misses": "sum",
    "chunks": "cat",
    "per_device": None,
    "extra": None,
}


def _per_device_states(state):
    """(WORLD, ...) inputs whose per-device slice is one device's local state."""
    return jax.tree_util.tree_map(
        lambda a: jnp.stack([a * (i + 1) for i in range(WORLD)]), state
    )


def _run_sync(mesh, state, reds, bucketed):
    def body(s):
        local = jax.tree_util.tree_map(lambda x: x[0], s)
        out = sync_state(local, reds, "data", bucketed=bucketed)
        return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), out)

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
    return jax.jit(f)(_per_device_states(state))


def _trace_count(reds, state, bucketed):
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda st: sync_state(st, reds, "data", bucketed=bucketed),
            axis_env=[("data", WORLD)],
        )(state)
    return box["count"]


# ----------------------------------------------------------------- parity ----
@pytest.mark.mesh8
def test_bitwise_parity_vs_per_leaf(mesh):
    out_b = _run_sync(mesh, _STATE, _REDS, bucketed=True)
    out_p = _run_sync(mesh, _STATE, _REDS, bucketed=False)
    flat_b, td_b = jax.tree_util.tree_flatten(out_b)
    flat_p, td_p = jax.tree_util.tree_flatten(out_p)
    assert td_b == td_p  # identical pytree structure
    for a, b in zip(flat_b, flat_p):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # bitwise


@pytest.mark.mesh8
def test_metric_sync_states_bitwise_parity(mesh):
    """A real metric's sync_states: bucketed vs per-leaf inside shard_map."""
    m = StatScores(reduce="macro", num_classes=5, compiled_compute=False)
    rng = np.random.default_rng(3)
    preds = jnp.asarray(rng.standard_normal((WORLD, 16, 5)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 5, (WORLD, 16)))

    def run(bucketed):
        def body(p, t):
            state = m.update_state(m.init_state(), p[0], t[0])
            synced = sync_state(state, m._reductions, "data", bucketed=bucketed)
            return jnp.expand_dims(m.compute_state(synced), 0)

        return np.asarray(
            jax.jit(
                shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
            )(preds, target)
        )

    np.testing.assert_array_equal(run(True), run(False))


@pytest.mark.mesh8
def test_collection_sync_states_bitwise_parity(mesh):
    """Whole-collection sync: the group-leader state set syncs bucketed."""
    coll = MetricCollection(
        {
            "precision": Precision(num_classes=5, average="macro"),
            "recall": Recall(num_classes=5, average="macro"),
        },
        compiled_compute=False,
    )
    rng = np.random.default_rng(4)
    preds = jnp.asarray(rng.standard_normal((WORLD, 16, 5)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 5, (WORLD, 16)))

    def run(bucketed):
        metrics_tpu.set_bucketed_sync(bucketed)
        try:
            def body(p, t):
                states = coll.update_state(coll.init_state(p[0], t[0]), p[0], t[0])
                vals = coll.sync_compute_state(states, axis_name="data")
                return {k: jnp.expand_dims(v, 0) for k, v in vals.items()}

            return jax.jit(
                shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
            )(preds, target)
        finally:
            metrics_tpu.set_bucketed_sync(None)

    out_b, out_p = run(True), run(False)
    assert set(out_b) == set(out_p)
    for k in out_b:
        np.testing.assert_array_equal(np.asarray(out_b[k]), np.asarray(out_p[k]))


# ------------------------------------------------------- container types -----
@pytest.mark.mesh8
def test_tuple_state_stays_tuple(mesh):
    """Regression: tuple states used to come back as [synced] lists, changing
    the pytree structure across a sync and forcing recompiles."""
    state = {"buf": (jnp.arange(3, dtype=jnp.float32),), "n": jnp.asarray(1.0)}
    reds = {"buf": "cat", "n": "sum"}
    for bucketed in (True, False):
        out = _run_sync(mesh, state, reds, bucketed=bucketed)
        assert isinstance(out["buf"], tuple), f"bucketed={bucketed}"
        assert len(out["buf"]) == 1
    # list states keep coming back as lists
    lstate = {"buf": [jnp.arange(3, dtype=jnp.float32)], "n": jnp.asarray(1.0)}
    out = _run_sync(mesh, lstate, reds, bucketed=True)
    assert isinstance(out["buf"], list)


def test_sync_preserves_key_order():
    """Bucketing reorders work internally; the output dict must not notice
    (checked inside the trace — jit boundaries re-sort dict pytrees anyway)."""
    state = {"z": jnp.asarray(1.0), "a": jnp.asarray(2.0), "m": jnp.asarray(3.0)}
    reds = {"z": "sum", "a": "sum", "m": "mean"}
    captured = {}

    def run(st):
        out = sync_state(st, reds, "data", bucketed=True)
        captured["in"], captured["out"] = list(st), list(out)
        return out

    jax.make_jaxpr(run, axis_env=[("data", WORLD)])(state)
    assert captured["out"] == captured["in"]
    assert list(sync_state(state, reds, None)) == list(state)  # no-axis path too


# ------------------------------------------------------ collective counts ----
def test_collective_count_shrinks():
    per_leaf = _trace_count(_REDS, _STATE, bucketed=False)
    bucketed = _trace_count(_REDS, _STATE, bucketed=True)
    assert per_leaf == len(_STATE)
    # buckets: f32-sum(3), f32-mean(2), f32-max(1), i32-sum(2), cat(1), None(2)
    assert bucketed == 6
    assert bucketed < per_leaf


def test_singleton_buckets_match_per_leaf_count():
    state = {"a": jnp.asarray(1.0), "b": jnp.arange(3, dtype=jnp.int32)}
    reds = {"a": "sum", "b": "sum"}  # different dtypes: two singleton buckets
    assert _trace_count(reds, state, bucketed=True) == 2


@pytest.mark.mesh8
def test_stat_scores_collection_counts(mesh):
    """The config2-shaped sync: a stat-scores state (5 same-dtype sum leaves)
    collapses to ONE psum."""
    m = StatScores(reduce="macro", num_classes=5)
    state = m.init_state()
    assert _trace_count(m._reductions, state, bucketed=False) == len(state)
    assert _trace_count(m._reductions, state, bucketed=True) == 1


# --------------------------------------------------------------- switches ----
def test_global_switch_controls_default():
    m = StatScores(reduce="macro", num_classes=5)
    state = m.init_state()
    metrics_tpu.set_bucketed_sync(False)
    assert not sync_mod.bucketed_sync_enabled()
    assert _trace_count(m._reductions, state, bucketed=None) == len(state)
    metrics_tpu.set_bucketed_sync(True)
    assert _trace_count(m._reductions, state, bucketed=None) == 1


def test_explicit_arg_beats_global():
    m = StatScores(reduce="macro", num_classes=5)
    state = m.init_state()
    metrics_tpu.set_bucketed_sync(False)
    assert _trace_count(m._reductions, state, bucketed=True) == 1
    metrics_tpu.set_bucketed_sync(True)
    assert _trace_count(m._reductions, state, bucketed=False) == len(state)


def test_env_flag(monkeypatch):
    metrics_tpu.set_bucketed_sync(None)
    monkeypatch.setenv("METRICS_TPU_BUCKETED_SYNC", "0")
    assert not sync_mod.bucketed_sync_enabled()
    monkeypatch.setenv("METRICS_TPU_BUCKETED_SYNC", "1")
    assert sync_mod.bucketed_sync_enabled()


# ------------------------------------------------------------- callables -----
@pytest.mark.mesh8
def test_callable_reduction_stays_per_leaf(mesh):
    """Custom dist_reduce_fx callables see the stacked (world, ...) gather —
    bucketing must leave them alone."""
    merge = lambda stacked: jnp.sum(stacked, axis=0) * 10.0
    state = {"a": jnp.asarray([1.0, 2.0]), "b": jnp.asarray([3.0, 4.0])}
    reds = {"a": merge, "b": "sum"}
    out = _run_sync(mesh, state, reds, bucketed=True)
    # merge over stacked per-device (i+1)-scaled values: sum_i (i+1)*x * 10
    scale = sum(range(1, WORLD + 1))
    np.testing.assert_allclose(
        np.asarray(out["a"])[0], np.asarray([1.0, 2.0]) * scale * 10.0, rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(out["b"])[0], np.asarray([3.0, 4.0]) * scale, rtol=1e-6
    )


# ------------------------------------------------------------ by-kind tally --
def test_count_collectives_tallies_by_kind():
    """The counter box breaks the tally down per collective kind — the
    analyzer's E106 diagnostics depend on this split."""
    state = {
        "s": jnp.zeros((3,)),
        "m": jnp.zeros((3,)),
        "hi": jnp.zeros(()),
        "lo": jnp.zeros(()),
        "g": jnp.zeros((2,)),
    }
    reds = {"s": "sum", "m": "mean", "hi": "max", "lo": "min", "g": None}
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda st: sync_state(st, reds, "data", bucketed=False),
            axis_env=[("data", WORLD)],
        )(state)
    assert box["by_kind"] == {"psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "all_gather": 1}
    assert box["count"] == 5


def test_count_collectives_nested_boxes_unwind_by_identity():
    """Nested boxes with identical contents (e.g. the engine's first-compile
    capture — usually empty — inside a user-level box) must pop their own box
    on exit, not the first *equal* one; otherwise later ticks are credited to
    the dead inner box and the outer exit raises ValueError."""
    from metrics_tpu.parallel.sync import _tick_collective

    with count_collectives() as outer:
        with count_collectives() as inner:
            pass  # both boxes are identical empty dicts at this exit
        _tick_collective("psum", 16)
    assert outer["by_kind"] == {"psum": 1}
    assert outer["bytes_by_kind"] == {"psum": 16}
    assert inner["count"] == 0


def test_bucketed_coalesces_by_kind():
    state = {k: jnp.zeros((4,)) for k in ("a", "b", "c")}
    reds = {k: "sum" for k in state}
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda st: sync_state(st, reds, "data", bucketed=True),
            axis_env=[("data", WORLD)],
        )(state)
    assert box["by_kind"] == {"psum": 1}  # one bucket, one collective


# ------------------------------------------------------------ CatBuffers -----
from metrics_tpu.core.buffers import CatBuffer  # noqa: E402

_CAP = 8


def _device_buffers(i):
    """Per-device CatBuffers with axis-index-dependent fill counts; rows past
    the count are sentinel garbage the compaction must drop."""
    base = jnp.arange(_CAP, dtype=jnp.float32)
    fbuf = CatBuffer(base + 100.0 * i.astype(jnp.float32), (i % 3) + 1)
    ibuf = CatBuffer(jnp.arange(_CAP, dtype=jnp.int32) + 1000 * i, (i % 5) + 1)
    return fbuf, ibuf


def _run_buffer_sync(mesh, bucketed):
    reds = {"fbuf": "cat", "ibuf": "cat", "n": "sum"}

    def body(n):
        i = jax.lax.axis_index("data")
        fbuf, ibuf = _device_buffers(i)
        out = sync_state({"fbuf": fbuf, "ibuf": ibuf, "n": n[0]}, reds, "data", bucketed=bucketed)
        flat = (
            out["fbuf"].data, out["fbuf"].count, out["fbuf"].overflowed,
            out["ibuf"].data, out["ibuf"].count, out["ibuf"].overflowed,
            out["n"],
        )
        return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), flat)

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
    return jax.jit(f)(jnp.ones((WORLD,), jnp.float32))


@pytest.mark.mesh8
def test_catbuffer_bitwise_parity_vs_gather(mesh):
    """Bucketed CatBuffer sync (one stacked meta gather + one data gather per
    dtype) must be bitwise-identical to per-buffer ``CatBuffer.gather``."""
    out_b = _run_buffer_sync(mesh, bucketed=True)
    out_p = _run_buffer_sync(mesh, bucketed=False)
    for a, b in zip(out_b, out_p):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.mesh8
def test_catbuffer_sync_content(mesh):
    """The synced buffer holds the device-order concatenation of every
    device's valid prefix, at capacity WORLD * cap."""
    data, count, overflowed = (np.asarray(x)[0] for x in _run_buffer_sync(mesh, bucketed=True)[:3])
    expected = np.concatenate(
        [(np.arange(_CAP, dtype=np.float32) + 100.0 * i)[: (i % 3) + 1] for i in range(WORLD)]
    )
    assert data.shape[0] == WORLD * _CAP
    assert count == expected.shape[0]
    assert not overflowed
    np.testing.assert_array_equal(data[: count], expected)


def test_catbuffer_collective_count():
    """Buffers join the bucketed plan: 3 buffers cost 1 meta gather + 1 data
    gather per item dtype instead of 3 collectives each."""
    i0 = jnp.asarray(0, jnp.int32)
    fbuf, ibuf = _device_buffers(i0)
    fbuf2 = CatBuffer(jnp.ones((_CAP,), jnp.float32), 2)
    state = {"fbuf": fbuf, "fbuf2": fbuf2, "ibuf": ibuf, "n": jnp.asarray(1.0)}
    reds = {"fbuf": "cat", "fbuf2": "cat", "ibuf": "cat", "n": "sum"}
    assert _trace_count(reds, state, bucketed=False) == 3 * 3 + 1
    assert _trace_count(reds, state, bucketed=True) == 1 + 2 + 1  # meta + {f32,i32} + sum


def test_unmaterialized_catbuffer_passthrough():
    """An empty (never-appended) buffer has no item dtype/shape to gather —
    it passes through both paths untouched and costs no collectives."""
    state = {"buf": CatBuffer.empty(_CAP), "n": jnp.asarray(1.0)}
    reds = {"buf": "cat", "n": "sum"}
    for bucketed in (True, False):
        assert _trace_count(reds, state, bucketed=bucketed) == 1

    out = sync_state({"buf": CatBuffer.empty(_CAP)}, {"buf": "cat"}, None)
    assert not out["buf"].materialized
