"""Quantized & compressed sync transports (``metrics_tpu/parallel/sync.py``).

The transport layer is an opt-in codec per sync bucket — ``exact`` (default,
bitwise), ``bf16`` (cast-psum-upcast), ``int8`` (blockwise max-abs scales,
two-phase scale exchange + quantized psum), ``sparse_count`` (index+value
gather for near-empty count buckets). These tests pin the contract on the
8-device CPU mesh: the all-exact configuration is the *same code path* as
before the layer existed (bitwise, identical collective counts); quantized
buckets land within both their declared tolerance and the abstract
``transport_error_bound``; the error-budget gate refuses over-budget buckets
with a reason-carrying record and falls back bitwise; wire-vs-logical byte
accounting per transport feeds the bench/observability surfaces; selection
precedence is per-state declaration > global switch > env; and transport is
configuration, never state — checkpoints interchange across declarations.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu
from metrics_tpu import Metric
from metrics_tpu.parallel import sync as sync_mod
from metrics_tpu.parallel.sync import (
    DEFAULT_TOLERANCES,
    TRANSPORTS,
    count_collectives,
    set_sync_transport,
    sync_state,
    sync_transport_default,
    transport_error_bound,
    transport_plan,
)

WORLD = 8


@pytest.fixture(autouse=True)
def _exact_default():
    set_sync_transport(None)
    yield
    set_sync_transport(None)


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


# mixed dtypes/reductions: f32 sum, i32 sum (count-like), f32 max, gather
_STATE = {
    "fsum": jnp.linspace(0.1, 40.0, 50, dtype=jnp.float32),
    "fsum2": jnp.asarray(3.5, jnp.float32),
    "counts": (jnp.arange(1000, dtype=jnp.int32) % 7),
    "hits": jnp.asarray(3, jnp.int32),
    "mx": jnp.asarray([7.0, 1.0], jnp.float32),
    "gather": jnp.asarray([1.0, 2.0]),
}
_REDS = {
    "fsum": "sum", "fsum2": "sum", "counts": "sum", "hits": "sum",
    "mx": "max", "gather": None,
}


def _per_device(state):
    return jax.tree_util.tree_map(
        lambda a: jnp.stack([a * (i + 1) for i in range(WORLD)]), state
    )


def _run_sync(mesh, state, reds, transports=None, tolerances=None):
    def body(s):
        local = jax.tree_util.tree_map(lambda x: x[0], s)
        out = sync_state(
            local, reds, "data", bucketed=True,
            transports=transports, tolerances=tolerances,
        )
        return jax.tree_util.tree_map(lambda x: jnp.expand_dims(x, 0), out)

    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
    return jax.jit(f)(_per_device(state))


def _trace_box(reds, state, transports=None, tolerances=None):
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda st: sync_state(
                st, reds, "data", bucketed=True,
                transports=transports, tolerances=tolerances,
            ),
            axis_env=[("data", WORLD)],
        )(state)
    return box


def _rel_err(got, want):
    """Max abs error relative to the bucket's max-magnitude exact value —
    the frame the error bound is stated in."""
    got, want = np.asarray(got, np.float64), np.asarray(want, np.float64)
    denom = max(np.max(np.abs(want)), 1e-30)
    return float(np.max(np.abs(got - want)) / denom)


# ------------------------------------------------------------ exact parity ---
@pytest.mark.mesh8
def test_exact_is_the_same_code_path(mesh):
    """The bitwise escape hatch: an explicit all-exact transport map traces to
    the very same jaxpr as no transport map at all — not merely equal values,
    the identical program."""
    exact = {name: "exact" for name in _STATE}
    jaxpr_none = jax.make_jaxpr(
        lambda st: sync_state(st, _REDS, "data", bucketed=True),
        axis_env=[("data", WORLD)],
    )(_STATE)
    jaxpr_exact = jax.make_jaxpr(
        lambda st: sync_state(st, _REDS, "data", bucketed=True, transports=exact),
        axis_env=[("data", WORLD)],
    )(_STATE)
    assert str(jaxpr_none) == str(jaxpr_exact)

    out_none = _run_sync(mesh, _STATE, _REDS)
    out_exact = _run_sync(mesh, _STATE, _REDS, transports=exact)
    for a, b in zip(*map(lambda t: jax.tree_util.tree_leaves(t), (out_none, out_exact))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------- quantized parity ----
@pytest.mark.mesh8
@pytest.mark.parametrize("transport", ["bf16", "int8"])
def test_quantized_error_within_declared_and_abstract_bound(mesh, transport):
    transports = {"fsum": transport, "fsum2": transport}
    out_q = _run_sync(mesh, _STATE, _REDS, transports=transports)
    out_e = _run_sync(mesh, _STATE, _REDS)
    bound = transport_error_bound(transport, WORLD)
    assert bound <= DEFAULT_TOLERANCES[transport]  # admitted, not refused
    # the quantized bucket: within the abstract bound
    got = np.concatenate([
        np.asarray(out_q["fsum"][0]).ravel(), np.asarray(out_q["fsum2"][0]).ravel()])
    want = np.concatenate([
        np.asarray(out_e["fsum"][0]).ravel(), np.asarray(out_e["fsum2"][0]).ravel()])
    assert _rel_err(got, want) <= bound
    # untouched buckets stay bitwise
    for name in ("counts", "hits", "mx", "gather"):
        np.testing.assert_array_equal(
            np.asarray(out_q[name]), np.asarray(out_e[name]))


@pytest.mark.mesh8
@pytest.mark.parametrize("transport", ["bf16", "int8"])
def test_integer_buckets_round_back_to_integers(mesh, transport):
    """config2's stat-score buckets are int32 sums — the codec must land on
    the integer grid (dequant + rint), within bound of the exact count."""
    transports = {"counts": transport, "hits": transport}
    out_q = _run_sync(mesh, _STATE, _REDS, transports=transports)
    out_e = _run_sync(mesh, _STATE, _REDS)
    for name in ("counts", "hits"):
        got, want = np.asarray(out_q[name][0]), np.asarray(out_e[name][0])
        assert got.dtype == want.dtype  # dtype survives the round trip
        assert _rel_err(got, want) <= transport_error_bound(transport, WORLD)


@pytest.mark.mesh8
def test_sparse_count_is_lossless_both_branches(mesh):
    """sparse_count is lossless on both sides of its runtime density cond:
    a near-empty bucket takes the sparse gather, a dense one the in-program
    psum fallback — both must equal exact bitwise."""
    nearly_empty = jnp.zeros((400,), jnp.int32).at[7].set(3).at[200].set(1)
    dense = jnp.arange(400, dtype=jnp.int32) % 5 + 1
    reds = {"s": "sum"}
    for leaf in (nearly_empty, dense):
        out_q = _run_sync(mesh, {"s": leaf}, reds, transports={"s": "sparse_count"})
        out_e = _run_sync(mesh, {"s": leaf}, reds)
        np.testing.assert_array_equal(np.asarray(out_q["s"]), np.asarray(out_e["s"]))


# ------------------------------------------------------------------- gate ----
@pytest.mark.mesh8
def test_refusal_falls_back_bitwise_with_reason(mesh):
    """A tolerance tighter than the W=8 bound refuses the bucket: the record
    carries the reason and the bucket syncs exact — bitwise, observable in
    bytes_by_transport."""
    transports = {"fsum": "bf16"}
    tolerances = {"fsum": 0.001}  # << 0.0391 bound at W=8
    box = _trace_box(_REDS, _STATE, transports, tolerances)
    assert len(box["refusals"]) == 1
    ref = box["refusals"][0]
    assert ref["reason"] == "error_budget"
    assert ref["transport"] == "bf16"
    assert ref["bound"] > ref["tolerance"] == 0.001
    assert "fsum" in ref["states"]
    assert "bf16" not in box["bytes_by_transport"]  # nothing crossed quantized

    out_q = _run_sync(mesh, _STATE, _REDS, transports=transports, tolerances=tolerances)
    out_e = _run_sync(mesh, _STATE, _REDS)
    for a, b in zip(jax.tree_util.tree_leaves(out_q), jax.tree_util.tree_leaves(out_e)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gate_refuses_at_wider_world():
    """The same default-tolerance bf16 bucket that passes at W=8 fails at
    W=16: the bound model scales with mesh width."""
    assert transport_error_bound("bf16", 8) <= DEFAULT_TOLERANCES["bf16"]
    assert transport_error_bound("bf16", 16) > DEFAULT_TOLERANCES["bf16"]
    state = {"fsum": jax.ShapeDtypeStruct((50,), jnp.float32)}
    plan8 = transport_plan(state, {"fsum": "sum"}, 8, transports={"fsum": "bf16"})
    plan16 = transport_plan(state, {"fsum": "sum"}, 16, transports={"fsum": "bf16"})
    assert plan8[0]["transport"] == "bf16" and plan8[0]["refusal"] is None
    assert plan16[0]["transport"] == "exact"
    assert plan16[0]["refusal"]["reason"] == "error_budget"


def test_gate_routes_inapplicable_combinations_silently():
    """A global bf16 switch must not spam refusals for max/gather buckets —
    inapplicable combinations are routing, not refusals."""
    set_sync_transport("bf16")
    box = _trace_box(_REDS, _STATE)
    assert box["refusals"] == []
    # the sum buckets went quantized, max/gather stayed exact
    assert "bf16" in box["bytes_by_transport"]
    assert "exact" in box["bytes_by_transport"]


def test_sparse_count_needs_a_byte_win():
    """sparse_count on a tiny bucket cannot beat dense wire bytes — refused
    with reason no_byte_win (2K slots + nnz exchange >= dense)."""
    state = {"c": jax.ShapeDtypeStruct((2,), jnp.int32)}
    plan = transport_plan(state, {"c": "sum"}, 8, transports={"c": "sparse_count"})
    assert plan[0]["transport"] == "exact"
    assert plan[0]["refusal"]["reason"] == "no_byte_win"


# ----------------------------------------------------------- wire accounting -
def test_wire_vs_logical_byte_accounting():
    counts = {"counts": (jnp.arange(1000, dtype=jnp.int32) % 7)}
    reds = {"counts": "sum"}
    logical = 1000 * 4
    exact = _trace_box(reds, counts)["bytes_by_transport"]
    assert exact == {"exact": {"wire": logical, "logical": logical}}

    bf16 = _trace_box(reds, counts, {"counts": "bf16"})["bytes_by_transport"]
    assert bf16["bf16"]["logical"] == logical
    assert bf16["bf16"]["wire"] * 2 == logical  # 4B -> 2B on the wire

    int8 = _trace_box(reds, counts, {"counts": "int8"})["bytes_by_transport"]
    assert int8["int8"]["logical"] == logical
    # quantized payload (block-padded int8) + scale pmax rides as protocol
    # overhead: wire ticks but logical stays 0, so the ratio is honest
    wire = sum(v["wire"] for k, v in int8.items() if k == "int8")
    assert logical / wire >= 3.5

    # the collective count per transport: bf16 folds into one psum; int8 pays
    # the scale pmax + quantized psum; sparse pays nnz pmax + gather + the
    # in-program dense fallback psum
    assert _trace_box(reds, counts, {"counts": "bf16"})["by_kind"] == {"psum": 1}
    assert _trace_box(reds, counts, {"counts": "int8"})["by_kind"] == {"pmax": 1, "psum": 1}
    sparse = _trace_box(reds, counts, {"counts": "sparse_count"})["by_kind"]
    assert sparse == {"pmax": 1, "all_gather": 1, "psum": 1}


# --------------------------------------------------------------- selection ---
def test_selection_precedence_and_validation(monkeypatch):
    assert sync_transport_default() == "exact"
    monkeypatch.setenv("METRICS_TPU_SYNC_TRANSPORT", "bf16")
    assert sync_transport_default() == "bf16"
    set_sync_transport("int8")  # global switch beats env
    assert sync_transport_default() == "int8"
    # per-state declaration beats the global switch
    assert sync_mod._resolve_transport("a", {"a": "exact"}) == "exact"
    assert sync_mod._resolve_transport("b", {"a": "exact"}) == "int8"
    set_sync_transport(None)
    assert sync_transport_default() == "bf16"  # back to env
    monkeypatch.setenv("METRICS_TPU_SYNC_TRANSPORT", "bogus")
    assert sync_transport_default() == "exact"  # unknown env ignored
    with pytest.raises(ValueError, match="unknown sync transport"):
        set_sync_transport("fp4")
    # the sync-time transports= dict validates too (not a bare KeyError later)
    with pytest.raises(ValueError, match="unknown sync transport 'float4'"):
        sync_mod._resolve_transport("x", {"x": "float4"})
    for t in TRANSPORTS:
        set_sync_transport(t)
        assert sync_transport_default() == t


def test_add_state_declarations_validate():
    class _M(Metric):
        full_state_update = False

        def __init__(self, **kw):
            super().__init__()
            self.add_state("total", jnp.zeros((4,), jnp.float32),
                           dist_reduce_fx="sum", **kw)

        def update(self, x):
            self.total = self.total + x

        def compute(self):
            return self.total

    m = _M(sync_transport="bf16", sync_tolerance=0.04)
    assert m.sync_transports == {"total": "bf16"}
    assert m.sync_tolerances == {"total": 0.04}
    assert _M().sync_transports == {}
    with pytest.raises(Exception, match="sync_transport"):
        _M(sync_transport="fp4")
    with pytest.raises(Exception, match="sync_tolerance"):
        _M(sync_transport="bf16", sync_tolerance=-0.1)


# ------------------------------------------------------- metric integration --
class _QuantMetric(Metric):
    """A metric declaring a quantized transport on its sum state."""

    full_state_update = False

    def __init__(self, transport=None, tolerance=None):
        super().__init__(compiled_compute=False)
        kw = {}
        if transport is not None:
            kw["sync_transport"] = transport
        if tolerance is not None:
            kw["sync_tolerance"] = tolerance
        self.add_state("total", jnp.zeros((32,), jnp.float32),
                       dist_reduce_fx="sum", **kw)
        self.add_state("n", jnp.zeros((), jnp.float32), dist_reduce_fx="sum")

    def update(self, x):
        self.total = self.total + x
        self.n = self.n + 1.0

    def compute(self):
        return self.total / jnp.maximum(self.n, 1.0)


@pytest.mark.mesh8
def test_metric_sync_states_honors_declaration(mesh):
    rng = np.random.default_rng(5)
    xs = jnp.asarray(rng.uniform(0.5, 2.0, (WORLD, 32)).astype(np.float32))

    def run(m):
        def body(x):
            state = m.update_state(m.init_state(), x[0])
            synced = m.sync_states(state, axis_name="data")
            return jax.tree_util.tree_map(lambda v: jnp.expand_dims(v, 0), synced)

        return jax.jit(
            shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
        )(xs)

    exact = run(_QuantMetric())
    quant = run(_QuantMetric(transport="int8"))
    got, want = np.asarray(quant["total"][0]), np.asarray(exact["total"][0])
    assert _rel_err(got, want) <= transport_error_bound("int8", WORLD)
    assert 0.0 < _rel_err(got, want)  # it actually quantized
    # the undeclared state shares no bucket with the declared one: bitwise
    np.testing.assert_array_equal(np.asarray(quant["n"]), np.asarray(exact["n"]))


def test_transport_is_config_not_state(tmp_path):
    """Checkpoints interchange across transport declarations — the transport
    never reaches the state pytree or the fingerprint."""
    a, b = _QuantMetric(transport="int8", tolerance=0.05), _QuantMetric()
    a.update(jnp.full((32,), 2.0))
    path = tmp_path / "ckpt"
    metrics_tpu.save_checkpoint(a, str(path))
    metrics_tpu.restore_checkpoint(b, str(path))
    for name in ("total", "n"):
        np.testing.assert_array_equal(
            np.asarray(a.get_state()[name]), np.asarray(b.get_state()[name]))
    # and the reverse direction: undeclared -> declared
    metrics_tpu.save_checkpoint(b, str(path))
    metrics_tpu.restore_checkpoint(a, str(path))
    assert a.sync_transports == {"total": "int8"}  # declaration untouched
