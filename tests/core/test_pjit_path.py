"""GSPMD (pjit-style) coverage: auto-partitioned jit over NamedSharding.

shard_map is the explicit-collective path the suite exercises everywhere; the
OTHER documented usage (README quickstart, docs/distributed.md) is plain
``jit`` over sharded inputs, where XLA inserts the cross-device reductions
itself. State stays replicated; the batch axis is sharded; the compiled
update must produce the same accumulation as a single-device run.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from metrics_tpu import Accuracy, F1Score, MeanSquaredError, MetricCollection

NUM_CLASSES = 7


pytestmark = pytest.mark.mesh8

@pytest.fixture(scope="module")
def mesh():
    devices = jax.devices()
    if len(devices) < 8:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:8]), ("data",))


def test_pjit_sharded_batch_accuracy(mesh):
    metric = Accuracy(num_classes=NUM_CLASSES)
    rng = np.random.default_rng(0)
    logits = rng.normal(size=(64, NUM_CLASSES)).astype(np.float32)
    target = rng.integers(0, NUM_CLASSES, size=(64,)).astype(np.int32)

    batch_sharding = NamedSharding(mesh, P("data"))
    replicated = NamedSharding(mesh, P())

    logits_sharded = jax.device_put(jnp.asarray(logits), batch_sharding)
    target_sharded = jax.device_put(jnp.asarray(target), batch_sharding)

    step = jax.jit(metric.update_state, out_shardings=replicated)
    state = jax.device_put(metric.init_state(), replicated)
    for _ in range(3):
        state = step(state, logits_sharded, target_sharded)

    expected = float((np.argmax(logits, -1) == target).mean())
    assert float(metric.compute_state(state)) == pytest.approx(expected, abs=1e-6)
    # the accumulated state itself must be replicated across all 8 devices
    assert all(len(leaf.sharding.device_set) == 8 for leaf in jax.tree.leaves(state))


def test_pjit_sharded_collection(mesh):
    coll = MetricCollection(
        {"acc": Accuracy(num_classes=NUM_CLASSES), "f1": F1Score(num_classes=NUM_CLASSES, average="macro")}
    )
    rng = np.random.default_rng(1)
    logits = rng.normal(size=(32, NUM_CLASSES)).astype(np.float32)
    target = rng.integers(0, NUM_CLASSES, size=(32,)).astype(np.int32)

    batch_sharding = NamedSharding(mesh, P("data"))
    step = jax.jit(lambda s, x, y: coll.update_state(s, x, y))
    state = step(
        coll.init_state(),
        jax.device_put(jnp.asarray(logits), batch_sharding),
        jax.device_put(jnp.asarray(target), batch_sharding),
    )
    values = coll.compute_state(state)

    single = MetricCollection(
        {"acc": Accuracy(num_classes=NUM_CLASSES), "f1": F1Score(num_classes=NUM_CLASSES, average="macro")}
    )
    expected = single.compute_state(single.update_state(single.init_state(), jnp.asarray(logits), jnp.asarray(target)))
    for key in expected:
        assert float(values[key]) == pytest.approx(float(expected[key]), abs=1e-6), key


def test_pjit_regression_sharded(mesh):
    metric = MeanSquaredError()
    rng = np.random.default_rng(2)
    preds = rng.normal(size=(64,)).astype(np.float32)
    target = rng.normal(size=(64,)).astype(np.float32)
    batch_sharding = NamedSharding(mesh, P("data"))
    step = jax.jit(metric.update_state)
    state = step(
        metric.init_state(),
        jax.device_put(jnp.asarray(preds), batch_sharding),
        jax.device_put(jnp.asarray(target), batch_sharding),
    )
    assert float(metric.compute_state(state)) == pytest.approx(float(((preds - target) ** 2).mean()), abs=1e-6)
