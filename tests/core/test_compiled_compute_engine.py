"""Compiled-compute engine: cached sync∘compute dispatch, fallback, fusion.

The engine (``metrics_tpu/core/engine.py``) makes plain ``metric.compute()``
hit a cached jitted ``sync_states ∘ compute_state`` from the second call per
state signature, and fuses ``MetricCollection.compute()`` into one program
over the group leaders' states. These tests pin the dispatch contract:
warmup-then-compile counting, ``_computed`` memoization skipping the engine,
eager parity across one metric per domain package, the permanent eager
fallback for untraceable ``compute_state``, bitwise sync parity of the fused
``sync_compute_state`` against the eager sync+compute on the 8-device CPU
mesh, and the dispatch-overhead guard against a hand-jitted compute_state.
"""
import pickle
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu
from metrics_tpu import (
    AUROC,
    Accuracy,
    F1Score,
    MeanMetric,
    MeanSquaredError,
    Metric,
    MetricCollection,
    PeakSignalNoiseRatio,
    Precision,
    Recall,
    RetrievalRecall,
    SignalNoiseRatio,
    StatScores,
    WordErrorRate,
)
from metrics_tpu.parallel.sync import sync_state


@pytest.fixture(autouse=True)
def _engine_on():
    metrics_tpu.set_compiled_compute(True)
    yield
    metrics_tpu.set_compiled_compute(None)


def _data(n=64, c=5, seed=0):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(rng.standard_normal((n, c)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, c, n))
    return preds, target


# --------------------------------------------------------------------- cache --
class TestCacheCounting:
    def test_warmup_then_hit(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(3):
            m.update(preds, target)  # update resets _computed -> real dispatches
            m.compute()
        stats = m._compute_engine.stats
        assert stats.eager_calls == 1  # first compute per state signature is eager
        assert stats.cache_misses == 1  # second compiles
        assert stats.cache_hits == 1

    def test_memoized_compute_skips_engine(self):
        preds, target = _data()
        m = Accuracy()
        m.update(preds, target)
        v1 = m.compute()
        stats_before = m._compute_engine.stats.eager_calls
        v2 = m.compute()  # `_computed` memo: no second dispatch
        assert v1 is v2
        assert m._compute_engine.stats.eager_calls == stats_before

    def test_global_switch(self):
        preds, target = _data()
        metrics_tpu.set_compiled_compute(False)
        m = Accuracy()
        m.update(preds, target)
        m.compute()
        assert m._compute_engine is None
        # per-instance True overrides the global False
        m2 = Accuracy(compiled_compute=True)
        for _ in range(2):
            m2.update(preds, target)
            m2.compute()
        assert m2._compute_engine.stats.compiled_calls == 1

    def test_list_state_metric_stays_eager(self):
        m = AUROC()  # unbounded list states -> compute not compilable
        rng = np.random.default_rng(0)
        p = jnp.asarray(rng.random(32).astype(np.float32))
        t = jnp.asarray(rng.integers(0, 2, 32))
        for _ in range(3):
            m.update(p, t)
            m.compute()
            m._computed = None
        assert not m.supports_compiled_compute
        assert m._compute_engine.stats.compiled_calls == 0

    def test_untraceable_compute_falls_back_permanently(self):
        class HostCompute(Metric):
            full_state_update = False

            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

            def update(self, x):
                self.total = self.total + jnp.sum(x)

            def compute(self):
                if float(self.total) > -1e30:  # host readback: untraceable
                    return self.total + 0.0
                return self.total

        m = HostCompute()
        x = jnp.asarray([1.0, 2.0])
        m.update(x)
        assert float(m.compute()) == 3.0  # warmup: eager
        m.update(x)
        with pytest.warns(UserWarning, match="compiled-compute engine disabled"):
            m.compute()  # first compiled attempt fails the trace
        assert m._compute_engine.broken is not None
        m.update(x)
        assert float(m.compute()) == 9.0  # all computes applied eagerly
        assert m._compute_engine.stats.compiled_calls == 0


# ------------------------------------------------------------- domain sweep --
def _cls_data(seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.standard_normal((64, 5)).astype(np.float32)),
        jnp.asarray(rng.integers(0, 5, 64)),
    )


def _pair_data(seed=1):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.random(64).astype(np.float32)),
        jnp.asarray(rng.random(64).astype(np.float32)),
    )


def _retrieval_data(seed=3):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.random(24).astype(np.float32)),
        jnp.asarray(rng.integers(0, 2, 24)),
        jnp.asarray(np.repeat(np.arange(4), 6)),
    )


DOMAIN_CASES = [
    pytest.param(lambda **kw: Accuracy(**kw), _cls_data, id="classification-accuracy"),
    pytest.param(lambda **kw: MeanSquaredError(**kw), _pair_data, id="regression-mse"),
    pytest.param(
        lambda **kw: MeanMetric(**kw),
        lambda: (jnp.asarray(np.random.default_rng(2).random(64).astype(np.float32)),),
        id="aggregation-mean",
    ),
    pytest.param(
        lambda **kw: PeakSignalNoiseRatio(data_range=1.0, **kw),
        lambda: tuple(x.reshape(4, 4, 4) for x in _pair_data(4)),
        id="image-psnr",
    ),
    pytest.param(
        lambda **kw: WordErrorRate(**kw),
        lambda: (["hello world foo", "bar baz"], ["hello word foo", "bar baz qux"]),
        id="text-wer",
    ),
    pytest.param(
        lambda **kw: SignalNoiseRatio(**kw),
        lambda: tuple(x.reshape(8, 8) for x in _pair_data(5)),
        id="audio-snr",
    ),
    pytest.param(
        lambda **kw: RetrievalRecall(
            max_queries=8, max_docs_per_query=32, buffer_capacity=128, **kw
        ),
        _retrieval_data,
        id="retrieval-recall",
    ),
]


@pytest.mark.parametrize("build, data", DOMAIN_CASES)
def test_compiled_vs_eager_compute_parity(build, data):
    """One metric per domain package: 3 update/compute rounds, compiled path
    must match the eager facade exactly and actually hit the jit cache."""
    m = build()
    ref = build(compiled_compute=False)
    args = data()
    for _ in range(3):
        m.update(*args)
        ref.update(*args)
        got, want = m.compute(), ref.compute()
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
        )
    assert ref._compute_engine is None
    assert m.supports_compiled_compute
    assert m._compute_engine is not None
    assert m._compute_engine.broken is None
    assert m._compute_engine.stats.compiled_calls >= 1


# ------------------------------------------------------------------ syncing --
WORLD = 8


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


def test_sync_state_no_axis_is_identity():
    m = StatScores(reduce="macro", num_classes=5, compiled_compute=False)
    preds, target = _data()
    m.update(preds, target)
    state = m.get_state()
    out = sync_state(state, m._reductions, None)
    assert set(out) == set(state)
    for k in state:
        assert out[k] is state[k]  # fast path: no collective, no copy


@pytest.mark.mesh8
def test_plain_jit_sync_compute_folds_sync(mesh):
    """Outside any collective program, jit(sync_compute_state) == compute."""
    m = StatScores(reduce="macro", num_classes=5, compiled_compute=False)
    preds, target = _data()
    m.update(preds, target)
    state = m.get_state()
    fused = jax.jit(m.sync_compute_state)(state)
    np.testing.assert_array_equal(np.asarray(fused), np.asarray(m.compute()))


@pytest.mark.mesh8
def test_fused_sync_compute_bitwise_parity(mesh):
    """The engine's jitted unit (sync_states ∘ compute_state) must be
    bitwise-identical to the eager two-step sync inside a shard_map."""
    m = StatScores(reduce="macro", num_classes=5, compiled_compute=False)

    def fused(x):
        state = m.update_state(m.init_state(), x[0], x[1])
        return jnp.expand_dims(m.sync_compute_state(state, axis_name="data"), 0)

    def eager(x):
        state = m.update_state(m.init_state(), x[0], x[1])
        state = m.sync_states(state, "data")
        return jnp.expand_dims(m.compute_state(state), 0)

    rng = np.random.default_rng(7)
    preds = jnp.asarray(rng.standard_normal((WORLD, 16, 5)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 5, (WORLD, 16)))

    def run(body):
        return np.asarray(
            jax.jit(
                shard_map(
                    lambda p, t: body((p[0], t[0])),
                    mesh=mesh,
                    in_specs=P("data"),
                    out_specs=P("data"),
                    check_rep=False,
                )
            )(preds, target)
        )

    np.testing.assert_array_equal(run(fused), run(eager))  # bitwise


@pytest.mark.mesh8
def test_mean_reduction_fused_sync_parity(mesh):
    m = MeanSquaredError(compiled_compute=False)

    def fused(p, t):
        state = m.update_state(m.init_state(), p[0], t[0])
        return jnp.expand_dims(m.sync_compute_state(state, axis_name="data"), 0)

    rng = np.random.default_rng(8)
    preds = jnp.asarray(rng.random((WORLD, 32)).astype(np.float32))
    target = jnp.asarray(rng.random((WORLD, 32)).astype(np.float32))
    out = np.asarray(
        jax.jit(
            shard_map(fused, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
        )(preds[:, None], target[:, None])
    )
    ref = MeanSquaredError(compiled_compute=False)
    ref.update(preds.reshape(-1), target.reshape(-1))
    np.testing.assert_allclose(out, float(ref.compute()), rtol=1e-6)
    assert np.all(out == out[0])  # identical on every device


# --------------------------------------------------------------- collections --
class TestCollectionComputeEngine:
    def _coll(self, **kw):
        return MetricCollection(
            {
                "precision": Precision(num_classes=5, average="macro"),
                "recall": Recall(num_classes=5, average="macro"),
                "acc": Accuracy(),
            },
            **kw,
        )

    def test_fused_parity(self):
        preds, target = _data()
        coll = self._coll()
        ref = self._coll(compiled_compute=False)
        for member in ref.values():
            member._compiled_compute = False
        for _ in range(3):
            coll.update(preds, target)
            ref.update(preds, target)
            r1, r2 = coll.compute(), ref.compute()
            assert set(r1) == set(r2)
            for k in r1:
                np.testing.assert_allclose(np.asarray(r1[k]), np.asarray(r2[k]))
        stats = coll._compute_engine.stats
        assert stats.eager_calls == 1 and stats.cache_misses == 1 and stats.cache_hits == 1

    def test_fused_compute_populates_member_memo(self):
        preds, target = _data()
        coll = self._coll()
        for _ in range(2):
            coll.update(preds, target)
            res = coll.compute()
        for name in ("precision", "recall", "acc"):
            member = coll[name]
            assert member._computed is not None
            np.testing.assert_allclose(
                np.asarray(member._computed), np.asarray(res[name])
            )

    def test_group_rebuild_invalidates_engine(self):
        preds, target = _data()
        coll = self._coll()
        for _ in range(2):
            coll.update(preds, target)
            coll.compute()
        stale = coll._compute_engine
        assert stale is not None
        coll["f1"] = F1Score(num_classes=5, average="macro")
        assert coll._compute_engine is None  # rebuild dropped the stale executable
        coll.update(preds, target)
        f1_solo = F1Score(num_classes=5, average="macro", compiled_compute=False)
        f1_solo.update(preds, target)
        np.testing.assert_allclose(
            np.asarray(coll.compute()["f1"]), np.asarray(f1_solo.compute())
        )

    def test_member_opt_out_disables_fusion(self):
        preds, target = _data()
        coll = self._coll()
        coll["acc"]._compiled_compute = False
        coll.update(preds, target)
        coll.update(preds, target)
        coll.compute()
        engine = coll._compute_engine
        assert engine is None or engine.stats.compiled_calls == 0


# ------------------------------------------------------------- lifecycle ----
class TestLifecycle:
    def test_clone_and_pickle_drop_engine(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(3):
            m.update(preds, target)
            m.compute()
        assert m._compute_engine is not None
        c = m.clone()
        assert c._compute_engine is None
        c.update(preds, target)
        c.compute()  # engine rebuilds lazily
        p = pickle.loads(pickle.dumps(m))
        assert p._compute_engine is None
        np.testing.assert_array_equal(np.asarray(p.compute()), np.asarray(m.compute()))

    def test_reset_keeps_compiled_cache(self):
        preds, target = _data()
        m = StatScores(reduce="macro", num_classes=5)
        for _ in range(3):
            m.update(preds, target)
            m.compute()
        misses = m._compute_engine.stats.cache_misses
        m.reset()
        m.update(preds, target)
        m.compute()  # same state signature: straight to the cached executable
        assert m._compute_engine.stats.cache_misses == misses
        ref = StatScores(reduce="macro", num_classes=5, compiled_compute=False)
        ref.update(preds, target)
        np.testing.assert_array_equal(np.asarray(m.compute()), np.asarray(ref.compute()))


# ------------------------------------------------ dispatch-overhead guard ----
def test_compute_dispatch_overhead_guard():
    """Tier-1 perf guard: the stateful jit-cached ``compute()`` must stay
    within ~2x of driving the raw jitted ``compute_state`` by hand (plus a
    fixed per-call bookkeeping floor for signature hashing / stats)."""
    preds, target = _data(n=256)
    raw = StatScores(reduce="macro", num_classes=5, compiled_compute=False)
    raw.update(preds, target)
    state = raw.get_state()
    fn = jax.jit(raw.compute_state)
    jax.block_until_ready(fn(state))

    def time_raw():
        jax.block_until_ready(fn(state))
        t0 = time.perf_counter()
        for _ in range(64):
            out = fn(state)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / 64

    stateful = StatScores(reduce="macro", num_classes=5)
    stateful.update(preds, target)
    for _ in range(3):  # warmup sighting + compile + first cached hit
        stateful._computed = None
        stateful.compute()

    def time_stateful():
        stateful._computed = None
        jax.block_until_ready(stateful.compute())
        t0 = time.perf_counter()
        for _ in range(64):
            stateful._computed = None
            out = stateful.compute()
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / 64

    raw_s = min(time_raw() for _ in range(3))
    stateful_s = min(time_stateful() for _ in range(3))
    assert stateful.supports_compiled_compute
    assert stateful._compute_engine.stats.compiled_calls > 64
    # 2x relative + 150us absolute floor absorbs timer noise on tiny steps
    assert stateful_s <= 2.0 * raw_s + 150e-6, (
        f"stateful jit-cached compute too slow: {stateful_s * 1e6:.1f}us/call vs "
        f"raw jitted {raw_s * 1e6:.1f}us/call"
    )
