"""CatBuffer: fixed-capacity jittable cat states (SURVEY.md §7 hard part 1).

Covers the contract VERDICT item 4 demands: curve metrics run under the jitted
pure protocol (jit / lax.scan / shard_map), overflow inside compiled programs
is detected at compute, eager appends grow, and list<->buffer checkpoints
interconvert.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P
from sklearn.metrics import roc_auc_score

from metrics_tpu import AUROC, CatMetric, PrecisionRecallCurve
from metrics_tpu.core.buffers import CatBuffer
from metrics_tpu.utils.exceptions import MetricsUserError

WORLD = 8
_rng = np.random.default_rng(7)


# --------------------------------------------------------------------------- #
# unit behavior
# --------------------------------------------------------------------------- #
def test_append_and_to_array():
    buf = CatBuffer.empty(8)
    buf.append(jnp.arange(3, dtype=jnp.float32))
    buf.append(jnp.arange(3, 5, dtype=jnp.float32))
    np.testing.assert_allclose(np.asarray(buf.to_array()), np.arange(5))
    assert buf.capacity == 8 and len(buf) == 5


def test_list_add_idiom():
    buf = CatBuffer.empty(4)
    buf = buf + [jnp.asarray([1.0, 2.0])] + [jnp.asarray(3.0)]  # scalar counts as one row
    np.testing.assert_allclose(np.asarray(buf.to_array()), [1.0, 2.0, 3.0])


def test_eager_growth():
    buf = CatBuffer.empty(2)
    for i in range(5):
        buf.append(jnp.asarray([float(i), float(i)]))
    assert buf.capacity == 16  # 2 -> 4 -> 8 -> 16
    np.testing.assert_allclose(np.asarray(buf.to_array()), np.repeat(np.arange(5.0), 2))


def test_item_shape_mismatch_raises():
    buf = CatBuffer.empty(4)
    buf.append(jnp.zeros((2, 3)))
    with pytest.raises(MetricsUserError, match="item shape mismatch"):
        buf.append(jnp.zeros((2, 5)))


def test_merge_eager_and_traced_agree():
    a = CatBuffer.empty(4)
    a.append(jnp.asarray([1.0, 2.0]))
    b = CatBuffer.empty(4)
    b.append(jnp.asarray([3.0]))
    eager = a.merge(b)
    np.testing.assert_allclose(np.asarray(eager.to_array()), [1.0, 2.0, 3.0])

    traced = jax.jit(lambda x, y: x.merge(y))(a, b)
    np.testing.assert_allclose(np.asarray(traced.to_array()), [1.0, 2.0, 3.0])
    assert traced.capacity == 8  # traced merge concatenates capacities


def test_traced_overflow_detected_at_compute():
    buf = CatBuffer.empty(4)

    @jax.jit
    def add(b, x):
        b = b.copy()
        b.append(x)
        return b

    b = buf
    for i in range(3):
        b = add(b, jnp.full((2,), float(i)))
    assert int(b.count) == 6
    with pytest.raises(MetricsUserError, match="overflow"):
        b.to_array()


def test_overflow_is_sticky_through_merge_and_append():
    """Review regression: merging an overflowed buffer must not launder the
    overflow just because the combined capacity now covers the summed count."""
    buf = CatBuffer.empty(4)

    @jax.jit
    def add(b, x):
        b = b.copy()
        b.append(x)
        return b

    b = buf
    for i in range(3):  # 6 rows into capacity 4 -> corrupt tail
        b = add(b, jnp.full((2,), float(i)))
    other = CatBuffer.empty(4)
    other.append(jnp.asarray([7.0, 8.0]))

    merged = b.merge(other)  # capacity 8 >= count 8, but data is corrupt
    with pytest.raises(MetricsUserError, match="overflow"):
        merged.to_array()
    # eager append growth must not launder either
    b.append(jnp.asarray([9.0]))
    with pytest.raises(MetricsUserError, match="overflow"):
        b.to_array()


def test_non_bufferable_metric_rejects_capacity():
    """Per-element list states (mAP's host-list mode) cannot be buffered."""
    from metrics_tpu import MeanAveragePrecision

    with pytest.raises(MetricsUserError, match="does not support `buffer_capacity`"):
        MeanAveragePrecision(device_state=False, buffer_capacity=64)

    # the device-state default replaces the per-image lists with pow2-padded
    # CatBuffers, so there buffer_capacity is the image capacity
    m = MeanAveragePrecision(buffer_capacity=64)
    assert m.device_state and m.det_boxes.capacity == 64


def test_from_array_roundtrip():
    vals = jnp.asarray(_rng.normal(size=(5, 3)).astype(np.float32))
    buf = CatBuffer.from_array(vals, capacity=9)
    assert buf.capacity == 9
    np.testing.assert_allclose(np.asarray(buf.to_array()), np.asarray(vals))


# --------------------------------------------------------------------------- #
# metric integration
# --------------------------------------------------------------------------- #
def _batches(n=4, b=32):
    ps = [_rng.uniform(size=(b,)).astype(np.float32) for _ in range(n)]
    ts = [_rng.integers(0, 2, b).astype(np.int32) for _ in range(n)]
    return ps, ts


def test_list_metric_tracer_warns():
    m = AUROC()  # list states, no capacity
    assert not m.supports_compiled_update
    # first compiled update from empty lists is silent (the ddp sync pattern);
    # tracing with a populated list state warns about recompile churn.
    p, t = jnp.zeros((4,)) + 0.5, jnp.zeros((4,), jnp.int32)
    state = jax.jit(m.update_state)(m.init_state(), p, t)
    with pytest.warns(UserWarning, match="buffer_capacity"):
        jax.jit(m.update_state)(state, p, t)


def test_buffered_auroc_jit_parity():
    ps, ts = _batches()
    m = AUROC(buffer_capacity=256)
    assert m.supports_compiled_update
    state = m.init_state()
    step = jax.jit(m.update_state)
    for p, t in zip(ps, ts):
        state = step(state, jnp.asarray(p), jnp.asarray(t))
    want = roc_auc_score(np.concatenate(ts), np.concatenate(ps))
    assert abs(float(m.compute_state(state)) - want) < 1e-6


def test_buffered_auroc_scan_epoch():
    ps, ts = _batches()
    m = AUROC(buffer_capacity=256)
    s0 = m.init_state(jax.ShapeDtypeStruct((32,), jnp.float32), jax.ShapeDtypeStruct((32,), jnp.int32))

    @jax.jit
    def epoch(s, bp, bt):
        def body(carry, xt):
            return m.update_state(carry, xt[0], xt[1]), None

        out, _ = jax.lax.scan(body, s, (bp, bt))
        return out

    state = epoch(s0, jnp.asarray(np.stack(ps)), jnp.asarray(np.stack(ts)))
    want = roc_auc_score(np.concatenate(ts), np.concatenate(ps))
    assert abs(float(m.compute_state(state)) - want) < 1e-6


def test_buffered_pr_curve_matches_list_state():
    ps, ts = _batches(n=2)
    m_buf = PrecisionRecallCurve(buffer_capacity=128)
    m_list = PrecisionRecallCurve()
    state = m_buf.init_state()
    step = jax.jit(m_buf.update_state)
    for p, t in zip(ps, ts):
        state = step(state, jnp.asarray(p), jnp.asarray(t))
        m_list.update(jnp.asarray(p), jnp.asarray(t))
    for got, want in zip(m_buf.compute_state(state), m_list.compute()):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)


def test_buffered_cat_metric_forward():
    m = CatMetric(buffer_capacity=4)
    m(jnp.asarray([1.0, 2.0]))
    m(jnp.asarray([3.0]))
    np.testing.assert_allclose(np.asarray(m.compute()), [1.0, 2.0, 3.0])


def test_buffered_state_dict_interconverts_with_list_state():
    ps, ts = _batches(n=1)
    m_buf = AUROC(buffer_capacity=64)
    m_buf.persistent(True)
    m_buf.update(jnp.asarray(ps[0]), jnp.asarray(ts[0]))
    sd = m_buf.state_dict()
    assert isinstance(sd["preds"], np.ndarray)  # compact array, not a buffer blob

    m_back = AUROC(buffer_capacity=64)
    m_back.load_state_dict(sd)
    m_back._update_count, m_back.mode = 1, m_buf.mode
    assert abs(float(m_back.compute()) - float(m_buf.compute())) < 1e-9


# --------------------------------------------------------------------------- #
# distributed
# --------------------------------------------------------------------------- #
@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


@pytest.mark.mesh8
def test_buffered_gather_compaction(mesh):
    """Each device appends a different number of valid rows; the gathered
    buffer holds every row exactly once, in device order."""

    def body(x):
        buf = CatBuffer.empty(4, item_shape=(), dtype=jnp.float32)
        idx = x[0, 0]
        buf.append(jnp.stack([idx * 10.0, idx * 10.0 + 1.0]))
        return buf.gather("data")

    xs = jnp.arange(WORLD, dtype=jnp.float32).reshape(WORLD, 1)
    out = jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P(), check_vma=False)
    )(xs)
    got = np.asarray(out.to_array())
    want = np.concatenate([[d * 10.0, d * 10.0 + 1] for d in range(WORLD)])
    np.testing.assert_allclose(got, want)
    assert out.capacity == WORLD * 4


@pytest.mark.mesh8
def test_ddp_buffered_curve_metric(mesh):
    """VERDICT item 4 'done' criterion: a curve metric under shard_map with
    strided batches matches sklearn on the concatenation."""
    ps, ts = _batches(n=1, b=WORLD * 16)
    m = AUROC(buffer_capacity=32)
    s0 = m.init_state(jax.ShapeDtypeStruct((16,), jnp.float32), jax.ShapeDtypeStruct((16,), jnp.int32))
    specs = jax.tree_util.tree_map(lambda _: P(), s0)

    def step(state, pp, tt):
        s = m.update_state(state, pp, tt)
        return m.sync_states(s, "data")

    sm = jax.shard_map(step, mesh=mesh, in_specs=(specs, P("data"), P("data")), out_specs=specs, check_vma=False)
    synced = jax.jit(sm)(s0, jnp.asarray(ps[0]), jnp.asarray(ts[0]))
    want = roc_auc_score(ts[0], ps[0])
    assert abs(float(m.compute_state(synced)) - want) < 1e-6
