"""MetricCollection tests (reference parity: tests/bases/test_collections.py)."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Metric, MetricCollection
from tests.helpers.testers import DummyMetricDiff, DummyMetricSum


class _Sum2(DummyMetricSum):
    pass


def test_from_list_and_dict():
    col = MetricCollection([DummyMetricSum(), DummyMetricDiff()])
    assert set(col.keys(keep_base=True)) == {"DummyMetricSum", "DummyMetricDiff"}
    col2 = MetricCollection({"a": DummyMetricSum(), "b": DummyMetricDiff()})
    assert set(col2.keys(keep_base=True)) == {"a", "b"}


def test_duplicate_names_raise():
    with pytest.raises(ValueError, match="share the class name"):
        MetricCollection([DummyMetricSum(), DummyMetricSum()])


def test_not_a_metric_raises():
    with pytest.raises(ValueError):
        MetricCollection([DummyMetricSum(), 5])


def test_update_compute_reset():
    col = MetricCollection({"s": DummyMetricSum(), "d": DummyMetricDiff()})
    col.update(x=jnp.asarray(2.0), y=jnp.asarray(2.0))
    res = col.compute()
    assert float(res["s"]) == 2.0
    assert float(res["d"]) == -2.0
    col.reset()
    assert float(col["s"].x) == 0.0


def test_kwarg_routing():
    """Each member receives only the kwargs its update accepts (metric.py:679)."""
    col = MetricCollection({"s": DummyMetricSum(), "d": DummyMetricDiff()})
    col.update(x=jnp.asarray(3.0), y=jnp.asarray(1.0))
    res = col.compute()
    assert float(res["s"]) == 3.0
    assert float(res["d"]) == -1.0


def test_prefix_postfix():
    col = MetricCollection([DummyMetricSum()], prefix="pre_", postfix="_post")
    col.update(jnp.asarray(1.0))
    res = col.compute()
    assert list(res) == ["pre_DummyMetricSum_post"]
    c2 = col.clone(prefix="new_")
    assert list(c2.keys()) == ["new_DummyMetricSum_post"]


def test_forward_returns_batch_values():
    col = MetricCollection({"s": DummyMetricSum()})
    out = col(jnp.asarray(1.0))
    assert float(out["s"]) == 1.0
    out = col(jnp.asarray(2.0))
    assert float(out["s"]) == 2.0
    assert float(col.compute()["s"]) == 3.0


class _GroupedA(Metric):
    full_state_update = False

    def __init__(self, scale=1.0, **kw):
        super().__init__(**kw)
        self.scale = scale
        self.add_state("total", jnp.asarray(0.0), "sum")

    def update(self, x):
        self.total = self.total + jnp.sum(x)

    def compute(self):
        return self.total * self.scale

    def _update_signature(self):
        return ("sum-total",)


class _GroupedB(_GroupedA):
    def compute(self):
        return self.total * 10


def test_static_compute_groups():
    col = MetricCollection({"a": _GroupedA(), "b": _GroupedB()})
    groups = col.compute_groups
    assert len(groups) == 1 and set(groups[0]) == {"a", "b"}

    col.update(jnp.asarray([1.0, 2.0]))
    res = col.compute()
    assert float(res["a"]) == 3.0
    assert float(res["b"]) == 30.0
    # member state was shared, not independently updated
    assert col["b"]._update_count == col["a"]._update_count == 1


def test_compute_groups_disabled():
    col = MetricCollection({"a": _GroupedA(), "b": _GroupedB()}, compute_groups=False)
    assert len(col.compute_groups) == 2
    col.update(jnp.asarray([1.0]))
    res = col.compute()
    assert float(res["a"]) == 1.0
    assert float(res["b"]) == 10.0


def test_fused_pure_protocol():
    col = MetricCollection({"a": _GroupedA(), "b": _GroupedB()})
    states = col.init_state()
    assert len(states) == 1  # one state per group, not per metric
    states = col.update_state(states, jnp.asarray([1.0, 2.0]))
    res = col.compute_state(states)
    assert float(res["a"]) == 3.0
    assert float(res["b"]) == 30.0


def test_nested_collections():
    inner = MetricCollection({"s": DummyMetricSum()})
    outer = MetricCollection({"in": inner, "d": DummyMetricDiff()})
    assert set(outer.keys(keep_base=True)) == {"in_s", "d"}


def test_state_dict_roundtrip():
    col = MetricCollection({"a": _GroupedA()})
    col["a"].persistent(True)
    col.update(jnp.asarray([5.0]))
    sd = col.state_dict()
    col2 = MetricCollection({"a": _GroupedA()})
    col2["a"].persistent(True)
    col2.load_state_dict(sd)
    assert float(col2["a"].total) == 5.0


def test_group_compute_under_distribution():
    """Regression: group compute must not double-unsync when sync is active."""
    from metrics_tpu.parallel import sync as _s

    col = MetricCollection({"a": _GroupedA(), "b": _GroupedB()})
    col.update(jnp.asarray([2.0]))
    # simulate a distributed context where sync actually fires (world size 1
    # collectives are identity outside shard_map, so patch distributed check)
    orig = _s.distributed_available
    _s.distributed_available = lambda: False
    try:
        res = col.compute()
    finally:
        _s.distributed_available = orig
    assert float(res["a"]) == 2.0 and float(res["b"]) == 20.0


@pytest.mark.parametrize("compute_groups", [True, False])
def test_compute_groups_value_equivalence(compute_groups):
    """Fused and unfused collections must produce identical values across a
    mixed stat-scores family (reference overview.rst:313 claims fusion only
    changes cost, never results)."""
    from metrics_tpu import Accuracy, F1Score, Precision, Recall, Specificity

    def make():
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=5),
                "f1": F1Score(num_classes=5, average="macro"),
                "precision": Precision(num_classes=5, average="macro"),
                "recall": Recall(num_classes=5, average="macro"),
                "specificity": Specificity(num_classes=5, average="macro"),
            },
            compute_groups=compute_groups,
        )

    rng = np.random.default_rng(3)
    col = make()
    state = col.init_state()
    batches = [
        (
            jnp.asarray(rng.normal(size=(16, 5)).astype(np.float32)),
            jnp.asarray(rng.integers(0, 5, size=(16,)).astype(np.int32)),
        )
        for _ in range(3)
    ]
    for logits, target in batches:
        state = col.update_state(state, logits, target)
    values = col.compute_state(state)

    reference_col = make() if compute_groups else MetricCollection(
        {
            "acc": Accuracy(num_classes=5),
            "f1": F1Score(num_classes=5, average="macro"),
            "precision": Precision(num_classes=5, average="macro"),
            "recall": Recall(num_classes=5, average="macro"),
            "specificity": Specificity(num_classes=5, average="macro"),
        }
    )
    ref_state = reference_col.init_state()
    for logits, target in batches:
        ref_state = reference_col.update_state(ref_state, logits, target)
    expected = reference_col.compute_state(ref_state)

    assert set(values) == set(expected)
    for key in expected:
        np.testing.assert_allclose(np.asarray(values[key]), np.asarray(expected[key]), atol=1e-7, err_msg=key)

    # the macro family must actually share one group when fusion is on
    if compute_groups:
        group_sizes = sorted(len(members) for members in col.compute_groups.values())
        assert group_sizes[-1] >= 3


# ---- container-protocol surface (reference test_collections.py:205-263) ----
def test_add_metrics_after_construction_rebuilds_groups():
    col = MetricCollection({"a": _GroupedA()})
    col.add_metrics({"b": _GroupedB()})
    col.add_metrics(DummyMetricDiff())
    assert set(col.keys(keep_base=True)) == {"a", "b", "DummyMetricDiff"}
    # the grouped pair share an update signature -> fused after the rebuild
    groups = {frozenset(v) for v in col.compute_groups.values()}
    assert frozenset({"a", "b"}) in groups
    col.update(jnp.asarray([2.0]))
    res = col.compute()
    assert float(res["a"]) == 2.0 and float(res["b"]) == 20.0


def test_add_metrics_sequence_class_name_collision_raises():
    col = MetricCollection([DummyMetricSum()])
    with pytest.raises(ValueError, match="DummyMetricSum"):
        col.add_metrics(DummyMetricSum())


def test_add_metrics_dict_overwrites_like_reference():
    """Dict adds overwrite an existing key silently (reference
    collections.py:304-317 routes through plain __setitem__)."""
    col = MetricCollection({"s": DummyMetricSum()})
    col.update(jnp.asarray(5.0))
    col.add_metrics({"s": DummyMetricSum()})
    assert float(col.compute()["s"]) == 0.0  # fresh metric replaced the old


def test_setitem_contains_len_iter_order():
    col = MetricCollection({"b": DummyMetricSum(), "a": DummyMetricSum()})
    col["c"] = DummyMetricDiff()
    assert "c" in col and "missing" not in col
    assert len(col) == 3
    # insertion order preserved; iteration yields keys (reference ModuleDict)
    assert list(col.keys(keep_base=True))[-1] == "c"
    assert list(iter(col))[-1] == "c"
    # a __setitem__-added metric participates in update/compute (groups rebuilt)
    col.update(jnp.asarray(2.0))
    res = col.compute()
    assert set(res) == {"a", "b", "c"} and float(res["c"]) == -2.0


def test_values_and_items_track_same_objects():
    col = MetricCollection({"x": DummyMetricSum()})
    (k, v), = list(col.items(keep_base=True))
    assert k == "x" and v is list(col.values())[0]
    v.update(jnp.asarray(3.0))
    assert float(col.compute()["x"]) == 3.0


def test_repr_lists_members():
    col = MetricCollection([DummyMetricSum()], prefix="p_")
    r = repr(col)
    assert "MetricCollection" in r and "DummyMetricSum" in r


def test_invalid_prefix_type_raises():
    with pytest.raises(ValueError, match="prefix"):
        MetricCollection([DummyMetricSum()], prefix=5)  # type: ignore[arg-type]


def test_clone_is_independent():
    col = MetricCollection({"s": DummyMetricSum()})
    col.update(jnp.asarray(4.0))
    twin = col.clone(prefix="t_")
    twin.update(jnp.asarray(10.0))
    assert float(col.compute()["s"]) == 4.0          # original untouched
    assert float(twin.compute()["t_s"]) == 14.0       # clone carried state then diverged


def test_persistent_flag_propagates():
    col = MetricCollection({"s": DummyMetricSum()})
    col.persistent(False)
    assert all(not any(m._persistent.values()) for m in col.values())
    col.persistent(True)
    assert all(all(m._persistent.values()) for m in col.values())
