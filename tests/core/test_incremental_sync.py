"""Incremental sync mode (ISSUE-15): per-bucket collectives inside the streak.

Pins the tentpole contract end to end on the 8-device CPU mesh:

* **bitwise identity** — an incremental streak (``init_incremental`` →
  ``advance_incremental``\\* → ``finalize_incremental_state``) produces exactly
  the bytes of the deferred path (``sync_state`` over the final state) for
  exact transports, across fold (integer-sum) and replace (float
  sum/mean/max/min) codecs and every cadence K, including cadence tails;
* **residue proof** — ``count_collectives`` shows emissions inside the streak
  (per-bucket counts) and a ``compute()``-time collective count of zero when
  the cadence divides the streak, residue-only otherwise;
* the **mode/cadence knob surface** — per-state ``add_state(sync_mode=)`` >
  ``set_sync_mode`` > ``METRICS_TPU_SYNC_MODE`` > deferred, and the matching
  ``sync_every`` / ``set_sync_cadence`` / ``METRICS_TPU_SYNC_EVERY`` ladder;
* composition with **quantized transports** (the cadence-compounded error
  bound and its ``emissions``-carrying refusal record), **sharded state**
  (shard_axis leaves stay deferred residue, reshard semantics intact), and
  the **partitioned dispatcher** (an ``"incremental"`` partition-view section;
  a mode flip re-keys the partition exactly once — zero steady-state
  recompiles).
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

import metrics_tpu
from metrics_tpu import Accuracy, MetricCollection, Precision, Recall
from metrics_tpu.core.engine import classify_incremental_member
from metrics_tpu.core.metric import Metric
from metrics_tpu.parallel import sync as sync_mod
from metrics_tpu.parallel.sync import (
    IncrementalCarry,
    advance_incremental,
    count_collectives,
    finalize_incremental_state,
    incremental_plan,
    init_incremental,
    sync_state,
    transport_plan,
)

WORLD = 8


@pytest.fixture(autouse=True)
def _mode_defaults():
    """Every test starts and ends on the factory mode/cadence defaults."""
    metrics_tpu.set_sync_mode(None)
    metrics_tpu.set_sync_cadence(None)
    yield
    metrics_tpu.set_sync_mode(None)
    metrics_tpu.set_sync_cadence(None)


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


# fold (int-sum) and replace (float sum/max) codecs side by side, plus a
# scalar of each — exercises both emission arms and both bucket layouts
_STATE = {
    "hits": jnp.arange(16, dtype=jnp.int32),
    "n": jnp.asarray(0, jnp.int32),
    "total": jnp.zeros((8,), jnp.float32),
    "peak": jnp.asarray(-jnp.inf, jnp.float32),
}
_REDS = {"hits": "sum", "n": "sum", "total": "sum", "peak": "max"}
_INCR = {k: "incremental" for k in _STATE}


def _step(state, x):
    """One deterministic, device-dependent update of _STATE."""
    return {
        "hits": state["hits"] + x.astype(jnp.int32),
        "n": state["n"] + jnp.asarray(1, jnp.int32),
        "total": state["total"] + jnp.sin(x[:8].astype(jnp.float32)),
        "peak": jnp.maximum(state["peak"], jnp.max(x.astype(jnp.float32))),
    }


def _batches(steps, seed=0):
    rng = np.random.default_rng(seed)
    return [
        jnp.asarray(rng.integers(0, 7, (WORLD, 16)), jnp.int32)
        for _ in range(steps)
    ]


def _run_incremental(mesh, batches, sync_every, reds=_REDS, modes=_INCR):
    """Full streak under shard_map: carry protocol, finalize at the end."""

    def body(xs):
        carry = init_incremental(
            dict(_STATE), reds, modes=modes, sync_every=sync_every
        )
        for i in range(xs.shape[1]):
            state = _step(carry.state, xs[0, i])
            carry = advance_incremental(carry, state, reds, "data", modes=modes)
        out = finalize_incremental_state(carry, reds, "data", modes=modes)
        return jax.tree_util.tree_map(lambda v: jnp.expand_dims(v, 0), out)

    stacked = jnp.stack(batches, axis=1)  # (WORLD, steps, 16)
    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
    return jax.jit(f)(stacked)


def _run_deferred(mesh, batches):
    """The seed path: update streak, one deferred sync_state at the end."""

    def body(xs):
        state = dict(_STATE)
        for i in range(xs.shape[1]):
            state = _step(state, xs[0, i])
        out = sync_state(state, _REDS, "data")
        return jax.tree_util.tree_map(lambda v: jnp.expand_dims(v, 0), out)

    stacked = jnp.stack(batches, axis=1)
    f = shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
    return jax.jit(f)(stacked)


def _assert_trees_bitwise(a, b):
    flat_a, td_a = jax.tree_util.tree_flatten(a)
    flat_b, td_b = jax.tree_util.tree_flatten(b)
    assert td_a == td_b
    for x, y in zip(flat_a, flat_b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ----------------------------------------------------------------- parity ----
@pytest.mark.mesh8
class TestBitwiseParity:
    @pytest.mark.parametrize("k", [1, 2, 5, 7])
    def test_streak_matches_deferred(self, mesh, k):
        """5-step streak, every cadence class: K=1 (emit each step), K=2
        (tail of 1), K=5 (single emission, no tail), K=7 (never emits —
        finalize degrades to the deferred path)."""
        batches = _batches(5)
        _assert_trees_bitwise(
            _run_incremental(mesh, batches, sync_every=k),
            _run_deferred(mesh, batches),
        )

    def test_mixed_modes_match_deferred(self, mesh):
        """Half the leaves declared incremental, half left deferred — the
        split-routing finalize still reproduces the deferred bytes."""
        modes = {"hits": "incremental", "total": "incremental"}
        batches = _batches(4, seed=3)
        _assert_trees_bitwise(
            _run_incremental(mesh, batches, sync_every=1, modes=modes),
            _run_deferred(mesh, batches),
        )

    def test_metric_protocol_matches_sync_states(self, mesh):
        """The Metric-level carry protocol on a real domain metric: an
        incremental Accuracy streak finalizes to the exact bytes (and the
        exact compute()) of the deferred sync_states path."""
        m = Accuracy(num_classes=5, average="micro")
        for name in m._defaults:
            m._sync_modes[name] = "incremental"
        rng = np.random.default_rng(7)
        preds = jnp.asarray(rng.standard_normal((4, WORLD, 16, 5)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, 5, (4, WORLD, 16)))

        def run_incr(p, t):
            carry = m.init_incremental(m.init_state(), sync_every=2)
            for i in range(p.shape[0]):
                carry = m.update_state_incremental(carry, p[i, 0], t[i, 0], axis_name="data")
            out = m.finalize_incremental(carry, "data")
            return jax.tree_util.tree_map(lambda v: jnp.expand_dims(v, 0), out)

        def run_def(p, t):
            state = m.init_state()
            for i in range(p.shape[0]):
                state = m.update_state(state, p[i, 0], t[i, 0])
            out = m.sync_states(state, "data")
            return jax.tree_util.tree_map(lambda v: jnp.expand_dims(v, 0), out)

        kw = dict(mesh=mesh, in_specs=P(None, "data"), out_specs=P("data"), check_rep=False)
        got = jax.jit(shard_map(run_incr, **kw))(preds, target)
        ref = jax.jit(shard_map(run_def, **kw))(preds, target)
        _assert_trees_bitwise(got, ref)
        np.testing.assert_array_equal(
            np.asarray(m.compute_state(jax.tree_util.tree_map(lambda v: v[0], got))),
            np.asarray(m.compute_state(jax.tree_util.tree_map(lambda v: v[0], ref))),
        )


# ------------------------------------------------------- collective counts ---
def _count_emit(sync_every, steps, modes=_INCR, reds=_REDS):
    """Per-phase trace-time collective counts of a whole streak."""

    def streak(state0):
        carry = init_incremental(dict(state0), reds, modes=modes, sync_every=sync_every)
        boxes = []
        for _ in range(steps):
            state = _step(carry.state, jnp.zeros((16,), jnp.int32))
            with count_collectives() as step_box:
                carry = advance_incremental(carry, state, reds, "data", modes=modes)
            boxes.append(step_box["count"])
        with count_collectives() as fin_box:
            finalize_incremental_state(carry, reds, "data", modes=modes)
        return boxes, fin_box["count"]

    per_step = []
    final = []

    def probe(state0):
        steps_counts, fin = streak(state0)
        per_step.extend(steps_counts)
        final.append(fin)
        return jnp.zeros(())

    jax.make_jaxpr(probe, axis_env=[("data", WORLD)])(_STATE)
    return per_step, final[0]


class TestCollectiveCounts:
    def test_k1_emits_per_bucket_every_step_and_free_finalize(self):
        # one int-sum fold bucket + one f32-sum replace + one f32-max replace
        per_step, final = _count_emit(sync_every=1, steps=4)
        assert per_step == [3, 3, 3, 3]
        assert final == 0  # pending == 0: compute-time collectives are gone

    def test_cadence_skips_steps_and_finalize_pays_tail_only(self):
        per_step, final = _count_emit(sync_every=4, steps=6)
        # emissions only on steps 4 (the rest just count pending)
        assert per_step == [0, 0, 0, 3, 0, 0]
        # tail of 2 pending: 1 residual fold-delta psum + the 2 replace
        # buckets re-sync fully through the deferred path
        assert final == 3

    def test_never_emitting_carry_finalizes_like_deferred(self):
        per_step, final = _count_emit(sync_every=9, steps=3)
        assert per_step == [0, 0, 0]
        with count_collectives() as ref:
            jax.make_jaxpr(
                lambda st: sync_state(st, _REDS, "data"),
                axis_env=[("data", WORLD)],
            )(_STATE)
        assert final == ref["count"]

    def test_deferred_leaves_cost_nothing_in_the_streak(self):
        modes = {"hits": "incremental"}  # one fold leaf; rest stays deferred
        per_step, final = _count_emit(sync_every=1, steps=2, modes=modes)
        assert per_step == [1, 1]
        # residue: one int-sum bucket ("n" shares hits' dtype but is
        # deferred), one f32-sum, one f32-max
        assert final == 3

    def test_no_axis_advance_never_emits(self):
        """The facade/plain-jit path: axis_name=None tracks state only, so
        the carry is deferred-equivalent by construction."""
        carry = init_incremental(dict(_STATE), _REDS, modes=_INCR, sync_every=1)
        with count_collectives() as box:
            jax.make_jaxpr(
                lambda st: advance_incremental(
                    carry, st, _REDS, None, modes=_INCR
                ).state
            )(_STATE)
        assert box["count"] == 0
        stepped = advance_incremental(carry, dict(_STATE), _REDS, None, modes=_INCR)
        assert stepped.emissions == 0
        out = finalize_incremental_state(stepped, _REDS, None, modes=_INCR)
        _assert_trees_bitwise(out, dict(_STATE))


# --------------------------------------------------- carry / retrace bounds --
class TestCarryStability:
    def test_carry_is_a_registered_pytree(self):
        carry = init_incremental(dict(_STATE), _REDS, modes=_INCR, sync_every=3)
        leaves, treedef = jax.tree_util.tree_flatten(carry)
        rebuilt = jax.tree_util.tree_unflatten(treedef, leaves)
        assert isinstance(rebuilt, IncrementalCarry)
        assert rebuilt.sync_every == 3 and rebuilt.pending == 0
        assert not rebuilt.synced

    def test_signature_set_is_bounded_by_cadence(self):
        """pending cycles 0..K-1 and emissions saturates at 1 without
        quantized transports — a 20-step K=3 streak sees a bounded set of
        static carry signatures, so a per-step jit compiles a bounded number
        of programs no matter how long the streak runs."""
        seen = set()

        def streak(state0):
            carry = init_incremental(dict(state0), _REDS, modes=_INCR, sync_every=3)
            for _ in range(20):
                state = _step(carry.state, jnp.zeros((16,), jnp.int32))
                carry = advance_incremental(carry, state, _REDS, "data", modes=_INCR)
                # static aux is concrete at trace time — this IS the treedef
                seen.add((carry.sync_every, carry.pending, carry.emissions,
                          carry.track_emissions))
            return jnp.zeros(())

        jax.make_jaxpr(streak, axis_env=[("data", WORLD)])(_STATE)
        assert {p for (_, p, _, _) in seen} == {0, 1, 2}  # cycles, never grows
        # pre-first-emission steps carry 0; afterwards saturated at 1 forever
        assert {e for (_, _, e, _) in seen} == {0, 1}
        assert len(seen) <= 5

    def test_no_axis_pending_saturates(self):
        carry = init_incremental(dict(_STATE), _REDS, modes=_INCR, sync_every=2)
        for _ in range(10):
            carry = advance_incremental(carry, dict(_STATE), _REDS, None, modes=_INCR)
        assert carry.pending == 2  # saturated at K, not 10


# ------------------------------------------------------------ mode plumbing --
class TestModeSurface:
    def test_plan_routing_and_codecs(self):
        plan = incremental_plan(_STATE, _REDS, modes=_INCR)
        assert plan["hits"]["codec"] == "fold" and plan["hits"]["mode"] == "incremental"
        assert plan["n"]["codec"] == "fold"
        assert plan["total"]["codec"] == "replace"
        assert plan["peak"]["codec"] == "replace"
        assert all(e["eligible"] for e in plan.values())

    def test_default_mode_is_deferred(self):
        assert metrics_tpu.sync_mode_default() == "deferred"
        plan = incremental_plan(_STATE, _REDS)
        assert all(e["mode"] == "deferred" for e in plan.values())
        assert all(e["eligible"] for e in plan.values())

    def test_global_switch_engages_all_eligible(self):
        metrics_tpu.set_sync_mode("incremental")
        plan = incremental_plan(_STATE, _REDS)
        assert all(e["mode"] == "incremental" for e in plan.values())

    def test_per_state_declaration_beats_global(self):
        metrics_tpu.set_sync_mode("incremental")
        plan = incremental_plan(_STATE, _REDS, modes={"hits": "deferred"})
        assert plan["hits"]["mode"] == "deferred"
        assert plan["total"]["mode"] == "incremental"

    def test_env_var_is_the_weakest_rung(self, monkeypatch):
        monkeypatch.setenv("METRICS_TPU_SYNC_MODE", "incremental")
        assert metrics_tpu.sync_mode_default() == "incremental"
        metrics_tpu.set_sync_mode("deferred")  # process switch beats env
        assert metrics_tpu.sync_mode_default() == "deferred"
        metrics_tpu.set_sync_mode(None)  # back to env
        assert metrics_tpu.sync_mode_default() == "incremental"

    def test_unknown_modes_raise(self):
        with pytest.raises(ValueError, match="unknown sync mode"):
            metrics_tpu.set_sync_mode("streaming")
        with pytest.raises(ValueError, match="unknown sync mode"):
            incremental_plan(_STATE, _REDS, modes={"hits": "lazy"})

    def test_add_state_sync_mode_kwarg(self):
        class Declared(Metric):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state(
                    "c", default=jnp.zeros((), jnp.int32), dist_reduce_fx="sum",
                    sync_mode="incremental",
                )

            def update(self):
                self.c = self.c + 1

            def compute(self):
                return self.c

        m = Declared()
        assert m.sync_modes == {"c": "incremental"}
        assert m.incremental_plan()["c"]["mode"] == "incremental"

        class Bad(Metric):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_state(
                    "c", default=jnp.zeros(()), dist_reduce_fx="sum",
                    sync_mode="sometimes",
                )

            def update(self):
                pass

            def compute(self):
                return self.c

        with pytest.raises(ValueError, match="sync_mode"):
            Bad()

    def test_cadence_ladder(self, monkeypatch):
        assert metrics_tpu.sync_cadence_default() == 1
        monkeypatch.setenv("METRICS_TPU_SYNC_EVERY", "4")
        assert metrics_tpu.sync_cadence_default() == 4
        metrics_tpu.set_sync_cadence(2)
        assert metrics_tpu.sync_cadence_default() == 2
        metrics_tpu.set_sync_cadence(None)
        assert metrics_tpu.sync_cadence_default() == 4
        with pytest.raises(ValueError):
            metrics_tpu.set_sync_cadence(0)
        with pytest.raises(ValueError):
            init_incremental(dict(_STATE), _REDS, modes=_INCR, sync_every=0)


# ----------------------------------------------- ineligible leaves / residue --
class TestResidueRouting:
    def test_cat_list_callable_and_sharded_stay_deferred(self):
        state = {
            "rows": jnp.zeros((4, 2)),
            "chunks": [jnp.zeros((2,))],
            "custom": jnp.zeros(()),
            "tiles": jnp.zeros((8, 3)),
        }
        reds = {
            "rows": "cat",
            "chunks": "cat",
            "custom": lambda xs: xs,
            "tiles": "sum",
        }
        plan = incremental_plan(
            state, reds,
            modes={k: "incremental" for k in state},
            shard_axes={"tiles": 0},
        )
        assert all(e["mode"] == "deferred" for e in plan.values())
        assert all(not e["eligible"] for e in plan.values())
        assert "not mergeable-elementwise" in plan["rows"]["reason"]
        assert "per-device layout" in plan["chunks"]["reason"]
        assert "resharded at finalize" in plan["tiles"]["reason"]

    @pytest.mark.mesh8
    def test_sharded_leaf_reshards_at_finalize_only(self, mesh):
        """shard_axis residue under incremental mode: the streak emits only
        the elementwise buckets; finalize routes the sharded leaf through the
        same reshard path as the deferred seed, bitwise."""
        state = {
            "tiles": jnp.arange(WORLD * 3, dtype=jnp.float32).reshape(WORLD, 3),
            "hits": jnp.arange(4, dtype=jnp.int32),
        }
        reds = {"tiles": "sum", "hits": "sum"}
        modes = {k: "incremental" for k in state}
        shard_axes = {"tiles": 0}

        def body_incr(st):
            local = jax.tree_util.tree_map(lambda v: v[0], st)
            carry = init_incremental(
                local, reds, modes=modes, shard_axes=shard_axes, sync_every=1
            )
            stepped = {
                "tiles": local["tiles"] * 2.0, "hits": local["hits"] + 1
            }
            carry = advance_incremental(
                carry, stepped, reds, "data", modes=modes, shard_axes=shard_axes
            )
            out = finalize_incremental_state(
                carry, reds, "data", modes=modes, shard_axes=shard_axes
            )
            return jax.tree_util.tree_map(lambda v: jnp.expand_dims(v, 0), out)

        def body_def(st):
            local = jax.tree_util.tree_map(lambda v: v[0], st)
            stepped = {
                "tiles": local["tiles"] * 2.0, "hits": local["hits"] + 1
            }
            out = sync_state(stepped, reds, "data", shard_axes=shard_axes)
            return jax.tree_util.tree_map(lambda v: jnp.expand_dims(v, 0), out)

        per_dev = jax.tree_util.tree_map(
            lambda v: jnp.stack([v * (i + 1) for i in range(WORLD)]), state
        )
        kw = dict(mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_rep=False)
        got = jax.jit(shard_map(body_incr, **kw))(per_dev)
        ref = jax.jit(shard_map(body_def, **kw))(per_dev)
        _assert_trees_bitwise(got, ref)

    def test_sharded_emission_excludes_reshard_buckets(self):
        state = {
            "tiles": jnp.zeros((WORLD, 3), jnp.float32),
            "hits": jnp.zeros((4,), jnp.int32),
        }
        reds = {"tiles": "sum", "hits": "sum"}
        modes = {k: "incremental" for k in state}
        carry = init_incremental(
            state, reds, modes=modes, shard_axes={"tiles": 0}, sync_every=1
        )
        assert set(carry.acc) == {"hits"}  # the sharded leaf is residue
        with count_collectives() as box:
            jax.make_jaxpr(
                lambda st: advance_incremental(
                    carry, st, reds, "data", modes=modes, shard_axes={"tiles": 0}
                ).acc,
                axis_env=[("data", WORLD)],
            )(state)
        assert box["count"] == 1  # the int-sum fold bucket only


# --------------------------------------------------- quantized composition ---
class TestQuantizedComposition:
    def test_cadence_compounds_the_error_bound(self):
        state = {"total": jnp.zeros((256,), jnp.float32)}
        reds = {"total": "sum"}
        # tolerance wide enough to admit both scales: the planned bound must
        # be the per-emission bound compounded by the emission ordinal
        tol = metrics_tpu.transport_error_bound("bf16", WORLD) * 8.0
        single = transport_plan(
            state, reds, WORLD,
            transports={"total": "bf16"}, tolerances={"total": tol},
        )
        fourth = transport_plan(
            state, reds, WORLD,
            transports={"total": "bf16"}, tolerances={"total": tol},
            error_scale=4.0,
        )
        assert single[0]["transport"] == fourth[0]["transport"] == "bf16"
        assert fourth[0]["bound"] == pytest.approx(single[0]["bound"] * 4.0)

    def test_refusal_reports_effective_emission_count(self):
        """A tolerance sized for the single-shot bound but not the 4th
        compounded emission: the gate refuses and the record says which
        emission ordinal's bound was judged."""
        state = {"total": jnp.zeros((256,), jnp.float32)}
        reds = {"total": "sum"}
        tol = metrics_tpu.transport_error_bound("bf16", WORLD) * 2.0
        ok = transport_plan(
            state, reds, WORLD,
            transports={"total": "bf16"}, tolerances={"total": tol},
        )
        assert ok[0]["refusal"] is None
        refused = transport_plan(
            state, reds, WORLD,
            transports={"total": "bf16"}, tolerances={"total": tol},
            error_scale=4.0,
        )
        assert refused[0]["transport"] == "exact"
        assert refused[0]["refusal"]["reason"] == "error_budget"
        assert refused[0]["refusal"]["emissions"] == 4

    def test_quantized_emissions_track_real_ordinal(self):
        """With a quantized transport on a covered leaf the carry tracks the
        true emission ordinal (no saturation) so each emission's gate judges
        the compounded bound; exact carries saturate at 1 instead."""
        state = {"hits": jnp.zeros((16,), jnp.int32)}
        reds = {"hits": "sum"}
        modes = {"hits": "incremental"}

        def streak(st0, transports):
            ordinals = []
            carry = init_incremental(
                dict(st0), reds, modes=modes, sync_every=1, transports=transports
            )
            for _ in range(3):
                state = {"hits": carry.state["hits"] + 1}
                carry = advance_incremental(
                    carry, state, reds, "data", modes=modes, transports=transports
                )
                ordinals.append(carry.emissions)
            return ordinals

        quant = []
        exact = []
        jax.make_jaxpr(
            lambda st: (quant.extend(streak(st, {"hits": "sparse_count"})), jnp.zeros(()))[1],
            axis_env=[("data", WORLD)],
        )(state)
        jax.make_jaxpr(
            lambda st: (exact.extend(streak(st, None)), jnp.zeros(()))[1],
            axis_env=[("data", WORLD)],
        )(state)
        assert quant == [1, 2, 3]  # real ordinals — the gate compounds
        assert exact == [1, 1, 1]  # saturated — bounded jit signatures


# -------------------------------------------------- engine / partition view --
class TestEngineIntegration:
    def _config2(self):
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=5, average="micro"),
                "prec": Precision(num_classes=5, average="macro"),
                "rec": Recall(num_classes=5, average="macro"),
            }
        )

    def test_classifier_follows_the_resolved_mode(self):
        m = Accuracy(num_classes=5)
        assert classify_incremental_member(m)[0] == "deferred"
        metrics_tpu.set_sync_mode("incremental")
        path, reason = classify_incremental_member(m)
        assert path == "incremental"
        assert "emission" in reason

    def test_partition_view_reports_incremental_section(self):
        coll = self._config2()
        rng = np.random.default_rng(0)
        preds = jnp.asarray(rng.standard_normal((32, 5)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, 5, 32))
        coll.update(preds, target)
        view = coll._dispatcher.partition_view()
        assert set(view["incremental"]) == set(coll._metrics)
        assert all(
            info["path"] in ("incremental", "deferred")
            for info in view["incremental"].values()
        )
        assert all(
            info["path"] == "deferred" for info in view["incremental"].values()
        )

    def test_mode_flip_rekeys_partition_exactly_once(self):
        coll = self._config2()
        rng = np.random.default_rng(1)
        preds = jnp.asarray(rng.standard_normal((32, 5)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, 5, 32))
        for _ in range(4):
            coll.update(preds, target)
        assert coll._dispatcher.stats.builds == 1
        metrics_tpu.set_sync_mode("incremental")
        try:
            coll.update(preds, target)
            stats = coll._dispatcher.stats
            assert stats.repartitions == 1
            view = coll._dispatcher.partition_view()
            assert all(
                info["path"] == "incremental"
                for info in view["incremental"].values()
            )
            # steady state after the flip: no further churn
            for _ in range(3):
                coll.update(preds, target)
            assert coll._dispatcher.stats.repartitions == 1
        finally:
            metrics_tpu.set_sync_mode(None)

    def test_default_deferred_path_is_structurally_unchanged(self):
        """With the mode ladder at its default every leaf routes deferred and
        sync_states traces to exactly the canonical bucketed program — the
        incremental machinery is invisible until opted into."""
        m = Accuracy(num_classes=5)
        plan = m.incremental_plan(m.init_state())
        assert all(e["mode"] == "deferred" for e in plan.values())
        state = m.init_state()
        jx_now = str(
            jax.make_jaxpr(
                lambda st: m.sync_states(st, "data"), axis_env=[("data", WORLD)]
            )(state)
        )
        jx_raw = str(
            jax.make_jaxpr(
                lambda st: sync_state(st, m._reductions, "data"),
                axis_env=[("data", WORLD)],
            )(state)
        )
        assert jx_now == jx_raw

    def test_collection_carry_protocol_round_trips(self):
        coll = self._config2()
        rng = np.random.default_rng(5)
        preds = jnp.asarray(rng.standard_normal((32, 5)).astype(np.float32))
        target = jnp.asarray(rng.integers(0, 5, 32))
        states = coll.init_state()
        carries = coll.init_incremental(states, sync_every=2)
        assert set(carries) == {g[0] for g in coll._groups}
        carries = coll.update_state_incremental(carries, preds, target)
        out = coll.finalize_incremental(carries)
        ref = {g[0]: coll._metrics[g[0]].update_state(states[g[0]], preds, target)
               for g in coll._groups}
        _assert_trees_bitwise(out, ref)  # axis-free: deferred-equivalent
        vals = coll.sync_compute_incremental(
            coll.update_state_incremental(coll.init_incremental(states), preds, target)
        )
        ref_vals = coll.compute_state(ref)
        for name in ref_vals:
            np.testing.assert_array_equal(
                np.asarray(vals[name]), np.asarray(ref_vals[name])
            )
