"""Distributed sync semantics over the 8-device CPU mesh.

Reference parity: tests/bases/test_ddp.py — reduction correctness (:31-60),
compositional metrics under DDP (:84-91), synced-save/unsync-restore
(:135-241). The gloo pool is replaced by shard_map collectives.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Metric
from tests.helpers.testers import DummyListMetric, DummyMetricSum

WORLD = 8


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


@pytest.mark.mesh8
def test_sum_sync(mesh):
    m = DummyMetricSum()

    def body(x):
        state = m.update_state(m.init_state(), x[0, 0])
        state = m.sync_states(state, "data")
        return jnp.expand_dims(m.compute_state(state), 0)

    xs = jnp.arange(WORLD, dtype=jnp.float32).reshape(WORLD, 1)
    out = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_vma=False))(xs)
    assert float(out[0]) == sum(range(WORLD))
    assert np.allclose(np.asarray(out), sum(range(WORLD)))  # identical on every device


@pytest.mark.mesh8
def test_cat_sync_preserves_order(mesh):
    m = DummyListMetric()

    def body(x):
        state = m.update_state(m.init_state(), x[0])
        state = m.sync_states(state, "data")
        return jnp.expand_dims(jnp.concatenate([jnp.atleast_1d(v) for v in state["x"]]), 0)

    xs = jnp.arange(WORLD, dtype=jnp.float32).reshape(WORLD, 1)
    out = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_vma=False))(xs)
    np.testing.assert_allclose(np.asarray(out[0]), np.arange(WORLD))


@pytest.mark.mesh8
def test_all_reduction_tags(mesh):
    class Multi(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("s", jnp.asarray(0.0), "sum")
            self.add_state("mu", jnp.asarray(0.0), "mean")
            self.add_state("mx", jnp.asarray(-jnp.inf), "max")
            self.add_state("mn", jnp.asarray(jnp.inf), "min")

        def update(self, x):
            self.s, self.mu, self.mx, self.mn = x, x, x, x

        def compute(self):
            return jnp.stack([self.s, self.mu, self.mx, self.mn])

    m = Multi()

    def body(x):
        state = m.update_state(m.init_state(), x[0, 0])
        state = m.sync_states(state, "data")
        return jnp.expand_dims(m.compute_state(state), 0)

    xs = jnp.arange(WORLD, dtype=jnp.float32).reshape(WORLD, 1)
    out = np.asarray(
        jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_vma=False))(xs)
    )[0]
    vals = np.arange(WORLD, dtype=np.float32)
    np.testing.assert_allclose(out, [vals.sum(), vals.mean(), vals.max(), vals.min()])


@pytest.mark.mesh8
def test_custom_callable_reduction(mesh):
    class Custom(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("x", jnp.asarray(0.0), dist_reduce_fx=lambda stacked: jnp.prod(stacked, axis=0))

        def update(self, x):
            self.x = x

        def compute(self):
            return self.x

    m = Custom()

    def body(x):
        state = m.update_state(m.init_state(), x[0, 0])
        state = m.sync_states(state, "data")
        return jnp.expand_dims(m.compute_state(state), 0)

    xs = (jnp.arange(WORLD, dtype=jnp.float32) + 1).reshape(WORLD, 1)
    out = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_vma=False))(xs)
    assert float(out[0]) == float(np.prod(np.arange(WORLD) + 1))


def test_merge_equals_sync():
    """Cross-batch merge and cross-device sync are the same reduction —
    the single-code-path property (SURVEY.md §7 decision 2)."""
    m = DummyMetricSum()
    states = [m.update_state(m.init_state(), jnp.asarray(float(i))) for i in range(4)]
    merged = states[0]
    for s in states[1:]:
        merged = m.merge_states(merged, s)
    assert float(m.compute_state(merged)) == 6.0
