"""Checkpoint / state-dict roundtrip parity sweep over every exported metric.

Driven by the same declarative ``ANALYSIS_SPECS`` tables the static analyzer
uses (``metrics_tpu.analysis.registry``), extended with the ``"ckpt"`` key:
concrete inputs where synthesized ``(dtype, shape)`` arrays would be invalid
(strings, box dicts, monotonic x), ``int_high`` bounds for label inputs, and
explicit skips with reasons (host DSP, network-weight models).

Two assertions per metric:

* ``state_dict`` -> fresh instance -> ``load_state_dict`` reproduces the
  registered states exactly. (State only: update-determined python config
  like ``Accuracy.mode`` is deliberately outside ``state_dict`` — that is
  what the checkpoint's aux channel exists for.)
* ``save_checkpoint`` -> fresh instance -> ``restore_checkpoint`` reproduces
  states, update counts, *and* ``compute()`` output, including for wrappers
  whose state lives in child metrics.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np
import pytest

from metrics_tpu.analysis.registry import Entry, build_registry
from metrics_tpu.checkpoint import restore_checkpoint, save_checkpoint
from metrics_tpu.core.buffers import CatBuffer
from metrics_tpu.sketches.base import is_sketch


def _sweepable(entry: Entry) -> bool:
    if entry.spec is None or entry.ckpt.get("skip"):
        return False
    if entry.spec.get("no_probe") and "init_fn" not in entry.ckpt:
        return False
    return True


_ENTRIES: Dict[str, Entry] = {e.name: e for e in build_registry()}
_SWEEP = sorted(name for name, e in _ENTRIES.items() if _sweepable(e))


def _make(entry: Entry) -> Any:
    if "init_fn" in entry.ckpt:
        return entry.ckpt["init_fn"]()
    init_fn = entry.spec.get("init_fn")
    if init_fn is not None:
        return init_fn()
    return entry.cls(**entry.spec.get("init", {}))


def _synth_inputs(entry: Entry, seed: int) -> Tuple[Tuple[Any, ...], Dict[str, Any]]:
    if "inputs_fn" in entry.ckpt:
        return entry.ckpt["inputs_fn"]()
    inputs = entry.spec.get("inputs")
    if not inputs:
        pytest.fail(
            f"{entry.name}: no 'inputs' spec and no ckpt inputs_fn/skip — every "
            "exported metric must declare checkpoint-sweep coverage"
        )
    rng = np.random.default_rng(seed)
    int_high = int(entry.ckpt.get("int_high", 2))
    args: List[Any] = []
    for dtype, shape in inputs:
        if np.issubdtype(np.dtype(dtype), np.integer):
            args.append(rng.integers(0, int_high, shape).astype(dtype))
        else:
            args.append(rng.uniform(0.0, 1.0, shape).astype(dtype))
    return tuple(args), dict(entry.spec.get("static_kwargs", {}))


def _feed(metric: Any, entry: Entry) -> None:
    n_updates = int(entry.ckpt.get("updates", 2))
    for i in range(n_updates):
        args, kwargs = _synth_inputs(entry, seed=100 + i)
        metric.update(*args, **kwargs)


def _assert_leaf_equal(va: Any, vb: Any, where: str) -> None:
    if is_sketch(va):
        assert type(va) is type(vb), where
        assert va.config_dict() == vb.config_dict(), where
        for fname, _ in va.sketch_fields:
            np.testing.assert_array_equal(
                np.asarray(getattr(va, fname)),
                np.asarray(getattr(vb, fname)),
                err_msg=f"{where}.{fname}",
            )
    elif isinstance(va, CatBuffer):
        assert isinstance(vb, CatBuffer), where
        empty_a = not va.materialized or int(va.count) == 0
        empty_b = not vb.materialized or int(vb.count) == 0
        if empty_a or empty_b:
            assert empty_a == empty_b, where
            return
        np.testing.assert_array_equal(
            np.asarray(va.to_array()), np.asarray(vb.to_array()), err_msg=where
        )
    elif isinstance(va, (list, tuple)):
        assert isinstance(vb, (list, tuple)) and len(va) == len(vb), where
        for i, (xa, xb) in enumerate(zip(va, vb)):
            np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb), err_msg=f"{where}[{i}]")
    else:
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb), err_msg=where)


def _assert_state_equal(ma: Any, mb: Any, ctx: str) -> None:
    sa, sb = ma.get_state(), mb.get_state()
    assert set(sa) == set(sb), ctx
    for key in sa:
        _assert_leaf_equal(sa[key], sb[key], f"{ctx}:{key}")


def _assert_compute_equal(ra: Any, rb: Any, ctx: str) -> None:
    import jax

    la, ta = jax.tree_util.tree_flatten(ra)
    lb, tb = jax.tree_util.tree_flatten(rb)
    assert ta == tb, f"{ctx}: compute tree structure differs"
    for i, (xa, xb) in enumerate(zip(la, lb)):
        np.testing.assert_allclose(
            np.asarray(xa), np.asarray(xb), rtol=1e-6, atol=1e-6, err_msg=f"{ctx}:leaf{i}"
        )


@pytest.mark.parametrize("name", _SWEEP)
def test_state_dict_roundtrip(name: str) -> None:
    entry = _ENTRIES[name]
    m1 = _make(entry)
    m1.persistent(True)  # states default to persistent=False (reference parity)
    _feed(m1, entry)
    m2 = _make(entry)
    m2.persistent(True)
    m2.load_state_dict(m1.state_dict())
    _assert_state_equal(m1, m2, f"{name}:state_dict")


@pytest.mark.parametrize("name", _SWEEP)
def test_checkpoint_roundtrip(name: str, tmp_path) -> None:
    entry = _ENTRIES[name]
    m1 = _make(entry)
    _feed(m1, entry)
    handle = save_checkpoint(m1, str(tmp_path), shard_index=0, world_size=1)
    assert handle.committed

    m2 = _make(entry)
    restore_checkpoint(m2, str(tmp_path), host_index=0, host_count=1)
    _assert_state_equal(m1, m2, f"{name}:checkpoint")
    assert m1._update_count == m2._update_count, name
    _assert_compute_equal(m1.compute(), m2.compute(), name)


def test_every_export_declares_sweep_coverage() -> None:
    """The merge gate: a metric is either swept or carries an explicit reason."""
    for name, entry in _ENTRIES.items():
        if name in _SWEEP:
            continue
        assert (
            entry.spec is not None
            and (entry.ckpt.get("skip") or entry.spec.get("no_probe"))
        ), f"{name} is neither swept nor explicitly ckpt-skipped"


def test_skips_carry_reasons() -> None:
    for name, entry in _ENTRIES.items():
        skip = entry.ckpt.get("skip")
        if skip is not None:
            assert isinstance(skip, str) and len(skip) > 10, name
