"""Checkpoint/resume through orbax — the real trainer integration path.

The reference piggybacks on ``nn.Module.state_dict`` consumed by torch
checkpointers (reference metric.py:639-677, SURVEY.md §5.4); the TPU analog is
``Metric.state_dict`` (numpy leaves) saved and restored with orbax, the
standard JAX checkpointer. These tests do the full disk round trip:
accumulate -> save -> keep training -> crash -> restore -> resume -> compute,
asserting the resumed value equals an uninterrupted run.
"""
import numpy as np
import pytest

import jax.numpy as jnp

ocp = pytest.importorskip("orbax.checkpoint")

from metrics_tpu import AUROC, Accuracy, MeanMetric, MetricCollection  # noqa: E402


def _batches(n, seed=0, classes=10, batch=32):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        yield (
            jnp.asarray(rng.normal(size=(batch, classes)).astype(np.float32)),
            jnp.asarray(rng.integers(0, classes, size=(batch,)).astype(np.int32)),
        )


def _save(path, tree):
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree)


def _restore(path, like):
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, like)


def test_metric_state_dict_orbax_roundtrip(tmp_path):
    metric = Accuracy(num_classes=10)
    metric.persistent(True)  # states are non-persistent by default (reference parity)
    batches = list(_batches(6))
    for preds, target in batches[:3]:
        metric.update(preds, target)

    _save(tmp_path / "ckpt", metric.state_dict())

    # the process "crashes": a fresh metric restores mid-epoch state from disk
    resumed = Accuracy(num_classes=10)
    resumed.persistent(True)
    restored = _restore(tmp_path / "ckpt", resumed.state_dict())
    resumed.load_state_dict(restored)
    for preds, target in batches[3:]:
        resumed.update(preds, target)

    uninterrupted = Accuracy(num_classes=10)
    for preds, target in batches:
        uninterrupted.update(preds, target)
    assert float(resumed.compute()) == pytest.approx(float(uninterrupted.compute()), abs=1e-7)


def test_collection_orbax_roundtrip(tmp_path):
    def make():
        coll = MetricCollection({"acc": Accuracy(num_classes=10), "mean": MeanMetric()})
        coll.persistent(True)
        return coll

    coll = make()
    batches = list(_batches(4, seed=1))
    for preds, target in batches[:2]:
        coll["acc"].update(preds, target)
        coll["mean"].update(preds.mean())

    _save(tmp_path / "ckpt", coll.state_dict())

    resumed = make()
    resumed.load_state_dict(_restore(tmp_path / "ckpt", resumed.state_dict()))
    for preds, target in batches[2:]:
        resumed["acc"].update(preds, target)
        resumed["mean"].update(preds.mean())

    full = make()
    for preds, target in batches:
        full["acc"].update(preds, target)
        full["mean"].update(preds.mean())
    got, want = resumed.compute(), full.compute()
    for key in want:
        assert float(got[key]) == pytest.approx(float(want[key]), abs=1e-6), key


def test_catbuffer_state_orbax_roundtrip(tmp_path):
    """List/buffer states (curve metrics) survive the disk round trip too."""
    metric = AUROC(buffer_capacity=256)
    metric.persistent(True)
    batches = [
        (jnp.asarray(np.random.default_rng(i).uniform(size=(32,)).astype(np.float32)),
         jnp.asarray(np.random.default_rng(100 + i).integers(0, 2, size=(32,)).astype(np.int32)))
        for i in range(4)
    ]
    for preds, target in batches[:2]:
        metric.update(preds, target)

    _save(tmp_path / "ckpt", metric.state_dict())

    resumed = AUROC(buffer_capacity=256)
    resumed.persistent(True)
    resumed.load_state_dict(_restore(tmp_path / "ckpt", resumed.state_dict()))
    for preds, target in batches[2:]:
        resumed.update(preds, target)

    full = AUROC(buffer_capacity=256)
    for preds, target in batches:
        full.update(preds, target)
    assert float(resumed.compute()) == pytest.approx(float(full.compute()), abs=1e-6)
