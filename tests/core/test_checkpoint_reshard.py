"""Reshard-on-restore: an N-host checkpoint folds onto M hosts.

Host i of M claims shards {i, i+M, i+2M, ...} and folds them with each
leaf's recorded reduction via the metric's own ``merge_states`` — so a folded
restore is bitwise-identical to having accumulated on fewer hosts from the
start, for every mergeable reduction. Multi-host saves are simulated by
writing each shard from its own metric instance with explicit
``shard_index``/``world_size``.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import AUROC, Accuracy, MeanMetric
from metrics_tpu.checkpoint import (
    CheckpointMismatchError,
    assign_shards,
    merge_shards,
    restore_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)
from metrics_tpu.core.metric import Metric

N = 8  # hosts that wrote the checkpoint


def _host_batch(i, n=16):
    rng = np.random.default_rng(1000 + i)
    return (
        jnp.asarray(rng.uniform(0, 1, (n,)).astype(np.float32)),
        jnp.asarray(rng.integers(0, 2, (n,)).astype(np.int32)),
    )


def _save_world(make, root, world=N, updates_for=lambda i: 1):
    """One metric instance per simulated host; every shard into one step."""
    metrics = []
    for i in range(world):
        m = make()
        for u in range(updates_for(i)):
            m.update(*_host_batch(i * 100 + u))
        metrics.append(m)
        save_checkpoint(m, root, step=0, shard_index=i, world_size=world)
    return metrics


def _reference(make, world=N, updates_for=lambda i: 1):
    """The 'always ran on one host' ground truth: same batches, one metric."""
    ref = make()
    for i in range(world):
        for u in range(updates_for(i)):
            ref.update(*_host_batch(i * 100 + u))
    return ref


def test_assign_shards_round_robin():
    assert assign_shards(8, 0, 4) == (0, 4)
    assert assign_shards(8, 3, 4) == (3, 7)
    assert assign_shards(8, 0, 1) == tuple(range(8))
    assert assign_shards(2, 5, 8) == ()  # more hosts than shards
    with pytest.raises(Exception):
        assign_shards(8, 4, 4)


@pytest.mark.parametrize("m_hosts", [1, 4])
def test_accuracy_folds_bitwise(tmp_path, m_hosts):
    _save_world(Accuracy, str(tmp_path))
    ref = _reference(Accuracy)

    # fold every host's restored state into one ground-truth comparison
    total_state, total_count = None, 0
    carrier = Accuracy()
    for host in range(m_hosts):
        m = Accuracy()
        info = restore_checkpoint(m, str(tmp_path), host_index=host, host_count=m_hosts)
        assert info.shards_loaded == assign_shards(N, host, m_hosts)
        if total_state is None:
            total_state, total_count = m.get_state(), m._update_count
        else:
            total_state = carrier.merge_states(total_state, m.get_state(), (total_count, m._update_count))
            total_count += m._update_count
    carrier.set_state(total_state)
    carrier.mode = ref.mode
    carrier._update_count = total_count
    np.testing.assert_array_equal(np.asarray(ref.compute()), np.asarray(carrier.compute()))
    assert total_count == ref._update_count


def test_catbuffer_folds_bitwise(tmp_path):
    make = lambda: AUROC(buffer_capacity=512)
    _save_world(make, str(tmp_path))
    ref = _reference(make)

    m = make()
    restore_checkpoint(m, str(tmp_path), host_index=0, host_count=1)
    np.testing.assert_array_equal(np.asarray(ref.compute()), np.asarray(m.compute()))


def test_mean_fold_recomputed_from_counts(tmp_path):
    # uneven update counts per shard: mean must be count-weighted, not averaged
    make = MeanMetric
    updates = lambda i: i + 1
    metrics = []
    for i in range(4):
        m = make()
        for u in range(updates(i)):
            m.update(jnp.asarray(float(10 * i + u)))
        metrics.append(m)
        save_checkpoint(m, str(tmp_path), step=0, shard_index=i, world_size=4)
    ref = make()
    for i in range(4):
        for u in range(updates(i)):
            ref.update(jnp.asarray(float(10 * i + u)))

    folded = make()
    restore_checkpoint(folded, str(tmp_path), host_index=0, host_count=1)
    np.testing.assert_allclose(np.asarray(folded.compute()), np.asarray(ref.compute()), rtol=1e-6)


def test_more_hosts_than_shards_get_defaults(tmp_path):
    _save_world(Accuracy, str(tmp_path), world=2)
    m = Accuracy()
    info = restore_checkpoint(m, str(tmp_path), host_index=5, host_count=8)
    assert info.shards_loaded == ()
    assert m._update_count == 0
    for val in m.get_state().values():
        np.testing.assert_array_equal(np.asarray(val), 0)


def test_preemption_cycle_save_kill_restore_continue(tmp_path):
    """The headline flow: train on 8 hosts, snapshot, lose the job, resume on
    1 host, keep training — identical to never having been preempted."""
    metrics = _save_world(Accuracy, str(tmp_path))
    ref = _reference(Accuracy)
    del metrics  # the 'kill'

    resumed = Accuracy()
    restore_checkpoint(resumed, str(tmp_path), host_index=0, host_count=1)
    extra = _host_batch(999)
    resumed.update(*extra)
    ref.update(*extra)
    np.testing.assert_array_equal(np.asarray(ref.compute()), np.asarray(resumed.compute()))
    assert resumed._update_count == ref._update_count


def test_offline_merge_matches_live_fold(tmp_path):
    _save_world(Accuracy, str(tmp_path / "in"))
    ref = _reference(Accuracy)
    merge_shards(str(tmp_path / "in"), str(tmp_path / "out"))
    assert verify_checkpoint(str(tmp_path / "out")).ok

    m = Accuracy()
    restore_checkpoint(m, str(tmp_path / "out"), host_index=0, host_count=1)
    np.testing.assert_array_equal(np.asarray(ref.compute()), np.asarray(m.compute()))
    assert m._update_count == ref._update_count


# ------------------------------------------------------ unmergeable ----------
class _CallableReduce(Metric):
    full_state_update = False

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("acc", default=jnp.asarray(0.0), dist_reduce_fx=lambda stacked: jnp.sum(stacked, axis=0))

    def update(self, x):
        self.acc = self.acc + jnp.sum(x)

    def compute(self):
        return self.acc


def test_callable_reduction_refuses_fold_but_allows_same_world(tmp_path):
    for i in range(2):
        m = _CallableReduce()
        m.update(jnp.asarray([float(i + 1)]))
        save_checkpoint(m, str(tmp_path), step=0, shard_index=i, world_size=2)

    # N == M: each host takes its own shard untouched — fine
    m = _CallableReduce()
    restore_checkpoint(m, str(tmp_path), host_index=1, host_count=2)
    np.testing.assert_allclose(np.asarray(m.compute()), 2.0)

    # N != M would have to fold with unknowable semantics — refused
    with pytest.raises(CheckpointMismatchError, match="folded|reduction"):
        restore_checkpoint(_CallableReduce(), str(tmp_path), host_index=0, host_count=1)
