"""Reshard-on-restore: an N-host checkpoint folds onto M hosts.

Host i of M claims shards {i, i+M, i+2M, ...} and folds them with each
leaf's recorded reduction via the metric's own ``merge_states`` — so a folded
restore is bitwise-identical to having accumulated on fewer hosts from the
start, for every mergeable reduction. Multi-host saves are simulated by
writing each shard from its own metric instance with explicit
``shard_index``/``world_size``.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import AUROC, Accuracy, MeanMetric
from metrics_tpu.checkpoint import (
    CheckpointMismatchError,
    assign_shards,
    merge_shards,
    restore_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)
from metrics_tpu.core.metric import Metric

N = 8  # hosts that wrote the checkpoint


def _host_batch(i, n=16):
    rng = np.random.default_rng(1000 + i)
    return (
        jnp.asarray(rng.uniform(0, 1, (n,)).astype(np.float32)),
        jnp.asarray(rng.integers(0, 2, (n,)).astype(np.int32)),
    )


def _save_world(make, root, world=N, updates_for=lambda i: 1):
    """One metric instance per simulated host; every shard into one step."""
    metrics = []
    for i in range(world):
        m = make()
        for u in range(updates_for(i)):
            m.update(*_host_batch(i * 100 + u))
        metrics.append(m)
        save_checkpoint(m, root, step=0, shard_index=i, world_size=world)
    return metrics


def _reference(make, world=N, updates_for=lambda i: 1):
    """The 'always ran on one host' ground truth: same batches, one metric."""
    ref = make()
    for i in range(world):
        for u in range(updates_for(i)):
            ref.update(*_host_batch(i * 100 + u))
    return ref


def test_assign_shards_round_robin():
    assert assign_shards(8, 0, 4) == (0, 4)
    assert assign_shards(8, 3, 4) == (3, 7)
    assert assign_shards(8, 0, 1) == tuple(range(8))
    assert assign_shards(2, 5, 8) == ()  # more hosts than shards
    with pytest.raises(Exception):
        assign_shards(8, 4, 4)


@pytest.mark.parametrize("m_hosts", [1, 4])
def test_accuracy_folds_bitwise(tmp_path, m_hosts):
    _save_world(Accuracy, str(tmp_path))
    ref = _reference(Accuracy)

    # fold every host's restored state into one ground-truth comparison
    total_state, total_count = None, 0
    carrier = Accuracy()
    for host in range(m_hosts):
        m = Accuracy()
        info = restore_checkpoint(m, str(tmp_path), host_index=host, host_count=m_hosts)
        assert info.shards_loaded == assign_shards(N, host, m_hosts)
        if total_state is None:
            total_state, total_count = m.get_state(), m._update_count
        else:
            total_state = carrier.merge_states(total_state, m.get_state(), (total_count, m._update_count))
            total_count += m._update_count
    carrier.set_state(total_state)
    carrier.mode = ref.mode
    carrier._update_count = total_count
    np.testing.assert_array_equal(np.asarray(ref.compute()), np.asarray(carrier.compute()))
    assert total_count == ref._update_count


def test_catbuffer_folds_bitwise(tmp_path):
    make = lambda: AUROC(buffer_capacity=512)
    _save_world(make, str(tmp_path))
    ref = _reference(make)

    m = make()
    restore_checkpoint(m, str(tmp_path), host_index=0, host_count=1)
    np.testing.assert_array_equal(np.asarray(ref.compute()), np.asarray(m.compute()))


def test_mean_fold_recomputed_from_counts(tmp_path):
    # uneven update counts per shard: mean must be count-weighted, not averaged
    make = MeanMetric
    updates = lambda i: i + 1
    metrics = []
    for i in range(4):
        m = make()
        for u in range(updates(i)):
            m.update(jnp.asarray(float(10 * i + u)))
        metrics.append(m)
        save_checkpoint(m, str(tmp_path), step=0, shard_index=i, world_size=4)
    ref = make()
    for i in range(4):
        for u in range(updates(i)):
            ref.update(jnp.asarray(float(10 * i + u)))

    folded = make()
    restore_checkpoint(folded, str(tmp_path), host_index=0, host_count=1)
    np.testing.assert_allclose(np.asarray(folded.compute()), np.asarray(ref.compute()), rtol=1e-6)


def test_more_hosts_than_shards_get_defaults(tmp_path):
    _save_world(Accuracy, str(tmp_path), world=2)
    m = Accuracy()
    info = restore_checkpoint(m, str(tmp_path), host_index=5, host_count=8)
    assert info.shards_loaded == ()
    assert m._update_count == 0
    for val in m.get_state().values():
        np.testing.assert_array_equal(np.asarray(val), 0)


def test_preemption_cycle_save_kill_restore_continue(tmp_path):
    """The headline flow: train on 8 hosts, snapshot, lose the job, resume on
    1 host, keep training — identical to never having been preempted."""
    metrics = _save_world(Accuracy, str(tmp_path))
    ref = _reference(Accuracy)
    del metrics  # the 'kill'

    resumed = Accuracy()
    restore_checkpoint(resumed, str(tmp_path), host_index=0, host_count=1)
    extra = _host_batch(999)
    resumed.update(*extra)
    ref.update(*extra)
    np.testing.assert_array_equal(np.asarray(ref.compute()), np.asarray(resumed.compute()))
    assert resumed._update_count == ref._update_count


def test_offline_merge_matches_live_fold(tmp_path):
    _save_world(Accuracy, str(tmp_path / "in"))
    ref = _reference(Accuracy)
    merge_shards(str(tmp_path / "in"), str(tmp_path / "out"))
    assert verify_checkpoint(str(tmp_path / "out")).ok

    m = Accuracy()
    restore_checkpoint(m, str(tmp_path / "out"), host_index=0, host_count=1)
    np.testing.assert_array_equal(np.asarray(ref.compute()), np.asarray(m.compute()))
    assert m._update_count == ref._update_count


# ------------------------------------------------------ unmergeable ----------
class _CallableReduce(Metric):
    full_state_update = False

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.add_state("acc", default=jnp.asarray(0.0), dist_reduce_fx=lambda stacked: jnp.sum(stacked, axis=0))

    def update(self, x):
        self.acc = self.acc + jnp.sum(x)

    def compute(self):
        return self.acc


def test_callable_reduction_refuses_fold_but_allows_same_world(tmp_path):
    for i in range(2):
        m = _CallableReduce()
        m.update(jnp.asarray([float(i + 1)]))
        save_checkpoint(m, str(tmp_path), step=0, shard_index=i, world_size=2)

    # N == M: each host takes its own shard untouched — fine
    m = _CallableReduce()
    restore_checkpoint(m, str(tmp_path), host_index=1, host_count=2)
    np.testing.assert_allclose(np.asarray(m.compute()), 2.0)

    # N != M would have to fold with unknowable semantics — refused
    with pytest.raises(CheckpointMismatchError, match="folded|reduction"):
        restore_checkpoint(_CallableReduce(), str(tmp_path), host_index=0, host_count=1)


# ------------------------------------------------------ reshard planner ------
def test_reshard_plan_structure_and_peaks(tmp_path):
    """The plan is compiled from manifest metadata alone: a load/fold/free
    triple per assigned shard, with the streaming peak bounded by folded
    state + one transfer block — strictly below gather-everything for N>1."""
    from metrics_tpu.checkpoint import build_reshard_plan
    from metrics_tpu.checkpoint import io as _io
    from metrics_tpu import ConfusionMatrix

    make = lambda: ConfusionMatrix(num_classes=64)
    for i in range(N):
        m = make()
        rng = np.random.default_rng(i)
        m.update(
            jnp.asarray(rng.integers(0, 64, (128,))), jnp.asarray(rng.integers(0, 64, (128,)))
        )
        save_checkpoint(m, str(tmp_path), step=0, shard_index=i, world_size=N)

    manifest = _io.read_manifest(str(tmp_path), 0)
    plan = build_reshard_plan(manifest, assign_shards(N, 0, 1))
    assert plan.world_size == N and plan.shards == tuple(range(N))
    assert [s["op"] for s in plan.steps] == ["load", "fold", "free"] * N
    # dense sum state: the fold never grows past one (64, 64) int32 copy
    state_bytes = 64 * 64 * 4
    assert all(s["bytes"] == state_bytes for s in plan.steps if s["op"] == "fold")
    largest_payload = max(int(s["bytes"]) for s in manifest["shards"])
    assert plan.plan_peak_bytes <= state_bytes + largest_payload
    assert plan.plan_peak_bytes < plan.gather_peak_bytes
    # modeled baseline really is the sum of every assigned payload
    assert plan.gather_peak_bytes == sum(int(s["bytes"]) for s in manifest["shards"]) + state_bytes


def test_streaming_restore_n_to_m_peak_below_gather(tmp_path):
    """N=8 shards folded onto M=3 hosts through the planner: results bitwise
    vs the reference fold, and the measured resident peak stays strictly
    below the gather-everything model on every host that folds >1 shard."""
    from metrics_tpu import ConfusionMatrix

    make = lambda: ConfusionMatrix(num_classes=64)
    ref = make()
    for i in range(N):
        m = make()
        rng = np.random.default_rng(i)
        batch = (
            jnp.asarray(rng.integers(0, 64, (128,))),
            jnp.asarray(rng.integers(0, 64, (128,))),
        )
        m.update(*batch)
        ref.update(*batch)
        save_checkpoint(m, str(tmp_path), step=0, shard_index=i, world_size=N)

    M = 3
    folded_total = np.zeros((64, 64), np.int64)
    for host in range(M):
        m = make()
        info = restore_checkpoint(m, str(tmp_path), host_index=host, host_count=M)
        assert info.reshard_plan is not None
        assert info.reshard_plan.shards == assign_shards(N, host, M)
        assert info.plan_peak_bytes == info.reshard_plan.plan_peak_bytes
        assert info.gather_peak_bytes == info.reshard_plan.gather_peak_bytes
        if len(info.shards_loaded) > 1:
            assert info.measured_peak_bytes < info.gather_peak_bytes
            assert info.plan_peak_bytes < info.gather_peak_bytes
        assert info.measured_peak_bytes > 0
        folded_total += np.asarray(m.confmat, dtype=np.int64)
    np.testing.assert_array_equal(np.asarray(ref.confmat, dtype=np.int64), folded_total)


def test_single_shard_plan_degenerates(tmp_path):
    """N == M: one shard per host — streaming and gathering coincide."""
    _save_world(Accuracy, str(tmp_path), world=2)
    m = Accuracy()
    info = restore_checkpoint(m, str(tmp_path), host_index=1, host_count=2)
    plan = info.reshard_plan
    assert plan is not None and plan.shards == (1,)
    assert [s["op"] for s in plan.steps] == ["load", "fold", "free"]
    assert plan.plan_peak_bytes == plan.gather_peak_bytes


def test_catbuffer_plan_accumulates_concat_bytes(tmp_path):
    """Concatenating leaves grow the fold: the modeled fold bytes must be
    non-decreasing across shards and the final figure covers every prefix."""
    from metrics_tpu.checkpoint import build_reshard_plan
    from metrics_tpu.checkpoint import io as _io

    make = lambda: AUROC(buffer_capacity=512)
    _save_world(make, str(tmp_path), world=4)
    manifest = _io.read_manifest(str(tmp_path), 0)
    plan = build_reshard_plan(manifest, assign_shards(4, 0, 1))
    fold_bytes = [s["bytes"] for s in plan.steps if s["op"] == "fold"]
    assert fold_bytes == sorted(fold_bytes)
    assert fold_bytes[-1] > fold_bytes[0]
