"""Base-runtime lifecycle tests.

Reference parity: tests/bases/test_metric.py — state registry, reset, forward
semantics (full vs reduced), compute caching, pickling, state_dict round-trip,
plus the pure protocol (init/update/compute/merge) that the reference lacks.
"""
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Metric
from metrics_tpu.utils.exceptions import MetricsUserError
from tests.helpers.testers import DummyListMetric, DummyMetric, DummyMetricDiff, DummyMetricSum


def test_add_state_validation():
    m = DummyMetric()
    with pytest.raises(ValueError, match="state variable"):
        m.add_state("bad", 42, "sum")
    with pytest.raises(ValueError, match="dist_reduce_fx"):
        m.add_state("bad", jnp.asarray(0.0), "nope")


def test_inherit():
    DummyMetric()


def test_add_state_sets_attributes():
    m = DummyMetric()
    m.add_state("a", jnp.asarray(0.0), "sum")
    m.add_state("b", [], "cat")
    assert float(m.a) == 0.0
    assert m.b == []
    assert m._reductions["a"] == "sum"


def test_reset():
    m = DummyMetricSum()
    m.update(jnp.asarray(2.0))
    assert float(m.x) == 2.0
    m.reset()
    assert float(m.x) == 0.0
    assert m._update_count == 0

    lm = DummyListMetric()
    lm.update(jnp.asarray(1.0))
    assert len(lm.x) == 1
    lm.reset()
    assert lm.x == []


def test_update_and_compute():
    m = DummyMetricSum()
    m.update(jnp.asarray(1.0))
    m.update(jnp.asarray(2.0))
    assert float(m.compute()) == 3.0
    assert m._update_count == 2


def test_compute_cached_until_update():
    m = DummyMetricSum()
    m.update(jnp.asarray(1.0))
    assert float(m.compute()) == 1.0
    assert m._computed is not None
    m.update(jnp.asarray(1.0))
    assert m._computed is None
    assert float(m.compute()) == 2.0


def test_forward_full_vs_reduced():
    class Full(DummyMetricSum):
        full_state_update = True

    class Reduced(DummyMetricSum):
        full_state_update = False

    for cls in (Full, Reduced):
        m = cls()
        b1 = m(jnp.asarray(1.0))
        assert float(b1) == 1.0  # batch value
        b2 = m(jnp.asarray(2.0))
        assert float(b2) == 2.0
        assert float(m.compute()) == 3.0  # accumulated


def test_forward_reduced_mean_state():
    class MeanState(Metric):
        full_state_update = False

        def __init__(self):
            super().__init__()
            self.add_state("m", jnp.asarray(0.0), dist_reduce_fx="mean")

        def update(self, x):
            self.m = self.m + x  # fresh state per batch in reduced mode

        def compute(self):
            return self.m

    m = MeanState()
    m(jnp.asarray(2.0))
    m(jnp.asarray(4.0))
    assert float(m.compute()) == pytest.approx(3.0)


def test_forward_while_synced_raises():
    m = DummyMetricSum()
    m.update(jnp.asarray(1.0))
    m._is_synced = True
    with pytest.raises(MetricsUserError, match="shouldn't be synced"):
        m(jnp.asarray(1.0))


def test_sync_unsync_state_machine():
    m = DummyMetricSum()
    m.update(jnp.asarray(1.0))
    # single process: sync is a no-op but guards still hold
    m.sync(should_sync=True, distributed_available=lambda: False)
    assert not m._is_synced
    with pytest.raises(MetricsUserError, match="un-synced"):
        m.unsync()
    # double sync raises
    m._is_synced = True
    with pytest.raises(MetricsUserError, match="already been synced"):
        m.sync()
    m._is_synced = False


def test_pickle():
    m = DummyMetricSum()
    m.update(jnp.asarray(3.0))
    m2 = pickle.loads(pickle.dumps(m))
    assert float(m2.compute()) == 3.0


def test_state_dict_roundtrip():
    m = DummyMetricSum()
    m.add_state("persisted", jnp.asarray(5.0), "sum", persistent=True)
    sd = m.state_dict()
    assert "persisted" in sd and "x" not in sd
    m2 = DummyMetricSum()
    m2.add_state("persisted", jnp.asarray(0.0), "sum", persistent=True)
    m2.load_state_dict(sd)
    assert float(m2.persisted) == 5.0


def test_protected_class_constants():
    m = DummyMetric()
    with pytest.raises(RuntimeError, match="Can't change const"):
        m.is_differentiable = True
    with pytest.raises(RuntimeError, match="Can't change const"):
        m.higher_is_better = True
    with pytest.raises(RuntimeError, match="Can't change const"):
        m.full_state_update = False


def test_hash():
    m1, m2 = DummyMetric(), DummyMetric()
    assert hash(m1) != hash(m2)
    assert {m1, m2}  # usable in sets


def test_metric_state_property():
    m = DummyMetricSum()
    m.update(jnp.asarray(2.0))
    assert set(m.metric_state) == {"x"}
    assert float(m.metric_state["x"]) == 2.0


# --------------------------------------------------------------------------- #
# pure protocol
# --------------------------------------------------------------------------- #
def test_pure_protocol_matches_stateful():
    m = DummyMetricSum()
    state = m.init_state()
    state = m.update_state(state, jnp.asarray(1.0))
    state = m.update_state(state, jnp.asarray(2.0))
    assert float(m.compute_state(state)) == 3.0
    # facade untouched
    assert float(m.x) == 0.0


def test_pure_update_is_jittable():
    m = DummyMetricSum()
    f = jax.jit(lambda s, x: m.update_state(s, x))
    state = m.init_state()
    state = f(state, jnp.asarray(1.0))
    state = f(state, jnp.asarray(2.0))
    assert float(m.compute_state(state)) == 3.0


def test_merge_states_reductions():
    class Multi(Metric):
        def __init__(self):
            super().__init__()
            self.add_state("s", jnp.asarray(1.0), "sum")
            self.add_state("mx", jnp.asarray(1.0), "max")
            self.add_state("mn", jnp.asarray(1.0), "min")
            self.add_state("c", [], "cat")

        def update(self):
            pass

        def compute(self):
            return self.s

    m = Multi()
    a = {"s": jnp.asarray(1.0), "mx": jnp.asarray(1.0), "mn": jnp.asarray(1.0), "c": [jnp.asarray([1.0])]}
    b = {"s": jnp.asarray(2.0), "mx": jnp.asarray(3.0), "mn": jnp.asarray(0.5), "c": [jnp.asarray([2.0])]}
    merged = m.merge_states(a, b)
    assert float(merged["s"]) == 3.0
    assert float(merged["mx"]) == 3.0
    assert float(merged["mn"]) == 0.5
    assert len(merged["c"]) == 2


def test_compute_without_update_warns():
    m = DummyMetricSum()
    with pytest.warns(UserWarning, match="before the ``update`` method"):
        m.compute()


def test_enum_from_str_with_spaces():
    from metrics_tpu.utils.enums import DataType

    assert DataType.from_str("multi-dim multi-class") is DataType.MULTIDIM_MULTICLASS
    assert DataType.from_str("binary") is DataType.BINARY
    assert DataType.from_str("bogus") is None


def test_astype_survives_reset():
    from tests.helpers.testers import DummyMetricSum

    m = DummyMetricSum().astype(jnp.bfloat16)
    m.update(jnp.asarray(1.0, jnp.bfloat16))
    m.reset()
    assert m.x.dtype == jnp.bfloat16
