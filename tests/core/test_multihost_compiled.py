"""COMPILED cross-process sync: 2 processes x 4 devices, one global mesh.

Complements test_multihost.py (eager ragged gather): this is the DCN story
SURVEY.md §5.8 promises — the jitted update -> sync_states(psum) ->
compute_state chain running under shard_map over a GLOBAL mesh that spans
jax.distributed processes, so the collective crosses process boundaries
instead of staying inside one PJRT client. Each rank feeds only its local
shards (jax.make_array_from_process_local_data) and every device must end up
with the value a single process computes from ALL the data.
"""
import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = textwrap.dedent(
    """
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    proc_id, port = int(sys.argv[1]), sys.argv[2]
    jax.distributed.initialize(f"localhost:{port}", num_processes=2, process_id=proc_id)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from metrics_tpu import Accuracy, MeanSquaredError

    WORLD = 8  # 2 processes x 4 local devices
    assert len(jax.devices()) == WORLD, jax.devices()
    assert len(jax.local_devices()) == 4
    mesh = Mesh(np.asarray(jax.devices()), ("data",))

    # deterministic global batch; every rank derives the same full arrays and
    # contributes only its local quarter through process-local data
    rng = np.random.default_rng(0)
    B = 16
    preds_all = rng.dirichlet(np.ones(4), size=(WORLD, B)).astype(np.float32)
    labels_all = rng.integers(0, 4, size=(WORLD, B)).astype(np.int32)

    sharding = NamedSharding(mesh, P("data"))
    lo, hi = proc_id * 4, (proc_id + 1) * 4
    preds = jax.make_array_from_process_local_data(sharding, preds_all[lo:hi], preds_all.shape)
    labels = jax.make_array_from_process_local_data(sharding, labels_all[lo:hi], labels_all.shape)

    acc = Accuracy(num_classes=4)
    mse = MeanSquaredError()

    def program(p, t):
        st = acc.update_state(acc.get_state(), p.reshape(-1, 4), t.reshape(-1))
        st = acc.sync_states(st, "data")  # psum over BOTH processes
        st2 = mse.update_state(mse.get_state(), p[..., 0].reshape(-1), t.reshape(-1).astype(jnp.float32) / 4)
        st2 = mse.sync_states(st2, "data")
        out = jnp.stack([acc.compute_state(st), mse.compute_state(st2)])
        return jnp.expand_dims(out, 0)

    fn = jax.jit(jax.shard_map(
        program, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data"), check_vma=False,
    ))
    out = fn(preds, labels)

    # oracle from ALL data, computed locally on this process
    want_acc = (preds_all.reshape(-1, 4).argmax(-1) == labels_all.reshape(-1)).mean()
    want_mse = ((preds_all[..., 0].reshape(-1) - labels_all.reshape(-1) / 4.0) ** 2).mean()

    # each process checks its LOCAL rows of the global output
    local_rows = np.stack([np.asarray(s.data).reshape(2) for s in out.addressable_shards])
    np.testing.assert_allclose(local_rows[:, 0], want_acc, atol=1e-6)
    np.testing.assert_allclose(local_rows[:, 1], want_mse, atol=1e-5)
    print("COMPILED_SYNC_OK", proc_id)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_two_ranks(tmp_path, child_src: str, marker: str) -> None:
    """Launch the child program as 2 jax.distributed processes and assert
    each prints its success marker."""
    child = tmp_path / "child.py"
    child.write_text(child_src)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["XLA_FLAGS"] = ""  # the child sets its own device-count flag
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(child), str(rank), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=REPO,
        )
        for rank in range(2)
    ]
    try:
        outs = [p.communicate(timeout=300)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert f"{marker} {rank}" in out


def test_compiled_sync_spans_processes(tmp_path, multiprocess_backend):
    _run_two_ranks(tmp_path, _CHILD, "COMPILED_SYNC_OK")


_CHILD_GATHER = textwrap.dedent(
    """
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    proc_id, port = int(sys.argv[1]), sys.argv[2]
    jax.distributed.initialize(f"localhost:{port}", num_processes=2, process_id=proc_id)
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from sklearn.metrics import roc_auc_score

    from metrics_tpu import AUROC

    WORLD, B = 8, 16
    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    rng = np.random.default_rng(3)
    scores_all = rng.random((WORLD, B)).astype(np.float32)
    labels_all = rng.integers(0, 2, (WORLD, B)).astype(np.int32)
    labels_all[:, 0], labels_all[:, 1] = 0, 1

    sharding = NamedSharding(mesh, P("data"))
    lo, hi = proc_id * 4, (proc_id + 1) * 4
    scores = jax.make_array_from_process_local_data(sharding, scores_all[lo:hi], scores_all.shape)
    labels = jax.make_array_from_process_local_data(sharding, labels_all[lo:hi], labels_all.shape)

    # buffered cat states: the gather is a lax.all_gather crossing BOTH
    # processes; the synced buffer is replicated, compute happens eagerly
    # on each process afterwards (exact curves are eager-only by design)
    m = AUROC(pos_label=1, buffer_capacity=WORLD * B)

    def program(s, t):
        # pure path: sync_states takes the axis explicitly, no ambient context
        st = m.update_state(m.init_state(), s.reshape(-1), t.reshape(-1))
        return m.sync_states(st, "data")

    fn = jax.jit(jax.shard_map(
        program, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P(), check_vma=False,
    ))
    synced = fn(scores, labels)
    got = float(m.compute_state(jax.device_get(synced)))
    want = roc_auc_score(labels_all.reshape(-1), scores_all.reshape(-1))
    np.testing.assert_allclose(got, want, atol=1e-6)
    print("GATHER_SYNC_OK", proc_id)
    """
)


def test_compiled_cat_gather_spans_processes(tmp_path, multiprocess_backend):
    """Buffered cat-state all_gather across process boundaries: the synced
    CatBuffer must hold every process's samples and compute the global AUROC."""
    _run_two_ranks(tmp_path, _CHILD_GATHER, "GATHER_SYNC_OK")
