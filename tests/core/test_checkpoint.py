"""metrics_tpu.checkpoint: snapshot/restore roundtrips, async saves, engine
interplay (fused-streak realization, signature-memo invalidation), aux config,
and the CLI."""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu
from metrics_tpu import (
    AUROC,
    Accuracy,
    CatMetric,
    F1Score,
    MeanMetric,
    MetricCollection,
    Precision,
    Recall,
    ROC,
)
from metrics_tpu.checkpoint import (
    CheckpointMismatchError,
    CheckpointNotFoundError,
    restore_checkpoint,
    save_checkpoint,
    verify_checkpoint,
)
from metrics_tpu.checkpoint import io as ckpt_io
from metrics_tpu.checkpoint.__main__ import main as ckpt_cli
from metrics_tpu.utils.exceptions import MetricsUserError

_RNG = np.random.default_rng(0)


def _batch(n=32, seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.uniform(0, 1, (n,)).astype(np.float32)),
        jnp.asarray(rng.integers(0, 2, (n,)).astype(np.int32)),
    )


def _tree_equal(a, b):
    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    assert ta == tb
    for xa, xb in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


# ------------------------------------------------------------- roundtrips ----
def test_metric_roundtrip_with_aux(tmp_path):
    m = Accuracy()
    m.update(*_batch(seed=1))
    m.update(*_batch(seed=2))
    ref = m.compute()

    handle = save_checkpoint(m, str(tmp_path))
    assert handle.committed and handle.done

    fresh = Accuracy()
    info = restore_checkpoint(fresh, str(tmp_path), host_index=0, host_count=1)
    assert info.world_size == 1 and info.shards_loaded == (0,)
    # mode is update-determined python config; without the aux channel the
    # restored metric could not compute before seeing data (DataType is a
    # str-mixin enum, so the JSON-roundtripped plain string compares equal)
    assert fresh.mode == m.mode
    assert fresh._update_count == m._update_count
    _tree_equal(ref, fresh.compute())


def test_collection_roundtrip(tmp_path):
    coll = MetricCollection([Accuracy(), F1Score(), Precision(), Recall()])
    for seed in (3, 4):
        coll.update(*_batch(seed=seed))
    ref = coll.compute()

    save_checkpoint(coll, str(tmp_path))
    fresh = MetricCollection([Accuracy(), F1Score(), Precision(), Recall()])
    restore_checkpoint(fresh, str(tmp_path), host_index=0, host_count=1)
    for k in ref:
        _tree_equal(ref[k], fresh.compute()[k])


def test_catbuffer_roundtrip_grows_capacity(tmp_path):
    m = AUROC(buffer_capacity=64)
    m.update(*_batch(seed=5))
    save_checkpoint(m, str(tmp_path))

    # live capacity smaller than the saved prefix: restore re-materializes at
    # the larger of the two
    small = AUROC(buffer_capacity=64)
    restore_checkpoint(small, str(tmp_path), host_index=0, host_count=1)
    _tree_equal(m.compute(), small.compute())


def test_list_state_roundtrip(tmp_path):
    m = CatMetric()  # unbounded list state
    m.update(jnp.asarray([1.0, 2.0]))
    m.update(jnp.asarray([3.0]))
    save_checkpoint(m, str(tmp_path))
    fresh = CatMetric()
    restore_checkpoint(fresh, str(tmp_path), host_index=0, host_count=1)
    np.testing.assert_array_equal(np.asarray(fresh.compute()), [1.0, 2.0, 3.0])


def test_multiple_steps_latest_wins(tmp_path):
    m = MeanMetric()
    m.update(jnp.asarray(1.0))
    save_checkpoint(m, str(tmp_path))
    m.update(jnp.asarray(5.0))
    save_checkpoint(m, str(tmp_path))
    assert len(ckpt_io.available_steps(str(tmp_path))) == 2

    fresh = MeanMetric()
    restore_checkpoint(fresh, str(tmp_path), host_index=0, host_count=1)  # latest
    np.testing.assert_allclose(np.asarray(fresh.compute()), 3.0)
    fresh2 = MeanMetric()
    restore_checkpoint(fresh2, str(tmp_path), step=ckpt_io.available_steps(str(tmp_path))[0], host_index=0, host_count=1)
    np.testing.assert_allclose(np.asarray(fresh2.compute()), 1.0)


def test_restore_missing_raises(tmp_path):
    with pytest.raises(CheckpointNotFoundError):
        restore_checkpoint(Accuracy(), str(tmp_path / "nope"), host_index=0, host_count=1)


# ------------------------------------------------------------- async save ----
def test_async_save_commits(tmp_path):
    m = Accuracy()
    m.update(*_batch(seed=6))
    ref = m.compute()
    handle = save_checkpoint(m, str(tmp_path), blocking=False)
    handle.wait()
    assert handle.committed
    # donation safety: the payload was copied to host before update continued
    m.update(*_batch(seed=7))
    fresh = Accuracy()
    restore_checkpoint(fresh, str(tmp_path), host_index=0, host_count=1)
    _tree_equal(ref, fresh.compute())


def test_async_save_error_surfaces_on_wait(tmp_path):
    target = tmp_path / "blocked"
    target.write_text("a file where the checkpoint root should go")
    m = Accuracy()
    m.update(*_batch(seed=8))
    handle = save_checkpoint(m, str(target), blocking=False)
    with pytest.raises(Exception):
        handle.wait()


def test_overlap_copy_save_commits_and_times_both_phases(tmp_path):
    """ISSUE-15 overlap model: overlap_copy=True enqueues the device→host
    copy and returns; the save thread drains it. The handle times both sides
    (copy_enqueue_s on the caller, host_copy_s on the thread) and the
    restored bytes match a snapshot taken before further updates."""
    m = Accuracy()
    m.update(*_batch(seed=20))
    ref = m.compute()
    handle = save_checkpoint(m, str(tmp_path), blocking=False, overlap_copy=True)
    # the caller-side streak continues while the copy drains on the thread
    m.update(*_batch(seed=21))
    handle.wait()
    assert handle.committed
    assert "copy_enqueue_s" in handle.timings
    assert "host_copy_s" in handle.timings
    assert handle.timings["copy_enqueue_s"] >= 0.0
    assert handle.timings["host_copy_s"] >= 0.0
    fresh = Accuracy()
    restore_checkpoint(fresh, str(tmp_path), host_index=0, host_count=1)
    _tree_equal(ref, fresh.compute())


def test_overlap_copy_requires_async(tmp_path):
    m = Accuracy()
    m.update(*_batch(seed=22))
    with pytest.raises(ValueError, match="overlap_copy"):
        save_checkpoint(m, str(tmp_path), blocking=True, overlap_copy=True)


# -------------------------------------------------- engine/streak interop ----
def test_save_during_fused_streak_realizes_members(tmp_path):
    coll = MetricCollection([Precision(), Recall()])
    metrics_tpu.set_fused_update(True)
    try:
        coll.update(*_batch(seed=9))
        # snapshot mid-streak: describe() realizes detached member states first
        save_checkpoint(coll, str(tmp_path))
        ref = coll.compute()
    finally:
        metrics_tpu.set_fused_update(None)
    fresh = MetricCollection([Precision(), Recall()])
    restore_checkpoint(fresh, str(tmp_path), host_index=0, host_count=1)
    for k in ref:
        _tree_equal(ref[k], fresh.compute()[k])


def test_detached_member_read_raises_actionable_error():
    coll = MetricCollection([Precision(), Recall()])
    metrics_tpu.set_fused_update(True)
    try:
        # the streak (and member detachment) starts on the second fused update
        coll.update(*_batch(seed=10))
        coll.update(*_batch(seed=10))
        detached = [
            m for m in coll._metrics.values() if getattr(m, "_states_detached", False)
        ]
        if not detached:
            pytest.skip("no compute-group followers detached in this configuration")
        with pytest.raises(MetricsUserError, match="detached"):
            _ = detached[0].tp
        # realization through the collection clears the poison
        coll._realias_members()
        _ = detached[0].tp
    finally:
        metrics_tpu.set_fused_update(None)


def test_restore_invalidates_compute_memo(tmp_path):
    m = Accuracy()
    m.update(*_batch(seed=11))
    save_checkpoint(m, str(tmp_path))
    m.update(*_batch(seed=12))
    stale = m.compute()  # memoized for the 2-update state
    restore_checkpoint(m, str(tmp_path), host_index=0, host_count=1)
    restored = m.compute()
    assert m._update_count == 1
    # 1-update and 2-update accuracies differ for these batches
    assert not np.allclose(np.asarray(stale), np.asarray(restored))


def test_load_state_dict_clears_compute_memo():
    m = MeanMetric()
    m.persistent(True)
    m.update(jnp.asarray(2.0))
    sd = m.state_dict()
    m.update(jnp.asarray(10.0))
    assert float(m.compute()) == 6.0  # memoized now
    m.load_state_dict(sd)
    assert float(m.compute()) == 2.0  # stale memo must not survive the load


# ---------------------------------------------------------------- refusal ----
def test_mismatch_refused_with_diff(tmp_path):
    m = AUROC(buffer_capacity=64)
    m.update(*_batch(seed=13))
    save_checkpoint(m, str(tmp_path))
    with pytest.raises(CheckpointMismatchError, match="class"):
        restore_checkpoint(Accuracy(), str(tmp_path), host_index=0, host_count=1)


def test_aux_num_classes_roundtrip(tmp_path):
    # binary updates make ROC *infer* num_classes/pos_label; the aux channel
    # must carry the inference so the restored metric can compute
    m = ROC(buffer_capacity=64)
    m.update(*_batch(seed=14))
    assert m.num_classes is not None
    save_checkpoint(m, str(tmp_path))
    fresh = ROC(buffer_capacity=64)
    restore_checkpoint(fresh, str(tmp_path), host_index=0, host_count=1)
    assert fresh.num_classes == m.num_classes
    assert fresh.pos_label == m.pos_label
    _tree_equal(m.compute(), fresh.compute())


# -------------------------------------------------------------------- CLI ----
def test_cli_inspect_verify(tmp_path, capsys):
    m = Accuracy()
    m.update(*_batch(seed=15))
    save_checkpoint(m, str(tmp_path))
    assert ckpt_cli(["inspect", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "Accuracy" in out and "world_size" in out
    assert ckpt_cli(["verify", str(tmp_path)]) == 0
    assert ckpt_cli(["verify", str(tmp_path), "--all"]) == 0


def test_cli_verify_fails_on_corruption(tmp_path, capsys):
    m = Accuracy()
    m.update(*_batch(seed=16))
    save_checkpoint(m, str(tmp_path))
    step = ckpt_io.latest_step(str(tmp_path))
    step_dir = os.path.join(str(tmp_path), ckpt_io.step_dir_name(step))
    npz = [f for f in os.listdir(step_dir) if f.endswith(".npz")][0]
    with open(os.path.join(step_dir, npz), "r+b") as f:
        f.seek(0)
        f.write(b"\x00" * 16)
    assert ckpt_cli(["verify", str(tmp_path)]) != 0
    report = verify_checkpoint(str(tmp_path))
    assert not report.ok and report.issues


def test_cli_merge(tmp_path, capsys):
    m = Accuracy()
    m.update(*_batch(seed=17))
    save_checkpoint(m, str(tmp_path / "in"))
    assert ckpt_cli(["merge", str(tmp_path / "in"), str(tmp_path / "out")]) == 0
    fresh = Accuracy()
    restore_checkpoint(fresh, str(tmp_path / "out"), host_index=0, host_count=1)
    _tree_equal(m.compute(), fresh.compute())
