"""Facade constructor kwargs that had no dedicated coverage.

``compute_on_cpu`` (the reference's GPU-memory relief valve, metric.py:90,
381-391 — here device->host offload of list states), ``sync_on_compute``
(whether ``compute()`` synchronizes automatically, metric.py:96), and
``dist_sync_fn`` (the injection point Lightning uses for its fused gather,
metric.py:104) — the three §5.6/§5.8 config mechanisms of the base class.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import AUROC, Accuracy, CatMetric, MeanMetric
from metrics_tpu.parallel.sync import sync_axes


def test_compute_on_cpu_offloads_list_states_and_computes_correctly():
    metric = CatMetric(compute_on_cpu=True)
    metric.update(jnp.asarray([1.0, 2.0]))
    metric.update(jnp.asarray([3.0]))
    cpu_devices = {d for d in jax.devices("cpu")}
    for chunk in metric.value:
        assert next(iter(chunk.devices())) in cpu_devices
    np.testing.assert_array_equal(np.asarray(metric.compute()), [1.0, 2.0, 3.0])


def test_compute_on_cpu_curve_metric_matches_default():
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.uniform(size=(64,)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 2, size=(64,)).astype(np.int32))
    offloaded, default = AUROC(compute_on_cpu=True), AUROC()
    offloaded.update(preds, target)
    default.update(preds, target)
    assert float(offloaded.compute()) == pytest.approx(float(default.compute()), abs=1e-7)


def test_compute_on_cpu_rejects_non_bool():
    with pytest.raises(ValueError, match="compute_on_cpu"):
        Accuracy(compute_on_cpu="yes")


def test_sync_on_compute_false_skips_automatic_sync():
    calls = []

    def spy_sync_fn(state, reductions, axes):
        calls.append(axes)
        return state

    metric = MeanMetric(sync_on_compute=False, dist_sync_fn=spy_sync_fn)
    metric.update(jnp.asarray([1.0, 3.0]))
    with sync_axes("data"):  # a collective context is active...
        assert float(metric.compute()) == pytest.approx(2.0)
    assert calls == []  # ...but sync_on_compute=False must not sync

    synced = MeanMetric(sync_on_compute=True, dist_sync_fn=spy_sync_fn)
    synced.update(jnp.asarray([1.0, 3.0]))
    with sync_axes("data"):
        synced.compute()
    assert len(calls) == 1  # the default cadence does sync


def test_dist_sync_fn_injection_replaces_builtin_sync():
    """A custom sync callable sees (state, reductions, axes) and its returned
    state is what compute() consumes — the Lightning fused-gather contract."""
    seen = {}

    def doubling_sync(state, reductions, axes):
        seen["reductions"] = dict(reductions)
        seen["axes"] = axes
        return {k: jax.tree.map(lambda x: x * 2, v) if not isinstance(v, list) else v for k, v in state.items()}

    metric = MeanMetric(dist_sync_fn=doubling_sync, process_group="data")
    metric.update(jnp.asarray([1.0, 3.0]))
    # sum-reduced states doubled on both sides: the mean is unchanged,
    # proving compute() ran on the injected function's output
    with sync_axes("data"):
        assert float(metric.compute()) == pytest.approx(2.0)
    assert seen["axes"] == "data"
    assert set(seen["reductions"]) == {"value", "weight"}
    assert metric._is_synced is False  # unsync restored local state after compute


def test_dist_sync_fn_rejects_non_callable():
    with pytest.raises(ValueError, match="dist_sync_fn"):
        Accuracy(dist_sync_fn="not-a-function")
