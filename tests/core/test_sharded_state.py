"""Sharded metric state: SPMD placement with reshard-at-compute sync.

``add_state(..., shard_axis=k)`` declares a state leaf shardable along one
dimension; :meth:`Metric.shard_state` places declared leaves as
``NamedSharding``-sharded global arrays over a mesh. These tests pin the
contract on the 8-device CPU mesh:

* the declaration alone is inert — replicated placement, psum sync, every
  existing path byte-identical;
* after ``shard_state()`` each device holds a 1/width block
  (``addressable_shards``), updates run through the compiled donated engines,
  and ``compute()`` is bitwise-equal to the replicated metric on the same
  data;
* sync routing: sharded leaves spend *zero* psum/all_gather bytes — their
  only collective is the single reshard (tiled all-gather) at compute;
* placement survives ``reset``, ``state_dict`` roundtrips, and checkpoint
  save/restore; ``unshard_state`` returns to replicated;
* fused collection streaks handle mixed sharded/replicated members.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import metrics_tpu
from metrics_tpu import (
    Accuracy,
    BinnedPrecisionRecallCurve,
    CatMetric,
    ConfusionMatrix,
    F1Score,
    MetricCollection,
    Precision,
)
from metrics_tpu.parallel import make_mesh
from metrics_tpu.parallel.sync import count_collectives

WORLD = 8


@pytest.fixture(autouse=True)
def _bucketed_default():
    metrics_tpu.set_bucketed_sync(None)
    yield
    metrics_tpu.set_bucketed_sync(None)


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return make_mesh([WORLD], ["data"], devices[:WORLD])


def _rng():
    return np.random.default_rng(0)


def _leaves_equal(a, b, exact=True):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    if exact:
        return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))
    return all(
        np.allclose(np.asarray(x), np.asarray(y), rtol=1e-6, atol=0)
        for x, y in zip(la, lb)
    )


def _per_device_nbytes(leaf):
    shards = getattr(leaf, "addressable_shards", None)
    return int(shards[0].data.nbytes) if shards else int(leaf.nbytes)


# --------------------------------------------------------------------------- #
# declaration surface
# --------------------------------------------------------------------------- #
class _Declared(metrics_tpu.Metric):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("grid", default=jnp.zeros((4, 6)), dist_reduce_fx="sum", shard_axis=1)

    def update(self, x):
        self.grid = self.grid + x

    def compute(self):
        return self.grid.sum()


def test_add_state_shard_axis_validation():
    class Bad(metrics_tpu.Metric):
        def __init__(self, default, shard_axis, **kw):
            super().__init__(**kw)
            self.add_state("s", default=default, dist_reduce_fx="sum", shard_axis=shard_axis)

        def update(self):
            pass

        def compute(self):
            return self.s

    with pytest.raises(ValueError, match="must be an int"):
        Bad(jnp.zeros((4,)), "0")
    with pytest.raises(ValueError, match="scalar states"):
        Bad(jnp.asarray(0.0), 0)
    with pytest.raises(ValueError, match="out of range"):
        Bad(jnp.zeros((4,)), 2)
    with pytest.raises(ValueError, match="unbounded list states"):
        Bad([], 0)
    # negative axes within rank are accepted (numpy convention)
    assert Bad(jnp.zeros((2, 3)), -1).shard_axes == {"s": -1}


def test_declaration_is_inert():
    """shard_axis alone changes nothing: no active axes, psum routing."""
    m = _Declared()
    assert m.shard_axes == {"grid": 1}
    assert m.active_shard_axes == {}
    with count_collectives() as box:
        jax.make_jaxpr(lambda s: m.sync_states(s, "data"), axis_env=[("data", WORLD)])(
            m.init_state()
        )
    assert box["by_kind"].get("reshard", 0) == 0
    assert box["by_kind"].get("psum", 0) >= 1


@pytest.mark.mesh8
def test_shard_state_requires_known_axis(mesh):
    with pytest.raises(Exception, match="axis"):
        _Declared().shard_state(mesh, axis_name="model")


@pytest.mark.mesh8
def test_shard_state_without_declarations_warns(mesh):
    with pytest.warns(UserWarning, match="shard_axis"):
        Accuracy(num_classes=4, average="micro").shard_state(mesh)


# --------------------------------------------------------------------------- #
# replicated-vs-sharded parity sweep
# --------------------------------------------------------------------------- #
def _confmat_case():
    rng = _rng()
    data = [
        (jnp.asarray(rng.integers(0, 64, size=(128,))), jnp.asarray(rng.integers(0, 64, size=(128,))))
        for _ in range(3)
    ]
    return lambda: ConfusionMatrix(num_classes=64), data, True


def _precision_case():
    rng = _rng()
    data = [
        (
            jnp.asarray(rng.random((64, 16), dtype=np.float32)),
            jnp.asarray(rng.integers(0, 16, size=(64,))),
        )
        for _ in range(3)
    ]
    # macro averaging reduces *over* the sharded class axis: GSPMD may reorder
    # that float reduction, so parity is to 1 ulp, not bitwise — integer
    # accumulation and elementwise computes (the other cases) stay exact
    return lambda: Precision(num_classes=16, average="macro"), data, False


def _binned_case():
    rng = _rng()
    data = [
        (
            jnp.asarray(rng.random((32, 16), dtype=np.float32)),
            jnp.asarray(rng.integers(0, 2, size=(32, 16))),
        )
        for _ in range(3)
    ]
    return lambda: BinnedPrecisionRecallCurve(num_classes=16, thresholds=10), data, True


def _catbuffer_case():
    data = [(jnp.arange(i * 8, i * 8 + 8, dtype=jnp.float32),) for i in range(4)]
    return lambda: CatMetric(buffer_capacity=64), data, True


@pytest.mark.parametrize(
    "case",
    [_confmat_case, _precision_case, _binned_case, _catbuffer_case],
    ids=["confmat", "precision_macro", "binned_pr", "catbuffer"],
)
@pytest.mark.mesh8
def test_sharded_parity_and_footprint(mesh, case):
    build, data, exact = case()
    ref = build()
    for args in data:
        ref.update(*args)
    expect = ref.compute()

    m = build().shard_state(mesh)
    assert m.active_shard_axes == m.shard_axes and m.shard_axes
    for args in data:
        m.update(*args)

    # every declared leaf holds a 1/WORLD block per device
    state = m.metric_state
    for name in m.shard_axes:
        leaf = state[name]
        if isinstance(leaf, metrics_tpu.CatBuffer):
            leaf = leaf.data
        assert _per_device_nbytes(leaf) * WORLD == int(leaf.nbytes)

    assert _leaves_equal(expect, m.compute(), exact=exact)


@pytest.mark.mesh8
def test_sharded_update_uses_compiled_donated_engine(mesh):
    rng = _rng()
    m = ConfusionMatrix(num_classes=64).shard_state(mesh)
    for _ in range(5):
        m.update(
            jnp.asarray(rng.integers(0, 64, size=(64,))),
            jnp.asarray(rng.integers(0, 64, size=(64,))),
        )
    stats = m.engine_stats()["update"]
    assert stats is not None
    assert stats.compiled_calls > 0
    assert stats.donated_calls > 0
    assert not m.engine_stats()["fallback_reasons"]


# --------------------------------------------------------------------------- #
# sync routing: sharded leaves never psum
# --------------------------------------------------------------------------- #
@pytest.mark.mesh8
def test_sharded_leaves_spend_zero_psum_bytes(mesh):
    m = ConfusionMatrix(num_classes=64).shard_state(mesh)
    with count_collectives() as box:
        jax.make_jaxpr(lambda s: m.sync_states(s, "data"), axis_env=[("data", WORLD)])(
            {"confmat": jnp.zeros((64, 64), jnp.int32)}
        )
    assert box["bytes_by_kind"].get("psum", 0) == 0
    assert box["bytes_by_kind"].get("all_gather", 0) == 0
    assert box["by_kind"] == {"reshard": 1}
    assert box["bytes_by_kind"]["reshard"] == 64 * 64 * 4


@pytest.mark.mesh8
def test_mixed_state_splits_buckets(mesh):
    """Micro-Accuracy scalars keep their psum bucket; macro leaves reshard."""
    coll = MetricCollection(
        {
            "acc": Accuracy(num_classes=16, average="micro"),
            "f1": F1Score(num_classes=16, average="macro"),
        }
    ).shard_state(mesh)
    member = coll["f1"]
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda s: member.sync_states(s, "data"), axis_env=[("data", WORLD)]
        )(member.init_state())
    assert box["by_kind"].get("reshard", 0) >= 1
    acc = coll["acc"]
    with count_collectives() as box:
        jax.make_jaxpr(lambda s: acc.sync_states(s, "data"), axis_env=[("data", WORLD)])(
            acc.init_state()
        )
    assert box["by_kind"].get("reshard", 0) == 0
    assert box["by_kind"].get("psum", 0) >= 1


# --------------------------------------------------------------------------- #
# lifecycle: reset / state_dict / checkpoint / unshard
# --------------------------------------------------------------------------- #
def _sharded_spec(leaf):
    return getattr(leaf.sharding, "spec", None)


@pytest.mark.mesh8
def test_reset_keeps_placement(mesh):
    rng = _rng()
    m = ConfusionMatrix(num_classes=64).shard_state(mesh)
    m.update(
        jnp.asarray(rng.integers(0, 64, size=(64,))),
        jnp.asarray(rng.integers(0, 64, size=(64,))),
    )
    m.reset()
    assert _per_device_nbytes(m.confmat) * WORLD == int(m.confmat.nbytes)
    assert np.asarray(m.confmat).sum() == 0


@pytest.mark.mesh8
def test_state_dict_roundtrip_keeps_placement(mesh):
    rng = _rng()
    preds = jnp.asarray(rng.integers(0, 64, size=(128,)))
    target = jnp.asarray(rng.integers(0, 64, size=(128,)))

    def build():
        m = ConfusionMatrix(num_classes=64)
        m._persistent["confmat"] = True  # state_dict snapshots persistent states
        return m.shard_state(mesh)

    src = build()
    src.update(preds, target)

    dst = build()
    dst.load_state_dict(src.state_dict())
    assert _per_device_nbytes(dst.confmat) * WORLD == int(dst.confmat.nbytes)
    assert _leaves_equal(src.compute(), dst.compute())


@pytest.mark.mesh8
def test_checkpoint_roundtrip_sharded(mesh, tmp_path):
    from metrics_tpu.checkpoint import restore_checkpoint, save_checkpoint

    rng = _rng()
    preds = jnp.asarray(rng.integers(0, 64, size=(128,)))
    target = jnp.asarray(rng.integers(0, 64, size=(128,)))
    src = ConfusionMatrix(num_classes=64).shard_state(mesh)
    src.update(preds, target)
    expect = np.asarray(src.compute())
    save_checkpoint(src, str(tmp_path), step=1)

    # sharded -> sharded: placement restored
    dst = ConfusionMatrix(num_classes=64).shard_state(mesh)
    restore_checkpoint(dst, str(tmp_path))
    assert _per_device_nbytes(dst.confmat) * WORLD == int(dst.confmat.nbytes)
    assert np.array_equal(expect, np.asarray(dst.compute()))

    # sharded -> replicated: the payload is placement-free
    flat = ConfusionMatrix(num_classes=64)
    restore_checkpoint(flat, str(tmp_path))
    assert np.array_equal(expect, np.asarray(flat.compute()))


def test_checkpoint_fingerprint_includes_shard_axis():
    from metrics_tpu.checkpoint.format import metric_fingerprint

    fp = metric_fingerprint(ConfusionMatrix(num_classes=8))
    assert fp["states"]["confmat"]["shard_axis"] == 0
    fp_micro = metric_fingerprint(Accuracy(num_classes=8, average="micro"))
    assert "shard_axis" not in fp_micro["states"]["tp"]


def test_checkpoint_fingerprint_shard_axis_back_compat():
    """Checkpoints written before a class gained its shard_axis declaration
    must stay restorable — the declaration is placement-inert and the payload
    placement-free. Two *conflicting* declarations still refuse."""
    import copy

    from metrics_tpu.checkpoint.format import fingerprint_diff, metric_fingerprint

    live = metric_fingerprint(ConfusionMatrix(num_classes=8))
    pre_sharding = copy.deepcopy(live)
    del pre_sharding["states"]["confmat"]["shard_axis"]
    assert fingerprint_diff(pre_sharding, live) == []  # old checkpoint, new class
    assert fingerprint_diff(live, pre_sharding) == []  # new checkpoint, old class
    conflicting = copy.deepcopy(live)
    conflicting["states"]["confmat"]["shard_axis"] = 1
    assert fingerprint_diff(conflicting, live)


@pytest.mark.mesh8
def test_sharded_catbuffer_keeps_overflow_flag(mesh):
    """The sticky `overflowed` flag must survive sharded placement, the
    per-step sharding constraint inside compiled updates, and the gather back
    to replicated — dropping it would hand corrupt tail data to to_array()."""
    from metrics_tpu.core.buffers import CatBuffer

    m = CatMetric(buffer_capacity=WORLD).shard_state(mesh)
    over = CatBuffer(jnp.zeros((WORLD,), jnp.float32), WORLD + 2, None, True)

    placed = m._place_sharded_value("value", over)
    assert bool(placed.overflowed)

    constrained = m._constrain_state({"value": placed})["value"]
    assert bool(constrained.overflowed)

    m.value = placed
    m.unshard_state()
    assert bool(m.value.overflowed)


@pytest.mark.mesh8
def test_unshard_state(mesh):
    rng = _rng()
    m = ConfusionMatrix(num_classes=64).shard_state(mesh)
    m.update(
        jnp.asarray(rng.integers(0, 64, size=(128,))),
        jnp.asarray(rng.integers(0, 64, size=(128,))),
    )
    before = np.asarray(m.compute())
    m.unshard_state()
    assert m.active_shard_axes == {}
    assert _per_device_nbytes(m.confmat) == int(m.confmat.nbytes)
    assert np.array_equal(before, np.asarray(m.compute()))


@pytest.mark.mesh8
def test_unshard_round_trip_reshard_accounting(mesh):
    """Every host-side re-materialization is billed as ``"reshard"`` — the
    sharded→compute→unshard round trip spends exactly one state-sized tick
    (at unshard; the facade compute runs gather-free under GSPMD)."""
    rng = _rng()
    m = ConfusionMatrix(num_classes=64).shard_state(mesh)
    m.update(
        jnp.asarray(rng.integers(0, 64, size=(128,))),
        jnp.asarray(rng.integers(0, 64, size=(128,))),
    )
    with count_collectives() as box:
        m.compute()
        m.unshard_state()
    assert box["by_kind"] == {"reshard": 1}
    assert box["bytes_by_kind"] == {"reshard": 64 * 64 * 4}

    # catbuffer states bill their payload buffer the same way
    c = CatMetric(buffer_capacity=WORLD * 4).shard_state(mesh)
    c.update(jnp.arange(WORLD * 4, dtype=jnp.float32))
    with count_collectives() as box:
        c.unshard_state()
    assert box["by_kind"] == {"reshard": 1}
    assert box["bytes_by_kind"] == {"reshard": WORLD * 4 * 4}


# --------------------------------------------------------------------------- #
# fused collection streaks with mixed members
# --------------------------------------------------------------------------- #
@pytest.mark.mesh8
def test_fused_collection_mixed_sharded_members(mesh):
    rng = _rng()
    data = [
        (
            jnp.asarray(rng.random((64, 16), dtype=np.float32)),
            jnp.asarray(rng.integers(0, 16, size=(64,))),
        )
        for _ in range(5)
    ]

    def build():
        return MetricCollection(
            {
                "acc": Accuracy(num_classes=16, average="micro"),  # replicated
                "prec": Precision(num_classes=16, average="macro"),  # sharded
                "confmat": ConfusionMatrix(num_classes=16),  # sharded
            }
        )

    ref = build()
    for args in data:
        ref.update(*args)
    expect = ref.compute()

    coll = build().shard_state(mesh)
    for args in data:
        coll.update(*args)
    got = coll.compute()

    for key in expect:
        assert _leaves_equal(expect[key], got[key]), key

    stats = coll.engine_stats()["update"]
    assert stats is not None and stats.compiled_calls > 0 and stats.donated_calls > 0

    # member leaves really are distributed inside the fused streak
    coll._realias_members()
    confmat = coll["confmat"].confmat
    assert _per_device_nbytes(confmat) * WORLD == int(confmat.nbytes)


@pytest.mark.mesh8
def test_collection_unshard_state(mesh):
    rng = _rng()
    coll = MetricCollection(
        {"prec": Precision(num_classes=16, average="macro")}
    ).shard_state(mesh)
    coll.update(
        jnp.asarray(rng.random((32, 16), dtype=np.float32)),
        jnp.asarray(rng.integers(0, 16, size=(32,))),
    )
    before = coll.compute()
    coll.unshard_state()
    assert coll["prec"].active_shard_axes == {}
    assert _leaves_equal(before, coll.compute())


# --------------------------------------------------------------------------- #
# engine capture: collective bytes land in EngineStats
# --------------------------------------------------------------------------- #
@pytest.mark.mesh8
def test_engine_stats_record_reshard_bytes(mesh):
    rng = _rng()
    m = ConfusionMatrix(num_classes=64).shard_state(mesh)
    # two update→compute cycles: the engine lifecycle runs the first call
    # eager, so only the second compute goes through the compiled path where
    # the trace-time collective capture happens
    for _ in range(2):
        m.update(
            jnp.asarray(rng.integers(0, 64, size=(64,))),
            jnp.asarray(rng.integers(0, 64, size=(64,))),
        )
        m.compute()
    stats = m.engine_stats()["compute"]
    assert stats is not None and stats.cache_misses > 0
    assert not m.engine_stats()["fallback_reasons"]
    # single-process sync short-circuits before emitting collectives; the
    # capture contract is: whatever kinds the trace ticked are tallied
    assert isinstance(stats.collective_counts, dict)
    assert isinstance(stats.collective_bytes, dict)
