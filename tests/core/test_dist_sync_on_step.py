"""``dist_sync_on_step`` semantics under a collective context.

Reference analog: _class_test runs every metric with
dist_sync_on_step=[False, True] (tests/helpers/testers.py:131-171): with True,
``forward`` must return the batch value computed from ALL ranks' batch;
with False, the local rank's batch value. Here the "ranks" are mesh devices
inside shard_map with the sync_axes context declared.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from metrics_tpu import Accuracy, MeanSquaredError
from metrics_tpu.parallel.sync import sync_axes
from tests.helpers.testers import DummyMetricSum

pytestmark = pytest.mark.mesh8

WORLD = 8


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return Mesh(np.asarray(devices[:WORLD]), ("data",))


@pytest.mark.parametrize("sync_step", [False, True], ids=["local", "dist_sync_on_step"])
def test_forward_batch_value_scope(mesh, sync_step):
    """forward() returns the cross-device batch value iff dist_sync_on_step."""
    m = DummyMetricSum(dist_sync_on_step=sync_step)

    def body(x):
        with sync_axes("data"):
            val = m(x[0, 0])  # forward: batch value + accumulation
        return jnp.expand_dims(jnp.asarray(val), 0)

    xs = jnp.arange(1.0, WORLD + 1).reshape(WORLD, 1)
    out = np.asarray(
        jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("data"), out_specs=P("data"), check_vma=False))(xs)
    )
    if sync_step:
        np.testing.assert_allclose(out, np.full(WORLD, xs.sum()))  # global batch sum everywhere
    else:
        np.testing.assert_allclose(out, np.arange(1.0, WORLD + 1))  # each device its own


@pytest.mark.parametrize("sync_step", [False, True], ids=["local", "dist_sync_on_step"])
def test_forward_value_metric_accuracy(mesh, sync_step):
    """Same contract through a real metric with derived (ratio) compute."""
    rng = np.random.default_rng(3)
    preds = jnp.asarray(rng.dirichlet(np.ones(4), (WORLD, 16)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, 4, (WORLD, 16)))
    m = Accuracy(num_classes=4, dist_sync_on_step=sync_step)

    def body(p, t):
        with sync_axes("data"):
            val = m(p.reshape(-1, 4), t.reshape(-1))
        return jnp.expand_dims(jnp.asarray(val), 0)

    out = np.asarray(jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data"), check_vma=False,
    ))(preds, target))

    per_device = (np.asarray(preds).argmax(-1) == np.asarray(target)).mean(axis=1)
    if sync_step:
        np.testing.assert_allclose(out, per_device.mean(), atol=1e-6)
    else:
        np.testing.assert_allclose(out, per_device, atol=1e-6)


def test_forward_accumulation_unaffected_by_step_sync(mesh):
    """dist_sync_on_step changes the RETURNED batch value only — the
    accumulated epoch state must be identical either way."""
    results = {}
    for sync_step in (False, True):
        m = MeanSquaredError(dist_sync_on_step=sync_step)

        def body(p, t):
            with sync_axes("data"):
                _ = m(p[0], t[0])
                state = m.get_state()
                state = m.sync_states(state, "data")
                out = m.compute_state(state)
            return jnp.expand_dims(out, 0)

        rng = np.random.default_rng(7)
        p = jnp.asarray(rng.random((WORLD, 16)).astype(np.float32))
        t = jnp.asarray(rng.random((WORLD, 16)).astype(np.float32))
        out = np.asarray(jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=(P("data"), P("data")), out_specs=P("data"), check_vma=False,
        ))(p, t))
        results[sync_step] = out
    np.testing.assert_allclose(results[False], results[True], atol=1e-7)
