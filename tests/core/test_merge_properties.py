"""Property-based tests of the merge/sync algebra (hypothesis).

``merge_states`` is the load-bearing primitive: cross-device sync IS a merge
of per-device partial states (SURVEY.md §7 design decision 2). The property
that makes distributed results correct is the accumulation homomorphism —
updating on a data split and merging must equal updating sequentially —
plus merge commutativity for order-independent metrics. Hypothesis searches
the input space instead of relying on a handful of fixtures.
"""
import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="property-based tests need the `test` extra (pip install metrics-tpu[test])")
from hypothesis import HealthCheck, assume, given, settings
from hypothesis import strategies as st
from hypothesis.extra.numpy import arrays

import jax.numpy as jnp

from metrics_tpu import (
    Accuracy,
    MaxMetric,
    MeanMetric,
    MeanSquaredError,
    MinMetric,
    PearsonCorrCoef,
    R2Score,
    StatScores,
    SumMetric,
)

SETTINGS = settings(
    max_examples=25,
    deadline=None,  # jit compiles on first example
    suppress_health_check=[HealthCheck.too_slow, HealthCheck.data_too_large],
    derandomize=True,  # CI-stable example sequence
)

floats = st.floats(min_value=-1e3, max_value=1e3, allow_nan=False, width=32)


def batches_strategy(n_batches=2):
    """A list of float batches with independent lengths in [1, 16]."""
    return st.lists(
        st.integers(min_value=1, max_value=16).flatmap(
            lambda n: arrays(np.float32, (n,), elements=floats)
        ),
        min_size=n_batches,
        max_size=n_batches,
    )


def _accumulate(metric, batches, update):
    state = metric.init_state()
    for batch in batches:
        state = update(state, batch)
    return state


@pytest.mark.parametrize("metric_cls", [SumMetric, MeanMetric, MaxMetric, MinMetric])
@SETTINGS
@given(batches=batches_strategy(4))
def test_aggregator_split_merge_equals_sequential(metric_cls, batches):
    metric = metric_cls(nan_strategy="ignore")

    def update(state, batch):
        return metric.update_state(state, jnp.asarray(batch))

    sequential = _accumulate(metric, batches, update)
    left = _accumulate(metric, batches[:2], update)
    right = _accumulate(metric, batches[2:], update)
    merged = metric.merge_states(left, right, update_counts=(2, 2))
    np.testing.assert_allclose(
        np.asarray(metric.compute_state(merged)),
        np.asarray(metric.compute_state(sequential)),
        rtol=1e-5,
        atol=1e-5,
    )


@pytest.mark.parametrize("metric_cls", [SumMetric, MaxMetric, MinMetric])
@SETTINGS
@given(batches=batches_strategy(2))
def test_aggregator_merge_commutes(metric_cls, batches):
    metric = metric_cls(nan_strategy="ignore")

    def one(batch):
        return metric.update_state(metric.init_state(), jnp.asarray(batch))

    a, b = one(batches[0]), one(batches[1])
    ab = metric.compute_state(metric.merge_states(a, b))
    ba = metric.compute_state(metric.merge_states(b, a))
    np.testing.assert_allclose(np.asarray(ab), np.asarray(ba), rtol=1e-6)


@SETTINGS
@given(
    preds=arrays(np.float32, (24,), elements=floats),
    target=arrays(np.float32, (24,), elements=floats),
)
def test_mse_split_merge_equals_sequential(preds, target):
    metric = MeanSquaredError()

    def upd(state, lo, hi):
        return metric.update_state(state, jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))

    sequential = upd(upd(metric.init_state(), 0, 12), 12, 24)
    merged = metric.merge_states(upd(metric.init_state(), 0, 12), upd(metric.init_state(), 12, 24))
    np.testing.assert_allclose(
        np.asarray(metric.compute_state(merged)),
        np.asarray(metric.compute_state(sequential)),
        rtol=1e-5,
    )


@SETTINGS
@given(
    preds=arrays(np.float32, (32,), elements=floats),
    target=arrays(np.float32, (32,), elements=floats),
)
def test_pearson_running_moments_merge(preds, target):
    """Chan-style moment merging must match single-pass accumulation — the
    trickiest merge in the library (reference pearson.py:66 running update).

    Zero-variance draws are excluded: with var(x) = 0 the correlation is 0/0,
    mathematically undefined, and the two accumulation orders legitimately
    produce different f32 noise there.
    """
    for arr in (preds, target):
        for chunk in (arr[:20], arr[20:]):
            assume(float(np.std(chunk.astype(np.float64))) > 1e-2)
    metric = PearsonCorrCoef()

    def upd(state, lo, hi):
        return metric.update_state(state, jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))

    sequential = upd(upd(metric.init_state(), 0, 20), 20, 32)
    merged = metric.merge_states(upd(metric.init_state(), 0, 20), upd(metric.init_state(), 20, 32))
    seq_val = np.asarray(metric.compute_state(sequential))
    mrg_val = np.asarray(metric.compute_state(merged))
    if np.isnan(seq_val) or np.isnan(mrg_val):  # degenerate zero-variance draws
        assert np.isnan(seq_val) and np.isnan(mrg_val)
    else:
        np.testing.assert_allclose(mrg_val, seq_val, rtol=1e-3, atol=1e-5)


@SETTINGS
@given(
    preds=arrays(np.int64, (40,), elements=st.integers(min_value=0, max_value=4)),
    target=arrays(np.int64, (40,), elements=st.integers(min_value=0, max_value=4)),
    split=st.integers(min_value=1, max_value=39),
)
def test_stat_scores_split_merge_equals_sequential(preds, target, split):
    metric = StatScores(reduce="macro", num_classes=5)

    def upd(state, lo, hi):
        return metric.update_state(state, jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))

    sequential = upd(upd(metric.init_state(), 0, split), split, 40)
    merged = metric.merge_states(upd(metric.init_state(), 0, split), upd(metric.init_state(), split, 40))
    np.testing.assert_array_equal(
        np.asarray(metric.compute_state(merged)), np.asarray(metric.compute_state(sequential))
    )


@SETTINGS
@given(
    preds=arrays(np.int64, (30,), elements=st.integers(min_value=0, max_value=3)),
    target=arrays(np.int64, (30,), elements=st.integers(min_value=0, max_value=3)),
)
def test_accuracy_matches_numpy_anywhere(preds, target):
    metric = Accuracy(num_classes=4)
    metric.update(jnp.asarray(preds), jnp.asarray(target))
    np.testing.assert_allclose(float(metric.compute()), float((preds == target).mean()), rtol=1e-6)


@SETTINGS
@given(
    preds=arrays(np.float32, (16,), elements=floats),
    target=arrays(np.float32, (16,), elements=floats),
)
def test_r2_split_merge_equals_sequential(preds, target):
    metric = R2Score()

    def upd(state, lo, hi):
        return metric.update_state(state, jnp.asarray(preds[lo:hi]), jnp.asarray(target[lo:hi]))

    sequential = upd(upd(metric.init_state(), 0, 8), 8, 16)
    merged = metric.merge_states(upd(metric.init_state(), 0, 8), upd(metric.init_state(), 8, 16))
    seq_val = np.asarray(metric.compute_state(sequential))
    mrg_val = np.asarray(metric.compute_state(merged))
    np.testing.assert_allclose(mrg_val, seq_val, rtol=1e-4, atol=1e-5)
