"""Eager multi-host gather over a real 2-process ``jax.distributed`` run.

Covers ``parallel/sync.py:gather_all_arrays`` — the first code path a real
multi-host TPU pod hits outside ``shard_map`` (ragged pad-to-max gather).
Reference contract: ``gather_all_tensors``
(torchmetrics/utilities/distributed.py:102-151), whose tests spawn a
2-process gloo group; here each rank is a subprocess in its own CPU backend
joined through ``jax.distributed.initialize``.
"""
import os
import socket
import subprocess
import sys
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = textwrap.dedent(
    """
    import sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    proc_id, port = int(sys.argv[1]), sys.argv[2]
    jax.distributed.initialize(f"localhost:{port}", num_processes=2, process_id=proc_id)
    import jax.numpy as jnp
    import numpy as np
    from metrics_tpu.parallel.sync import gather_all_arrays

    # ragged: rank 0 holds 3 rows, rank 1 holds 5 (forces the pad/trim path)
    n = 3 if proc_id == 0 else 5
    x = jnp.arange(n * 2, dtype=jnp.float32).reshape(n, 2) + 100 * proc_id
    out = gather_all_arrays(x)
    assert len(out) == 2, out
    np.testing.assert_allclose(np.asarray(out[0]), np.arange(6, dtype=np.float32).reshape(3, 2))
    np.testing.assert_allclose(np.asarray(out[1]), np.arange(10, dtype=np.float32).reshape(5, 2) + 100)

    # equal-shape fast path
    eq = gather_all_arrays(jnp.full((2,), float(proc_id)))
    np.testing.assert_allclose(np.asarray(eq[1]), [1.0, 1.0])

    # scalar state (e.g. an aggregation count)
    s = gather_all_arrays(jnp.asarray(float(proc_id)))
    assert [float(v[0]) for v in s] == [0.0, 1.0], s
    print("GATHER_OK", proc_id)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_gather_all_arrays_two_process(tmp_path, multiprocess_backend):
    child = tmp_path / "gather_child.py"
    child.write_text(_CHILD)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # drop the conftest's forced-8-device flag: one local device per process
    env["XLA_FLAGS"] = ""
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(child), str(rank), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd=REPO,
        )
        for rank in range(2)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert f"GATHER_OK {rank}" in out
