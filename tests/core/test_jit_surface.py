"""jit-vs-eager parity across the regression/image/audio/pairwise functional
surface — the compiled-path guarantee beyond classification
(tests/classification/test_jit_parity.py covers that domain).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import ops

_rng = np.random.default_rng(53)

_P = jnp.asarray((0.2 + _rng.random(24)).astype(np.float32))
_T = jnp.asarray((0.2 + _rng.random(24)).astype(np.float32))
_P2 = jnp.asarray((0.2 + _rng.random((8, 6))).astype(np.float32))
_T2 = jnp.asarray((0.2 + _rng.random((8, 6))).astype(np.float32))
_IMG_P = jnp.asarray(_rng.random((2, 3, 16, 16)).astype(np.float32))
_IMG_T = jnp.asarray(_rng.random((2, 3, 16, 16)).astype(np.float32))
_AUD_T = jnp.asarray(_rng.normal(size=(2, 2000)).astype(np.float32))
_AUD_P = _AUD_T + 0.3 * jnp.asarray(_rng.normal(size=(2, 2000)).astype(np.float32))
_MIX_T = jnp.asarray(_rng.normal(size=(2, 3, 1500)).astype(np.float32))  # (B, S, T)
_MIX_P = _MIX_T[:, ::-1] + 0.2 * jnp.asarray(_rng.normal(size=(2, 3, 1500)).astype(np.float32))
_STOI_T = jnp.asarray(_rng.normal(size=(8000,)).astype(np.float32))
_STOI_P = _STOI_T + 0.2 * jnp.asarray(_rng.normal(size=(8000,)).astype(np.float32))
_BIG_T = jnp.asarray(_rng.random((1, 1, 192, 192)).astype(np.float32))
_BIG_P = 0.8 * _BIG_T + 0.2 * jnp.asarray(_rng.random((1, 1, 192, 192)).astype(np.float32))
_RP = jnp.asarray(_rng.random(10).astype(np.float32))
_RT = jnp.asarray(_rng.integers(0, 2, 10).astype(bool))
_BOXES_A = jnp.asarray((_rng.random((4, 2)) * 50).astype(np.float32))
_BOXES_A = jnp.concatenate([_BOXES_A, _BOXES_A + 10], axis=1)
_BOXES_B = _BOXES_A[:2] + 5.0
_MASKS = jnp.asarray(_rng.integers(0, 2, (3, 8, 8)).astype(bool))
__sq_a = jnp.asarray(_rng.normal(size=(6, 6)).astype(np.float32))
__sq_b = jnp.asarray(_rng.normal(size=(6, 6)).astype(np.float32))
_COV_A = __sq_a @ __sq_a.T  # symmetric PSD
_COV_B = __sq_b @ __sq_b.T
_FEAT_A = jnp.asarray(_rng.normal(size=(32, 6)).astype(np.float32))
_FEAT_B = jnp.asarray(_rng.normal(size=(32, 6)).astype(np.float32))

from metrics_tpu.ops.detection import boxes as _boxes  # noqa: E402
from metrics_tpu.ops.image import fid as _fid_ops  # noqa: E402

CASES = [
    ("mse", lambda: ops.mean_squared_error(_P, _T)),
    ("mae", lambda: ops.mean_absolute_error(_P, _T)),
    ("msle", lambda: ops.mean_squared_log_error(_P, _T)),
    ("mape", lambda: ops.mean_absolute_percentage_error(_P, _T)),
    ("smape", lambda: ops.symmetric_mean_absolute_percentage_error(_P, _T)),
    ("wmape", lambda: ops.weighted_mean_absolute_percentage_error(_P, _T)),
    ("explained_variance", lambda: ops.explained_variance(_P, _T)),
    ("r2", lambda: ops.r2_score(_P, _T)),
    ("pearson", lambda: ops.pearson_corrcoef(_P, _T)),
    ("spearman", lambda: ops.spearman_corrcoef(_P, _T)),
    ("cosine", lambda: ops.cosine_similarity(_P2, _T2)),
    ("tweedie", lambda: ops.tweedie_deviance_score(_P, _T, power=1.5)),
    ("psnr", lambda: ops.peak_signal_noise_ratio(_IMG_P, _IMG_T, data_range=1.0)),
    ("ssim", lambda: ops.structural_similarity_index_measure(_IMG_P, _IMG_T, data_range=1.0)),
    ("uqi", lambda: ops.universal_image_quality_index(_IMG_P, _IMG_T)),
    ("sam", lambda: ops.spectral_angle_mapper(_IMG_P, _IMG_T)),
    ("ergas", lambda: ops.error_relative_global_dimensionless_synthesis(_IMG_P, _IMG_T)),
    ("d_lambda", lambda: ops.spectral_distortion_index(_IMG_P, _IMG_T)),
    ("snr", lambda: ops.signal_noise_ratio(_AUD_P, _AUD_T)),
    ("si_snr", lambda: ops.scale_invariant_signal_noise_ratio(_AUD_P, _AUD_T)),
    ("si_sdr", lambda: ops.scale_invariant_signal_distortion_ratio(_AUD_P, _AUD_T)),
    ("sdr", lambda: ops.signal_distortion_ratio(_AUD_P, _AUD_T)),
    ("pit", lambda: ops.permutation_invariant_training(_MIX_P, _MIX_T, ops.scale_invariant_signal_noise_ratio)[0]),
    ("pairwise_cosine", lambda: ops.pairwise_cosine_similarity(_P2, _T2)),
    ("pairwise_euclidean", lambda: ops.pairwise_euclidean_distance(_P2, _T2)),
    ("pairwise_linear", lambda: ops.pairwise_linear_similarity(_P2, _T2)),
    ("pairwise_manhattan", lambda: ops.pairwise_manhattan_distance(_P2, _T2)),
    ("stoi", lambda: ops.short_time_objective_intelligibility(_STOI_P, _STOI_T, 8000)),
    ("msssim", lambda: ops.multiscale_structural_similarity_index_measure(_BIG_P, _BIG_T, data_range=1.0)),
    ("image_gradients_dy", lambda: ops.image_gradients(_IMG_P)[0]),
    ("retrieval_ap", lambda: ops.retrieval_average_precision(_RP, _RT)),
    ("retrieval_mrr", lambda: ops.retrieval_reciprocal_rank(_RP, _RT)),
    ("retrieval_ndcg", lambda: ops.retrieval_normalized_dcg(_RP, _RT)),
    ("retrieval_precision", lambda: ops.retrieval_precision(_RP, _RT, k=3)),
    ("retrieval_recall", lambda: ops.retrieval_recall(_RP, _RT, k=3)),
    ("retrieval_fall_out", lambda: ops.retrieval_fall_out(_RP, _RT, k=3)),
    ("retrieval_hit_rate", lambda: ops.retrieval_hit_rate(_RP, _RT, k=3)),
    ("retrieval_r_precision", lambda: ops.retrieval_r_precision(_RP, _RT)),
    ("retrieval_curve_precision", lambda: ops.retrieval_precision_recall_curve(_RP, _RT, max_k=5)[0]),
    ("retrieval_curve_recall", lambda: ops.retrieval_precision_recall_curve(_RP, _RT, max_k=5)[1]),
    ("box_iou", lambda: _boxes.box_iou(_BOXES_A, _BOXES_B)),
    ("box_area", lambda: _boxes.box_area(_BOXES_A)),
    ("box_convert", lambda: _boxes.box_convert(_BOXES_A, "xyxy", "cxcywh")),
    ("mask_iou", lambda: _boxes.mask_iou(_MASKS, _MASKS)),
    ("fid_trace_sqrtm", lambda: _fid_ops.trace_sqrtm_product(_COV_A, _COV_B)),
    ("fid_frechet", lambda: _fid_ops.frechet_distance(_FEAT_A, _FEAT_B)),
]


@pytest.mark.parametrize("name,thunk", CASES, ids=[c[0] for c in CASES])
def test_jit_matches_eager(name, thunk):
    eager = thunk()
    jitted = jax.jit(thunk)()
    np.testing.assert_allclose(np.asarray(jitted), np.asarray(eager), rtol=2e-5, atol=1e-5)
