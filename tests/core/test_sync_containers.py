"""Regression: every state container type must come back from sync with the
same pytree structure it went in with (the PR-3 tuple->list drift class).

All traces run under the mock 8-device mesh (``make_jaxpr`` with an
``axis_env``), so treedef stability is checked exactly where it matters — at
trace time, where a drift would recompile every finalize and corrupt
``set_state`` round-trips.
"""
import jax
import jax.numpy as jnp
import pytest

from metrics_tpu import CatMetric, MeanMetric
from metrics_tpu.core.buffers import CatBuffer
from metrics_tpu.parallel import sync as _sync

AXIS = "data"
WORLD = 8


def _sync_shape(state, reductions, bucketed):
    _, shape = jax.make_jaxpr(
        lambda s: _sync.sync_state(s, reductions, AXIS, bucketed=bucketed),
        axis_env=[(AXIS, WORLD)],
        return_shape=True,
    )(state)
    return shape


CONTAINER_STATES = {
    "array-sum": ({"v": jnp.zeros((4,))}, {"v": "sum"}),
    "array-mean": ({"v": jnp.zeros((4,))}, {"v": "mean"}),
    "array-max": ({"v": jnp.zeros(())}, {"v": "max"}),
    "array-min": ({"v": jnp.zeros(())}, {"v": "min"}),
    "array-gather": ({"v": jnp.zeros((4,))}, {"v": None}),
    "nonempty-list-cat": ({"v": [jnp.zeros((2,)), jnp.zeros((3,))]}, {"v": "cat"}),
    "nonempty-tuple-cat": ({"v": (jnp.zeros((2,)), jnp.zeros((3,)))}, {"v": "cat"}),
    "empty-list": ({"v": []}, {"v": "cat"}),
    "empty-tuple": ({"v": ()}, {"v": "cat"}),
    "catbuffer": ({"v": CatBuffer.from_array(jnp.arange(4.0), capacity=8)}, {"v": "cat"}),
    "catbuffer-unmaterialized": ({"v": CatBuffer.empty(capacity=8)}, {"v": "cat"}),
    "mixed": (
        {
            "total": jnp.zeros(()),
            "count": jnp.zeros((), jnp.int32),
            "buf": (jnp.zeros((2,)),),
            "cat": CatBuffer.from_array(jnp.arange(3.0), capacity=8),
        },
        {"total": "sum", "count": "sum", "buf": "cat", "cat": "cat"},
    ),
}


def _expected_structure(state):
    """Sync's container contract: container types are preserved; non-empty
    list/tuple states collapse to one locally-concatenated element; everything
    else keeps its structure leaf-for-leaf."""
    out = {}
    for key, val in state.items():
        if isinstance(val, (list, tuple)) and len(val) > 1:
            out[key] = type(val)((val[0],))
        else:
            out[key] = val
    return jax.tree_util.tree_structure(out)


@pytest.mark.parametrize("bucketed", [True, False], ids=["bucketed", "per-leaf"])
@pytest.mark.parametrize("name", sorted(CONTAINER_STATES))
def test_sync_preserves_treedef_and_container_types(name, bucketed):
    state, reductions = CONTAINER_STATES[name]
    out = _sync_shape(state, reductions, bucketed)
    assert jax.tree_util.tree_structure(out) == _expected_structure(state)
    for key, val in state.items():
        if isinstance(val, (list, tuple, CatBuffer)):
            # the PR-3 drift class: a tuple state must come back a tuple
            assert type(out[key]) is type(val)


def test_no_axis_sync_is_identity_structure():
    state, reductions = CONTAINER_STATES["mixed"]
    out = _sync.sync_state(state, reductions, None)
    assert jax.tree_util.tree_structure(out) == jax.tree_util.tree_structure(state)


@pytest.mark.parametrize(
    "make",
    [
        lambda: MeanMetric(),
        lambda: CatMetric(buffer_capacity=8),
    ],
    ids=["MeanMetric", "CatMetric-buffered"],
)
def test_metric_sync_states_treedef_stable(make):
    m = make()
    m.update(jnp.arange(4.0))
    state = m.get_state()
    _, shape = jax.make_jaxpr(
        lambda s: m.sync_states(s, AXIS), axis_env=[(AXIS, WORLD)], return_shape=True
    )(state)
    assert jax.tree_util.tree_structure(shape) == jax.tree_util.tree_structure(state)
    # and the state survives a set_state round-trip with the synced shape
    m.set_state(jax.tree_util.tree_map(lambda l: jnp.zeros(l.shape, l.dtype), shape))


def test_bucketed_matches_per_leaf_bitwise():
    state = {"a": jnp.arange(3.0), "b": jnp.arange(5.0), "n": jnp.asarray(2.0)}
    reductions = {"a": "sum", "b": "sum", "n": "sum"}

    def run(bucketed):
        return jax.pmap(
            lambda s: _sync.sync_state(s, reductions, AXIS, bucketed=bucketed),
            axis_name=AXIS,
        )(jax.tree_util.tree_map(lambda l: jnp.stack([l] * WORLD), state))

    a, b = run(True), run(False)
    for key in state:
        assert jnp.array_equal(a[key], b[key])
