"""Gather-free sharded compute: the ``compute_sharded_state`` protocol.

Metrics whose finalize factors into a per-shard reduction plus a small
cross-shard combine declare :meth:`Metric.compute_sharded_state`; with an
active placement and a single named axis, ``sync_compute_state`` routes there
instead of re-materializing tiled state, so the only collectives are
result-sized ``psum``/``all_gather`` — ``"reshard"`` bytes drop to zero.

Pinned here on the 8-device CPU mesh:

* every declaring metric matches its replicated twin under ``shard_map``
  (bitwise for integer/elementwise finalizes, 1-ulp for cross-shard float
  reductions) while spending zero ``"reshard"`` bytes;
* subclasses that override ``compute`` without re-declaring the sharded twin
  fall back to the reshard path (the MRO guard in
  ``supports_sharded_compute``);
* multi-axis placements and inactive declarations never route through the
  protocol.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

import metrics_tpu
from metrics_tpu import (
    BinnedAveragePrecision,
    BinnedPrecisionRecallCurve,
    BinnedRecallAtFixedPrecision,
    CatMetric,
    ConfusionMatrix,
    F1Score,
    MatthewsCorrCoef,
    Precision,
    Recall,
    StatScores,
)
from metrics_tpu.parallel import make_mesh
from metrics_tpu.parallel.sync import count_collectives

WORLD = 8


@pytest.fixture()
def mesh():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return make_mesh([WORLD], ["data"], devices[:WORLD])


def _rng():
    return np.random.default_rng(0)


def _leaves_equal(a, b, exact=True):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    if exact:
        return all(
            np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True)
            for x, y in zip(la, lb)
        )
    return all(
        np.allclose(np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-6, equal_nan=True)
        for x, y in zip(la, lb)
    )


# --------------------------------------------------------------------------- #
# eligibility: the MRO guard
# --------------------------------------------------------------------------- #
def test_declaring_metrics_support_sharded_compute():
    declaring = [
        ConfusionMatrix(num_classes=8),
        MatthewsCorrCoef(num_classes=8),
        StatScores(reduce="macro", num_classes=8),
        Precision(average="macro", num_classes=8),
        Recall(average="none", num_classes=8),
        BinnedPrecisionRecallCurve(num_classes=8, thresholds=5),
        BinnedAveragePrecision(num_classes=8, thresholds=5),
        BinnedRecallAtFixedPrecision(num_classes=8, thresholds=5, min_precision=0.5),
        CatMetric(buffer_capacity=16),
    ]
    for m in declaring:
        assert m.supports_sharded_compute, type(m).__name__


def test_compute_override_disables_inherited_sharded_compute():
    """A subclass redefining ``compute`` silently invalidates a parent's
    sharded twin — the guard must refuse it rather than compute wrong."""
    assert not F1Score(num_classes=8, average="macro").supports_sharded_compute

    class _Doubled(ConfusionMatrix):
        def compute(self):
            return super().compute() * 2

    assert not _Doubled(num_classes=8).supports_sharded_compute

    class _Redeclared(_Doubled):
        def compute_sharded_state(self, state, axis_name):
            return super().compute_sharded_state(state, axis_name) * 2

    assert _Redeclared(num_classes=8).supports_sharded_compute


def test_base_stub_raises():
    with pytest.raises(NotImplementedError):
        metrics_tpu.Metric.compute_sharded_state(
            ConfusionMatrix(num_classes=4), {}, "data"
        )


# --------------------------------------------------------------------------- #
# parity sweep: replicated compute vs sharded protocol under shard_map
# --------------------------------------------------------------------------- #
def _cls_data(C=64, n=4096):
    rng = _rng()
    return (
        jnp.asarray(rng.integers(0, C, size=(n,))),
        jnp.asarray(rng.integers(0, C, size=(n,))),
    )


def _prob_data(C=64, n=512):
    rng = _rng()
    return (
        jnp.asarray(rng.random((n, C), dtype=np.float32)),
        jnp.asarray(rng.integers(0, C, size=(n,))),
    )


C = 64

_PROTOCOL_CASES = [
    ("confmat", lambda: ConfusionMatrix(num_classes=C), _cls_data, True),
    ("confmat_norm_true", lambda: ConfusionMatrix(num_classes=C, normalize="true"), _cls_data, True),
    ("confmat_norm_pred", lambda: ConfusionMatrix(num_classes=C, normalize="pred"), _cls_data, False),
    ("confmat_norm_all", lambda: ConfusionMatrix(num_classes=C, normalize="all"), _cls_data, False),
    ("matthews", lambda: MatthewsCorrCoef(num_classes=C), _cls_data, True),
    ("stat_scores_macro", lambda: StatScores(reduce="macro", num_classes=C), _cls_data, True),
    ("precision_macro", lambda: Precision(average="macro", num_classes=C), _cls_data, False),
    ("precision_none", lambda: Precision(average="none", num_classes=C), _cls_data, True),
    ("recall_weighted", lambda: Recall(average="weighted", num_classes=C), _cls_data, False),
    ("binned_pr_curve", lambda: BinnedPrecisionRecallCurve(num_classes=C, thresholds=16), _prob_data, True),
    ("binned_ap", lambda: BinnedAveragePrecision(num_classes=C, thresholds=16), _prob_data, False),
    ("binned_recall_at_p", lambda: BinnedRecallAtFixedPrecision(num_classes=C, thresholds=16, min_precision=0.5), _prob_data, True),
]


@pytest.mark.parametrize(
    "build,data_fn,exact",
    [c[1:] for c in _PROTOCOL_CASES],
    ids=[c[0] for c in _PROTOCOL_CASES],
)
@pytest.mark.mesh8
def test_protocol_parity_zero_reshard(mesh, build, data_fn, exact):
    args = data_fn()
    ref = build()
    ref.update(*args)
    expect = ref.compute()

    m = build()
    m.update(*args)
    state = {k: getattr(m, k) for k in m._defaults}
    m._state_sharding = (mesh, "data")
    assert m.supports_sharded_compute
    active = m.active_shard_axes
    in_specs = ({k: P("data") if active.get(k) is not None else P() for k in state},)
    fn = shard_map(
        lambda st: m.sync_compute_state(st, axis_name="data"),
        mesh=mesh,
        in_specs=in_specs,
        out_specs=P(),
        check_rep=False,
    )
    with count_collectives() as box:
        got = fn(state)

    assert _leaves_equal(expect, got, exact=exact)
    # the protocol's whole point: zero state re-materialization
    assert box["bytes_by_kind"].get("reshard", 0) == 0
    assert box["by_kind"].get("reshard", 0) == 0
    # ...while the combine really did cross shards with result-sized traffic
    assert box["count"] >= 1


@pytest.mark.mesh8
def test_catmetric_protocol_gathers_without_reshard(mesh):
    """CatMetric's sharded buffer normally re-materializes through the
    ``"reshard"``-tagged catbuffer bucket; the protocol routes through
    ``CatBuffer.gather`` (three ``all_gather`` ticks) instead."""
    from metrics_tpu.utils.exceptions import MetricsUserError

    m = CatMetric(buffer_capacity=WORLD * 2).shard_state(mesh)
    m.update(jnp.arange(WORLD * 2, dtype=jnp.float32))
    state = {"value": m.value}

    # the trace legitimately dies at to_array() (data-dependent shape, same as
    # the replicated compute under jit) — but only after the combine ran, so
    # the collective accounting for the protocol leg is already complete
    with count_collectives() as box:
        with pytest.raises(MetricsUserError, match="to_array"):
            jax.make_jaxpr(
                lambda s: m.sync_compute_state(s, axis_name="data"),
                axis_env=[("data", WORLD)],
            )(state)
    assert box["by_kind"].get("reshard", 0) == 0
    assert box["by_kind"].get("all_gather", 0) == 3

    with count_collectives() as box:
        jax.make_jaxpr(
            lambda s: m.sync_states(s, "data"), axis_env=[("data", WORLD)]
        )(state)
    assert box["by_kind"].get("reshard", 0) >= 1


# --------------------------------------------------------------------------- #
# routing: who takes the protocol path, who resharding
# --------------------------------------------------------------------------- #
def _local_confmat_block():
    return {"confmat": jnp.zeros((C // WORLD, C), jnp.int32)}


@pytest.mark.mesh8
def test_protocol_traffic_is_result_sized(mesh):
    m = ConfusionMatrix(num_classes=C).shard_state(mesh)
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda s: m.sync_compute_state(s, axis_name="data"),
            axis_env=[("data", WORLD)],
        )(_local_confmat_block())
    # one result gather of the local block, nothing tagged as reshard
    assert box["by_kind"] == {"all_gather": 1}
    assert box["bytes_by_kind"]["all_gather"] == (C // WORLD) * C * 4
    assert box["bytes_by_kind"].get("reshard", 0) == 0


@pytest.mark.mesh8
def test_non_declaring_metric_still_reshards(mesh):
    m = F1Score(num_classes=C, average="macro").shard_state(mesh)
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda s: m.sync_compute_state(s, axis_name="data"),
            axis_env=[("data", WORLD)],
        )(m.init_state())
    assert box["by_kind"].get("reshard", 0) >= 1


def test_inactive_sharding_skips_protocol():
    """Declaration alone must not route: per-device values of an unsharded
    metric inside shard_map are replicas, and the protocol would gather
    duplicates."""
    m = ConfusionMatrix(num_classes=C)
    assert m.supports_sharded_compute and m.active_shard_axes == {}
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda s: m.sync_compute_state(s, axis_name="data"),
            axis_env=[("data", WORLD)],
        )(m.init_state())
    assert box["by_kind"].get("all_gather", 0) == 0
    assert box["by_kind"].get("psum", 0) >= 1


@pytest.mark.mesh8
def test_axis_name_none_skips_protocol(mesh):
    """The facade/GSPMD path (axis_name=None) computes on the global sharded
    array under jit; the protocol is for explicit named-axis traces only."""
    rng = _rng()
    m = ConfusionMatrix(num_classes=C).shard_state(mesh)
    m.update(
        jnp.asarray(rng.integers(0, C, size=(128,))),
        jnp.asarray(rng.integers(0, C, size=(128,))),
    )
    with count_collectives() as box:
        out = m.sync_compute_state({"confmat": m.confmat}, None)
    assert out.shape == (C, C)
    assert box["count"] == 0
