"""Multi-axis sharded state: tuple ``shard_axis`` over 2-D meshes.

``add_state(..., shard_axis=(0, 1))`` declares a grid leaf (class x threshold)
whose dimensions pair positionally with the mesh axis names handed to
``shard_state(mesh, axis_name=("cls", "thr"))``. Pinned on the 8-device CPU
mesh folded as 4x2:

* placement: each device holds a 1/8 grid block under
  ``PartitionSpec("cls", "thr")`` (the :func:`~metrics_tpu.parallel.grid_sharded`
  spec helper);
* sync routing: one tiled all-gather per mesh axis, every tick tagged
  ``"reshard"``;
* parity: integer grids stay bitwise; float computes that reduce *over* a
  sharded mesh axis carry the 1-ulp cross-shard carve-out;
* lifecycle: reset / ``state_dict`` / checkpoint round trips restore both the
  values and the 2-D placement, and the leaf metadata + fingerprint carry the
  axis tuple (JSON list form).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import metrics_tpu
from metrics_tpu.parallel import grid_sharded, make_mesh
from metrics_tpu.parallel.sync import count_collectives

WORLD = 8
SHAPE = (16, 8)


@pytest.fixture()
def mesh2d():
    devices = jax.devices()
    if len(devices) < WORLD:
        pytest.skip("needs 8 devices")
    return make_mesh([4, 2], ["cls", "thr"], devices[:WORLD])


class GridMetric(metrics_tpu.Metric):
    """Integer class x threshold grid: bitwise across every placement."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state(
            "grid", default=jnp.zeros(SHAPE, jnp.int32), dist_reduce_fx="sum", shard_axis=(0, 1)
        )

    def update(self, x):
        self.grid = self.grid + x

    def compute(self):
        return self.grid.sum(axis=1)


def _grid_batch():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.integers(0, 100, size=SHAPE), dtype=jnp.int32)


def _block_nbytes(leaf):
    return int(leaf.addressable_shards[0].data.nbytes)


# --------------------------------------------------------------------------- #
# declaration + placement surface
# --------------------------------------------------------------------------- #
def test_add_state_tuple_validation():
    class Bad(metrics_tpu.Metric):
        def __init__(self, default, shard_axis, **kw):
            super().__init__(**kw)
            self.add_state("s", default=default, dist_reduce_fx="sum", shard_axis=shard_axis)

        def update(self):
            pass

        def compute(self):
            return self.s

    with pytest.raises(ValueError, match="non-empty ints"):
        Bad(jnp.zeros((4, 4)), (0, "1"))
    with pytest.raises(ValueError, match="out of range"):
        Bad(jnp.zeros((4, 4)), (0, 2))
    with pytest.raises(ValueError, match="same array axis twice"):
        Bad(jnp.zeros((4, 4)), (1, -1))
    with pytest.raises(ValueError):
        Bad(jnp.zeros((4, 4)), ())
    # negative entries are accepted and normalized at placement
    assert Bad(jnp.zeros((4, 4)), (0, -1)).shard_axes == {"s": (0, -1)}


@pytest.mark.mesh8
def test_grid_sharded_spec(mesh2d):
    s = grid_sharded(mesh2d, ("cls", "thr"), (0, 1), 2)
    assert s.spec == P("cls", "thr")
    s = grid_sharded(mesh2d, ("cls", "thr"), (1, 0), 3)
    assert s.spec == P("thr", "cls", None)
    with pytest.raises(ValueError):
        grid_sharded(mesh2d, ("cls",), (0, 1), 2)


@pytest.mark.mesh8
def test_shard_state_multi_axis_requirements(mesh2d):
    with pytest.raises(ValueError, match="mesh"):
        GridMetric().shard_state(axis_name=("cls", "thr"))
    with pytest.raises(Exception, match="axis"):
        GridMetric().shard_state(mesh2d, axis_name=("cls", "model"))
    with pytest.raises(ValueError):
        # rank-2 declaration needs two mesh axes
        GridMetric().shard_state(mesh2d, axis_name=("cls",))


@pytest.mark.mesh8
def test_multi_axis_placement(mesh2d):
    m = GridMetric().shard_state(mesh2d, axis_name=("cls", "thr"))
    assert m.grid.sharding.spec == P("cls", "thr")
    assert _block_nbytes(m.grid) * WORLD == int(m.grid.nbytes)
    assert m.active_shard_axes == {"grid": (0, 1)}


# --------------------------------------------------------------------------- #
# parity + sync routing
# --------------------------------------------------------------------------- #
@pytest.mark.mesh8
def test_multi_axis_parity_bitwise(mesh2d):
    x = _grid_batch()
    ref = GridMetric()
    ref.update(x)
    want = np.asarray(ref.compute())

    m = GridMetric().shard_state(mesh2d, axis_name=("cls", "thr"))
    m.update(x)
    assert np.array_equal(want, np.asarray(m.compute()))
    # placement survives the compiled update
    assert m.grid.sharding.spec == P("cls", "thr")


@pytest.mark.mesh8
def test_multi_axis_sync_reshards_per_mesh_axis(mesh2d):
    m = GridMetric().shard_state(mesh2d, axis_name=("cls", "thr"))
    local = {"grid": jnp.zeros((SHAPE[0] // 4, SHAPE[1] // 2), jnp.int32)}
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda s: m.sync_states(s, ("cls", "thr")),
            axis_env=[("cls", 4), ("thr", 2)],
        )(local)
    # one tiled gather per mesh axis, both billed as reshard: the (4, 4)
    # block gathers to (16, 4) over cls, then to (16, 8) over thr
    assert box["by_kind"] == {"reshard": 2}
    assert box["bytes_by_kind"]["reshard"] == 4 * 4 * 4 + 16 * 4 * 4


@pytest.mark.mesh8
def test_multi_axis_never_routes_sharded_compute(mesh2d):
    """The result-combine helpers address one named axis; grid placements
    always re-materialize even if the class declares the protocol."""

    class GridWithProtocol(GridMetric):
        def compute(self):
            return self.grid.sum(axis=1)

        def compute_sharded_state(self, state, axis_name):  # pragma: no cover
            raise AssertionError("must not route for tuple axis names")

    m = GridWithProtocol().shard_state(mesh2d, axis_name=("cls", "thr"))
    assert m.supports_sharded_compute
    local = {"grid": jnp.zeros((4, 4), jnp.int32)}
    with count_collectives() as box:
        jax.make_jaxpr(
            lambda s: m.sync_compute_state(s, axis_name=("cls", "thr")),
            axis_env=[("cls", 4), ("thr", 2)],
        )(local)
    assert box["by_kind"].get("reshard", 0) == 2


# --------------------------------------------------------------------------- #
# lifecycle: reset / state_dict / checkpoint
# --------------------------------------------------------------------------- #
@pytest.mark.mesh8
def test_multi_axis_reset_keeps_placement(mesh2d):
    m = GridMetric().shard_state(mesh2d, axis_name=("cls", "thr"))
    m.update(_grid_batch())
    m.reset()
    assert m.grid.sharding.spec == P("cls", "thr")
    assert int(np.asarray(m.grid).sum()) == 0


@pytest.mark.mesh8
def test_multi_axis_state_dict_roundtrip(mesh2d):
    x = _grid_batch()

    def build():
        m = GridMetric()
        m._persistent["grid"] = True
        return m.shard_state(mesh2d, axis_name=("cls", "thr"))

    src = build()
    src.update(x)
    dst = build()
    dst.load_state_dict(src.state_dict())
    assert dst.grid.sharding.spec == P("cls", "thr")
    assert _block_nbytes(dst.grid) * WORLD == int(dst.grid.nbytes)
    assert np.array_equal(np.asarray(src.compute()), np.asarray(dst.compute()))


@pytest.mark.mesh8
def test_multi_axis_checkpoint_roundtrip(mesh2d, tmp_path):
    from metrics_tpu.checkpoint import restore_checkpoint, save_checkpoint

    x = _grid_batch()
    src = GridMetric().shard_state(mesh2d, axis_name=("cls", "thr"))
    src.update(x)
    want = np.asarray(src.compute())
    save_checkpoint(src, str(tmp_path), step=1)

    dst = GridMetric().shard_state(mesh2d, axis_name=("cls", "thr"))
    restore_checkpoint(dst, str(tmp_path))
    assert dst.grid.sharding.spec == P("cls", "thr")
    assert _block_nbytes(dst.grid) * WORLD == int(dst.grid.nbytes)
    assert np.array_equal(want, np.asarray(dst.compute()))

    # the payload stays placement-free: restores replicated too
    flat = GridMetric()
    restore_checkpoint(flat, str(tmp_path))
    assert np.array_equal(want, np.asarray(flat.compute()))


@pytest.mark.mesh8
def test_multi_axis_leaf_meta_and_fingerprint(mesh2d):
    from metrics_tpu.checkpoint.format import (
        fingerprint_diff,
        metric_fingerprint,
        metric_leaves,
    )

    m = GridMetric()
    fp = metric_fingerprint(m)
    assert fp["states"]["grid"]["shard_axis"] == [0, 1]
    _, meta = metric_leaves(m, "")
    assert meta["grid"]["shard_axis"] == [0, 1]

    # back-compat: pre-declaration checkpoints restore; conflicting tuples diff
    import copy

    old = copy.deepcopy(fp)
    del old["states"]["grid"]["shard_axis"]
    assert fingerprint_diff(old, fp) == []
    conflicting = copy.deepcopy(fp)
    conflicting["states"]["grid"]["shard_axis"] = [1, 0]
    assert fingerprint_diff(conflicting, fp)


@pytest.mark.mesh8
def test_multi_axis_unshard(mesh2d):
    x = _grid_batch()
    m = GridMetric().shard_state(mesh2d, axis_name=("cls", "thr"))
    m.update(x)
    want = np.asarray(m.compute())
    with count_collectives() as box:
        m.unshard_state()
    assert box["by_kind"] == {"reshard": 1}
    assert m.active_shard_axes == {}
    assert m.grid.nbytes == _block_nbytes(m.grid) if not hasattr(m.grid, "addressable_shards") else True
    assert np.array_equal(want, np.asarray(m.compute()))
