"""Profiling helper tests (SURVEY.md §5.1 additions)."""
import jax.numpy as jnp
import numpy as np

from metrics_tpu import Accuracy
from metrics_tpu.utils.profiling import annotate, time_update, trace_metric

_rng = np.random.default_rng(0)


def test_annotate_and_trace_metric():
    acc = Accuracy(num_classes=4)
    trace_metric(acc, "update")
    logits = jnp.asarray(_rng.normal(size=(16, 4)).astype(np.float32))
    target = jnp.asarray(_rng.integers(0, 4, 16))
    with annotate("metrics/test"):
        acc.update(logits, target)
    assert acc._update_count == 1
    assert float(acc.compute()) >= 0


def test_time_update_reports():
    acc = Accuracy(num_classes=4)
    logits = jnp.asarray(_rng.normal(size=(16, 4)).astype(np.float32))
    target = jnp.asarray(_rng.integers(0, 4, 16))
    res = time_update(acc, logits, target, steps=10, warmup=1)
    assert set(res) == {"eager_us", "compiled_us", "compile_s", "speedup"}
    assert res["compiled_us"] > 0 and res["eager_us"] > 0
    # timer must leave the metric reset
    assert acc._update_count == 0
