"""Docstring examples executed as doctests — the API-documentation layer.

Reference parity: every torchmetrics class docstring example runs in CI via
pytest-doctestplus (reference setup.cfg:1-13, Makefile:23). Here the curated
module list below is executed with stock doctest inside the normal pytest run;
each listed module must contain at least one example.
"""
import doctest
import importlib

import pytest

MODULES = [
    "metrics_tpu.aggregation",
    "metrics_tpu.audio.pesq",
    "metrics_tpu.audio.pit",
    "metrics_tpu.audio.sdr",
    "metrics_tpu.audio.snr",
    "metrics_tpu.audio.stoi",
    "metrics_tpu.classification.avg_precision",
    "metrics_tpu.classification.binned_precision_recall",
    "metrics_tpu.classification.calibration_error",
    "metrics_tpu.classification.hinge",
    "metrics_tpu.classification.precision_recall_curve",
    "metrics_tpu.classification.ranking",
    "metrics_tpu.classification.roc",
    "metrics_tpu.classification.stat_scores",
    "metrics_tpu.core.buffers",
    "metrics_tpu.core.metric",
    "metrics_tpu.image.inception",
    "metrics_tpu.image.kid",
    "metrics_tpu.image.lpip",
    "metrics_tpu.retrieval.precision_recall_curve",
    "metrics_tpu.text.bert",
    "metrics_tpu.text.eed",
    "metrics_tpu.classification.auc",
    "metrics_tpu.classification.dice",
    "metrics_tpu.classification.hamming",
    "metrics_tpu.classification.kl_divergence",
    "metrics_tpu.classification.matthews_corrcoef",
    "metrics_tpu.classification.specificity",
    "metrics_tpu.image.quality",
    "metrics_tpu.regression.other",
    "metrics_tpu.text.chrf",
    "metrics_tpu.text.squad",
    "metrics_tpu.text.ter",
    "metrics_tpu.wrappers.classwise",
    "metrics_tpu.wrappers.bootstrapping",
    "metrics_tpu.wrappers.minmax",
    "metrics_tpu.wrappers.multioutput",
    "metrics_tpu.wrappers.tracker",
    "metrics_tpu.classification.accuracy",
    "metrics_tpu.classification.auroc",
    "metrics_tpu.classification.cohen_kappa",
    "metrics_tpu.classification.confusion_matrix",
    "metrics_tpu.classification.f_beta",
    "metrics_tpu.classification.jaccard",
    "metrics_tpu.classification.precision_recall",
    "metrics_tpu.core.collections",
    "metrics_tpu.detection.mean_ap",
    "metrics_tpu.image.fid",
    "metrics_tpu.image.psnr",
    "metrics_tpu.image.ssim",
    "metrics_tpu.regression.basic",
    "metrics_tpu.regression.moments",
    "metrics_tpu.retrieval.metrics",
    "metrics_tpu.text.bleu",
    "metrics_tpu.text.error_rates",
    "metrics_tpu.text.rouge",
]


@pytest.mark.parametrize("name", MODULES)
def test_module_doctests(name):
    module = importlib.import_module(name)
    result = doctest.testmod(module, optionflags=doctest.NORMALIZE_WHITESPACE, verbose=False)
    assert result.attempted > 0, f"no doctest examples found in {name}"
    assert result.failed == 0, f"{result.failed} doctest failure(s) in {name}"
