"""Public-API parity lock vs the reference's export lists.

Reference: torchmetrics/__init__.py (~85 exported names) and
torchmetrics/functional/__init__.py (~77 functions). Every reference export
must resolve on metrics_tpu (modulo the reference's optional-dependency
guards, which metrics_tpu exports unconditionally).
"""
import metrics_tpu
import metrics_tpu.ops as ops

REF_TOP_LEVEL = [
    "functional", "Accuracy", "AUC", "AUROC", "AveragePrecision", "BinnedAveragePrecision",
    "BinnedPrecisionRecallCurve", "BinnedRecallAtFixedPrecision", "BLEUScore", "BootStrapper",
    "CalibrationError", "CatMetric", "CharErrorRate", "CHRFScore", "ClasswiseWrapper", "CohenKappa",
    "ConfusionMatrix", "CosineSimilarity", "CoverageError", "Dice", "ErrorRelativeGlobalDimensionlessSynthesis",
    "ExplainedVariance", "ExtendedEditDistance", "F1Score", "FBetaScore", "HammingDistance", "HingeLoss",
    "JaccardIndex", "KLDivergence", "LabelRankingAveragePrecision", "LabelRankingLoss", "MatchErrorRate",
    "MatthewsCorrCoef", "MaxMetric", "MeanAbsoluteError", "MeanAbsolutePercentageError", "MeanMetric",
    "MeanSquaredError", "MeanSquaredLogError", "Metric", "MetricCollection", "MetricTracker", "MinMaxMetric",
    "MinMetric", "MultiScaleStructuralSimilarityIndexMeasure", "MultioutputWrapper", "PearsonCorrCoef",
    "PeakSignalNoiseRatio", "PermutationInvariantTraining", "Precision", "PrecisionRecallCurve", "R2Score",
    "Recall", "RetrievalFallOut", "RetrievalHitRate", "RetrievalMAP", "RetrievalMRR", "RetrievalNormalizedDCG",
    "RetrievalPrecision", "RetrievalPrecisionRecallCurve", "RetrievalRecall", "RetrievalRecallAtFixedPrecision",
    "RetrievalRPrecision", "ROC", "SacreBLEUScore", "ScaleInvariantSignalDistortionRatio",
    "ScaleInvariantSignalNoiseRatio", "SignalDistortionRatio", "SignalNoiseRatio", "SpearmanCorrCoef",
    "Specificity", "SpectralAngleMapper", "SpectralDistortionIndex", "SQuAD", "StatScores",
    "StructuralSimilarityIndexMeasure", "SumMetric", "SymmetricMeanAbsolutePercentageError",
    "TranslationEditRate", "TweedieDevianceScore", "UniversalImageQualityIndex", "WeightedMeanAbsolutePercentageError",
    "WordErrorRate", "WordInfoLost", "WordInfoPreserved",
]

REF_FUNCTIONAL = [
    "accuracy", "auc", "auroc", "average_precision", "bleu_score", "calibration_error", "char_error_rate",
    "chrf_score", "cohen_kappa", "confusion_matrix", "cosine_similarity", "coverage_error", "tweedie_deviance_score",
    "dice_score", "dice", "error_relative_global_dimensionless_synthesis", "explained_variance",
    "extended_edit_distance", "f1_score", "fbeta_score", "hamming_distance", "hinge_loss", "image_gradients",
    "jaccard_index", "kl_divergence", "label_ranking_average_precision", "label_ranking_loss", "match_error_rate",
    "matthews_corrcoef", "mean_absolute_error", "mean_absolute_percentage_error", "mean_squared_error",
    "mean_squared_log_error", "multiscale_structural_similarity_index_measure", "pairwise_cosine_similarity",
    "pairwise_euclidean_distance", "pairwise_linear_similarity", "pairwise_manhattan_distance", "pearson_corrcoef",
    "peak_signal_noise_ratio", "permutation_invariant_training", "pit_permutate", "precision", "precision_recall",
    "precision_recall_curve", "psnr", "r2_score", "recall", "retrieval_average_precision", "retrieval_fall_out",
    "retrieval_hit_rate", "retrieval_normalized_dcg", "retrieval_precision", "retrieval_r_precision",
    "retrieval_recall", "retrieval_reciprocal_rank", "roc", "rouge_score", "sacre_bleu_score",
    "scale_invariant_signal_distortion_ratio", "scale_invariant_signal_noise_ratio", "signal_distortion_ratio",
    "signal_noise_ratio", "spearman_corrcoef", "specificity", "spectral_angle_mapper", "spectral_distortion_index",
    "squad", "structural_similarity_index_measure", "stat_scores", "symmetric_mean_absolute_percentage_error",
    "translation_edit_rate", "universal_image_quality_index", "word_error_rate", "word_information_lost",
    "word_information_preserved",
]


def test_top_level_exports():
    missing = [n for n in REF_TOP_LEVEL if not hasattr(metrics_tpu, n)]
    assert not missing, f"missing top-level exports: {missing}"


def test_functional_exports():
    # psnr is a pre-0.9 alias the reference still exports; accept either name
    missing = [
        n for n in REF_FUNCTIONAL if not hasattr(ops, n) and not (n == "psnr" and hasattr(ops, "peak_signal_noise_ratio"))
    ]
    assert not missing, f"missing functional exports: {missing}"


def test_functional_alias_module():
    import metrics_tpu.functional as F

    assert F.accuracy is ops.accuracy
