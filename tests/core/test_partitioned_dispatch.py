"""Partition-aware collection dispatch (ISSUE 9): fused / bucketed / eager
member sets instead of whole-collection eager demotion.

Pins the dispatcher contract end to end: static classification of members,
partition stability across a streak (no churn, one dict-lookup steady state),
an untraceable straggler migrating *alone* while the rest keep a rebuilt
fused program (bitwise-identical to the eager loop), a ``batch_buckets``
member coexisting with the fused set on its own pow2-bucketed engine, the
``engine_stats()["partition"]`` view, and the observability surfaces
(tracer ``partition/*`` events, ``metrics_tpu_partition_*`` samples).
"""
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

import metrics_tpu
from metrics_tpu import (
    Accuracy,
    F1Score,
    MetricCollection,
    Precision,
    Recall,
    observability as obs,
)
from metrics_tpu.core import engine as engine_mod
from metrics_tpu.core.engine import (
    PATH_BUCKETED,
    PATH_EAGER,
    PATH_FUSED,
    classify_compute_member,
    classify_update_member,
)
from metrics_tpu.core.metric import Metric
from metrics_tpu.observability import instruments as _instruments


@pytest.fixture(autouse=True)
def _engines_on():
    metrics_tpu.set_compiled_update(True)
    metrics_tpu.set_fused_update(True)
    yield
    metrics_tpu.set_compiled_update(None)
    metrics_tpu.set_fused_update(None)


def _data(n=64, c=5, seed=0):
    rng = np.random.default_rng(seed)
    preds = jnp.asarray(rng.standard_normal((n, c)).astype(np.float32))
    target = jnp.asarray(rng.integers(0, c, n))
    return preds, target


class _HostReadbackMetric(Metric):
    """Untraceable update: the host readback breaks the fused trace probe."""

    full_state_update = False

    def __init__(self, **kw):
        super().__init__(**kw)
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds, target):
        if float(jnp.sum(preds)) > -1e30:  # host readback: untraceable
            self.total = self.total + jnp.sum(preds)

    def compute(self):
        return self.total


def _config2(c=5, **kw):
    return MetricCollection(
        {
            "acc": Accuracy(num_classes=c, average="micro"),
            "f1": F1Score(num_classes=c, average="macro"),
            "precision": Precision(num_classes=c, average="macro"),
            "recall": Recall(num_classes=c, average="macro"),
        },
        **kw,
    )


# ---------------------------------------------------------- classification ---
class TestClassification:
    def test_plain_metric_is_fused_both_ways(self):
        m = Accuracy()
        assert classify_update_member(m)[0] == PATH_FUSED
        assert classify_compute_member(m)[0] == PATH_FUSED

    def test_batch_buckets_member_is_bucketed(self):
        m = Accuracy(batch_buckets=True)
        path, reason = classify_update_member(m)
        assert path == PATH_BUCKETED
        assert "batch_buckets" in reason
        # bucketing only reshapes update inputs; compute still fuses
        assert classify_compute_member(m)[0] == PATH_FUSED

    def test_opt_out_is_eager(self):
        m = Accuracy(compiled_update=False, compiled_compute=False)
        assert classify_update_member(m)[0] == PATH_EAGER
        assert classify_compute_member(m)[0] == PATH_EAGER

    def test_compute_on_cpu_is_compute_eager_but_update_fused(self):
        m = Accuracy(compute_on_cpu=True)
        assert classify_update_member(m)[0] == PATH_FUSED
        assert classify_compute_member(m)[0] == PATH_EAGER

    def test_dist_sync_fn_is_compute_eager(self):
        m = Accuracy(dist_sync_fn=lambda state, group: state)
        assert classify_compute_member(m)[0] == PATH_EAGER


# -------------------------------------------------------------- stability ----
class TestPartitionStability:
    def test_streak_keeps_one_partition(self):
        coll = _config2()
        p, t = _data()
        for _ in range(8):
            coll.update(p, t)
        stats = coll._dispatcher.stats
        assert stats.builds == 1
        assert stats.repartitions == 0
        assert stats.migrations == 0
        assert stats.stable_hits >= 7
        part = coll._dispatcher._partition
        assert set(part.update_fused) == {g[0] for g in coll._groups}
        assert part.update_bucketed == () and part.update_eager == ()

    def test_reset_keeps_partition_and_executables(self):
        """The stable_hits regression pinned by Metric.reset()'s audit note:
        reset restores default leaves with the SAME shapes/dtypes, so neither
        the partition nor any cached executable is invalidated — a
        reset->update cycle costs zero recompiles, forever."""
        coll = _config2()
        p, t = _data()
        for _ in range(4):
            coll.update(p, t)
        warm = coll.engine_stats()["update"]
        warm_misses, warm_eager = warm.cache_misses, warm.eager_calls
        prev_hits = coll._dispatcher.stats.stable_hits
        for _cycle in range(3):
            coll.reset()
            for _ in range(4):
                coll.update(p, t)
            stats = coll._dispatcher.stats
            assert stats.builds == 1
            assert stats.repartitions == 0
            assert stats.migrations == 0
            assert stats.stable_hits > prev_hits
            prev_hits = stats.stable_hits
            engine = coll.engine_stats()["update"]
            assert engine.cache_misses == warm_misses  # no retrace after reset
            assert engine.eager_calls == warm_eager  # no warmup restart either

    def test_flag_flip_rebuilds_partition(self):
        coll = _config2()
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        assert coll._dispatcher.stats.repartitions == 0
        coll["acc"]._compiled_update = False  # placement change mid-run
        coll.update(p, t)
        stats = coll._dispatcher.stats
        assert stats.repartitions == 1
        part = coll._dispatcher._partition
        assert "acc" in part.update_eager
        assert "acc" not in part.update_fused

    def test_membership_change_drops_dispatcher(self):
        coll = _config2()
        p, t = _data()
        coll.update(p, t)
        assert coll._dispatcher is not None
        coll.add_metrics({"acc2": Accuracy()})
        assert coll._dispatcher is None
        coll.update(p, t)  # rebuilds cleanly with the new membership
        assert "acc2" in coll._dispatcher.partition_view()["update"]


# -------------------------------------------------- straggler coexistence ----
class TestStragglerCoexistence:
    def test_untraceable_member_bitwise_identical_to_eager(self):
        """The fused remainder + migrated straggler must reproduce the eager
        loop bit for bit — same stream, same computes."""
        part_coll = _config2()
        part_coll.add_metrics({"host": _HostReadbackMetric()})
        ref_coll = _config2(fused_update=False)
        ref_coll.add_metrics({"host": _HostReadbackMetric()})
        for seed in range(5):
            p, t = _data(seed=seed)
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                part_coll.update(p, t)
            ref_coll.update(p, t)
        part_res, ref_res = part_coll.compute(), ref_coll.compute()
        assert set(part_res) == set(ref_res)
        for key in ref_res:
            assert (
                np.asarray(part_res[key]).tobytes() == np.asarray(ref_res[key]).tobytes()
            ), key
        # and the fused remainder really ran compiled
        dispatcher = part_coll._dispatcher
        assert set(dispatcher._migrated_update) == {"host"}
        assert dispatcher.stats.migrations == 1
        assert part_coll._update_engine.broken is None
        assert part_coll._update_engine.stats.compiled_calls >= 1

    def test_bucketed_member_coexists_with_fused_set(self):
        part_coll = _config2()
        part_coll.add_metrics({"bucketed_acc": Accuracy(batch_buckets=True)})
        ref_coll = _config2(fused_update=False)
        ref_coll.add_metrics({"bucketed_acc": Accuracy(batch_buckets=True)})
        for seed in range(4):
            p, t = _data(seed=seed)
            part_coll.update(p, t)
            ref_coll.update(p, t)
        part = part_coll._dispatcher._partition
        assert part.update_bucketed == ("bucketed_acc",)
        assert "bucketed_acc" not in part.update_fused
        assert part_coll._update_engine.stats.compiled_calls >= 1
        # the bucketed member's own pow2 engine compiled too
        bucketed = part_coll["bucketed_acc"]
        assert bucketed._update_engine is not None
        assert bucketed._update_engine.broken is None
        part_res, ref_res = part_coll.compute(), ref_coll.compute()
        for key in ref_res:
            assert (
                np.asarray(part_res[key]).tobytes() == np.asarray(ref_res[key]).tobytes()
            ), key


# ------------------------------------------------------------ stats views ----
class TestPartitionViews:
    def test_collection_engine_stats_partition_shape(self):
        coll = _config2()
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        view = coll.engine_stats()["partition"]
        assert set(view) >= {
            "update", "compute", "builds", "repartitions", "migrations", "stable_hits",
        }
        assert set(view["update"]) == set(coll.keys())
        for info in view["update"].values():
            assert set(info) == {"path", "reason"}
            assert info["path"] in (PATH_FUSED, PATH_BUCKETED, PATH_EAGER)
        assert view["builds"] == 1

    def test_view_without_dispatch_is_transient(self):
        coll = _config2()
        view = coll.engine_stats()["partition"]
        assert view["builds"] == 0 and view["stable_hits"] == 0
        assert all(i["path"] == PATH_FUSED for i in view["update"].values())

    def test_metric_engine_stats_partition(self):
        m = Accuracy(batch_buckets=True)
        view = m.engine_stats()["partition"]
        assert view["update"]["path"] == PATH_BUCKETED
        assert view["compute"]["path"] == PATH_FUSED

    def test_broken_metric_engine_reports_eager(self):
        m = _HostReadbackMetric()
        p, t = _data()
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for _ in range(3):
                m.update(p, t)
        view = m.engine_stats()["partition"]
        assert view["update"]["path"] == PATH_EAGER
        assert "runtime fallback" in view["update"]["reason"]


# ----------------------------------------------------------- observability ---
class TestPartitionObservability:
    def test_build_and_migrate_events(self):
        p, t = _data()
        with obs.trace() as tracer:
            coll = _config2()
            coll.add_metrics({"host": _HostReadbackMetric()})
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                for _ in range(4):
                    coll.update(p, t)
        counts = tracer.counts_by_name()
        assert counts.get("partition/build", 0) == 1
        assert counts.get("partition/migrate", 0) == 1
        assert counts.get("partition/rebuild", 0) == 1  # post-migration rebuild
        migrate = next(e for e in tracer.events() if e.name == "partition/migrate")
        assert migrate.args["members"] == ["host"]
        assert migrate.args["kind"] == "update"
        build = next(e for e in tracer.events() if e.name == "partition/build")
        assert build.args["fused"] >= 1

    def test_partition_samples_in_registry(self):
        coll = _config2()
        coll.add_metrics({"bucketed_acc": Accuracy(batch_buckets=True)})
        p, t = _data()
        for _ in range(3):
            coll.update(p, t)
        samples = [
            s for s in _instruments.REGISTRY.samples()
            if s.name.startswith("metrics_tpu_partition_")
        ]
        names = {s.name for s in samples}
        assert {
            "metrics_tpu_partition_members",
            "metrics_tpu_partition_builds",
            "metrics_tpu_partition_stable_hits",
        } <= names
        # other live collections may be registered too; ours is the one with
        # a bucketed member, so assert its series exist rather than uniqueness
        member_samples = [
            s for s in samples
            if s.name == "metrics_tpu_partition_members"
            and s.labels["kind"] == "update"
            and s.labels["owner"] == "MetricCollection"
        ]
        assert any(s.labels["path"] == "bucketed" and s.value == 1.0 for s in member_samples)
        assert any(s.labels["path"] == "fused" and s.value == 4.0 for s in member_samples)
