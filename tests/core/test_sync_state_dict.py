"""Synced-save / unsync-restore semantics (reference tests/bases/test_ddp.py:135-241).

The documented distributed checkpoint flow: ``sync()`` swaps in the globally
reduced state (caching the local state), ``state_dict()`` then snapshots the
GLOBAL state, and ``unsync()`` restores the local accumulation so training
can continue. Sync here goes through an injected ``dist_sync_fn`` standing in
for the collective (the same hook a trainer framework injects).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from metrics_tpu import MeanMetric, SumMetric
from metrics_tpu.utils.exceptions import MetricsUserError

ALWAYS = lambda: True


def _world_sum(world: int):
    """A stand-in all-reduce: what `world` identical workers would produce."""

    def sync_fn(state, reductions, axes):
        return {k: jax.tree.map(lambda x: x * world, v) if not isinstance(v, list) else v for k, v in state.items()}

    return sync_fn


def test_sync_state_dict_unsync_roundtrip():
    metric = SumMetric(dist_sync_fn=_world_sum(4))
    metric.persistent(True)
    metric.update(jnp.asarray([1.0, 2.0]))  # local total: 3

    metric.sync(distributed_available=ALWAYS)
    assert float(metric.value) == pytest.approx(12.0)  # global view while synced
    global_snapshot = metric.state_dict()
    assert float(np.asarray(global_snapshot["value"])) == pytest.approx(12.0)

    metric.unsync()
    assert float(metric.value) == pytest.approx(3.0)  # local state restored

    # local accumulation continues from the LOCAL state, not the synced one
    metric.update(jnp.asarray(5.0))
    assert float(metric.compute()) == pytest.approx(8.0)

    # the saved global snapshot restores into a fresh metric
    resumed = SumMetric()
    resumed.persistent(True)
    resumed.load_state_dict(global_snapshot)
    assert float(resumed.compute()) == pytest.approx(12.0)


def test_sync_state_machine_guards():
    metric = MeanMetric(dist_sync_fn=_world_sum(2))
    metric.update(jnp.asarray(1.0))
    metric.sync(distributed_available=ALWAYS)
    with pytest.raises(MetricsUserError, match="already"):
        metric.sync(distributed_available=ALWAYS)
    metric.unsync()
    with pytest.raises(MetricsUserError, match="sync"):
        metric.unsync()
