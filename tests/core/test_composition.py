"""Metric arithmetic / CompositionalMetric matrix.

Reference parity: tests/bases/test_composition.py (554 LoC) — every operator
overload composes lazily, routes updates to both operands, and computes the
op over the children's computes. Exercised here over metric-vs-metric,
metric-vs-scalar, and reflected scalar-vs-metric operands plus the unary set.
"""
import operator

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.core.metric import CompositionalMetric
from tests.helpers.testers import DummyMetricDiff, DummyMetricSum

# (python operator, value-level oracle) — applied to compute() results 5.0 / 3.0
BINARY_OPS = [
    ("add", operator.add, lambda a, b: a + b),
    ("sub", operator.sub, lambda a, b: a - b),
    ("mul", operator.mul, lambda a, b: a * b),
    ("truediv", operator.truediv, lambda a, b: a / b),
    ("floordiv", operator.floordiv, lambda a, b: a // b),
    ("mod", operator.mod, lambda a, b: a % b),
    ("pow", operator.pow, lambda a, b: a**b),
    ("lt", operator.lt, lambda a, b: float(a < b)),
    ("le", operator.le, lambda a, b: float(a <= b)),
    ("gt", operator.gt, lambda a, b: float(a > b)),
    ("ge", operator.ge, lambda a, b: float(a >= b)),
    ("eq", operator.eq, lambda a, b: float(a == b)),
    ("ne", operator.ne, lambda a, b: float(a != b)),
]

A_VAL, B_VAL = 5.0, 3.0


def _sum_metric(value):
    m = DummyMetricSum()
    m.update(jnp.asarray(value))
    return m


@pytest.mark.parametrize("name,op,oracle", BINARY_OPS, ids=[c[0] for c in BINARY_OPS])
def test_binary_metric_metric(name, op, oracle):
    comp = op(_sum_metric(A_VAL), _sum_metric(B_VAL))
    assert isinstance(comp, CompositionalMetric)
    np.testing.assert_allclose(float(comp.compute()), oracle(A_VAL, B_VAL))


@pytest.mark.parametrize("name,op,oracle", BINARY_OPS, ids=[c[0] for c in BINARY_OPS])
def test_binary_metric_scalar(name, op, oracle):
    comp = op(_sum_metric(A_VAL), B_VAL)
    np.testing.assert_allclose(float(comp.compute()), oracle(A_VAL, B_VAL))


@pytest.mark.parametrize("name,op,oracle", BINARY_OPS, ids=[c[0] for c in BINARY_OPS])
def test_binary_reflected_scalar_metric(name, op, oracle):
    # scalar OP metric hits the __r<op>__ overloads (except comparisons,
    # which python resolves by swapping — the oracle swap covers both)
    comp = op(A_VAL, _sum_metric(B_VAL))
    np.testing.assert_allclose(float(comp.compute()), oracle(A_VAL, B_VAL))


def test_bitwise_ops():
    a, b = 0b1100, 0b1010
    ma = DummyMetricSum()
    ma.x = jnp.asarray(a)
    mb = DummyMetricSum()
    mb.x = jnp.asarray(b)
    assert int((ma & mb).compute()) == a & b
    assert int((ma | mb).compute()) == a | b
    assert int((ma ^ mb).compute()) == a ^ b
    assert int((a & mb).compute()) == a & b
    assert int((a | mb).compute()) == a | b
    assert int((a ^ mb).compute()) == a ^ b


def test_matmul():
    ma = DummyMetricSum()
    ma.x = jnp.asarray([1.0, 2.0])
    mb = DummyMetricSum()
    mb.x = jnp.asarray([3.0, 4.0])
    np.testing.assert_allclose(float((ma @ mb).compute()), 11.0)


def test_unary_ops():
    m = _sum_metric(-A_VAL)
    np.testing.assert_allclose(float(abs(m).compute()), A_VAL)
    # reference quirk kept for parity: __neg__ is -abs, __pos__ is abs
    np.testing.assert_allclose(float((-m).compute()), -A_VAL)
    np.testing.assert_allclose(float((+m).compute()), A_VAL)
    mi = DummyMetricSum()
    mi.x = jnp.asarray(0)
    assert bool((~mi).compute()) is True


def test_getitem():
    m = DummyMetricSum()
    m.update(jnp.asarray([1.0, 4.0, 9.0]))
    np.testing.assert_allclose(float(m[2].compute()), 9.0)


def test_update_routes_to_both_children():
    comp = _sum_metric(0.0) + _sum_metric(0.0)
    comp.update(jnp.asarray(2.0))
    comp.update(jnp.asarray(3.0))
    np.testing.assert_allclose(float(comp.compute()), 10.0)  # both sides saw 5


def test_update_filters_kwargs_per_child():
    # children with different update signatures receive only their kwargs
    comp = DummyMetricSum() + DummyMetricDiff()
    comp.update(x=jnp.asarray(4.0), y=jnp.asarray(1.0))
    np.testing.assert_allclose(float(comp.compute()), 3.0)  # (+4) + (-1)


def test_nested_composition():
    comp = (_sum_metric(A_VAL) + _sum_metric(B_VAL)) / 2.0
    np.testing.assert_allclose(float(comp.compute()), (A_VAL + B_VAL) / 2)


def test_reset_propagates():
    ma, mb = _sum_metric(A_VAL), _sum_metric(B_VAL)
    comp = ma + mb
    comp.reset()
    np.testing.assert_allclose(float(comp.compute()), 0.0)
    assert float(ma.x) == 0.0 and float(mb.x) == 0.0


def test_forward_composes_batch_values():
    comp = DummyMetricSum() + DummyMetricSum()
    out = comp(jnp.asarray(2.0))
    np.testing.assert_allclose(float(out), 4.0)  # batch value on both sides
    out = comp(jnp.asarray(3.0))
    np.testing.assert_allclose(float(out), 6.0)  # forward = batch-only value
    np.testing.assert_allclose(float(comp.compute()), 10.0)  # compute = accumulated


def test_repr_mentions_op_and_children():
    comp = DummyMetricSum() + DummyMetricSum()
    text = repr(comp)
    assert "CompositionalMetric" in text and "add" in text and "DummyMetricSum" in text
