"""Gated pycocotools differential for MeanAveragePrecision.

Decision record (VERDICT r2 item 6): this library reproduces the REFERENCE's
matching semantics (torchmetrics/detection/mean_ap.py:659-663), which exclude
area-ignored ground truths from matching entirely. pycocotools instead allows
detections to match ignored GTs and discounts those matches afterwards
(gtIgnore handling in cocoeval.py). The two agree exactly whenever every GT
falls inside the evaluated area range, and may diverge when GTs straddle area
boundaries; the divergence is the reference's (documented) deviation, kept
here for parity. This module quantifies it: strict parity on in-range
fixtures, a bounded delta on boundary fixtures. Skips when pycocotools is not
installed (it is absent in the offline image; the numpy oracle in oracle.py
covers the protocol there).
"""
import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")
pycocotools = pytest.importorskip("pycocotools")

from metrics_tpu.detection import MeanAveragePrecision  # noqa: E402

_rng = np.random.default_rng(23)


def _boxes(n, lo=8, hi=120):
    xy = _rng.uniform(0, 300, size=(n, 2))
    wh = _rng.uniform(lo, hi, size=(n, 2))
    return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)


def _fixture(n_img=8, n_det=12, n_gt=6, n_cls=4, gt_size=(8, 90)):
    preds, targets = [], []
    for _ in range(n_img):
        preds.append(
            {
                "boxes": _boxes(n_det),
                "scores": _rng.uniform(size=(n_det,)).astype(np.float32),
                "labels": _rng.integers(0, n_cls, size=(n_det,)).astype(np.int32),
            }
        )
        targets.append(
            {
                "boxes": _boxes(n_gt, *gt_size),
                "labels": _rng.integers(0, n_cls, size=(n_gt,)).astype(np.int32),
            }
        )
    return preds, targets


def _pycoco_stats(preds, targets):
    from pycocotools.coco import COCO
    from pycocotools.cocoeval import COCOeval

    cats = sorted({int(l) for t in targets for l in t["labels"]} | {int(l) for p in preds for l in p["labels"]})
    images, annotations, det_list = [], [], []
    ann_id = 1
    for img_id, (pred, tgt) in enumerate(zip(preds, targets), start=1):
        images.append({"id": img_id})
        for box, label in zip(tgt["boxes"], tgt["labels"]):
            x1, y1, x2, y2 = (float(v) for v in box)
            annotations.append(
                {
                    "id": ann_id,
                    "image_id": img_id,
                    "category_id": int(label),
                    "bbox": [x1, y1, x2 - x1, y2 - y1],
                    "area": (x2 - x1) * (y2 - y1),
                    "iscrowd": 0,
                }
            )
            ann_id += 1
        for box, score, label in zip(pred["boxes"], pred["scores"], pred["labels"]):
            x1, y1, x2, y2 = (float(v) for v in box)
            det_list.append(
                {
                    "image_id": img_id,
                    "category_id": int(label),
                    "bbox": [x1, y1, x2 - x1, y2 - y1],
                    "score": float(score),
                }
            )
    gt = COCO()
    gt.dataset = {"images": images, "annotations": annotations, "categories": [{"id": c} for c in cats]}
    gt.createIndex()
    dt = gt.loadRes(det_list)
    ev = COCOeval(gt, dt, iouType="bbox")
    ev.evaluate()
    ev.accumulate()
    ev.summarize()
    return ev.stats  # [map, map50, map75, map_s, map_m, map_l, mar1, mar10, mar100, mar_s, mar_m, mar_l]


_KEYS = ["map", "map_50", "map_75", "map_small", "map_medium", "map_large",
         "mar_1", "mar_10", "mar_100", "mar_small", "mar_medium", "mar_large"]


def _ours(preds, targets):
    metric = MeanAveragePrecision()
    metric.update(
        [{k: jnp.asarray(v) for k, v in p.items()} for p in preds],
        [{k: jnp.asarray(v) for k, v in t.items()} for t in targets],
    )
    out = metric.compute()
    return np.asarray([float(out[k]) for k in _KEYS])


def test_pycocotools_parity_in_range():
    """All GTs in the 'all' area range and well inside small/medium bins:
    the reference deviation cannot trigger, values must agree tightly."""
    preds, targets = _fixture(gt_size=(8, 90))
    got = _ours(preds, targets)
    want = _pycoco_stats(preds, targets)
    np.testing.assert_allclose(got, want, atol=1e-3)


def test_pycocotools_delta_boundary_areas():
    """GT boxes spanning area-range boundaries: quantify the documented
    deviation (reference excludes area-ignored GTs from matching) and keep it
    bounded on the headline 'all'-range metrics."""
    preds, targets = _fixture(gt_size=(20, 260))
    got = _ours(preds, targets)
    want = _pycoco_stats(preds, targets)
    # headline (area='all', maxDet=100) metrics are unaffected by per-range
    # ignore semantics on non-crowd data; size-binned metrics may deviate
    np.testing.assert_allclose(got[[0, 1, 2, 8]], want[[0, 1, 2, 8]], atol=1e-3)
    delta = np.max(np.abs(got - want))
    assert delta < 0.1, f"size-binned deviation vs pycocotools too large: {delta}"
