"""MeanAveragePrecision parity vs the independent numpy COCO oracle.

Reference parity: tests/detection/test_map.py (there vs pycocotools, not
installed here; tests/detection/oracle.py is the stand-in trusted reference,
written with ragged per-image loops vs the library's padded vmapped kernel).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu.detection import MeanAveragePrecision
from metrics_tpu.ops.detection import box_area, box_convert, box_iou, mask_iou
from tests.detection.oracle import box_iou_np, coco_map

_rng = np.random.default_rng(31)


def _random_boxes(n, img_size=640.0, rng=_rng):
    xy = rng.uniform(0, img_size * 0.8, size=(n, 2))
    wh = rng.uniform(4, img_size * 0.3, size=(n, 2))
    return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)


def _random_dataset(n_imgs=6, n_classes=4, max_gt=12, max_det=20, rng=_rng):
    preds, targets = [], []
    for _ in range(n_imgs):
        n_gt = int(rng.integers(0, max_gt))
        gt_boxes = _random_boxes(n_gt, rng=rng)
        gt_labels = rng.integers(0, n_classes, size=n_gt).astype(np.int32)
        # detections: jittered copies of gts (varying quality) + random noise
        det_boxes, det_labels, det_scores = [], [], []
        for b, l in zip(gt_boxes, gt_labels):
            if rng.random() < 0.8:
                jitter = rng.normal(0, rng.uniform(1, 25), size=4).astype(np.float32)
                det_boxes.append(b + jitter)
                det_labels.append(l if rng.random() < 0.9 else rng.integers(0, n_classes))
                det_scores.append(rng.uniform(0.3, 1.0))
        n_noise = int(rng.integers(0, max_det - len(det_boxes) + 1))
        for b in _random_boxes(n_noise, rng=rng):
            det_boxes.append(b)
            det_labels.append(rng.integers(0, n_classes))
            det_scores.append(rng.uniform(0.0, 0.7))
        det_boxes = np.asarray(det_boxes, dtype=np.float32).reshape(-1, 4)
        preds.append(
            {
                "boxes": det_boxes,
                "scores": np.asarray(det_scores, dtype=np.float32),
                "labels": np.asarray(det_labels, dtype=np.int32),
            }
        )
        targets.append({"boxes": gt_boxes, "labels": gt_labels})
    return preds, targets


# --------------------------------------------------------------------------- #
# box ops
# --------------------------------------------------------------------------- #
def test_box_iou_vs_numpy():
    a, b = _random_boxes(10), _random_boxes(7)
    np.testing.assert_allclose(np.asarray(box_iou(jnp.asarray(a), jnp.asarray(b))), box_iou_np(a, b), atol=1e-5)


def test_box_convert_roundtrip():
    boxes = _random_boxes(5)
    for fmt in ("xywh", "cxcywh"):
        converted = box_convert(jnp.asarray(boxes), "xyxy", fmt)
        back = box_convert(converted, fmt, "xyxy")
        np.testing.assert_allclose(np.asarray(back), boxes, atol=1e-4)


def test_box_area():
    boxes = jnp.asarray([[0.0, 0.0, 10.0, 5.0], [2.0, 2.0, 4.0, 8.0]])
    np.testing.assert_allclose(np.asarray(box_area(boxes)), [50.0, 12.0])


def test_mask_iou():
    m1 = np.zeros((2, 16, 16), dtype=bool)
    m2 = np.zeros((2, 16, 16), dtype=bool)
    m1[0, :8, :8] = True
    m2[0, :8, :8] = True  # identical -> 1
    m1[1, :8, :] = True
    m2[1, 4:12, :] = True  # half overlap: inter 4*16, union 12*16
    res = np.asarray(mask_iou(jnp.asarray(m1), jnp.asarray(m2)))
    np.testing.assert_allclose(res[0, 0], 1.0)
    np.testing.assert_allclose(res[1, 1], (4 * 16) / (12 * 16), atol=1e-6)


# --------------------------------------------------------------------------- #
# end-to-end mAP vs oracle
# --------------------------------------------------------------------------- #
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_map_random_datasets_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    preds, targets = _random_dataset(rng=rng)
    metric = MeanAveragePrecision()
    metric.update(preds, targets)
    got = {k: float(v) for k, v in metric.compute().items() if not k.endswith("per_class")}
    want = coco_map(preds, targets)
    for key, val in want.items():
        np.testing.assert_allclose(got[key], val, atol=1e-6, err_msg=key)


def test_map_perfect_predictions():
    boxes = _random_boxes(5)
    labels = np.arange(5, dtype=np.int32)
    preds = [{"boxes": boxes, "scores": np.full(5, 0.9, dtype=np.float32), "labels": labels}]
    targets = [{"boxes": boxes, "labels": labels}]
    metric = MeanAveragePrecision()
    metric.update(preds, targets)
    res = metric.compute()
    np.testing.assert_allclose(float(res["map"]), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(res["mar_100"]), 1.0, atol=1e-6)


def test_map_no_detections():
    targets = [{"boxes": _random_boxes(3), "labels": np.asarray([0, 1, 2], dtype=np.int32)}]
    preds = [{"boxes": np.zeros((0, 4), np.float32), "scores": np.zeros(0, np.float32), "labels": np.zeros(0, np.int32)}]
    metric = MeanAveragePrecision()
    metric.update(preds, targets)
    res = metric.compute()
    np.testing.assert_allclose(float(res["map"]), 0.0, atol=1e-6)


def test_map_empty_everything():
    metric = MeanAveragePrecision()
    preds = [{"boxes": np.zeros((0, 4), np.float32), "scores": np.zeros(0, np.float32), "labels": np.zeros(0, np.int32)}]
    targets = [{"boxes": np.zeros((0, 4), np.float32), "labels": np.zeros(0, np.int32)}]
    metric.update(preds, targets)
    res = metric.compute()
    assert float(res["map"]) == -1.0


def test_map_multiple_updates_match_single():
    preds, targets = _random_dataset(n_imgs=4)
    m1 = MeanAveragePrecision()
    m1.update(preds, targets)
    m2 = MeanAveragePrecision()
    m2.update(preds[:2], targets[:2])
    m2.update(preds[2:], targets[2:])
    r1, r2 = m1.compute(), m2.compute()
    for k in r1:
        np.testing.assert_allclose(np.asarray(r1[k]), np.asarray(r2[k]), atol=1e-6, err_msg=k)


def test_map_class_metrics():
    preds, targets = _random_dataset(n_imgs=4, n_classes=3)
    metric = MeanAveragePrecision(class_metrics=True)
    metric.update(preds, targets)
    res = metric.compute()
    n_classes = len(
        set(np.concatenate([p["labels"] for p in preds] + [t["labels"] for t in targets]).astype(int).tolist())
    )
    assert res["map_per_class"].shape == (n_classes,)
    assert res["mar_100_per_class"].shape == (n_classes,)
    # macro-average consistency: mean of per-class maps == overall map
    per_cls = np.asarray(res["map_per_class"])
    valid = per_cls[per_cls > -1]
    np.testing.assert_allclose(valid.mean(), float(res["map"]), atol=1e-6)


def test_map_box_format_conversion():
    preds, targets = _random_dataset(n_imgs=3)
    ref = MeanAveragePrecision()
    ref.update(preds, targets)

    def to_xywh(item):
        out = dict(item)
        b = item["boxes"]
        out["boxes"] = np.concatenate([b[:, :2], b[:, 2:] - b[:, :2]], axis=1) if len(b) else b
        return out

    alt = MeanAveragePrecision(box_format="xywh")
    alt.update([to_xywh(p) for p in preds], [to_xywh(t) for t in targets])
    np.testing.assert_allclose(float(ref.compute()["map"]), float(alt.compute()["map"]), atol=1e-5)


def test_map_segm():
    # two images with dense masks; perfect on one object, half-shifted on other
    def mk_mask(x0, x1):
        m = np.zeros((64, 64), dtype=bool)
        m[:, x0:x1] = True
        return m

    targets = [{"masks": np.stack([mk_mask(0, 32), mk_mask(40, 60)]), "labels": np.asarray([0, 1], np.int32)}]
    preds = [
        {
            "masks": np.stack([mk_mask(0, 32), mk_mask(50, 64)]),
            "scores": np.asarray([0.9, 0.8], np.float32),
            "labels": np.asarray([0, 1], np.int32),
        }
    ]
    metric = MeanAveragePrecision(iou_type="segm")
    metric.update(preds, targets)
    res = metric.compute()
    # class 0 perfect at all thresholds; class 1 IoU = 10/24 < 0.5 -> 0
    np.testing.assert_allclose(float(res["map"]), 0.5, atol=1e-6)


def test_map_custom_max_detection_thresholds():
    preds, targets = _random_dataset(n_imgs=3)
    metric = MeanAveragePrecision(max_detection_thresholds=[1, 10, 50])
    metric.update(preds, targets)
    res = metric.compute()
    assert "mar_50" in res and float(res["map"]) >= 0


def test_map_input_validation():
    metric = MeanAveragePrecision()
    with pytest.raises(ValueError, match="same length"):
        metric.update([], [{"boxes": np.zeros((0, 4)), "labels": np.zeros(0)}])
    with pytest.raises(ValueError, match="scores"):
        metric.update([{"boxes": np.zeros((1, 4)), "labels": np.zeros(1)}], [{"boxes": np.zeros((1, 4)), "labels": np.zeros(1)}])
    with pytest.raises(ValueError, match="box_format"):
        MeanAveragePrecision(box_format="bad")
