"""DDP grid for detection — the list-state gather path.

Reference parity: the reference runs MeanAveragePrecision with
ddp=[False, True] (tests/detection/test_map.py via testers.py:398-439). mAP
keeps per-image variable-length box/label/score lists, which is exactly the
state shape the gather path must preserve: merge must concatenate the ranks'
image lists without reordering boxes within an image or pairing detections
with the wrong ground truths. The merged compute must EXACTLY equal a single
process that saw every image.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import MeanAveragePrecision
from tests.helpers.testers import merge_world

WORLD = 4
N_IMAGES = 8

_rng = np.random.default_rng(99)


def _random_image(n_det: int, n_gt: int, n_classes: int = 3, size: float = 100.0):
    def boxes(n):
        xy = _rng.random((n, 2)) * (size / 2)
        wh = 5.0 + _rng.random((n, 2)) * (size / 3)
        return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)

    pred = dict(
        boxes=jnp.asarray(boxes(n_det)),
        scores=jnp.asarray(_rng.random(n_det).astype(np.float32)),
        labels=jnp.asarray(_rng.integers(0, n_classes, n_det)),
    )
    target = dict(
        boxes=jnp.asarray(boxes(n_gt)),
        labels=jnp.asarray(_rng.integers(0, n_classes, n_gt)),
    )
    return pred, target


def _random_mask_image(n_det: int, n_gt: int, n_classes: int = 3, hw: int = 24):
    def masks(n):
        out = np.zeros((n, hw, hw), dtype=bool)
        for i in range(n):
            x0, y0 = _rng.integers(0, hw - 8, 2)
            w, h = _rng.integers(4, 8, 2)
            out[i, y0:y0 + h, x0:x0 + w] = True
        return out

    pred = dict(
        masks=jnp.asarray(masks(n_det)),
        scores=jnp.asarray(_rng.random(n_det).astype(np.float32)),
        labels=jnp.asarray(_rng.integers(0, n_classes, n_det)),
    )
    target = dict(
        masks=jnp.asarray(masks(n_gt)),
        labels=jnp.asarray(_rng.integers(0, n_classes, n_gt)),
    )
    return pred, target


_BBOX_IMAGES = [_random_image(_rng.integers(1, 6), _rng.integers(1, 5)) for _ in range(N_IMAGES)]
_SEGM_IMAGES = [_random_mask_image(_rng.integers(1, 4), _rng.integers(1, 4)) for _ in range(N_IMAGES)]


def _assert_map_equal(got: dict, want: dict):
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(
            np.asarray(got[k], dtype=np.float64), np.asarray(want[k], dtype=np.float64),
            atol=1e-6, err_msg=k,
        )


@pytest.mark.parametrize(
    "iou_type,images,kwargs",
    [
        ("bbox", _BBOX_IMAGES, {}),
        ("bbox", _BBOX_IMAGES, {"class_metrics": True}),
        ("segm", _SEGM_IMAGES, {}),
    ],
    ids=["bbox", "bbox-classwise", "segm"],
)
def test_map_ddp_merge_equals_single_process(iou_type, images, kwargs):
    preds = [p for p, _ in images]
    targets = [t for _, t in images]

    single = MeanAveragePrecision(iou_type=iou_type, **kwargs)
    single.update(preds, targets)
    want = single.compute()

    ranks = [MeanAveragePrecision(iou_type=iou_type, **kwargs) for _ in range(WORLD)]
    for rank in range(WORLD):
        ranks[rank].update(preds[rank::WORLD], targets[rank::WORLD])
    got = merge_world(ranks).compute()

    _assert_map_equal(got, want)


def test_map_ddp_uneven_ranks():
    """Ranks with different image counts (the real-world tail batch)."""
    preds = [p for p, _ in _BBOX_IMAGES]
    targets = [t for _, t in _BBOX_IMAGES]

    single = MeanAveragePrecision()
    single.update(preds, targets)
    want = single.compute()

    splits = [0, 1, 4, 8]  # rank sizes 1, 3, 4 — rank 0 empty is exercised too
    ranks = [MeanAveragePrecision() for _ in range(len(splits) - 1 + 1)]
    ranks[0].update([], [])  # a rank that saw no data must not poison the merge
    for i in range(len(splits) - 1):
        ranks[i + 1].update(preds[splits[i]:splits[i + 1]], targets[splits[i]:splits[i + 1]])
    got = merge_world(ranks).compute()

    _assert_map_equal(got, want)
