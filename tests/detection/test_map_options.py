"""MeanAveragePrecision threshold-option grid.

Reference analog: detection/mean_ap.py:199 constructor options
(iou_thresholds, rec_thresholds, max_detection_thresholds, box_format).
The reference test suite exercises these through tests/detection/test_map.py's
pycocotools comparisons; here custom threshold lists are pinned by internal
consistency against the default-grid results (single-threshold runs must
reproduce map_50/map_75 exactly; mAP is monotone non-increasing in the IoU
threshold; rec_thresholds given explicitly at the COCO grid must be a no-op).
"""
import numpy as np
import pytest

from metrics_tpu.detection import MeanAveragePrecision
from tests.detection.test_map import _random_dataset


def _value(preds, targets, key="map", **kwargs):
    m = MeanAveragePrecision(**kwargs)
    m.update(preds, targets)
    return float(m.compute()[key])


@pytest.fixture(scope="module")
def data():
    return _random_dataset(rng=np.random.default_rng(42))


@pytest.mark.parametrize("thr,key", [(0.5, "map_50"), (0.75, "map_75")])
def test_single_iou_threshold_reproduces_default_column(data, thr, key):
    preds, targets = data
    single = _value(preds, targets, iou_thresholds=[thr])
    default_col = _value(preds, targets, key=key)
    np.testing.assert_allclose(single, default_col, atol=1e-6)


def test_map_monotone_in_iou_threshold(data):
    preds, targets = data
    vals = [_value(preds, targets, iou_thresholds=[t]) for t in (0.3, 0.5, 0.7, 0.9)]
    assert all(a >= b - 1e-9 for a, b in zip(vals, vals[1:])), vals


def test_explicit_coco_rec_thresholds_noop(data):
    preds, targets = data
    default = _value(preds, targets)
    explicit = _value(preds, targets, rec_thresholds=list(np.linspace(0.0, 1.0, 101)))
    np.testing.assert_allclose(explicit, default, atol=1e-6)


def test_coarse_rec_thresholds_still_bounded(data):
    preds, targets = data
    coarse = _value(preds, targets, rec_thresholds=[0.0, 0.5, 1.0])
    assert 0.0 <= coarse <= 1.0


def test_max_detection_thresholds_monotone(data):
    """mar_k is non-decreasing in k (more detections can only help recall)."""
    preds, targets = data
    m = MeanAveragePrecision(max_detection_thresholds=[1, 10, 100])
    m.update(preds, targets)
    res = {k: float(v) for k, v in m.compute().items()}
    assert res["mar_1"] <= res["mar_10"] + 1e-9 <= res["mar_100"] + 2e-9


def test_custom_iou_grid_matches_mean_of_singles(data):
    """A two-threshold grid averages the per-threshold AP columns."""
    preds, targets = data
    pair = _value(preds, targets, iou_thresholds=[0.5, 0.75])
    singles = [_value(preds, targets, iou_thresholds=[t]) for t in (0.5, 0.75)]
    np.testing.assert_allclose(pair, np.mean(singles), atol=1e-6)


@pytest.mark.parametrize("fmt", ["xywh", "cxcywh"])
def test_box_format_equivalence_full_dataset(data, fmt):
    """Format conversion on the whole random dataset, not just one box."""
    preds, targets = data

    def convert(boxes):
        b = np.asarray(boxes, dtype=np.float32).reshape(-1, 4)
        x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
        w, h = x2 - x1, y2 - y1
        if fmt == "xywh":
            return np.stack([x1, y1, w, h], axis=1)
        return np.stack([x1 + w / 2, y1 + h / 2, w, h], axis=1)

    conv_preds = [{**p, "boxes": convert(p["boxes"])} for p in preds]
    conv_targets = [{**t, "boxes": convert(t["boxes"])} for t in targets]
    np.testing.assert_allclose(
        _value(conv_preds, conv_targets, box_format=fmt), _value(preds, targets), atol=1e-6
    )


def test_invalid_iou_type_raises():
    with pytest.raises(ValueError):
        MeanAveragePrecision(iou_type="polygon")
