"""Independent numpy COCO mAP oracle (dynamic shapes, per-image loops).

A straightforward reimplementation of the COCO evaluation protocol as the
reference implements it (torchmetrics/detection/mean_ap.py:537-871): ragged
per-image/per-class greedy matching with Python loops — deliberately the
opposite code shape from the library's padded/vmapped kernel, so the two
paths cross-check each other (tests/helpers parity philosophy, SURVEY.md §4).
"""
import numpy as np

IOU_THRS = np.round(np.arange(0.5, 1.0, 0.05), 2)
REC_THRS = np.linspace(0, 1, 101)
AREA_RANGES = {"all": (0, 1e10), "small": (0, 32 ** 2), "medium": (32 ** 2, 96 ** 2), "large": (96 ** 2, 1e10)}
MAX_DETS = [1, 10, 100]


def box_iou_np(a, b):
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return np.where(union > 0, inter / union, 0.0)


def _evaluate_image(det_boxes, det_scores, gt_boxes, area_range, max_det):
    """Greedy matching for one image+class; returns dt/gt data or None."""
    if len(det_boxes) == 0 and len(gt_boxes) == 0:
        return None
    order = np.argsort(-det_scores, kind="stable")[:max_det]
    det_boxes = det_boxes[order]
    det_scores = det_scores[order]
    gt_areas = (gt_boxes[:, 2] - gt_boxes[:, 0]) * (gt_boxes[:, 3] - gt_boxes[:, 1]) if len(gt_boxes) else np.zeros(0)
    gt_ignore = (gt_areas < area_range[0]) | (gt_areas > area_range[1])
    T, D, G = len(IOU_THRS), len(det_boxes), len(gt_boxes)
    dt_m = np.zeros((T, D), dtype=bool)
    gt_m = np.zeros((T, G), dtype=bool)
    if D and G:
        ious = box_iou_np(det_boxes, gt_boxes)
        for ti, thr in enumerate(IOU_THRS):
            for d in range(D):
                cand = ~gt_m[ti] & ~gt_ignore
                vals = ious[d] * cand
                if vals.size == 0:
                    continue
                m = int(np.argmax(vals))
                if vals[m] > thr:
                    dt_m[ti, d] = True
                    gt_m[ti, m] = True
    det_areas = (det_boxes[:, 2] - det_boxes[:, 0]) * (det_boxes[:, 3] - det_boxes[:, 1]) if D else np.zeros(0)
    det_area_ignore = (det_areas < area_range[0]) | (det_areas > area_range[1])
    dt_ig = (~dt_m) & det_area_ignore[None, :]
    return {"dtm": dt_m, "dtIg": dt_ig, "scores": det_scores, "gtIg": gt_ignore}


def coco_map(preds, targets):
    """preds/targets: lists of dicts with numpy boxes/scores/labels (xyxy)."""
    classes = sorted(
        set(np.concatenate([p["labels"] for p in preds] + [t["labels"] for t in targets]).astype(int).tolist())
    )
    K, A, M, T, R = len(classes), len(AREA_RANGES), len(MAX_DETS), len(IOU_THRS), len(REC_THRS)
    precision = -np.ones((T, R, K, A, M))
    recall = -np.ones((T, K, A, M))

    for ki, cls in enumerate(classes):
        for ai, area in enumerate(AREA_RANGES.values()):
            evals = []
            for p, t in zip(preds, targets):
                dm = p["labels"] == cls
                gm = t["labels"] == cls
                e = _evaluate_image(p["boxes"][dm], p["scores"][dm], t["boxes"][gm], area, MAX_DETS[-1])
                if e is not None:
                    evals.append(e)
            if not evals:
                continue
            npig = int(sum((~e["gtIg"]).sum() for e in evals))
            if npig == 0:
                continue
            for mi, mdet in enumerate(MAX_DETS):
                scores = np.concatenate([e["scores"][:mdet] for e in evals])
                dtm = np.concatenate([e["dtm"][:, :mdet] for e in evals], axis=1)
                dtig = np.concatenate([e["dtIg"][:, :mdet] for e in evals], axis=1)
                inds = np.argsort(-scores, kind="stable")
                dtm, dtig = dtm[:, inds], dtig[:, inds]
                tps = dtm & ~dtig
                fps = ~dtm & ~dtig
                tp_sum = np.cumsum(tps, axis=1).astype(float)
                fp_sum = np.cumsum(fps, axis=1).astype(float)
                for ti in range(T):
                    tp, fp = tp_sum[ti], fp_sum[ti]
                    nd = len(tp)
                    rc = tp / npig
                    pr = tp / (fp + tp + np.finfo(np.float64).eps)
                    recall[ti, ki, ai, mi] = rc[-1] if nd else 0
                    pr = np.maximum.accumulate(pr[::-1])[::-1]
                    i_thr = np.searchsorted(rc, REC_THRS, side="left")
                    num = int(i_thr.argmax()) if (i_thr.size and i_thr.max() >= nd) else R
                    prec = np.zeros(R)
                    prec[:num] = pr[i_thr[:num]]
                    precision[ti, :, ki, ai, mi] = prec

    def summarize(avg_prec, iou=None, area="all", mdet=100):
        ai = list(AREA_RANGES).index(area)
        mi = MAX_DETS.index(mdet)
        arr = precision[..., ai, mi] if avg_prec else recall[..., ai, mi]
        if iou is not None:
            arr = arr[list(IOU_THRS).index(iou)]
        valid = arr[arr > -1]
        return -1.0 if valid.size == 0 else float(valid.mean())

    return {
        "map": summarize(True),
        "map_50": summarize(True, iou=0.5),
        "map_75": summarize(True, iou=0.75),
        "map_small": summarize(True, area="small"),
        "map_medium": summarize(True, area="medium"),
        "map_large": summarize(True, area="large"),
        "mar_1": summarize(False, mdet=1),
        "mar_10": summarize(False, mdet=10),
        "mar_100": summarize(False, mdet=100),
        "mar_small": summarize(False, area="small"),
        "mar_medium": summarize(False, area="medium"),
        "mar_large": summarize(False, area="large"),
    }
