"""RLE mask codec and RLE ingestion for segm mAP.

Reference parity: torchmetrics/detection/mean_ap.py:127-142 evaluates masks
through pycocotools RLE. Here RLE is an ingestion format: decode host-side
(ops/detection/rle.py), evaluate densely on device. Differential against
pycocotools when installed; hand-built fixtures otherwise.
"""
import importlib.util

import numpy as np
import pytest

import jax.numpy as jnp

from metrics_tpu import MeanAveragePrecision
from metrics_tpu.ops.detection.rle import (
    is_rle,
    masks_from_rle_list,
    rle_decode,
    rle_encode,
)

_HAS_PYCOCO = importlib.util.find_spec("pycocotools") is not None

_rng = np.random.default_rng(5)


def _random_mask(h=17, w=23, p=0.3):
    return _rng.random((h, w)) < p


# --------------------------------------------------------------------------- #
# codec
# --------------------------------------------------------------------------- #
def test_uncompressed_roundtrip_hand_fixture():
    # 2x3 mask, column-major runs: col0 = [1,0], col1 = [0,1], col2 = [1,1]
    mask = np.asarray([[1, 0, 1], [0, 1, 1]], dtype=bool)
    rle = rle_encode(mask, compress=False)
    assert rle["size"] == [2, 3]
    # flat(F) = 1,0,0,1,1,1 -> starts with fg => leading 0 run
    assert rle["counts"] == [0, 1, 2, 3]
    np.testing.assert_array_equal(rle_decode(rle), mask)


@pytest.mark.parametrize("compress", [False, True], ids=["plain", "compressed"])
@pytest.mark.parametrize("shape", [(1, 1), (7, 3), (17, 23), (64, 64)], ids=str)
def test_roundtrip_random(compress, shape):
    mask = _rng.random(shape) < 0.4
    np.testing.assert_array_equal(rle_decode(rle_encode(mask, compress=compress)), mask)


def test_roundtrip_extremes():
    for mask in (np.zeros((5, 4), bool), np.ones((5, 4), bool)):
        for compress in (False, True):
            np.testing.assert_array_equal(rle_decode(rle_encode(mask, compress=compress)), mask)


def test_decode_validates():
    with pytest.raises(ValueError, match="size"):
        rle_decode({"counts": [4]})
    with pytest.raises(ValueError, match="pixels"):
        rle_decode({"size": [2, 2], "counts": [3]})
    with pytest.raises(ValueError, match="share a size"):
        masks_from_rle_list([rle_encode(np.zeros((2, 2), bool)), rle_encode(np.zeros((3, 3), bool))])


def test_is_rle():
    assert is_rle({"size": [2, 2], "counts": [4]})
    assert not is_rle({"masks": 1})
    assert not is_rle([1, 2])


@pytest.mark.skipif(not _HAS_PYCOCO, reason="pycocotools absent")
def test_codec_differential_pycocotools():
    from pycocotools import mask as mask_utils

    for _ in range(20):
        m = _random_mask(h=int(_rng.integers(1, 40)), w=int(_rng.integers(1, 40)))
        theirs = mask_utils.encode(np.asfortranarray(m.astype(np.uint8)))
        ours = rle_encode(m, compress=True)
        assert ours["counts"] == theirs["counts"], "compressed byte strings differ"
        np.testing.assert_array_equal(rle_decode(theirs), m)


# --------------------------------------------------------------------------- #
# mAP ingestion: RLE input == dense input
# --------------------------------------------------------------------------- #
def _mask_image(n, hw=24):
    out = np.zeros((n, hw, hw), dtype=bool)
    for i in range(n):
        x0, y0 = _rng.integers(0, hw - 8, 2)
        w, h = _rng.integers(4, 8, 2)
        out[i, y0:y0 + h, x0:x0 + w] = True
    return out


@pytest.mark.parametrize("compress", [False, True], ids=["plain", "compressed"])
def test_segm_map_from_rle_equals_dense(compress):
    n_imgs = 4
    preds_dense, targets_dense, preds_rle, targets_rle = [], [], [], []
    for _ in range(n_imgs):
        nd, ng = int(_rng.integers(1, 4)), int(_rng.integers(1, 4))
        dm, gm = _mask_image(nd), _mask_image(ng)
        scores = _rng.random(nd).astype(np.float32)
        dl = _rng.integers(0, 2, nd)
        gl = _rng.integers(0, 2, ng)
        preds_dense.append(dict(masks=jnp.asarray(dm), scores=jnp.asarray(scores), labels=jnp.asarray(dl)))
        targets_dense.append(dict(masks=jnp.asarray(gm), labels=jnp.asarray(gl)))
        preds_rle.append(dict(
            masks=[rle_encode(m, compress=compress) for m in dm],
            scores=jnp.asarray(scores), labels=jnp.asarray(dl),
        ))
        targets_rle.append(dict(
            masks=[rle_encode(m, compress=compress) for m in gm], labels=jnp.asarray(gl),
        ))

    m_dense = MeanAveragePrecision(iou_type="segm")
    m_dense.update(preds_dense, targets_dense)
    want = m_dense.compute()

    m_rle = MeanAveragePrecision(iou_type="segm")
    m_rle.update(preds_rle, targets_rle)
    got = m_rle.compute()

    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(
            np.asarray(got[k], np.float64), np.asarray(want[k], np.float64), atol=1e-6, err_msg=k,
        )
