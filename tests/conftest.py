"""Test configuration: force an 8-device CPU mesh (SURVEY.md §4 TPU translation).

The container's sitecustomize imports jax and registers the TPU platform before
pytest starts, so env-var selection is too late; instead we update the (lazy)
platform config and XLA flags before the first backend initialization.
"""
import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

NUM_DEVICES = len(jax.devices())


def import_reference_torchmetrics(allow_module_level: bool = False):
    """Import the reference checkout's torchmetrics (skip if unavailable).

    One shared copy of the pkg_resources shim + sys.path dance used by the
    reference-differential tests. Pass ``allow_module_level=True`` when
    calling at import time (module-gated suites).
    """
    import pathlib
    import sys
    import types

    import pytest

    if not pathlib.Path("/root/reference/torchmetrics").exists():
        pytest.skip("reference checkout unavailable", allow_module_level=allow_module_level)
    pytest.importorskip("torch")
    if "pkg_resources" not in sys.modules:  # removed from modern setuptools
        shim = types.ModuleType("pkg_resources")
        shim.DistributionNotFound = type("DistributionNotFound", (Exception,), {})

        def get_distribution(name):
            raise shim.DistributionNotFound(name)

        shim.get_distribution = get_distribution
        sys.modules["pkg_resources"] = shim
    if "/root/reference" not in sys.path:
        # APPEND: the reference has its own tests/ package that must not shadow ours
        sys.path.append("/root/reference")
    import torchmetrics

    return torchmetrics


def reference_functional():
    """(torch, torchmetrics.functional) from the reference checkout — the
    shared entry point for the per-domain reference-differential suites."""
    import_reference_torchmetrics()
    import torch
    import torchmetrics.functional as F

    return torch, F


def reference_modular():
    """(torch, torchmetrics) module pair from the reference checkout — the
    class-level counterpart of reference_functional()."""
    tm = import_reference_torchmetrics()
    import torch

    return torch, tm
