"""Test configuration: force an 8-device CPU mesh (SURVEY.md §4 TPU translation).

The container's sitecustomize imports jax and registers the TPU platform before
pytest starts, so env-var selection is too late; instead we update the (lazy)
platform config and XLA flags before the first backend initialization.
"""
import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

NUM_DEVICES = len(jax.devices())
