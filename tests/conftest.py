"""Test configuration: force an 8-device CPU mesh (SURVEY.md §4 TPU translation).

The container's sitecustomize imports jax and registers the TPU platform before
pytest starts, so env-var selection is too late; instead we update the (lazy)
platform config and XLA flags before the first backend initialization.
"""
import os

os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")

NUM_DEVICES = len(jax.devices())


def import_reference_torchmetrics(allow_module_level: bool = False):
    """Import the reference checkout's torchmetrics (skip if unavailable).

    One shared copy of the pkg_resources shim + sys.path dance used by the
    reference-differential tests. Pass ``allow_module_level=True`` when
    calling at import time (module-gated suites).
    """
    import pathlib
    import sys
    import types

    import pytest

    if not pathlib.Path("/root/reference/torchmetrics").exists():
        pytest.skip("reference checkout unavailable", allow_module_level=allow_module_level)
    pytest.importorskip("torch")
    if "pkg_resources" not in sys.modules:  # removed from modern setuptools
        shim = types.ModuleType("pkg_resources")
        shim.DistributionNotFound = type("DistributionNotFound", (Exception,), {})

        def get_distribution(name):
            raise shim.DistributionNotFound(name)

        shim.get_distribution = get_distribution
        sys.modules["pkg_resources"] = shim
    if "/root/reference" not in sys.path:
        # APPEND: the reference has its own tests/ package that must not shadow ours
        sys.path.append("/root/reference")
    import torchmetrics

    return torchmetrics


def reference_functional():
    """(torch, torchmetrics.functional) from the reference checkout — the
    shared entry point for the per-domain reference-differential suites."""
    import_reference_torchmetrics()
    import torch
    import torchmetrics.functional as F

    return torch, F


def reference_modular():
    """(torch, torchmetrics) module pair from the reference checkout — the
    class-level counterpart of reference_functional()."""
    tm = import_reference_torchmetrics()
    import torch

    return torch, tm


_MULTIPROCESS_PROBE_RESULT = None  # cached: "" = available, else the skip reason


def multiprocess_backend_skip_reason() -> str:
    """Probe (once per session) whether a real 2-process ``jax.distributed``
    run can execute a cross-process collective in this environment.

    Sandboxes commonly fail this in one of two ways: the coordinator cannot
    launch/bind, or — as with CPU-only jaxlib builds — distributed init works
    but collectives raise ``Multiprocess computations aren't implemented on
    the CPU backend``. Returns "" when multi-process collectives work, else a
    skip reason including the child's last error line.
    """
    global _MULTIPROCESS_PROBE_RESULT
    if _MULTIPROCESS_PROBE_RESULT is not None:
        return _MULTIPROCESS_PROBE_RESULT

    import socket
    import subprocess
    import sys
    import tempfile
    import textwrap

    child_src = textwrap.dedent(
        """
        import sys
        import jax
        jax.config.update("jax_platforms", "cpu")
        proc_id, port = int(sys.argv[1]), sys.argv[2]
        jax.distributed.initialize(f"localhost:{port}", num_processes=2, process_id=proc_id)
        import jax.numpy as jnp
        from jax.experimental import multihost_utils
        out = multihost_utils.process_allgather(jnp.asarray([proc_id]))
        assert out.shape[0] == 2, out
        print("PROBE_OK", proc_id)
        """
    )
    with tempfile.NamedTemporaryFile("w", suffix="_mp_probe.py", delete=False) as f:
        f.write(child_src)
        child_path = f.name
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env["XLA_FLAGS"] = ""  # one local device per process
    env.pop("JAX_PLATFORMS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, child_path, str(rank), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        try:
            outs.append(p.communicate(timeout=120)[0])
        except subprocess.TimeoutExpired:
            p.kill()
            outs.append("probe timed out after 120s")
    if all(p.returncode == 0 for p in procs) and all("PROBE_OK" in o for o in outs):
        _MULTIPROCESS_PROBE_RESULT = ""
    else:
        err_lines = [ln for o in outs for ln in o.strip().splitlines() if ln.strip()]
        last_err = err_lines[-1] if err_lines else "no output"
        _MULTIPROCESS_PROBE_RESULT = (
            "multi-process jax backend unavailable in this environment "
            f"(2-process collective probe failed: {last_err})"
        )
    return _MULTIPROCESS_PROBE_RESULT


@pytest.fixture
def multiprocess_backend():
    """Skip the test when real 2-process jax collectives can't run here."""
    reason = multiprocess_backend_skip_reason()
    if reason:
        pytest.skip(reason)
