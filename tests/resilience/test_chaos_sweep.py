"""The resilience layer's headline property, end to end: an update / sync /
checkpoint / restore / compute loop run under a seeded fault schedule —
engine dispatch faults (fallback + probation), flaky storage (retry), a torn
checkpoint write on a sacrificial step (restore fallback) — produces a final
``compute()`` that is **bitwise-equal** to the fault-free run.

The quick single-seed case runs in the tier-1 gate; the full 3-seed sweep is
``slow``."""
import contextlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import Accuracy, MetricCollection, Precision, Recall, set_probation
from metrics_tpu.checkpoint import (
    InMemoryStorage,
    available_steps,
    restore_checkpoint,
    save_checkpoint,
    use_retry_policy,
    use_storage,
)
from metrics_tpu.resilience import FaultSpec, RetryPolicy
from metrics_tpu.resilience import chaos

pytestmark = [pytest.mark.chaos, pytest.mark.filterwarnings("ignore::UserWarning")]

NUM_CLASSES = 8
STEPS = 16
FAST = RetryPolicy(backoff_base_s=0.0, backoff_max_s=0.0, jitter=0.0, seed=0)


def _build():
    return MetricCollection(
        {
            "acc": Accuracy(num_classes=NUM_CLASSES, average="micro"),
            "prec": Precision(num_classes=NUM_CLASSES, average="macro"),
            "rec": Recall(num_classes=NUM_CLASSES, average="macro"),
        }
    )


def _batches():
    rng = np.random.default_rng(1234)
    return [
        (
            jnp.asarray(rng.normal(size=(32, NUM_CLASSES)), dtype=jnp.float32),
            jnp.asarray(rng.integers(0, NUM_CLASSES, size=(32,)), dtype=jnp.int32),
        )
        for _ in range(STEPS)
    ]


def _specs():
    return [
        # one steady-state dispatch fault: fallback + migration + probation
        FaultSpec("engine/dispatch", nth=4, times=1),
        # flaky storage, deterministically recovered by the retry wrapper
        FaultSpec("storage/write", every=7, times=4),
        FaultSpec("storage/read", every=5, times=4),
        # seed-sensitive read flakiness (still transient, still retried)
        FaultSpec("storage/read", probability=0.2, times=3),
        # tear the LAST save of the loop: restore must fall back to the
        # previous verifiable step — which the loop makes state-identical by
        # saving the same state twice
        FaultSpec("ckpt/write", kind="partial_write", nth=5, fraction=0.5),
    ]


def _eval_loop(seed=None):
    """updates -> save -> save-again (torn under chaos) -> restore-latest ->
    compute, optionally under a seeded fault plan. Returns compute() bytes."""
    batches = _batches()
    store = InMemoryStorage()
    set_probation(3)
    try:
        with contextlib.ExitStack() as stack:
            stack.enter_context(use_storage(store))
            stack.enter_context(use_retry_policy(FAST))
            plan_ = None
            if seed is not None:
                plan_ = stack.enter_context(chaos.plan(_specs(), seed=seed))
            coll = _build()
            for logits, target in batches:
                coll.update(logits, target)
            save_checkpoint(coll, "sweep/ckpt", world_size=1, shard_index=0)
            # second save of the same state: under chaos its npz write is
            # torn (ckpt/write partial), so restore-latest must fall back
            save_checkpoint(coll, "sweep/ckpt", world_size=1, shard_index=0)
            fresh = _build()
            info = restore_checkpoint(fresh, "sweep/ckpt", host_count=1)
            values = fresh.compute()
            steps = available_steps("sweep/ckpt")
            fired = plan_.fired() if plan_ is not None else 0
        return (
            {k: np.asarray(v).tobytes() for k, v in values.items()},
            {"fired": fired, "restored_step": info.step,
             "fallback_from": info.fallback_from, "steps": steps},
        )
    finally:
        set_probation(None)


def test_single_seed_chaos_loop_is_bitwise_equal():
    baseline, _ = _eval_loop(seed=None)
    faulted, stats = _eval_loop(seed=0)
    assert stats["fired"] > 0, "the plan must actually inject faults"
    assert faulted == baseline

    # and the schedule replays identically
    again, stats2 = _eval_loop(seed=0)
    assert again == faulted
    assert stats2 == stats


def test_torn_second_save_forces_restore_fallback():
    _, stats = _eval_loop(seed=0)
    # both saves committed, but the newest is torn: restore fell back
    assert len(stats["steps"]) == 2
    assert stats["restored_step"] == stats["steps"][0]
    assert stats["fallback_from"] == stats["steps"][1]


@pytest.mark.slow
def test_three_seed_sweep_is_bitwise_equal():
    baseline, _ = _eval_loop(seed=None)
    for seed in (0, 1, 2):
        faulted, stats = _eval_loop(seed=seed)
        assert stats["fired"] > 0
        assert faulted == baseline, f"seed {seed} diverged from the fault-free run"


# --------------------------------------------------------------------------- #
# ISSUE-15: the sync/incremental site — in-streak emissions under chaos
# --------------------------------------------------------------------------- #
def _incremental_streak(seed=None, steps=4):
    """A pmap incremental streak over integer-sum state; returns the final
    globally-synced bytes plus how many emission faults fired."""
    from metrics_tpu.parallel.sync import (
        advance_incremental, finalize_incremental_state, init_incremental,
    )

    reds = {"hits": "sum"}
    modes = {"hits": "incremental"}
    n_dev = jax.local_device_count()

    def run(xs):
        carry = init_incremental(
            {"hits": jnp.zeros((4,), jnp.int32)}, reds, modes=modes, sync_every=1
        )
        for i in range(steps):
            state = {"hits": carry.state["hits"] + xs[i]}
            carry = advance_incremental(carry, state, reds, "i", modes=modes)
        return finalize_incremental_state(carry, reds, "i", modes=modes)["hits"]

    xs = jnp.arange(n_dev * steps * 4, dtype=jnp.int32).reshape(n_dev, steps, 4)
    with contextlib.ExitStack() as stack:
        plan_ = None
        if seed is not None:
            plan_ = stack.enter_context(
                chaos.plan(
                    [FaultSpec("sync/incremental", kind="latency",
                               probability=0.5, latency_s=0.0)],
                    seed=seed,
                )
            )
        out = np.asarray(jax.pmap(run, axis_name="i")(xs)).tobytes()
        fired = plan_.fired("sync/incremental") if plan_ is not None else 0
    return out, fired


def test_incremental_emission_fault_fires_at_trace_time():
    from metrics_tpu.parallel.sync import (
        advance_incremental, init_incremental,
    )

    reds = {"hits": "sum"}
    modes = {"hits": "incremental"}
    n_dev = jax.local_device_count()

    def f(v):
        carry = init_incremental(
            {"hits": jnp.zeros((4,), jnp.int32)}, reds, modes=modes, sync_every=1
        )
        return advance_incremental(
            carry, {"hits": v}, reds, "i", modes=modes
        ).acc["hits"]

    x = jnp.ones((n_dev, 4), jnp.int32)
    with chaos.plan([FaultSpec("sync/incremental", nth=1)]) as p:
        with pytest.raises(chaos.ChaosError):
            jax.pmap(f, axis_name="i")(x)
    assert p.fired("sync/incremental") == 1


def test_incremental_streak_seeded_sweep_is_bitwise_equal():
    """Seeded latency faults at every emission leave the finalized state
    bitwise-identical to the fault-free streak, for every seed."""
    baseline, _ = _incremental_streak(seed=None)
    for seed in (0, 1, 2):
        faulted, fired = _incremental_streak(seed=seed)
        assert fired > 0, "the plan must actually hit emissions"
        assert faulted == baseline
