"""RetryPolicy / call_with_retry semantics: bounded attempts, exponential
backoff with seeded jitter, per-op timeouts, transient-vs-fatal
classification, and the retry/giveup counters."""
import time

import pytest

from metrics_tpu.observability.instruments import REGISTRY
from metrics_tpu.resilience import ChaosError, RetryPolicy, call_with_retry, default_classify

FAST = RetryPolicy(backoff_base_s=0.0, backoff_max_s=0.0, jitter=0.0, seed=0)


class Flaky:
    """Fails the first ``failures`` calls with ``err``, then returns "ok"."""

    def __init__(self, failures, err=None):
        self.failures = failures
        self.calls = 0
        self.err = err if err is not None else OSError("flaky")

    def __call__(self):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.err
        return "ok"


class TestAttempts:
    def test_transient_recovers_within_budget(self):
        fn = Flaky(3)
        assert call_with_retry(fn, FAST) == "ok"
        assert fn.calls == 4

    def test_giveup_reraises_last_error(self):
        fn = Flaky(10)
        with pytest.raises(OSError, match="flaky"):
            call_with_retry(fn, FAST)
        assert fn.calls == FAST.max_attempts

    def test_fatal_short_circuits(self):
        fn = Flaky(10, err=FileNotFoundError("gone"))
        with pytest.raises(FileNotFoundError):
            call_with_retry(fn, FAST)
        assert fn.calls == 1

    def test_chaos_transient_flag_is_respected(self):
        ok = Flaky(1, err=ChaosError("x", transient=True))
        assert call_with_retry(ok, FAST) == "ok"
        fatal = Flaky(1, err=ChaosError("x", transient=False))
        with pytest.raises(ChaosError):
            call_with_retry(fatal, FAST)
        assert fatal.calls == 1

    def test_custom_classifier_wins(self):
        pol = RetryPolicy(
            backoff_base_s=0.0, jitter=0.0,
            classify=lambda e: isinstance(e, ValueError),
        )
        assert call_with_retry(Flaky(1, err=ValueError("transient here")), pol) == "ok"
        with pytest.raises(OSError):
            call_with_retry(Flaky(1), pol)  # OSError is fatal under this classifier

    def test_op_timeout_bounds_the_attempt_train(self):
        pol = RetryPolicy(
            max_attempts=1000, backoff_base_s=0.02, backoff_multiplier=1.0,
            jitter=0.0, op_timeout_s=0.06, seed=0,
        )
        fn = Flaky(10_000)
        t0 = time.monotonic()
        with pytest.raises(OSError):
            call_with_retry(fn, pol)
        assert time.monotonic() - t0 < 2.0
        assert fn.calls < 10


class TestBackoff:
    def test_exponential_capped_and_jitter_bounded(self):
        pol = RetryPolicy(
            backoff_base_s=0.01, backoff_multiplier=2.0, backoff_max_s=0.05,
            jitter=0.5, seed=42,
        )
        rng = pol.rng()
        for attempt in range(1, 8):
            bound = min(0.01 * 2.0 ** (attempt - 1), 0.05)
            delay = pol.backoff_for(attempt, rng)
            assert bound * 0.5 <= delay <= bound

    def test_seeded_jitter_is_deterministic(self):
        pol = RetryPolicy(seed=7)
        r1, r2 = pol.rng(), pol.rng()
        a = [pol.backoff_for(k, r1) for k in range(1, 6)]
        b = [pol.backoff_for(k, r2) for k in range(1, 6)]
        assert a == b

    def test_policy_validation(self):
        with pytest.raises(ValueError):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError):
            RetryPolicy(jitter=1.5)
        with pytest.raises(ValueError):
            RetryPolicy(backoff_multiplier=0.5)


class TestClassifyAndCounters:
    def test_default_classify_table(self):
        assert default_classify(TimeoutError()) is True
        assert default_classify(ConnectionError()) is True
        assert default_classify(OSError()) is True
        assert default_classify(InterruptedError()) is True
        assert default_classify(FileNotFoundError()) is False
        assert default_classify(PermissionError()) is False
        assert default_classify(NotADirectoryError()) is False
        assert default_classify(ValueError()) is False

    def test_retry_and_giveup_counters(self):
        retries = REGISTRY.counter(
            "checkpoint_retries_total",
            "Storage-backend ops retried after a transient error, by op.",
            op="unit",
        )
        giveups = REGISTRY.counter(
            "checkpoint_retry_giveups_total",
            "Storage-backend ops that exhausted retries (or hit a fatal error), by op.",
            op="unit",
        )
        r0, g0 = retries.value, giveups.value
        call_with_retry(Flaky(2), FAST, op="unit")
        assert retries.value == r0 + 2
        with pytest.raises(OSError):
            call_with_retry(Flaky(10), FAST, op="unit")
        assert giveups.value == g0 + 1
