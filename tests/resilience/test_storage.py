"""Pluggable checkpoint storage backends under the retry wrapper: in-memory
object storage roundtrips, LocalStorage primitives, transient-fault recovery,
fatal-fault short circuits, and torn writes caught downstream."""
import hashlib

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MeanMetric
from metrics_tpu.checkpoint import (
    InMemoryStorage,
    LocalStorage,
    restore_checkpoint,
    save_checkpoint,
    use_retry_policy,
    use_storage,
    verify_checkpoint,
)
from metrics_tpu.resilience import ChaosError, FaultSpec, RetryPolicy
from metrics_tpu.resilience import chaos

FAST = RetryPolicy(backoff_base_s=0.0, backoff_max_s=0.0, jitter=0.0, seed=0)


def _mean(value):
    m = MeanMetric()
    m.update(jnp.asarray(value, jnp.float32))
    return m


class TestBackends:
    def test_inmemory_roundtrip(self):
        store = InMemoryStorage()
        m = _mean(3.5)
        with use_storage(store):
            save_checkpoint(m, "mem/ckpt", world_size=1, shard_index=0)
            assert len(store) > 0
            fresh = MeanMetric()
            restore_checkpoint(fresh, "mem/ckpt", host_count=1)
        assert np.asarray(fresh.compute()) == np.asarray(m.compute())

    def test_local_storage_primitives(self, tmp_path):
        st = LocalStorage()
        d = str(tmp_path / "a")
        p = str(tmp_path / "a" / "b.bin")
        st.makedirs(d)
        st.write_atomic(p, b"hello")
        assert st.read_bytes(p) == b"hello"
        assert st.exists(p) and not st.isdir(p) and st.isdir(d)
        assert st.size(p) == 5
        assert st.sha256(p) == hashlib.sha256(b"hello").hexdigest()
        st.rename(p, str(tmp_path / "a" / "c.bin"))
        assert not st.exists(p)
        assert st.listdir(d) == ["c.bin"]
        st.delete(str(tmp_path / "a" / "c.bin"))
        assert st.listdir(d) == []

    def test_object_storage_emulates_directories(self):
        store = InMemoryStorage()
        store.write_atomic("root/step_0/x.npz", b"payload")
        assert store.isdir("root") and store.isdir("root/step_0")
        assert store.listdir("root") == ["step_0"]
        assert store.listdir("root/step_0") == ["x.npz"]
        store.rename("root/step_0", "root/step_1")
        assert store.read_bytes("root/step_1/x.npz") == b"payload"
        assert not store.exists("root/step_0/x.npz")

    def test_default_backend_writes_real_files(self, tmp_path):
        root = str(tmp_path / "ckpt")
        m = _mean(1.0)
        save_checkpoint(m, root, world_size=1, shard_index=0)
        fresh = MeanMetric()
        restore_checkpoint(fresh, root, host_count=1)
        assert np.asarray(fresh.compute()) == np.asarray(m.compute())


class TestFaultedStorage:
    def test_transient_write_faults_are_retried_to_success(self):
        store = InMemoryStorage()
        m = _mean(1.0)
        with use_storage(store), use_retry_policy(FAST):
            with chaos.plan([FaultSpec("storage/write", every=3, times=4)]) as p:
                save_checkpoint(m, "mem/ckpt", world_size=1, shard_index=0)
            assert p.fired("storage/write") >= 1
            fresh = MeanMetric()
            restore_checkpoint(fresh, "mem/ckpt", host_count=1)
        assert np.asarray(fresh.compute()) == np.asarray(m.compute())

    def test_transient_read_faults_are_retried_to_success(self):
        store = InMemoryStorage()
        m = _mean(2.0)
        with use_storage(store), use_retry_policy(FAST):
            save_checkpoint(m, "mem/ckpt", world_size=1, shard_index=0)
            fresh = MeanMetric()
            with chaos.plan([FaultSpec("storage/read", every=2, times=4)]) as p:
                restore_checkpoint(fresh, "mem/ckpt", host_count=1)
            assert p.fired("storage/read") >= 1
        assert np.asarray(fresh.compute()) == np.asarray(m.compute())

    def test_fatal_fault_gives_up_without_retrying(self):
        store = InMemoryStorage()
        m = _mean(1.0)
        with use_storage(store), use_retry_policy(FAST):
            with chaos.plan([FaultSpec("storage/write", transient=False)]) as p:
                with pytest.raises(ChaosError):
                    save_checkpoint(m, "mem/ckpt", world_size=1, shard_index=0)
            # a fatal error never schedules a second attempt at the same op
            assert p.fired("storage/write") == 1

    def test_exhausted_retries_reraise(self):
        store = InMemoryStorage()
        m = _mean(1.0)
        pol = RetryPolicy(max_attempts=2, backoff_base_s=0.0, backoff_max_s=0.0,
                          jitter=0.0, seed=0)
        with use_storage(store), use_retry_policy(pol):
            with chaos.plan([FaultSpec("storage/write", every=1)]):
                with pytest.raises(ChaosError):
                    save_checkpoint(m, "mem/ckpt", world_size=1, shard_index=0)

    def test_torn_write_is_caught_as_corruption(self, tmp_path):
        root = str(tmp_path / "ckpt")
        m = _mean(2.0)
        # truncate the FIRST atomic write of the save (the shard npz): a torn
        # write that still publishes — verification must flag it, not crash
        with chaos.plan(
            [FaultSpec("ckpt/write", kind="partial_write", nth=1, fraction=0.5)]
        ):
            save_checkpoint(m, root, world_size=1, shard_index=0)
        report = verify_checkpoint(root)
        assert not report.ok
        assert any("unreadable" in issue or "checksum" in issue for issue in report.issues)
