"""The deterministic fault harness itself: scheduling, replay, site matching,
the three fault kinds, and the wired fault points (sync bucket build, scrape
server)."""
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from metrics_tpu.resilience import ChaosError, FaultSpec, KNOWN_SITES
from metrics_tpu.resilience import chaos

pytestmark = pytest.mark.chaos


class TestScheduling:
    def test_nth_fires_exactly_once(self):
        with chaos.plan([FaultSpec("x/site", nth=3)]) as p:
            for i in range(1, 6):
                if i == 3:
                    with pytest.raises(ChaosError):
                        chaos.maybe_fail("x/site")
                else:
                    chaos.maybe_fail("x/site")
        assert p.fired("x/site") == 1
        assert [e.call for e in p.log] == [3]

    def test_every_with_times_cap(self):
        fired = []
        with chaos.plan([FaultSpec("x/site", every=2, times=2)]):
            for i in range(1, 9):
                try:
                    chaos.maybe_fail("x/site")
                except ChaosError:
                    fired.append(i)
        assert fired == [2, 4]

    def test_default_schedule_is_every_call(self):
        with chaos.plan([FaultSpec("x/site")]) as p:
            for _ in range(3):
                with pytest.raises(ChaosError):
                    chaos.maybe_fail("x/site")
        assert p.fired() == 3

    def test_probability_schedule_replays_bitwise(self):
        def run(seed):
            hits = []
            with chaos.plan([FaultSpec("x/site", probability=0.5)], seed=seed):
                for i in range(64):
                    try:
                        chaos.maybe_fail("x/site")
                    except ChaosError:
                        hits.append(i)
            return hits

        assert run(7) == run(7)
        assert run(7) != run(8)

    def test_two_specs_draw_independent_streams(self):
        def hits(plan_, site):
            return [e.call for e in plan_.log if e.site == site]

        with chaos.plan(
            [FaultSpec("a/site", probability=0.5), FaultSpec("b/site", probability=0.5)],
            seed=3,
        ) as p:
            for _ in range(64):
                for site in ("a/site", "b/site"):
                    try:
                        chaos.maybe_fail(site)
                    except ChaosError:
                        pass
        assert hits(p, "a/site") != hits(p, "b/site")

    def test_wildcard_site_matching(self):
        spec = FaultSpec("storage/*")
        assert spec.matches("storage/write") and spec.matches("storage/read")
        assert not spec.matches("ckpt/write")
        with chaos.plan([FaultSpec("storage/*", nth=1)]):
            with pytest.raises(ChaosError):
                chaos.maybe_fail("storage/read")
            chaos.maybe_fail("storage/write")  # per-spec counter already past nth

    def test_spec_validation(self):
        with pytest.raises(ValueError):
            FaultSpec("x", kind="explode")
        with pytest.raises(ValueError):
            FaultSpec("x", nth=2, every=3)
        with pytest.raises(ValueError):
            FaultSpec("x", probability=1.5)
        with pytest.raises(ValueError):
            FaultSpec("x", kind="partial_write", fraction=1.0)


class TestFaultKinds:
    def test_partial_write_fraction(self):
        with chaos.plan(
            [FaultSpec("ckpt/write", kind="partial_write", nth=2, fraction=0.25)]
        ) as p:
            assert chaos.partial_write_fraction("ckpt/write") is None
            assert chaos.partial_write_fraction("ckpt/write") == 0.25
            assert chaos.partial_write_fraction("ckpt/write") is None
        assert p.fired() == 1

    def test_latency_kind_sleeps_then_proceeds(self):
        with chaos.plan([FaultSpec("x/site", kind="latency", latency_s=0.02, nth=1)]) as p:
            t0 = time.perf_counter()
            chaos.maybe_fail("x/site")  # sleeps, must NOT raise
            assert time.perf_counter() - t0 >= 0.015
        assert p.fired() == 1

    def test_transient_flag_rides_the_error(self):
        with chaos.plan([FaultSpec("x/site", transient=False, message="boom")]):
            with pytest.raises(ChaosError) as exc:
                chaos.maybe_fail("x/site")
        assert exc.value.transient is False
        assert "boom" in str(exc.value)


class TestLifecycle:
    def test_disabled_path_is_inert(self):
        assert chaos.active is False
        chaos.maybe_fail("x/site")  # no plan armed: a no-op
        assert chaos.partial_write_fraction("x/site") is None

    def test_plan_context_always_disarms(self):
        with pytest.raises(RuntimeError, match="body blew up"):
            with chaos.plan([FaultSpec("x/site", nth=10**9)]):
                assert chaos.active and chaos.current_plan() is not None
                raise RuntimeError("body blew up")
        assert chaos.active is False and chaos.current_plan() is None

    def test_known_sites_cover_the_documented_seams(self):
        for site in (
            "engine/compile", "engine/dispatch", "sync/bucket_build",
            "ckpt/write", "ckpt/commit", "ckpt/read", "ckpt/manifest",
            "storage/write", "storage/read", "server/scrape",
        ):
            assert site in KNOWN_SITES


class TestWiredSites:
    def test_sync_bucket_build_fault_fires_at_trace_time(self):
        from metrics_tpu.parallel.sync import sync_state

        devs = jax.local_device_count()
        x = jnp.ones((devs, 4), jnp.float32)

        def f(v):
            return sync_state({"total": v}, {"total": "sum"}, "i")["total"]

        with chaos.plan([FaultSpec("sync/bucket_build", nth=1)]) as p:
            with pytest.raises(ChaosError):
                jax.pmap(f, axis_name="i")(x)
        assert p.fired("sync/bucket_build") == 1

    @pytest.mark.network
    def test_scrape_fault_is_a_500_not_a_crash(self):
        from metrics_tpu import observability

        observability.enable()
        try:
            server = observability.serve(port=0)
            with chaos.plan([FaultSpec("server/scrape", nth=1)]):
                with pytest.raises(urllib.error.HTTPError) as exc:
                    urllib.request.urlopen(server.url + "/healthz", timeout=10)
                assert exc.value.code == 500
                # next scrape (the fault was nth=1) succeeds: the server
                # degraded one response, it did not die
                with urllib.request.urlopen(server.url + "/healthz", timeout=10) as resp:
                    assert resp.status == 200
        finally:
            observability.shutdown()
            observability.disable()
