"""Dispatcher probation: a runtime migration is a bounded re-probe schedule,
not a permanent eager sentence. Covers the full lifecycle (migrate -> cooldown
-> trial -> re-promotion), the cooldown=0 opt-out, and exponential backoff on
failed trials."""
import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import (
    Accuracy,
    MetricCollection,
    Precision,
    probation_cooldown,
    set_probation,
)
from metrics_tpu.resilience import FaultSpec
from metrics_tpu.resilience import chaos

pytestmark = [pytest.mark.chaos, pytest.mark.filterwarnings("ignore::UserWarning")]


def _build():
    return MetricCollection(
        {
            "acc": Accuracy(num_classes=4, average="micro"),
            "prec": Precision(num_classes=4, average="macro"),
        }
    )


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    logits = jnp.asarray(rng.normal(size=(16, 4)), dtype=jnp.float32)
    target = jnp.asarray(rng.integers(0, 4, size=(16,)), dtype=jnp.int32)
    return logits, target


def _pv(coll):
    return coll.engine_stats()["partition"]


class TestKnobs:
    def test_set_probation_overrides_and_restores_env_default(self):
        default = probation_cooldown()
        set_probation(7)
        assert probation_cooldown() == 7
        set_probation(None)
        assert probation_cooldown() == default

    def test_env_default(self, monkeypatch):
        set_probation(None)
        monkeypatch.setenv("METRICS_TPU_PROBATION_COOLDOWN", "11")
        assert probation_cooldown() == 11


class TestLifecycle:
    def test_migration_then_cooldown_then_repromotion(self):
        set_probation(2)
        logits, target = _batch()
        coll = _build()
        migrate_at = promote_at = None
        # the 3rd compiled steady-state dispatch faults once: fallback,
        # migration, probation — then the trial dispatch re-promotes
        with chaos.plan([FaultSpec("engine/dispatch", nth=3, times=1)]):
            for step in range(1, 40):
                coll.update(logits, target)
                pv = _pv(coll)
                if migrate_at is None and pv["migrations"]:
                    migrate_at = step
                    assert pv["probations"] >= 1
                    assert pv["probation"], "probation ledger must hold the demoted members"
                if pv["repromotions"]:
                    promote_at = step
                    break
        assert migrate_at is not None, "injected dispatch fault never migrated"
        assert promote_at is not None, "probation trial never re-promoted"
        assert promote_at > migrate_at
        pv = _pv(coll)
        assert pv["probation"] == {}, "a survived trial clears the ledger for good"
        assert all(info["path"] == "fused" for info in pv["update"].values())
        # the faulted run still computes the exact same numbers
        reference = _build()
        for _ in range(promote_at):
            reference.update(logits, target)
        ours, ref = coll.compute(), reference.compute()
        assert set(ours) == set(ref)
        for key in ref:
            assert np.asarray(ours[key]).tobytes() == np.asarray(ref[key]).tobytes()

    def test_migration_records_last_fallback_exception(self):
        set_probation(0)
        logits, target = _batch()
        coll = _build()
        with chaos.plan([FaultSpec("engine/dispatch", nth=3, times=1, message="kaboom")]):
            for _ in range(8):
                coll.update(logits, target)
        pv = _pv(coll)
        assert pv["migrations"] >= 1
        assert pv["last_fallback_exception"] is not None
        assert pv["last_fallback_exception"].startswith("ChaosError")
        assert "kaboom" in pv["last_fallback_exception"]


class TestOptOutAndBackoff:
    def test_cooldown_zero_makes_migration_permanent(self):
        set_probation(0)
        logits, target = _batch()
        coll = _build()
        with chaos.plan([FaultSpec("engine/dispatch", nth=3, times=1)]):
            for _ in range(40):
                coll.update(logits, target)
        pv = _pv(coll)
        assert pv["migrations"] >= 1
        assert pv["probations"] == 0
        assert pv["repromotions"] == 0
        assert any(info["path"] == "eager" for info in pv["update"].values())

    def test_deterministic_trace_failure_is_not_reprobed(self):
        """A member whose update genuinely cannot trace (host readback) is
        attributed by the post-mortem probe and demoted permanently: no
        probation trials, no repeated recompiles on the steady-state path."""
        from metrics_tpu import Metric

        class HostReadback(Metric):
            full_state_update = False

            def __init__(self, **kwargs):
                super().__init__(**kwargs)
                self.add_state("total", default=jnp.zeros(()), dist_reduce_fx="sum")

            def update(self, logits, target):
                self.total = self.total + float(jnp.sum(target))

            def compute(self):
                return self.total

        set_probation(2)  # short cooldown: trials WOULD fire if scheduled
        logits, target = _batch()
        coll = MetricCollection(
            {"acc": Accuracy(num_classes=4, average="micro"), "host": HostReadback()}
        )
        for _ in range(30):
            coll.update(logits, target)
        pv = _pv(coll)
        host_migrations = [
            e for (_, name), e in coll._dispatcher._probation.items() if name == "host"
        ]
        assert pv["update"]["host"]["path"] == "eager"
        assert pv["update"]["acc"]["path"] == "fused"
        assert pv["probations"] == 0, "a deterministic culprit must not be re-probed"
        assert pv["repromotions"] == 0
        assert all(e["failures"] == 1 for e in host_migrations)

    def test_failed_trials_re_migrate_with_backoff(self):
        set_probation(1)
        logits, target = _batch()
        coll = _build()
        # EVERY compiled attempt faults (compile probes included): the first
        # failure migrates, every re-probe trial fails again and re-migrates
        # with a doubled cooldown until the trial budget is spent
        with chaos.plan([FaultSpec("engine/*", every=1)]):
            for _ in range(60):
                coll.update(logits, target)
            pv = _pv(coll)
        assert pv["repromotions"] == 0
        assert pv["migrations"] >= 2, "a failed trial must count as a fresh migration"
        entries = list(pv["probation"].values())
        assert entries
        assert max(e["failures"] for e in entries) >= 2
