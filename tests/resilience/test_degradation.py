"""Graceful degradation: restore falls back to the latest *verifiable* step
when the newest is corrupt, and the opt-in non-finite guard enforces its
raise/warn/quarantine policies at the facade boundaries."""
import os

import jax.numpy as jnp
import numpy as np
import pytest

from metrics_tpu import MeanMetric, MeanSquaredError, SumMetric
from metrics_tpu.checkpoint import (
    CheckpointCorruptError,
    available_steps,
    restore_checkpoint,
    save_checkpoint,
)
from metrics_tpu.checkpoint import io as _io
from metrics_tpu.resilience import NonFiniteStateError, guarded
from metrics_tpu.resilience import guard as _guard


def _corrupt_newest_payload(root):
    """Flip bytes inside the newest step's npz so its checksum fails."""
    step = available_steps(root)[-1]
    sdir = _io.step_dir(root, step)
    npz = next(n for n in os.listdir(sdir) if n.endswith(".npz"))
    path = os.path.join(sdir, npz)
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(path, "wb") as fh:
        fh.write(bytes(data))
    return step


class TestRestoreFallback:
    def _two_steps(self, tmp_path):
        root = str(tmp_path / "ckpt")
        m = MeanMetric()
        m.update(jnp.asarray(1.0, jnp.float32))
        save_checkpoint(m, root, world_size=1, shard_index=0)
        m.update(jnp.asarray(3.0, jnp.float32))
        save_checkpoint(m, root, world_size=1, shard_index=0)
        return root

    def test_falls_back_to_latest_verifiable_step(self, tmp_path):
        root = self._two_steps(tmp_path)
        bad_step = _corrupt_newest_payload(root)
        good_step = available_steps(root)[0]
        fresh = MeanMetric()
        with pytest.warns(UserWarning, match="fall"):
            info = restore_checkpoint(fresh, root, host_count=1)
        assert info.step == good_step
        assert info.fallback_from == bad_step
        assert float(np.asarray(fresh.compute())) == 1.0  # the older snapshot

    def test_explicit_step_never_falls_back(self, tmp_path):
        root = self._two_steps(tmp_path)
        bad_step = _corrupt_newest_payload(root)
        with pytest.raises(CheckpointCorruptError):
            restore_checkpoint(MeanMetric(), root, step=bad_step, host_count=1)

    def test_opt_out_restores_raise_on_first_corruption(self, tmp_path):
        root = self._two_steps(tmp_path)
        _corrupt_newest_payload(root)
        with pytest.raises(CheckpointCorruptError):
            restore_checkpoint(
                MeanMetric(), root, host_count=1, fallback_to_verified=False
            )

    def test_no_fallback_needed_reports_none(self, tmp_path):
        root = self._two_steps(tmp_path)
        fresh = MeanMetric()
        info = restore_checkpoint(fresh, root, host_count=1)
        assert info.fallback_from is None
        assert info.step == available_steps(root)[-1]
        assert float(np.asarray(fresh.compute())) == 2.0

    def test_every_step_corrupt_raises_the_newest_error(self, tmp_path):
        root = self._two_steps(tmp_path)
        _corrupt_newest_payload(root)
        # corrupt the older one too
        older = available_steps(root)[0]
        sdir = _io.step_dir(root, older)
        npz = next(n for n in os.listdir(sdir) if n.endswith(".npz"))
        with open(os.path.join(sdir, npz), "r+b") as fh:
            data = bytearray(fh.read())
            data[len(data) // 2] ^= 0xFF
            fh.seek(0)
            fh.write(bytes(data))
        with pytest.raises(CheckpointCorruptError):
            with pytest.warns(UserWarning):
                restore_checkpoint(MeanMetric(), root, host_count=1)


def _poisoned(value=jnp.nan):
    """A batch whose squared error carries ``value`` into MSE state."""
    return jnp.asarray([value], jnp.float32), jnp.asarray([0.0], jnp.float32)


class TestNonFiniteGuard:
    def test_off_by_default(self):
        assert _guard.active is False
        m = MeanSquaredError()
        m.update(*_poisoned())  # no guard: the nan sails into state
        assert np.isnan(np.asarray(m.compute()))

    def test_warn_counts_and_keeps_state(self):
        m = MeanSquaredError()
        with guarded("warn"):
            with pytest.warns(UserWarning, match="non-finite"):
                m.update(*_poisoned())
        assert np.isnan(np.asarray(m.compute()))  # state deliberately untouched

    def test_raise_policy_raises_at_update(self):
        m = MeanSquaredError()
        with guarded("raise"):
            with pytest.raises(NonFiniteStateError) as exc:
                m.update(*_poisoned(jnp.inf))
        assert exc.value.where == "update"
        assert exc.value.owner == "MeanSquaredError"

    def test_quarantine_rolls_back_the_poisoned_update(self):
        m = MeanSquaredError()
        m.update(jnp.asarray([1.0], jnp.float32), jnp.asarray([0.0], jnp.float32))
        with guarded("quarantine"):
            with pytest.warns(UserWarning, match="quarantined"):
                m.update(*_poisoned())
        # the poisoned batch is dropped: state and count as before
        assert float(np.asarray(m.compute())) == 1.0
        m.update(jnp.asarray([3.0], jnp.float32), jnp.asarray([1.0], jnp.float32))
        assert float(np.asarray(m.compute())) == 2.5

    def test_raise_policy_covers_the_compute_boundary(self):
        m = SumMetric()
        m.update(jnp.asarray(1.0, jnp.float32))
        # poison the state behind the facade so update-boundary checks miss it
        m.set_state({"value": jnp.asarray(jnp.nan, jnp.float32)})
        with guarded("raise"):
            with pytest.raises(NonFiniteStateError) as exc:
                m.compute()
        assert exc.value.where == "compute"

    def test_guarded_context_restores_prior_policy(self):
        assert _guard.guard_policy() is None
        with guarded("warn"):
            assert _guard.guard_policy() == "warn"
            with guarded("raise"):
                assert _guard.guard_policy() == "raise"
            assert _guard.guard_policy() == "warn"
        assert _guard.guard_policy() is None

    def test_nonfinite_leaves_names_the_bad_leaf(self):
        tree = {
            "ok": jnp.ones((2,), jnp.float32),
            "bad": jnp.asarray([1.0, jnp.nan], jnp.float32),
            "ints": jnp.zeros((2,), jnp.int32),  # non-float leaves are skipped
        }
        assert _guard.nonfinite_leaves(tree) == ["bad"]
