"""Resilience suite hygiene: the chaos plan, the non-finite guard, the
probation cooldown, and the storage/retry selections are process-global —
every test leaves them exactly as it found them (harness disarmed, guard
off, env-default cooldown, LocalStorage + default RetryPolicy)."""
import pytest

from metrics_tpu.checkpoint import storage as _storage
from metrics_tpu.core.engine import set_probation
from metrics_tpu.resilience import chaos as _chaos
from metrics_tpu.resilience import guard as _guard


@pytest.fixture(autouse=True)
def _pristine_resilience_globals():
    yield
    _chaos.uninstall()
    _guard.set_guard(None)
    set_probation(None)
    _storage.set_storage(None)
    _storage.set_retry_policy(None)
