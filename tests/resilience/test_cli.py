"""The checkpoint CLI's resilience-facing subcommands: ``verify --all``
(exit non-zero naming the first corrupt step) and ``clean --dry-run``."""
import os

import jax.numpy as jnp

from metrics_tpu import MeanMetric
from metrics_tpu.checkpoint import available_steps, save_checkpoint
from metrics_tpu.checkpoint import io as _io
from metrics_tpu.checkpoint.__main__ import main as cli_main


def _save_steps(root, n=3):
    m = MeanMetric()
    for i in range(n):
        m.update(jnp.asarray(float(i + 1), jnp.float32))
        save_checkpoint(m, root, world_size=1, shard_index=0)
    return available_steps(root)


def _corrupt(root, step):
    sdir = _io.step_dir(root, step)
    npz = next(n for n in os.listdir(sdir) if n.endswith(".npz"))
    path = os.path.join(sdir, npz)
    with open(path, "r+b") as fh:
        data = bytearray(fh.read())
        data[len(data) // 2] ^= 0xFF
        fh.seek(0)
        fh.write(bytes(data))


class TestVerifyAll:
    def test_all_clean_exits_zero(self, tmp_path, capsys):
        root = str(tmp_path / "ckpt")
        steps = _save_steps(root)
        assert cli_main(["verify", root, "--all"]) == 0
        out = capsys.readouterr().out
        for step in steps:
            assert f"step {step}: OK" in out

    def test_corruption_exits_nonzero_naming_first_bad_step(self, tmp_path, capsys):
        root = str(tmp_path / "ckpt")
        steps = _save_steps(root)
        _corrupt(root, steps[1])
        _corrupt(root, steps[2])
        assert cli_main(["verify", root, "--all"]) == 1
        captured = capsys.readouterr()
        assert f"first corrupt step is {steps[1]}" in captured.err
        assert "2 of 3 step(s) failed verification" in captured.err
        assert f"step {steps[0]}: OK" in captured.out
        assert f"step {steps[1]}: FAIL" in captured.out

    def test_empty_root_exits_nonzero(self, tmp_path, capsys):
        assert cli_main(["verify", str(tmp_path / "empty"), "--all"]) == 1
        assert "no committed checkpoint" in capsys.readouterr().err


class TestCleanDryRun:
    def _orphan_pending(self, root):
        pending = _io.pending_dir(root, 99)
        os.makedirs(pending)
        with open(os.path.join(pending, "junk.npz"), "wb") as fh:
            fh.write(b"aborted save")
        return pending

    def test_dry_run_lists_without_touching(self, tmp_path, capsys):
        root = str(tmp_path / "ckpt")
        _save_steps(root, n=1)
        pending = self._orphan_pending(root)
        assert cli_main(["clean", root, "--dry-run"]) == 0
        out = capsys.readouterr().out
        assert f"would remove {pending}" in out
        assert "1 pending dir(s) found" in out
        assert os.path.isdir(pending), "--dry-run must not delete anything"

    def test_real_clean_reaps_and_spares_committed(self, tmp_path, capsys):
        root = str(tmp_path / "ckpt")
        steps = _save_steps(root, n=1)
        pending = self._orphan_pending(root)
        assert cli_main(["clean", root]) == 0
        out = capsys.readouterr().out
        assert f"removed {pending}" in out
        assert "1 pending dir(s) reaped" in out
        assert not os.path.exists(pending)
        assert available_steps(root) == steps  # committed snapshots untouched
