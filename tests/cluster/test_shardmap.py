"""ShardMap: deterministic routing, pins, epochs, serde, rebalance planning.

Pure host-side logic — no jax, no servers; this file is the fast half of the
cluster suite.
"""
import json
import subprocess
import sys

import pytest

from metrics_tpu.cluster.shardmap import Move, ShardMap, plan_rebalance, rendezvous_owner

pytestmark = pytest.mark.cluster

REPLICAS = ("r0", "r1", "r2")


class TestRendezvous:
    def test_owner_is_deterministic_and_order_independent(self):
        for tenant in ("t0", "alpha", 42, "tenant-čž"):
            owner = rendezvous_owner(tenant, REPLICAS)
            assert owner in REPLICAS
            assert rendezvous_owner(tenant, REPLICAS[::-1]) == owner
            assert rendezvous_owner(str(tenant), list(REPLICAS)) == owner

    def test_owner_is_stable_across_processes(self):
        # the whole point of BLAKE2 over hash(): immune to PYTHONHASHSEED
        tenants = [f"t{i}" for i in range(16)]
        script = (
            "from metrics_tpu.cluster.shardmap import rendezvous_owner;"
            "import json,sys;"
            f"print(json.dumps([rendezvous_owner(t, {REPLICAS!r}) for t in {tenants!r}]))"
        )
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, check=True,
            env={"PYTHONHASHSEED": "12345", "PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu"},
        )
        assert json.loads(out.stdout) == [rendezvous_owner(t, REPLICAS) for t in tenants]

    def test_minimal_churn_on_growth(self):
        # rendezvous property: adding a replica only moves tenants *to* it
        tenants = [f"t{i}" for i in range(64)]
        before = {t: rendezvous_owner(t, REPLICAS[:2]) for t in tenants}
        after = {t: rendezvous_owner(t, REPLICAS) for t in tenants}
        moved = {t for t in tenants if before[t] != after[t]}
        assert all(after[t] == "r2" for t in moved)
        assert moved  # and some actually land on the new replica

    def test_empty_replica_list_is_an_error(self):
        with pytest.raises(ValueError):
            rendezvous_owner("t", [])


class TestShardMap:
    def test_pins_override_rendezvous_and_bump_epoch(self):
        m = ShardMap(REPLICAS)
        natural = m.owner("t0")
        other = next(r for r in REPLICAS if r != natural)
        pinned = m.with_pin("t0", other)
        assert pinned.owner("t0") == other
        assert pinned.epoch == m.epoch + 1
        assert m.owner("t0") == natural  # immutable: the old map is untouched
        unpinned = pinned.without_pin("t0")
        assert unpinned.owner("t0") == natural
        assert unpinned.epoch == pinned.epoch + 1

    def test_pin_to_unknown_replica_refused(self):
        with pytest.raises(ValueError):
            ShardMap(REPLICAS).with_pin("t0", "nope")

    def test_with_replicas_pins_live_tenants_in_place(self):
        m = ShardMap(("r0", "r1"))
        live = [f"t{i}" for i in range(32)]
        owners = {t: m.owner(t) for t in live}
        grown = m.with_replicas(("r0", "r1", "r2"), live)
        # membership change must not re-route any tenant whose state exists
        assert {t: grown.owner(t) for t in live} == owners
        assert grown.epoch == m.epoch + 1
        # fresh tenants may land on the new replica
        fresh = [t for t in (f"new{i}" for i in range(64)) if grown.owner(t) == "r2"]
        assert fresh

    def test_cannot_drop_replica_still_owning_pins(self):
        m = ShardMap(REPLICAS).with_pin("t0", "r2")
        with pytest.raises(ValueError, match="migrate them away first"):
            m.with_replicas(("r0", "r1"), ["t0"])

    def test_assignment_partitions_all_tenants(self):
        m = ShardMap(REPLICAS)
        tenants = [f"t{i}" for i in range(20)]
        assignment = m.assignment(tenants)
        assert sorted(t for ts in assignment.values() for t in ts) == sorted(tenants)

    def test_json_round_trip_is_exact(self):
        m = ShardMap(REPLICAS, epoch=7, pins={"t1": "r2"})
        back = ShardMap.from_json(m.to_json())
        assert back == m
        assert ShardMap.from_json(back.to_json()).to_json() == m.to_json()

    def test_unsupported_wire_version_refused(self):
        doc = ShardMap(REPLICAS).to_dict()
        doc["version"] = 99
        with pytest.raises(ValueError, match="wire version"):
            ShardMap.from_dict(doc)

    def test_duplicate_or_empty_replicas_refused(self):
        with pytest.raises(ValueError):
            ShardMap(())
        with pytest.raises(ValueError):
            ShardMap(("r0", "r0"))


class TestPlanRebalance:
    def test_hot_shard_is_flattened_within_tolerance(self):
        m = ShardMap(("r0", "r1"))
        occupancy = {"r0": {"a": 10.0, "b": 8.0, "c": 2.0}, "r1": {"d": 2.0}}
        moves = plan_rebalance(m, occupancy, tolerance=0.10)
        assert moves
        loads = {"r0": 20.0, "r1": 2.0}
        for mv in moves:
            assert mv.src == "r0" and mv.dst == "r1"
            loads[mv.src] -= mv.weight
            loads[mv.dst] += mv.weight
        mean = 22.0 / 2
        assert max(loads.values()) <= mean * 1.10

    def test_plan_is_deterministic(self):
        m = ShardMap(REPLICAS)
        occupancy = {
            "r0": {"a": 5.0, "b": 5.0, "e": 1.0},
            "r1": {"c": 1.0},
            "r2": {"d": 1.0},
        }
        first = plan_rebalance(m, occupancy)
        assert first == plan_rebalance(m, dict(reversed(list(occupancy.items()))))
        assert [m.to_dict() for m in first] == [m.to_dict() for m in first]

    def test_balanced_cluster_proposes_nothing(self):
        m = ShardMap(("r0", "r1"))
        assert plan_rebalance(m, {"r0": {"a": 5.0}, "r1": {"b": 5.0}}) == []

    def test_single_giant_tenant_cannot_wedge_or_thrash(self):
        m = ShardMap(("r0", "r1"))
        # moving the only tenant would just swap which replica is hot
        moves = plan_rebalance(m, {"r0": {"whale": 100.0}, "r1": {}})
        assert moves == []

    def test_max_moves_caps_the_plan(self):
        m = ShardMap(("r0", "r1"))
        occupancy = {"r0": {f"t{i}": 4.0 for i in range(6)}, "r1": {}}
        moves = plan_rebalance(m, occupancy, max_moves=1)
        assert len(moves) == 1
        assert isinstance(moves[0], Move)

    def test_unknown_replica_in_occupancy_refused(self):
        with pytest.raises(ValueError, match="unknown replica"):
            plan_rebalance(ShardMap(("r0",)), {"rX": {"t": 1.0}})
