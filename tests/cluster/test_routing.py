"""Client routing: direct-to-owner, stale-map redirects, fence verdicts, and
the occupancy fields healthz/stats grew for the coordinator (queue depth,
dead letters, per-tenant applied watermark)."""
import json
import urllib.request

import numpy as np
import pytest

from metrics_tpu.serve import IngestServer
from metrics_tpu.serve.server import SHARD_EPOCH_HEADER
from metrics_tpu.cluster import ClusterClient, ClusterCoordinator, ShardMap

from tests.cluster.conftest import (
    assert_matches_oracle,
    make_pipeline,
    post_stream,
)

pytestmark = pytest.mark.cluster


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 4, size=(8,)).astype(np.int32),
            rng.integers(0, 4, size=(8,)).astype(np.int32))


class TestInProcessRouting:
    def test_posts_land_on_the_owning_replica_only(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2)
        tenants = [f"t{i}" for i in range(6)]
        log = post_stream(client, tenants, steps=2)
        for replica in coordinator.replicas.values():
            replica.pipeline.drain(30.0)
        assignment = coordinator.shard_map.assignment(tenants)
        for rid, replica in coordinator.replicas.items():
            assert sorted(map(str, replica.tenant_ids())) == assignment[rid]
        assert_matches_oracle(client, log)
        assert client.redirects_followed == 0  # fresh map: zero extra hops

    def test_fenced_tenant_gets_429_with_retry_hint(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2)
        preds, target = _batch()
        client.post("t0", preds, target)
        owner = coordinator.replica_of("t0")
        owner.fence_tenant("t0", retry_after_s=0.01)
        doc = client.post("t0", preds, target)
        assert doc == {
            "admitted": False, "reason": "tenant_fenced", "status": 429,
            "queue_depth": doc["queue_depth"], "retry_after_s": 0.01,
        }
        # the fence is per-tenant: everyone else is untouched
        assert client.post("x0", preds, target)["admitted"]
        owner.unfence_tenant("t0")
        assert client.post("t0", preds, target)["admitted"]

    def test_stale_map_follows_not_owner_verdict(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2)
        preds, target = _batch()
        assert client.post("t0", preds, target)["admitted"]
        src = coordinator.owner("t0")
        dst = next(r for r in coordinator.replicas if r != src)
        record = coordinator.migrate("t0", dst)
        assert record.outcome == "committed"
        # the client's copy still says src; the gate answers not_owner and the
        # client refreshes + retries transparently
        assert client.shard_map.owner("t0") == src
        doc = client.post("t0", preds, target)
        assert doc["admitted"], doc
        assert client.redirects_followed >= 1
        assert client.shard_map.owner("t0") == dst

    def test_unknown_replica_in_map_fails_loud(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=2)
        client.shard_map = ShardMap(("r0", "r1", "ghost"), epoch=99,
                                    pins={"t0": "ghost"})
        with pytest.raises(KeyError, match="ghost"):
            client.post("t0", *_batch())


class TestOccupancySurfaces:
    def test_stats_carries_per_tenant_watermark_and_fences(self, cluster_factory):
        coordinator, client = cluster_factory(n_replicas=1)
        log = post_stream(client, ["t0", "t1"], steps=3)
        replica = coordinator.replicas["r0"]
        replica.pipeline.drain(30.0)
        replica.fence_tenant("t1")
        stats = replica.pipeline.stats()
        per_tenant = stats["ledger"]["per_tenant"]
        assert per_tenant["t0"]["last_applied_step"] == 3
        assert per_tenant["t0"]["pending"] == 0
        assert stats["ledger"]["fenced"] == ["t1"]
        assert stats["queue"]["depth"] == 0
        occupancy = replica.occupancy()
        assert occupancy == {"t0": 3.0, "t1": 3.0}

    def test_healthz_reports_the_rebalance_signal(self, cluster_factory):
        server = IngestServer(make_pipeline("hz"), port=0)
        server.start()
        try:
            client = ClusterClient(
                {"r0": server},
                lambda: ShardMap(("r0",)),
            )
            post_stream(client, ["a"], steps=2)
            server.pipeline.drain(30.0)
            server.pipeline.fence_tenant("b-fenced")
            with urllib.request.urlopen(f"{server.url}/healthz", timeout=10) as resp:
                doc = json.loads(resp.read().decode())
            assert doc["queue_depth"] == 0
            assert doc["queue_capacity"] == server.pipeline.queue.capacity
            assert doc["dead_letters"] == 0
            assert doc["fenced_tenants"] == ["b-fenced"]
            assert doc["last_applied_step"]["a"] == 2
        finally:
            server.stop(drain=False, timeout=5.0)


class TestHTTPRouting:
    def test_real_307_redirect_carries_epoch_and_owner(self, cluster_factory):
        servers = {rid: IngestServer(make_pipeline(f"http-{rid}"), port=0).start()
                   for rid in ("r0", "r1")}
        try:
            coordinator = ClusterCoordinator(servers, name="http-cl").start()
            client = ClusterClient(dict(coordinator.replicas), coordinator)
            preds, target = _batch()
            assert client.post("t0", preds, target)["admitted"]
            src = coordinator.owner("t0")
            dst = next(r for r in servers if r != src)
            epoch_before = coordinator.shard_map.epoch
            record = coordinator.migrate("t0", dst)
            assert record.outcome == "committed"
            assert record.epoch == epoch_before + 1

            # raw HTTP against the old owner: a trusting client sees 307 +
            # Location + the shard-epoch header
            # the redirect fires before body decoding, so a trivial body works
            req = urllib.request.Request(
                f"{servers[src].url}/ingest/t0", data=b"{}",
                headers={"Content-Type": "application/json"},
            )
            try:
                resp = urllib.request.urlopen(req, timeout=10)
                status, headers = resp.status, resp.headers
            except urllib.error.HTTPError as err:
                status, headers = err.code, err.headers
            assert status == 307
            assert headers["Location"].startswith(servers[dst].url)
            assert int(headers[SHARD_EPOCH_HEADER]) == coordinator.shard_map.epoch

            # the shard-aware client rides the redirect without raising
            doc = client.post("t0", preds, target)
            assert doc["admitted"], doc
            assert client.redirects_followed >= 1
            read = client.read("t0", max_staleness_steps=0, timeout_s=30.0)
            assert read["values"] is not None
        finally:
            for server in servers.values():
                server.stop(drain=False, timeout=5.0)
