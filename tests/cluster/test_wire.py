"""The migration wire format: canonical bytes, streamed frames, verification.

Includes the byte-stability regression (satellite of the cluster PR): the
canonical npz encoding of one snapshot must be identical across processes —
checkpoints of migrated tenants and the chaos harness's bitwise comparisons
both lean on it.
"""
import hashlib
import pickle
import subprocess
import sys

import numpy as np
import pytest

from metrics_tpu.core.buffers import CatBuffer
from metrics_tpu.sketches import CountMinSketch, QuantileSketch
from metrics_tpu.cluster.wire import (
    Frame,
    TenantTransfer,
    TransferError,
    decode_tenant_snapshot,
    encode_tenant_snapshot,
    iter_frames,
    plan_transfer,
)

pytestmark = pytest.mark.cluster


def _snapshot():
    """One snapshot exercising every wire leaf kind and several dtypes."""
    sketch = CountMinSketch(width=64, depth=2)
    sketch = sketch.replace(
        counts=np.arange(128, dtype=np.float32).reshape(2, 64),
        total=np.asarray(128.0, dtype=np.float32),
    )
    qsketch = QuantileSketch()
    return {
        "states": {
            "acc": {
                "correct": np.asarray(7, dtype=np.int32),        # 0-d int
                "total": np.asarray(9.5, dtype=np.float64),      # 0-d float
                "confmat": np.arange(16, dtype=np.uint8).reshape(4, 4),
                "freqs": sketch,
            },
        },
        "eager_states": {
            "mse": {
                "vals": [np.zeros((3,), np.float16), np.ones((3,), np.float16)],
                "buf": CatBuffer(
                    np.arange(8, dtype=np.bfloat16 if hasattr(np, "bfloat16") else np.float32),
                    5, overflowed=True,
                ),
                "empty_buf": CatBuffer(None, 0, capacity=12),
                "mode": "global",                                 # scalar config
                "quants": qsketch,
            },
        },
        "update_count": 42,
        "aux": {"mse": {"num_outputs": 1}},
    }


def _assert_snapshots_equal(a, b):
    assert a["update_count"] == b["update_count"]
    assert a["aux"] == b["aux"]
    for group in ("states", "eager_states"):
        assert sorted(a[group]) == sorted(b[group])
        for leader in a[group]:
            assert sorted(a[group][leader]) == sorted(b[group][leader])
            for state, left in a[group][leader].items():
                right = b[group][leader][state]
                _assert_leaf_equal(left, right, f"{group}/{leader}/{state}")


def _assert_leaf_equal(left, right, where):
    if isinstance(left, CatBuffer):
        assert isinstance(right, CatBuffer), where
        assert int(np.asarray(right.count)) == int(np.asarray(left.count)), where
        assert bool(np.asarray(right.overflowed)) == bool(np.asarray(left.overflowed)), where
        if left.data is None:
            assert right.data is None and right.capacity == left.capacity, where
        else:
            _assert_array_equal(np.asarray(left.data), np.asarray(right.data), where)
    elif isinstance(left, list):
        assert isinstance(right, list) and len(right) == len(left), where
        for i, (l, r) in enumerate(zip(left, right)):
            _assert_array_equal(np.asarray(l), np.asarray(r), f"{where}[{i}]")
    elif hasattr(left, "components"):
        assert type(right).__name__ == type(left).__name__, where
        assert right.config_dict() == left.config_dict(), where
        for name, comp in left.components().items():
            _assert_array_equal(
                np.asarray(comp), np.asarray(right.components()[name]), f"{where}.{name}"
            )
    elif hasattr(left, "dtype"):
        _assert_array_equal(np.asarray(left), np.asarray(right), where)
    else:
        assert left == right, where


def _assert_array_equal(left, right, where):
    assert right.dtype == left.dtype, f"{where}: dtype {right.dtype} != {left.dtype}"
    assert right.shape == left.shape, f"{where}: shape {right.shape} != {left.shape}"
    np.testing.assert_array_equal(right, left, err_msg=where)


class TestCanonicalEncoding:
    def test_round_trip_preserves_every_leaf_kind(self):
        snap = _snapshot()
        back = decode_tenant_snapshot(encode_tenant_snapshot(snap))
        _assert_snapshots_equal(snap, back)

    def test_zero_d_arrays_survive(self):
        # regression: ascontiguousarray silently promoted () to (1,)
        snap = {"states": {"m": {"x": np.asarray(3.5)}}, "eager_states": {},
                "update_count": 1, "aux": {}}
        back = decode_tenant_snapshot(encode_tenant_snapshot(snap))
        assert back["states"]["m"]["x"].shape == ()

    def test_encoding_is_byte_stable_within_process(self):
        snap = _snapshot()
        assert encode_tenant_snapshot(snap) == encode_tenant_snapshot(_snapshot())

    def test_encoding_is_byte_stable_across_process_boundary(self, tmp_path):
        # satellite: the same snapshot pickled into a fresh interpreter (with a
        # different hash seed) must encode to the identical bytes
        snap = _snapshot()
        blob = encode_tenant_snapshot(snap)
        payload = tmp_path / "snap.pkl"
        payload.write_bytes(pickle.dumps(snap))
        script = (
            "import pickle, sys, hashlib;"
            "from metrics_tpu.cluster.wire import encode_tenant_snapshot;"
            f"snap = pickle.load(open({str(payload)!r}, 'rb'));"
            "sys.stdout.write(hashlib.sha256(encode_tenant_snapshot(snap)).hexdigest())"
        )
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, check=True,
            env={"PYTHONHASHSEED": "9876", "PATH": "/usr/bin:/bin",
                 "JAX_PLATFORMS": "cpu", "HOME": "/root"},
        )
        assert out.stdout.strip() == hashlib.sha256(blob).hexdigest()

    def test_truncated_blob_is_refused(self):
        blob = encode_tenant_snapshot(_snapshot())
        with pytest.raises(TransferError):
            decode_tenant_snapshot(blob[: len(blob) // 2])

    def test_header_is_required(self):
        with pytest.raises(TransferError, match="undecodable|header"):
            decode_tenant_snapshot(b"PK\x05\x06" + b"\x00" * 18)


class TestStreamedTransfer:
    def test_frames_reassemble_bitwise(self):
        snap = _snapshot()
        recv = TenantTransfer()
        for frame in iter_frames(snap, chunk_bytes=97):
            recv.feed(frame, frame.digest)
        back = recv.finish()
        _assert_snapshots_equal(snap, back)
        assert recv.frames_fed > 3

    def test_peak_memory_is_one_leaf_not_the_gather(self):
        snap = _snapshot()
        plan = plan_transfer(snap, chunk_bytes=64)
        assert plan.plan_peak_bytes < plan.gather_peak_bytes
        assert plan.total_bytes == plan.gather_peak_bytes
        ops = [s["op"] for s in plan.steps]
        assert ops[:3] == ["load", "send", "free"]
        recv = TenantTransfer()
        for frame in iter_frames(snap, chunk_bytes=1 << 20):
            recv.feed(frame, frame.digest)
        recv.finish()
        # the receiver never held more than the largest single leaf blob + slop
        assert recv.peak_bytes <= plan.plan_peak_bytes + 4096

    def test_corrupted_frame_is_detected(self):
        frames = list(iter_frames(_snapshot(), chunk_bytes=128))
        recv = TenantTransfer()
        recv.feed(frames[0], frames[0].digest)
        bad = Frame(
            seq=frames[1].seq, leaf=frames[1].leaf, index=frames[1].index,
            last=frames[1].last, payload=frames[1].payload[:-1] + b"\x00",
        )
        with pytest.raises(TransferError, match="digest mismatch|corrupted"):
            recv.feed(bad, frames[1].digest)

    def test_dropped_frame_is_detected(self):
        frames = list(iter_frames(_snapshot(), chunk_bytes=128))
        recv = TenantTransfer()
        recv.feed(frames[0], frames[0].digest)
        with pytest.raises(TransferError, match="out of order"):
            recv.feed(frames[2], frames[2].digest)

    def test_truncated_stream_is_detected_at_finish(self):
        frames = list(iter_frames(_snapshot(), chunk_bytes=128))
        recv = TenantTransfer()
        for frame in frames[:-3]:
            recv.feed(frame, frame.digest)
        with pytest.raises(TransferError, match="truncated"):
            recv.finish()

    def test_leaf_frames_before_manifest_are_refused(self):
        frames = list(iter_frames(_snapshot(), chunk_bytes=128))
        recv = TenantTransfer()
        shifted = Frame(seq=0, leaf=frames[1].leaf, index=0, last=frames[1].last,
                        payload=frames[1].payload)
        with pytest.raises(TransferError, match="manifest"):
            recv.feed(shifted, shifted.digest)
