"""``python -m metrics_tpu.cluster`` — the control-plane verbs end to end.

The non-slow tests drive :func:`main` in-process (same argv surface, no
interpreter start-up); one slow test proves the real ``python -m`` entry."""
import json
import subprocess
import sys

import pytest

from metrics_tpu.cluster.__main__ import main

pytestmark = pytest.mark.cluster


def _run(capsys, argv):
    code = main(argv)
    return code, json.loads(capsys.readouterr().out)


def test_plan_reports_occupancy_and_moves(capsys):
    code, doc = _run(capsys, ["plan", "--demo"])
    assert code == 0
    assert doc["epoch"] >= 1
    assert set(doc["occupancy"]) == {"r0", "r1"}
    for move in doc["moves"]:
        assert {"tenant", "src", "dst", "weight"} <= set(move)


def test_status_demo_prints_the_document(capsys):
    code, doc = _run(capsys, ["status", "--demo"])
    assert code == 0
    assert doc["name"] == "demo"
    assert sorted(doc["replicas"]) == ["r0", "r1"]
    assert sum(doc["shard_sizes"].values()) == 8


def test_migrate_prints_a_committed_record(capsys):
    # tenant-0's owner is deterministic (rendezvous), so pick the other side
    from metrics_tpu.cluster import ShardMap

    dst = "r1" if ShardMap(("r0", "r1")).owner("tenant-0") == "r0" else "r0"
    code, doc = _run(capsys, ["migrate", "--demo", "--tenant", "tenant-0", "--dst", dst])
    assert code == 0
    assert doc["outcome"] == "committed"
    assert doc["phase"] == "done"
    assert doc["dst"] == dst


def test_rebalance_add_replica_scales_two_to_three(capsys):
    code, doc = _run(capsys, ["rebalance", "--demo", "--add-replica"])
    assert code == 0
    assert set(doc["shard_sizes"]) == {"r0", "r1", "r2"}
    assert doc["shard_sizes"]["r2"] > 0
    assert sum(doc["shard_sizes"].values()) == 8
    assert all(m["outcome"] == "committed" for m in doc["migrations"])


@pytest.mark.slow
def test_python_dash_m_entry_point():
    out = subprocess.run(
        [sys.executable, "-m", "metrics_tpu.cluster", "plan", "--demo"],
        capture_output=True, text=True, timeout=300,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin", "HOME": "/root"},
    )
    assert out.returncode == 0, out.stderr
    assert "occupancy" in json.loads(out.stdout)
