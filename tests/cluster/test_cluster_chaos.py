"""Chaos-swept migration: a fault at any phase aborts the move, rolls the
tenant back to the source, and leaves every read bitwise-equal to the
pure-protocol oracle; a clean retry then commits. Replica death mid-move and
checkpoint-based recovery ride the same guarantees."""
import pytest

from metrics_tpu.resilience import chaos
from metrics_tpu.cluster import ReplicaLost

from tests.cluster.conftest import assert_matches_oracle, make_pipeline, post_stream

pytestmark = pytest.mark.cluster

FAULT_SITES = {
    "cluster/fence": "fence",
    "cluster/export": "export",
    "cluster/transfer": "transfer",
    "cluster/import": "import",
    "cluster/cutover": "cutover",
}


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fault_at_every_phase_aborts_rolls_back_then_retry_commits(
    seed, cluster_factory
):
    coordinator, client = cluster_factory(n_replicas=2, name=f"chaos{seed}")
    tenants = [f"t{i}" for i in range(3)]
    log = post_stream(client, tenants, steps=2, seed=seed)
    # settle the dispatchers so residency assertions below see applied state
    # (a fence-phase fault aborts before the migration's own drain phase)
    for replica in coordinator.replicas.values():
        replica.pipeline.drain(30.0)
    tenant = tenants[0]
    src = coordinator.owner(tenant)
    dst = next(r for r in coordinator.replicas if r != src)

    for site, phase in FAULT_SITES.items():
        epoch_before = coordinator.shard_map.epoch
        with chaos.plan(
            [chaos.FaultSpec(site=site, kind="error", nth=1, times=1)], seed=seed
        ) as armed:
            record = coordinator.migrate(tenant, dst)
        assert record.outcome == "aborted", (site, record.to_dict())
        assert record.phase == phase, (site, record.to_dict())
        assert [e.site for e in armed.log] == [site]
        # total rollback: ownership, epoch, fence and state all unchanged
        assert coordinator.owner(tenant) == src
        assert coordinator.shard_map.epoch == epoch_before
        assert tenant not in map(str, coordinator.replicas[dst].tenant_ids())
        assert tenant in map(str, coordinator.replicas[src].tenant_ids())
        assert tenant not in map(
            str, coordinator.replicas[src].pipeline.fenced_tenants()
        )
        # the tenant still serves, and serves the *right* numbers
        doc = client.post_with_retry(tenant, *log[0][1][:2])
        assert doc["admitted"], (site, doc)
        log.append((tenant, log[0][1], {}))

    record = coordinator.migrate(tenant, dst)
    assert record.outcome == "committed"
    assert coordinator.owner(tenant) == dst
    assert_matches_oracle(client, log)
    counts = {r.outcome: 0 for r in coordinator.migrations}
    for r in coordinator.migrations:
        counts[r.outcome] += 1
    assert counts == {"aborted": len(FAULT_SITES), "committed": 1}


def test_source_crash_mid_move_aborts_without_corrupting_dst(cluster_factory):
    coordinator, client = cluster_factory(n_replicas=2, name="crash")
    tenants = ["t0", "t1"]
    log = post_stream(client, tenants, steps=2)
    tenant = tenants[0]
    src = coordinator.owner(tenant)
    dst = next(r for r in coordinator.replicas if r != src)

    def kill_src(phase):
        if phase == "export":
            coordinator.replicas[src].kill()

    record = coordinator.migrate(tenant, dst, on_phase=kill_src)
    assert record.outcome == "aborted"
    assert record.phase == "export"
    assert "export" in record.error or src in record.error
    # nothing half-imported on the destination, map untouched
    assert tenant not in map(str, coordinator.replicas[dst].tenant_ids())
    assert coordinator.owner(tenant) == src
    assert coordinator.status()["degraded"]
    with pytest.raises(ReplicaLost):
        coordinator.replicas[src].export_tenant(tenant)


def test_replica_loss_degrades_and_checkpoint_recovery_restores(
    cluster_factory,
):
    coordinator, client = cluster_factory(
        n_replicas=2, name="recover", checkpoint_root=True
    )
    tenants = [f"t{i}" for i in range(4)]
    log = post_stream(client, tenants, steps=3)
    for replica in coordinator.replicas.values():
        replica.pipeline.drain(30.0)
    paths = coordinator.checkpoint_all(step=1)
    assert all(paths.values())

    lost = coordinator.owner(tenants[0])
    survivor = next(r for r in coordinator.replicas if r != lost)
    coordinator.mark_lost(lost)
    assert coordinator.status()["degraded"]

    # degraded-but-serving: the survivor's tenants are untouched
    on_survivor = [t for t in tenants if coordinator.owner(t) == survivor]
    if on_survivor:
        survivor_log = [e for e in log if e[0] in on_survivor]
        assert_matches_oracle(client, survivor_log)
    # writes to the dead replica's tenants are rejected, not lost silently
    doc = client.post(tenants[0], *log[0][1][:2])
    assert not doc["admitted"]

    coordinator.recover_replica(lost, make_pipeline("recover-rb"))
    assert not coordinator.status()["degraded"]
    # the client still points at the dead stack; re-target like a reconnect
    client.add_target(lost, coordinator.replicas[lost])
    assert_matches_oracle(client, log)
    for tid in tenants:
        if coordinator.owner(tid) == lost:
            doc = client.read(tid, max_staleness_steps=0, timeout_s=30.0)
            assert doc["last_applied_step"] == 3  # ledger seeded from the shard

    # the restored shard keeps serving new writes
    log += post_stream(client, tenants, steps=1, seed=9)
    assert_matches_oracle(client, log)
